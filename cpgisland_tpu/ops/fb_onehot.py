"""One-hot-emission reduced kernels for the forward-backward E-step.

The probability-space twin of ops.viterbi_onehot: for one-hot-emission
models (the flagship 8-state preset — emissions at CpGIslandFinder.java:
166-173; one-hot rows are EM fixed points, so trained models keep the
structure) the alpha/beta vectors are EXACTLY ZERO outside the 2-state
group of the position's symbol, so the K-state recurrences reduce to
2-state recurrences whose per-step 2x2 transition is A (times the emission
probability) sliced between the previous symbol's group and the current
symbol's group.

Unlike the max-plus case, the reduction here is exact WITHOUT caveats about
out-of-group candidates: in (+, x) the dropped terms are multiplications by
exact f32 zeros, so the reduced sums equal the dense sums bit-for-bit; the
only cross-engine differences are the per-tile renormalization scalars of
the products kernel (dense normalizes over all K^2 entries, reduced over
its 4 — directions, which are all that leave the kernel, agree to ~1 ulp).

Pieces (wired into ops.fb_pallas behind its ``onehot`` static flags):
- `_oh_prod_kernel` — per-lane 2x2 transfer products, t-tiled with the
  running product in VMEM scratch (mirrors fb_pallas._prod_kernel).
- `_oh_fwd_kernel` / `_oh_bwd_kernel` / `_oh_bwd_conf_kernel` — the reduced
  recurrences with the same deferred-Rabiner / time-shifted-input structure
  as their dense twins; streams shrink from 32 to 8 B/symbol per direction.
- XLA twins for non-TPU backends (the Pallas interpreter evaluates these
  select-derived carried chains pathologically slowly — same workaround as
  ops.viterbi_onehot, same bit-level arithmetic).

Shared with the decode engine: group detection (`viterbi_onehot._groups`),
the pair stream with two-level forward-fill (`viterbi_onehot._pair_stream`),
and the lane-broadcast table trick (`_bcast_tab` — Mosaic supports [1, LT]
sublane broadcasts but not [1, 1] scalar broadcasts).

r12 adds the STACKED multi-model variants (the "Stacked multi-model
kernels" section below): M family members' chains over one shared pair
stream in ONE launch set, per-member arithmetic identical to the
single-model kernels — see BASELINE.md "Multi-model occupancy".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - mirrors ops.viterbi_pallas
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.viterbi_onehot import (
    GROUP,
    LANE_TILE,
    ROW_TILE,
    _bcast_tab,
    _groups,
    _interpret,
    _pair_stream,
    _vspec,
    supports,
    supports_concrete,
)

__all__ = [
    "supports", "supports_concrete", "prob_pair_table", "products_reduced",
]


def prob_pair_table(params: HmmParams, gt: jnp.ndarray):
    """Probability-space pair tables.

    tab[p] for p = s_prev * S + s_cur holds [T00, T01, T10, T11] with
    T[a, c] = A[gt[s_prev, a], gt[s_cur, c]] * B[gt[s_cur, c], s_cur] — the
    same product the dense kernels compute per lane (A row times the
    emission select), so values are bit-identical.  PAD pairs (p >= S*S)
    carry the identity and are handled by the select-tree defaults.
    """
    S = params.n_symbols
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    A_red = A[gt[:, :, None, None], gt[None, None, :, :]]  # [S, 2, S, 2]
    B_red = B[gt, jnp.arange(S)[:, None]]  # [S, 2]
    M = A_red * B_red[None, None, :, :]
    return jnp.transpose(M, (0, 2, 1, 3)).reshape(S * S, 4).astype(jnp.float32)


PROB_IDENT = (1.0, 0.0, 0.0, 1.0)  # the (+, x) identity matrix entries


def _select4_prob(tile, tab_ref, nreal, base=0):
    """Pair select with probability identity defaults (shared select tree —
    viterbi_onehot._select4 parametrized by the semiring identity; ``base``
    keys a member's slice of a stacked multi-model table)."""
    from cpgisland_tpu.ops.viterbi_onehot import _select4

    return _select4(tile, tab_ref, nreal, ident=PROB_IDENT, base=base)


def _oh_prod_kernel(pair_ref, tab_ref, out_ref, C_scr, *, nreal, bk):
    """(+,x) product of each lane's reduced step matrices -> [4, LT].

    Mirrors fb_pallas._prod_kernel: t tiled over the inner grid axis with
    the running product carried in VMEM scratch; every ROW_TILE steps the
    2x2 renormalizes by its own total (directions only leave the kernel).
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = pair_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        C_scr[0:1, :] = jnp.ones((1, lt), jnp.float32)
        C_scr[1:2, :] = jnp.zeros((1, lt), jnp.float32)
        C_scr[2:3, :] = jnp.zeros((1, lt), jnp.float32)
        C_scr[3:4, :] = jnp.ones((1, lt), jnp.float32)

    C0 = tuple(C_scr[i : i + 1, :] for i in range(4))

    def body(c, C):
        c00, c01, c10, c11 = C
        tile = pair_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]
        t00, t01, t10, t11 = _select4_prob(tile, tab_ref, nreal)
        for r in range(ROW_TILE):
            a00 = t00[r : r + 1, :]
            a01 = t01[r : r + 1, :]
            a10 = t10[r : r + 1, :]
            a11 = t11[r : r + 1, :]
            n00 = c00 * a00 + c01 * a10
            n01 = c00 * a01 + c01 * a11
            n10 = c10 * a00 + c11 * a10
            n11 = c10 * a01 + c11 * a11
            c00, c01, c10, c11 = n00, n01, n10, n11
        tot = c00 + c01 + c10 + c11
        inv = 1.0 / jnp.maximum(tot, 1e-30)
        return c00 * inv, c01 * inv, c10 * inv, c11 * inv

    C = jax.lax.fori_loop(0, bk // ROW_TILE, body, C0)
    for i in range(4):
        C_scr[i : i + 1, :] = C[i]

    @pl.when(j == n_t - 1)
    def _flush():
        for i in range(4):
            out_ref[i : i + 1, :] = C_scr[i : i + 1, :]


def _xla_products_prob(tab: jnp.ndarray, pair2: jnp.ndarray) -> jnp.ndarray:
    """XLA twin of the reduced products (non-TPU): per-step renorm instead of
    per-tile (directions identical; only the internal scalar differs)."""
    nP = tab.shape[0]
    NL = pair2.shape[1]
    ident = jnp.asarray([1.0, 0.0, 0.0, 1.0], jnp.float32)
    tab_ext = jnp.concatenate([tab, jnp.broadcast_to(ident, (1, 4))], axis=0)
    C0 = jnp.broadcast_to(ident, (NL, 4)) + (pair2[0, :, None] * 0).astype(jnp.float32)

    def step(C, pk):
        oh = jax.nn.one_hot(jnp.minimum(pk, nP), nP + 1, dtype=tab.dtype)
        T = jnp.matmul(oh, tab_ext, precision=jax.lax.Precision.HIGHEST)
        n00 = C[:, 0] * T[:, 0] + C[:, 1] * T[:, 2]
        n01 = C[:, 0] * T[:, 1] + C[:, 1] * T[:, 3]
        n10 = C[:, 2] * T[:, 0] + C[:, 3] * T[:, 2]
        n11 = C[:, 2] * T[:, 1] + C[:, 3] * T[:, 3]
        C = jnp.stack([n00, n01, n10, n11], axis=1)
        return C / jnp.maximum(jnp.sum(C, axis=1, keepdims=True), 1e-30), None

    C, _ = jax.lax.scan(step, C0, pair2)
    return C.reshape(NL, GROUP, GROUP)


def _scatter_products_prob(red, gt, e_in, e_out, K):
    """[NL, 2, 2] reduced products -> [NL, K, K] dense (zero fill) — exact:
    the dense product's out-of-group entries are multiplied by exact zeros
    in every consumer (entering directions / anchor compositions)."""
    from cpgisland_tpu.ops.viterbi_onehot import _scatter_products

    return _scatter_products(red, gt, e_in, e_out, K, fill=0.0)


# ---------------------------------------------------------------------------
# Reduced forward / backward kernels (the dense twins: fb_pallas._fwd_kernel,
# _bwd_kernel, _bwd_conf_kernel — same deferred-Rabiner / time-shifted-input
# structure, 2-component carries, 8 B/symbol streams instead of 32).


def _oh_fwd_kernel(pair_ref, lens_ref, a0raw_ref, tab_ref, alphas_ref,
                   carry_ref, *, nreal, Tt):
    j = pl.program_id(1)
    lens = lens_ref[0, :]
    v0 = jnp.where(j == 0, a0raw_ref[0:1, :], carry_ref[0:1, :])
    v1 = jnp.where(j == 0, a0raw_ref[1:2, :], carry_ref[1:2, :])

    def body(tile_i, carry):
        v0, v1 = carry
        base = tile_i * ROW_TILE
        tile = pair_ref[pl.ds(base, ROW_TILE), :]
        t00, t01, t10, t11 = _select4_prob(tile, tab_ref, nreal)
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            v_t = (t < lens)[None, :]
            # Deferred Rabiner: stored v_t = raw_t / sum(v_{t-1}); the sum
            # and reciprocal hang off the previous step, not the chain.
            inv = 1.0 / (v0 + v1)
            raw0 = v0 * t00[r : r + 1, :] + v1 * t10[r : r + 1, :]
            raw1 = v0 * t01[r : r + 1, :] + v1 * t11[r : r + 1, :]
            n0 = jnp.where(v_t, raw0 * inv, v0)
            n1 = jnp.where(v_t, raw1 * inv, v1)
            n0 = jnp.where(t == 0, a0raw_ref[0:1, :], n0)
            n1 = jnp.where(t == 0, a0raw_ref[1:2, :], n1)
            alphas_ref[base + r, :, :] = jnp.concatenate([n0, n1], axis=0)
            v0, v1 = n0, n1
        return v0, v1

    v0, v1 = jax.lax.fori_loop(0, Tt // ROW_TILE, body, (v0, v1))
    carry_ref[0:1, :] = v0
    carry_ref[1:2, :] = v1


def _oh_bwd_kernel(pairnext_ref, lens_ref, tab_ref, csnext_ref, beta0_ref,
                   betas_ref, beta_scr, *, nreal, Tt, T):
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lens = lens_ref[0, :]
    t0 = (n_t - 1 - j) * Tt

    @pl.when(j == 0)
    def _init():
        beta_scr[:, :] = beta0_ref[:, :]

    def body(tile_rev, carry):
        bn0, bn1 = carry
        base = (Tt // ROW_TILE - 1 - tile_rev) * ROW_TILE
        tile = pairnext_ref[pl.ds(base, ROW_TILE), :]
        cn = csnext_ref[pl.ds(base, ROW_TILE), :]
        t00, t01, t10, t11 = _select4_prob(tile, tab_ref, nreal)
        # Off-chain per-tile precompute: the next step's matrix rows scaled
        # by 1/c_{t+1} (the time-shifted inputs, like the dense twin).
        inv_cn = 1.0 / cn
        s00 = t00 * inv_cn
        s01 = t01 * inv_cn
        s10 = t10 * inv_cn
        s11 = t11 * inv_cn
        for rr in range(ROW_TILE):
            r = ROW_TILE - 1 - rr
            t = t0 + base + r
            active = t <= T - 2
            v_next = (t + 1) < lens
            b0 = s00[r : r + 1, :] * bn0 + s01[r : r + 1, :] * bn1
            b1 = s10[r : r + 1, :] * bn0 + s11[r : r + 1, :] * bn1
            keep = (active & v_next)[None, :]
            b0 = jnp.where(keep, b0, bn0)
            b1 = jnp.where(keep, b1, bn1)
            betas_ref[base + r, :, :] = jnp.concatenate([b0, b1], axis=0)
            bn0, bn1 = b0, b1
        return bn0, bn1

    bn0, bn1 = jax.lax.fori_loop(
        0, Tt // ROW_TILE, body, (beta_scr[0:1, :], beta_scr[1:2, :])
    )
    beta_scr[0:1, :] = bn0
    beta_scr[1:2, :] = bn1


def _oh_fwdbwd_kernel(pair_ref, pairn_ref, lens_ref, a0raw_ref, beta0_ref,
                      tab_ref, alphas_ref, betas_ref, fcarry, bcarry,
                      *, nreal, Tt, T):
    """CO-SCHEDULED forward + backward chains in ONE kernel launch.

    The r8 cost attribution (BASELINE.md "Where the ~8-11 ms go") showed
    the EM/posterior fixed cost is per-pass CHAIN DRAIN — three passes'
    worth of sequential 2x2 recurrence that cannot overlap the next pass's
    start.  Given the products-pass boundary messages, the forward and
    backward chains are INDEPENDENT (the classic coupling — backward needs
    the forward's Rabiner scales — is removed by self-normalizing the
    backward with its own deferred previous-step sum; stored betas are
    then per-position arbitrarily scaled DIRECTIONS, exactly what every
    reduced consumer is already invariant to: the z-normalized stats
    kernel, the conf ratio, the MPM argmax).  Grid cell j walks forward
    tile j AND backward tile n_t-1-j, interleaving the two recurrences
    per step so both chains fill VPU issue slots while either stalls —
    one chain drain instead of two.

    Outputs: alphas (deferred-Rabiner, = _oh_fwd_kernel bit-for-bit) and
    SELF-NORMALIZED betas (per-position scale differs from _oh_bwd_kernel;
    directions identical).  The XLA twin is :func:`_xla_fwdbwd_onehot` —
    one scan computing both chains, same arithmetic in the same order.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lens = lens_ref[0, :]
    v0 = jnp.where(j == 0, a0raw_ref[0:1, :], fcarry[0:1, :])
    v1 = jnp.where(j == 0, a0raw_ref[1:2, :], fcarry[1:2, :])
    bn0 = jnp.where(j == 0, beta0_ref[0:1, :], bcarry[0:1, :])
    bn1 = jnp.where(j == 0, beta0_ref[1:2, :], bcarry[1:2, :])
    bt0 = (n_t - 1 - j) * Tt  # global base of this cell's backward tile

    def body(tile_i, carry):
        v0, v1, bn0, bn1 = carry
        fbase = tile_i * ROW_TILE
        bbase = (Tt // ROW_TILE - 1 - tile_i) * ROW_TILE
        ftile = pair_ref[pl.ds(fbase, ROW_TILE), :]
        btile = pairn_ref[pl.ds(bbase, ROW_TILE), :]
        f00, f01, f10, f11 = _select4_prob(ftile, tab_ref, nreal)
        g00, g01, g10, g11 = _select4_prob(btile, tab_ref, nreal)
        for r in range(ROW_TILE):
            # -- forward row r (ascending) — _oh_fwd_kernel arithmetic.
            t = j * Tt + fbase + r
            v_t = (t < lens)[None, :]
            inv = 1.0 / (v0 + v1)
            raw0 = v0 * f00[r : r + 1, :] + v1 * f10[r : r + 1, :]
            raw1 = v0 * f01[r : r + 1, :] + v1 * f11[r : r + 1, :]
            n0 = jnp.where(v_t, raw0 * inv, v0)
            n1 = jnp.where(v_t, raw1 * inv, v1)
            n0 = jnp.where(t == 0, a0raw_ref[0:1, :], n0)
            n1 = jnp.where(t == 0, a0raw_ref[1:2, :], n1)
            alphas_ref[fbase + r, :, :] = jnp.concatenate([n0, n1], axis=0)
            v0, v1 = n0, n1
            # -- backward row (descending) — independent chain; the VPU
            # interleaves it with the forward's multiply-add tree.  Self-
            # normalized: divide by the previous beta's own sum (off-chain
            # reciprocal, the same deferred-Rabiner trick as the forward).
            rr = ROW_TILE - 1 - r
            tb = bt0 + bbase + rr
            active = tb <= T - 2
            v_next = (tb + 1) < lens
            binv = 1.0 / (bn0 + bn1)
            b0 = (g00[rr : rr + 1, :] * bn0 + g01[rr : rr + 1, :] * bn1) * binv
            b1 = (g10[rr : rr + 1, :] * bn0 + g11[rr : rr + 1, :] * bn1) * binv
            keep = (active & v_next)[None, :]
            b0 = jnp.where(keep, b0, bn0)
            b1 = jnp.where(keep, b1, bn1)
            betas_ref[bbase + rr, :, :] = jnp.concatenate([b0, b1], axis=0)
            bn0, bn1 = b0, b1
        return v0, v1, bn0, bn1

    v0, v1, bn0, bn1 = jax.lax.fori_loop(
        0, Tt // ROW_TILE, body, (v0, v1, bn0, bn1)
    )
    fcarry[0:1, :] = v0
    fcarry[1:2, :] = v1
    bcarry[0:1, :] = bn0
    bcarry[1:2, :] = bn1


def _oh_fwdbwd_mat_kernel(pair_ref, pairn_ref, lens_ref, tab_ref,
                          va_ref, wb_ref, fcarry, bcarry, *, nreal, Tt, T):
    """TRUE one-pass co-scheduled chains: ENTRY-FREE matrix carries.

    The reduced 2-state chains are LINEAR in their entry direction, so
    instead of seeding a0/beta0 (which the products pass had to compute
    first), this kernel carries the [2,2] transfer-matrix form of each
    chain — 4 carry rows per direction instead of 2 — seeded IDENTITY.
    The stored streams are then per-lane operators:

      Va[t] = M_1 . M_2 ... M_t   (renormalized by its own running sum —
                                   deferred, like the vector forward; the
                                   within-lane t == 0 row stores I, since
                                   M_0 belongs to the entry direction v_0)
      Wb[t] = M_{t+1} ... M_{l-1} (self-normalized like the r9 fused
                                   backward; the last valid row stores I)

    so alphas2[t] = a0_red^T . Va[t] and betas2[t] = Wb[t] . beta0_red are
    recovered by an ELEMENTWISE epilogue contraction once the boundary
    messages exist — and the per-lane transfer total itself is
    M_0 . Va[last], which replaces the standalone products pass: the r7
    reduced [NL, 2, 2] boundary combine becomes an O(NL) epilogue of THIS
    kernel's outputs, and posterior/em-seq drop to ONE T-scaling pass.

    The trade (ISSUE 17): 4 carry rows, 32 B/sym of stored stream instead
    of 16, wider VMEM footprint (graftmem family ``fb.fwdbwdmat.onehot``)
    — only decidable on silicon, so the 2-pass arm stays routable
    (``one_pass`` static arg everywhere).  Scale contract: Va rows are
    renormalized by the MATRIX total (sum of 4 entries), not the vector
    sum — contracted alphas2 carry a different (still deferred) scale
    than the 2-pass stream, exact for every scale-free consumer and for
    the telescoped loglik (fb_pallas._seq_stats_core one-pass arm), and
    NOT a Rabiner cs source.  XLA twin: :func:`_xla_fwdbwd_mat_onehot`.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = pair_ref.shape[1]
    lens = lens_ref[0, :]
    one = jnp.ones((1, lt), jnp.float32)
    zero = jnp.zeros((1, lt), jnp.float32)
    v00 = jnp.where(j == 0, one, fcarry[0:1, :])
    v01 = jnp.where(j == 0, zero, fcarry[1:2, :])
    v10 = jnp.where(j == 0, zero, fcarry[2:3, :])
    v11 = jnp.where(j == 0, one, fcarry[3:4, :])
    w00 = jnp.where(j == 0, one, bcarry[0:1, :])
    w01 = jnp.where(j == 0, zero, bcarry[1:2, :])
    w10 = jnp.where(j == 0, zero, bcarry[2:3, :])
    w11 = jnp.where(j == 0, one, bcarry[3:4, :])
    bt0 = (n_t - 1 - j) * Tt  # global base of this cell's backward tile

    def body(tile_i, carry):
        v00, v01, v10, v11, w00, w01, w10, w11 = carry
        fbase = tile_i * ROW_TILE
        bbase = (Tt // ROW_TILE - 1 - tile_i) * ROW_TILE
        ftile = pair_ref[pl.ds(fbase, ROW_TILE), :]
        btile = pairn_ref[pl.ds(bbase, ROW_TILE), :]
        f00, f01, f10, f11 = _select4_prob(ftile, tab_ref, nreal)
        g00, g01, g10, g11 = _select4_prob(btile, tab_ref, nreal)
        for r in range(ROW_TILE):
            # -- forward row r (ascending): V <- V . M_t, each entry row
            # the _oh_fwd_kernel update; ONE deferred renorm scalar (the
            # matrix total) serves both rows.
            t = j * Tt + fbase + r
            v_t = (t < lens)[None, :]
            inv = 1.0 / (v00 + v01 + v10 + v11)
            a00 = f00[r : r + 1, :]
            a01 = f01[r : r + 1, :]
            a10 = f10[r : r + 1, :]
            a11 = f11[r : r + 1, :]
            r00 = v00 * a00 + v01 * a10
            r01 = v00 * a01 + v01 * a11
            r10 = v10 * a00 + v11 * a10
            r11 = v10 * a01 + v11 * a11
            n00 = jnp.where(v_t, r00 * inv, v00)
            n01 = jnp.where(v_t, r01 * inv, v01)
            n10 = jnp.where(v_t, r10 * inv, v10)
            n11 = jnp.where(v_t, r11 * inv, v11)
            n00 = jnp.where(t == 0, one, n00)
            n01 = jnp.where(t == 0, zero, n01)
            n10 = jnp.where(t == 0, zero, n10)
            n11 = jnp.where(t == 0, one, n11)
            va_ref[fbase + r, :, :] = jnp.concatenate(
                [n00, n01, n10, n11], axis=0
            )
            v00, v01, v10, v11 = n00, n01, n10, n11
            # -- backward row (descending): W <- M_{t+1} . W, independent
            # chain interleaved into the same VPU issue slots; self-
            # normalized by its own previous matrix total.
            rr = ROW_TILE - 1 - r
            tb = bt0 + bbase + rr
            active = tb <= T - 2
            v_next = (tb + 1) < lens
            binv = 1.0 / (w00 + w01 + w10 + w11)
            c00 = g00[rr : rr + 1, :]
            c01 = g01[rr : rr + 1, :]
            c10 = g10[rr : rr + 1, :]
            c11 = g11[rr : rr + 1, :]
            b00 = (c00 * w00 + c01 * w10) * binv
            b01 = (c00 * w01 + c01 * w11) * binv
            b10 = (c10 * w00 + c11 * w10) * binv
            b11 = (c10 * w01 + c11 * w11) * binv
            keep = (active & v_next)[None, :]
            b00 = jnp.where(keep, b00, w00)
            b01 = jnp.where(keep, b01, w01)
            b10 = jnp.where(keep, b10, w10)
            b11 = jnp.where(keep, b11, w11)
            wb_ref[bbase + rr, :, :] = jnp.concatenate(
                [b00, b01, b10, b11], axis=0
            )
            w00, w01, w10, w11 = b00, b01, b10, b11
        return v00, v01, v10, v11, w00, w01, w10, w11

    v00, v01, v10, v11, w00, w01, w10, w11 = jax.lax.fori_loop(
        0, Tt // ROW_TILE, body,
        (v00, v01, v10, v11, w00, w01, w10, w11),
    )
    fcarry[0:1, :] = v00
    fcarry[1:2, :] = v01
    fcarry[2:3, :] = v10
    fcarry[3:4, :] = v11
    bcarry[0:1, :] = w00
    bcarry[1:2, :] = w01
    bcarry[2:3, :] = w10
    bcarry[3:4, :] = w11


def _sel_mask2(tile, mtab_ref, n, by_sym, S):
    """Per-position island-mask components from the lane-broadcast mask
    table (rows 2k / 2k+1 = mask of the exit group's low/high state).

    The exit symbol of ANY pair index is p mod S (real pairs p = prev*S +
    cur; PAD pairs p = S*S + sym, and S | S*S), so when S is a power of two
    the table keys on ``tile & (S-1)`` — S rows and S compares instead of
    S*S + S (this kernel family is VPU-issue-bound).  Other S fall back to
    the full per-pair table."""
    key = tile & (S - 1) if by_sym else tile
    m0 = jnp.zeros(tile.shape, jnp.float32)
    m1 = jnp.zeros(tile.shape, jnp.float32)
    for k in range(n):
        cmp = key == k
        m0 = jnp.where(cmp, mtab_ref[2 * k : 2 * k + 1, :], m0)
        m1 = jnp.where(cmp, mtab_ref[2 * k + 1 : 2 * k + 2, :], m1)
    return m0, m1


def _oh_bwd_conf_kernel(pairnext_ref, pair_ref, lens_ref, tab_ref, csnext_ref,
                        beta0_ref, alphas_ref, mtab_ref, conf_ref, beta_scr,
                        *, nreal, nM, mask_by_sym, S, Tt, T):
    """The reduced backward walk EMITTING island confidence (dense twin:
    fb_pallas._bwd_conf_kernel) — betas never reach HBM; the island mask is
    selected PER POSITION from the pair stream (the islandness of the 2
    live states depends on the position's symbol group)."""
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lens = lens_ref[0, :]
    t0 = (n_t - 1 - j) * Tt

    @pl.when(j == 0)
    def _init():
        beta_scr[:, :] = beta0_ref[:, :]

    def body(tile_rev, carry):
        bn0, bn1 = carry
        base = (Tt // ROW_TILE - 1 - tile_rev) * ROW_TILE
        tile_n = pairnext_ref[pl.ds(base, ROW_TILE), :]
        tile_c = pair_ref[pl.ds(base, ROW_TILE), :]
        cn = csnext_ref[pl.ds(base, ROW_TILE), :]
        t00, t01, t10, t11 = _select4_prob(tile_n, tab_ref, nreal)
        m0, m1 = _sel_mask2(tile_c, mtab_ref, nM, mask_by_sym, S)
        inv_cn = 1.0 / cn
        s00 = t00 * inv_cn
        s01 = t01 * inv_cn
        s10 = t10 * inv_cn
        s11 = t11 * inv_cn
        conf_rows = [None] * ROW_TILE
        for rr in range(ROW_TILE):
            r = ROW_TILE - 1 - rr
            t = t0 + base + r
            active = t <= T - 2
            v_next = (t + 1) < lens
            b0 = s00[r : r + 1, :] * bn0 + s01[r : r + 1, :] * bn1
            b1 = s10[r : r + 1, :] * bn0 + s11[r : r + 1, :] * bn1
            keep = (active & v_next)[None, :]
            b0 = jnp.where(keep, b0, bn0)
            b1 = jnp.where(keep, b1, bn1)
            a_row = alphas_ref[base + r, :, :]  # [2, lt]
            g0 = a_row[0:1, :] * b0
            g1 = a_row[1:2, :] * b1
            tot = g0 + g1
            isl = m0[r : r + 1, :] * g0 + m1[r : r + 1, :] * g1
            valid = (t < lens)[None, :]
            conf_rows[r] = jnp.where(
                valid, isl * (1.0 / jnp.maximum(tot, 1e-30)), 0.0
            )
            bn0, bn1 = b0, b1
        conf_ref[pl.ds(base, ROW_TILE), :] = jnp.concatenate(conf_rows, axis=0)
        return bn0, bn1

    bn0, bn1 = jax.lax.fori_loop(
        0, Tt // ROW_TILE, body, (beta_scr[0:1, :], beta_scr[1:2, :])
    )
    beta_scr[0:1, :] = bn0
    beta_scr[1:2, :] = bn1


def _sel_sym_tables(tile, brtab_ref, gttab_ref, S, base=0):
    """(b0, b1, glow, ghigh) [8, lt] tiles keyed on the pair tile's exit
    symbol (tile & (S-1); pow2 S only — the ONE copy shared by both stats
    kernels, whose parity-twin relationship must not drift).  ``base``:
    static row offset of a member's slice in a stacked table."""
    key = tile & (S - 1)
    b0 = jnp.zeros(tile.shape, jnp.float32)
    b1 = jnp.zeros(tile.shape, jnp.float32)
    gl = jnp.zeros(tile.shape, jnp.int32)
    gh = jnp.zeros(tile.shape, jnp.int32)
    for k in range(S):
        cmp = key == k
        r = base + 2 * k
        b0 = jnp.where(cmp, brtab_ref[r : r + 1, :], b0)
        b1 = jnp.where(cmp, brtab_ref[r + 1 : r + 2, :], b1)
        gl = jnp.where(cmp, gttab_ref[r : r + 1, :], gl)
        gh = jnp.where(cmp, gttab_ref[r + 1 : r + 2, :], gh)
    return b0, b1, gl, gh


def _oh_stats_kernel(alphas_ref, betas_ref, pair_ref, lens_ref, brtab_ref,
                     gttab_ref, macc_ref, emit_ref, ll_ref,
                     macc_scr, emit_scr, ll_scr, aprev_scr,
                     *, K, S, Tt):
    """Reduced-stream twin of fb_pallas._stats_kernel (chunked semantics).

    Reads the 2-component alpha/beta streams (16 B/symbol vs the dense
    pass's 64 — the dense stats pass is streaming-bound) and rebuilds the
    dense [K, lt] alpha-hat / w rows IN REGISTERS from the per-position
    group ids, so the accumulator math (and its output contract) is
    identical to the dense kernel with no HBM scatter anywhere.  Emission
    counts accumulate in reduced [S*GROUP] buckets (gamma is zero outside
    the emitted symbol's group); macc keeps the dense [K*K] layout.

    brtab: lane-broadcast B_red ([S, GROUP] — B[gt[s,c], s]); gttab:
    lane-broadcast gt as int32 ([S, GROUP] state ids).  Lowers only for
    power-of-two S (the symbol of any pair index is then p & (S-1));
    run_stats_onehot raises for other S and its callers fall back to the
    dense stats pass.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = pair_ref.shape[1]
    lens = lens_ref[0, :]

    @pl.when(j == 0)
    def _init():
        macc_scr[:, :] = jnp.zeros((K * K, lt), jnp.float32)
        emit_scr[:, :] = jnp.zeros((S * GROUP, lt), jnp.float32)
        ll_scr[:, :] = jnp.zeros((1, lt), jnp.float32)
        aprev_scr[:, :] = jnp.zeros((K, lt), jnp.float32)

    iK = jax.lax.broadcasted_iota(jnp.int32, (K, lt), 0)

    def sel_sym_tables(tile):
        return _sel_sym_tables(tile, brtab_ref, gttab_ref, S)

    def body(tile_i, carry):
        aprev, macc, emit, ll = carry
        base = tile_i * ROW_TILE
        p_tile = pair_ref[pl.ds(base, ROW_TILE), :]
        b0t, b1t, glt, ght = sel_sym_tables(p_tile)
        esym = p_tile & (S - 1)
        macc = list(macc)
        emit = list(emit)
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            valid = (t < lens)[None, :]  # [1, lt]
            a_row = alphas_ref[base + r, :, :]  # [2, lt]
            b_row = betas_ref[base + r, :, :]
            a0 = a_row[0:1, :]
            a1 = a_row[1:2, :]
            be0 = b_row[0:1, :]
            be1 = b_row[1:2, :]
            cs = a0 + a1
            inv_cs = 1.0 / jnp.maximum(cs, 1e-30)
            g0 = a0 * be0
            g1 = a1 * be1
            inv_g = 1.0 / jnp.maximum(g0 + g1, 1e-30)
            gm0 = jnp.where(valid, g0 * inv_g, 0.0)
            gm1 = jnp.where(valid, g1 * inv_g, 0.0)
            # Reduced emission buckets: bucket = the emitted symbol itself.
            sym_r = esym[r : r + 1, :]
            for s in range(S):
                m = sym_r == s
                emit[2 * s] = emit[2 * s] + jnp.where(m, gm0, 0.0)
                emit[2 * s + 1] = emit[2 * s + 1] + jnp.where(m, gm1, 0.0)
            ll = ll + jnp.where(valid, jnp.log(jnp.maximum(cs, 1e-30)), 0.0)
            # Dense rows rebuilt in registers: w = B[:, o_t] * beta / c.
            glow = glt[r : r + 1, :]
            ghigh = ght[r : r + 1, :]
            w0 = b0t[r : r + 1, :] * be0 * inv_cs
            w1 = b1t[r : r + 1, :] * be1 * inv_cs
            w_full = jnp.where(iK == glow, w0, 0.0) + jnp.where(
                iK == ghigh, w1, 0.0
            )
            wm = jnp.where(jnp.logical_and(valid, t >= 1), w_full, 0.0)
            for jj in range(K):
                macc[jj] = macc[jj] + aprev[jj : jj + 1, :] * wm
            ah0 = a0 * inv_cs
            ah1 = a1 * inv_cs
            aprev = jnp.where(iK == glow, ah0, 0.0) + jnp.where(
                iK == ghigh, ah1, 0.0
            )
        return aprev, tuple(macc), tuple(emit), ll

    zeroK = jnp.zeros((K, lt), jnp.float32)
    zero1 = jnp.zeros((1, lt), jnp.float32)
    carry0 = (
        aprev_scr[:, :],
        tuple(zeroK for _ in range(K)),
        tuple(zero1 for _ in range(S * GROUP)),
        jnp.zeros((1, lt), jnp.float32),
    )
    aprev, macc, emit, ll = jax.lax.fori_loop(0, Tt // ROW_TILE, body, carry0)
    aprev_scr[:, :] = aprev
    for jj in range(K):
        sl = slice(jj * K, (jj + 1) * K)
        macc_scr[sl, :] = macc_scr[sl, :] + macc[jj]
    for i in range(S * GROUP):
        emit_scr[i : i + 1, :] = emit_scr[i : i + 1, :] + emit[i]
    ll_scr[:, :] = ll_scr[:, :] + ll

    @pl.when(j == n_t - 1)
    def _flush():
        macc_ref[:, :] = macc_scr[:, :]
        emit_ref[:, :] = emit_scr[:, :]
        ll_ref[:, :] = ll_scr[:, :]


def beta_scale_of(fused, one_pass=False):
    """The beta-stream scale convention a given FB launch produced:
    ``"cs"`` (split arm — true Rabiner cs-scaled), ``"selfnorm"`` (fused
    co-scheduled backward — per-position directions), or ``"matrix"``
    (one-pass transfer-matrix epilogue — also directions).  Route points
    pass this to :func:`run_stats_onehot`'s ``betas_scale`` so the r9
    bad pairing (cs-scaled stats over self-normalized betas) is
    unrepresentable, not merely documented."""
    if one_pass:
        return "matrix"
    return "selfnorm" if fused else "cs"


def run_stats_onehot(params, alphas2, betas2, pair2, lens2, gt, Tt, *,
                     betas_scale="cs"):
    """Per-lane count reductions from REDUCED streams — (macc [K*K, NL],
    emit_red [S*GROUP, NL], ll [1, NL]).  emit_red buckets are
    (symbol, group member): emit_full[gt[s, c], s] = emit_red[2s + c].
    Lowers to the kernel only for power-of-two S (the flagship S=4);
    other S raise on TPU — callers fall back to the dense stats pass
    (the XLA twin for non-TPU backends is S-generic).

    ``betas_scale`` is the routing guard (graftcheck Layer 6's runtime
    half): this kernel's macc is DEGREE 1 in its betas — the per-pair xi
    terms are resolved against the split backward's true cs scaling, so
    only ``"cs"`` betas are legal.  Fused ("selfnorm") and one-pass
    ("matrix") betas are per-position directions; pairing them here is
    the r9 chunked-stats bug and raises.  Those arms must route
    :func:`run_seq_stats_onehot` (z-normalized; scale-free in betas)
    with zero enters and an all-zero pair0 mask."""
    if betas_scale != "cs":
        raise ValueError(
            f"run_stats_onehot is cs-scaled (macc is degree 1 in betas) "
            f"but was routed {betas_scale!r} betas — self-normalized "
            f"directions must pair with the z-normalized "
            f"run_seq_stats_onehot (zero enters, all-zero pair0_mask); "
            f"'that pairing is a bug' (r9, CLAUDE.md)"
        )
    K, S = params.n_states, params.n_symbols
    Tp, _, NL = alphas2.shape
    by_sym = S & (S - 1) == 0
    if not by_sym and not _interpret():
        raise ValueError(
            "run_stats_onehot lowers only for power-of-two n_symbols; "
            "callers fall back to the dense stats pass otherwise"
        )
    B = jnp.exp(params.log_B).astype(jnp.float32)
    B_red = B[gt, jnp.arange(S)[:, None]]  # [S, GROUP]
    gt_tab = gt.astype(jnp.int32)
    if _interpret():
        # XLA twin: identical math over the reduced streams.
        esym2 = decode_esym(pair2, S)
        a0, a1 = alphas2[:, 0], alphas2[:, 1]
        be0, be1 = betas2[:, 0], betas2[:, 1]
        cs = a0 + a1
        inv_cs = 1.0 / jnp.maximum(cs, 1e-30)
        vmask = jnp.arange(Tp)[:, None] < lens2
        g0, g1 = a0 * be0, a1 * be1
        inv_g = 1.0 / jnp.maximum(g0 + g1, 1e-30)
        gm0 = jnp.where(vmask, g0 * inv_g, 0.0)
        gm1 = jnp.where(vmask, g1 * inv_g, 0.0)
        emit_rows = []
        for s in range(S):
            m = esym2 == s
            emit_rows.append(jnp.sum(jnp.where(m, gm0, 0.0), axis=0))
            emit_rows.append(jnp.sum(jnp.where(m, gm1, 0.0), axis=0))
        emit_red = jnp.stack(emit_rows, axis=0)  # [S*GROUP, NL]
        ll = jnp.sum(
            jnp.where(vmask, jnp.log(jnp.maximum(cs, 1e-30)), 0.0), axis=0
        )[None, :]
        Bsel0 = B_red[esym2, 0]
        Bsel1 = B_red[esym2, 1]
        w_full = scatter_streams(
            jnp.stack([Bsel0 * be0 * inv_cs, Bsel1 * be1 * inv_cs], axis=1),
            gt, esym2, K,
        )
        a_hat = scatter_streams(
            jnp.stack([a0 * inv_cs, a1 * inv_cs], axis=1), gt, esym2, K
        )
        pairm = vmask & (jnp.arange(Tp)[:, None] >= 1)
        aprev = jnp.concatenate([jnp.zeros((1, K, NL)), a_hat[:-1]], axis=0)
        aprev = jnp.where(pairm[:, None, :], aprev, 0.0)
        wq = jnp.where(pairm[:, None, :], w_full, 0.0)
        macc = jnp.einsum(
            "tin,tjn->ijn", aprev, wq, precision=jax.lax.Precision.HIGHEST
        ).reshape(K * K, NL)
        return macc, emit_red, ll
    lt = LANE_TILE
    n_t = Tp // Tt
    grid = (NL // lt, n_t)
    brtabb = _bcast_tab(B_red, lt)
    gttabb = _bcast_tab(gt_tab, lt)
    return pl.pallas_call(
        functools.partial(_oh_stats_kernel, K=K, S=S, Tt=Tt),
        grid=grid,
        in_specs=[
            _vspec((Tt, GROUP, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, GROUP, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, lt), lambda i, j: (j, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
            _vspec(brtabb.shape, lambda i, j: (0, 0)),
            _vspec(gttabb.shape, lambda i, j: (0, 0)),
        ],
        out_specs=[
            _vspec((K * K, lt), lambda i, j: (0, i)),
            _vspec((S * GROUP, lt), lambda i, j: (0, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K * K, NL), jnp.float32),
            jax.ShapeDtypeStruct((S * GROUP, NL), jnp.float32),
            jax.ShapeDtypeStruct((1, NL), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((K * K, lt), jnp.float32),
            pltpu.VMEM((S * GROUP, lt), jnp.float32),
            pltpu.VMEM((1, lt), jnp.float32),
            pltpu.VMEM((K, lt), jnp.float32),
        ],
    )(alphas2, betas2, pair2, lens2, brtabb, gttabb)


def _oh_seq_stats_kernel(alphas_ref, betas_ref, pair_ref, lens_ref, tab_ref,
                         brtab_ref, gttab_ref, enters_full_ref, enters_red_ref,
                         pair0m_ref, macc_ref, emit_ref, ll_ref,
                         macc_scr, emit_scr, ll_scr, aprev_scr, aprev2_scr,
                         *, K, S, nreal, Tt):
    """Reduced-stream stats for the WHOLE-SEQUENCE (direction-beta) path.

    The chunked kernel's macc math needs true-scaled betas; the seq path's
    betas are per-lane DIRECTIONS, so this variant normalizes each pair's
    xi by its own total (z_t = sum_ac aprev2[a] * T[p_t][a, c] * beta2[c] —
    the pair table supplies A*B, betas supply the rest), exactly the
    scale-free scheme of fb_pallas._seq_stats_core's XLA assembly, which
    remains the off-TPU lowering and the parity twin.  Per-lane boundary
    pairs are owned by the lane: at within-lane t == 0 the previous-alpha
    is the ENTERING message (enters_full / enters_red inputs, living on the
    entering group = the pair stream's per-lane seed symbol, which is also
    what T[p_0] maps from); ``pair0m`` masks only the global-init lane.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = pair_ref.shape[1]
    lens = lens_ref[0, :]
    pair0m = pair0m_ref[:, :]  # [1, lt] f32 0/1

    @pl.when(j == 0)
    def _init():
        macc_scr[:, :] = jnp.zeros((K * K, lt), jnp.float32)
        emit_scr[:, :] = jnp.zeros((S * GROUP, lt), jnp.float32)
        ll_scr[:, :] = jnp.zeros((1, lt), jnp.float32)
        aprev_scr[:, :] = jnp.zeros((K, lt), jnp.float32)
        aprev2_scr[:, :] = jnp.zeros((GROUP, lt), jnp.float32)

    iK = jax.lax.broadcasted_iota(jnp.int32, (K, lt), 0)

    def sel_sym_tables(tile):
        return _sel_sym_tables(tile, brtab_ref, gttab_ref, S)

    def body(tile_i, carry):
        aprev, ap2_0, ap2_1, macc, emit, ll = carry
        base = tile_i * ROW_TILE
        p_tile = pair_ref[pl.ds(base, ROW_TILE), :]
        t00, t01, t10, t11 = _select4_prob(p_tile, tab_ref, nreal)
        b0t, b1t, glt, ght = sel_sym_tables(p_tile)
        esym = p_tile & (S - 1)
        macc = list(macc)
        emit = list(emit)
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            valid = (t < lens)[None, :]  # [1, lt]
            a_row = alphas_ref[base + r, :, :]  # [2, lt]
            b_row = betas_ref[base + r, :, :]
            a0 = a_row[0:1, :]
            a1 = a_row[1:2, :]
            be0 = b_row[0:1, :]
            be1 = b_row[1:2, :]
            cs = a0 + a1
            inv_cs = 1.0 / jnp.maximum(cs, 1e-30)
            g0 = a0 * be0
            g1 = a1 * be1
            inv_g = 1.0 / jnp.maximum(g0 + g1, 1e-30)
            gm0 = jnp.where(valid, g0 * inv_g, 0.0)
            gm1 = jnp.where(valid, g1 * inv_g, 0.0)
            sym_r = esym[r : r + 1, :]
            for s in range(S):
                m = sym_r == s
                emit[2 * s] = emit[2 * s] + jnp.where(m, gm0, 0.0)
                emit[2 * s + 1] = emit[2 * s + 1] + jnp.where(m, gm1, 0.0)
            ll = ll + jnp.where(valid, jnp.log(jnp.maximum(cs, 1e-30)), 0.0)
            # Within-lane t == 0: the previous alpha is the entering message.
            is0 = t == 0
            apf = jnp.where(is0, enters_full_ref[:, :], aprev)
            ap0 = jnp.where(is0, enters_red_ref[0:1, :], ap2_0)
            ap1 = jnp.where(is0, enters_red_ref[1:2, :], ap2_1)
            pairm = jnp.where(is0, valid * pair0m, valid.astype(jnp.float32))
            # Scale-free xi: z = sum_ac aprev2[a] T[a,c] beta2[c].
            z = ap0 * (t00[r : r + 1, :] * be0 + t01[r : r + 1, :] * be1) + \
                ap1 * (t10[r : r + 1, :] * be0 + t11[r : r + 1, :] * be1)
            inv_z = pairm * (1.0 / jnp.maximum(z, 1e-30))
            glow = glt[r : r + 1, :]
            ghigh = ght[r : r + 1, :]
            w_full = jnp.where(iK == glow, b0t[r : r + 1, :] * be0, 0.0) + \
                jnp.where(iK == ghigh, b1t[r : r + 1, :] * be1, 0.0)
            wz = w_full * inv_z
            for jj in range(K):
                macc[jj] = macc[jj] + apf[jj : jj + 1, :] * wz
            ah0 = a0 * inv_cs
            ah1 = a1 * inv_cs
            aprev = jnp.where(iK == glow, ah0, 0.0) + jnp.where(
                iK == ghigh, ah1, 0.0
            )
            ap2_0, ap2_1 = ah0, ah1
        return aprev, ap2_0, ap2_1, tuple(macc), tuple(emit), ll

    zeroK = jnp.zeros((K, lt), jnp.float32)
    zero1 = jnp.zeros((1, lt), jnp.float32)
    carry0 = (
        aprev_scr[:, :],
        aprev2_scr[0:1, :],
        aprev2_scr[1:2, :],
        tuple(zeroK for _ in range(K)),
        tuple(zero1 for _ in range(S * GROUP)),
        jnp.zeros((1, lt), jnp.float32),
    )
    aprev, ap2_0, ap2_1, macc, emit, ll = jax.lax.fori_loop(
        0, Tt // ROW_TILE, body, carry0
    )
    aprev_scr[:, :] = aprev
    aprev2_scr[0:1, :] = ap2_0
    aprev2_scr[1:2, :] = ap2_1
    for jj in range(K):
        sl = slice(jj * K, (jj + 1) * K)
        macc_scr[sl, :] = macc_scr[sl, :] + macc[jj]
    for i in range(S * GROUP):
        emit_scr[i : i + 1, :] = emit_scr[i : i + 1, :] + emit[i]
    ll_scr[:, :] = ll_scr[:, :] + ll

    @pl.when(j == n_t - 1)
    def _flush():
        macc_ref[:, :] = macc_scr[:, :]
        emit_ref[:, :] = emit_scr[:, :]
        ll_ref[:, :] = ll_scr[:, :]


def run_seq_stats_onehot(params, alphas2, betas2, pair2, lens2, gt,
                         enters_red, enters_full, pair0_mask, Tt):
    """Z-normalized stats from REDUCED streams (power-of-two S; callers
    keep the scatter + XLA assembly for other S).  Per-pair xi
    normalization makes the scheme invariant to ANY per-position beta
    scale — it serves the cs-scaled split streams, the self-normalized
    fused streams, AND (with zero enters + an all-zero pair0_mask) the
    chunked layout, whose lanes are independent records with no incoming
    t==0 pair.  Returns (macc [K*K, NL] — trans = A * macc-sum; emit_red
    [S*GROUP, NL]; ll [1, NL]).  Off-TPU lowering: the arithmetic twin
    :func:`_xla_znorm_stats` (the kernel's parity reference)."""
    K, S = params.n_states, params.n_symbols
    if S & (S - 1):
        raise ValueError("run_seq_stats_onehot: power-of-two S only")
    if _interpret():
        return _xla_znorm_stats(
            params, alphas2, betas2, pair2, lens2, gt, enters_red,
            enters_full, pair0_mask,
        )
    Tp, _, NL = alphas2.shape
    tab = prob_pair_table(params, gt)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    B_red = B[gt, jnp.arange(S)[:, None]]
    lt = LANE_TILE
    grid = (NL // lt, Tp // Tt)
    tabb = _bcast_tab(tab, lt)
    brtabb = _bcast_tab(B_red, lt)
    gttabb = _bcast_tab(gt.astype(jnp.int32), lt)
    return pl.pallas_call(
        functools.partial(_oh_seq_stats_kernel, K=K, S=S, nreal=S * S, Tt=Tt),
        grid=grid,
        in_specs=[
            _vspec((Tt, GROUP, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, GROUP, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, lt), lambda i, j: (j, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
            _vspec(tabb.shape, lambda i, j: (0, 0)),
            _vspec(brtabb.shape, lambda i, j: (0, 0)),
            _vspec(gttabb.shape, lambda i, j: (0, 0)),
            _vspec((K, lt), lambda i, j: (0, i)),
            _vspec((GROUP, lt), lambda i, j: (0, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
        ],
        out_specs=[
            _vspec((K * K, lt), lambda i, j: (0, i)),
            _vspec((S * GROUP, lt), lambda i, j: (0, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K * K, NL), jnp.float32),
            jax.ShapeDtypeStruct((S * GROUP, NL), jnp.float32),
            jax.ShapeDtypeStruct((1, NL), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((K * K, lt), jnp.float32),
            pltpu.VMEM((S * GROUP, lt), jnp.float32),
            pltpu.VMEM((1, lt), jnp.float32),
            pltpu.VMEM((K, lt), jnp.float32),
            pltpu.VMEM((GROUP, lt), jnp.float32),
        ],
    )(alphas2, betas2, pair2, lens2, tabb, brtabb, gttabb,
      enters_full, enters_red, pair0_mask)


# --- XLA twins (non-TPU backends; same arithmetic, scan lowering) ----------


def _tab_sel_nl(tab_ext, pk):
    """Exact per-lane row select [NL] -> [NL, m] (one-hot contraction)."""
    oh = jax.nn.one_hot(pk, tab_ext.shape[0], dtype=tab_ext.dtype)
    return jnp.matmul(oh, tab_ext, precision=jax.lax.Precision.HIGHEST)


def _xla_fwd_onehot(tab_ext, pair2, lens2, a0_red):
    """Reduced forward scan: returns alphas2 [Tp, 2, NL] (deferred-scale)."""
    Tp = pair2.shape[0]
    lens = lens2[0]

    def step(carry, x):
        v0, v1 = carry
        pk, t = x
        T4 = _tab_sel_nl(tab_ext, pk)
        inv = 1.0 / (v0 + v1)
        raw0 = v0 * T4[:, 0] + v1 * T4[:, 2]
        raw1 = v0 * T4[:, 1] + v1 * T4[:, 3]
        v_t = t < lens
        n0 = jnp.where(v_t, raw0 * inv, v0)
        n1 = jnp.where(v_t, raw1 * inv, v1)
        n0 = jnp.where(t == 0, a0_red[:, 0], n0)
        n1 = jnp.where(t == 0, a0_red[:, 1], n1)
        return (n0, n1), jnp.stack([n0, n1], axis=0)

    _, alphas2 = jax.lax.scan(
        step, (a0_red[:, 0], a0_red[:, 1]),
        (pair2, jnp.arange(Tp, dtype=jnp.int32)),
    )
    return alphas2  # [Tp, 2, NL]


def _xla_bwd_onehot(tab_ext, pair_next, lens2, cs_next, beta0_red, T):
    Tp = pair_next.shape[0]
    lens = lens2[0]

    def step(carry, x):
        bn0, bn1 = carry
        pk, cn, t = x
        T4 = _tab_sel_nl(tab_ext, pk)
        inv_cn = 1.0 / cn
        b0 = (T4[:, 0] * bn0 + T4[:, 1] * bn1) * inv_cn
        b1 = (T4[:, 2] * bn0 + T4[:, 3] * bn1) * inv_cn
        keep = (t <= T - 2) & ((t + 1) < lens)
        b0 = jnp.where(keep, b0, bn0)
        b1 = jnp.where(keep, b1, bn1)
        return (b0, b1), jnp.stack([b0, b1], axis=0)

    _, betas2 = jax.lax.scan(
        step, (beta0_red[:, 0], beta0_red[:, 1]),
        (pair_next, cs_next, jnp.arange(Tp, dtype=jnp.int32)),
        reverse=True,
    )
    return betas2


def _xla_fwdbwd_onehot(tab_ext, pair2, pair_next, lens2, a0_red, beta0_red, T):
    """XLA twin of :func:`_oh_fwdbwd_kernel`: ONE scan computing both the
    forward chain (position k ascending) and the self-normalized backward
    chain (position Tp-1-k descending) — the fused pass the cost contracts
    count (posterior/em-seq drop to 2 T-scaling passes, chunked EM to 1).
    Returns (alphas2 [Tp, 2, NL], betas2 [Tp, 2, NL] self-normalized)."""
    Tp = pair2.shape[0]
    lens = lens2[0]
    pairn_rev = jnp.flip(pair_next, axis=0)

    def step(carry, x):
        v0, v1, bn0, bn1 = carry
        pk, qk, t = x
        T4 = _tab_sel_nl(tab_ext, pk)
        G4 = _tab_sel_nl(tab_ext, qk)
        # forward — _xla_fwd_onehot arithmetic, same order.
        inv = 1.0 / (v0 + v1)
        raw0 = v0 * T4[:, 0] + v1 * T4[:, 2]
        raw1 = v0 * T4[:, 1] + v1 * T4[:, 3]
        v_t = t < lens
        n0 = jnp.where(v_t, raw0 * inv, v0)
        n1 = jnp.where(v_t, raw1 * inv, v1)
        n0 = jnp.where(t == 0, a0_red[:, 0], n0)
        n1 = jnp.where(t == 0, a0_red[:, 1], n1)
        # backward at tb = Tp-1-t — self-normalized (the kernel's order:
        # raw contraction first, then the off-chain previous-sum scale).
        tb = Tp - 1 - t
        binv = 1.0 / (bn0 + bn1)
        b0 = (G4[:, 0] * bn0 + G4[:, 1] * bn1) * binv
        b1 = (G4[:, 2] * bn0 + G4[:, 3] * bn1) * binv
        keep = (tb <= T - 2) & ((tb + 1) < lens)
        b0 = jnp.where(keep, b0, bn0)
        b1 = jnp.where(keep, b1, bn1)
        return (n0, n1, b0, b1), (
            jnp.stack([n0, n1], axis=0), jnp.stack([b0, b1], axis=0)
        )

    _, (alphas2, betas_rev) = jax.lax.scan(
        step,
        (a0_red[:, 0], a0_red[:, 1], beta0_red[:, 0], beta0_red[:, 1]),
        (pair2, pairn_rev, jnp.arange(Tp, dtype=jnp.int32)),
    )
    return alphas2, jnp.flip(betas_rev, axis=0)


def _xla_fwdbwd_mat_onehot(tab_ext, pair2, pair_next, lens2, T):
    """XLA twin of :func:`_oh_fwdbwd_mat_kernel`: ONE scan carrying BOTH
    matrix chains (8 components) — the single T-scaling pass the one-pass
    cost contracts count.  Entry-free; same arithmetic in the same order
    as the chip kernel.  Returns (Va [Tp, 4, NL], Wb [Tp, 4, NL])."""
    Tp, NL = pair2.shape
    lens = lens2[0]
    pairn_rev = jnp.flip(pair_next, axis=0)
    one = jnp.ones((NL,), jnp.float32)
    zero = jnp.zeros((NL,), jnp.float32)

    def step(carry, x):
        v00, v01, v10, v11, w00, w01, w10, w11 = carry
        pk, qk, t = x
        T4 = _tab_sel_nl(tab_ext, pk)
        G4 = _tab_sel_nl(tab_ext, qk)
        # forward: V <- V . M_t, matrix-total deferred renorm.
        inv = 1.0 / (v00 + v01 + v10 + v11)
        r00 = v00 * T4[:, 0] + v01 * T4[:, 2]
        r01 = v00 * T4[:, 1] + v01 * T4[:, 3]
        r10 = v10 * T4[:, 0] + v11 * T4[:, 2]
        r11 = v10 * T4[:, 1] + v11 * T4[:, 3]
        v_t = t < lens
        n00 = jnp.where(v_t, r00 * inv, v00)
        n01 = jnp.where(v_t, r01 * inv, v01)
        n10 = jnp.where(v_t, r10 * inv, v10)
        n11 = jnp.where(v_t, r11 * inv, v11)
        n00 = jnp.where(t == 0, one, n00)
        n01 = jnp.where(t == 0, zero, n01)
        n10 = jnp.where(t == 0, zero, n10)
        n11 = jnp.where(t == 0, one, n11)
        # backward at tb = Tp-1-t: W <- M_{tb+1} . W, self-normalized.
        tb = Tp - 1 - t
        binv = 1.0 / (w00 + w01 + w10 + w11)
        b00 = (G4[:, 0] * w00 + G4[:, 1] * w10) * binv
        b01 = (G4[:, 0] * w01 + G4[:, 1] * w11) * binv
        b10 = (G4[:, 2] * w00 + G4[:, 3] * w10) * binv
        b11 = (G4[:, 2] * w01 + G4[:, 3] * w11) * binv
        keep = (tb <= T - 2) & ((tb + 1) < lens)
        b00 = jnp.where(keep, b00, w00)
        b01 = jnp.where(keep, b01, w01)
        b10 = jnp.where(keep, b10, w10)
        b11 = jnp.where(keep, b11, w11)
        return (n00, n01, n10, n11, b00, b01, b10, b11), (
            jnp.stack([n00, n01, n10, n11], axis=0),
            jnp.stack([b00, b01, b10, b11], axis=0),
        )

    _, (va, wb_rev) = jax.lax.scan(
        step,
        (one, zero, zero, one, one, zero, zero, one),
        (pair2, pairn_rev, jnp.arange(Tp, dtype=jnp.int32)),
    )
    return va, jnp.flip(wb_rev, axis=0)


def conf_from_reduced(alphas2, betas2, esym2, lens2, conf_mask, gt):
    """Per-position island confidence from the reduced streams (elementwise
    — no serial chain, so it is NOT a pass in the cost-contract sense; the
    throughput epilogue of the fused fwd/bwd pass).  Scale-free: any
    per-position scale on the betas cancels in the ratio, which is what
    makes the self-normalized fused backward exact here.  The one
    implementation shared by both platforms (TPU runs it as fused XLA
    elementwise ops over the kernel outputs)."""
    S = gt.shape[0]
    mtab = conf_mask[gt].astype(jnp.float32)  # [S, GROUP]
    m0 = jnp.zeros(esym2.shape, jnp.float32)
    m1 = jnp.zeros(esym2.shape, jnp.float32)
    for s in range(S):
        cmp = esym2 == s
        m0 = jnp.where(cmp, mtab[s, 0], m0)
        m1 = jnp.where(cmp, mtab[s, 1], m1)
    graw0 = alphas2[:, 0] * betas2[:, 0]
    graw1 = alphas2[:, 1] * betas2[:, 1]
    tot = jnp.maximum(graw0 + graw1, 1e-30)
    vmask = jnp.arange(alphas2.shape[0])[:, None] < lens2
    return jnp.where(vmask, (m0 * graw0 + m1 * graw1) / tot, 0.0)


def _xla_znorm_stats(params, alphas2, betas2, pair2, lens2, gt, enters_red,
                     enters_full, pair0_mask):
    """XLA twin of :func:`_oh_seq_stats_kernel` on the REDUCED streams —
    the off-TPU lowering of run_seq_stats_onehot (and, with zero enters +
    an all-zero pair0_mask, of the fused chunked stats: every lane is an
    independent record whose t==0 has no incoming pair).  Same per-pair
    z-normalized scale-free xi, so it is exact for betas carrying ANY
    per-position scale (cs-scaled split streams and self-normalized fused
    streams alike)."""
    K, S = params.n_states, params.n_symbols
    Tp, _, NL = alphas2.shape
    tab = prob_pair_table(params, gt)
    ident = jnp.asarray([PROB_IDENT], jnp.float32)
    tab_ext = jnp.concatenate([tab, ident], axis=0)
    T4 = _tab_sel_nl(tab_ext, jnp.minimum(pair2, S * S).reshape(-1)).reshape(
        Tp, NL, 4
    )
    esym2 = decode_esym(pair2, S)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    B_red = B[gt, jnp.arange(S)[:, None]]  # [S, GROUP]
    a0, a1 = alphas2[:, 0], alphas2[:, 1]
    be0, be1 = betas2[:, 0], betas2[:, 1]
    cs = a0 + a1
    inv_cs = 1.0 / jnp.maximum(cs, 1e-30)
    vmask = jnp.arange(Tp)[:, None] < lens2
    g0, g1 = a0 * be0, a1 * be1
    inv_g = 1.0 / jnp.maximum(g0 + g1, 1e-30)
    gm0 = jnp.where(vmask, g0 * inv_g, 0.0)
    gm1 = jnp.where(vmask, g1 * inv_g, 0.0)
    emit_rows = []
    for s in range(S):
        m = esym2 == s
        emit_rows.append(jnp.sum(jnp.where(m, gm0, 0.0), axis=0))
        emit_rows.append(jnp.sum(jnp.where(m, gm1, 0.0), axis=0))
    emit_red = jnp.stack(emit_rows, axis=0)  # [S*GROUP, NL]
    ll = jnp.sum(
        jnp.where(vmask, jnp.log(jnp.maximum(cs, 1e-30)), 0.0), axis=0
    )[None, :]
    # Previous-position a_hat (reduced + full-K scatter), entering messages
    # at within-lane t == 0 — the kernel's is0 branch.
    ah2 = jnp.stack([a0 * inv_cs, a1 * inv_cs], axis=1)  # [Tp, 2, NL]
    ah_full = scatter_streams(ah2, gt, esym2, K)  # [Tp, K, NL]
    ap2 = jnp.concatenate([enters_red[None], ah2[:-1]], axis=0)
    apf = jnp.concatenate([enters_full[None], ah_full[:-1]], axis=0)
    pairm = vmask.astype(jnp.float32)
    pairm = pairm.at[0].set(pairm[0] * pair0_mask[0])
    z = ap2[:, 0] * (T4[..., 0] * be0 + T4[..., 1] * be1) + \
        ap2[:, 1] * (T4[..., 2] * be0 + T4[..., 3] * be1)
    inv_z = pairm * (1.0 / jnp.maximum(z, 1e-30))
    w_full = scatter_streams(
        jnp.stack([B_red[esym2, 0] * be0, B_red[esym2, 1] * be1], axis=1),
        gt, esym2, K,
    )
    wz = w_full * inv_z[:, None, :]
    macc = jnp.einsum(
        "tin,tjn->ijn", apf, wz, precision=jax.lax.Precision.HIGHEST
    ).reshape(K * K, NL)
    return macc, emit_red, ll


# --- runner + scatter glue -------------------------------------------------


def decode_esym(pair2: jnp.ndarray, S: int) -> jnp.ndarray:
    """Per-position emitted symbol (PADs forward-filled) from pair indices:
    p < S*S encodes (prev, cur) with cur = p mod S; p >= S*S is a PAD
    carrying symbol p - S*S."""
    cur = pair2 - (pair2 // S) * S
    return jnp.where(pair2 < S * S, cur, pair2 - S * S).astype(jnp.int32)


def scatter_streams(x2: jnp.ndarray, gt: jnp.ndarray, esym2: jnp.ndarray,
                    K: int) -> jnp.ndarray:
    """[Tp, 2, NL] reduced streams -> [Tp, K, NL] dense (zero fill) — exact
    for every consumer (out-of-group entries are exact zeros in the dense
    alphas, and the dense betas' nonzero out-of-group entries are only ever
    multiplied by those zeros or by one-hot emission zeros)."""
    glow = jnp.take(gt[:, 0], esym2)  # [Tp, NL]
    ghigh = jnp.take(gt[:, 1], esym2)
    iK = jnp.arange(K, dtype=jnp.int32)
    full = jnp.where(
        iK[None, :, None] == glow[:, None, :], x2[:, 0:1, :], 0.0
    )
    # The two group members are distinct states, so add-compose is exact.
    return full + jnp.where(
        iK[None, :, None] == ghigh[:, None, :], x2[:, 1:2, :], 0.0
    )


def run_fb_kernels_onehot(
    params: HmmParams,
    sel_t: jnp.ndarray,
    prev_dev,
    lens2: jnp.ndarray,
    a0_raw: jnp.ndarray,
    beta0: jnp.ndarray,
    Tt: int,
    T: int,
    conf_mask=None,
    pair_esym=None,
    fused: bool = False,
):
    """Reduced forward + backward pair over the [Tp, NL] lane layout.

    Mirrors fb_pallas._run_fb_kernels: a0_raw/beta0 arrive FULL-K [K, NL]
    and are projected onto each lane's entry/exit group here.  Returns
    (alphas2 [Tp, 2, NL], cs [Tp, NL], betas2 [Tp, 2, NL] — or conf2
    [Tp, NL] with ``conf_mask`` — and esym2 [Tp, NL] for scatter-back).
    ``pair_esym``: a prepared (pair2, esym2) or (pair2, esym2, pairn2)
    pair-stream (esym2/pairn2 may be None — they rederive arithmetically);
    inline prep otherwise.

    ``fused`` (static) co-schedules both chains in ONE launch
    (:func:`_oh_fwdbwd_kernel` / the one-scan XLA twin).  CONTRACT: the
    fused betas are SELF-NORMALIZED per-position directions, not the
    split path's cs-scaled betas — exact for every scale-free consumer
    (conf ratio, z-normalized stats, gamma/MPM argmax), WRONG for the
    chunked dense-stats kernel's cs-scaled macc (that caller must pass
    fused=False).  conf_mask + fused computes the confidence as a
    throughput-bound elementwise epilogue (:func:`conf_from_reduced`)
    instead of the in-backward conf emission.
    """
    K, S = params.n_states, params.n_symbols
    gt = _groups(params)
    tab = prob_pair_table(params, gt)
    pairn_pre = None
    if pair_esym is None:
        pair2, _, _ = _pair_stream(params, sel_t, jnp.asarray(prev_dev, jnp.int32))
        esym2 = decode_esym(pair2, S)
    else:
        pair2, esym2 = pair_esym[0], pair_esym[1]
        pairn_pre = pair_esym[2] if len(pair_esym) > 2 else None
        if esym2 is None:
            esym2 = decode_esym(pair2, S)
    Tp, NL = pair2.shape

    a0_red = jnp.take_along_axis(a0_raw.T, gt[esym2[0]], axis=1)  # [NL, 2]
    beta0_red = jnp.take_along_axis(beta0.T, gt[esym2[-1]], axis=1)
    pair_next = (
        pairn_pre
        if pairn_pre is not None
        else jnp.concatenate(
            [pair2[1:], jnp.full((1, NL), S * S, jnp.int32)], axis=0
        )
    )
    ident = jnp.asarray([PROB_IDENT], jnp.float32)
    tab_ext = jnp.concatenate([tab, ident], axis=0)
    pair_c = jnp.minimum(pair2, S * S)  # clamp PAD pairs onto the identity row
    pairn_c = jnp.minimum(pair_next, S * S)

    if _interpret():
        if fused:
            alphas2, betas2 = _xla_fwdbwd_onehot(
                tab_ext, pair_c, pairn_c, lens2, a0_red, beta0_red, T
            )
            cs = jnp.sum(alphas2, axis=1)
            if conf_mask is None:
                return alphas2, cs, betas2, esym2
            conf2 = conf_from_reduced(
                alphas2, betas2, esym2, lens2, conf_mask, gt
            )
            return alphas2, cs, conf2, esym2
        alphas2 = _xla_fwd_onehot(tab_ext, pair_c, lens2, a0_red)
        cs = jnp.sum(alphas2, axis=1)
        cs_next = jnp.concatenate([cs[1:], jnp.ones((1, NL), cs.dtype)], axis=0)
        betas2 = _xla_bwd_onehot(
            tab_ext, pairn_c, lens2, cs_next, beta0_red, T
        )
        if conf_mask is None:
            return alphas2, cs, betas2, esym2
        m2 = conf_mask[gt[esym2]]  # [Tp, NL, 2]
        graw0 = alphas2[:, 0] * betas2[:, 0]
        graw1 = alphas2[:, 1] * betas2[:, 1]
        tot = jnp.maximum(graw0 + graw1, 1e-30)
        vmask = jnp.arange(Tp)[:, None] < lens2
        conf2 = jnp.where(
            vmask, (m2[..., 0] * graw0 + m2[..., 1] * graw1) / tot, 0.0
        )
        return alphas2, cs, conf2, esym2

    from cpgisland_tpu.ops.fb_pallas import _fb_lane_tile

    lt = _fb_lane_tile(NL)
    n_t = Tp // Tt
    grid = (NL // lt, n_t)
    lane_spec = _vspec((1, lt), lambda i, j: (0, i))
    glane_spec = _vspec((GROUP, lt), lambda i, j: (0, i))
    step_spec = _vspec((Tt, lt), lambda i, j: (j, i))
    tabb = _bcast_tab(tab, lt)
    if fused:
        rev_spec = _vspec((Tt, lt), lambda i, j: (n_t - 1 - j, i))
        alphas2, betas2 = pl.pallas_call(
            functools.partial(_oh_fwdbwd_kernel, nreal=S * S, Tt=Tt, T=T),
            grid=grid,
            in_specs=[
                step_spec,
                rev_spec,
                lane_spec,
                glane_spec,
                glane_spec,
                _vspec(tabb.shape, lambda i, j: (0, 0)),
            ],
            out_specs=[
                _vspec((Tt, GROUP, lt), lambda i, j: (j, 0, i)),
                _vspec((Tt, GROUP, lt), lambda i, j: (n_t - 1 - j, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((Tp, GROUP, NL), jnp.float32),
                jax.ShapeDtypeStruct((Tp, GROUP, NL), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((GROUP, lt), jnp.float32),
                pltpu.VMEM((GROUP, lt), jnp.float32),
            ],
        )(pair2, pair_next, lens2, a0_red.T, beta0_red.T, tabb)
        cs = jnp.sum(alphas2, axis=1)
        if conf_mask is None:
            return alphas2, cs, betas2, esym2
        conf2 = conf_from_reduced(alphas2, betas2, esym2, lens2, conf_mask, gt)
        return alphas2, cs, conf2, esym2
    (alphas2,) = pl.pallas_call(
        functools.partial(_oh_fwd_kernel, nreal=S * S, Tt=Tt),
        grid=grid,
        in_specs=[step_spec, lane_spec, glane_spec, _vspec(tabb.shape, lambda i, j: (0, 0))],
        out_specs=[_vspec((Tt, GROUP, lt), lambda i, j: (j, 0, i))],
        out_shape=[jax.ShapeDtypeStruct((Tp, GROUP, NL), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((GROUP, lt), jnp.float32)],
    )(pair2, lens2, a0_red.T, tabb)
    cs = jnp.sum(alphas2, axis=1)
    cs_next = jnp.concatenate([cs[1:], jnp.ones((1, NL), cs.dtype)], axis=0)
    rev_step_spec = _vspec((Tt, lt), lambda i, j: (n_t - 1 - j, i))
    if conf_mask is not None:
        # Per-pair island-mask components (traced values — changing the
        # island set never recompiles).
        from cpgisland_tpu.ops.viterbi_onehot import pair_exit_syms

        mask_by_sym = S & (S - 1) == 0  # exit symbol = pair & (S-1)
        mtab = conf_mask[
            gt if mask_by_sym else gt[pair_exit_syms(S)]
        ].astype(jnp.float32)
        mtabb = _bcast_tab(mtab, lt)
        (conf2,) = pl.pallas_call(
            functools.partial(
                _oh_bwd_conf_kernel, nreal=S * S, nM=mtab.shape[0],
                mask_by_sym=mask_by_sym, S=S, Tt=Tt, T=T
            ),
            grid=grid,
            in_specs=[
                rev_step_spec,
                rev_step_spec,
                lane_spec,
                _vspec(tabb.shape, lambda i, j: (0, 0)),
                rev_step_spec,
                glane_spec,
                _vspec((Tt, GROUP, lt), lambda i, j: (n_t - 1 - j, 0, i)),
                _vspec(mtabb.shape, lambda i, j: (0, 0)),
            ],
            out_specs=[rev_step_spec],
            out_shape=[jax.ShapeDtypeStruct((Tp, NL), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((GROUP, lt), jnp.float32)],
        )(pair_next, pair2, lens2, tabb, cs_next, beta0_red.T, alphas2, mtabb)
        return alphas2, cs, conf2, esym2
    (betas2,) = pl.pallas_call(
        functools.partial(_oh_bwd_kernel, nreal=S * S, Tt=Tt, T=T),
        grid=grid,
        in_specs=[
            rev_step_spec,
            lane_spec,
            _vspec(tabb.shape, lambda i, j: (0, 0)),
            rev_step_spec,
            glane_spec,
        ],
        out_specs=[_vspec((Tt, GROUP, lt), lambda i, j: (n_t - 1 - j, 0, i))],
        out_shape=[jax.ShapeDtypeStruct((Tp, GROUP, NL), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((GROUP, lt), jnp.float32)],
    )(pair_next, lens2, tabb, cs_next, beta0_red.T)
    return alphas2, cs, betas2, esym2


def run_fb_mat_onehot(params: HmmParams, lens2: jnp.ndarray, Tt: int, T: int,
                      pair_esym):
    """ENTRY-FREE matrix-carried chains over the [Tp, NL] lane layout —
    the ONE T-scaling pass of the one-pass posterior/em-seq arm.

    Unlike :func:`run_fb_kernels_onehot` this needs NO boundary messages
    (no a0/beta0 inputs): the kernel carries the [2,2] transfer-matrix
    form of both chains, and the per-lane transfer total that the
    standalone products pass used to compute is recovered here as the
    O(NL) epilogue ``red[n] = M_0(n) . Va[last, n]`` (bit-compatible
    directions with products_reduced — only the internal renorm scalar
    differs, exactly the products-kernel-vs-XLA-twin relationship).

    ``pair_esym``: (pair2, esym2-or-None, pairn2-or-None) — the pair
    stream is REQUIRED (every one-pass caller already built it for the
    boundary epilogue).  Returns (va [Tp, 4, NL], wb [Tp, 4, NL],
    esym2 [Tp, NL], red [NL, 2, 2]); contract the streams with
    :func:`contract_mat_streams` once boundary messages exist.
    """
    S = params.n_symbols
    gt = _groups(params)
    tab = prob_pair_table(params, gt)
    pair2, esym2 = pair_esym[0], pair_esym[1]
    pairn_pre = pair_esym[2] if len(pair_esym) > 2 else None
    if esym2 is None:
        esym2 = decode_esym(pair2, S)
    Tp, NL = pair2.shape
    pair_next = (
        pairn_pre
        if pairn_pre is not None
        else jnp.concatenate(
            [pair2[1:], jnp.full((1, NL), S * S, jnp.int32)], axis=0
        )
    )
    ident = jnp.asarray([PROB_IDENT], jnp.float32)
    tab_ext = jnp.concatenate([tab, ident], axis=0)
    pair_c = jnp.minimum(pair2, S * S)
    pairn_c = jnp.minimum(pair_next, S * S)

    if _interpret():
        va, wb = _xla_fwdbwd_mat_onehot(tab_ext, pair_c, pairn_c, lens2, T)
    else:
        from cpgisland_tpu.ops.fb_pallas import _fb_lane_tile

        lt = _fb_lane_tile(NL)
        n_t = Tp // Tt
        grid = (NL // lt, n_t)
        G2 = GROUP * GROUP
        tabb = _bcast_tab(tab, lt)
        va, wb = pl.pallas_call(
            functools.partial(_oh_fwdbwd_mat_kernel, nreal=S * S, Tt=Tt, T=T),
            grid=grid,
            in_specs=[
                _vspec((Tt, lt), lambda i, j: (j, i)),
                _vspec((Tt, lt), lambda i, j: (n_t - 1 - j, i)),
                _vspec((1, lt), lambda i, j: (0, i)),
                _vspec(tabb.shape, lambda i, j: (0, 0)),
            ],
            out_specs=[
                _vspec((Tt, G2, lt), lambda i, j: (j, 0, i)),
                _vspec((Tt, G2, lt), lambda i, j: (n_t - 1 - j, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((Tp, G2, NL), jnp.float32),
                jax.ShapeDtypeStruct((Tp, G2, NL), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((G2, lt), jnp.float32),
                pltpu.VMEM((G2, lt), jnp.float32),
            ],
        )(pair2, pair_next, lens2, tabb)

    # Per-lane transfer totals: products_reduced's value as an O(NL)
    # epilogue.  Va[last] = M_1 ... M_{l-1} (pass-through fills the pad
    # tail), so prepending position 0's step matrix — identity for the
    # mask_first'd global init and for empty lanes — rebuilds the full
    # lane product; renormalized to products-kernel magnitudes.
    M0 = _tab_sel_nl(tab_ext, pair_c[0]).reshape(NL, GROUP, GROUP)
    Vend = va[-1].T.reshape(NL, GROUP, GROUP)
    red = jnp.einsum(
        "nik,nkj->nij", M0, Vend, precision=jax.lax.Precision.HIGHEST
    )
    red = red / jnp.maximum(
        jnp.sum(red, axis=(-2, -1), keepdims=True), 1e-30
    )
    return va, wb, esym2, red


def contract_mat_streams(va, wb, a0_raw, beta0, gt, esym2):
    """alphas2/betas2 from the matrix streams + boundary entries — the
    elementwise epilogue applying the true entry directions per position
    (no serial chain: NOT a pass in the cost-contract sense).

    a0_raw/beta0 arrive FULL-K [K, NL] like run_fb_kernels_onehot's and
    are projected onto each lane's entry/exit group here (exact: one-hot
    emissions zero the out-of-group components of both).  Returns
    (alphas2 [Tp, 2, NL], betas2 [Tp, 2, NL]).  SCALE CONTRACT: both
    streams carry matrix-total deferred scales — directions match the
    fused 2-pass streams to ~ulp, but sum(alphas2, axis=1) is NOT the
    Rabiner cs (no one-pass consumer reads it; the em-seq loglik comes
    from the telescoped :func:`mat_loglik_lanes` instead)."""
    Tp, G2, NL = va.shape
    a0_red = jnp.take_along_axis(a0_raw.T, gt[esym2[0]], axis=1)  # [NL, 2]
    beta0_red = jnp.take_along_axis(beta0.T, gt[esym2[-1]], axis=1)
    va4 = va.reshape(Tp, GROUP, GROUP, NL)
    wb4 = wb.reshape(Tp, GROUP, GROUP, NL)
    alphas2 = jnp.einsum(
        "ne,tecn->tcn", a0_red, va4, precision=jax.lax.Precision.HIGHEST
    )
    betas2 = jnp.einsum(
        "taen,ne->tan", wb4, beta0_red, precision=jax.lax.Precision.HIGHEST
    )
    return alphas2, betas2


def mat_loglik_lanes(va, alphas2, lens2):
    """EXACT per-lane log-likelihood from the matrix stream — the one-pass
    replacement for the znorm stats kernel's sum-of-log-cs (whose cs the
    matrix arm does not produce).  The forward renorms telescope:

      sum(alphas2[l-1]) = sum(a0^T M_1 ... M_{l-1}) / prod_{t<=l-2} sig_t

    with sig_t = sum4(Va[t]) the stored matrix totals (sig_0 = sum4(I) =
    2 — self-consistent), so

      ll_n = log sum_c alphas2[last, c, n] + sum_{t+1 < l_n} log sig_t,n

    pass-through fills the pad tail, so row Tp-1 IS row l-1.  Lanes with
    l_n == 0 are masked OUT entirely (the 2-pass arm's per-position
    valid mask contributes nothing there; the unmasked first term would
    leak log sum(a0_red) garbage).  Returns ll [1, NL]."""
    Tp = va.shape[0]
    sig = jnp.sum(va, axis=1)  # [Tp, NL]
    smask = (jnp.arange(Tp)[:, None] + 1) < lens2
    ll = (
        jnp.log(jnp.maximum(jnp.sum(alphas2[-1], axis=0), 1e-30))[None, :]
        + jnp.sum(
            jnp.where(smask, jnp.log(jnp.maximum(sig, 1e-30)), 0.0), axis=0
        )[None, :]
    )
    return jnp.where(lens2 > 0, ll, 0.0)


# ---------------------------------------------------------------------------
# Stacked multi-model kernels: M members' reduced chains in ONE launch.
#
# Same design as the max-plus stacked passes (ops.viterbi_onehot): the pair
# stream is symbol-only and SHARED, each member's [NL, 2] chain state rides
# two extra carry rows, and each step selects member m's 2x2 matrix from
# rows [m*4*nreal, (m+1)*4*nreal) of a row-stacked lane-broadcast table.
# Per-member arithmetic is the single-model kernel's op for op (the r9
# fused kernel already proved independent chains interleave and fill VPU
# issue slots) — so member m's streams are BIT-IDENTICAL to a single-model
# launch, and N members pay ONE chain drain of fixed cost instead of N.
# Off-TPU the twins are the single-model one-scan XLA twins over
# lane-concatenated streams (exact: the one-hot table contraction adds
# only exact zeros; every chain op is elementwise across lanes).

# Reduced-engine state envelope: the chains themselves are K-free (2
# components), but the stats kernels accumulate [K*K] rows per member in
# VMEM and the boundary glue scatters [K]-vectors — 32 covers the order-2
# dinucleotide member (ROADMAP item 2's K<=8 lift) with bounded scratch.
ONEHOT_MAX_STATES = 32

# graftmem kernel family behind each reduced-path tuning knob — the ONE
# mapping the graftune sweep prunes knob tuples through (tune.tasks) and
# the lane-tile note: the chunked/seq stats kernels run the wide 256-lane
# tile via fb_pallas._fb_lane_tile when the lane count divides, 128
# otherwise, so feasibility checks evaluate at lane_tile=256 (the
# envelope case).  A new reduced kernel family registers here AND in
# memmodel._BUILDERS, or its knobs silently escape the sweep's prune.
TUNE_KERNELS = {
    "posterior": "fb.fwdbwd.onehot",
    "em_seq": "fb.seqstats.onehot",
    "em_chunked": "fb.stats.onehot",
    # One-pass arm (r17): flipping one_pass=True routes these paths onto
    # the matrix-carried kernel — the family the one_pass graftune tasks
    # prune their True candidate through before compiling it.
    "posterior_onepass": "fb.fwdbwdmat.onehot",
    "em_seq_onepass": "fb.fwdbwdmat.onehot",
}

# graftscale (Layer 6) declarations: per consumer, the homogeneity degree
# of each output in its tagged beta-stream input ("free" = scale-free,
# "deg:1" = positively homogeneous degree 1, "mixed" = pinned log-domain
# — exactness there is a runtime-parity fact, not a homogeneity fact).
# scale_contracts derives these signatures from the jaxpr dataflow and
# CROSS-CHECKS them against this table, so the contract lives next to
# the kernels it certifies.  The runtime half of the same invariant is
# run_stats_onehot's betas_scale guard (beta_scale_of at route points).
SCALE_TAGS = {
    "run_seq_stats_onehot": {
        "tagged": "betas2", "mode": "linear",
        "outputs": {"macc": "free", "emit_red": "free", "ll": "free"},
    },
    "run_stats_onehot": {
        # The EXACT split arm: macc carries the cs scale by construction.
        "tagged": "betas2", "mode": "linear",
        "outputs": {"macc": "deg:1", "emit_red": "free", "ll": "free"},
    },
    "conf_from_reduced": {
        "tagged": "betas2", "mode": "linear",
        "outputs": {"conf": "free"},
    },
    "contract_mat_streams": {
        "tagged": "beta0", "mode": "linear",
        "outputs": {"alphas2": "free", "betas2": "deg:1"},
    },
    "mat_loglik_lanes": {
        "tagged": "va", "mode": "linear",
        "outputs": {"ll": "mixed"},
    },
}


def check_stacked_members(params_list) -> int:
    """Validate a stacked member set (shared alphabet, envelope) and return
    S.  Callers group members by (order, S) before reaching here."""
    if not params_list:
        raise ValueError("stacked launch needs at least one member")
    S = params_list[0].n_symbols
    for p in params_list:
        if p.n_symbols != S:
            raise ValueError(
                "stacked members must share one alphabet, got n_symbols "
                f"{[int(q.n_symbols) for q in params_list]}"
            )
        if p.n_states > ONEHOT_MAX_STATES:
            raise ValueError(
                f"member with {p.n_states} states exceeds the reduced-"
                f"engine envelope ({ONEHOT_MAX_STATES})"
            )
    return S


def _stacked_prob_tables(params_list):
    """Per-member (gt, tab) lists for the stacked probability-space passes."""
    gts = [_groups(p) for p in params_list]
    tabs = [prob_pair_table(p, gt) for p, gt in zip(params_list, gts)]
    return gts, tabs


def _oh_prod_stacked_kernel(pair_ref, tab_ref, out_ref, C_scr, *, nreal, bk,
                            M):
    """Stacked (+,x) products: member m's running 2x2 at C_scr/out rows
    [4m, 4m+4) — one pair-tile read feeds every member's select."""
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = pair_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        for m in range(M):
            C_scr[4 * m + 0 : 4 * m + 1, :] = jnp.ones((1, lt), jnp.float32)
            C_scr[4 * m + 1 : 4 * m + 2, :] = jnp.zeros((1, lt), jnp.float32)
            C_scr[4 * m + 2 : 4 * m + 3, :] = jnp.zeros((1, lt), jnp.float32)
            C_scr[4 * m + 3 : 4 * m + 4, :] = jnp.ones((1, lt), jnp.float32)

    C0 = tuple(
        tuple(C_scr[4 * m + i : 4 * m + i + 1, :] for i in range(4))
        for m in range(M)
    )

    def body(c, Cs):
        tile = pair_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]
        sels = [
            _select4_prob(tile, tab_ref, nreal, base=m * 4 * nreal)
            for m in range(M)
        ]
        out = []
        for m in range(M):
            c00, c01, c10, c11 = Cs[m]
            t00, t01, t10, t11 = sels[m]
            for r in range(ROW_TILE):
                a00 = t00[r : r + 1, :]
                a01 = t01[r : r + 1, :]
                a10 = t10[r : r + 1, :]
                a11 = t11[r : r + 1, :]
                n00 = c00 * a00 + c01 * a10
                n01 = c00 * a01 + c01 * a11
                n10 = c10 * a00 + c11 * a10
                n11 = c10 * a01 + c11 * a11
                c00, c01, c10, c11 = n00, n01, n10, n11
            tot = c00 + c01 + c10 + c11
            inv = 1.0 / jnp.maximum(tot, 1e-30)
            out.append((c00 * inv, c01 * inv, c10 * inv, c11 * inv))
        return tuple(out)

    Cs = jax.lax.fori_loop(0, bk // ROW_TILE, body, C0)
    for m in range(M):
        for i in range(4):
            C_scr[4 * m + i : 4 * m + i + 1, :] = Cs[m][i]

    @pl.when(j == n_t - 1)
    def _flush():
        for i in range(4 * M):
            out_ref[i : i + 1, :] = C_scr[i : i + 1, :]


def products_reduced_stacked(params_list, pair2: jnp.ndarray, Tt: int) -> list:
    """Stacked :func:`products_reduced`: every member's [NL, 2, 2] lane
    products in ONE launch over the shared pair stream (per-member results
    bit-identical to the single-model pass)."""
    M = len(params_list)
    S = check_stacked_members(params_list)
    gts, tabs = _stacked_prob_tables(params_list)
    del gts
    NL = pair2.shape[1]
    if _interpret():
        return _xla_products_prob_stacked(tabs, pair2)
    tabb = _bcast_tab(jnp.concatenate(tabs, axis=0))
    (red_flat,) = pl.pallas_call(
        functools.partial(
            _oh_prod_stacked_kernel, nreal=S * S, bk=Tt, M=M
        ),
        grid=(NL // LANE_TILE, pair2.shape[0] // Tt),
        in_specs=[
            _vspec((Tt, LANE_TILE), lambda i, j: (j, i)),
            _vspec(tabb.shape, lambda i, j: (0, 0)),
        ],
        out_specs=[_vspec((4 * M, LANE_TILE), lambda i, j: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((4 * M, NL), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((4 * M, LANE_TILE), jnp.float32)],
    )(pair2, tabb)
    return [
        red_flat[4 * m : 4 * m + 4].T.reshape(NL, GROUP, GROUP)
        for m in range(M)
    ]


def _xla_products_prob_stacked(tabs, pair2: jnp.ndarray) -> list:
    """ONE scan over M members' reduced (+,x) lane products — per-member
    arithmetic = :func:`_xla_products_prob` (the shared one-hot row select
    adds only exact zeros, so member m's product is bit-identical)."""
    M = len(tabs)
    nP = tabs[0].shape[0]
    NL = pair2.shape[1]
    ident = jnp.asarray([1.0, 0.0, 0.0, 1.0], jnp.float32)
    tab_exts = [
        jnp.concatenate([t, jnp.broadcast_to(ident, (1, 4))], axis=0)
        for t in tabs
    ]
    C0 = tuple(
        jnp.broadcast_to(ident, (NL, 4))
        + (pair2[0, :, None] * 0).astype(jnp.float32)
        for _ in range(M)
    )

    def step(Cs, pk):
        oh = jax.nn.one_hot(jnp.minimum(pk, nP), nP + 1, dtype=tabs[0].dtype)
        new = []
        for m in range(M):
            T = jnp.matmul(
                oh, tab_exts[m], precision=jax.lax.Precision.HIGHEST
            )
            C = Cs[m]
            n00 = C[:, 0] * T[:, 0] + C[:, 1] * T[:, 2]
            n01 = C[:, 0] * T[:, 1] + C[:, 1] * T[:, 3]
            n10 = C[:, 2] * T[:, 0] + C[:, 3] * T[:, 2]
            n11 = C[:, 2] * T[:, 1] + C[:, 3] * T[:, 3]
            Cn = jnp.stack([n00, n01, n10, n11], axis=1)
            new.append(
                Cn / jnp.maximum(jnp.sum(Cn, axis=1, keepdims=True), 1e-30)
            )
        return tuple(new), None

    Cs, _ = jax.lax.scan(step, C0, pair2)
    return [C.reshape(NL, GROUP, GROUP) for C in Cs]


def _oh_fwd_stacked_kernel(pair_ref, lens_ref, a0raw_ref, tab_ref,
                           alphas_ref, carry_ref, *, nreal, Tt, M):
    """Stacked reduced forward: member m's chain at rows [2m, 2m+2) of the
    carries/init/outputs — _oh_fwd_kernel arithmetic per member."""
    j = pl.program_id(1)
    lens = lens_ref[0, :]
    vs = []
    for m in range(M):
        vs.append((
            jnp.where(j == 0, a0raw_ref[2 * m : 2 * m + 1, :],
                      carry_ref[2 * m : 2 * m + 1, :]),
            jnp.where(j == 0, a0raw_ref[2 * m + 1 : 2 * m + 2, :],
                      carry_ref[2 * m + 1 : 2 * m + 2, :]),
        ))

    def body(tile_i, carry):
        base = tile_i * ROW_TILE
        tile = pair_ref[pl.ds(base, ROW_TILE), :]
        sels = [
            _select4_prob(tile, tab_ref, nreal, base=m * 4 * nreal)
            for m in range(M)
        ]
        carry = list(carry)
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            v_t = (t < lens)[None, :]
            rows = []
            for m in range(M):
                v0, v1 = carry[m]
                t00, t01, t10, t11 = sels[m]
                inv = 1.0 / (v0 + v1)
                raw0 = v0 * t00[r : r + 1, :] + v1 * t10[r : r + 1, :]
                raw1 = v0 * t01[r : r + 1, :] + v1 * t11[r : r + 1, :]
                n0 = jnp.where(v_t, raw0 * inv, v0)
                n1 = jnp.where(v_t, raw1 * inv, v1)
                n0 = jnp.where(t == 0, a0raw_ref[2 * m : 2 * m + 1, :], n0)
                n1 = jnp.where(t == 0, a0raw_ref[2 * m + 1 : 2 * m + 2, :], n1)
                rows.extend((n0, n1))
                carry[m] = (n0, n1)
            alphas_ref[base + r, :, :] = jnp.concatenate(rows, axis=0)
        return tuple(carry)

    vs = jax.lax.fori_loop(0, Tt // ROW_TILE, body, tuple(vs))
    for m in range(M):
        carry_ref[2 * m : 2 * m + 1, :] = vs[m][0]
        carry_ref[2 * m + 1 : 2 * m + 2, :] = vs[m][1]


def _oh_bwd_stacked_kernel(pairnext_ref, lens_ref, tab_ref, csnext_ref,
                           beta0_ref, betas_ref, beta_scr, *, nreal, Tt, T,
                           M):
    """Stacked split backward: member m's cs-scaled chain at rows [2m, 2m+2)
    (csnext_ref [Tt, M, lt] — each member's own Rabiner scales)."""
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lens = lens_ref[0, :]
    t0 = (n_t - 1 - j) * Tt

    @pl.when(j == 0)
    def _init():
        beta_scr[:, :] = beta0_ref[:, :]

    def body(tile_rev, carry):
        base = (Tt // ROW_TILE - 1 - tile_rev) * ROW_TILE
        tile = pairnext_ref[pl.ds(base, ROW_TILE), :]
        sels = [
            _select4_prob(tile, tab_ref, nreal, base=m * 4 * nreal)
            for m in range(M)
        ]
        scaled = []
        for m in range(M):
            cn = csnext_ref[pl.ds(base, ROW_TILE), m, :]
            inv_cn = 1.0 / cn
            t00, t01, t10, t11 = sels[m]
            scaled.append(
                (t00 * inv_cn, t01 * inv_cn, t10 * inv_cn, t11 * inv_cn)
            )
        carry = list(carry)
        for rr in range(ROW_TILE):
            r = ROW_TILE - 1 - rr
            t = t0 + base + r
            active = t <= T - 2
            v_next = (t + 1) < lens
            keep = (active & v_next)[None, :]
            rows = []
            for m in range(M):
                bn0, bn1 = carry[m]
                s00, s01, s10, s11 = scaled[m]
                b0 = s00[r : r + 1, :] * bn0 + s01[r : r + 1, :] * bn1
                b1 = s10[r : r + 1, :] * bn0 + s11[r : r + 1, :] * bn1
                b0 = jnp.where(keep, b0, bn0)
                b1 = jnp.where(keep, b1, bn1)
                rows.extend((b0, b1))
                carry[m] = (b0, b1)
            betas_ref[base + r, :, :] = jnp.concatenate(rows, axis=0)
        return tuple(carry)

    carry0 = tuple(
        (beta_scr[2 * m : 2 * m + 1, :], beta_scr[2 * m + 1 : 2 * m + 2, :])
        for m in range(M)
    )
    carry = jax.lax.fori_loop(0, Tt // ROW_TILE, body, carry0)
    for m in range(M):
        beta_scr[2 * m : 2 * m + 1, :] = carry[m][0]
        beta_scr[2 * m + 1 : 2 * m + 2, :] = carry[m][1]


def _oh_fwdbwd_stacked_kernel(pair_ref, pairn_ref, lens_ref, a0raw_ref,
                              beta0_ref, tab_ref, alphas_ref, betas_ref,
                              fcarry, bcarry, *, nreal, Tt, T, M):
    """CO-SCHEDULED stacked fwd/bwd: 2M independent chains in ONE launch.

    The model-axis generalization of :func:`_oh_fwdbwd_kernel` — the r9
    kernel's two interleaved chains become 2M (M forward + M self-
    normalized backward), all filling VPU issue slots while any one
    stalls.  Member m's rows sit at [2m, 2m+2) of every stacked operand;
    per-member arithmetic (and so every output) is the single-model fused
    kernel's, bit for bit.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lens = lens_ref[0, :]
    state = []
    for m in range(M):
        state.append((
            jnp.where(j == 0, a0raw_ref[2 * m : 2 * m + 1, :],
                      fcarry[2 * m : 2 * m + 1, :]),
            jnp.where(j == 0, a0raw_ref[2 * m + 1 : 2 * m + 2, :],
                      fcarry[2 * m + 1 : 2 * m + 2, :]),
            jnp.where(j == 0, beta0_ref[2 * m : 2 * m + 1, :],
                      bcarry[2 * m : 2 * m + 1, :]),
            jnp.where(j == 0, beta0_ref[2 * m + 1 : 2 * m + 2, :],
                      bcarry[2 * m + 1 : 2 * m + 2, :]),
        ))
    bt0 = (n_t - 1 - j) * Tt

    def body(tile_i, carry):
        fbase = tile_i * ROW_TILE
        bbase = (Tt // ROW_TILE - 1 - tile_i) * ROW_TILE
        ftile = pair_ref[pl.ds(fbase, ROW_TILE), :]
        btile = pairn_ref[pl.ds(bbase, ROW_TILE), :]
        fsels = [
            _select4_prob(ftile, tab_ref, nreal, base=m * 4 * nreal)
            for m in range(M)
        ]
        bsels = [
            _select4_prob(btile, tab_ref, nreal, base=m * 4 * nreal)
            for m in range(M)
        ]
        carry = list(carry)
        for r in range(ROW_TILE):
            t = j * Tt + fbase + r
            v_t = (t < lens)[None, :]
            rr = ROW_TILE - 1 - r
            tb = bt0 + bbase + rr
            active = tb <= T - 2
            v_next = (tb + 1) < lens
            keep = (active & v_next)[None, :]
            arows, brows = [], []
            for m in range(M):
                v0, v1, bn0, bn1 = carry[m]
                f00, f01, f10, f11 = fsels[m]
                g00, g01, g10, g11 = bsels[m]
                inv = 1.0 / (v0 + v1)
                raw0 = v0 * f00[r : r + 1, :] + v1 * f10[r : r + 1, :]
                raw1 = v0 * f01[r : r + 1, :] + v1 * f11[r : r + 1, :]
                n0 = jnp.where(v_t, raw0 * inv, v0)
                n1 = jnp.where(v_t, raw1 * inv, v1)
                n0 = jnp.where(t == 0, a0raw_ref[2 * m : 2 * m + 1, :], n0)
                n1 = jnp.where(
                    t == 0, a0raw_ref[2 * m + 1 : 2 * m + 2, :], n1
                )
                arows.extend((n0, n1))
                binv = 1.0 / (bn0 + bn1)
                b0 = (
                    g00[rr : rr + 1, :] * bn0 + g01[rr : rr + 1, :] * bn1
                ) * binv
                b1 = (
                    g10[rr : rr + 1, :] * bn0 + g11[rr : rr + 1, :] * bn1
                ) * binv
                b0 = jnp.where(keep, b0, bn0)
                b1 = jnp.where(keep, b1, bn1)
                brows.extend((b0, b1))
                carry[m] = (n0, n1, b0, b1)
            alphas_ref[fbase + r, :, :] = jnp.concatenate(arows, axis=0)
            betas_ref[bbase + rr, :, :] = jnp.concatenate(brows, axis=0)
        return tuple(carry)

    state = jax.lax.fori_loop(0, Tt // ROW_TILE, body, tuple(state))
    for m in range(M):
        v0, v1, bn0, bn1 = state[m]
        fcarry[2 * m : 2 * m + 1, :] = v0
        fcarry[2 * m + 1 : 2 * m + 2, :] = v1
        bcarry[2 * m : 2 * m + 1, :] = bn0
        bcarry[2 * m + 1 : 2 * m + 2, :] = bn1


def run_fb_kernels_onehot_stacked(
    params_list,
    sel_t: jnp.ndarray,
    prev_dev,
    lens2: jnp.ndarray,
    a0_raws,
    beta0s,
    Tt: int,
    T: int,
    conf_masks=None,
    pair_esym=None,
    fused: bool = True,
):
    """Stacked :func:`run_fb_kernels_onehot`: M members' forward/backward
    chains over ONE shared pair stream in one launch (fused) or one launch
    per direction (split).  ``a0_raws``/``beta0s``: per-member [K_m, NL]
    lists; ``conf_masks``: per-member [K_m] island masks — the confidence
    epilogue is the scale-free :func:`conf_from_reduced` on BOTH arms
    (exact for self-normalized fused betas AND the split arm's cs-scaled
    betas; bit-identical to the fused sequential arm, which uses the same
    epilogue — the split sequential arm's in-backward conf kernel differs
    only in the final divide's rounding).  Returns (alphas2 list, cs list,
    betas2-or-conf2 list, esym2).
    """
    M = len(params_list)
    S = check_stacked_members(params_list)
    gts, tabs = _stacked_prob_tables(params_list)
    pairn_pre = None
    if pair_esym is None:
        pair2, _, _ = _pair_stream(
            params_list[0], sel_t, jnp.asarray(prev_dev, jnp.int32)
        )
        esym2 = decode_esym(pair2, S)
    else:
        pair2, esym2 = pair_esym[0], pair_esym[1]
        pairn_pre = pair_esym[2] if len(pair_esym) > 2 else None
        if esym2 is None:
            esym2 = decode_esym(pair2, S)
    Tp, NL = pair2.shape

    a0_reds = [
        jnp.take_along_axis(a0_raws[m].T, gts[m][esym2[0]], axis=1)
        for m in range(M)
    ]
    beta0_reds = [
        jnp.take_along_axis(beta0s[m].T, gts[m][esym2[-1]], axis=1)
        for m in range(M)
    ]
    pair_next = (
        pairn_pre
        if pairn_pre is not None
        else jnp.concatenate(
            [pair2[1:], jnp.full((1, NL), S * S, jnp.int32)], axis=0
        )
    )
    ident = jnp.asarray([PROB_IDENT], jnp.float32)
    tab_exts = [jnp.concatenate([t, ident], axis=0) for t in tabs]
    pair_c = jnp.minimum(pair2, S * S)
    pairn_c = jnp.minimum(pair_next, S * S)

    def _epilogue(alphas_list, betas_list):
        cs_list = [jnp.sum(a, axis=1) for a in alphas_list]
        if conf_masks is None:
            return alphas_list, cs_list, betas_list, esym2
        confs = [
            conf_from_reduced(
                alphas_list[m], betas_list[m], esym2, lens2, conf_masks[m],
                gts[m],
            )
            for m in range(M)
        ]
        return alphas_list, cs_list, confs, esym2

    if _interpret():
        # ONE-scan stacked twins: one lax.scan carries every member's
        # chain state, each member selecting from ITS tab_ext with the
        # single-model arithmetic — bit-identical per member, and the
        # per-step select stays O(M * nreal) (a lane-concatenated one-hot
        # would grow O(M^2) and trip cost.reduced-no-dense-pair).
        if fused:
            al_bt = _xla_fwdbwd_onehot_stacked(
                tab_exts, pair_c, pairn_c, lens2, a0_reds, beta0_reds, T
            )
            alphas_list = [a for a, _ in al_bt]
            betas_list = [b for _, b in al_bt]
        else:
            alphas_list = _xla_fwd_onehot_stacked(
                tab_exts, pair_c, lens2, a0_reds
            )
            cs_nexts = [
                jnp.concatenate(
                    [jnp.sum(a, axis=1)[1:], jnp.ones((1, NL), jnp.float32)],
                    axis=0,
                )
                for a in alphas_list
            ]
            betas_list = _xla_bwd_onehot_stacked(
                tab_exts, pairn_c, lens2, cs_nexts, beta0_reds, T
            )
        return _epilogue(alphas_list, betas_list)

    from cpgisland_tpu.ops.fb_pallas import _fb_lane_tile

    lt = _fb_lane_tile(NL)
    n_t = Tp // Tt
    grid = (NL // lt, n_t)
    lane_spec = _vspec((1, lt), lambda i, j: (0, i))
    mg_spec = _vspec((M * GROUP, lt), lambda i, j: (0, i))
    step_spec = _vspec((Tt, lt), lambda i, j: (j, i))
    tabb = _bcast_tab(jnp.concatenate(tabs, axis=0), lt)
    a0_st = jnp.concatenate([a.T for a in a0_reds], axis=0)  # [M*G, NL]
    b0_st = jnp.concatenate([b.T for b in beta0_reds], axis=0)
    if fused:
        rev_spec = _vspec((Tt, lt), lambda i, j: (n_t - 1 - j, i))
        alphas_st, betas_st = pl.pallas_call(
            functools.partial(
                _oh_fwdbwd_stacked_kernel, nreal=S * S, Tt=Tt, T=T, M=M
            ),
            grid=grid,
            in_specs=[
                step_spec,
                rev_spec,
                lane_spec,
                mg_spec,
                mg_spec,
                _vspec(tabb.shape, lambda i, j: (0, 0)),
            ],
            out_specs=[
                _vspec((Tt, M * GROUP, lt), lambda i, j: (j, 0, i)),
                _vspec((Tt, M * GROUP, lt), lambda i, j: (n_t - 1 - j, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((Tp, M * GROUP, NL), jnp.float32),
                jax.ShapeDtypeStruct((Tp, M * GROUP, NL), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((M * GROUP, lt), jnp.float32),
                pltpu.VMEM((M * GROUP, lt), jnp.float32),
            ],
        )(pair2, pair_next, lens2, a0_st, b0_st, tabb)
        alphas_list = [
            alphas_st[:, 2 * m : 2 * m + 2, :] for m in range(M)
        ]
        betas_list = [betas_st[:, 2 * m : 2 * m + 2, :] for m in range(M)]
        return _epilogue(alphas_list, betas_list)
    (alphas_st,) = pl.pallas_call(
        functools.partial(
            _oh_fwd_stacked_kernel, nreal=S * S, Tt=Tt, M=M
        ),
        grid=grid,
        in_specs=[
            step_spec, lane_spec, mg_spec,
            _vspec(tabb.shape, lambda i, j: (0, 0)),
        ],
        out_specs=[_vspec((Tt, M * GROUP, lt), lambda i, j: (j, 0, i))],
        out_shape=[jax.ShapeDtypeStruct((Tp, M * GROUP, NL), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((M * GROUP, lt), jnp.float32)],
    )(pair2, lens2, a0_st, tabb)
    alphas_list = [alphas_st[:, 2 * m : 2 * m + 2, :] for m in range(M)]
    cs_st = jnp.stack(
        [jnp.sum(a, axis=1) for a in alphas_list], axis=1
    )  # [Tp, M, NL]
    cs_next_st = jnp.concatenate(
        [cs_st[1:], jnp.ones((1, M, NL), cs_st.dtype)], axis=0
    )
    rev_step_spec = _vspec((Tt, lt), lambda i, j: (n_t - 1 - j, i))
    (betas_st,) = pl.pallas_call(
        functools.partial(
            _oh_bwd_stacked_kernel, nreal=S * S, Tt=Tt, T=T, M=M
        ),
        grid=grid,
        in_specs=[
            rev_step_spec,
            lane_spec,
            _vspec(tabb.shape, lambda i, j: (0, 0)),
            _vspec((Tt, M, lt), lambda i, j: (n_t - 1 - j, 0, i)),
            mg_spec,
        ],
        out_specs=[_vspec((Tt, M * GROUP, lt), lambda i, j: (n_t - 1 - j, 0, i))],
        out_shape=[jax.ShapeDtypeStruct((Tp, M * GROUP, NL), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((M * GROUP, lt), jnp.float32)],
    )(pair_next, lens2, tabb, cs_next_st, b0_st)
    betas_list = [betas_st[:, 2 * m : 2 * m + 2, :] for m in range(M)]
    return _epilogue(alphas_list, betas_list)


def _xla_fwd_onehot_stacked(tab_exts, pair2, lens2, a0_reds):
    """ONE scan over M members' reduced forward chains (each member's
    per-step arithmetic = :func:`_xla_fwd_onehot`, bit for bit).  Returns
    the per-member alphas2 [Tp, 2, NL] list."""
    M = len(tab_exts)
    Tp = pair2.shape[0]
    lens = lens2[0]

    def step(carry, x):
        pk, t = x
        new, ys = [], []
        for m in range(M):
            v0, v1 = carry[m]
            T4 = _tab_sel_nl(tab_exts[m], pk)
            inv = 1.0 / (v0 + v1)
            raw0 = v0 * T4[:, 0] + v1 * T4[:, 2]
            raw1 = v0 * T4[:, 1] + v1 * T4[:, 3]
            v_t = t < lens
            n0 = jnp.where(v_t, raw0 * inv, v0)
            n1 = jnp.where(v_t, raw1 * inv, v1)
            n0 = jnp.where(t == 0, a0_reds[m][:, 0], n0)
            n1 = jnp.where(t == 0, a0_reds[m][:, 1], n1)
            new.append((n0, n1))
            ys.append(jnp.stack([n0, n1], axis=0))
        return tuple(new), tuple(ys)

    _, ys = jax.lax.scan(
        step,
        tuple((a[:, 0], a[:, 1]) for a in a0_reds),
        (pair2, jnp.arange(Tp, dtype=jnp.int32)),
    )
    return list(ys)


def _xla_bwd_onehot_stacked(tab_exts, pair_next, lens2, cs_nexts,
                            beta0_reds, T):
    """ONE scan over M members' split (cs-scaled) backward chains —
    per-member arithmetic = :func:`_xla_bwd_onehot`."""
    M = len(tab_exts)
    Tp = pair_next.shape[0]
    lens = lens2[0]

    def step(carry, x):
        pk, cns, t = x
        new, ys = [], []
        for m in range(M):
            bn0, bn1 = carry[m]
            T4 = _tab_sel_nl(tab_exts[m], pk)
            inv_cn = 1.0 / cns[m]
            b0 = (T4[:, 0] * bn0 + T4[:, 1] * bn1) * inv_cn
            b1 = (T4[:, 2] * bn0 + T4[:, 3] * bn1) * inv_cn
            keep = (t <= T - 2) & ((t + 1) < lens)
            b0 = jnp.where(keep, b0, bn0)
            b1 = jnp.where(keep, b1, bn1)
            new.append((b0, b1))
            ys.append(jnp.stack([b0, b1], axis=0))
        return tuple(new), tuple(ys)

    _, ys = jax.lax.scan(
        step,
        tuple((b[:, 0], b[:, 1]) for b in beta0_reds),
        (pair_next, tuple(cs_nexts), jnp.arange(Tp, dtype=jnp.int32)),
        reverse=True,
    )
    return list(ys)


def _xla_fwdbwd_onehot_stacked(tab_exts, pair2, pair_next, lens2, a0_reds,
                               beta0_reds, T):
    """ONE scan computing M members' CO-SCHEDULED fwd + self-normalized
    bwd chains — the stacked twin of :func:`_xla_fwdbwd_onehot` (per-member
    arithmetic identical, so member m's streams are bit-identical to its
    own single-model fused scan).  Returns per-member (alphas2, betas2)."""
    M = len(tab_exts)
    Tp = pair2.shape[0]
    lens = lens2[0]
    pairn_rev = jnp.flip(pair_next, axis=0)

    def step(carry, x):
        pk, qk, t = x
        tb = Tp - 1 - t
        new, ys = [], []
        for m in range(M):
            v0, v1, bn0, bn1 = carry[m]
            T4 = _tab_sel_nl(tab_exts[m], pk)
            G4 = _tab_sel_nl(tab_exts[m], qk)
            inv = 1.0 / (v0 + v1)
            raw0 = v0 * T4[:, 0] + v1 * T4[:, 2]
            raw1 = v0 * T4[:, 1] + v1 * T4[:, 3]
            v_t = t < lens
            n0 = jnp.where(v_t, raw0 * inv, v0)
            n1 = jnp.where(v_t, raw1 * inv, v1)
            n0 = jnp.where(t == 0, a0_reds[m][:, 0], n0)
            n1 = jnp.where(t == 0, a0_reds[m][:, 1], n1)
            binv = 1.0 / (bn0 + bn1)
            b0 = (G4[:, 0] * bn0 + G4[:, 1] * bn1) * binv
            b1 = (G4[:, 2] * bn0 + G4[:, 3] * bn1) * binv
            keep = (tb <= T - 2) & ((tb + 1) < lens)
            b0 = jnp.where(keep, b0, bn0)
            b1 = jnp.where(keep, b1, bn1)
            new.append((n0, n1, b0, b1))
            ys.append((
                jnp.stack([n0, n1], axis=0), jnp.stack([b0, b1], axis=0)
            ))
        return tuple(new), tuple(ys)

    _, ys = jax.lax.scan(
        step,
        tuple(
            (a[:, 0], a[:, 1], b[:, 0], b[:, 1])
            for a, b in zip(a0_reds, beta0_reds)
        ),
        (pair2, pairn_rev, jnp.arange(Tp, dtype=jnp.int32)),
    )
    return [(al, jnp.flip(bt, axis=0)) for al, bt in ys]


def _oh_seq_stats_stacked_kernel(alphas_ref, betas_ref, pair_ref, lens_ref,
                                 tab_ref, brtab_ref, gttab_ref,
                                 enters_full_ref, enters_red_ref, pair0m_ref,
                                 macc_ref, emit_ref, ll_ref, macc_scr,
                                 emit_scr, ll_scr, aprev_scr, aprev2_scr,
                                 *, K, S, nreal, Tt, M):
    """Stacked z-normalized stats: M same-K members' count reductions in
    ONE pass over the shared pair stream (member m's macc rows at
    [m*K*K, (m+1)*K*K), emit at [m*S*GROUP, ...), ll row m; per-member
    arithmetic = _oh_seq_stats_kernel).  The stats pass is throughput-
    bound (no serial chain), so stacking shares the pair-stream read and
    the launch, not a chain drain."""
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = pair_ref.shape[1]
    lens = lens_ref[0, :]
    pair0m = pair0m_ref[:, :]

    @pl.when(j == 0)
    def _init():
        macc_scr[:, :] = jnp.zeros((M * K * K, lt), jnp.float32)
        emit_scr[:, :] = jnp.zeros((M * S * GROUP, lt), jnp.float32)
        ll_scr[:, :] = jnp.zeros((M, lt), jnp.float32)
        aprev_scr[:, :] = jnp.zeros((M * K, lt), jnp.float32)
        aprev2_scr[:, :] = jnp.zeros((M * GROUP, lt), jnp.float32)

    iK = jax.lax.broadcasted_iota(jnp.int32, (K, lt), 0)

    def body(tile_i, carry):
        base = tile_i * ROW_TILE
        p_tile = pair_ref[pl.ds(base, ROW_TILE), :]
        esym = p_tile & (S - 1)
        carry = [list(c) for c in carry]
        sels = [
            _select4_prob(p_tile, tab_ref, nreal, base=m * 4 * nreal)
            for m in range(M)
        ]
        syms = [
            _sel_sym_tables(
                p_tile, brtab_ref, gttab_ref, S, base=m * 2 * S
            )
            for m in range(M)
        ]
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            valid = (t < lens)[None, :]
            is0 = t == 0
            pairm = jnp.where(
                is0, valid * pair0m, valid.astype(jnp.float32)
            )
            sym_r = esym[r : r + 1, :]
            for m in range(M):
                aprev, ap2_0, ap2_1, macc, emit, ll = carry[m]
                t00, t01, t10, t11 = sels[m]
                b0t, b1t, glt, ght = syms[m]
                a_row = alphas_ref[base + r, 2 * m : 2 * m + 2, :]
                b_row = betas_ref[base + r, 2 * m : 2 * m + 2, :]
                a0 = a_row[0:1, :]
                a1 = a_row[1:2, :]
                be0 = b_row[0:1, :]
                be1 = b_row[1:2, :]
                cs = a0 + a1
                inv_cs = 1.0 / jnp.maximum(cs, 1e-30)
                g0 = a0 * be0
                g1 = a1 * be1
                inv_g = 1.0 / jnp.maximum(g0 + g1, 1e-30)
                gm0 = jnp.where(valid, g0 * inv_g, 0.0)
                gm1 = jnp.where(valid, g1 * inv_g, 0.0)
                emit = list(emit)
                for s in range(S):
                    msk = sym_r == s
                    emit[2 * s] = emit[2 * s] + jnp.where(msk, gm0, 0.0)
                    emit[2 * s + 1] = emit[2 * s + 1] + jnp.where(
                        msk, gm1, 0.0
                    )
                ll = ll + jnp.where(
                    valid, jnp.log(jnp.maximum(cs, 1e-30)), 0.0
                )
                apf = jnp.where(
                    is0,
                    enters_full_ref[m * K : (m + 1) * K, :],
                    aprev,
                )
                ap0 = jnp.where(
                    is0, enters_red_ref[2 * m : 2 * m + 1, :], ap2_0
                )
                ap1 = jnp.where(
                    is0, enters_red_ref[2 * m + 1 : 2 * m + 2, :], ap2_1
                )
                z = ap0 * (t00[r : r + 1, :] * be0 + t01[r : r + 1, :] * be1) + \
                    ap1 * (t10[r : r + 1, :] * be0 + t11[r : r + 1, :] * be1)
                inv_z = pairm * (1.0 / jnp.maximum(z, 1e-30))
                glow = glt[r : r + 1, :]
                ghigh = ght[r : r + 1, :]
                w_full = jnp.where(iK == glow, b0t[r : r + 1, :] * be0, 0.0) + \
                    jnp.where(iK == ghigh, b1t[r : r + 1, :] * be1, 0.0)
                wz = w_full * inv_z
                macc = list(macc)
                for jj in range(K):
                    macc[jj] = macc[jj] + apf[jj : jj + 1, :] * wz
                ah0 = a0 * inv_cs
                ah1 = a1 * inv_cs
                aprev = jnp.where(iK == glow, ah0, 0.0) + jnp.where(
                    iK == ghigh, ah1, 0.0
                )
                carry[m] = [aprev, ah0, ah1, tuple(macc), tuple(emit), ll]
        return tuple(tuple(c) for c in carry)

    zeroK = jnp.zeros((K, lt), jnp.float32)
    zero1 = jnp.zeros((1, lt), jnp.float32)
    carry0 = tuple(
        (
            aprev_scr[m * K : (m + 1) * K, :],
            aprev2_scr[2 * m : 2 * m + 1, :],
            aprev2_scr[2 * m + 1 : 2 * m + 2, :],
            tuple(zeroK for _ in range(K)),
            tuple(zero1 for _ in range(S * GROUP)),
            jnp.zeros((1, lt), jnp.float32),
        )
        for m in range(M)
    )
    out = jax.lax.fori_loop(0, Tt // ROW_TILE, body, carry0)
    for m in range(M):
        aprev, ap2_0, ap2_1, macc, emit, ll = out[m]
        aprev_scr[m * K : (m + 1) * K, :] = aprev
        aprev2_scr[2 * m : 2 * m + 1, :] = ap2_0
        aprev2_scr[2 * m + 1 : 2 * m + 2, :] = ap2_1
        for jj in range(K):
            sl = slice(m * K * K + jj * K, m * K * K + (jj + 1) * K)
            macc_scr[sl, :] = macc_scr[sl, :] + macc[jj]
        for i in range(S * GROUP):
            r0 = m * S * GROUP + i
            emit_scr[r0 : r0 + 1, :] = emit_scr[r0 : r0 + 1, :] + emit[i]
        ll_scr[m : m + 1, :] = ll_scr[m : m + 1, :] + ll

    @pl.when(j == n_t - 1)
    def _flush():
        macc_ref[:, :] = macc_scr[:, :]
        emit_ref[:, :] = emit_scr[:, :]
        ll_ref[:, :] = ll_scr[:, :]


def run_seq_stats_onehot_stacked(params_list, alphas2_list, betas2_list,
                                 pair2, lens2, gts, enters_red_list,
                                 enters_full_list, pair0_mask, Tt):
    """Stacked :func:`run_seq_stats_onehot`: M members' z-normalized count
    reductions in ONE launch (pow2 S; per-member results bit-identical to
    the single-model pass).  The TPU kernel additionally requires one
    common K across members (per-member VMEM accumulator rows are sliced
    statically); the off-TPU twin loops :func:`_xla_znorm_stats` per
    member inside the same program — contractions, not serial passes.
    Returns per-member (macc, emit_red, ll) tuples."""
    M = len(params_list)
    S = check_stacked_members(params_list)
    if S & (S - 1):
        raise ValueError("run_seq_stats_onehot_stacked: power-of-two S only")
    if _interpret():
        return [
            _xla_znorm_stats(
                params_list[m], alphas2_list[m], betas2_list[m], pair2,
                lens2, gts[m], enters_red_list[m], enters_full_list[m],
                pair0_mask,
            )
            for m in range(M)
        ]
    K = params_list[0].n_states
    for p in params_list[1:]:
        if p.n_states != K:
            raise ValueError(
                "the stacked stats kernel needs one common n_states; got "
                f"{[int(q.n_states) for q in params_list]} — run mixed-K "
                "members through per-member run_seq_stats_onehot"
            )
    Tp, _, NL = alphas2_list[0].shape
    tabs, brtabs, gttabs = [], [], []
    for m, p in enumerate(params_list):
        tabs.append(prob_pair_table(p, gts[m]))
        B = jnp.exp(p.log_B).astype(jnp.float32)
        brtabs.append(B[gts[m], jnp.arange(S)[:, None]])
        gttabs.append(gts[m].astype(jnp.int32))
    lt = LANE_TILE
    grid = (NL // lt, Tp // Tt)
    tabb = _bcast_tab(jnp.concatenate(tabs, axis=0), lt)
    brtabb = _bcast_tab(jnp.concatenate(brtabs, axis=0), lt)
    gttabb = _bcast_tab(jnp.concatenate(gttabs, axis=0), lt)
    alphas_st = jnp.concatenate(alphas2_list, axis=1)  # [Tp, M*G, NL]
    betas_st = jnp.concatenate(betas2_list, axis=1)
    enters_full_st = jnp.concatenate(enters_full_list, axis=0)  # [M*K, NL]
    enters_red_st = jnp.concatenate(enters_red_list, axis=0)  # [M*G, NL]
    macc, emit, ll = pl.pallas_call(
        functools.partial(
            _oh_seq_stats_stacked_kernel, K=K, S=S, nreal=S * S, Tt=Tt, M=M
        ),
        grid=grid,
        in_specs=[
            _vspec((Tt, M * GROUP, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, M * GROUP, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, lt), lambda i, j: (j, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
            _vspec(tabb.shape, lambda i, j: (0, 0)),
            _vspec(brtabb.shape, lambda i, j: (0, 0)),
            _vspec(gttabb.shape, lambda i, j: (0, 0)),
            _vspec((M * K, lt), lambda i, j: (0, i)),
            _vspec((M * GROUP, lt), lambda i, j: (0, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
        ],
        out_specs=[
            _vspec((M * K * K, lt), lambda i, j: (0, i)),
            _vspec((M * S * GROUP, lt), lambda i, j: (0, i)),
            _vspec((M, lt), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M * K * K, NL), jnp.float32),
            jax.ShapeDtypeStruct((M * S * GROUP, NL), jnp.float32),
            jax.ShapeDtypeStruct((M, NL), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((M * K * K, lt), jnp.float32),
            pltpu.VMEM((M * S * GROUP, lt), jnp.float32),
            pltpu.VMEM((M, lt), jnp.float32),
            pltpu.VMEM((M * K, lt), jnp.float32),
            pltpu.VMEM((M * GROUP, lt), jnp.float32),
        ],
    )(alphas_st, betas_st, pair2, lens2, tabb, brtabb, gttabb,
      enters_full_st, enters_red_st, pair0_mask)
    return [
        (
            macc[m * K * K : (m + 1) * K * K],
            emit[m * S * GROUP : (m + 1) * S * GROUP],
            ll[m : m + 1],
        )
        for m in range(M)
    ]


def products_reduced(params: HmmParams, pair2: jnp.ndarray, Tt: int) -> jnp.ndarray:
    """Per-lane REDUCED transfer products [NL, 2, 2] from a pair stream
    ([lane_T, NL]; pallas kernel on TPU, the per-step-renorm XLA twin
    elsewhere — directions identical, only the internal scalar differs).

    Adjacent lanes' reduced products COMPOSE directly: the pair stream's
    forward-fill guarantees e_in[n+1] == e_out[n], so lane n's exit group
    is lane n+1's entry group and a 2x2 chain over lanes equals the dense
    scattered chain exactly (the dense product's out-of-group entries are
    exact zeros in every consumer) — the boundary-message scans in
    fb_pallas._lane_streams run in this reduced space.
    """
    S = params.n_symbols
    gt = _groups(params)
    tab = prob_pair_table(params, gt)
    NL = pair2.shape[1]
    if _interpret():
        return _xla_products_prob(tab, pair2)
    tabb = _bcast_tab(tab)
    (red_flat,) = pl.pallas_call(
        functools.partial(_oh_prod_kernel, nreal=S * S, bk=Tt),
        grid=(NL // LANE_TILE, pair2.shape[0] // Tt),
        in_specs=[
            _vspec((Tt, LANE_TILE), lambda i, j: (j, i)),
            _vspec(tabb.shape, lambda i, j: (0, 0)),
        ],
        out_specs=[_vspec((4, LANE_TILE), lambda i, j: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((4, NL), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((4, LANE_TILE), jnp.float32)],
    )(pair2, tabb)
    return red_flat.T.reshape(NL, GROUP, GROUP)


