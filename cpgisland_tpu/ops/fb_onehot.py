"""One-hot-emission reduced kernels for the forward-backward E-step.

The probability-space twin of ops.viterbi_onehot: for one-hot-emission
models (the flagship 8-state preset — emissions at CpGIslandFinder.java:
166-173; one-hot rows are EM fixed points, so trained models keep the
structure) the alpha/beta vectors are EXACTLY ZERO outside the 2-state
group of the position's symbol, so the K-state recurrences reduce to
2-state recurrences whose per-step 2x2 transition is A (times the emission
probability) sliced between the previous symbol's group and the current
symbol's group.

Unlike the max-plus case, the reduction here is exact WITHOUT caveats about
out-of-group candidates: in (+, x) the dropped terms are multiplications by
exact f32 zeros, so the reduced sums equal the dense sums bit-for-bit; the
only cross-engine differences are the per-tile renormalization scalars of
the products kernel (dense normalizes over all K^2 entries, reduced over
its 4 — directions, which are all that leave the kernel, agree to ~1 ulp).

Pieces (wired into ops.fb_pallas behind its ``onehot`` static flags):
- `_oh_prod_kernel` — per-lane 2x2 transfer products, t-tiled with the
  running product in VMEM scratch (mirrors fb_pallas._prod_kernel).
- `_oh_fwd_kernel` / `_oh_bwd_kernel` / `_oh_bwd_conf_kernel` — the reduced
  recurrences with the same deferred-Rabiner / time-shifted-input structure
  as their dense twins; streams shrink from 32 to 8 B/symbol per direction.
- XLA twins for non-TPU backends (the Pallas interpreter evaluates these
  select-derived carried chains pathologically slowly — same workaround as
  ops.viterbi_onehot, same bit-level arithmetic).

Shared with the decode engine: group detection (`viterbi_onehot._groups`),
the pair stream with two-level forward-fill (`viterbi_onehot._pair_stream`),
and the lane-broadcast table trick (`_bcast_tab` — Mosaic supports [1, LT]
sublane broadcasts but not [1, 1] scalar broadcasts).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - mirrors ops.viterbi_pallas
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.viterbi_onehot import (
    GROUP,
    LANE_TILE,
    ROW_TILE,
    _bcast_tab,
    _groups,
    _interpret,
    _pair_stream,
    _vspec,
    supports,
    supports_concrete,
)

__all__ = [
    "supports", "supports_concrete", "prob_pair_table", "run_products_onehot",
]


def prob_pair_table(params: HmmParams, gt: jnp.ndarray):
    """Probability-space pair tables.

    tab[p] for p = s_prev * S + s_cur holds [T00, T01, T10, T11] with
    T[a, c] = A[gt[s_prev, a], gt[s_cur, c]] * B[gt[s_cur, c], s_cur] — the
    same product the dense kernels compute per lane (A row times the
    emission select), so values are bit-identical.  PAD pairs (p >= S*S)
    carry the identity and are handled by the select-tree defaults.
    """
    S = params.n_symbols
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    A_red = A[gt[:, :, None, None], gt[None, None, :, :]]  # [S, 2, S, 2]
    B_red = B[gt, jnp.arange(S)[:, None]]  # [S, 2]
    M = A_red * B_red[None, None, :, :]
    return jnp.transpose(M, (0, 2, 1, 3)).reshape(S * S, 4).astype(jnp.float32)


PROB_IDENT = (1.0, 0.0, 0.0, 1.0)  # the (+, x) identity matrix entries


def _select4_prob(tile, tab_ref, nreal):
    """Pair select with probability identity defaults (shared select tree —
    viterbi_onehot._select4 parametrized by the semiring identity)."""
    from cpgisland_tpu.ops.viterbi_onehot import _select4

    return _select4(tile, tab_ref, nreal, ident=PROB_IDENT)


def _oh_prod_kernel(pair_ref, tab_ref, out_ref, C_scr, *, nreal, bk):
    """(+,x) product of each lane's reduced step matrices -> [4, LT].

    Mirrors fb_pallas._prod_kernel: t tiled over the inner grid axis with
    the running product carried in VMEM scratch; every ROW_TILE steps the
    2x2 renormalizes by its own total (directions only leave the kernel).
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = pair_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        C_scr[0:1, :] = jnp.ones((1, lt), jnp.float32)
        C_scr[1:2, :] = jnp.zeros((1, lt), jnp.float32)
        C_scr[2:3, :] = jnp.zeros((1, lt), jnp.float32)
        C_scr[3:4, :] = jnp.ones((1, lt), jnp.float32)

    C0 = tuple(C_scr[i : i + 1, :] for i in range(4))

    def body(c, C):
        c00, c01, c10, c11 = C
        tile = pair_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]
        t00, t01, t10, t11 = _select4_prob(tile, tab_ref, nreal)
        for r in range(ROW_TILE):
            a00 = t00[r : r + 1, :]
            a01 = t01[r : r + 1, :]
            a10 = t10[r : r + 1, :]
            a11 = t11[r : r + 1, :]
            n00 = c00 * a00 + c01 * a10
            n01 = c00 * a01 + c01 * a11
            n10 = c10 * a00 + c11 * a10
            n11 = c10 * a01 + c11 * a11
            c00, c01, c10, c11 = n00, n01, n10, n11
        tot = c00 + c01 + c10 + c11
        inv = 1.0 / jnp.maximum(tot, 1e-30)
        return c00 * inv, c01 * inv, c10 * inv, c11 * inv

    C = jax.lax.fori_loop(0, bk // ROW_TILE, body, C0)
    for i in range(4):
        C_scr[i : i + 1, :] = C[i]

    @pl.when(j == n_t - 1)
    def _flush():
        for i in range(4):
            out_ref[i : i + 1, :] = C_scr[i : i + 1, :]


def _xla_products_prob(tab: jnp.ndarray, pair2: jnp.ndarray) -> jnp.ndarray:
    """XLA twin of the reduced products (non-TPU): per-step renorm instead of
    per-tile (directions identical; only the internal scalar differs)."""
    nP = tab.shape[0]
    NL = pair2.shape[1]
    ident = jnp.asarray([1.0, 0.0, 0.0, 1.0], jnp.float32)
    tab_ext = jnp.concatenate([tab, jnp.broadcast_to(ident, (1, 4))], axis=0)
    C0 = jnp.broadcast_to(ident, (NL, 4)) + (pair2[0, :, None] * 0).astype(jnp.float32)

    def step(C, pk):
        oh = jax.nn.one_hot(jnp.minimum(pk, nP), nP + 1, dtype=tab.dtype)
        T = jnp.matmul(oh, tab_ext, precision=jax.lax.Precision.HIGHEST)
        n00 = C[:, 0] * T[:, 0] + C[:, 1] * T[:, 2]
        n01 = C[:, 0] * T[:, 1] + C[:, 1] * T[:, 3]
        n10 = C[:, 2] * T[:, 0] + C[:, 3] * T[:, 2]
        n11 = C[:, 2] * T[:, 1] + C[:, 3] * T[:, 3]
        C = jnp.stack([n00, n01, n10, n11], axis=1)
        return C / jnp.maximum(jnp.sum(C, axis=1, keepdims=True), 1e-30), None

    C, _ = jax.lax.scan(step, C0, pair2)
    return C.reshape(NL, GROUP, GROUP)


def _scatter_products_prob(red, gt, e_in, e_out, K):
    """[NL, 2, 2] reduced products -> [NL, K, K] dense (zero fill) — exact:
    the dense product's out-of-group entries are multiplied by exact zeros
    in every consumer (entering directions / anchor compositions)."""
    from cpgisland_tpu.ops.viterbi_onehot import _scatter_products

    return _scatter_products(red, gt, e_in, e_out, K, fill=0.0)


def run_products_onehot(
    params: HmmParams, sel_t: jnp.ndarray, prev0, Tt: int
) -> jnp.ndarray:
    """Reduced per-lane transfer products, scattered to dense [NL, K, K].

    sel_t: [lane_T, NL] int32 selection symbols (PAD >= S marks identity
    steps, exactly _run_products_kernel's input transposed); prev0: [] the
    symbol emitted before this segment's first position (entry group of
    lane 0).  Drop-in replacement for fb_pallas._run_products_kernel for
    one-hot models.
    """
    K, S = params.n_states, params.n_symbols
    gt = _groups(params)
    tab = prob_pair_table(params, gt)
    pair2, e_in, e_out = _pair_stream(params, sel_t, jnp.asarray(prev0, jnp.int32))
    NL = sel_t.shape[1]
    if _interpret():
        red = _xla_products_prob(tab, pair2)
    else:
        tabb = _bcast_tab(tab)
        (red_flat,) = pl.pallas_call(
            functools.partial(_oh_prod_kernel, nreal=S * S, bk=Tt),
            grid=(NL // LANE_TILE, sel_t.shape[0] // Tt),
            in_specs=[
                _vspec((Tt, LANE_TILE), lambda i, j: (j, i)),
                _vspec(tabb.shape, lambda i, j: (0, 0)),
            ],
            out_specs=[_vspec((4, LANE_TILE), lambda i, j: (0, i))],
            out_shape=[jax.ShapeDtypeStruct((4, NL), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((4, LANE_TILE), jnp.float32)],
        )(pair2, tabb)
        red = red_flat.T.reshape(NL, GROUP, GROUP)
    return _scatter_products_prob(red, gt, e_in, e_out, K)
