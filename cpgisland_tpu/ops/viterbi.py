"""Viterbi decoding as JAX scans.

Replaces the reference's ``HmmEvaluator.decode(model, seq, logScaled=true)``
call (CpGIslandFinder.java:260) — Mahout's sequential log-space Viterbi DP run
one 1 MiB chunk at a time on the driver JVM.  Here:

- :func:`viterbi` — log-space DP as a single `lax.scan` with int8 backpointers
  and a reverse-scan backtrace.  Exact, O(T) sequential steps; the baseline and
  the per-chunk compat path.  `vmap`-able over a batch of chunks.
- :func:`viterbi_padded` — same, but observation values >= n_symbols (the
  chunking PAD sentinel) are treated as "no observation": the DP state passes
  through unchanged, so padded tails never affect the decoded prefix.

This sequential decoder is the semantic baseline the parallel (blockwise
max-plus scan) decoder is tested against.

All scores use float32 log space with the finite LOG_ZERO stand-in from
``models.hmm`` so -inf arithmetic can never produce NaNs on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from cpgisland_tpu.models.hmm import HmmParams


@partial(jax.jit, static_argnames=("return_score",))
def viterbi(params: HmmParams, obs: jnp.ndarray, return_score: bool = True):
    """Most-likely hidden-state path for one observation sequence.

    obs: [T] integer symbols in [0, n_symbols).
    Returns (path [T] int32, score float32): path the argmax state sequence,
    score its joint log-probability (what Mahout's decode maximizes).
    """
    return _viterbi_impl(params, obs, None, return_score)


@partial(jax.jit, static_argnames=("return_score",))
def viterbi_padded(params: HmmParams, obs: jnp.ndarray, length: jnp.ndarray, return_score: bool = True):
    """Viterbi over a padded chunk: positions >= length are pass-through.

    The returned path is only meaningful for t < length (padded tail positions
    repeat the final state).
    """
    return _viterbi_impl(params, obs, length, return_score)


def _viterbi_impl(params, obs, length, return_score):
    K = params.n_states
    obs = obs.astype(jnp.int32)
    T = obs.shape[0]
    # Emission log-prob rows indexed by symbol: [M, K]; padded symbols (>= M)
    # contribute 0 so they cannot perturb scores even before masking.
    emit_t = params.log_B.T  # [M, K]
    if length is not None:
        emit_t = jnp.concatenate([emit_t, jnp.zeros((1, K), emit_t.dtype)], axis=0)
        obs_clipped = jnp.minimum(obs, params.n_symbols)
    else:
        obs_clipped = obs

    delta0 = params.log_pi + emit_t[obs_clipped[0]]

    # The carry is (delta normalized to max 0, accumulated offset): scores
    # grow ~-1.3/symbol, and unnormalized f32 deltas at genome length reach
    # magnitudes where the ulp dwarfs the O(1) per-state differences every
    # argmax decision rides on (the same f32-range guard the parallel
    # engines apply per combine, viterbi_parallel.nrm_maxplus).  Subtracting
    # the per-step max is decision-invariant; the offset restores the true
    # score at the end.
    off0 = jnp.max(delta0)
    delta0 = delta0 - off0

    def step(carry, inputs):
        delta, off, comp = carry
        o_t, t = inputs
        scores = delta[:, None] + params.log_A  # [K_from, K_to]
        bp = jnp.argmax(scores, axis=0).astype(jnp.int32)  # [K_to]
        new_delta = jnp.max(scores, axis=0) + emit_t[o_t]
        step_off = jnp.max(new_delta)
        new_delta = new_delta - step_off
        # Kahan-compensated offset sum: T scalar adds at growing magnitude
        # would otherwise drift the returned score by ~1e-5/step.
        y = step_off - comp
        tsum = off + y
        new_comp = (tsum - off) - y
        new_off = tsum
        if length is not None:
            is_pad = t >= length
            new_delta = jnp.where(is_pad, delta, new_delta)
            new_off = jnp.where(is_pad, off, new_off)
            new_comp = jnp.where(is_pad, comp, new_comp)
            bp = jnp.where(is_pad, jnp.arange(K, dtype=jnp.int32), bp)
        return (new_delta, new_off, new_comp), bp

    ts = jnp.arange(1, T)
    (delta_final, off_final, _), bps = jax.lax.scan(
        step, (delta0, off0, jnp.zeros((), delta0.dtype)), (obs_clipped[1:], ts)
    )

    last_state = jnp.argmax(delta_final).astype(jnp.int32)

    def back(state, bp):
        prev = bp[state]
        return prev, state

    # path_tail[t] is the chosen state at time t+1; the final carry is time 0.
    carry0, path_tail = jax.lax.scan(back, last_state, bps, reverse=True)
    path = jnp.concatenate([carry0[None], path_tail])
    if not return_score:
        return path
    return path, jnp.max(delta_final) + off_final


@partial(jax.jit, static_argnames=("return_score",))
def viterbi_batch(params: HmmParams, chunks: jnp.ndarray, lengths: jnp.ndarray, return_score: bool = True):
    """Decode a [N, T] batch of padded chunks in parallel via vmap.

    This is the batched replacement for the reference's serial per-chunk decode
    loop (CpGIslandFinder.java:256-260).
    """
    fn = lambda o, l: viterbi_padded(params, o, l, return_score=return_score)
    return jax.vmap(fn)(chunks, lengths)
