"""Pallas TPU kernels for the blockwise-parallel Viterbi decode.

Same three-pass algorithm as ops.viterbi_parallel (products -> backpointers ->
backtrace; see that module's docstring for the math and the reference citation
CpGIslandFinder.java:256-260), but with the hot per-step loops as fused Pallas
kernels instead of `lax.scan` over XLA HLO:

- **Lane layout**: decode lanes (sequence blocks) ride the 128-wide TPU lane
  dimension; the K<=8 state dimension rides sublanes.  Every per-step op is a
  full-width VPU op — no [8,8] matrices rattling around in padded (8,128)
  tiles the way the XLA scan lays them out.
- **Fused step matrices**: M_t[i,j] = logA[i,j] + logB[j, o_t] is built in
  registers from the symbol byte each step — the [S+1, K, K] table gather /
  one-hot matmul of the XLA path disappears.
- **Bit-packed backpointers**: all K argmax pointers of a step pack into one
  int32 (3 bits x 8 states), so the backtrace state machine is
  ``state = (packed >> 3*state) & 7`` — 4 bytes/symbol of HBM traffic instead
  of 8, and the exit->entry composition table threads through the same packing.

The kernels are exact: same scores, same first-argmax tie-breaking as the XLA
path.  On non-TPU backends `interpret=True` runs them through the Pallas
interpreter so CI on the virtual CPU mesh exercises identical code.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some non-TPU builds; interpret mode needs only pl
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from cpgisland_tpu.models.hmm import LOG_ZERO, HmmParams
from cpgisland_tpu.ops.viterbi_parallel import maxplus_matmul

LANE_TILE = 128  # lanes per kernel instance = one TPU vreg width
DEFAULT_BLOCK = 512  # symbols per lane (bk); VMEM per instance stays ~1 MiB

MAX_PACK_STATES = 8  # 3-bit packing: state ids 0..7 -> one int32 per step


def _vspec(block_shape=None, index_map=None):
    if _VMEM is None:
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


def supports(params: HmmParams) -> bool:
    """Kernel eligibility: the 3-bit backpointer packing needs K <= 8."""
    return params.n_states <= MAX_PACK_STATES


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _step_mats_const(params: HmmParams):
    """Kernel operands: log transition/emission matrices as f32 (passed as
    pallas inputs — kernels may not close over traced values)."""
    K, S = params.n_states, params.n_symbols
    logA = jnp.asarray(params.log_A, jnp.float32)
    logB = jnp.asarray(params.log_B, jnp.float32)
    return K, S, logA, logB


def _eye_log(K: int, lt: int) -> jnp.ndarray:
    """[K, K, lt] broadcast max-plus identity, built from iota in-kernel."""
    i = jax.lax.broadcasted_iota(jnp.int32, (K, K, lt), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (K, K, lt), 1)
    return jnp.where(i == j, 0.0, LOG_ZERO).astype(jnp.float32)


def _emit_sel(logB, syms, K, S):
    """Bsel[j, :] = logB[j, syms[:]] via an unrolled compare-select tree.

    syms: [LT] int32 (PAD >= S allowed — caller masks separately).
    Returns [K, LT] f32.
    """
    out = jnp.zeros((K, syms.shape[-1]), jnp.float32)
    for s in range(S):
        out = jnp.where((syms == s)[None, :], logB[:, s][:, None], out)
    return out


def _products_kernel(steps_ref, logA_ref, logB_ref, out_ref, *, K, S, bk):
    """Pass A: max-plus product of the lane's bk step matrices -> [K*K, LT]."""
    lt = steps_ref.shape[1]
    logA = logA_ref[:, :]
    logB = logB_ref[:, :]
    eye_b = _eye_log(K, lt)
    C0 = eye_b

    def body(t, C):
        syms = steps_ref[t, :]
        is_pad = (syms >= S)[None, None, :]
        Bsel = _emit_sel(logB, syms, K, S)  # [K, LT]
        M = jnp.where(is_pad, eye_b, logA[:, :, None] + Bsel[None, :, :])
        # new_C[i, j] = max_m C[i, m] + M[m, j]
        return jnp.max(C[:, :, None, :] + M[None, :, :, :], axis=1)

    C = jax.lax.fori_loop(0, bk, body, C0)
    out_ref[:, :] = C.reshape(K * K, lt)


def _backpointers_kernel(
    steps_ref, venter_ref, logA_ref, logB_ref, bp_ref, dexit_ref, ftab_ref, *, K, S, bk
):
    """Pass B: forward delta recursion with true entering vectors.

    Emits per-step bit-packed backpointers, the block's exit score vector, and
    the packed exit->entry composition table.
    """
    lt = steps_ref.shape[1]
    logA = logA_ref[:, :]
    logB = logB_ref[:, :]
    delta0 = venter_ref[:, :]  # [K, LT]
    # E_packed[lane] holds E[j] (3 bits each): entry state reached from exit j.
    e0 = jnp.zeros((lt,), jnp.int32)
    for j in range(K):
        e0 = e0 | (j << (3 * j))

    def body(t, carry):
        delta, E = carry
        syms = steps_ref[t, :]
        is_pad = syms >= S
        Bsel = _emit_sel(logB, syms, K, S)
        # scores[i, j, :] = delta[i, :] + M[i, j, :] with the emission folded
        # into M before the max — bit-exact with the XLA twin's rounding and
        # tie-breaking (viterbi_parallel._pass_backpointers).
        scores = delta[:, None, :] + (logA[:, :, None] + Bsel[None, :, :])
        bp = jnp.argmax(scores, axis=0).astype(jnp.int32)  # [K_to, LT]
        new_delta = jnp.max(scores, axis=0)
        # PAD -> identity step: delta unchanged, bp[j] = j.
        jj = jax.lax.broadcasted_iota(jnp.int32, (K, lt), 0)
        bp = jnp.where(is_pad[None, :], jj, bp)
        new_delta = jnp.where(is_pad[None, :], delta, new_delta)
        # Pack this step's K pointers into one int32 per lane.
        packed = jnp.zeros((lt,), jnp.int32)
        for j in range(K):
            packed = packed | (bp[j] << (3 * j))
        bp_ref[t, :] = packed
        # Compose: E'[j] = E[bp[j]]  (unpack at a variable offset, repack).
        newE = jnp.zeros((lt,), jnp.int32)
        for j in range(K):
            ej = jnp.right_shift(E, 3 * bp[j]) & 7
            newE = newE | (ej << (3 * j))
        return new_delta, newE

    delta, E = jax.lax.fori_loop(0, bk, body, (delta0, e0))
    dexit_ref[:, :] = delta
    ftab_ref[0, :] = E


def _backtrace_kernel(bp_ref, exit_ref, path_ref, *, bk):
    """Pass C: walk packed backpointers from the anchored exit state."""

    def body(i, state):
        t = bk - 1 - i
        path_ref[t, :] = state.astype(jnp.int8)
        return jnp.right_shift(bp_ref[t, :], 3 * state) & 7

    jax.lax.fori_loop(0, bk, body, exit_ref[0, :])


def _pad_lanes(x, nb_pad, fill):
    pad = nb_pad - x.shape[-1]
    if pad == 0:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)], constant_values=fill)


# --- Pass-level API (same contracts as the XLA twins in ops.viterbi_parallel,
# so parallel.decode can swap engines under shard_map).  Lane counts that are
# not multiples of LANE_TILE are padded internally with identity blocks and
# sliced back off.


def pass_products(params: HmmParams, steps2: jnp.ndarray):
    """Pallas twin of viterbi_parallel._pass_products: (incl [nb,K,K], total)."""
    K, S, logA, logB = _step_mats_const(params)
    bk, nb = steps2.shape
    nb_pad = -(-nb // LANE_TILE) * LANE_TILE
    steps2 = _pad_lanes(steps2, nb_pad, jnp.int32(S))
    P_flat = pl.pallas_call(
        functools.partial(_products_kernel, K=K, S=S, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((K, K), lambda i: (0, 0)),
            _vspec((K, S), lambda i: (0, 0)),
        ],
        out_specs=_vspec((K * K, LANE_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((K * K, nb_pad), jnp.float32),
        interpret=_interpret(),
    )(steps2, logA, logB)
    P = P_flat.T.reshape(nb_pad, K, K)[:nb]
    incl = jax.lax.associative_scan(maxplus_matmul, P, axis=0)
    return incl, incl[-1]


def pass_backpointers(params: HmmParams, v_enter: jnp.ndarray, steps2: jnp.ndarray):
    """Pallas twin of viterbi_parallel._pass_backpointers.

    Returns (delta_blocks [nb, K], F [nb, K], bp_packed [bk, nb] int32) — the
    backpointer blob is bit-packed, consumed only by :func:`pass_backtrace`.
    """
    K, S, logA, logB = _step_mats_const(params)
    bk, nb = steps2.shape
    nb_pad = -(-nb // LANE_TILE) * LANE_TILE
    steps2 = _pad_lanes(steps2, nb_pad, jnp.int32(S))
    v_enter2 = _pad_lanes(v_enter.T, nb_pad, 0.0)
    bp_packed, dexit, ftab_packed = pl.pallas_call(
        functools.partial(_backpointers_kernel, K=K, S=S, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((K, LANE_TILE), lambda i: (0, i)),
            _vspec((K, K), lambda i: (0, 0)),
            _vspec((K, S), lambda i: (0, 0)),
        ],
        out_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((K, LANE_TILE), lambda i: (0, i)),
            _vspec((1, LANE_TILE), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bk, nb_pad), jnp.int32),
            jax.ShapeDtypeStruct((K, nb_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, nb_pad), jnp.int32),
        ],
        interpret=_interpret(),
    )(steps2, v_enter2, logA, logB)
    shifts = 3 * jnp.arange(K, dtype=jnp.int32)
    F = (jnp.right_shift(ftab_packed[0, :nb, None], shifts[None, :]) & 7).astype(jnp.int32)
    # bp_packed stays lane-padded — it is the dominant buffer (~4 B/symbol) and
    # pass_backtrace consumes it as-is, deriving nb from len(exits); slicing it
    # here would materialize an extra HBM copy just to re-pad it there.
    return dexit.T[:nb], F, bp_packed


def pass_backtrace(bp_packed: jnp.ndarray, exits: jnp.ndarray) -> jnp.ndarray:
    """Pallas twin of viterbi_parallel._pass_backtrace -> [bk*nb] path.

    bp_packed: [bk, >=nb] (possibly lane-padded by pass_backpointers);
    exits: [nb] — the real lane count.
    """
    bk = bp_packed.shape[0]
    nb = exits.shape[0]
    nb_pad = -(-bp_packed.shape[1] // LANE_TILE) * LANE_TILE
    bp_packed = _pad_lanes(bp_packed, nb_pad, 0)
    exits2 = _pad_lanes(exits[None, :], nb_pad, 0)
    path2 = pl.pallas_call(
        functools.partial(_backtrace_kernel, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((1, LANE_TILE), lambda i: (0, i)),
        ],
        out_specs=_vspec((bk, LANE_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((bk, nb_pad), jnp.int8),
        interpret=_interpret(),
    )(bp_packed, exits2)
    return path2[:, :nb].T.reshape(-1).astype(jnp.int32)


def _require_support(params):
    if not supports(params):
        raise ValueError(
            f"viterbi_pallas packs backpointers 3 bits/state: needs "
            f"n_states <= {MAX_PACK_STATES}, got {params.n_states}"
        )


def viterbi_pallas(
    params: HmmParams,
    obs: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK,
    return_score: bool = True,
):
    """Exact Viterbi path via the fused Pallas block kernels (single device).

    Thin front-end over ops.viterbi_parallel.viterbi_parallel(engine="pallas")
    — one shared wrapper owns the padding / T==1 / entry-state logic for both
    lowerings, so they cannot drift.  Same PAD semantics, same tie-breaking.
    """
    _require_support(params)
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel

    return viterbi_parallel(
        params, obs, block_size=block_size, return_score=return_score, engine="pallas"
    )


def viterbi_pallas_batch(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK,
    return_score: bool = True,
):
    """Batched decode through the Pallas engine (see viterbi_parallel_batch)."""
    _require_support(params)
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel_batch

    return viterbi_parallel_batch(
        params, chunks, lengths, block_size=block_size, return_score=return_score,
        engine="pallas",
    )
