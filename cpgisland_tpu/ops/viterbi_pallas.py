"""Pallas TPU kernels for the blockwise-parallel Viterbi decode.

Same three-pass algorithm as ops.viterbi_parallel (products -> backpointers ->
backtrace; see that module's docstring for the math and the reference citation
CpGIslandFinder.java:256-260), but with the hot per-step loops as fused Pallas
kernels instead of `lax.scan` over XLA HLO:

- **Lane layout**: decode lanes (sequence blocks) ride the 128-wide TPU lane
  dimension; the K<=8 state dimension rides sublanes.  Every per-step op is a
  full-width VPU op — no [8,8] matrices rattling around in padded (8,128)
  tiles the way the XLA scan lays them out.
- **Fused step matrices**: M_t[i,j] = logA[i,j] + logB[j, o_t] is built in
  registers from the symbol byte each step — the [S+1, K, K] table gather /
  one-hot matmul of the XLA path disappears.
- **Bit-packed backpointers**: all K argmax pointers of a step pack into one
  int32 (3 bits x 8 states), so the backtrace state machine is
  ``state = (packed >> 3*state) & 7`` — 4 bytes/symbol of HBM traffic instead
  of 8, and the exit->entry composition table threads through the same packing.

The kernels are exact: same scores, same first-argmax tie-breaking as the XLA
path.  On non-TPU backends `interpret=True` runs them through the Pallas
interpreter so CI on the virtual CPU mesh exercises identical code.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some non-TPU builds; interpret mode needs only pl
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from cpgisland_tpu.models.hmm import LOG_ZERO, HmmParams

# One shared block-size default for both lowerings (the sweep that set it
# lives at viterbi_parallel.DEFAULT_BLOCK) — a separate pallas default once
# silently pinned the production batch path at 512 while benches measured
# the retuned value.
from cpgisland_tpu.ops.viterbi_parallel import DEFAULT_BLOCK, scan_block_products

LANE_TILE = 128  # lanes per kernel instance = one TPU vreg width

# All in-kernel dynamic row offsets are multiples of ROW_TILE: Mosaic requires
# statically-provable sublane alignment for dynamic VMEM loads/stores of
# (8,128)-tiled i32/f32, so the per-step loops work on 8-row tiles with the
# per-row work unrolled.  Block lengths are padded up to a multiple internally
# (PAD rows are identity steps, so padding is semantics-free).
ROW_TILE = 8

MAX_PACK_STATES = 8  # 3-bit packing: state ids 0..7 -> one int32 per step

# Identity exit->entry table, 3-bit packed: bits [3j, 3j+3) hold j.
PACKED_IDENTITY = 0
for _j in range(MAX_PACK_STATES):
    PACKED_IDENTITY |= _j << (3 * _j)
del _j


def _vspec(block_shape=None, index_map=None):
    if _VMEM is None:
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


def supports(params: HmmParams) -> bool:
    """Kernel eligibility: the 3-bit backpointer packing needs K <= 8."""
    return params.n_states <= MAX_PACK_STATES


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _step_mats_const(params: HmmParams):
    """Kernel operands: log transition/emission matrices as f32 (passed as
    pallas inputs — kernels may not close over traced values).  The transition
    matrix is passed TRANSPOSED (logAT[j, i] = logA[i, j]) so kernels can take
    its columns as [K, 1] slices without an in-kernel relayout."""
    K, S = params.n_states, params.n_symbols
    logAT = jnp.asarray(params.log_A, jnp.float32).T
    logB = jnp.asarray(params.log_B, jnp.float32)
    return K, S, logAT, logB


def _id_col(K: int, m: int) -> jnp.ndarray:
    """[K, 1] max-plus identity column m: 0 at row m, LOG_ZERO elsewhere."""
    i = jax.lax.broadcasted_iota(jnp.int32, (K, 1), 0)
    return jnp.where(i == m, 0.0, LOG_ZERO).astype(jnp.float32)


def _emit_sel(logB, syms, K, S):
    """Bsel[j, :] = logB[j, syms[:]] via an unrolled compare-select tree.

    syms: [1, LT] int32 (PAD >= S allowed — caller masks separately).
    Returns [K, LT] f32.

    Everything in these kernels stays rank 2 with shapes (sublane, lane):
    Mosaic's vector layout assigns the last two dims to (sublane, lane) and
    this toolchain both rejects some rank-1 values outright and mis-lowers
    broadcast/reduce over the leading dims of rank-3/4 arrays (observed:
    duplicated rows in the max-plus contraction).  Hence the unrolled loops
    over the tiny K<=8 state dimension instead of batched rank-3/4 ops.
    """
    out = jnp.zeros((K, syms.shape[-1]), jnp.float32)
    for s in range(S):
        out = jnp.where(syms == s, logB[:, s : s + 1], out)
    return out


def _products_kernel(steps_ref, logAT_ref, logB_ref, out_ref, *, K, S, bk):
    """Pass A: max-plus product of the lane's bk step matrices -> [K*K, LT].

    C is carried as a tuple of K rank-2 rows: C[i] is [K, LT] with
    C[i][m, lane] = product[i, m] for that lane's block prefix.
    """
    lt = steps_ref.shape[1]
    logAT = logAT_ref[:, :]
    logB = logB_ref[:, :]
    C0 = tuple(jnp.broadcast_to(_id_col(K, i), (K, lt)) for i in range(K))

    def body(c, C):
        tile = steps_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]  # aligned [8, LT]
        for r in range(ROW_TILE):
            syms = tile[r : r + 1, :]  # [1, LT]
            is_pad = syms >= S
            Bsel = _emit_sel(logB, syms, K, S)  # [K, LT]
            # M_m[j, lane] = logA[m, j] + logB[j, sym] (identity col for PAD),
            # computed once per m; same add order as the XLA twin (M first).
            Ms = tuple(
                jnp.where(is_pad, _id_col(K, m), logAT[:, m : m + 1] + Bsel)
                for m in range(K)
            )
            # new_C[i][j] = max_m C[i][m] + M_m[j]
            C = tuple(
                functools.reduce(
                    jnp.maximum,
                    [Ci[m : m + 1, :] + Ms[m] for m in range(K)],
                )
                for Ci in C
            )
        return C

    C = jax.lax.fori_loop(0, bk // ROW_TILE, body, C0)
    for i in range(K):
        out_ref[i * K : (i + 1) * K, :] = C[i]


def _backpointers_kernel(
    steps_ref, venter_ref, logAT_ref, logB_ref, bp_ref, dexit_ref, ftab_ref, *, K, S, bk
):
    """Pass B: forward delta recursion with true entering vectors.

    Emits per-step bit-packed backpointers, the block's exit score vector, and
    the packed exit->entry composition table.
    """
    lt = steps_ref.shape[1]
    logAT = logAT_ref[:, :]
    logB = logB_ref[:, :]
    delta0 = venter_ref[:, :]  # [K, LT]
    # E_packed[lane] holds E[j] (3 bits each): entry state reached from exit j.
    e0 = jnp.full((1, lt), PACKED_IDENTITY, jnp.int32)

    def body(c, carry):
        delta, E = carry
        tile = steps_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]  # aligned [8, LT]
        rows = []
        for r in range(ROW_TILE):
            syms = tile[r : r + 1, :]  # [1, LT]
            is_pad = syms >= S
            Bsel = _emit_sel(logB, syms, K, S)
            # scores_m[j, :] = delta[m, :] + M[m, j, :] with the emission
            # folded into M before the max — bit-exact with the XLA twin's
            # rounding (viterbi_parallel._pass_backpointers); the strict >
            # ascending-m sweep reproduces argmax's first-max tie-breaking.
            best = jnp.full((K, lt), LOG_ZERO, jnp.float32)
            bp = jnp.zeros((K, lt), jnp.int32)
            for m in range(K):
                cand = delta[m : m + 1, :] + (logAT[:, m : m + 1] + Bsel)
                take = cand > best
                bp = jnp.where(take, m, bp)
                best = jnp.where(take, cand, best)
            # PAD -> identity step: delta unchanged, bp[j] = j.
            jj = jax.lax.broadcasted_iota(jnp.int32, (K, lt), 0)
            bp = jnp.where(is_pad, jj, bp)
            delta = jnp.where(is_pad, delta, best)
            # Pack this step's K pointers into one int32 per lane.
            packed = jnp.zeros((1, lt), jnp.int32)
            for j in range(K):
                packed = packed | (bp[j : j + 1, :] << (3 * j))
            rows.append(packed)
            # Compose: E'[j] = E[bp[j]]  (unpack at a variable offset, repack).
            newE = jnp.zeros((1, lt), jnp.int32)
            for j in range(K):
                ej = jnp.right_shift(E, 3 * bp[j : j + 1, :]) & 7
                newE = newE | (ej << (3 * j))
            E = newE
        bp_ref[pl.ds(c * ROW_TILE, ROW_TILE), :] = jnp.concatenate(rows, axis=0)
        return delta, E

    delta, E = jax.lax.fori_loop(0, bk // ROW_TILE, body, (delta0, e0))
    dexit_ref[:, :] = delta
    ftab_ref[:, :] = E


def _backtrace_kernel(bp_ref, exit_ref, path_ref, *, bk):
    """Pass C: walk packed backpointers from the anchored exit state."""
    nc = bk // ROW_TILE

    def body(i, state):
        c = nc - 1 - i
        tile = bp_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]  # aligned [8, LT]
        rows = [None] * ROW_TILE
        for r in range(ROW_TILE - 1, -1, -1):
            rows[r] = state  # [1, LT]
            state = jnp.right_shift(tile[r : r + 1, :], 3 * state) & 7
        path_ref[pl.ds(c * ROW_TILE, ROW_TILE), :] = jnp.concatenate(rows, axis=0)
        return state

    jax.lax.fori_loop(0, nc, body, exit_ref[:, :])


def _pad_lanes(x, nb_pad, fill):
    pad = nb_pad - x.shape[-1]
    if pad == 0:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)], constant_values=fill)


def _pad_rows(steps2, S):
    """Pad the step axis to a multiple of ROW_TILE with PAD (identity) steps."""
    bk = steps2.shape[0]
    bk_pad = -(-bk // ROW_TILE) * ROW_TILE
    if bk_pad == bk:
        return steps2, bk_pad
    return (
        jnp.pad(steps2, [(0, bk_pad - bk), (0, 0)], constant_values=jnp.int32(S)),
        bk_pad,
    )


# --- Pass-level API (same contracts as the XLA twins in ops.viterbi_parallel,
# so parallel.decode can swap engines under shard_map).  Lane counts that are
# not multiples of LANE_TILE are padded internally with identity blocks and
# sliced back off.


def pass_products(params: HmmParams, steps2: jnp.ndarray, prev0=None):
    """Pallas twin of viterbi_parallel._pass_products: (incl, offs, total)."""
    K, S, logAT, logB = _step_mats_const(params)
    nb = steps2.shape[1]
    nb_pad = -(-nb // LANE_TILE) * LANE_TILE
    steps2 = _pad_lanes(steps2, nb_pad, jnp.int32(S))
    steps2, bk = _pad_rows(steps2, S)
    P_flat = pl.pallas_call(
        functools.partial(_products_kernel, K=K, S=S, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((K, K), lambda i: (0, 0)),
            _vspec((K, S), lambda i: (0, 0)),
        ],
        out_specs=_vspec((K * K, LANE_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((K * K, nb_pad), jnp.float32),
        interpret=_interpret(),
    )(steps2, logAT, logB)
    P = P_flat.T.reshape(nb_pad, K, K)[:nb]
    # The prefix scan + f32-range normalization is the SHARED implementation
    # (viterbi_parallel.scan_block_products) so both engines stay bit-identical.
    incl, offs = scan_block_products(P)
    return incl, offs, incl[-1]


def pass_backpointers(params: HmmParams, v_enter: jnp.ndarray, steps2: jnp.ndarray, prev0=None):
    """Pallas twin of viterbi_parallel._pass_backpointers.

    Returns (delta_blocks [nb, K], F [nb, K], blob) — the backpointer blob is
    bit-packed and row/lane-padded, consumed only by :func:`pass_backtrace`.
    """
    K, S, logAT, logB = _step_mats_const(params)
    bk_real, nb = steps2.shape
    nb_pad = -(-nb // LANE_TILE) * LANE_TILE
    steps2 = _pad_lanes(steps2, nb_pad, jnp.int32(S))
    steps2, bk = _pad_rows(steps2, S)
    v_enter2 = _pad_lanes(v_enter.T, nb_pad, 0.0)
    bp_packed, dexit, ftab_packed = pl.pallas_call(
        functools.partial(_backpointers_kernel, K=K, S=S, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((K, LANE_TILE), lambda i: (0, i)),
            _vspec((K, K), lambda i: (0, 0)),
            _vspec((K, S), lambda i: (0, 0)),
        ],
        out_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((K, LANE_TILE), lambda i: (0, i)),
            _vspec((1, LANE_TILE), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bk, nb_pad), jnp.int32),
            jax.ShapeDtypeStruct((K, nb_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, nb_pad), jnp.int32),
        ],
        interpret=_interpret(),
    )(steps2, v_enter2, logAT, logB)
    shifts = 3 * jnp.arange(K, dtype=jnp.int32)
    F = (jnp.right_shift(ftab_packed[0, :nb, None], shifts[None, :]) & 7).astype(jnp.int32)
    # bp_packed stays row- and lane-padded — it is the dominant buffer
    # (~4 B/symbol) and pass_backtrace consumes it as-is (padded rows are
    # identity tables, so walking them is a no-op); slicing here would
    # materialize an extra HBM copy just to re-pad it there.
    return dexit.T[:nb], F, (bp_packed, bk_real)


def pass_backtrace(blob, exits: jnp.ndarray) -> jnp.ndarray:
    """Pallas twin of viterbi_parallel._pass_backtrace -> [bk*nb] path.

    blob: (bp_packed [bk_pad, >=nb], bk) from pass_backpointers;
    exits: [nb] — the real lane count.
    """
    bp_packed, bk_real = blob
    bk = bp_packed.shape[0]
    nb = exits.shape[0]
    nb_pad = -(-bp_packed.shape[1] // LANE_TILE) * LANE_TILE
    bp_packed = _pad_lanes(bp_packed, nb_pad, PACKED_IDENTITY)
    exits2 = _pad_lanes(exits[None, :], nb_pad, 0)
    path2 = pl.pallas_call(
        functools.partial(_backtrace_kernel, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((1, LANE_TILE), lambda i: (0, i)),
        ],
        out_specs=_vspec((bk, LANE_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((bk, nb_pad), jnp.int32),
        interpret=_interpret(),
    )(bp_packed, exits2)
    return path2[:bk_real, :nb].T.reshape(-1)


def _require_support(params):
    if not supports(params):
        raise ValueError(
            f"viterbi_pallas packs backpointers 3 bits/state: needs "
            f"n_states <= {MAX_PACK_STATES}, got {params.n_states}"
        )


def viterbi_pallas(
    params: HmmParams,
    obs: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK,
    return_score: bool = True,
):
    """Exact Viterbi path via the fused Pallas block kernels (single device).

    Thin front-end over ops.viterbi_parallel.viterbi_parallel(engine="pallas")
    — one shared wrapper owns the padding / T==1 / entry-state logic for both
    lowerings, so they cannot drift.  Same PAD semantics, same tie-breaking.
    """
    _require_support(params)
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel

    return viterbi_parallel(
        params, obs, block_size=block_size, return_score=return_score, engine="pallas"
    )


def viterbi_pallas_batch(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK,
    return_score: bool = True,
):
    """Batched decode through the Pallas engine (see viterbi_parallel_batch)."""
    _require_support(params)
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel_batch

    return viterbi_parallel_batch(
        params, chunks, lengths, block_size=block_size, return_score=return_score,
        engine="pallas",
    )
