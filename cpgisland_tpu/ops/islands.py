"""CpG island calling from a decoded state path.

Replaces the reference's per-chunk sequential state machine
(CpGIslandFinder.java:262-339) with a fully vectorized NumPy implementation:
island runs are found with boundary masks, per-run C/G/CpG counts with prefix
sums, and the machine's ``atC`` carry with a vectorized forward-fill — O(T) with
no Python loop, so post-processing keeps up with TPU decode throughput.

Two semantic modes:

- ``compat=True`` reproduces the reference bit-for-bit, including its quirks:
  (a) an island still open at the end of the path is never emitted
  (java:269-339: islands close only on seeing a background state);
  (b) ``atC`` is not cleared when an island opens on a non-C state
  (java:325-331), so a C at the tail of the previous island can contribute one
  spurious CpG count to the next island;
  (c) no minimum-length filter (the ``len > 200`` test is commented out,
  java:285).
- ``compat=False`` is the clean mode: islands open at the end of the path are
  emitted, CpG counts are strictly within-island C->G adjacencies, and
  ``min_len`` (Gardiner-Garden & Frommer's 200 bp) is applied if given.

Both modes emit records (beg, end, length, gc_content, oe_ratio) with 1-based
inclusive global coordinates beg + chunk*chunk_size + 1 (java:287-288) and the
filters GC > 0.5 and observed/expected CpG > 0.6 (java:285).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import numpy as np

from cpgisland_tpu.utils.chunking import DECODE_CHUNK

# State-id conventions (presets.HIDDEN_STATE_NAMES): 0..3 = A+C+G+T+ (island),
# 4..7 = A-C-G-T- (background); C state = 1, G state = 2 in both blocks.
N_ISLAND_STATES = 4
C_STATE = 1
G_STATE = 2


@dataclass(frozen=True)
class IslandCalls:
    """Columnar island-call records (1-based inclusive global coordinates)."""

    beg: np.ndarray  # int64 [n]
    end: np.ndarray  # int64 [n]
    length: np.ndarray  # int64 [n]
    gc_content: np.ndarray  # float64 [n]
    oe_ratio: np.ndarray  # float64 [n]
    # Optional record (chromosome) names, one per call — set by the clean
    # path's per-record decode; None keeps the reference's bare format.
    names: Optional[np.ndarray] = None  # object [n]

    def __len__(self) -> int:
        return int(self.beg.shape[0])

    def with_names(self, name: str) -> "IslandCalls":
        return replace(self, names=np.full(len(self), name, dtype=object))

    def as_tuples(self):
        return list(
            zip(
                self.beg.tolist(),
                self.end.tolist(),
                self.length.tolist(),
                self.gc_content.tolist(),
                self.oe_ratio.tolist(),
            )
        )

    def format_lines(self) -> str:
        """Reference output format: '%d %d %d %f %f\\n' (java:287-288); a
        record-name column is prefixed when per-record names are present."""
        if self.names is None:
            return "".join(
                "%d %d %d %f %f\n" % rec
                for rec in zip(self.beg, self.end, self.length, self.gc_content, self.oe_ratio)
            )
        return "".join(
            "%s %d %d %d %f %f\n" % rec
            for rec in zip(
                self.names, self.beg, self.end, self.length, self.gc_content, self.oe_ratio
            )
        )

    @staticmethod
    def concatenate(parts: list["IslandCalls"]) -> "IslandCalls":
        if not parts:
            return _empty_calls()
        named = [p.names is not None for p in parts]
        names = None
        if any(named):
            names = np.concatenate(
                [
                    p.names if p.names is not None else np.full(len(p), "", dtype=object)
                    for p in parts
                ]
            )
        return IslandCalls(
            beg=np.concatenate([p.beg for p in parts]),
            end=np.concatenate([p.end for p in parts]),
            length=np.concatenate([p.length for p in parts]),
            gc_content=np.concatenate([p.gc_content for p in parts]),
            oe_ratio=np.concatenate([p.oe_ratio for p in parts]),
            names=names,
        )


def _empty_calls() -> IslandCalls:
    z = np.zeros(0, dtype=np.int64)
    f = np.zeros(0, dtype=np.float64)
    return IslandCalls(z, z, z, f, f)


def counts_to_gc_oe(c_count, g_count, cg_count, length):
    """(gc_content, oe_ratio) in f64 from per-run int64 counts.

    THE one copy of the reference's two formulas (CpGIslandFinder.java:
    281-283): the host caller uses it directly and the device caller's host
    refine (islands_device._fetch_calls) uses it on compacted counts, so
    device/host bit-identity holds by construction, not by parallel edits.
    """
    gc = (c_count + g_count) / length
    both = (c_count > 0) & (g_count > 0)
    with np.errstate(divide="ignore", invalid="ignore"):
        oe = np.where(
            both,
            cg_count.astype(np.float64) * length
            / np.where(both, c_count.astype(np.float64) * g_count, 1.0),
            0.0,
        )
    return gc, oe


def _adjacency(in_mask: np.ndarray):
    """(prev_in, opening, continuing) boundary masks for island runs."""
    T = in_mask.shape[0]
    prev_in = np.empty(T, dtype=bool)
    prev_in[0] = False
    prev_in[1:] = in_mask[:-1]
    return prev_in, in_mask & ~prev_in, in_mask & prev_in


def _runs_to_calls(
    in_mask: np.ndarray,
    opening: np.ndarray,
    is_c: np.ndarray,
    is_g: np.ndarray,
    cg_event: np.ndarray,
    *,
    drop_open_at_end: bool,
    min_len: int | None,
    gc_threshold: float,
    oe_threshold: float,
    offset: int,
) -> IslandCalls:
    """Shared run accounting: masks -> filtered (beg,end,len,gc,oe) records.

    The single source of truth for run boundaries, prefix-sum counting, the
    gc/oe formulas, and the thresholds — both the 8-state caller and the
    observation-based caller feed it their mode-specific masks (``opening``
    comes from the caller's _adjacency pass; no mask is recomputed here).
    """
    T = in_mask.shape[0]
    starts = np.flatnonzero(opening)
    if starts.size == 0:
        return _empty_calls()
    next_in = np.empty(T, dtype=bool)
    next_in[-1] = False
    next_in[:-1] = in_mask[1:]
    last = np.flatnonzero(in_mask & ~next_in)  # last in-island index per run

    if drop_open_at_end:
        # Reference quirk (a): a run reaching the end of the path is never
        # closed, so it is never emitted (java:269-339).
        open_at_end = last == T - 1
        starts, last = starts[~open_at_end], last[~open_at_end]
        if starts.size == 0:
            return _empty_calls()

    def run_sums(events: np.ndarray) -> np.ndarray:
        cum = np.concatenate([[0], np.cumsum(events, dtype=np.int64)])
        return cum[last + 1] - cum[starts]

    c_count = run_sums(is_c)
    g_count = run_sums(is_g)
    cg_count = run_sums(cg_event)
    length = last - starts + 1

    gc, oe = counts_to_gc_oe(c_count, g_count, cg_count, length)

    keep = (gc > gc_threshold) & (oe > oe_threshold)
    if min_len is not None:
        keep &= length > min_len

    return IslandCalls(
        beg=(starts[keep] + offset + 1).astype(np.int64),
        end=(last[keep] + offset + 1).astype(np.int64),
        length=length[keep].astype(np.int64),
        gc_content=gc[keep].astype(np.float64),
        oe_ratio=oe[keep].astype(np.float64),
    )


def call_islands(
    path: np.ndarray,
    *,
    chunk: int = 0,
    chunk_size: int = DECODE_CHUNK,
    compat: bool = True,
    min_len: int | None = None,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
) -> IslandCalls:
    """Call CpG islands from a state path (see module docstring for modes)."""
    path = np.asarray(path)
    T = path.shape[0]
    if T == 0:
        return _empty_calls()

    in_mask = path < N_ISLAND_STATES
    prev_in, opening, continuing = _adjacency(in_mask)

    is_c = in_mask & (path == C_STATE)
    is_g = in_mask & (path == G_STATE)

    if compat:
        # Quirk (b): the machine's atC carry.  atC is (re)assigned at continuing
        # positions (to path==C) and at openings on a C (to True); everywhere
        # else it holds its previous value.  Forward-fill the latest assignment.
        definitive = continuing | (opening & is_c)
        idx = np.arange(T)
        last_def = np.maximum.accumulate(np.where(definitive, idx, -1))
        last_def_before = np.empty(T, dtype=np.int64)
        last_def_before[0] = -1
        last_def_before[1:] = last_def[:-1]
        atc_before = (last_def_before >= 0) & (path[np.maximum(last_def_before, 0)] == C_STATE)
        # CpG counted only in the continuing branch (java:299-305).
        cg_event = continuing & (path == G_STATE) & atc_before
    else:
        cg_event = continuing & is_g & np.concatenate([[False], is_c[:-1]])

    return _runs_to_calls(
        in_mask, opening, is_c, is_g, cg_event,
        drop_open_at_end=compat,
        min_len=None if compat else min_len,
        gc_threshold=gc_threshold,
        oe_threshold=oe_threshold,
        offset=chunk * chunk_size,
    )


def call_islands_obs(
    path: np.ndarray,
    obs: np.ndarray,
    *,
    island_states,
    min_len: int | None = None,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
) -> IslandCalls:
    """Island calling for ARBITRARY state sets (clean semantics only).

    The 8-state caller above reads base identity out of the state ids (state
    1 = C+, state 2 = G+ — a property of the reference's A+-T- labeling,
    CpGIslandFinder.java:182-189).  Models whose states don't encode bases
    (e.g. presets.two_state_cpg, or any user HMM) need membership from the
    decoded PATH but composition from the OBSERVATIONS — which is what this
    does: a position is in an island iff path[t] is in ``island_states``;
    C/G/CpG counts come from obs[t] (symbol ids 0..3 = acgt).

    Emits the same (beg, end, length, gc, oe) records and thresholds; run
    coordinates are 1-based with ``offset`` added (pass the record's global
    start for multi-span files).
    """
    path = np.asarray(path)
    obs = np.asarray(obs)
    if path.shape != obs.shape:
        raise ValueError(f"path {path.shape} and obs {obs.shape} differ")
    if path.shape[0] == 0:
        return _empty_calls()

    in_mask = np.isin(path, np.asarray(list(island_states)))
    prev_in, opening, _ = _adjacency(in_mask)
    is_c = in_mask & (obs == 1)  # codec.C
    is_g = in_mask & (obs == 2)  # codec.G
    cg_event = in_mask & prev_in & (obs == 2) & np.concatenate([[False], obs[:-1] == 1])

    return _runs_to_calls(
        in_mask, opening, is_c, is_g, cg_event,
        drop_open_at_end=False,
        min_len=min_len,
        gc_threshold=gc_threshold,
        oe_threshold=oe_threshold,
        offset=offset,
    )
