"""Prepared symbol-stream artifacts: everything derivable from symbols alone.

BASELINE.md's roofline leaves the EM paths bounded by per-iteration FIXED
cost, not bandwidth — and a large slice of that fixed cost is symbol-only
work re-materialized every iteration: the reduced pair streams and their
two-level cummax forward-fill (viterbi_onehot._pair_stream), the lane
layout pads/reshapes (fb_pallas._lane_layout / the chunked batch setup),
PAD/entry-group encodings, and prev-symbol threading.  None of it depends
on the model parameters, so inside the fused EM ``lax.while_loop`` (and
across decode -> posterior -> EM on the same input) it is pure waste.

This module factors that work into explicit, cacheable artifacts:

- :class:`PreparedChunked` — the chunked/batched lane layout (one record
  per VPU lane; ops.fb_pallas.batch_stats_pallas / batch_posterior_pallas).
- :class:`PreparedSeq` — the whole-sequence lane layout (single-device
  spans; ops.fb_pallas.seq_stats_pallas / seq_posterior_pallas /
  seq_transfer_total_pallas).

Both are registered dataclass pytrees: the arrays are DATA (so a prepared
object is passed as an explicit jit argument — never closed over, which
graftcheck's ``jit-big-closure`` rule bans) and the geometry ints are META
(part of the jit cache key, so a mismatched-geometry prepared object can
never silently retrace into wrong shapes — consumers also validate via
:func:`check_chunked` / :func:`check_seq`).

The builders (:func:`prepare_chunked` / :func:`prepare_seq`) are the SAME
code the engine entries run inline when no prepared object is passed, so
prepared-vs-inline results are bit-identical by construction.  The cached
wrappers (:func:`for_chunked` / :func:`for_seq`) key on the *identity* of
the placed input arrays plus the static geometry — weakref-validated, so a
recycled ``id()`` can never alias a dead entry — and emit a
``prepared_streams`` obs event per lookup (cache key, hit/miss, bytes
resident, prep ms).  Invalidation is automatic: new arrays, a different
lane geometry, or a different engine each produce a different key.

Scope note: prepared objects serve the single-device / per-shard layouts.
Backends that run under ``shard_map`` build their per-device prepared
arrays through a sharded builder (train.backends.SpmdBackend) or fall back
to inline prep (the collective-dependent whole-sequence exchange paths).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu import obs as obs_mod

__all__ = [
    "PreparedChunked",
    "PreparedSeq",
    "PreparedStreams",
    "prepare_chunked",
    "prepare_seq",
    "for_chunked",
    "for_seq",
    "check_chunked",
    "check_seq",
    "chunked_Tt",
    "cache_stats",
    "clear_cache",
    "evict",
]


@dataclasses.dataclass(frozen=True)
class PreparedChunked:
    """Symbol-only prep for the chunked lane layout (one record per lane).

    steps2 [Tp, NL] clamped symbols; lens2 [1, NL]; sel2 [Tp, NL] PAD-marked
    selection symbols, pair2/esym2/pairn2 the reduced pair stream (pairn2 =
    the time-shifted next-step pairs the backward/fused kernels consume) —
    the last four only for the one-hot engines (None on dense preps).
    ``Tt``/``S`` are meta (jit-cache-keyed) so a stale prep can never
    retrace silently.
    """

    steps2: jnp.ndarray
    lens2: jnp.ndarray
    sel2: Optional[jnp.ndarray]
    pair2: Optional[jnp.ndarray]
    esym2: Optional[jnp.ndarray]
    pairn2: Optional[jnp.ndarray]
    S: int
    Tt: int
    onehot: bool
    # The builder's [N, T] batch shape: NL/Tp round up, so shapes alone
    # cannot distinguish a prep built for a smaller batch (its pad lanes
    # would silently drop the extra records) — check_chunked compares these.
    N: int
    T: int


jax.tree_util.register_dataclass(
    PreparedChunked,
    data_fields=["steps2", "lens2", "sel2", "pair2", "esym2", "pairn2"],
    meta_fields=["S", "Tt", "onehot", "N", "T"],
)


@dataclasses.dataclass(frozen=True)
class PreparedSeq:
    """Symbol-only prep for the whole-sequence lane layout (one span,
    single device).  obs_l/sel_l [NL, lane_T]; lane_lens [NL]; o0 [] the
    first (clamped) symbol; prev_dev [] the symbol entering the span's
    reduced chain and pair2/e_in/e_out/pairn2 its pair stream (pairn2 =
    time-shifted next-step pairs for the backward/fused kernels; one-hot
    only).  The one-pass matrix kernel (fb_onehot.run_fb_mat_onehot)
    consumes the SAME pair2/pairn2 fields — no extra prepared stream, so
    prepared-vs-inline stays bit-identical on the one-pass arm too."""

    obs_l: jnp.ndarray
    sel_l: jnp.ndarray
    lane_lens: jnp.ndarray
    o0: jnp.ndarray
    prev_dev: Optional[jnp.ndarray]
    pair2: Optional[jnp.ndarray]
    e_in: Optional[jnp.ndarray]
    e_out: Optional[jnp.ndarray]
    pairn2: Optional[jnp.ndarray]
    S: int
    lane_T: int
    Tt: int
    first: bool
    onehot: bool
    # The builder's padded input length (NL rounds to full 128-lane grids,
    # so different T can share a lane shape) and — when the builder saw a
    # CONCRETE continuation prev_sym — its value, so a prep reused with a
    # different entering symbol raises instead of mis-conditioning the
    # reduced chain's entry group (None = first span / traced prev).
    T: int
    prev_key: Optional[int]


jax.tree_util.register_dataclass(
    PreparedSeq,
    data_fields=[
        "obs_l", "sel_l", "lane_lens", "o0", "prev_dev",
        "pair2", "e_in", "e_out", "pairn2",
    ],
    meta_fields=["S", "lane_T", "Tt", "first", "onehot", "T", "prev_key"],
)


def _pair_next(pair2, S: int):
    """Time-shifted next-step pair stream (the backward/fused kernels'
    input) — the SAME derivation fb_onehot.run_fb_kernels_onehot performs
    inline, hoisted here so the fused EM while-body does not re-shift the
    4 B/symbol stream every iteration."""
    NL = pair2.shape[1]
    return jnp.concatenate(
        [pair2[1:], jnp.full((1, NL), S * S, jnp.int32)], axis=0
    )


def chunked_Tt(T: int, t_tile: int) -> int:
    """The ONE t-tile derivation of the chunked layout (mirrors
    fb_pallas._batch_lane_setup; ROW_TILE-aligned)."""
    from cpgisland_tpu.ops import fb_pallas

    return -(-min(t_tile, T) // fb_pallas.ROW_TILE) * fb_pallas.ROW_TILE


def prepare_chunked(
    S: int, chunks, lengths, *, t_tile: int, onehot: bool = False
) -> PreparedChunked:
    """Build the chunked-layout prep (traceable; the inline twin of what
    batch_stats_pallas/batch_posterior_pallas run when no prep is passed —
    the SAME code path, so prepared-vs-inline is bit-identical)."""
    from cpgisland_tpu.ops import fb_pallas

    chunks = jnp.asarray(chunks)
    lengths = jnp.asarray(lengths).astype(jnp.int32)
    N, T = chunks.shape
    obs_c = jnp.where(
        jnp.arange(T)[None, :] < lengths[:, None],
        jnp.minimum(chunks.astype(jnp.int32), S - 1),
        0,
    )
    NL = -(-N // fb_pallas.LANE_TILE) * fb_pallas.LANE_TILE
    Tt = chunked_Tt(T, t_tile)
    n_t = -(-T // Tt)
    Tp = n_t * Tt
    steps2 = fb_pallas._pad_axis(
        fb_pallas._pad_axis(obs_c.T, Tp, 0, 0), NL, 1, 0
    )  # [Tp, NL]
    lens2 = fb_pallas._pad_axis(lengths[None, :], NL, 1, 0)  # [1, NL]
    sel2 = pair2 = esym2 = pairn2 = None
    if onehot:
        from cpgisland_tpu.ops import fb_onehot
        from cpgisland_tpu.ops.viterbi_onehot import pair_stream

        # PAD-marked steps for the reduced kernels' pair stream; lanes are
        # INDEPENDENT records, so the prev0=0 seed is inert (each lane's
        # position-0 pair is never consumed — the t == 0 init override).
        sel2 = jnp.where(jnp.arange(Tp)[:, None] < lens2, steps2, S)
        pair2, _, _ = pair_stream(S, sel2, jnp.int32(0))
        esym2 = fb_onehot.decode_esym(pair2, S)
        pairn2 = _pair_next(pair2, S)
    return PreparedChunked(
        steps2=steps2, lens2=lens2, sel2=sel2, pair2=pair2, esym2=esym2,
        pairn2=pairn2, S=S, Tt=Tt, onehot=onehot, N=int(N), T=int(T),
    )


def prepare_seq(
    S: int,
    obs,
    length,
    *,
    lane_T: int,
    t_tile: int,
    first: bool = True,
    onehot: bool = False,
    prev_sym=None,
    prev_key: Optional[int] = None,
) -> PreparedSeq:
    """Build the whole-sequence-layout prep for ONE single-device span
    (axis=None — the collective prev-symbol threading of the sharded paths
    stays inline).  ``first``/``prev_sym`` follow _lane_streams' span
    contract: continuation spans of one-hot models need the symbol emitted
    before the span (it conditions the reduced chain's entry group)."""
    from cpgisland_tpu.ops import fb_pallas

    obs = jnp.asarray(obs)
    obs_l, sel_l, lane_lens, obs_flat, Tt, _NL = fb_pallas._lane_layout(
        obs, length, S, lane_T, t_tile, bool(first)
    )
    o0 = obs_flat[0]
    prev_dev = pair2 = e_in = e_out = pairn2 = None
    if onehot:
        from cpgisland_tpu.ops.viterbi_onehot import pair_stream

        if not first and prev_sym is None:
            raise ValueError(
                "onehot continuation spans (first=False) need prev_sym"
            )
        prev_dev = jnp.asarray(o0 if first else prev_sym, jnp.int32)
        pair2, e_in, e_out = pair_stream(S, sel_l.T, prev_dev)
        pairn2 = _pair_next(pair2, S)
    if prev_key is None and not first and isinstance(prev_sym, (int, np.integer)):
        prev_key = int(prev_sym)
    return PreparedSeq(
        obs_l=obs_l, sel_l=sel_l, lane_lens=lane_lens, o0=o0,
        prev_dev=prev_dev, pair2=pair2, e_in=e_in, e_out=e_out,
        pairn2=pairn2, S=S, lane_T=lane_T, Tt=Tt, first=bool(first),
        onehot=onehot, T=int(obs.shape[0]), prev_key=prev_key,
    )


# Jitted builder entries for the CACHE-MISS path: one dispatch per miss
# (eagerly, each pad/where/cummax would be its own device program — ~8-10
# relay round trips of launch latency per prep).  Inline in-graph prep
# keeps calling the raw functions; under an outer trace the jit inlines,
# so prepared-vs-inline stays the same HLO.
_prepare_chunked_jit = functools.partial(
    jax.jit, static_argnums=(0,), static_argnames=("t_tile", "onehot")
)(prepare_chunked)
_prepare_seq_jit = functools.partial(
    jax.jit, static_argnums=(0,),
    static_argnames=("lane_T", "t_tile", "first", "onehot", "prev_key"),
)(prepare_seq)


def check_chunked(
    prep: PreparedChunked, S: int, N: int, T: int, t_tile: int, onehot: bool
) -> None:
    """Static consistency gate between a prepared object and its consumer's
    geometry — a mismatch raises instead of silently computing on the wrong
    layout (all checks are on meta fields / shapes, free under trace).
    N/T are exact-matched: the lane/step paddings round up, so a prep for a
    smaller batch would otherwise pass on shape and silently drop records.
    """
    if not isinstance(prep, PreparedChunked):
        raise TypeError(
            f"expected PreparedChunked, got {type(prep).__name__}"
        )
    want_Tt = chunked_Tt(T, t_tile)
    if (
        prep.S != S or prep.Tt != want_Tt
        or prep.N != int(N) or prep.T != int(T)
    ):
        raise ValueError(
            f"prepared chunked streams were built for S={prep.S}, "
            f"N={prep.N}, T={prep.T}, Tt={prep.Tt}; this call needs S={S}, "
            f"N={int(N)}, T={int(T)}, Tt={want_Tt} — rebuild the prep for "
            "this input/geometry"
        )
    if onehot and prep.pair2 is None:
        raise ValueError(
            "this call needs a one-hot prep (pair2/esym2); the prepared "
            "object was built with onehot=False"
        )


def check_seq(
    prep: PreparedSeq, S: int, T: int, lane_T: int, t_tile: int, first: bool,
    onehot: bool, prev_sym=None,
) -> None:
    """check_chunked's whole-sequence twin.  ``prev_sym``: when BOTH the
    prep and this call carry a concrete continuation prev symbol, they must
    agree (the reduced chain's entry group is conditioned on it)."""
    if not isinstance(prep, PreparedSeq):
        raise TypeError(f"expected PreparedSeq, got {type(prep).__name__}")
    want_Tt = -(-min(t_tile, lane_T) // 8) * 8
    if (
        prep.S != S or prep.lane_T != lane_T or prep.Tt != want_Tt
        or prep.first != bool(first) or prep.T != int(T)
    ):
        raise ValueError(
            f"prepared seq streams were built for S={prep.S}, T={prep.T}, "
            f"lane_T={prep.lane_T}, Tt={prep.Tt}, first={prep.first}; this "
            f"call needs S={S}, T={int(T)}, lane_T={lane_T}, Tt={want_Tt}, "
            f"first={bool(first)} — rebuild the prep for this geometry"
        )
    if onehot and prep.pair2 is None:
        raise ValueError(
            "this call needs a one-hot prep (pair stream); the prepared "
            "object was built with onehot=False"
        )
    if (
        prep.prev_key is not None
        and isinstance(prev_sym, (int, np.integer))
        and int(prev_sym) != prep.prev_key
    ):
        raise ValueError(
            f"prepared seq streams were conditioned on prev_sym="
            f"{prep.prev_key}; this call passes prev_sym={int(prev_sym)} — "
            "rebuild the prep for this span"
        )


# --- identity-keyed cache ---------------------------------------------------
#
# Keyed on the *placed array identities* plus the static geometry: training
# inputs are placed once and reused across iterations/fits, so identity is
# the natural (and cheap) cache key; weakrefs validate each hit so a
# recycled id() can never alias a dead entry, and content never needs
# hashing.  Bounded FIFO — each entry pins its prep arrays (comparable in
# size to the input) on device, so the bound is deliberately small.
#
# Thread contract: the cache is process-global and the serve daemon reaches
# it from several threads (the worker loop's flushes, transport threads
# calling broker.stats(), Session.close() dropping a tenant), so every
# _cache/_stats access holds _CACHE_LOCK.  Prep BUILDS run OUTSIDE the lock
# (a jitted build dispatches device work — holding the lock would serialize
# every concurrent session behind one tenant's compile); a build raced by
# another thread keeps the first-published entry, so handed-out preps never
# silently diverge in identity.

_CACHE_MAX = 8
_CACHE_LOCK = threading.RLock()
# key -> (weakrefs of keyed arrays, prep tree, resident bytes of the prep)
_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
_stats = {
    "hits": 0,
    "misses": 0,
    # Eviction reasons (long-lived serving processes read these through
    # cache_stats() in the obs report): dead = a keyed input array was
    # garbage-collected (or its id recycled), capacity = FIFO bound,
    # explicit = evict()/PreparedStreams.clear_session().
    "evictions_dead": 0,
    "evictions_capacity": 0,
    "evictions_explicit": 0,
}


def cache_stats() -> dict:
    """Hit/miss/eviction counters since process start (or clear_cache),
    plus current occupancy: ``entries`` and ``resident_bytes`` (the summed
    size of all cached prep trees — comparable to the inputs they were
    built from, so a serving daemon watches this through the obs report)."""
    with _CACHE_LOCK:
        out = dict(_stats)
        out["entries"] = len(_cache)
        out["resident_bytes"] = sum(ent[2] for ent in _cache.values())
    return out


def clear_cache() -> None:
    with _CACHE_LOCK:
        _cache.clear()
        for k in _stats:
            _stats[k] = 0


def evict(*arrays) -> int:
    """Explicitly drop every cache entry keyed on any of ``arrays``.

    The automatic lifecycle (dead-ref sweep on miss + FIFO capacity bound)
    eventually releases prep trees, but a long-lived daemon dropping a
    tenant's placed inputs wants the input-sized device allocations gone
    NOW, not at the next unrelated miss.  Returns the number of entries
    evicted; emits one ``prepared_evict`` obs event when anything dropped.
    """
    ids = {id(a) for a in arrays}
    with _CACHE_LOCK:
        # Entries whose keyed inputs already died can't be matched by id (a
        # dropped tenant's arrays are usually GC'd BEFORE Session.close()
        # calls here) — sweep them now rather than at the next unrelated
        # miss, or a quiet daemon would hold their prep trees indefinitely.
        _sweep_dead_locked()
        dead = [k for k in _cache if ids.intersection(k[2])]
        nbytes = 0
        for k in dead:
            nbytes += _cache[k][2]
            del _cache[k]
        if dead:
            _stats["evictions_explicit"] += len(dead)
    if dead:
        obs_mod.event(
            "prepared_evict", entries=len(dead), bytes_released=nbytes
        )
    return len(dead)


def _sweep_dead_locked() -> None:
    """Drop entries whose keyed input arrays died: their prep trees (often
    input-sized, device-resident) must not wait for capacity eviction.
    Caller holds _CACHE_LOCK (the ``_locked`` suffix convention)."""
    dead = [k for k, ent in _cache.items() if any(r() is None for r in ent[0])]
    for k in dead:
        del _cache[k]
    _stats["evictions_dead"] += len(dead)


def _entry_live(ent, arrays) -> bool:
    return ent is not None and all(r() is a for r, a in zip(ent[0], arrays))


def _cached(kind: str, arrays: tuple, skey: tuple, build):
    key = (kind, skey, tuple(id(a) for a in arrays))
    with _CACHE_LOCK:
        ent = _cache.get(key)
        if _entry_live(ent, arrays):
            _cache.move_to_end(key)
            _stats["hits"] += 1
            hit = ent[1]
        else:
            if ent is not None:  # id recycled onto a new array — stale entry
                del _cache[key]
                _stats["evictions_dead"] += 1
            _sweep_dead_locked()
            hit = None
    if hit is not None:
        obs_mod.event("prepared_streams", kind=kind, hit=True)
        return hit
    # Build OUTSIDE the lock (see the thread-contract note above).
    t0 = time.perf_counter()
    prep = build()
    prep_ms = (time.perf_counter() - t0) * 1e3
    nbytes = sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(prep)
    )
    with _CACHE_LOCK:
        _stats["misses"] += 1
        cur = _cache.get(key)
        if _entry_live(cur, arrays):
            # Another session built this entry while we did: keep the
            # FIRST-published prep (it may already be in use downstream) and
            # drop ours — no lost entries, no double insert.
            _cache.move_to_end(key)
            prep = cur[1]
        else:
            _cache[key] = (
                tuple(weakref.ref(a) for a in arrays), prep, nbytes
            )
            while len(_cache) > _CACHE_MAX:
                _cache.popitem(last=False)
                _stats["evictions_capacity"] += 1
    obs_mod.event(
        "prepared_streams", kind=kind, hit=False,
        bytes_resident=nbytes, prep_ms=round(prep_ms, 2), key=repr(skey),
    )
    return prep


def cached_build(kind: str, arrays: tuple, skey: tuple, build):
    """Public cache entry for custom builders (e.g. the shard_map prep
    builders in train.backends): identity-keyed on ``arrays`` + ``skey``,
    same hit/miss accounting and ``prepared_streams`` events as the
    standard layouts."""
    return _cached(kind, arrays, skey, build)


def chunked_spec_tree(
    S: int, N_local: int, T: int, t_tile: int, onehot: bool, lane_axis: str
):
    """A PreparedChunked of PartitionSpecs — the shard_map out_specs tree
    for building per-device chunked preps in place (lane axis = the mesh
    axis the record batch is sharded over; ``N_local`` = rows per device).
    Meta fields mirror what the per-device :func:`prepare_chunked`
    produces, so the spec tree and the output tree have identical
    treedefs."""
    from jax.sharding import PartitionSpec as P

    sp = P(None, lane_axis)
    return PreparedChunked(
        steps2=sp, lens2=sp,
        sel2=sp if onehot else None,
        pair2=sp if onehot else None,
        esym2=sp if onehot else None,
        pairn2=sp if onehot else None,
        S=S, Tt=chunked_Tt(T, t_tile), onehot=onehot,
        N=int(N_local), T=int(T),
    )


def sharded_chunked_builder(
    mesh, lane_axis: str, in_specs, S: int, N_local: int, T: int,
    t_tile: int, onehot: bool, lengths_2d: bool = False,
):
    """jit(shard_map(prepare_chunked)): build per-device chunked preps IN
    PLACE over an already-placed batch (one dispatch, no host round trip of
    the symbols).  The ONE copy shared by SpmdBackend, Seq2DBackend's rows
    path, and any future sharded chunked consumer — their builder spec
    trees cannot drift.  ``lengths_2d``: the lengths operand is the 2-D
    [N, sp] per-shard layout (Seq2D) rather than [N]."""

    def build(c, l):
        return prepare_chunked(
            S, c, l[:, 0] if lengths_2d else l, t_tile=t_tile, onehot=onehot
        )

    return jax.jit(
        jax.shard_map(
            build,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=chunked_spec_tree(
                S, N_local, T, t_tile, onehot, lane_axis
            ),
            check_vma=False,
        )
    )


def kw_prepared_shim(fn):
    """Keyword-normalizing wrapper for shard_map-compiled stats fns: the
    fused EM driver passes ``prepared=`` by name, which shard_map-wrapped
    callables don't accept.  One shared shim so every prep-aware compiled
    fn exposes the same signature."""

    def call(params, a, b, prepared, _fn=fn):
        return _fn(params, a, b, prepared)

    return call


def for_chunked(
    S: int, chunks, lengths, *, t_tile: int, onehot: bool = False
) -> PreparedChunked:
    """Cached :func:`prepare_chunked` keyed on (chunks, lengths) identity +
    geometry.  Call with PLACED device arrays (backends.place output) so
    repeated fits/iterations on the same input hit."""
    skey = (S, int(t_tile), bool(onehot), tuple(chunks.shape),
            str(chunks.dtype))
    return _cached(
        "chunked", (chunks, lengths), skey,
        # One jitted dispatch per miss (the eager builder would dispatch
        # each pad/where/cummax as its own program over the relay).
        lambda: _prepare_chunked_jit(
            S, chunks, lengths, t_tile=t_tile, onehot=onehot
        ),
    )


def for_seq(
    S: int,
    obs,
    length: int,
    *,
    lane_T: int,
    t_tile: int,
    first: bool = True,
    onehot: bool = False,
    prev_sym=None,
) -> PreparedSeq:
    """Cached :func:`prepare_seq` (single-device spans).  ``length`` and
    ``prev_sym`` must be concrete here — they are part of the cache key."""
    skey = (
        S, int(length), int(lane_T), int(t_tile), bool(first), bool(onehot),
        None if prev_sym is None else int(prev_sym), tuple(obs.shape),
        str(obs.dtype),
    )
    return _cached(
        "seq", (obs,), skey,
        lambda: _prepare_seq_jit(
            S, obs, jnp.int32(length), lane_T=lane_T, t_tile=t_tile,
            first=bool(first), onehot=onehot,
            prev_sym=None if prev_sym is None else jnp.int32(prev_sym),
            prev_key=(
                None if (first or prev_sym is None) else int(prev_sym)
            ),
        ),
    )


class PreparedStreams:
    """Host-side handle: ONE input's prepared artifacts across layouts.

    pipeline-level flows (decode -> posterior -> EM over the same placed
    arrays) hold one of these instead of three independent preps; each
    layout builds lazily through the identity-keyed cache, so mixed
    consumers (a chunked posterior and a chunked E-step, or two span
    sweeps over one placed span) share the same device-resident artifact.

    The handle also remembers (by weakref) every input array it keyed a
    lookup on, so a long-lived owner — a serve Session dropping a tenant —
    can release all of its prep trees at once via :meth:`clear_session`
    instead of waiting for the dead-ref sweep or capacity eviction.
    """

    def __init__(self, n_symbols: int):
        self.S = int(n_symbols)
        self._seen: dict[int, weakref.ref] = {}

    def _note(self, arrays) -> None:
        for a in arrays:
            try:
                self._seen[id(a)] = weakref.ref(a)
            except TypeError:
                pass  # unweakrefable input (host scalar etc.) — nothing cached
        # Prune dead refs so a long-lived handle (a serve Session fielding
        # requests for weeks) stays O(live inputs), not O(inputs ever seen)
        # — dead entries' cache rows were already swept; only the bookkeeping
        # would leak.
        if len(self._seen) > 16:
            self._seen = {
                k: r for k, r in self._seen.items() if r() is not None
            }

    def clear_session(self) -> int:
        """Explicitly evict every cache entry built through this handle
        (live inputs only — dead ones already swept).  Returns the number
        of entries released."""
        live = [r() for r in self._seen.values()]
        n = evict(*[a for a in live if a is not None])
        self._seen.clear()
        return n

    def chunked(
        self, chunks, lengths, *, t_tile: int, onehot: bool = False
    ) -> PreparedChunked:
        self._note((chunks, lengths))
        return for_chunked(
            self.S, chunks, lengths, t_tile=t_tile, onehot=onehot
        )

    def seq(
        self, obs, length: int, *, lane_T: int, t_tile: int,
        first: bool = True, onehot: bool = False, prev_sym=None,
    ) -> PreparedSeq:
        self._note((obs,))
        return for_seq(
            self.S, obs, length, lane_T=lane_T, t_tile=t_tile, first=first,
            onehot=onehot, prev_sym=prev_sym,
        )
