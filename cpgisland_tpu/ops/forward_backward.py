"""Forward-backward and per-sequence Baum-Welch sufficient statistics.

This is the E-step "mapper" of the reference's distributed trainer: Mahout's
Hadoop Baum-Welch mappers run scaled forward-backward over one 65,536-symbol
chunk and emit expected initial/transition/emission counts
(BaumWelchDriver.runBaumWelchMR call site, CpGIslandFinder.java:200-201; the
"rescaling" numerics flag at :92).  Here a chunk's statistics are computed by
two `lax.scan` passes fused with the accumulation, in either numerics mode:

- ``mode="rescaled"``— Rabiner per-timestep rescaling in probability space,
  matching the reference's configured numerics — **the default**.
- ``mode="log"``     — log-semiring scans (logsumexp recurrences); kept for
  parity testing and as the template for the max-plus decode scans.

Why rescaled is the default: in float32, log-space gammas come from
``exp(alpha + beta - loglik)`` where all three terms are O(-1.3·T) — for a
65,536-symbol chunk that is a ~-85,000 + -85,000 cancellation whose f32
rounding error (observed: several nats on 46 Kbp) is big enough to break EM's
monotone-loglik guarantee near convergence.  The rescaled recurrences only
ever combine O(1) normalized quantities, so f32 stats track a float64 oracle
to ~0.1 nat over full-size chunks (tested:
tests/test_baum_welch.py::test_long_chunk_loglik_monotone_rescaled).  TPUs
have no fast f64 to hide behind — the numerics choice is the fix.

Memory: the forward pass stores alphas ([T, K] — 2 MB for a 64Ki x 8 chunk);
the backward pass consumes them streamingly and accumulates the [K], [K, K],
[K, M] count tensors, so nothing O(T·K²) is ever materialized.

Padded chunks (symbol == PAD sentinel, value >= n_symbols) contribute nothing:
pad steps are identity transitions in both passes and are excluded from counts,
so zero-length chunks produce exactly-zero statistics (needed for even sharding
across a mesh, see utils.chunking.pad_to_multiple).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from cpgisland_tpu.models.hmm import LOG_ZERO, HmmParams


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SuffStats:
    """Expected-count sufficient statistics (the mapper output contract).

    init:  [K]    expected count of starting in state i        (gamma_0)
    trans: [K, K] expected i->j transition counts              (sum_t xi_t)
    emit:  [K, M] expected state-i-emits-s counts              (sum_t gamma_t [o_t = s])
    loglik: []    total log-likelihood of the chunk(s)
    n_seqs: []    number of (non-empty) sequences accumulated
    """

    init: jnp.ndarray
    trans: jnp.ndarray
    emit: jnp.ndarray
    loglik: jnp.ndarray
    n_seqs: jnp.ndarray

    @staticmethod
    def zeros(n_states: int, n_symbols: int, dtype=jnp.float32) -> "SuffStats":
        return SuffStats(
            init=jnp.zeros(n_states, dtype),
            trans=jnp.zeros((n_states, n_states), dtype),
            emit=jnp.zeros((n_states, n_symbols), dtype),
            loglik=jnp.zeros((), dtype),
            n_seqs=jnp.zeros((), jnp.int32),
        )

    def __add__(self, other: "SuffStats") -> "SuffStats":
        return jax.tree_util.tree_map(lambda a, b: a + b, self, other)


def _logsumexp(x, axis):
    m = jnp.max(x, axis=axis, keepdims=True)
    m = jnp.maximum(m, LOG_ZERO)  # all-LOG_ZERO slices stay finite
    return jnp.squeeze(m, axis) + jnp.log(jnp.sum(jnp.exp(x - m), axis=axis))


@partial(jax.jit, static_argnames=("mode",))
def chunk_stats(
    params: HmmParams,
    obs: jnp.ndarray,
    length: jnp.ndarray,
    mode: str = "log",
) -> SuffStats:
    """Sufficient statistics for one padded chunk (the E-step mapper)."""
    if mode == "log":
        return _chunk_stats_log(params, obs, length)
    if mode == "rescaled":
        return _chunk_stats_rescaled(params, obs, length)
    raise ValueError(f"unknown numerics mode: {mode!r}")


def _masks(params, obs, length):
    obs = obs.astype(jnp.int32)
    T = obs.shape[0]
    valid = jnp.arange(T) < length  # [T] real (non-pad) positions
    obs_c = jnp.where(valid, jnp.minimum(obs, params.n_symbols - 1), 0)
    return obs_c, valid


def _chunk_stats_log(params, obs, length):
    K, M = params.n_states, params.n_symbols
    obs_c, valid = _masks(params, obs, length)
    T = obs_c.shape[0]
    emit_t = params.log_B.T  # [M, K]

    # --- forward: alpha[t] = log P(o_0..o_t, s_t) ; pad steps are identity.
    alpha0 = jnp.where(valid[0], params.log_pi + emit_t[obs_c[0]], LOG_ZERO)

    def fstep(alpha, inp):
        o_t, v_t = inp
        new = _logsumexp(alpha[:, None] + params.log_A, axis=0) + emit_t[o_t]
        new = jnp.where(v_t, new, alpha)
        return new, new

    alphaT, alphas_tail = jax.lax.scan(fstep, alpha0, (obs_c[1:], valid[1:]))
    alphas = jnp.concatenate([alpha0[None], alphas_tail])  # [T, K]
    loglik = _logsumexp(alphaT, axis=0)

    # --- backward + fused accumulation.  Carry zeros are derived from alpha0
    # so their device-varying type matches the scan outputs under shard_map.
    zK = alpha0 * 0.0
    beta_T = zK

    def bstep(carry, inp):
        beta_next, trans_acc, emit_acc = carry  # beta at t+1
        alpha_t, o_next, v_next, o_t, v_t = inp
        # xi_t[i,j] proportional to alpha_t[i] + A[i,j] + B[j,o_{t+1}] + beta_{t+1}[j]
        contrib = alpha_t[:, None] + params.log_A + (emit_t[o_next] + beta_next)[None, :] - loglik
        xi = jnp.where(v_next, jnp.exp(contrib), 0.0)
        # graftcheck: allow(no-stats-in-bwd-chain) -- XLA scan assembly: XLA schedules the count sums off the recurrence critical path; the ban targets the Pallas kernels' serial chain (CLAUDE.md)
        trans_acc = trans_acc + xi
        # gamma_t from alpha_t + beta_t; beta_t via recurrence.
        beta_t = _logsumexp(params.log_A + (emit_t[o_next] + beta_next)[None, :], axis=1)
        beta_t = jnp.where(v_next, beta_t, beta_next)
        gamma_t = jnp.exp(alpha_t + beta_t - loglik)
        gamma_t = jnp.where(v_t, gamma_t, 0.0)
        # graftcheck: allow(no-stats-in-bwd-chain) -- XLA scan assembly (see the trans_acc waiver above)
        emit_acc = emit_acc + gamma_t[:, None] * jax.nn.one_hot(o_t, M) * v_t
        return (beta_t, trans_acc, emit_acc), gamma_t

    inps = (
        alphas[:-1],
        obs_c[1:],
        valid[1:],
        obs_c[:-1],
        valid[:-1],
    )
    (beta_0, trans, emit), _ = jax.lax.scan(
        bstep,
        (beta_T, jnp.zeros((K, K)) + zK[:, None], jnp.zeros((K, M)) + zK[:, None]),
        inps,
        reverse=True,
    )
    # The reverse scan covered t = 0..T-2, which includes the last real
    # position whenever length < T (pad identity steps give it beta = 0 there).
    # Only an unpadded chunk (length == T) leaves position T-1 unaccounted.
    gamma_last = jnp.exp(alphaT - loglik)
    emit = emit + (length == T) * gamma_last[:, None] * jax.nn.one_hot(obs_c[T - 1], M)

    gamma0 = jnp.exp(alpha0 + beta_0 - loglik)
    nonempty = length > 0
    zero = SuffStats.zeros(K, M)
    got = SuffStats(
        init=gamma0,
        trans=trans,
        emit=emit,
        loglik=loglik,
        n_seqs=jnp.ones((), jnp.int32),
    )
    return jax.tree_util.tree_map(lambda z, g: jnp.where(nonempty, g, z), zero, got)


def _rescaled_forward(params, obs_c, valid):
    """Shared Rabiner-rescaled forward pass: (alphas [T,K], cs [T]).

    Pad steps (valid False) are identity (alpha pass-through, c = 1).  The
    single copy of the alpha recurrence — the E-step and the posterior
    entry points both scan through here.
    """
    K = params.n_states
    A = jnp.exp(params.log_A)
    B_t = jnp.exp(params.log_B).T  # [M, K]
    pi = jnp.exp(params.log_pi)

    a0_raw = jnp.where(valid[0], pi * B_t[obs_c[0]], jnp.ones(K) / K)
    c0 = jnp.sum(a0_raw)
    alpha0 = a0_raw / c0

    def fstep(alpha, inp):
        o_t, v_t = inp
        # HIGHEST: TPU's default matmul precision would round the f32
        # probabilities to bf16 on the MXU (~4e-3 relative), breaking
        # CPU/TPU agreement on E-step stats.
        raw = jnp.matmul(alpha, A, precision=jax.lax.Precision.HIGHEST) * B_t[o_t]
        c = jnp.sum(raw)
        new = raw / c
        new = jnp.where(v_t, new, alpha)
        c = jnp.where(v_t, c, 1.0)
        return new, (new, c)

    _, (alphas_tail, cs_tail) = jax.lax.scan(fstep, alpha0, (obs_c[1:], valid[1:]))
    alphas = jnp.concatenate([alpha0[None], alphas_tail])
    cs = jnp.concatenate([c0[None], cs_tail])  # [T]
    return alphas, cs


def _chunk_stats_rescaled(params, obs, length):
    """Rabiner per-step rescaling in probability space (reference numerics,
    CpGIslandFinder.java:92 'rescaling')."""
    K, M = params.n_states, params.n_symbols
    obs_c, valid = _masks(params, obs, length)
    T = obs_c.shape[0]
    A = jnp.exp(params.log_A)
    B_t = jnp.exp(params.log_B).T  # [M, K]

    alphas, cs = _rescaled_forward(params, obs_c, valid)
    alphaT = alphas[-1]
    loglik = jnp.sum(jnp.where(valid, jnp.log(cs), 0.0))

    zK = alphas[0] * 0.0
    beta_T = zK + 1.0

    def bstep(carry, inp):
        beta_next, trans_acc, emit_acc = carry
        alpha_t, o_next, v_next, c_next, o_t, v_t = inp
        w = B_t[o_next] * beta_next / c_next  # [K]
        xi = alpha_t[:, None] * A * w[None, :]
        # graftcheck: allow(no-stats-in-bwd-chain) -- XLA scan assembly: XLA schedules the count sums off the recurrence critical path; the ban targets the Pallas kernels' serial chain (CLAUDE.md)
        trans_acc = trans_acc + jnp.where(v_next, xi, 0.0)
        beta_t = jnp.matmul(A, w, precision=jax.lax.Precision.HIGHEST)
        beta_t = jnp.where(v_next, beta_t, beta_next)
        gamma_t = alpha_t * beta_t
        gamma_t = gamma_t / jnp.maximum(jnp.sum(gamma_t), 1e-30)
        gamma_t = jnp.where(v_t, gamma_t, 0.0)
        # graftcheck: allow(no-stats-in-bwd-chain) -- XLA scan assembly (see the trans_acc waiver above)
        emit_acc = emit_acc + gamma_t[:, None] * jax.nn.one_hot(o_t, M) * v_t
        return (beta_t, trans_acc, emit_acc), None

    inps = (alphas[:-1], obs_c[1:], valid[1:], cs[1:], obs_c[:-1], valid[:-1])
    (beta_0, trans, emit), _ = jax.lax.scan(
        bstep,
        (beta_T, jnp.zeros((K, K)) + zK[:, None], jnp.zeros((K, M)) + zK[:, None]),
        inps,
        reverse=True,
    )

    # Same boundary accounting as the log path: the reverse scan already
    # covered the last real position unless the chunk is unpadded.
    gamma_last = alphaT / jnp.maximum(jnp.sum(alphaT), 1e-30)
    emit = emit + (length == T) * gamma_last[:, None] * jax.nn.one_hot(obs_c[T - 1], M)

    gamma0 = alphas[0] * beta_0
    gamma0 = gamma0 / jnp.maximum(jnp.sum(gamma0), 1e-30)

    nonempty = length > 0
    zero = SuffStats.zeros(K, M)
    got = SuffStats(
        init=gamma0, trans=trans, emit=emit, loglik=loglik, n_seqs=jnp.ones((), jnp.int32)
    )
    return jax.tree_util.tree_map(lambda z, g: jnp.where(nonempty, g, z), zero, got)


@jax.jit
def posterior_marginals(params: HmmParams, obs: jnp.ndarray, length=None):
    """Per-position state posteriors gamma[t, k] = P(s_t = k | o_0..o_{T-1}).

    The reference's Mahout dependency exposes only Viterbi
    (HmmEvaluator.decode, CpGIslandFinder.java:260); posteriors are the
    soft-decoding completion of that surface — argmax(gamma) is
    max-posterior-marginal decoding, and gamma itself gives per-position
    island confidence.  Rescaled numerics, the SAME forward recurrence as
    the E-step (_rescaled_forward).  ``length`` masks a padded tail exactly
    like chunk_stats (gamma rows there are 0); omitted = all T positions
    real.  Returns (gamma [T, K], loglik).
    """
    K = params.n_states
    T = obs.shape[0]
    if length is None:
        length = T
    obs_c, valid = _masks(params, obs, length)
    A = jnp.exp(params.log_A)
    B_t = jnp.exp(params.log_B).T  # [M, K]

    alphas, cs = _rescaled_forward(params, obs_c, valid)
    loglik = jnp.sum(jnp.where(valid, jnp.log(cs), 0.0))

    def bstep(beta_next, inp):
        o_next, v_next, c_next = inp
        beta = jnp.matmul(A, B_t[o_next] * beta_next, precision=jax.lax.Precision.HIGHEST)
        beta = beta / c_next
        return jnp.where(v_next, beta, beta_next), None

    # Emit beta BEFORE each reverse step so betas[t] pairs with alphas[t];
    # pad steps pass through, leaving beta = 1 at the last valid position.
    def bstep_emit(beta_next, inp):
        new, _ = bstep(beta_next, inp)
        return new, new

    _, betas_front = jax.lax.scan(
        bstep_emit, jnp.ones(K), (obs_c[1:], valid[1:], cs[1:]), reverse=True
    )
    betas = jnp.concatenate([betas_front, jnp.ones((1, K))])
    graw = alphas * betas
    gamma = graw / jnp.maximum(jnp.sum(graw, axis=-1, keepdims=True), 1e-30)
    return jnp.where(valid[:, None], gamma, 0.0), loglik


@jax.jit
def sequence_loglik(params: HmmParams, obs: jnp.ndarray, length=None):
    """Total log-likelihood log P(obs | params) of one sequence — the
    forward pass alone (rescaled Rabiner numerics, HIGHEST-precision
    matmuls, exactly the E-step's recurrence).

    This is the scoring entry of the multi-model comparison workload
    (family.compare): per-model log-odds are differences of these values.
    Unlike chunk_stats' tail-only convention, PAD is positional here —
    any symbol >= n_symbols (or at/past ``length``) is an identity step
    contributing no transition and no emission, including a PAD FIRST
    position (the state prior carries through unscored).  This matches
    the engines' PAD semantics, so the score pairs consistently with
    their paths/posteriors.  (Note the order-2 pair streams do NOT open
    with PAD: codec.recode_pairs maps an unknown left context to the
    in-alphabet SELF-CONTEXT pair, which is scored normally — the
    dinuc_cpg exact-lift constant depends on that first pair being
    scored.)
    """
    T = obs.shape[0]
    if length is None:
        length = T
    obs32 = obs.astype(jnp.int32)
    valid = (jnp.arange(T) < length) & (obs32 < params.n_symbols)
    obs_c = jnp.where(valid, obs32, 0)
    A = jnp.exp(params.log_A)
    B_t = jnp.exp(params.log_B).T  # [M, K]
    pi = jnp.exp(params.log_pi)

    a0_raw = jnp.where(valid[0], pi * B_t[obs_c[0]], pi)
    c0 = jnp.sum(a0_raw)
    # Same zero-normalizer guard as fstep below: an impossible first
    # observation scores -inf via log(c0), never nan via 0/0.
    alpha0 = jnp.where(c0 > 0, a0_raw / jnp.where(c0 > 0, c0, 1.0), pi)

    def fstep(alpha, inp):
        o_t, v_t = inp
        raw = jnp.matmul(alpha, A, precision=jax.lax.Precision.HIGHEST) * B_t[o_t]
        c = jnp.sum(raw)
        # A structurally impossible observation (c == 0: zero emission
        # probability over every reachable state) must score -inf, not
        # nan: guard the renormalizing division (alpha carries through
        # arbitrarily — the total is already -inf) and let log(0) = -inf
        # flow into the sum.
        new = jnp.where(v_t & (c > 0), raw / jnp.where(c > 0, c, 1.0), alpha)
        return new, jnp.where(v_t, c, 1.0)

    _, cs_tail = jax.lax.scan(fstep, alpha0, (obs_c[1:], valid[1:]))
    ll0 = jnp.where(valid[0], jnp.log(c0), 0.0)
    return ll0 + jnp.sum(jnp.where(valid[1:], jnp.log(cs_tail), 0.0))


def posterior_decode(params: HmmParams, obs: jnp.ndarray, length=None) -> jnp.ndarray:
    """Max-posterior-marginal state path: argmax_k gamma[t, k] per position."""
    gamma, _ = posterior_marginals(params, obs, length)
    return jnp.argmax(gamma, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("mode",))
def batch_stats(params: HmmParams, chunks: jnp.ndarray, lengths: jnp.ndarray, mode: str = "log") -> SuffStats:
    """Map chunk_stats over a [N, T] batch and reduce by summation.

    This is exactly the reference's mapper (per-chunk forward-backward) and
    combiner (count summation) composed, on one device.
    """
    per = jax.vmap(lambda o, l: chunk_stats(params, o, l, mode=mode))(chunks, lengths)
    return jax.tree_util.tree_map(lambda x: jnp.sum(x, axis=0), per)
