"""Device-side CpG island calling (clean semantics) — XLA cumulative ops.

The host caller (ops.islands) is vectorized NumPy, but feeding it means
shipping the whole decoded path (4 B/symbol) device->host and then scanning it
on the host: at GRCh38 scale that is ~12 GB of PCIe traffic plus an O(T) host
pass — together far more wall-clock than the sharded decode itself.  This
module keeps the reduction ON DEVICE: the path goes in, only the compact
(beg, end, length, gc, oe) records come out (a few hundred KiB), so the
decode -> islands pipeline is one fused XLA program with no large transfer.

Mechanics — all TPU-native cumulative/elementwise ops, chosen for O(1)
compile scaling (an associative_scan ffill and a size-bounded flatnonzero
both made XLA:TPU compile time grow superlinearly in T; cummax and one
scatter do not), BLOCKED over time so device temp memory is O(block), not
O(T) (a whole-record formulation at 320 Mi symbols allocated ~15 GB of
s32[T] cumsum temporaries and OOMed a v5e chip — found by the r4 span-scale
bench):

- the path is reshaped to [n_blocks, BLOCK_W] (padded with one background
  sentinel past the end, which also closes a run at the true end — clean
  semantics) and reduced by ONE `lax.scan` whose carry threads the run
  state across blocks: previous position's membership/C flags, cumulative
  C/G/CpG totals, the open run's anchor (opening index + pre-opening
  cumsums), the emitted-call count, and the [cap] output columns;
- within a block: island membership, run boundaries, and C/G/CpG event
  masks exactly as the clean-mode host caller computes them; per-run
  aggregates via block cumsums + carried bases, with the open-run anchor
  forward-filled by `lax.cummax(where(opening, value, -1))` falling back
  to the carried anchor (every filled quantity is NONDECREASING in t, so
  the running max over opening positions IS the last opening's value);
- a run is emitted at its LEAVING position (first background position
  after it) and compacted into the carried [cap] columns with one
  cumsum-indexed scatter (`.at[target].set(..., mode="drop")` with an
  overflow dump slot) at the carried cursor.

Only CLEAN semantics (compat quirk reproduction stays on the host path — it
exists for byte-fidelity, not throughput).  Parity with
ops.islands.call_islands(compat=False) is tested on random and adversarial
paths (tests/test_islands_device.py).

Reference scope: the island state machine, CpGIslandFinder.java:262-339.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.ops.islands import (
    C_STATE,
    G_STATE,
    IslandCalls,
    N_ISLAND_STATES,
    _empty_calls,
    counts_to_gc_oe,
)

# Default maximum number of emitted calls per invocation.  Real genomes carry
# ~25-45k CpG islands TOTAL; 128 Ki per call site is a deep safety margin and
# costs only ~5 MB of device output buffers.
DEFAULT_CAP = 1 << 17

class IslandCapOverflow(ValueError):
    """More island calls survived the filters than ``cap`` output slots.

    Carries the true count so a caller can retry with a sufficient cap —
    the decoded path is typically still device-resident, so the retry
    re-runs only the (cheap) calling reduction, not the decode.
    """

    def __init__(self, n: int, cap: int):
        super().__init__(
            f"{n} island calls exceed cap={cap}; pass a larger cap "
            "(each slot costs ~40 B of device output)"
        )
        self.n = n
        self.cap = cap


# Relative width of the conservative device-side band around each float
# threshold (see _calls_from_masks): f32 gc/oe rounding is bounded by ~6e-7
# relative, so 1e-5 is a 16x safety margin — wide enough that no true call
# can be lost on device, narrow enough that essentially no extra compaction
# slots are spent on borderline runs.
_F32_BAND = 1e-5


# Time-block width of the scanned calling reduction: device temp memory is
# ~40 B x BLOCK_W (~160 MB at 4 Mi) regardless of record length, and a
# 256 Mi-symbol chromosome takes 64 scan steps of pure elementwise/cumsum
# work.  Shorter inputs use one block rounded to their size.
DEFAULT_BLOCK_W = 1 << 22


def _ffill_at_openings(vals, opening, carries):
    """Forward-fill each val to the latest opening position's value, falling
    back to the carried value from previous blocks before the block's first
    opening.

    Correct ONLY for vals nondecreasing in t (indices and cumsums are): the
    running max over opening positions equals the value at the LAST opening.
    Positions before the first opening anywhere fill with the initial -1
    carry (never read: a leaving position always has an opening at or
    before it, whose values are then either block-local or carried).
    """
    return tuple(
        jnp.where(
            (local := jax.lax.cummax(jnp.where(opening, v, jnp.int32(-1))))
            >= 0,
            local,
            c,
        )
        for v, c in zip(vals, carries)
    )


def _scan_calls(
    p2,
    o2,
    mask_fn,
    W: int,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
):
    """Blocked run accounting: [nB, W] path (+obs) blocks -> call columns.

    The ONE copy of the aggregation and thresholds — the 8-state path caller
    and the observation-based caller differ only in ``mask_fn``, which maps
    a (path block, obs block) to elementwise (in_mask, is_c, is_g, cgp)
    where ``cgp`` is the "this position is a C" indicator whose SHIFT gates
    the CpG event (is_c for the 8-state labeling, raw obs==C for the
    observation-based caller — matching ops.islands exactly).
    """
    nB = p2.shape[0]
    idx0 = jnp.arange(W, dtype=jnp.int32)
    carry0 = (
        jnp.asarray(False),  # prev_in: membership of the previous position
        jnp.asarray(False),  # prev_cgp
        jnp.int32(0), jnp.int32(0), jnp.int32(0),  # C/G/CpG cum bases
        jnp.int32(-1), jnp.int32(-1), jnp.int32(-1), jnp.int32(-1),  # anchor
        jnp.int32(0),  # emitted-call cursor
        tuple(jnp.zeros(cap, jnp.int32) for _ in range(6)),  # output columns
    )

    def body(carry, inp):
        (prev_in, prev_cgp, c_base, g_base, cg_base,
         o_start, o_c0, o_g0, o_cg0, n, bufs) = carry
        b_i, p, o = inp
        in_mask, is_c, is_g, cgp = mask_fn(p, o)
        gidx = b_i * W + idx0
        prev_in_v = jnp.concatenate([prev_in[None], in_mask[:-1]])
        prev_cgp_v = jnp.concatenate([prev_cgp[None], cgp[:-1]])
        # is_g implies in_mask, so this is the host caller's
        # in_mask & prev_in & is_g & prev_c.
        cg_event = is_g & prev_in_v & prev_cgp_v
        opening = in_mask & ~prev_in_v
        # A run is EMITTED at its leaving position (first background
        # position after it): the one-past-the-end padding guarantees every
        # run — including one at the true end of the record — leaves.
        leaving = prev_in_v & ~in_mask

        cum_c = c_base + jnp.cumsum(is_c.astype(jnp.int32))
        cum_g = g_base + jnp.cumsum(is_g.astype(jnp.int32))
        cum_cg = cg_base + jnp.cumsum(cg_event.astype(jnp.int32))

        # Propagate the open run's anchor (opening index + PRE-opening
        # cumsums) to every position; carried across blocks for runs that
        # span them.  cg_event is False at openings (prev_in is False).
        start_f, c0_f, g0_f, cg0_f = _ffill_at_openings(
            (
                gidx,
                cum_c - is_c.astype(jnp.int32),
                cum_g - is_g.astype(jnp.int32),
                cum_cg,
            ),
            opening,
            (o_start, o_c0, o_g0, o_cg0),
        )

        # At a leaving position t the run's last index is t-1, and the
        # position itself contributes no counts (it is background).
        length = gidx - start_f
        c_cnt = cum_c - c0_f
        g_cnt = cum_g - g0_f
        cg_cnt = cum_cg - cg0_f

        lengthf = length.astype(jnp.float32)
        gc = (c_cnt + g_cnt).astype(jnp.float32) / jnp.maximum(lengthf, 1.0)
        both = (c_cnt > 0) & (g_cnt > 0)
        # c*g in float32, not int32: a ~92k-symbol GC-rich run overflows the
        # int32 product and would silently fail the oe filter.
        cgprod = c_cnt.astype(jnp.float32) * g_cnt.astype(jnp.float32)
        oe = jnp.where(
            both,
            cg_cnt.astype(jnp.float32) * lengthf / jnp.where(both, cgprod, 1.0),
            0.0,
        )

        # The float cuts here are CONSERVATIVE, not final: without x64
        # there is no f64 on device, and f32 gc/oe carry up to ~6e-7
        # relative rounding.  The device keeps everything within a 1e-5
        # relative band of each threshold; _fetch_calls re-evaluates the
        # survivors exactly in f64 on the host from the compacted integer
        # counts, so the emitted set (and the published gc/oe values) are
        # bit-identical to ops.islands.  The default gc cut evaluates
        # integer-exactly on device (2*(C+G) > len) — no band at all.
        if gc_threshold == 0.5:
            gc_pass = 2 * (c_cnt + g_cnt) > length
        else:
            gc_pass = gc > gc_threshold - _F32_BAND * abs(gc_threshold)
        oe_pass = oe > oe_threshold - _F32_BAND * abs(oe_threshold)
        keep = leaving & gc_pass & oe_pass
        if min_len is not None:
            keep &= length > min_len

        # Compact this block's survivors at the carried cursor (cap = dump
        # slot, dropped by mode="drop"; kpos is unique within the block).
        kpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, n + kpos, cap)
        cols = (start_f, gidx - 1, length, c_cnt, g_cnt, cg_cnt)
        bufs = tuple(
            b.at[tgt].set(v, mode="drop") for b, v in zip(bufs, cols)
        )
        carry = (
            in_mask[-1], cgp[-1], cum_c[-1], cum_g[-1], cum_cg[-1],
            start_f[-1], c0_f[-1], g0_f[-1], cg0_f[-1],
            n + jnp.sum(keep.astype(jnp.int32)), bufs,
        )
        return carry, None

    carry, _ = jax.lax.scan(
        body, carry0, (jnp.arange(nB, dtype=jnp.int32), p2, o2)
    )
    return (*carry[-1], carry[-2])


def _block_layout(T: int, block_w: int) -> tuple:
    """(n_blocks, W, pad): pad >= 1 so the final position is background and
    every run leaves (the clean-mode a-run-at-the-end-still-closes rule)."""
    W = 1 << 10
    while W < min(block_w, T + 1):
        W <<= 1
    nB = -(-(T + 1) // W)
    return nB, W, nB * W - T


@functools.partial(
    jax.jit,
    static_argnames=("cap", "min_len", "gc_threshold", "oe_threshold", "block_w"),
)
def _device_calls(
    path,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
    block_w: int = DEFAULT_BLOCK_W,
):
    """Jitted 8-state core: [T] path -> fixed-size call columns + count.

    Base identity comes from the state ids (the reference's X+/X- labeling,
    CpGIslandFinder.java:182-189): state 1 = C+, state 2 = G+.  The input
    keeps its storage dtype (int8 span paths stay int8); each block casts
    on the fly.
    """
    T = path.shape[0]
    nB, W, pad = _block_layout(T, block_w)
    p2 = jnp.concatenate(
        [path, jnp.full(pad, N_ISLAND_STATES, path.dtype)]
    ).reshape(nB, W)

    def mask_fn(p, _o):
        p = p.astype(jnp.int32)
        in_mask = p < N_ISLAND_STATES
        is_c = in_mask & (p == C_STATE)
        is_g = in_mask & (p == G_STATE)
        return in_mask, is_c, is_g, is_c

    # o2 = p2: unused by mask_fn, same buffer — no second [T] allocation.
    return _scan_calls(
        p2, p2, mask_fn, W, cap, min_len, gc_threshold, oe_threshold
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "island_states", "cap", "min_len", "gc_threshold", "oe_threshold",
        "block_w",
    ),
)
def _device_calls_obs(
    path,
    obs,
    island_states: tuple,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
    block_w: int = DEFAULT_BLOCK_W,
):
    """Jitted generic core: membership from ``path`` in ``island_states``
    (static tuple — unrolled compares, no gather), base composition from the
    OBSERVATIONS (symbol ids 0..3 = acgt) — the device twin of
    ops.islands.call_islands_obs for models whose states don't encode bases
    (e.g. presets.two_state_cpg)."""
    T = path.shape[0]
    nB, W, pad = _block_layout(T, block_w)
    p2 = jnp.concatenate(
        # n_states: an id no model state uses -> padding is background for
        # every island_states set.
        [path, jnp.full(pad, len(island_states) and max(island_states) + 1, path.dtype)]
    ).reshape(nB, W)
    o2 = jnp.concatenate([obs, jnp.zeros(pad, obs.dtype)]).reshape(nB, W)

    def mask_fn(p, o):
        p = p.astype(jnp.int32)
        o = o.astype(jnp.int32)
        in_mask = jnp.zeros(p.shape, bool)
        for s in island_states:
            in_mask = in_mask | (p == s)
        obs_c = o == 1  # codec.C
        is_c = in_mask & obs_c
        is_g = in_mask & (o == 2)  # codec.G
        return in_mask, is_c, is_g, obs_c

    return _scan_calls(
        p2, o2, mask_fn, W, cap, min_len, gc_threshold, oe_threshold
    )


def call_islands_device_async(
    path,
    *,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
):
    """Dispatch the calling reduction NOW; return a thunk that fetches.

    The device work is queued immediately (async jit dispatch); invoking the
    returned zero-arg callable performs the one blocking host fetch and the
    exact f64 re-evaluation, raising IslandCapOverflow there if more than
    ``cap`` calls survived.  This is the latency-hiding split the overlapped
    pipeline uses: record r's compact columns are fetched only after record
    r+1's decode is already in flight, so the relay round trip hides behind
    device compute.  ``call_islands_device`` is exactly this thunk invoked
    immediately — one implementation, two cadences.
    """
    path = jnp.asarray(path)
    if path.shape[0] == 0:
        return _empty_calls
    cols = _device_calls(
        path, cap, min_len, float(gc_threshold), float(oe_threshold)
    )
    return lambda: _fetch_calls(cols, cap, offset, gc_threshold, oe_threshold)


def call_islands_device(
    path,
    *,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
) -> IslandCalls:
    """Clean-mode island calls computed on device; returns host IslandCalls.

    ``path`` may be a device array (stays resident — only the <= ``cap``
    records move to host) or anything jnp.asarray accepts.  Raises
    IslandCapOverflow if more than ``cap`` calls survive the filters (the
    exception carries the true count; each slot costs ~40 bytes of device
    output).  Emitted calls and their gc/oe values are bit-identical to
    ops.islands.call_islands(compat=False): the float thresholds are
    enforced in f64 on the host over the compact integer counts.
    """
    return call_islands_device_async(
        path, min_len=min_len, cap=cap, gc_threshold=gc_threshold,
        oe_threshold=oe_threshold, offset=offset,
    )()


def call_islands_device_obs_async(
    path,
    obs,
    *,
    island_states,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
):
    """Deferred-fetch twin of :func:`call_islands_device_obs` — same
    dispatch-now / fetch-at-the-thunk contract as
    :func:`call_islands_device_async`."""
    path = jnp.asarray(path)
    obs = jnp.asarray(obs)
    if path.shape[0] != obs.shape[0]:
        raise ValueError(f"path {path.shape} and obs {obs.shape} differ")
    if path.shape[0] == 0:
        return _empty_calls
    cols = _device_calls_obs(
        path, obs, tuple(sorted(island_states)), cap, min_len,
        float(gc_threshold), float(oe_threshold),
    )
    return lambda: _fetch_calls(cols, cap, offset, gc_threshold, oe_threshold)


def call_islands_device_obs(
    path,
    obs,
    *,
    island_states,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
) -> IslandCalls:
    """Device-side island calling for ARBITRARY state sets (clean semantics).

    Membership comes from the decoded ``path`` (in ``island_states``), base
    composition from the aligned ``obs`` symbols — the on-device counterpart
    of ops.islands.call_islands_obs, so clean decoding with e.g. the
    two_state preset keeps the path on device and ships only the compact
    call records to the host (same economics as the 8-state device caller).
    """
    return call_islands_device_obs_async(
        path, obs, island_states=island_states, min_len=min_len, cap=cap,
        gc_threshold=gc_threshold, oe_threshold=oe_threshold, offset=offset,
    )()


def _cols_to_host(cols):
    """One batched host fetch of the device call columns.

    Multi-host: columns computed from a global-mesh path carry the global
    device assignment (non-fully-addressable), so a plain fetch raises;
    gather every process a full replica in ONE collective — the columns are
    ~3 MB and every process needs the same call records for its own output
    anyway.  This is the [cap]-record-column twin of
    parallel.mesh.fetch_sharded_prefix's multi-host rule.
    """
    from cpgisland_tpu import obs

    if any(not getattr(c, "is_fully_addressable", True) for c in cols):
        from jax.experimental import multihost_utils

        with obs.span("multihost-gather", gather="island-call-columns"):
            return obs.note_fetch(
                multihost_utils.process_allgather(tuple(cols), tiled=True)
            )
    # graftcheck: allow(hot-path-host-sync) -- the deferred call-column fetch's one blocking point; counted by the obs ledger's device_get hook (note_fetch would double-count)
    return jax.device_get(cols)


def _fetch_calls(
    cols, cap: int, offset: int, gc_threshold: float, oe_threshold: float
) -> IslandCalls:
    """Compact device columns -> exact host IslandCalls.

    The device kept every run within the conservative f32 band of the
    thresholds; here the survivors' integer counts are re-evaluated in f64
    with exactly ops.islands._runs_to_calls' formulas, so both the emitted
    set and the gc/oe values match the host caller bit-for-bit (the device
    path adds no float error of its own — only exact int32 counts cross).
    ONE batched fetch moves every column: seven sequential blocking fetches
    would pay seven relay round-trips (~50-100 ms each on a tunneled TPU)
    for ~3 MB of data."""
    starts, lasts, length, c_cnt, g_cnt, cg_cnt, n = _cols_to_host(cols)
    n = int(n)
    if n < 0:
        # A negative count cannot come from the reduction (the cursor only
        # increments) — it means the fetch returned corrupt/stale buffers
        # (the degraded relay's phantom mode, CLAUDE.md).  RuntimeError on
        # purpose: fault-shaped, so the dispatch supervisor re-dispatches
        # instead of treating it as a sizing signal.
        raise RuntimeError(
            f"corrupt island-call columns: negative call count {n} "
            "(stale/phantom device fetch?)"
        )
    if n > cap:
        raise IslandCapOverflow(n, cap)
    sl = slice(0, n)
    if n and (
        np.any(np.asarray(length[sl]) <= 0)
        or np.any(np.asarray(starts[sl]) < 0)
    ):
        # Same reasoning: every emitted run has length >= 1 and a
        # non-negative start by construction — anything else is a corrupt
        # fetch, not a result.
        raise RuntimeError(
            "corrupt island-call columns: non-positive lengths or negative "
            "starts (stale/phantom device fetch?)"
        )
    starts = starts[sl].astype(np.int64)
    lasts = lasts[sl].astype(np.int64)
    length = length[sl].astype(np.int64)
    c_cnt = c_cnt[sl].astype(np.int64)
    g_cnt = g_cnt[sl].astype(np.int64)
    cg_cnt = cg_cnt[sl].astype(np.int64)
    gc, oe = counts_to_gc_oe(c_cnt, g_cnt, cg_cnt, length)
    keep = (gc > gc_threshold) & (oe > oe_threshold)
    return IslandCalls(
        beg=starts[keep] + offset + 1,
        end=lasts[keep] + offset + 1,
        length=length[keep],
        gc_content=np.asarray(gc[keep], np.float64),
        oe_ratio=np.asarray(oe[keep], np.float64),
    )
