"""Device-side CpG island calling (clean semantics) — XLA cumulative ops.

The host caller (ops.islands) is vectorized NumPy, but feeding it means
shipping the whole decoded path (4 B/symbol) device->host and then scanning it
on the host: at GRCh38 scale that is ~12 GB of PCIe traffic plus an O(T) host
pass — together far more wall-clock than the sharded decode itself.  This
module keeps the reduction ON DEVICE: the path goes in, only the compact
(beg, end, length, gc, oe) records come out (a few hundred KiB), so the
decode -> islands pipeline is one fused XLA program with no large transfer.

Mechanics — all TPU-native cumulative/elementwise ops, chosen for O(1)
compile scaling (an associative_scan ffill and a size-bounded flatnonzero
both made XLA:TPU compile time grow superlinearly in T; cummax and one
scatter do not):

- island membership, run boundaries, and C/G/CpG event masks exactly as the
  clean-mode host caller computes them;
- per-run aggregates via cumulative sums plus a forward-fill of each run's
  opening index and pre-opening cumsums.  Every filled quantity is
  NONDECREASING in t, so `lax.cummax(where(opening, value, -1))` IS the
  forward-fill of the last opening's value — no gathers, no segmented scan;
- the <= ``cap`` surviving calls are compacted with one cumsum-indexed
  scatter (`.at[target].set(..., mode="drop")` with an overflow dump slot).

Only CLEAN semantics (compat quirk reproduction stays on the host path — it
exists for byte-fidelity, not throughput).  Parity with
ops.islands.call_islands(compat=False) is tested on random and adversarial
paths (tests/test_islands_device.py).

Reference scope: the island state machine, CpGIslandFinder.java:262-339.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.ops.islands import (
    C_STATE,
    G_STATE,
    IslandCalls,
    N_ISLAND_STATES,
    _empty_calls,
    counts_to_gc_oe,
)

# Default maximum number of emitted calls per invocation.  Real genomes carry
# ~25-45k CpG islands TOTAL; 128 Ki per call site is a deep safety margin and
# costs only ~5 MB of device output buffers.
DEFAULT_CAP = 1 << 17

class IslandCapOverflow(ValueError):
    """More island calls survived the filters than ``cap`` output slots.

    Carries the true count so a caller can retry with a sufficient cap —
    the decoded path is typically still device-resident, so the retry
    re-runs only the (cheap) calling reduction, not the decode.
    """

    def __init__(self, n: int, cap: int):
        super().__init__(
            f"{n} island calls exceed cap={cap}; pass a larger cap "
            "(each slot costs ~40 B of device output)"
        )
        self.n = n
        self.cap = cap


# Relative width of the conservative device-side band around each float
# threshold (see _calls_from_masks): f32 gc/oe rounding is bounded by ~6e-7
# relative, so 1e-5 is a 16x safety margin — wide enough that no true call
# can be lost on device, narrow enough that essentially no extra compaction
# slots are spent on borderline runs.
_F32_BAND = 1e-5


def _ffill_at_openings(vals, opening):
    """Forward-fill each val to the latest opening position's value.

    Correct ONLY for vals nondecreasing in t (indices and cumsums are): the
    running max over opening positions equals the value at the LAST opening.
    Positions before the first opening fill with -1 (never read: a closing
    position always has an opening at or before it).
    """
    return tuple(
        jax.lax.cummax(jnp.where(opening, v, jnp.int32(-1))) for v in vals
    )


def _compact(keep, cols, cap):
    """Pack cols[i][keep] into [cap] slots, in order; overflow drops."""
    kpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep, kpos, cap)  # cap = dump slot, dropped by mode
    return tuple(
        jnp.zeros(cap, c.dtype).at[tgt].set(c, mode="drop") for c in cols
    )


def _calls_from_masks(
    in_mask,
    is_c,
    is_g,
    cg_event,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
):
    """Shared device-side run accounting: membership/event masks -> call
    columns.  The ONE copy of the cummax-ffill aggregation and thresholds —
    the 8-state path caller and the observation-based caller both feed it."""
    T = in_mask.shape[0]
    idx = jnp.arange(T, dtype=jnp.int32)
    prev_in = jnp.concatenate([jnp.zeros(1, bool), in_mask[:-1]])
    opening = in_mask & ~prev_in
    next_in = jnp.concatenate([in_mask[1:], jnp.zeros(1, bool)])
    closing = in_mask & ~next_in  # clean mode: a run at the end still closes

    cum_c = jnp.cumsum(is_c.astype(jnp.int32))
    cum_g = jnp.cumsum(is_g.astype(jnp.int32))
    cum_cg = jnp.cumsum(cg_event.astype(jnp.int32))

    # Propagate each run's opening index and PRE-opening cumsums to every
    # position of the run (so in particular to its closing position).
    start_idx, c0, g0, cg0 = _ffill_at_openings(
        (
            idx,
            cum_c - is_c.astype(jnp.int32),
            cum_g - is_g.astype(jnp.int32),
            cum_cg,  # cg_event is False at openings (prev_in is False there)
        ),
        opening,
    )

    length = idx - start_idx + 1
    c_cnt = cum_c - c0
    g_cnt = cum_g - g0
    cg_cnt = cum_cg - cg0

    lengthf = length.astype(jnp.float32)
    gc = (c_cnt + g_cnt).astype(jnp.float32) / lengthf
    both = (c_cnt > 0) & (g_cnt > 0)
    # c*g in float32, not int32: a ~92k-symbol GC-rich run overflows the
    # int32 product and would silently fail the oe filter.
    cgprod = c_cnt.astype(jnp.float32) * g_cnt.astype(jnp.float32)
    oe = jnp.where(
        both,
        cg_cnt.astype(jnp.float32) * lengthf / jnp.where(both, cgprod, 1.0),
        0.0,
    )

    # The float cuts here are CONSERVATIVE, not final: without x64 there is
    # no f64 on device, and f32 gc/oe carry up to ~6e-7 relative rounding
    # (int->f32 conversions at 2^28 magnitudes plus 3 arithmetic ops).  The
    # device keeps everything within a 1e-5 relative band of each threshold;
    # _fetch_calls re-evaluates the survivors exactly in f64 on the host
    # from the compacted integer counts, so the emitted set (and the
    # published gc/oe values) are bit-identical to ops.islands.  The default
    # gc cut evaluates integer-exactly on device (2*(C+G) > len), so it
    # needs no band at all.
    if gc_threshold == 0.5:
        gc_pass = 2 * (c_cnt + g_cnt) > length
    else:
        gc_pass = gc > gc_threshold - _F32_BAND * abs(gc_threshold)
    oe_pass = oe > oe_threshold - _F32_BAND * abs(oe_threshold)
    keep = closing & gc_pass & oe_pass
    if min_len is not None:
        keep &= length > min_len

    n = jnp.sum(keep.astype(jnp.int32))
    starts_o, lasts_o, len_o, c_o, g_o, cg_o = _compact(
        keep, (start_idx, idx, length, c_cnt, g_cnt, cg_cnt), cap
    )
    return starts_o, lasts_o, len_o, c_o, g_o, cg_o, n


@functools.partial(
    jax.jit, static_argnames=("cap", "min_len", "gc_threshold", "oe_threshold")
)
def _device_calls(
    path,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
):
    """Jitted 8-state core: [T] path -> fixed-size call columns + count.

    Base identity comes from the state ids (the reference's X+/X- labeling,
    CpGIslandFinder.java:182-189): state 1 = C+, state 2 = G+.
    """
    path = path.astype(jnp.int32)
    in_mask = path < N_ISLAND_STATES
    prev_in = jnp.concatenate([jnp.zeros(1, bool), in_mask[:-1]])
    is_c = in_mask & (path == C_STATE)
    is_g = in_mask & (path == G_STATE)
    prev_c = jnp.concatenate([jnp.zeros(1, bool), is_c[:-1]])
    cg_event = in_mask & prev_in & is_g & prev_c
    return _calls_from_masks(
        in_mask, is_c, is_g, cg_event, cap, min_len, gc_threshold, oe_threshold
    )


@functools.partial(
    jax.jit,
    static_argnames=("island_states", "cap", "min_len", "gc_threshold", "oe_threshold"),
)
def _device_calls_obs(
    path,
    obs,
    island_states: tuple,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
):
    """Jitted generic core: membership from ``path`` in ``island_states``
    (static tuple — unrolled compares, no gather), base composition from the
    OBSERVATIONS (symbol ids 0..3 = acgt) — the device twin of
    ops.islands.call_islands_obs for models whose states don't encode bases
    (e.g. presets.two_state_cpg)."""
    path = path.astype(jnp.int32)
    obs = obs.astype(jnp.int32)
    in_mask = jnp.zeros(path.shape, bool)
    for s in island_states:
        in_mask = in_mask | (path == s)
    prev_in = jnp.concatenate([jnp.zeros(1, bool), in_mask[:-1]])
    obs_c = obs == 1  # codec.C
    obs_g = obs == 2  # codec.G
    is_c = in_mask & obs_c
    is_g = in_mask & obs_g
    cg_event = (
        in_mask & prev_in & obs_g
        & jnp.concatenate([jnp.zeros(1, bool), obs_c[:-1]])
    )
    return _calls_from_masks(
        in_mask, is_c, is_g, cg_event, cap, min_len, gc_threshold, oe_threshold
    )


def call_islands_device(
    path,
    *,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
) -> IslandCalls:
    """Clean-mode island calls computed on device; returns host IslandCalls.

    ``path`` may be a device array (stays resident — only the <= ``cap``
    records move to host) or anything jnp.asarray accepts.  Raises
    IslandCapOverflow if more than ``cap`` calls survive the filters (the
    exception carries the true count; each slot costs ~40 bytes of device
    output).  Emitted calls and their gc/oe values are bit-identical to
    ops.islands.call_islands(compat=False): the float thresholds are
    enforced in f64 on the host over the compact integer counts.
    """
    path = jnp.asarray(path)
    if path.shape[0] == 0:
        return _empty_calls()
    cols = _device_calls(
        path, cap, min_len, float(gc_threshold), float(oe_threshold)
    )
    return _fetch_calls(cols, cap, offset, gc_threshold, oe_threshold)


def call_islands_device_obs(
    path,
    obs,
    *,
    island_states,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
) -> IslandCalls:
    """Device-side island calling for ARBITRARY state sets (clean semantics).

    Membership comes from the decoded ``path`` (in ``island_states``), base
    composition from the aligned ``obs`` symbols — the on-device counterpart
    of ops.islands.call_islands_obs, so clean decoding with e.g. the
    two_state preset keeps the path on device and ships only the compact
    call records to the host (same economics as the 8-state device caller).
    """
    path = jnp.asarray(path)
    obs = jnp.asarray(obs)
    if path.shape[0] != obs.shape[0]:
        raise ValueError(f"path {path.shape} and obs {obs.shape} differ")
    if path.shape[0] == 0:
        return _empty_calls()
    cols = _device_calls_obs(
        path, obs, tuple(sorted(island_states)), cap, min_len,
        float(gc_threshold), float(oe_threshold),
    )
    return _fetch_calls(cols, cap, offset, gc_threshold, oe_threshold)


def _cols_to_host(cols):
    """One batched host fetch of the device call columns.

    Multi-host: columns computed from a global-mesh path carry the global
    device assignment (non-fully-addressable), so a plain fetch raises;
    gather every process a full replica in ONE collective — the columns are
    ~3 MB and every process needs the same call records for its own output
    anyway.  This is the [cap]-record-column twin of
    parallel.mesh.fetch_sharded_prefix's multi-host rule.
    """
    if any(not getattr(c, "is_fully_addressable", True) for c in cols):
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(tuple(cols), tiled=True)
    return jax.device_get(cols)


def _fetch_calls(
    cols, cap: int, offset: int, gc_threshold: float, oe_threshold: float
) -> IslandCalls:
    """Compact device columns -> exact host IslandCalls.

    The device kept every run within the conservative f32 band of the
    thresholds; here the survivors' integer counts are re-evaluated in f64
    with exactly ops.islands._runs_to_calls' formulas, so both the emitted
    set and the gc/oe values match the host caller bit-for-bit (the device
    path adds no float error of its own — only exact int32 counts cross).
    ONE batched fetch moves every column: seven sequential blocking fetches
    would pay seven relay round-trips (~50-100 ms each on a tunneled TPU)
    for ~3 MB of data."""
    starts, lasts, length, c_cnt, g_cnt, cg_cnt, n = _cols_to_host(cols)
    n = int(n)
    if n > cap:
        raise IslandCapOverflow(n, cap)
    sl = slice(0, n)
    starts = starts[sl].astype(np.int64)
    lasts = lasts[sl].astype(np.int64)
    length = length[sl].astype(np.int64)
    c_cnt = c_cnt[sl].astype(np.int64)
    g_cnt = g_cnt[sl].astype(np.int64)
    cg_cnt = cg_cnt[sl].astype(np.int64)
    gc, oe = counts_to_gc_oe(c_cnt, g_cnt, cg_cnt, length)
    keep = (gc > gc_threshold) & (oe > oe_threshold)
    return IslandCalls(
        beg=starts[keep] + offset + 1,
        end=lasts[keep] + offset + 1,
        length=length[keep],
        gc_content=np.asarray(gc[keep], np.float64),
        oe_ratio=np.asarray(oe[keep], np.float64),
    )
