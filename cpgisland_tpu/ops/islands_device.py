"""Device-side CpG island calling (clean semantics) — XLA cumulative ops.

The host caller (ops.islands) is vectorized NumPy, but feeding it means
shipping the whole decoded path (4 B/symbol) device->host and then scanning it
on the host: at GRCh38 scale that is ~12 GB of PCIe traffic plus an O(T) host
pass — together far more wall-clock than the sharded decode itself.  This
module keeps the reduction ON DEVICE: the path goes in, only the compact
(beg, end, length, gc, oe) records come out (a few hundred KiB), so the
decode -> islands pipeline is one fused XLA program with no large transfer.

Mechanics — all TPU-native cumulative/elementwise ops, chosen for O(1)
compile scaling (an associative_scan ffill and a size-bounded flatnonzero
both made XLA:TPU compile time grow superlinearly in T; cummax and one
scatter do not):

- island membership, run boundaries, and C/G/CpG event masks exactly as the
  clean-mode host caller computes them;
- per-run aggregates via cumulative sums plus a forward-fill of each run's
  opening index and pre-opening cumsums.  Every filled quantity is
  NONDECREASING in t, so `lax.cummax(where(opening, value, -1))` IS the
  forward-fill of the last opening's value — no gathers, no segmented scan;
- the <= ``cap`` surviving calls are compacted with one cumsum-indexed
  scatter (`.at[target].set(..., mode="drop")` with an overflow dump slot).

Only CLEAN semantics (compat quirk reproduction stays on the host path — it
exists for byte-fidelity, not throughput).  Parity with
ops.islands.call_islands(compat=False) is tested on random and adversarial
paths (tests/test_islands_device.py).

Reference scope: the island state machine, CpGIslandFinder.java:262-339.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.ops.islands import (
    C_STATE,
    G_STATE,
    IslandCalls,
    N_ISLAND_STATES,
    _empty_calls,
)

# Default maximum number of emitted calls per invocation.  Real genomes carry
# ~25-45k CpG islands TOTAL; 128 Ki per call site is a deep safety margin and
# costs only ~5 MB of device output buffers.
DEFAULT_CAP = 1 << 17


def _ffill_at_openings(vals, opening):
    """Forward-fill each val to the latest opening position's value.

    Correct ONLY for vals nondecreasing in t (indices and cumsums are): the
    running max over opening positions equals the value at the LAST opening.
    Positions before the first opening fill with -1 (never read: a closing
    position always has an opening at or before it).
    """
    return tuple(
        jax.lax.cummax(jnp.where(opening, v, jnp.int32(-1))) for v in vals
    )


def _compact(keep, cols, cap):
    """Pack cols[i][keep] into [cap] slots, in order; overflow drops."""
    kpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep, kpos, cap)  # cap = dump slot, dropped by mode
    return tuple(
        jnp.zeros(cap, c.dtype).at[tgt].set(c, mode="drop") for c in cols
    )


def _calls_from_masks(
    in_mask,
    is_c,
    is_g,
    cg_event,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
):
    """Shared device-side run accounting: membership/event masks -> call
    columns.  The ONE copy of the cummax-ffill aggregation and thresholds —
    the 8-state path caller and the observation-based caller both feed it."""
    T = in_mask.shape[0]
    idx = jnp.arange(T, dtype=jnp.int32)
    prev_in = jnp.concatenate([jnp.zeros(1, bool), in_mask[:-1]])
    opening = in_mask & ~prev_in
    next_in = jnp.concatenate([in_mask[1:], jnp.zeros(1, bool)])
    closing = in_mask & ~next_in  # clean mode: a run at the end still closes

    cum_c = jnp.cumsum(is_c.astype(jnp.int32))
    cum_g = jnp.cumsum(is_g.astype(jnp.int32))
    cum_cg = jnp.cumsum(cg_event.astype(jnp.int32))

    # Propagate each run's opening index and PRE-opening cumsums to every
    # position of the run (so in particular to its closing position).
    start_idx, c0, g0, cg0 = _ffill_at_openings(
        (
            idx,
            cum_c - is_c.astype(jnp.int32),
            cum_g - is_g.astype(jnp.int32),
            cum_cg,  # cg_event is False at openings (prev_in is False there)
        ),
        opening,
    )

    length = idx - start_idx + 1
    c_cnt = cum_c - c0
    g_cnt = cum_g - g0
    cg_cnt = cum_cg - cg0

    lengthf = length.astype(jnp.float32)
    gc = (c_cnt + g_cnt).astype(jnp.float32) / lengthf
    both = (c_cnt > 0) & (g_cnt > 0)
    # c*g in float32, not int32: a ~92k-symbol GC-rich run overflows the
    # int32 product and would silently fail the oe filter.
    cgprod = c_cnt.astype(jnp.float32) * g_cnt.astype(jnp.float32)
    oe = jnp.where(
        both,
        cg_cnt.astype(jnp.float32) * lengthf / jnp.where(both, cgprod, 1.0),
        0.0,
    )

    # The default gc cut evaluates integer-exactly (2*(C+G) > len avoids the
    # f32-vs-f64 rounding flips the host caller can't see; the oe cut stays
    # f32 — without x64 there is no wider type — which can flip calls whose
    # oe sits within ~1e-7 of the threshold).
    if gc_threshold == 0.5:
        gc_pass = 2 * (c_cnt + g_cnt) > length
    else:
        gc_pass = gc > gc_threshold
    keep = closing & gc_pass & (oe > oe_threshold)
    if min_len is not None:
        keep &= length > min_len

    n = jnp.sum(keep.astype(jnp.int32))
    starts_o, lasts_o, len_o, gc_o, oe_o = _compact(
        keep, (start_idx, idx, length, gc, oe), cap
    )
    return starts_o, lasts_o, len_o, gc_o, oe_o, n


@functools.partial(
    jax.jit, static_argnames=("cap", "min_len", "gc_threshold", "oe_threshold")
)
def _device_calls(
    path,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
):
    """Jitted 8-state core: [T] path -> fixed-size call columns + count.

    Base identity comes from the state ids (the reference's X+/X- labeling,
    CpGIslandFinder.java:182-189): state 1 = C+, state 2 = G+.
    """
    path = path.astype(jnp.int32)
    in_mask = path < N_ISLAND_STATES
    prev_in = jnp.concatenate([jnp.zeros(1, bool), in_mask[:-1]])
    is_c = in_mask & (path == C_STATE)
    is_g = in_mask & (path == G_STATE)
    prev_c = jnp.concatenate([jnp.zeros(1, bool), is_c[:-1]])
    cg_event = in_mask & prev_in & is_g & prev_c
    return _calls_from_masks(
        in_mask, is_c, is_g, cg_event, cap, min_len, gc_threshold, oe_threshold
    )


@functools.partial(
    jax.jit,
    static_argnames=("island_states", "cap", "min_len", "gc_threshold", "oe_threshold"),
)
def _device_calls_obs(
    path,
    obs,
    island_states: tuple,
    cap: int,
    min_len: Optional[int],
    gc_threshold: float,
    oe_threshold: float,
):
    """Jitted generic core: membership from ``path`` in ``island_states``
    (static tuple — unrolled compares, no gather), base composition from the
    OBSERVATIONS (symbol ids 0..3 = acgt) — the device twin of
    ops.islands.call_islands_obs for models whose states don't encode bases
    (e.g. presets.two_state_cpg)."""
    path = path.astype(jnp.int32)
    obs = obs.astype(jnp.int32)
    in_mask = jnp.zeros(path.shape, bool)
    for s in island_states:
        in_mask = in_mask | (path == s)
    prev_in = jnp.concatenate([jnp.zeros(1, bool), in_mask[:-1]])
    obs_c = obs == 1  # codec.C
    obs_g = obs == 2  # codec.G
    is_c = in_mask & obs_c
    is_g = in_mask & obs_g
    cg_event = (
        in_mask & prev_in & obs_g
        & jnp.concatenate([jnp.zeros(1, bool), obs_c[:-1]])
    )
    return _calls_from_masks(
        in_mask, is_c, is_g, cg_event, cap, min_len, gc_threshold, oe_threshold
    )


def call_islands_device(
    path,
    *,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
) -> IslandCalls:
    """Clean-mode island calls computed on device; returns host IslandCalls.

    ``path`` may be a device array (stays resident — only the <= ``cap``
    records move to host) or anything jnp.asarray accepts.  Raises if more
    than ``cap`` calls survive the filters (raise the cap; each slot costs
    ~40 bytes of device output).
    """
    path = jnp.asarray(path)
    if path.shape[0] == 0:
        return _empty_calls()
    cols = _device_calls(
        path, cap, min_len, float(gc_threshold), float(oe_threshold)
    )
    return _fetch_calls(cols, cap, offset)


def call_islands_device_obs(
    path,
    obs,
    *,
    island_states,
    min_len: Optional[int] = None,
    cap: int = DEFAULT_CAP,
    gc_threshold: float = 0.5,
    oe_threshold: float = 0.6,
    offset: int = 0,
) -> IslandCalls:
    """Device-side island calling for ARBITRARY state sets (clean semantics).

    Membership comes from the decoded ``path`` (in ``island_states``), base
    composition from the aligned ``obs`` symbols — the on-device counterpart
    of ops.islands.call_islands_obs, so clean decoding with e.g. the
    two_state preset keeps the path on device and ships only the compact
    call records to the host (same economics as the 8-state device caller).
    """
    path = jnp.asarray(path)
    obs = jnp.asarray(obs)
    if path.shape[0] != obs.shape[0]:
        raise ValueError(f"path {path.shape} and obs {obs.shape} differ")
    if path.shape[0] == 0:
        return _empty_calls()
    cols = _device_calls_obs(
        path, obs, tuple(sorted(island_states)), cap, min_len,
        float(gc_threshold), float(oe_threshold),
    )
    return _fetch_calls(cols, cap, offset)


def _fetch_calls(cols, cap: int, offset: int) -> IslandCalls:
    starts, lasts, length, gc, oe, n = cols
    n = int(n)
    if n > cap:
        raise ValueError(
            f"{n} island calls exceed cap={cap}; pass a larger cap "
            "(each slot costs ~40 B of device output)"
        )
    sl = slice(0, n)
    return IslandCalls(
        beg=np.asarray(starts[sl]).astype(np.int64) + offset + 1,
        end=np.asarray(lasts[sl]).astype(np.int64) + offset + 1,
        length=np.asarray(length[sl]).astype(np.int64),
        gc_content=np.asarray(gc[sl]).astype(np.float64),
        oe_ratio=np.asarray(oe[sl]).astype(np.float64),
    )
