"""Parallel Viterbi: blockwise max-plus scan with composition backtrace.

The reference decodes sequentially — Mahout's Viterbi DP walks 1 MiB chunks one
timestep at a time on the driver JVM (HmmEvaluator.decode at
CpGIslandFinder.java:260).  A timestep of an HMM DP is a max-plus (tropical
semiring) matrix-vector product, and max-plus matrix *products* are associative,
so the whole recurrence is a parallel scan (SURVEY.md §5 "Long-sequence
scaling").  This module decodes with three block passes, each a `lax.scan` of
``block_size`` sequential steps over ``n_blocks`` parallel lanes — the layout
the TPU VPU wants — turning a T-step recurrence into O(block_size +
log n_blocks) sequential depth:

1. **Pass A** — each lane computes the max-plus product of its block's step
   matrices M_t[i,j] = logA[i,j] + logB[j, o_t]; an exclusive
   `associative_scan` over the [K,K] block products then yields every block's
   exact entering score vector.
2. **Pass B** — lanes re-scan their block with the true entering vector,
   emitting int8 argmax backpointers and carrying the block's
   exit-state -> entry-state composition table (backpointer tables are maps
   state->state; map composition is associative and runs forward).
3. **Pass C** — a tiny cross-block composition anchors every block's exit state
   to the global argmax, then lanes walk their backpointers once, emitting the
   exact argmax path.

Per-symbol step matrices are selected by one-hot contraction against the
[n_symbols+1, K*K] table (a small matmul) rather than dynamic gathers — TPU
gathers cost ~2x the whole decode.  Measured on one v5e core this decodes
~55 Msymbols/s (vs ~1 Msym/s for the sequential `lax.scan` decoder).

Results match ops.viterbi.viterbi exactly up to argmax tie-breaking (tested on
achieved path score, and on exact paths for tie-free inputs).  PAD symbols
(>= n_symbols) become identity steps, so padded tails are pass-through exactly
like the sequential decoder.

The same passes power the multi-device sequence-parallel decoder
(parallel.decode): each device runs them over its sequence shard and the
cross-shard stitching exchanges only [K,K] transfer matrices and [K]
composition tables — two tiny all_gathers on ICI per decode.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from cpgisland_tpu.models.hmm import LOG_ZERO, HmmParams

# Swept on a v5e chip (64 Mi random symbols, pallas engine): 256 -> 204,
# 1024 -> 343, 2048 -> 498, 4096 -> 555 Msym/s (779 at 256 Mi); 8192 exceeds
# the 16 MiB scoped-vmem budget of the fused kernels.  Small inputs clamp the
# block to the sequence length, so the large default costs them nothing.
DEFAULT_BLOCK = 4096


def _identity_logmat(K: int) -> jnp.ndarray:
    return jnp.where(jnp.eye(K, dtype=bool), 0.0, LOG_ZERO)


def _step_tables(params: HmmParams):
    """Per-symbol step matrices with a trailing identity for the PAD sentinel.

    M_ext[s][i, j] = logA[i, j] + logB[j, s] for s < n_symbols; M_ext[n_symbols]
    is the max-plus identity.  emit_ext likewise maps PAD to a zero emission row.
    """
    K = params.n_states
    M = params.log_A[None, :, :] + params.log_B.T[:, None, :]  # [S, K, K]
    M_ext = jnp.concatenate([M, _identity_logmat(K)[None]], axis=0)
    emit_ext = jnp.concatenate([params.log_B.T, jnp.zeros((1, K))], axis=0)
    return M_ext, emit_ext


def maxplus_matmul(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """(x (+,max) y)[..., i, j] = max_m x[..., i, m] + y[..., m, j]."""
    return jnp.max(x[..., :, :, None] + y[..., None, :, :], axis=-2)


def nrm_maxplus(m: jnp.ndarray) -> jnp.ndarray:
    """Shift a max-plus matrix so its max entry is 0 (f32 range guard).

    Max-plus scores grow ~-1.3 nat/symbol, so an unnormalized product chain
    reaches magnitude ~3e8 on a chromosome — where the f32 ulp (~32) is
    larger than the O(1) per-state score differences every argmax decision
    depends on.  Subtracting the (per-lane scalar) max is decision-invariant:
    it cancels in every within-lane comparison.  Offsets are tracked
    separately only where a true score must be returned.
    """
    return jnp.maximum(
        m - jnp.max(m, axis=(-2, -1), keepdims=True), LOG_ZERO
    )


def nrm_maxplus_vec(v: jnp.ndarray) -> jnp.ndarray:
    """The [K] score-vector twin of :func:`nrm_maxplus`."""
    return jnp.maximum(v - jnp.max(v, axis=-1, keepdims=True), LOG_ZERO)


def scan_block_products(P: jnp.ndarray):
    """Inclusive prefix of per-block max-plus products, NORMALIZED per combine.

    The one shared implementation for both engines (the XLA scan and the
    Pallas kernels hand their per-block products here), so their prefixes are
    bit-identical.  Returns (incl [nb, K, K] with per-matrix max 0,
    offs [nb] the subtracted offsets — true incl[b] = incl[b] + offs[b]).
    """
    mx0 = jnp.max(P, axis=(-2, -1))
    P0 = jnp.maximum(P - mx0[..., None, None], LOG_ZERO)

    def comb(a, b):
        m = maxplus_matmul(a[0], b[0])
        mx = jnp.max(m, axis=(-2, -1))
        return (
            jnp.maximum(m - mx[..., None, None], LOG_ZERO),
            a[1] + b[1] + mx,
        )

    incl, offs = jax.lax.associative_scan(comb, (P0, mx0), axis=0)
    return incl, offs


def _compose(earlier: jnp.ndarray, later: jnp.ndarray) -> jnp.ndarray:
    """Composition of state->state lookup tables: out[s] = earlier[later[s]].

    ``earlier ∘ later`` applies the later-in-time table first — exactly the
    backtrace order s_{t-1} = bp_t[s_t].  Associative, so scan-able.
    """
    return jnp.take_along_axis(earlier, later, axis=-1)


def _select_step_mats(syms: jnp.ndarray, M_flat: jnp.ndarray, K: int) -> jnp.ndarray:
    """One-hot-select per-lane step matrices: [nb] syms -> [nb, K, K].

    HIGHEST precision: on TPU the default matmul precision rounds f32 operands
    to bf16 on the MXU — a pure selection contraction must not perturb the
    selected log-probs (the Pallas engine selects exactly; keeping this exact
    keeps the engines bit-identical).
    """
    oh = jax.nn.one_hot(syms, M_flat.shape[0], dtype=M_flat.dtype)
    sel = jnp.matmul(oh, M_flat, precision=jax.lax.Precision.HIGHEST)
    return sel.reshape(syms.shape[0], K, K)


class BlockDecode(NamedTuple):
    """Everything segment-stitching layers need from a blockwise decode."""

    path: jnp.ndarray  # [S] int32 — state after each step
    delta_exit: jnp.ndarray  # [K] final score vector (normalized; see offset)
    total: jnp.ndarray  # [K, K] NORMALIZED max-plus product of ALL step matrices
    ftable: jnp.ndarray  # [K] int32 — maps segment exit state -> entry state
    score_offset: jnp.ndarray  # [] add to delta_exit for true (global) scores
    # want_scores=True only (onehot engine): per-block entering offsets and the
    # block-normalized per-step chain max — the flat batch decoder's score feed.
    enter_offs: jnp.ndarray | None = None  # [nb]
    dmax2: jnp.ndarray | None = None  # [bk, nb]


def _pass_products(params: HmmParams, steps2: jnp.ndarray, prev0=None):
    """Pass A: per-block max-plus products + their normalized inclusive prefix.

    steps2: [bk, nb].  Returns (incl [nb, K, K] normalized per block,
    offs [nb] subtracted offsets, total [K, K] = incl[-1]).  ``prev0`` (the
    symbol emitted before step 0) is consumed only by the onehot engine —
    the dense engines ignore it.
    """
    K = params.n_states
    M_ext, _ = _step_tables(params)
    M_flat = M_ext.reshape(M_ext.shape[0], K * K)
    nb = steps2.shape[1]
    # Identity init derived from steps2 so its device-varying type matches
    # under shard_map.
    eye_b = _identity_logmat(K)[None] + (steps2[0, :, None, None] * 0).astype(jnp.float32)
    eye_b = jnp.broadcast_to(eye_b, (nb, K, K))

    def passA(carry, syms_k):
        return maxplus_matmul(carry, _select_step_mats(syms_k, M_flat, K)), None

    P, _ = jax.lax.scan(passA, eye_b, steps2)  # [nb, K, K]
    incl, offs = scan_block_products(P)
    return incl, offs, incl[-1]


def _enter_vectors(v_enter0: jnp.ndarray, incl: jnp.ndarray, offs=None):
    """Exact entering score vector per block from the exclusive prefix.

    Returns NORMALIZED per-block entering vectors (max 0 — the f32-range
    guard, see :func:`nrm_maxplus`) plus, when ``offs`` (the prefix-scan
    offsets) is given, the per-block true-score offsets that were dropped.
    """
    K = v_enter0.shape[0]
    excl = jnp.concatenate(
        [_identity_logmat(K)[None] + v_enter0[None, :, None] * 0.0, incl[:-1]], axis=0
    )
    v = jnp.max(v_enter0[None, :, None] + excl, axis=1)  # [nb, K]
    vmax = jnp.max(v, axis=-1)
    v = jnp.maximum(v - vmax[:, None], LOG_ZERO)
    if offs is None:
        return v
    excl_off = jnp.concatenate([jnp.zeros_like(offs[:1]), offs[:-1]])
    return v, vmax + excl_off


def _pass_backpointers(params: HmmParams, v_enter: jnp.ndarray, steps2: jnp.ndarray, prev0=None):
    """Pass B: re-scan with true entering vectors; emit int8 backpointers and
    carry the within-block exit->entry composition E (E'[j] = E[bp[j]]).

    Returns (delta_exit [nb, K], F [nb, K], bps [bk, nb, K] int8).
    """
    K = params.n_states
    M_ext, _ = _step_tables(params)
    M_flat = M_ext.reshape(M_ext.shape[0], K * K)
    nb = steps2.shape[1]
    E0 = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32), (nb, K)) + v_enter.astype(jnp.int32) * 0

    def passB(carry, syms_k):
        delta, E = carry
        scores = delta[:, :, None] + _select_step_mats(syms_k, M_flat, K)  # [nb, from, to]
        bp = jnp.argmax(scores, axis=1)  # [nb, K_to]
        new_delta = jnp.max(scores, axis=1)
        oh_bp = jax.nn.one_hot(bp, K, dtype=delta.dtype)  # [nb, to, from]
        new_E = jnp.einsum("njk,nk->nj", oh_bp, E.astype(delta.dtype)).astype(jnp.int32)
        return (new_delta, new_E), bp.astype(jnp.int8)

    (delta_blocks, F), bps = jax.lax.scan(passB, (v_enter, E0), steps2)
    return delta_blocks, F, bps


def _suffix_compositions(F: jnp.ndarray) -> jnp.ndarray:
    """Gsuf[b] = F_b ∘ F_{b+1} ∘ ... (later-in-time tables applied first).

    associative_scan(reverse=True) is flip-scan-flip: the combine sees its
    operands in flipped positions, so flip them back inside the lambda.
    """
    return jax.lax.associative_scan(lambda a, b: _compose(b, a), F, axis=0, reverse=True)


def _pass_backtrace(bps: jnp.ndarray, exits: jnp.ndarray) -> jnp.ndarray:
    """Pass C: walk backpointers carrying one state per lane, emitting the
    state after each step (one-hot dot instead of gather).  Returns [S]."""
    K = bps.shape[-1]

    def passC(state, bp_k):
        oh = jax.nn.one_hot(state, K, dtype=jnp.float32)
        prev = jnp.einsum("nk,nk->n", oh, bp_k.astype(jnp.float32)).astype(jnp.int32)
        return prev, state

    _, path2 = jax.lax.scan(passC, exits, bps, reverse=True)  # [bk, nb]
    return path2.T.reshape(-1)  # global step order


def get_passes(engine: str):
    """Resolve a block-pass engine triple (products, backpointers, backtrace).

    'xla' — the lax.scan implementations in this module; 'pallas' — the fused
    TPU kernels (ops.viterbi_pallas; imported lazily to avoid a cycle);
    'onehot' — the reduced 2x2 kernels for one-hot-emission models
    (ops.viterbi_onehot; requires the caller to thread prev0).  The
    backpointer blob returned by backpointers() is engine-specific and flows
    opaquely into backtrace().
    """
    if engine == "xla":
        return _pass_products, _pass_backpointers, _pass_backtrace
    if engine == "pallas":
        from cpgisland_tpu.ops import viterbi_pallas

        return (
            viterbi_pallas.pass_products,
            viterbi_pallas.pass_backpointers,
            viterbi_pallas.pass_backtrace,
        )
    if engine == "onehot":
        from cpgisland_tpu.ops import viterbi_onehot

        return (
            viterbi_onehot.pass_products,
            viterbi_onehot.pass_backpointers,
            viterbi_onehot.pass_backtrace,
        )
    raise ValueError(f"unknown engine {engine!r}; expected xla|pallas|onehot")


def _block_passes(
    params: HmmParams,
    v_enter0: jnp.ndarray,
    steps: jnp.ndarray,
    block_size: int,
    anchor: jnp.ndarray | None = None,
    engine: str = "xla",
    prev0: jnp.ndarray | None = None,
    resets: jnp.ndarray | None = None,
    pre=None,
    want_scores: bool = False,
) -> BlockDecode:
    """Run the three block passes over ``steps`` (transition symbols), with
    ``v_enter0`` the score vector entering the first step.

    steps: [S] int32, PAD values allowed (identity steps); S must be a positive
    multiple of block_size (caller pads).  path[k] = state after step k,
    anchored at the segment end to ``anchor`` if given (sequence-parallel
    callers pass the globally-stitched exit state), else to the local argmax.
    ``resets`` ([bk, nb] bool; onehot engine only): marks steps that RESTART
    the chain at a new record's initial scores — the flat batch decoder
    (viterbi_onehot.decode_batch_flat).
    ``want_scores`` (onehot engine only): run the score-threading
    backpointers variant and populate ``enter_offs``/``dmax2`` so callers
    can read true chain maxes at arbitrary steps (the flat score route).
    """
    _pass_products, _pass_backpointers, _pass_backtrace = get_passes(engine)
    nb = steps.shape[0] // block_size
    steps2 = steps.reshape(nb, block_size).T  # [bk, nb] — scan over bk

    extra = {}
    if resets is not None:
        if engine != "onehot":
            raise ValueError("record-reset steps need the onehot engine")
        extra = {"resets": resets}
    if pre is not None:
        # A prepared symbol-only pair stream (viterbi_onehot.prepare_pairs)
        # shared by both pair-consuming passes — outside a jit the inline
        # streams are separate dispatches that CSE cannot merge.
        if engine != "onehot":
            raise ValueError("prepared pair streams need the onehot engine")
        extra["pre"] = pre
    incl, offs, total = _pass_products(params, steps2, prev0, **extra)
    v_enter, enter_offs = _enter_vectors(v_enter0, incl, offs)
    dmax2 = None
    if want_scores:
        if engine != "onehot":
            raise ValueError("want_scores needs the onehot engine")
        from cpgisland_tpu.ops import viterbi_onehot

        delta_blocks, F, bps, dmax2 = viterbi_onehot.pass_backpointers_scores(
            params, v_enter, steps2, prev0, **extra
        )
    else:
        delta_blocks, F, bps = _pass_backpointers(
            params, v_enter, steps2, prev0, **extra
        )
    delta_exit = delta_blocks[-1]

    s_exit = jnp.argmax(delta_exit).astype(jnp.int32) if anchor is None else anchor
    Gsuf = _suffix_compositions(F)
    # exits[b] for b < nb-1 = (F_{b+1} ∘ ... ∘ F_{nb-1})[s_exit].
    exits = jnp.concatenate([Gsuf[1:, :][:, s_exit], s_exit[None]])
    path = _pass_backtrace(bps, exits)

    # Block b's delta rides the normalized v_enter[b]; the dropped true-score
    # offset for the exit block is enter_offs[-1].
    return BlockDecode(
        path=path, delta_exit=delta_exit, total=total, ftable=Gsuf[0],
        score_offset=enter_offs[-1],
        enter_offs=enter_offs if want_scores else None, dmax2=dmax2,
    )


@partial(jax.jit, static_argnames=("block_size", "return_score", "engine"))
def viterbi_parallel(
    params: HmmParams,
    obs: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK,
    return_score: bool = True,
    engine: str = "xla",
):
    """Exact Viterbi path via the blockwise parallel scan (single device).

    Drop-in equivalent of ops.viterbi.viterbi; PAD symbols (>= n_symbols) are
    pass-through identity steps, so it also subsumes viterbi_padded.  The
    ``engine`` selects the block-pass lowering (see :func:`get_passes`); the
    dense engines produce identical paths (same rounding, same tie-breaking).
    Caveat: engine="onehot" additionally requires obs[0] < n_symbols (a PAD
    FIRST symbol has no entry group for the reduced chain; results are then
    deterministic but approximate).  Host-level entry points
    (parallel.decode, the pipeline) demote such records to a dense engine
    automatically — only direct jitted calls can reach the caveat.
    """
    _, emit_ext = _step_tables(params)
    obs = obs.astype(jnp.int32)
    T = obs.shape[0]
    pad_sym = params.n_symbols
    obs_c = jnp.minimum(obs, pad_sym)

    v0 = params.log_pi + emit_ext[obs_c[0]]
    if T == 1:
        path = jnp.argmax(v0).astype(jnp.int32)[None]
        return (path, jnp.max(v0)) if return_score else path

    S = T - 1
    bk = min(block_size, max(8, S))
    nb = -(-S // bk)
    padded = jnp.concatenate([obs_c[1:], jnp.full(nb * bk - S, pad_sym, jnp.int32)])
    dec = _block_passes(params, v0, padded, bk, engine=engine, prev0=obs_c[0])

    # path[0] (time 0) = entry state of the whole segment.
    s0 = dec.ftable[jnp.argmax(dec.delta_exit)]
    path = jnp.concatenate([s0[None], dec.path[:S]])
    if not return_score:
        return path
    return path, jnp.max(dec.delta_exit) + dec.score_offset


def viterbi_parallel_batch(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    block_size=None,
    return_score: bool = True,
    engine: str = "xla",
    vmap_records: bool = False,
):
    """Batched decode of a [N, T] batch of padded chunks.

    ``block_size=None`` resolves host-side HERE, before the jit boundary:
    the flat onehot route consults the graftune winner table (fresh
    applied ``flat.block`` winner -> table value, else the hard-coded
    DEFAULT_BLOCK bit for bit); every other route keeps DEFAULT_BLOCK.
    Explicit values pass through untouched, and the traced twin below
    only ever sees a concrete static int (a trace-time table lookup
    would freeze pre-sweep knobs into the jit cache).
    """
    if block_size is None:
        if engine == "onehot" and not vmap_records:
            from cpgisland_tpu import tune

            block_size = tune.default_block_size(
                scores=return_score, legacy=DEFAULT_BLOCK
            )
        else:
            block_size = DEFAULT_BLOCK
    return _viterbi_parallel_batch_traced(
        params, chunks, lengths, block_size=int(block_size),
        return_score=return_score, engine=engine,
        vmap_records=vmap_records,
    )


@partial(
    jax.jit,
    static_argnames=("block_size", "return_score", "engine", "vmap_records"),
)
def _viterbi_parallel_batch_traced(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK,
    return_score: bool = True,
    engine: str = "xla",
    vmap_records: bool = False,
):
    """The compiled body of :func:`viterbi_parallel_batch`.

    Keeps viterbi_batch's masking contract: positions >= lengths[i] are
    force-masked to the PAD sentinel, so arbitrary tail content (zero-filled
    buffers etc.) cannot leak into the global argmax.

    Onehot batches run FLAT (viterbi_onehot.decode_batch_flat): records
    concatenate into one stream with rank-one RESET steps at record
    boundaries, so every kernel runs at single-stream occupancy —
    vmap-of-pallas loads batch-wide VMEM slabs and measured 1004 vs 1635
    Msym/s at the same total (r5; block sizes >= 8192 fail to compile under
    vmap).  Since r6 ``return_score=True`` stays on the flat route too:
    per-record scores come EXACTLY off the flat stream (the reset
    constants telescope — decode_batch_flat's score path), so the vmap
    lowering and its ~4 MiB-per-record scoped-VMEM bound are reachable
    only by the explicit ``vmap_records=True`` opt-in — kept for parity
    testing, as the dense engines' only batch lowering, and for score
    consumers needing per-RECORD-magnitude f32 precision deep into a
    large batch (the flat route's scores quantize at the accumulated
    STREAM magnitude; see decode_batch_flat's precision caveat).
    Batches of larger records should decode per record through
    viterbi_parallel / viterbi_sharded_spans, which have no VMEM bound.
    """
    T = chunks.shape[1]
    if engine == "onehot" and not vmap_records and T >= 2:
        from cpgisland_tpu.ops.viterbi_onehot import decode_batch_flat

        return decode_batch_flat(
            params, chunks, lengths, block_size=block_size,
            return_score=return_score,
        )
    if (
        engine == "onehot" and vmap_records
        and jax.default_backend() == "tpu"
    ):
        # The vmap-of-pallas opt-in loads batch-wide VMEM slabs; graftmem's
        # model rejects the block sizes that failed scoped-VMEM compile on
        # chip (bk >= 8192, CLAUDE.md r5) with actionable numbers instead.
        # Onehot-only: the dense engines' vmap working set is a different
        # kernel family the model does not claim to describe.
        from cpgisland_tpu import obs
        from cpgisland_tpu.analysis import memmodel

        f = memmodel.feasible("decode.vmap.onehot", block_size=block_size)
        if not f.ok:
            obs.event(
                "mem_reject", site="decode_vmap_block",
                block_size=block_size, predicted_bytes=f.total,
                vmem_limit_bytes=f.limit,
                max_fit_block=memmodel.max_vmap_block(),
            )
            raise ValueError(
                f"viterbi_parallel_batch(vmap_records=True): "
                f"block_size={block_size} does not fit the vmap route's "
                f"VMEM model — {f.reason}; largest feasible block is "
                f"{memmodel.max_vmap_block()} (or use the flat route)"
            )
    chunks = jnp.where(
        jnp.arange(T)[None, :] >= lengths[:, None],
        params.n_symbols,
        chunks.astype(jnp.int32),
    )
    fn = lambda o: viterbi_parallel(
        params, o, block_size=block_size, return_score=return_score, engine=engine
    )
    return jax.vmap(fn)(chunks)
