"""Pallas TPU kernels for the forward-backward E-step (rescaled numerics).

The XLA E-step (ops.forward_backward._chunk_stats_rescaled) vmaps a
[K]-carry `lax.scan` over the chunk batch; with K=8 riding the minor dimension
that leaves the VPU lanes mostly idle.  These kernels put the chunk batch on
the 128-wide lane dimension (one chunk per lane, like ops.viterbi_pallas) and
fuse the per-step emission select, normalize, and statistics accumulation:

- **forward kernel** — per t-tile: alpha recurrence with DEFERRED Rabiner
  rescaling (stored v_t = alpha-hat_t * c_t; each step divides by the
  previous step's sum, so the sum computes off the sequential critical
  path); streams only the v's [T, K, lanes] to HBM (32 B/symbol — far under
  HBM bandwidth at these op intensities; no checkpoint/recompute needed at
  K=8).  The scale factors come back as time-parallel row sums in JAX.
- **backward kernel** — row-tiled reverse walk over t-tiles (reversed
  index_map), storing ONLY the scaled beta vectors; the o_{t+1}/c_{t+1} each
  step needs arrive as TIME-SHIFTED inputs (steps_next/cs_next, one cheap
  XLA pass) so every read is an aligned static-offset tile and the emission
  select + 1/c reciprocals hoist off the sequential chain — this took the
  backward from ~3x the forward's cost to parity.
- **stats kernel** — ONE fused streaming pass over the stored alphas/betas
  producing per-lane [K,K]/[K,S] count partials, loglik included
  (_stats_kernel).  It has no sequential dependency (each position's work is
  independent), so unlike the old in-backward accumulation it is
  throughput-bound; replacing the XLA einsum assembly with it cut the
  E-step ~30%.

Grid order note: the t-tile dimension is the innermost grid axis, so each
lane-tile's t-tiles run consecutively and VMEM scratch carries state between
them (the canonical multi-pass reduction pattern).

Semantics match the rescaled XLA path to float tolerance (same masking rules:
invalid steps are identity, empty chunks contribute exactly-zero statistics).
The reference equivalent is Mahout's Hadoop Baum-Welch mapper
(CpGIslandFinder.java:200-201, the "rescaling" numerics at :92).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.ops.viterbi_pallas import MAX_PACK_STATES, _interpret, _vspec

LANE_TILE = 128
DEFAULT_T_TILE = 512
# Whole-sequence lane length for SMALL inputs; pick_lane_T upgrades big
# ones.  Any multiple of the t-tile compiles now that the products kernel
# streams t in tiles.  Shared by single-device + shard_map.  The
# whole-sequence EM and posterior throughputs are PUBLISHED, enforced
# figures — see the em-seq / posterior rows in BASELINE.md (bench.py;
# tests/test_published_numbers.py keeps them honest).
DEFAULT_LANE_T = 8192


# Relative per-padded-symbol kernel rates by lane length, measured on v5e
# (r4 re-sweep at 64 Mi — the r3 "16384 no better" note predated the
# tiled-products/bwd-conf kernel reshapes): whole-sequence E-step
# 354 -> 433 -> 452 Msym/s/iter at 8192/16384/32768 (65536 within noise of
# 32768; 131072 regressed), fused posterior 520 -> 712 -> 726.
_LANE_RATE = {8192: 1.0, 16384: 1.25, 32768: 1.30}

# The reduced one-hot kernels (ops.fb_onehot) keep gaining from longer
# serial chains well past the dense knee (their per-step work and VMEM
# footprint are ~4x smaller): fused posterior 507 -> 908 -> 1162 -> 1224
# Msym/s at 8192/16384/32768/65536, ~+4% more at 131072.  The 131072 entry
# became possible when the seq-stats kernel replaced the XLA assembly on
# TPU (the assembly failed to compile there); the off-TPU XLA twins have
# no Mosaic constraint.
_LANE_RATE_ONEHOT = {
    8192: 1.0, 16384: 1.79, 32768: 2.29, 65536: 2.41, 131072: 2.50,
}


@functools.lru_cache(maxsize=None)
def _feasible_lane_rates(
    onehot: bool, long_lanes: bool, _table_gen: int = 0
) -> dict:
    """Rate-table candidates filtered by graftmem's static memory model
    (Layer 5).  The filter depends only on the flag pair — never on the
    input size — so it computes once per (onehot, long_lanes): the
    t-tiled chain kernels are lane_T-free, but the plain reduced path
    must also run the exact-seq XLA stats assembly, whose scoped-VMEM
    model bans 131072 — the same cap this table shipped as a hard-coded
    `k <= 65536` filter before graftmem (routing parity pinned by
    tests/test_graftmem.py).  ``_table_gen`` folds the graftune tuning-
    table generation into the cache key: the filter's OUTPUT does not
    depend on the table today (winner consultation happens per call in
    pick_lane_T, uncached), but any future table-derived candidate set
    (e.g. a sweep-updated rate table) inherits correct in-process
    ``--update-tune`` invalidation from this key instead of silently
    serving pre-sweep results for the rest of the session."""
    from cpgisland_tpu.analysis import memmodel

    rates = _LANE_RATE_ONEHOT if onehot else _LANE_RATE
    return {
        k: v for k, v in rates.items()
        if memmodel.lane_feasible(k, onehot=onehot, long_lanes=long_lanes)
    }


def legacy_lane_T(
    n: int, onehot: bool = False, long_lanes: bool = False,
    rates: Optional[dict] = None,
) -> int:
    """The hard-coded lane choice (rate-table cost minimization) — what
    :func:`pick_lane_T` returns whenever no fresh tuned winner matches,
    and the sweep driver's baseline arm.

    Minimizes estimated pass time = padded work / measured lane rate: the
    input pads to a full 128-lane grid of ``lane_T``-long lanes
    (_lane_layout), so a long lane just past a grid boundary can cost more
    in padding than its faster rate buys — gating on raw size alone made
    inputs just above each boundary ~20% slower than the short-lane
    default.  Ties prefer the longer lane."""
    if rates is None:
        from cpgisland_tpu import tune

        rates = _feasible_lane_rates(onehot, long_lanes, tune.generation())

    def est_cost(lt: int) -> float:
        n_lanes = -(-max(n, 1) // lt)
        grid = -(-n_lanes // LANE_TILE) * LANE_TILE
        return grid * lt / rates[lt]

    # Candidates ARE the rate table (one source of truth for the next
    # re-sweep); sorted longest-first so cost ties prefer the longer lane.
    return min(sorted(rates, reverse=True), key=est_cost)


def pick_lane_T(n: int, onehot: bool = False, long_lanes: bool = False) -> int:
    """Lane length for an ``n``-symbol (per-shard) input.

    Consults the graftune winner table first: a FRESH applied winner for
    this (path, platform, pow2 bucket) — fingerprint-current against
    COSTS.json and inside the feasible rate table — wins; anything else
    (absent, stale, fingerprint-drifted, out-of-domain) falls back
    BIT-FOR-BIT to :func:`legacy_lane_T`'s rate-table minimization
    (routing parity pinned by tests/test_graftune.py).  ``onehot``
    selects the reduced kernels' rate table (different knee — see
    _LANE_RATE_ONEHOT); ``long_lanes`` additionally admits the 131072
    entry, which is safe ONLY for paths that stay on reduced kernels end
    to end (the seq-stats kernel / the conf kernel) — the XLA assemblies
    over [Tp, K, NL] streams fail to remote-compile at that lane length,
    so callers opt in exactly where the kernelized path is guaranteed.
    """
    from cpgisland_tpu import tune

    rates = _feasible_lane_rates(onehot, long_lanes, tune.generation())
    tuned = tune.tuned_lane_T(
        n, onehot=onehot, long_lanes=long_lanes, candidates=tuple(rates)
    )
    lane_T = tuned if tuned is not None else legacy_lane_T(
        n, onehot, long_lanes, rates=rates
    )
    from cpgisland_tpu import obs

    # n is bucketed to its power-of-two class for the dedupe key: raw record
    # lengths are near-unique on real assemblies, and a distinct payload per
    # length would defeat the dedupe (one JSONL line per scaffold).
    obs.event(
        "lane_geometry", _dedupe=True,
        n_pow2=1 << max(int(n) - 1, 0).bit_length(), lane_T=lane_T,
        onehot=onehot, long_lanes=long_lanes, tuned=tuned is not None,
    )
    return lane_T


def supports(params: HmmParams) -> bool:
    # No packing constraint here, but keep the same "small state space on
    # sublanes" envelope as the decode kernels.
    return params.n_states <= MAX_PACK_STATES


def _emit_sel(B, syms, K, S):
    """Bsel[k, :] = B[k, syms[:]] via an unrolled compare-select tree."""
    out = jnp.zeros((K, syms.shape[-1]), jnp.float32)
    for s in range(S):
        out = jnp.where((syms == s)[None, :], B[:, s][:, None], out)
    return out


def _emit_sel_cols(B, syms, K):
    """Bsel[t, k, n] = B[k, syms[t, n]] — the [Tp, NL] batch variant."""
    out = jnp.zeros((syms.shape[0], K, syms.shape[1]), jnp.float32)
    for s in range(B.shape[1]):
        out = jnp.where((syms == s)[:, None, :], B[:, s][None, :, None], out)
    return out


ROW_TILE = 8  # sublane count of an (8, 128) f32/i32 VMEM tile


def _fwd_kernel(steps_ref, lens_ref, alpha0raw_ref, A_ref, B_ref,
                alphas_ref, carry_ref, *, K, S, Tt):
    # Row-tiled walk: dynamic sublane offsets into (8,128)-tiled VMEM must be
    # 8-aligned for Mosaic's fast path (see the ROW_TILE note in
    # viterbi_pallas.py), so steps move as aligned [8, lt] tiles with the
    # per-row recurrence unrolled — the per-step misaligned row load/store
    # was >3x the arithmetic cost of the recurrence itself.
    #
    # Deferred normalization: the stored value is v_t = raw_t / sum(v_{t-1}),
    # i.e. alpha-hat_t SCALED BY the Rabiner factor c_t (v_0 = pi*B[:,o_0]
    # unnormalized, so sum(v_0) = c_0; inductively sum(v_t) = c_t).  Values
    # stay O(1), the JAX assembly recovers cs as plain row sums, and the
    # step's own sum leaves the sequential dependency chain: 1/sum(v_{t-1})
    # computes concurrently with step t's multiply-add tree instead of
    # serializing normalize -> next step.
    j = pl.program_id(1)
    A = A_ref[:, :]
    B = B_ref[:, :]
    lens = lens_ref[0, :]
    v_in = jnp.where(j == 0, alpha0raw_ref[:, :], carry_ref[:, :])

    def body(tile_i, v):
        base = tile_i * ROW_TILE
        o_tile = steps_ref[pl.ds(base, ROW_TILE), :]  # aligned [8, lt]
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            o_t = o_tile[r, :]
            v_t = t < lens
            raw = jnp.sum(v[:, None, :] * A[:, :, None], axis=0) * _emit_sel(B, o_t, K, S)
            new = raw * (1.0 / jnp.sum(v, axis=0))
            new = jnp.where(v_t[None, :], new, v)
            # t == 0 has no incoming transition: v_0 is the precomputed init.
            new = jnp.where(t == 0, alpha0raw_ref[:, :], new)
            alphas_ref[base + r, :, :] = new  # [K, lt] = one full tile row
            v = new
        return v

    carry_ref[:, :] = jax.lax.fori_loop(0, Tt // ROW_TILE, body, v_in)


def _prod_kernel(steps_ref, A_ref, B_ref, out_ref, C_scr, *, K, S, bk):
    """(+,x) product of each lane's step matrices -> [K*K, LT], normalized.

    The probability-space twin of viterbi_pallas._products_kernel: C carried
    as a tuple of K rank-2 rows (C[i] is [K, LT], row i of the product — the
    Mosaic rank-2 constraint, see _emit_sel there).  Products shrink ~e^-1.3
    per step, so every ROW_TILE steps the whole matrix renormalizes by one
    per-lane scalar (relative row scales preserved); only DIRECTIONS leave
    this kernel — the boundary-message consumers renormalize anyway.

    The t dimension is tiled over the inner grid axis (``bk`` steps per
    tile), with the running product carried in VMEM scratch between tiles —
    the full-lane input block of the untiled version capped lane_T at 8192
    (a 16384-lane block is 8 MiB, 16 MiB double-buffered, the whole VMEM).
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = steps_ref.shape[1]
    A = A_ref[:, :]
    B = B_ref[:, :]

    @pl.when(j == 0)
    def _init():
        for i in range(K):
            C_scr[i * K : (i + 1) * K, :] = jnp.broadcast_to(
                (jnp.arange(K) == i).astype(jnp.float32)[:, None], (K, lt)
            )

    C0 = tuple(C_scr[i * K : (i + 1) * K, :] for i in range(K))

    def body(c, C):
        tile = steps_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]  # aligned [8, LT]
        for r in range(ROW_TILE):
            syms = tile[r : r + 1, :]  # [1, LT]
            is_pad = syms >= S
            Bsel = _emit_sel(B, syms[0, :], K, S)  # [K, LT]
            # M_m[j, lane] = A[m, j] * B[j, sym]; identity column for PAD.
            Ms = tuple(
                jnp.where(
                    is_pad,
                    (jnp.arange(K) == m).astype(jnp.float32)[:, None],
                    A[m : m + 1, :].T * Bsel,
                )
                for m in range(K)
            )
            C = tuple(
                sum(Ci[m : m + 1, :] * Ms[m] for m in range(K)) for Ci in C
            )
        tot = sum(jnp.sum(Ci, axis=0, keepdims=True) for Ci in C)  # [1, LT]
        inv = 1.0 / jnp.maximum(tot, 1e-30)
        return tuple(Ci * inv for Ci in C)

    C = jax.lax.fori_loop(0, bk // ROW_TILE, body, C0)
    for i in range(K):
        C_scr[i * K : (i + 1) * K, :] = C[i]

    @pl.when(j == n_t - 1)
    def _flush():
        for i in range(K):
            out_ref[i * K : (i + 1) * K, :] = C_scr[i * K : (i + 1) * K, :]


def _bwd_kernel(steps_next_ref, lens_ref, A_ref, B_ref, cs_next_ref, beta0_ref,
                betas_ref,
                beta_scr,
                *, K, S, Tt, T):
    """Row-tiled reverse t-walk storing ONLY the scaled beta vectors.

    The count tensors are NOT accumulated here (an earlier version did and
    spent ~60 vreg ops/step on xi/gamma outer products inside the sequential
    loop) — the chunked path reduces them in the separate throughput-bound
    _stats_kernel pass; the whole-sequence path still uses the time-parallel
    XLA contractions in _seq_stats_core.

    The inputs are TIME-SHIFTED in JAX (steps_next[t] = o_{t+1},
    cs_next[t] = c_{t+1}) so every row the recurrence needs lives at its own
    aligned tile position: no per-step dynamic sublane reads (which cost
    ~3x the recurrence arithmetic) and no cross-row carries (whose 8-row
    reversed unroll hit a Mosaic compiler abort).  The per-tile emission
    select and 1/c reciprocals also hoist out of the sequential chain —
    per-step work is one multiply, the K-term contraction, and two selects.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    A = A_ref[:, :]
    B = B_ref[:, :]
    lens = lens_ref[0, :]
    t0 = (n_t - 1 - j) * Tt

    @pl.when(j == 0)
    def _init():
        # Per-lane entering beta: ones for independent chunks, the suffix
        # boundary message for lanes continuing a longer sequence.
        beta_scr[:, :] = beta0_ref[:, :]

    def body(tile_rev, beta_next):
        # (count-1-i) * ROW_TILE, kept as a single multiply-by-8 so Mosaic's
        # alignment prover accepts the dynamic sublane offset at any lane
        # width (the equivalent Tt-8-i*8 form fails to prove at lt=256).
        base = (Tt // ROW_TILE - 1 - tile_rev) * ROW_TILE
        on_tile = steps_next_ref[pl.ds(base, ROW_TILE), :]  # aligned [8, lt]
        cn_tile = cs_next_ref[pl.ds(base, ROW_TILE), :]
        # Off-chain per-tile precompute: w_scale[r] = B[:, o_{t+1}] / c_{t+1}
        # for all 8 rows — vectorized, independent of the beta carry.
        inv_cn = 1.0 / cn_tile  # [8, lt]
        wscale = tuple(
            _emit_sel(B, on_tile[r, :], K, S) * inv_cn[r, :][None, :]
            for r in range(ROW_TILE)
        )
        for rr in range(ROW_TILE):
            r = ROW_TILE - 1 - rr
            t = t0 + base + r
            # beta_{T-1} = 1 (the init); the recurrence covers t <= T-2.
            active = t <= T - 2
            v_next = (t + 1) < lens
            w = wscale[r] * beta_next  # [K, lt]
            beta_t = jnp.sum(A[:, :, None] * w[None, :, :], axis=1)
            beta_t = jnp.where((active & v_next)[None, :], beta_t, beta_next)
            betas_ref[base + r, :, :] = beta_t
            beta_next = beta_t
        return beta_next

    beta_scr[:, :] = jax.lax.fori_loop(0, Tt // ROW_TILE, body, beta_scr[:, :])


def _bwd_conf_kernel(steps_next_ref, lens_ref, A_ref, B_ref, cs_next_ref,
                     beta0_ref, alphas_ref, mask_ref,
                     conf_ref,
                     beta_scr,
                     *, K, S, Tt, T):
    """The backward walk EMITTING island confidence instead of beta streams.

    The posterior path's hot variant of _bwd_kernel: betas never reach HBM —
    each step reads the aligned alphas tile (off the sequential chain, like
    the time-shifted inputs) and writes one float per position,
    conf[t] = sum_isl(alpha_t * beta_t) / sum(alpha_t * beta_t).  The conf
    math hangs OFF the beta recurrence (nothing feeds the next step), so it
    pipelines against the chain; HBM traffic drops from write-32 + read-64
    + write-4 B/symbol (betas out, XLA assembly in) to read-32 + write-4.
    Scale-free: the stored alphas carry v_t = alpha-hat_t * c_t, and any
    per-position scale cancels in the ratio.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    A = A_ref[:, :]
    B = B_ref[:, :]
    lens = lens_ref[0, :]
    mask = mask_ref[:, :]  # [K, 1] island indicator
    t0 = (n_t - 1 - j) * Tt

    @pl.when(j == 0)
    def _init():
        beta_scr[:, :] = beta0_ref[:, :]

    def body(tile_rev, beta_next):
        base = (Tt // ROW_TILE - 1 - tile_rev) * ROW_TILE
        on_tile = steps_next_ref[pl.ds(base, ROW_TILE), :]  # aligned [8, lt]
        cn_tile = cs_next_ref[pl.ds(base, ROW_TILE), :]
        inv_cn = 1.0 / cn_tile  # [8, lt]
        wscale = tuple(
            _emit_sel(B, on_tile[r, :], K, S) * inv_cn[r, :][None, :]
            for r in range(ROW_TILE)
        )
        conf_rows = [None] * ROW_TILE
        for rr in range(ROW_TILE):
            r = ROW_TILE - 1 - rr
            t = t0 + base + r
            active = t <= T - 2
            v_next = (t + 1) < lens
            w = wscale[r] * beta_next  # [K, lt]
            beta_t = jnp.sum(A[:, :, None] * w[None, :, :], axis=1)
            beta_t = jnp.where((active & v_next)[None, :], beta_t, beta_next)
            a_row = alphas_ref[base + r, :, :]  # [K, lt] aligned tile row
            g = a_row * beta_t
            tot = jnp.sum(g, axis=0, keepdims=True)
            isl = jnp.sum(g * mask, axis=0, keepdims=True)
            valid = (t < lens)[None, :]
            conf_rows[r] = jnp.where(
                valid, isl * (1.0 / jnp.maximum(tot, 1e-30)), 0.0
            )
            beta_next = beta_t
        conf_ref[pl.ds(base, ROW_TILE), :] = jnp.concatenate(conf_rows, axis=0)
        return beta_next

    beta_scr[:, :] = jax.lax.fori_loop(0, Tt // ROW_TILE, body, beta_scr[:, :])


def _fb_lane_tile(NL: int) -> int:
    """Lanes per kernel instance: 2 vregs wide when the (already 128-padded)
    lane count allows — the wider tile interleaves two independent dependency
    chains per step and measured ~20% faster on v5e; 512 blows VMEM."""
    return 256 if NL % 256 == 0 else LANE_TILE


def _run_fb_kernels(A, B, steps2, lens2, a0_raw, beta0, K, S, Tt, T,
                    conf_mask=None):
    """The forward + backward kernel pair over a [Tp, NL] lane layout.

    a0_raw: [K, NL] per-lane UNnormalized v_0 (sum = that position's c);
    beta0: [K, NL] per-lane entering beta (ones for independent chunks,
    suffix boundary messages for lanes of one long sequence).
    Returns (alphas [Tp,K,NL] with v_t = alpha-hat_t * c_t, cs [Tp,NL],
    betas [Tp,K,NL]) — or, with ``conf_mask`` ([K] island indicator), the
    third element is instead the per-position island confidence [Tp, NL]
    from the fused _bwd_conf_kernel (betas never reach HBM).
    """
    Tp, NL = steps2.shape
    n_t = Tp // Tt
    lt = _fb_lane_tile(NL)
    n_lt = NL // lt
    grid = (n_lt, n_t)
    interpret = _interpret()
    mat_spec = _vspec((K, K), lambda i, j: (0, 0))
    emitmat_spec = _vspec((K, S), lambda i, j: (0, 0))
    lane_spec = _vspec((1, lt), lambda i, j: (0, i))
    klane_spec = _vspec((K, lt), lambda i, j: (0, i))
    step_spec = _vspec((Tt, lt), lambda i, j: (j, i))

    (alphas,) = pl.pallas_call(
        functools.partial(_fwd_kernel, K=K, S=S, Tt=Tt),
        grid=grid,
        in_specs=[step_spec, lane_spec, klane_spec, mat_spec, emitmat_spec],
        out_specs=[
            _vspec((Tt, K, lt), lambda i, j: (j, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Tp, K, NL), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((K, lt), jnp.float32)],
        interpret=interpret,
    )(steps2, lens2, a0_raw, A, B)

    # The stored v_t = alpha-hat_t * c_t, so the Rabiner scale factors are
    # plain (time-parallel) row sums — they never sat on the kernel's
    # sequential critical path.
    cs = jnp.sum(alphas, axis=1)  # [Tp, NL]

    # Time-shifted views for the row-tiled backward: o_{t+1} / c_{t+1} land
    # at aligned tile position t, so the kernel does only static-offset tile
    # reads.  One cheap XLA pass (~1 ms at bench shapes) buys the removal of
    # per-step dynamic sublane reads from the 2x-longer sequential walk.
    steps_next = jnp.concatenate([steps2[1:], jnp.zeros((1, NL), steps2.dtype)], axis=0)
    cs_next = jnp.concatenate([cs[1:], jnp.ones((1, NL), cs.dtype)], axis=0)

    # Reversed t-walk: input/output t-blocks indexed by (n_t-1-j).
    rev_step_spec = _vspec((Tt, lt), lambda i, j: (n_t - 1 - j, i))
    if conf_mask is not None:
        (conf,) = pl.pallas_call(
            functools.partial(_bwd_conf_kernel, K=K, S=S, Tt=Tt, T=T),
            grid=grid,
            in_specs=[
                rev_step_spec,
                lane_spec,
                mat_spec,
                emitmat_spec,
                rev_step_spec,
                klane_spec,
                _vspec((Tt, K, lt), lambda i, j: (n_t - 1 - j, 0, i)),
                _vspec((K, 1), lambda i, j: (0, 0)),
            ],
            out_specs=[rev_step_spec],
            out_shape=[jax.ShapeDtypeStruct((Tp, NL), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((K, lt), jnp.float32)],
            interpret=interpret,
        )(
            steps_next, lens2, A, B, cs_next, beta0, alphas,
            conf_mask.astype(jnp.float32).reshape(K, 1),
        )
        return alphas, cs, conf
    (betas,) = pl.pallas_call(
        functools.partial(_bwd_kernel, K=K, S=S, Tt=Tt, T=T),
        grid=grid,
        in_specs=[
            rev_step_spec,
            lane_spec,
            mat_spec,
            emitmat_spec,
            rev_step_spec,
            klane_spec,
        ],
        out_specs=[
            _vspec((Tt, K, lt), lambda i, j: (n_t - 1 - j, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Tp, K, NL), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((K, lt), jnp.float32),
        ],
        interpret=interpret,
    )(steps_next, lens2, A, B, cs_next, beta0)
    return alphas, cs, betas


def _stats_kernel(alphas_ref, betas_ref, steps_ref, lens_ref, B_ref,
                  macc_ref, emit_ref, ll_ref,
                  macc_scr, emit_scr, ll_scr, aprev_scr,
                  *, K, S, Tt):
    """Fused per-lane reduction of the count tensors from the streamed
    alphas/betas — the XLA assembly's einsums/masked-sums as ONE pass.

    No sequential dependency (each row's work is independent given the
    loaded tiles), so unlike the old in-backward accumulation this is
    throughput-, not latency-bound.  Two-level summation keeps f32 error
    down: rows accumulate into register tiles inside the fori carry (<= Tt
    terms), each grid cell adds its total into VMEM scratch (<= n_t terms),
    and the final cross-lane reduction happens as an XLA tree sum.

    Outputs per lane: macc[j*K+k] = sum_t a_hat_{t-1}[j] * w_t[k] (trans
    before the elementwise A), emit[s*K+k] = sum_{t: o_t=s} gamma_t[k],
    ll = sum_t log c_t.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = steps_ref.shape[1]
    B = B_ref[:, :]
    lens = lens_ref[0, :]

    @pl.when(j == 0)
    def _init():
        macc_scr[:, :] = jnp.zeros((K * K, lt), jnp.float32)
        emit_scr[:, :] = jnp.zeros((K * S, lt), jnp.float32)
        ll_scr[:, :] = jnp.zeros((1, lt), jnp.float32)
        # t=0 has no incoming pair (masked below), so the initial a_prev
        # value is never read.
        aprev_scr[:, :] = jnp.zeros((K, lt), jnp.float32)

    def body(tile_i, carry):
        aprev, macc, emit, ll = carry
        base = tile_i * ROW_TILE
        o_tile = steps_ref[pl.ds(base, ROW_TILE), :]
        macc = list(macc)
        emit = list(emit)
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            o_t = o_tile[r, :]
            valid = (t < lens)[None, :]  # [1, lt]
            a_row = alphas_ref[base + r, :, :]  # [K, lt]
            b_row = betas_ref[base + r, :, :]
            cs = jnp.sum(a_row, axis=0, keepdims=True)  # [1, lt]
            inv_cs = 1.0 / jnp.maximum(cs, 1e-30)
            graw = a_row * b_row
            gsum = jnp.sum(graw, axis=0, keepdims=True)
            gamma = jnp.where(
                valid, graw * (1.0 / jnp.maximum(gsum, 1e-30)), 0.0
            )
            for s in range(S):
                emit[s] = emit[s] + jnp.where((o_t == s)[None, :], gamma, 0.0)
            ll = ll + jnp.where(valid, jnp.log(jnp.maximum(cs, 1e-30)), 0.0)
            # Pair (t-1 -> t): w carries B[:, o_t] * beta_t / c_t; a_prev is
            # the previous row's alpha-hat.  t == 0 has no incoming pair.
            w = _emit_sel(B, o_t, K, S) * b_row * inv_cs
            wm = jnp.where(jnp.logical_and(valid, t >= 1), w, 0.0)
            for jj in range(K):
                macc[jj] = macc[jj] + aprev[jj : jj + 1, :] * wm
            aprev = a_row * inv_cs
        return aprev, tuple(macc), tuple(emit), ll

    zero = jnp.zeros((K, lt), jnp.float32)
    carry0 = (
        aprev_scr[:, :],
        tuple(zero for _ in range(K)),
        tuple(zero for _ in range(S)),
        jnp.zeros((1, lt), jnp.float32),
    )
    aprev, macc, emit, ll = jax.lax.fori_loop(0, Tt // ROW_TILE, body, carry0)
    aprev_scr[:, :] = aprev
    for jj in range(K):
        sl = slice(jj * K, (jj + 1) * K)
        macc_scr[sl, :] = macc_scr[sl, :] + macc[jj]
    for s in range(S):
        sl = slice(s * K, (s + 1) * K)
        emit_scr[sl, :] = emit_scr[sl, :] + emit[s]
    ll_scr[:, :] = ll_scr[:, :] + ll

    @pl.when(j == n_t - 1)
    def _flush():
        macc_ref[:, :] = macc_scr[:, :]
        emit_ref[:, :] = emit_scr[:, :]
        ll_ref[:, :] = ll_scr[:, :]


def _run_stats_kernel(B, alphas, betas, steps2, lens2, K, S, Tt):
    """Per-lane count reductions: returns (macc [K*K,NL], emitf [K*S,NL],
    ll [1,NL]).  Fixed 128-lane tiles — the kernel has no serial chain to
    hide latency for, and the alphas+betas input blocks already fill VMEM."""
    Tp, _, NL = alphas.shape
    n_t = Tp // Tt
    lt = LANE_TILE
    grid = (NL // lt, n_t)
    return pl.pallas_call(
        functools.partial(_stats_kernel, K=K, S=S, Tt=Tt),
        grid=grid,
        in_specs=[
            _vspec((Tt, K, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, K, lt), lambda i, j: (j, 0, i)),
            _vspec((Tt, lt), lambda i, j: (j, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
            _vspec((K, S), lambda i, j: (0, 0)),
        ],
        out_specs=[
            _vspec((K * K, lt), lambda i, j: (0, i)),
            _vspec((K * S, lt), lambda i, j: (0, i)),
            _vspec((1, lt), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K * K, NL), jnp.float32),
            jax.ShapeDtypeStruct((K * S, NL), jnp.float32),
            jax.ShapeDtypeStruct((1, NL), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((K * K, lt), jnp.float32),
            pltpu.VMEM((K * S, lt), jnp.float32),
            pltpu.VMEM((1, lt), jnp.float32),
            pltpu.VMEM((K, lt), jnp.float32),
        ],
        interpret=_interpret(),
    )(alphas, betas, steps2, lens2, B)


def _gamma_emit_loglik(alphas, betas, cs, steps2, vmask, S):
    """Shared time-parallel assembly: (gamma, emit, loglik) from the streams.

    gamma_t = normalize(alpha_t * beta_t) at every valid position (the
    stored beta at the last valid position is exactly the entering-beta /
    ones init passed through, so no tail special-casing); emit is S masked
    sums; loglik sums log of the recovered Rabiner factors.
    """
    loglik = jnp.sum(jnp.where(vmask, jnp.log(jnp.maximum(cs, 1e-30)), 0.0))
    graw = alphas * betas  # [Tp, K, NL]
    gamma = graw / jnp.maximum(jnp.sum(graw, axis=1, keepdims=True), 1e-30)
    gamma = jnp.where(vmask[:, None, :], gamma, 0.0)
    emit = jnp.stack(
        [jnp.sum(gamma * (steps2 == s)[:, None, :], axis=(0, 2)) for s in range(S)],
        axis=1,
    )  # [K, S]
    return gamma, emit, loglik


def _pad_axis(x, size, axis, fill):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


def _batch_lane_setup(params: HmmParams, chunks, lengths, t_tile: int,
                      onehot: bool = False, prep=None):
    """Chunked lane layout shared by the batched E-step and the batched
    posterior: one INDEPENDENT record/chunk per lane, pi init, free end.

    The SYMBOL-ONLY half (lane reshapes, PAD-marked selection steps, the
    reduced pair stream) lives in ops.prepared.prepare_chunked — built
    inline here when no ``prep`` is passed, so prepared-vs-inline results
    are bit-identical by construction.  The params-dependent half (tables,
    the unnormalized v_0 init, free-end betas) is always computed here.

    Returns (A, B, pi, prep, a0_raw [K, NL], beta0 [K, NL], valid0 [NL]).
    """
    from cpgisland_tpu.ops import prepared as prep_mod

    K, S = params.n_states, params.n_symbols
    N, T = chunks.shape
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    pi = jnp.exp(params.log_pi).astype(jnp.float32)

    if prep is None:
        prep = prep_mod.prepare_chunked(
            S, chunks, lengths, t_tile=t_tile, onehot=onehot
        )
    else:
        prep_mod.check_chunked(prep, S, N, T, t_tile, onehot)
    steps2, lens2 = prep.steps2, prep.lens2
    valid0 = lens2[0] > 0  # [NL]

    # v_0 in JAX (one position, UNnormalized so sum(v_0) = c_0; the kernel
    # handles t >= 1 with deferred normalization — see _fwd_kernel).
    NL = steps2.shape[1]
    B0 = _emit_sel(B, steps2[0, :], K, S)  # [K, NL]
    a0_raw = jnp.where(valid0[None, :], pi[:, None] * B0, jnp.ones((K, NL)) / K)
    beta0 = jnp.ones((K, NL), jnp.float32)  # independent chunks end free
    return A, B, pi, prep, a0_raw, beta0, valid0


def _conf_path_from_streams(alphas, betas, lens2, island_mask):
    """Shared gamma assembly: (conf2 [Tp, NL], path2 [Tp, NL]) from stored
    alpha/beta streams — the want_path branch of both posterior layouts."""
    Tp = alphas.shape[0]
    vmask = jnp.arange(Tp)[:, None] < lens2
    graw = alphas * betas
    gsum = jnp.maximum(jnp.sum(graw, axis=1), 1e-30)
    gisl = jnp.sum(graw * island_mask[None, :, None], axis=1)
    conf2 = jnp.where(vmask, gisl / gsum, 0.0)
    path2 = jnp.where(vmask, jnp.argmax(graw, axis=1), 0).astype(jnp.int32)
    return conf2, path2


@functools.partial(jax.jit, static_argnames=("t_tile", "onehot", "fused"))
def batch_stats_pallas(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    t_tile: int = DEFAULT_T_TILE,
    onehot: bool = False,
    prepared=None,
    fused: bool = True,
) -> SuffStats:
    """Pallas twin of ops.forward_backward.batch_stats(mode="rescaled").

    chunks: [N, T] (padded), lengths: [N].  Returns batch-summed SuffStats.
    ``onehot`` routes the reduced 2-component kernels (one-hot-emission
    models); for power-of-two n_symbols (the flagship S=4 — the only case
    auto routes here) the count tensors come from the reduced-stream stats
    kernel with NO scatter anywhere, else the streams scatter back to dense
    for the dense stats pass — both exact.  ``prepared`` (an
    ops.prepared.PreparedChunked, passed as an explicit jit argument): the
    symbol-only lane layout + pair stream, amortized across EM iterations
    and pipeline passes; inline prep (same code) otherwise.

    ``fused`` (pow2-S onehot only; static): co-schedule the fwd/bwd chains
    in ONE launch and reduce counts with the z-normalized stats kernel —
    the chunked E-step's serial structure drops from two chain drains to
    ONE (the stats pass has no chain).  The split arm (fused=False, or any
    non-pow2-S / dense routing) keeps the r4 3-kernel path: its cs-scaled
    stats need the split backward's true Rabiner scaling.
    """
    K, S = params.n_states, params.n_symbols
    T = chunks.shape[1]
    A, B, pi, prep, a0_raw, beta0, valid0 = _batch_lane_setup(
        params, chunks, lengths, t_tile, onehot=onehot, prep=prepared
    )
    steps2, lens2, Tt = prep.steps2, prep.lens2, prep.Tt
    if onehot:
        from cpgisland_tpu.ops import fb_onehot

        can_znorm = S & (S - 1) == 0
        use_fused = fused and can_znorm
        al2, cs, b2, esym2 = fb_onehot.run_fb_kernels_onehot(
            params, prep.sel2, jnp.int32(0), lens2, a0_raw, beta0, Tt, T,
            pair_esym=(prep.pair2, prep.esym2, prep.pairn2),
            fused=use_fused,
        )
        gt = fb_onehot._groups(params)
        if can_znorm:
            if use_fused:
                # Z-normalized stats over the fused streams: per-pair xi
                # normalization is invariant to the self-normalized betas;
                # zero enters + an all-zero pair0 mask encode "every lane
                # is an independent record with no incoming t==0 pair".
                NL = al2.shape[2]
                macc, emit_red, ll = fb_onehot.run_seq_stats_onehot(
                    params, al2, b2, prep.pair2, lens2, gt,
                    jnp.zeros((fb_onehot.GROUP, NL), jnp.float32),
                    jnp.zeros((K, NL), jnp.float32),
                    jnp.zeros((1, NL), jnp.float32),
                    Tt,
                )
            else:
                # Reduced-stream stats: 16 B/symbol read instead of 64,
                # dense rows rebuilt in registers — no HBM scatter
                # anywhere.  Needs the split backward's cs-scaled betas;
                # the betas_scale guard makes the fused pairing raise.
                macc, emit_red, ll = fb_onehot.run_stats_onehot(
                    params, al2, b2, prep.pair2, lens2, gt, Tt,
                    betas_scale=fb_onehot.beta_scale_of(fused=use_fused),
                )
            trans, emit, loglik = _assemble_reduced_stats(
                params, A, gt, macc, emit_red, ll
            )
            init_l = jnp.where(
                valid0[None, :], _gamma0_full(al2, b2, gt, esym2, K), 0.0
            )
            return SuffStats(
                init=jnp.sum(init_l, axis=1),
                trans=trans,
                emit=emit,
                loglik=loglik,
                n_seqs=jnp.sum(valid0.astype(jnp.int32)),
            )
        alphas = fb_onehot.scatter_streams(al2, gt, esym2, K)
        betas = fb_onehot.scatter_streams(b2, gt, esym2, K)
    else:
        alphas, cs, betas = _run_fb_kernels(
            A, B, steps2, lens2, a0_raw, beta0, K, S, Tt, T
        )

    # Count-tensor assembly: ONE fused streaming pass over alphas/betas
    # (_stats_kernel) — the XLA-einsum formulation of the same math read the
    # big tensors several times and cost ~30% of the E-step.
    macc, emitf, ll = _run_stats_kernel(B, alphas, betas, steps2, lens2, K, S, Tt)
    trans = A * jnp.sum(macc, axis=1).reshape(K, K)
    emit = jnp.sum(emitf, axis=1).reshape(S, K).T
    loglik = jnp.sum(ll)

    # init = gamma_0 on valid lanes — one row of the posterior, tiny in XLA.
    g0raw = alphas[0] * betas[0]  # [K, NL]
    gamma0 = g0raw / jnp.maximum(jnp.sum(g0raw, axis=0, keepdims=True), 1e-30)
    init_l = jnp.where(valid0[None, :], gamma0, 0.0)

    return SuffStats(
        init=jnp.sum(init_l, axis=1),
        trans=trans,
        emit=emit,
        loglik=loglik,
        n_seqs=jnp.sum(valid0.astype(jnp.int32)),
    )


def _assemble_reduced_stats(params, A, gt, macc, emit_red, ll):
    """(trans, emit, loglik) from the reduced stats kernels' outputs — the
    ONE copy shared by the chunked (batch_stats_pallas) and whole-sequence
    (_seq_stats_core) consumers."""
    K, S = params.n_states, params.n_symbols
    trans = A * jnp.sum(macc, axis=1).reshape(K, K)
    iS = jnp.arange(S)
    emit = (
        jnp.zeros((K, S), jnp.float32)
        .at[gt[:, 0], iS].add(jnp.sum(emit_red[0::2], axis=1))
        .at[gt[:, 1], iS].add(jnp.sum(emit_red[1::2], axis=1))
    )
    return trans, emit, jnp.sum(ll)


def _gamma0_full(al2, b2, gt, esym2, K):
    """Dense gamma at within-lane position 0 from the reduced streams."""
    from cpgisland_tpu.ops import fb_onehot

    g02 = al2[0] * b2[0]  # [GROUP, NL]
    gamma02 = g02 / jnp.maximum(jnp.sum(g02, axis=0, keepdims=True), 1e-30)
    return fb_onehot.scatter_streams(gamma02[None], gt, esym2[0:1], K)[0]


def _norm_rows(v):
    return v / jnp.maximum(jnp.sum(v, axis=-1, keepdims=True), 1e-30)


@functools.partial(
    jax.jit, static_argnames=("lane_T", "t_tile", "onehot", "fused", "one_pass")
)
def seq_stats_pallas(
    params: HmmParams,
    obs: jnp.ndarray,
    length,
    lane_T: int = DEFAULT_LANE_T,
    t_tile: int = DEFAULT_T_TILE,
    onehot: bool = False,
    prepared=None,
    fused: bool = True,
    one_pass: bool = False,
) -> SuffStats:
    """EXACT whole-sequence statistics on one device via the fused kernels.

    The sequence splits into lanes of ``lane_T``; the (+,x) products kernel
    computes each lane's [K, K] transfer operator, an associative scan turns
    those into every lane's exact entering-alpha / exiting-beta boundary
    message (directions — scales are reconstructed scale-free below), and
    the same forward/backward kernels as the chunked E-step run with those
    messages instead of pi/ones.  Statistics equal
    parallel.fb_sharded.seq_stats_sharded (no chunk-independence
    approximation) at fused-kernel speed.

    Working set is ~64 B/symbol of HBM (alphas, betas, and two assembly
    tensors), so per-device sequences up to ~50 M symbols are comfortable —
    chromosome shards on a pod; longer single-device inputs should use the
    chunked path or a mesh.  ``prepared`` (ops.prepared.PreparedSeq): the
    symbol-only lane layout + pair stream, amortized across EM iterations;
    inline prep (same code) otherwise.  ``one_pass`` (static): the r17
    matrix-carried arm — products + fwd/bwd collapse to ONE T-scaling
    pass (pow2-S reduced-stats geometries; others keep the fused arm).
    """
    return _seq_stats_core(
        params, obs, length, lane_T, t_tile, axis=None, onehot=onehot,
        prepared=prepared, fused=fused, one_pass=one_pass,
    )


def _lane_combine(a, b):
    """Normalized probability-space matrix combine (the (+,x) semiring)."""
    m = jnp.einsum("...ij,...jk->...ik", a, b, precision=jax.lax.Precision.HIGHEST)
    return m / jnp.maximum(jnp.sum(m, axis=(-2, -1), keepdims=True), 1e-30)


def _lane_layout(obs, length, S, lane_T, t_tile, mask_first):
    """The ONE copy of the lane packing/masking math (Mosaic-sensitive —
    see the alignment notes in CLAUDE.md): pad/reshape one sequence into
    [NL, lane_T] lanes with PAD-masked selection symbols.

    ``mask_first``: global position 0's step becomes identity (its emission
    is folded into the base direction by the consumer) — traced bool or
    Python bool.  Returns (obs_l, sel_l, lane_lens, obs_flat, Tt, NL).
    """
    T = obs.shape[0]
    length = jnp.asarray(length, jnp.int32)
    nb = -(-T // lane_T)
    NL = -(-nb // LANE_TILE) * LANE_TILE
    Tp_all = NL * lane_T
    if lane_T % ROW_TILE:
        raise ValueError(f"lane_T={lane_T} must be a multiple of {ROW_TILE}")
    # ONE t-tile derivation for all three kernels (products + fwd/bwd).
    Tt = -(-min(t_tile, lane_T) // ROW_TILE) * ROW_TILE
    if lane_T % Tt:
        raise ValueError(
            f"lane_T={lane_T} must be a multiple of the t-tile ({Tt}); a "
            "floor-divided grid would silently skip each lane's tail rows"
        )
    valid_flat = jnp.arange(T) < length
    obs_flat = jnp.where(valid_flat, jnp.minimum(obs.astype(jnp.int32), S - 1), 0)
    # PAD (== S) marks invalid steps for the products kernel (identity).
    sel_flat = jnp.where(valid_flat, obs_flat, S)
    sel_flat = sel_flat.at[0].set(jnp.where(mask_first, S, sel_flat[0]))
    pad = Tp_all - T
    obs_l = jnp.pad(obs_flat, (0, pad)).reshape(NL, lane_T)
    sel_l = jnp.pad(sel_flat, (0, pad), constant_values=S).reshape(NL, lane_T)
    lane_lens = jnp.clip(length - jnp.arange(NL) * lane_T, 0, lane_T)
    return obs_l, sel_l, lane_lens, obs_flat, Tt, NL


def _run_products_kernel(A, B, sel_l, lane_T, Tt, K, S):
    """Per-lane probability-space transfer products via _prod_kernel.

    t tiled over the inner grid axis (scratch-carried running product), so
    lane_T is VMEM-unconstrained — 16 Ki+ lanes stream in t_tile blocks.
    Returns P [NL, K, K] (P[lane, i, m])."""
    NL = sel_l.shape[0]
    (prod_flat,) = pl.pallas_call(
        functools.partial(_prod_kernel, K=K, S=S, bk=Tt),
        grid=(NL // LANE_TILE, lane_T // Tt),
        in_specs=[
            _vspec((Tt, LANE_TILE), lambda i, j: (j, i)),
            _vspec((K, K), lambda i, j: (0, 0)),
            _vspec((K, S), lambda i, j: (0, 0)),
        ],
        out_specs=[_vspec((K * K, LANE_TILE), lambda i, j: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((K * K, NL), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((K * K, LANE_TILE), jnp.float32)],
        interpret=_interpret(),
    )(sel_l.T, A, B)
    return prod_flat.T.reshape(NL, K, K)


def _lane_streams(
    params: HmmParams,
    obs: jnp.ndarray,
    length,
    lane_T: int,
    t_tile: int,
    axis,
    enter_dir=None,
    exit_dir=None,
    first: bool = True,
    conf_mask=None,
    onehot: bool = False,
    prev_sym=None,
    return_reduced: bool = False,
    prepared=None,
    fused: bool = True,
    one_pass: bool = False,
):
    """Shared lane setup for the fused whole-sequence paths: lane transfer
    products -> boundary messages -> forward/backward kernel streams.

    ``fused`` (one-hot engines only): co-schedule the forward and backward
    chains in ONE kernel launch (fb_onehot._oh_fwdbwd_kernel) — the betas
    slot then carries SELF-NORMALIZED per-position directions, which every
    consumer of this path is scale-free in (conf ratio, z-normalized seq
    stats, the scale-free xi assembly, MPM argmax).  fused=False keeps the
    split fwd/bwd passes — the A/B arm (tools/bench_passfusion.py) and the
    r4-shaped 3-pass structure.

    ``one_pass`` (one-hot engines only; no-op otherwise, mirroring
    ``fused``): TRUE one-pass — the ENTRY-FREE matrix-carried kernel
    (fb_onehot._oh_fwdbwd_mat_kernel) runs FIRST, its epilogue rebuilds
    the per-lane transfer totals the standalone products pass used to
    compute, the unchanged O(NL) boundary combine below derives the
    entry directions, and an elementwise contraction applies them per
    position — ONE T-scaling pass instead of two.  Takes precedence
    over ``fused`` (there is no separate backward launch to split).
    Contracted streams carry matrix-total scales: exact for every
    scale-free consumer; the cs slot is NOT a Rabiner cs source (the
    em-seq loglik telescopes via fb_onehot.mat_loglik_lanes instead).

    With ``conf_mask`` ([K] island indicator) the backward kernel emits the
    per-position island confidence in the betas slot of the return tuple
    ([Tp, NL] instead of [Tp, K, NL]) and beta streams never reach HBM —
    the posterior fast path.

    ``first`` (static): this span starts the sequence — global position 0 is
    the init (its emission folds into the base direction).  ``enter_dir``
    ([K], used when not ``first``): the entering-alpha direction from the
    previous span; ``exit_dir`` ([K], optional): the exiting-beta direction
    from the next span (None = free end, the uniform direction).  Together
    these let a host driver thread EXACT messages across sequential spans of
    a record too large for one pass (pipeline.posterior_file), exactly like
    the cross-device exchange does across the mesh.

    ``prepared`` (ops.prepared.PreparedSeq; single-device spans only — the
    sharded paths' collective prev-symbol threading stays inline): the
    symbol-only lane layout + pair stream, amortized across iterations and
    span sweeps.

    One-hot models run their boundary-message combine REDUCED: lane
    transfer products stay [NL, 2, 2] (adjacent lanes' groups compose by
    the pair stream's forward-fill invariant e_in[n+1] == e_out[n]) through
    both associative scans and the enter/exit einsums, scattering to dense
    [K]-vectors only at the kernel interfaces — a 16x shrink of the
    per-iteration boundary-glue fixed cost vs the dense [NL, K, K] scans.

    Returns (alphas, cs, betas, steps2, lens2, enters, is_first, Tt) where
    is_first is the traced "this device holds the sequence init" flag.
    """
    K, S = params.n_states, params.n_symbols
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    pi = jnp.exp(params.log_pi).astype(jnp.float32)

    if not first and enter_dir is None:
        raise ValueError(
            "continuation spans (first=False) need enter_dir — the "
            "entering-alpha direction from the previous span"
        )
    if prepared is not None and axis is not None:
        raise ValueError(
            "prepared seq streams serve single-device spans (axis=None); "
            "sharded paths prep inline"
        )
    d = jax.lax.axis_index(axis) if axis is not None else 0
    is_first = (d == 0) if first else jnp.asarray(False)

    # The GLOBAL position 0's step is padded out of the products when this
    # device/span holds the init: the base direction already contains
    # pi * B[:, o_0], so including M_0 would double-apply it.
    if prepared is not None:
        from cpgisland_tpu.ops import prepared as prep_mod

        prep_mod.check_seq(
            prepared, S, obs.shape[0], lane_T, t_tile, first, onehot,
            prev_sym=prev_sym,
        )
        obs_l, sel_l, lane_lens = (
            prepared.obs_l, prepared.sel_l, prepared.lane_lens
        )
        o0, Tt, NL = prepared.o0, prepared.Tt, prepared.obs_l.shape[0]
        obs_flat = None
    else:
        obs_l, sel_l, lane_lens, obs_flat, Tt, NL = _lane_layout(
            obs, length, S, lane_T, t_tile, is_first
        )
        o0 = obs_flat[0]
    length = jnp.asarray(length, jnp.int32)

    # --- lane transfer operators (pallas) -> boundary messages (XLA) ------
    red = None
    if onehot:
        # Reduced 2x2 products for one-hot-emission models (ops.fb_onehot):
        # exact — the dense product entries outside the boundary symbol
        # groups are multiplied by exact zeros in every consumer below.
        from cpgisland_tpu.ops import fb_onehot, viterbi_onehot

        if prepared is not None:
            prev_dev = prepared.prev_dev
            pair2, e_in_l, e_out_l = (
                prepared.pair2, prepared.e_in, prepared.e_out
            )
        else:
            if not first and prev_sym is None:
                raise ValueError(
                    "onehot continuation spans (first=False) need prev_sym — "
                    "the symbol emitted before this span's first position"
                )
            prev_seg = jnp.asarray(o0 if first else prev_sym, jnp.int32)
            if axis is not None:
                T_in = obs.shape[0]
                seed_syms = jnp.where(jnp.arange(T_in) < length, obs_flat, S)
                prev_dev = viterbi_onehot.device_entry_sym(
                    seed_syms, S, axis, prev_seg
                )
            else:
                prev_dev = prev_seg
            pair2, e_in_l, e_out_l = viterbi_onehot.pair_stream(
                S, sel_l.T, prev_dev
            )
        gt = fb_onehot._groups(params)
        gin = gt[e_in_l]  # [NL, 2]
        gout = gt[e_out_l]
        pairn_pre = prepared.pairn2 if prepared is not None else None
        if one_pass:
            # r17 TRUE one-pass: the matrix-carried kernel is entry-free,
            # so it runs BEFORE any boundary message exists; red (the
            # products pass's output) falls out of its O(NL) epilogue and
            # the boundary combine below is unchanged.
            va_m, wb_m, esym2_m, red = fb_onehot.run_fb_mat_onehot(
                params, lane_lens[None, :], Tt, lane_T,
                (pair2, None, pairn_pre),
            )
        else:
            red = fb_onehot.products_reduced(params, pair2, Tt)  # [NL, 2, 2]
        incl_red = jax.lax.associative_scan(_lane_combine, red, axis=0)
    else:
        P = _run_products_kernel(A, B, sel_l, lane_T, Tt, K, S)  # P[lane, i, m]
        incl = jax.lax.associative_scan(_lane_combine, P, axis=0)

    a0_dir = _norm_rows(pi * B[:, o0])  # [K] — meaningful on device 0
    if axis is not None:
        # Cross-device boundary messages: the ONE shared implementation
        # (parallel.fb_sharded.device_boundary_messages) — both the XLA lane
        # path and this fused path exchange messages identically.  The
        # reduced path scatters ONLY its [K, K] device total to dense for
        # the exchange (the dense total's out-of-group entries are exact
        # zeros, so the exchange numerics are unchanged).
        from cpgisland_tpu.ops import fb_onehot as _fbo
        from cpgisland_tpu.parallel.fb_sharded import device_boundary_messages

        total_dev = (
            _fbo._scatter_products_prob(
                incl_red[-1:], gt, e_in_l[:1], e_out_l[-1:], K
            )[0]
            if onehot
            else incl[-1]
        )
        _, base_dir, anchor = device_boundary_messages(
            a0_dir, total_dev, d, axis,
            start_dir=None if first else enter_dir,
            end_dir=exit_dir,
        )
    else:
        base_dir = a0_dir if first else _norm_rows(enter_dir)
        anchor = (
            jnp.full((K,), 1.0 / K, jnp.float32)
            if exit_dir is None
            else _norm_rows(exit_dir)
        )

    iK = jnp.arange(K, dtype=jnp.int32)
    if onehot:
        # Reduced boundary combine: entering-alpha / exiting-beta directions
        # in the 2-component group space, scattered to the dense kernel
        # interface rows (out-of-group entries were exact zeros in the dense
        # formulation — one-hot emissions support base_dir/enters only on
        # their boundary symbol's group, and the kernels re-slice the group
        # components anyway).
        from cpgisland_tpu.ops.viterbi_onehot import GROUP as _G

        eye2 = jnp.broadcast_to(jnp.eye(_G, dtype=jnp.float32), (1, _G, _G))
        excl_red = jnp.concatenate([eye2, incl_red[:-1]], axis=0)
        base_red = jnp.take(base_dir, gin[0])  # [2]
        enters_red = _norm_rows(jnp.einsum("k,nkj->nj", base_red, excl_red))
        # Lane 0 enters with the FULL base direction: a span-threading
        # enter_dir may carry out-of-group mass that reaches lane 0's v_0
        # through A (the dense formulation's excl[0] = I row) — lanes >= 1
        # see it only through group-supported products, where the
        # restriction is exact.  enters_red row 0 carries the UNrenormalized
        # group components, matching the dense take_along_axis contract of
        # the seq-stats consumer.
        enters_red = enters_red.at[0].set(base_red)
        enters = (
            jnp.where(iK[None, :] == gin[:, 0:1], enters_red[:, 0:1], 0.0)
            + jnp.where(iK[None, :] == gin[:, 1:2], enters_red[:, 1:2], 0.0)
        )  # [NL, K]
        enters = enters.at[0].set(base_dir)
        Rsuf_red = jax.lax.associative_scan(
            lambda a, b: _lane_combine(b, a), red, axis=0, reverse=True
        )
        anchor_red = jnp.take(anchor, gout[-1])  # [2]
        beta_exits_red = jnp.concatenate(
            [_norm_rows(jnp.einsum("nij,j->ni", Rsuf_red[1:], anchor_red)),
             anchor_red[None]],
            axis=0,
        )  # [NL, 2]
        beta_exits = (
            jnp.where(iK[None, :] == gout[:, 0:1], beta_exits_red[:, 0:1], 0.0)
            + jnp.where(iK[None, :] == gout[:, 1:2], beta_exits_red[:, 1:2], 0.0)
        )  # [NL, K]
    else:
        eyeK = jnp.broadcast_to(jnp.eye(K, dtype=jnp.float32), (1, K, K))
        excl = jnp.concatenate([eyeK, incl[:-1]], axis=0)  # prefix products
        enters = _norm_rows(jnp.einsum("k,nkj->nj", base_dir, excl))  # [NL, K]
        Rsuf = jax.lax.associative_scan(
            lambda a, b: _lane_combine(b, a), P, axis=0, reverse=True
        )
        beta_exits = jnp.concatenate(
            [_norm_rows(jnp.einsum("nij,j->ni", Rsuf[1:], anchor)), anchor[None]],
            axis=0,
        )  # [NL, K]

    # --- per-lane v_0 (unnormalized: sum == that position's Rabiner c) ----
    o_first = obs_l[:, 0]  # [NL]
    Bf = B[:, o_first].T  # [NL, K]
    v0_cont = jnp.einsum("nk,kj->nj", enters, A, precision=jax.lax.Precision.HIGHEST) * Bf
    lane0_is_init = (jnp.arange(NL)[:, None] == 0) & is_first
    v0 = jnp.where(
        (lane_lens > 0)[:, None],
        jnp.where(lane0_is_init, (pi * B[:, o0])[None, :], v0_cont),
        jnp.ones((NL, K)) / K,
    )

    steps2 = obs_l.T  # [lane_T, NL] — within-lens symbols (kernels mask by lens)
    lens2 = lane_lens[None, :]
    if onehot:
        # Reduced 2-component forward/backward streams (ops.fb_onehot),
        # scattered back to the dense [Tp, K, NL] contract for the
        # assembly consumers — exact (out-of-group entries are exact
        # zeros wherever they are ever multiplied in); the conf fast path
        # consumes the reduced streams directly and the scatters are
        # dead-code-eliminated.
        ll_lane = None
        if one_pass:
            # Elementwise entry application — the matrix streams already
            # exist; only the boundary directions were pending.
            al2, third2 = fb_onehot.contract_mat_streams(
                va_m, wb_m, v0.T, beta_exits.T, gt, esym2_m
            )
            esym2 = esym2_m
            cs = jnp.sum(al2, axis=1)  # matrix-scaled — NOT Rabiner cs
            if conf_mask is not None:
                third2 = fb_onehot.conf_from_reduced(
                    al2, third2, esym2, lens2, conf_mask, gt
                )
            elif return_reduced:
                ll_lane = fb_onehot.mat_loglik_lanes(va_m, al2, lens2)
        else:
            al2, cs, third2, esym2 = fb_onehot.run_fb_kernels_onehot(
                params, sel_l.T, prev_dev, lens2, v0.T, beta_exits.T, Tt,
                lane_T, conf_mask=conf_mask,
                pair_esym=(pair2, None, pairn_pre), fused=fused,
            )
        if return_reduced and conf_mask is None:
            # Raw reduced streams for the seq-stats kernel consumer — the
            # pair stream and entering directions pass through ONCE (no
            # recompute, no re-gather).  ll_lane: the one-pass arm's
            # telescoped exact loglik (None on the cs-carrying arms).
            reduced = (
                al2, third2, esym2, pair2, e_in_l, gt, enters_red, ll_lane
            )
            return reduced, cs, None, steps2, lens2, enters, is_first, Tt
        alphas = fb_onehot.scatter_streams(al2, gt, esym2, K)
        third = (
            third2 if conf_mask is not None
            else fb_onehot.scatter_streams(third2, gt, esym2, K)
        )
        return alphas, cs, third, steps2, lens2, enters, is_first, Tt
    alphas, cs, third = _run_fb_kernels(
        A, B, steps2, lens2, v0.T, beta_exits.T, K, S, Tt, lane_T,
        conf_mask=conf_mask,
    )
    return alphas, cs, third, steps2, lens2, enters, is_first, Tt


def _seq_stats_core(
    params: HmmParams,
    obs: jnp.ndarray,
    length,
    lane_T: int,
    t_tile: int,
    axis,
    reduce: bool = True,
    onehot: bool = False,
    prepared=None,
    fused: bool = True,
    one_pass: bool = False,
) -> SuffStats:
    """The fused whole-sequence E-step over THIS device's time shard.

    axis=None is the single-device case; with an axis name (under
    shard_map) the per-device [K, K] transfer totals are all_gathered so
    every device gets its exact entering-alpha / exiting-beta boundary
    message, exactly the fb_sharded message scheme — the result is the
    ALREADY-psummed global statistics when ``reduce`` (callers composing
    several sequences per device, like the 2-D mesh body, pass
    reduce=False and psum once themselves).
    """
    K, S = params.n_states, params.n_symbols
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    length = jnp.asarray(length, jnp.int32)

    # Reduced-stream stats for power-of-two S on BOTH platforms now: the
    # off-TPU lowering is the z-normalized XLA twin (fb_onehot.
    # _xla_znorm_stats), arithmetic-identical to the chip kernel, so CPU
    # runs certify the same scheme the silicon executes.  (Non-pow2 S
    # keeps the scatter + dense scale-free assembly below — itself
    # invariant to the fused path's self-normalized betas.)
    use_kernel_stats = onehot and S & (S - 1) == 0
    # One-pass rides the reduced-stream stats kernel only: the non-pow2-S
    # dense assembly below derives its loglik from Rabiner cs, which the
    # matrix arm does not produce — those geometries silently keep the
    # fused 2-pass arm (routing bit-for-bit unchanged).
    one_pass = one_pass and use_kernel_stats
    alphas, cs, betas, steps2, lens2, enters, is_first, Tt_used = _lane_streams(
        params, obs, length, lane_T, t_tile, axis, onehot=onehot,
        return_reduced=use_kernel_stats, prepared=prepared, fused=fused,
        one_pass=one_pass,
    )
    NL = steps2.shape[1]
    if use_kernel_stats:
        # Reduced-stream seq stats kernel (z-normalized scale-free xi; the
        # scatter + XLA assembly below is its off-TPU twin).
        from cpgisland_tpu.ops import fb_onehot

        al2, b2, esym2, pair2, e_in_l, gt, enters_red, ll_lane = alphas
        ent_full = fb_onehot.scatter_streams(
            enters_red.T[None], gt, e_in_l[None, :], K
        )[0]  # [K, NL]
        pair0_mask = (
            ~((jnp.arange(NL) == 0) & is_first)
        ).astype(jnp.float32)[None, :]
        macc, emit_red, ll = fb_onehot.run_seq_stats_onehot(
            params, al2, b2, pair2, lens2, gt, enters_red.T, ent_full,
            pair0_mask, Tt_used,
        )
        if one_pass:
            # The stats kernel's sum-of-log-cs read the matrix-scaled
            # alphas (macc/emit are per-pair/-position normalized, so
            # they are exact regardless) — the loglik is the telescoped
            # per-lane reduction instead (fb_onehot.mat_loglik_lanes).
            ll = ll_lane
        trans, emit, loglik = _assemble_reduced_stats(
            params, A, gt, macc, emit_red, ll
        )
        g0f = _gamma0_full(al2, b2, gt, esym2, K)
        at_init = is_first & (length > 0)
        init = jnp.where(at_init, g0f[:, 0], jnp.zeros(K))
        stats = SuffStats(
            init=init,
            trans=trans,
            emit=emit,
            loglik=loglik,
            n_seqs=at_init.astype(jnp.int32),
        )
        if axis is not None and reduce:
            stats = jax.lax.psum(stats, axis)
        return stats

    # --- scale-free assembly ---------------------------------------------
    Tp = steps2.shape[0]
    vmask = jnp.arange(Tp)[:, None] < lens2  # [Tp, NL]
    gamma, emit, loglik = _gamma_emit_loglik(alphas, betas, cs, steps2, vmask, S)

    # xi per pair, scale-free: true xi sums to 1, so dividing each pair's
    # outer product by its own total reconstructs the exact counts from the
    # beta DIRECTIONS — no scale chain crosses lane or device boundaries.
    # Lane-0 rows use the entering-alpha message (the pairs the chunked
    # path drops); the device-crossing pair is lane 0 of device d > 0.
    w = _emit_sel_cols(B, steps2, K) * betas  # [Tp, K, NL] (no /c — scale-free)
    a_hat = alphas / jnp.maximum(cs[:, None, :], 1e-30)
    a_prev = jnp.concatenate([enters.T[None], a_hat[:-1]], axis=0)  # [Tp, K, NL]
    pair0 = vmask[0] & ~((jnp.arange(NL) == 0) & is_first)  # global init: no pair
    pair = vmask.at[0].set(pair0)
    a_prev = jnp.where(pair[:, None, :], a_prev, 0.0)
    Aw = jnp.einsum("jk,tkn->tjn", A, w, precision=jax.lax.Precision.HIGHEST)
    z = jnp.sum(a_prev * Aw, axis=1)  # [Tp, NL] — per-pair xi total
    a_scaled = a_prev / jnp.maximum(z, 1e-30)[:, None, :]
    trans = A * jnp.einsum("tin,tjn->ij", a_scaled, w, precision=jax.lax.Precision.HIGHEST)

    at_init = is_first & (length > 0)
    init = jnp.where(at_init, gamma[0, :, 0], jnp.zeros(K))

    stats = SuffStats(
        init=init,
        trans=trans,
        emit=emit,
        loglik=loglik,
        n_seqs=at_init.astype(jnp.int32),
    )
    if axis is not None and reduce:
        stats = jax.lax.psum(stats, axis)
    return stats


def _seq_posterior_core(
    params: HmmParams,
    obs: jnp.ndarray,
    length,
    island_mask: jnp.ndarray,
    lane_T: int,
    t_tile: int,
    axis,
    enter_dir=None,
    exit_dir=None,
    first: bool = True,
    want_path: bool = False,
    onehot: bool = False,
    prev_sym=None,
    prepared=None,
    fused: bool = True,
    one_pass: bool = False,
):
    """Per-position island confidence over THIS device's time shard, fused.

    The soft-decoding twin of the sharded Viterbi: the SAME forward/backward
    kernel streams as the E-step (boundary messages make them exact across
    lanes, devices, and — via enter_dir/exit_dir — sequential spans), with
    the per-position gamma reduced on device to one float per symbol:
    conf[t] = sum_{k in islands} gamma[t, k].  gamma is scale-free
    (normalize(alpha_t * beta_t)), so working from beta DIRECTIONS is exact.

    island_mask: [K] f32 0/1 — which states count as "island" (traced, so
    changing the set never recompiles).  ``want_path`` additionally returns
    the max-posterior-marginal state path (int32).  The reference's Mahout
    surface exposes only hard Viterbi (CpGIslandFinder.java:260); this is
    its soft completion at full kernel speed.

    Returns (conf [T] f32, path [T] int32 — zeros unless want_path).
    """
    T = obs.shape[0]
    if not want_path:
        # Fast path: the backward kernel emits confidence directly (betas
        # never reach HBM — see _bwd_conf_kernel).
        _, _, conf2, steps2, _, _, _, _ = _lane_streams(
            params, obs, length, lane_T, t_tile, axis,
            enter_dir=enter_dir, exit_dir=exit_dir, first=first,
            conf_mask=island_mask, onehot=onehot, prev_sym=prev_sym,
            prepared=prepared, fused=fused, one_pass=one_pass,
        )
        # Lane n covers global positions [n*lane_T, (n+1)*lane_T): transpose
        # the [lane_T, NL] lane layout back to global order, slice the pad.
        return conf2.T.reshape(-1)[:T], jnp.zeros((T,), jnp.int32)
    alphas, cs, betas, steps2, lens2, _, _, _ = _lane_streams(
        params, obs, length, lane_T, t_tile, axis,
        enter_dir=enter_dir, exit_dir=exit_dir, first=first,
        onehot=onehot, prev_sym=prev_sym, prepared=prepared, fused=fused,
        one_pass=one_pass,
    )
    # With the fused backward the betas are per-position directions; the
    # gamma normalize/argmax below is scale-free, so the branch is shared.
    conf2, path2 = _conf_path_from_streams(alphas, betas, lens2, island_mask)
    return conf2.T.reshape(-1)[:T], path2.T.reshape(-1)[:T]


@functools.partial(
    jax.jit,
    static_argnames=(
        "lane_T", "t_tile", "first", "want_path", "onehot", "fused",
        "one_pass",
    ),
)
def seq_posterior_pallas(
    params: HmmParams,
    obs: jnp.ndarray,
    length,
    island_mask: jnp.ndarray,
    enter_dir=None,
    exit_dir=None,
    first: bool = True,
    want_path: bool = False,
    lane_T: int = DEFAULT_LANE_T,
    t_tile: int = DEFAULT_T_TILE,
    onehot: bool = False,
    prev_sym=None,
    prepared=None,
    fused: bool = True,
    one_pass: bool = False,
):
    """Single-device fused posterior: (conf [T], mpm path [T]).

    Drop-in fast path for ops.forward_backward.posterior_marginals'
    island-confidence reduction (bit-compatible to f32 tolerance); spans of
    longer records thread enter_dir/exit_dir (see _seq_posterior_core).
    ``prepared``: the same PreparedSeq the span's other sweeps use — one
    symbol-only prep per placed span instead of one per sweep.
    ``one_pass`` (static): the r17 matrix-carried arm — ONE T-scaling
    pass for any one-hot engine (conf/gamma/MPM are scale-free).
    """
    return _seq_posterior_core(
        params, obs, length, island_mask, lane_T, t_tile, axis=None,
        enter_dir=enter_dir, exit_dir=exit_dir, first=first,
        want_path=want_path, onehot=onehot, prev_sym=prev_sym,
        prepared=prepared, fused=fused, one_pass=one_pass,
    )


@functools.partial(
    jax.jit, static_argnames=("t_tile", "want_path", "onehot", "fused")
)
def batch_posterior_pallas(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    island_mask: jnp.ndarray,
    t_tile: int = DEFAULT_T_TILE,
    want_path: bool = False,
    onehot: bool = False,
    prepared=None,
    fused: bool = True,
):
    """Posterior island confidence for a [N, T] batch of INDEPENDENT records.

    The soft twin of viterbi_*_batch: each record rides one VPU lane in the
    chunked kernel layout (batch_stats_pallas), with pi-init and free-end
    betas — EXACT per record since every record fits its lane whole.  This
    is how scaffold-heavy assemblies avoid one dispatch (and one
    mostly-idle lane pass) per tiny record.  Returns (conf [N, T] f32,
    path [N, T] int32 — zeros unless want_path).  ``prepared``: same
    contract as batch_stats_pallas — one PreparedChunked serves both
    entries on the same batch (the pipeline's posterior -> EM reuse).
    NOTE: there is no ``one_pass`` knob here — independent records have
    trivial boundary messages (pi-init / free end), so the chunked layout
    never ran a products pass and is ALREADY one T-scaling pass when
    fused (the r17 arm targets the lane-coupled whole-sequence paths).
    """
    K, S = params.n_states, params.n_symbols
    N, T = chunks.shape
    A, B, _, prep, a0_raw, beta0, _, = _batch_lane_setup(
        params, chunks, lengths, t_tile, onehot=onehot, prep=prepared
    )
    steps2, lens2, Tt = prep.steps2, prep.lens2, prep.Tt
    if onehot:
        from cpgisland_tpu.ops import fb_onehot

        if not want_path:
            _, _, conf2, _ = fb_onehot.run_fb_kernels_onehot(
                params, prep.sel2, jnp.int32(0), lens2, a0_raw, beta0, Tt, T,
                conf_mask=island_mask,
                pair_esym=(prep.pair2, prep.esym2, prep.pairn2), fused=fused,
            )
            return conf2.T[:N, :T], jnp.zeros((N, T), jnp.int32)
        al2, _, b2, esym2 = fb_onehot.run_fb_kernels_onehot(
            params, prep.sel2, jnp.int32(0), lens2, a0_raw, beta0, Tt, T,
            pair_esym=(prep.pair2, prep.esym2, prep.pairn2), fused=fused,
        )
        gt = fb_onehot._groups(params)
        alphas = fb_onehot.scatter_streams(al2, gt, esym2, K)
        betas = fb_onehot.scatter_streams(b2, gt, esym2, K)
    elif not want_path:
        _, _, conf2 = _run_fb_kernels(
            A, B, steps2, lens2, a0_raw, beta0, K, S, Tt, T,
            conf_mask=island_mask,
        )
        return conf2.T[:N, :T], jnp.zeros((N, T), jnp.int32)
    else:
        alphas, _, betas = _run_fb_kernels(
            A, B, steps2, lens2, a0_raw, beta0, K, S, Tt, T
        )
    conf2, path2 = _conf_path_from_streams(alphas, betas, lens2, island_mask)
    return conf2.T[:N, :T], path2.T[:N, :T]


@functools.partial(
    jax.jit, static_argnames=("lane_T", "t_tile", "first", "onehot")
)
def seq_transfer_total_pallas(
    params: HmmParams,
    obs: jnp.ndarray,
    length,
    first: bool = True,
    lane_T: int = DEFAULT_LANE_T,
    t_tile: int = DEFAULT_T_TILE,
    onehot: bool = False,
    prev_sym=None,
    prepared=None,
) -> jnp.ndarray:
    """Normalized probability-space transfer operator of one span (products
    kernel only — the cheap forward sweep of span-threaded processing).

    Returns [K, K] M with alpha_dir_out ∝ alpha_dir_in @ M.  ``first`` masks
    global position 0 (its step is the init, folded into the base direction
    by the consumer) — pass True only for the sequence's first span.
    ``onehot`` (one-hot-emission models) swaps in the reduced 2x2 products
    kernel; continuation spans then need ``prev_sym`` (the symbol before the
    span — it conditions the reduced chain's entry group), and the
    cross-lane combine runs REDUCED ([NL, 2, 2] — see _lane_streams),
    scattering only the final [K, K] total.  ``prepared``
    (ops.prepared.PreparedSeq): one symbol-only prep shared with the span's
    posterior sweep (pipeline.posterior_file builds it once per placed
    span).
    """
    K, S = params.n_states, params.n_symbols
    if prepared is not None:
        from cpgisland_tpu.ops import prepared as prep_mod

        prep_mod.check_seq(
            prepared, S, obs.shape[0], lane_T, t_tile, first, onehot,
            prev_sym=prev_sym,
        )
        sel_l, o0 = prepared.sel_l, prepared.o0
        Tt = prepared.Tt
    else:
        _, sel_l, _, obs_flat, Tt, _ = _lane_layout(
            obs, length, S, lane_T, t_tile, first
        )
        o0 = obs_flat[0]
    if onehot:
        from cpgisland_tpu.ops import fb_onehot
        from cpgisland_tpu.ops.viterbi_onehot import pair_stream

        if prepared is not None:
            pair2, e_in, e_out = prepared.pair2, prepared.e_in, prepared.e_out
        else:
            if not first and prev_sym is None:
                raise ValueError("onehot continuation spans need prev_sym")
            prev_seg = jnp.asarray(o0 if first else prev_sym, jnp.int32)
            pair2, e_in, e_out = pair_stream(S, sel_l.T, prev_seg)
        gt = fb_onehot._groups(params)
        red = fb_onehot.products_reduced(params, pair2, Tt)
        total_red = jax.lax.associative_scan(_lane_combine, red, axis=0)[-1:]
        return fb_onehot._scatter_products_prob(
            total_red, gt, e_in[:1], e_out[-1:], K
        )[0]
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    P = _run_products_kernel(A, B, sel_l, lane_T, Tt, K, S)
    return jax.lax.associative_scan(_lane_combine, P, axis=0)[-1]


# ---------------------------------------------------------------------------
# Stacked multi-model drivers: M members' reduced chains over ONE stream in
# one launch set (ops.fb_onehot's stacked kernels).  Per-member numerics
# mirror the single-model paths op for op, so every member's outputs are
# bit-identical to its own sequential dispatch over the same input — the
# exactness contract family.compare / the stacked E-step pin in tests.


def _lane_streams_stacked(
    params_list,
    obs: jnp.ndarray,
    length,
    lane_T: int,
    t_tile: int,
    axis,
    exit_dirs=None,
    conf_masks=None,
    prepared=None,
    fused: bool = True,
):
    """Stacked whole-sequence lane setup (one-hot members, first spans).

    The model-axis twin of :func:`_lane_streams`' onehot branch: the lane
    layout and pair stream are symbol-only and built ONCE; the per-member
    boundary glue (reduced products from one stacked launch, prefix/suffix
    combines, entering directions) loops members over model-sized arrays;
    the T-scaling forward/backward chains run STACKED
    (fb_onehot.run_fb_kernels_onehot_stacked).  ``exit_dirs``: per-member
    [K_m] exiting-beta directions (None = free end).  Returns
    (per-member [(alphas, cs, third)], steps2, lens2, Tt) where ``third``
    is conf2 [Tp, NL] with ``conf_masks`` else the dense scattered betas.
    """
    from cpgisland_tpu.ops import fb_onehot, viterbi_onehot

    M = len(params_list)
    S = fb_onehot.check_stacked_members(params_list)
    if prepared is not None and axis is not None:
        raise ValueError(
            "prepared seq streams serve single-device spans (axis=None)"
        )
    d = jax.lax.axis_index(axis) if axis is not None else 0
    is_first = d == 0

    if prepared is not None:
        from cpgisland_tpu.ops import prepared as prep_mod

        prep_mod.check_seq(
            prepared, S, obs.shape[0], lane_T, t_tile, True, True,
        )
        obs_l, sel_l, lane_lens = (
            prepared.obs_l, prepared.sel_l, prepared.lane_lens
        )
        o0, Tt, NL = prepared.o0, prepared.Tt, prepared.obs_l.shape[0]
        obs_flat = None
        prev_dev = prepared.prev_dev
        pair2, e_in_l, e_out_l = (
            prepared.pair2, prepared.e_in, prepared.e_out
        )
        pairn_pre = prepared.pairn2
    else:
        obs_l, sel_l, lane_lens, obs_flat, Tt, NL = _lane_layout(
            obs, length, S, lane_T, t_tile, is_first
        )
        o0 = obs_flat[0]
        prev_seg = jnp.asarray(o0, jnp.int32)
        if axis is not None:
            T_in = obs.shape[0]
            seed_syms = jnp.where(
                jnp.arange(T_in) < jnp.asarray(length, jnp.int32), obs_flat, S
            )
            prev_dev = viterbi_onehot.device_entry_sym(
                seed_syms, S, axis, prev_seg
            )
        else:
            prev_dev = prev_seg
        pair2, e_in_l, e_out_l = viterbi_onehot.pair_stream(
            S, sel_l.T, prev_dev
        )
        pairn_pre = None
    length = jnp.asarray(length, jnp.int32)

    gts = [fb_onehot._groups(p) for p in params_list]
    reds = fb_onehot.products_reduced_stacked(params_list, pair2, Tt)

    steps2 = obs_l.T
    lens2 = lane_lens[None, :]
    o_first = obs_l[:, 0]  # [NL]
    v0s, beta_exits_list = [], []
    for m, params in enumerate(params_list):
        K = params.n_states
        A = jnp.exp(params.log_A).astype(jnp.float32)
        B = jnp.exp(params.log_B).astype(jnp.float32)
        pi = jnp.exp(params.log_pi).astype(jnp.float32)
        gt, red = gts[m], reds[m]
        gin = gt[e_in_l]
        gout = gt[e_out_l]
        incl_red = jax.lax.associative_scan(_lane_combine, red, axis=0)
        a0_dir = _norm_rows(pi * B[:, o0])
        exit_dir = None if exit_dirs is None else exit_dirs[m]
        if axis is not None:
            from cpgisland_tpu.parallel.fb_sharded import (
                device_boundary_messages,
            )

            total_dev = fb_onehot._scatter_products_prob(
                incl_red[-1:], gt, e_in_l[:1], e_out_l[-1:], K
            )[0]
            _, base_dir, anchor = device_boundary_messages(
                a0_dir, total_dev, d, axis, start_dir=None, end_dir=exit_dir
            )
        else:
            base_dir = a0_dir
            anchor = (
                jnp.full((K,), 1.0 / K, jnp.float32)
                if exit_dir is None
                else _norm_rows(exit_dir)
            )
        iK = jnp.arange(K, dtype=jnp.int32)
        eye2 = jnp.broadcast_to(
            jnp.eye(fb_onehot.GROUP, dtype=jnp.float32),
            (1, fb_onehot.GROUP, fb_onehot.GROUP),
        )
        excl_red = jnp.concatenate([eye2, incl_red[:-1]], axis=0)
        base_red = jnp.take(base_dir, gin[0])
        enters_red = _norm_rows(jnp.einsum("k,nkj->nj", base_red, excl_red))
        enters_red = enters_red.at[0].set(base_red)
        enters = (
            jnp.where(iK[None, :] == gin[:, 0:1], enters_red[:, 0:1], 0.0)
            + jnp.where(iK[None, :] == gin[:, 1:2], enters_red[:, 1:2], 0.0)
        )
        enters = enters.at[0].set(base_dir)
        Rsuf_red = jax.lax.associative_scan(
            lambda a, b: _lane_combine(b, a), red, axis=0, reverse=True
        )
        anchor_red = jnp.take(anchor, gout[-1])
        beta_exits_red = jnp.concatenate(
            [_norm_rows(jnp.einsum("nij,j->ni", Rsuf_red[1:], anchor_red)),
             anchor_red[None]],
            axis=0,
        )
        beta_exits = (
            jnp.where(iK[None, :] == gout[:, 0:1], beta_exits_red[:, 0:1], 0.0)
            + jnp.where(iK[None, :] == gout[:, 1:2], beta_exits_red[:, 1:2], 0.0)
        )
        Bf = B[:, o_first].T
        v0_cont = jnp.einsum(
            "nk,kj->nj", enters, A, precision=jax.lax.Precision.HIGHEST
        ) * Bf
        lane0_is_init = (jnp.arange(NL)[:, None] == 0) & is_first
        v0 = jnp.where(
            (lane_lens > 0)[:, None],
            jnp.where(lane0_is_init, (pi * B[:, o0])[None, :], v0_cont),
            jnp.ones((NL, K)) / K,
        )
        v0s.append(v0.T)
        beta_exits_list.append(beta_exits.T)

    al_list, cs_list, third_list, esym2 = (
        fb_onehot.run_fb_kernels_onehot_stacked(
            params_list, sel_l.T, prev_dev, lens2, v0s, beta_exits_list,
            Tt, lane_T, conf_masks=conf_masks,
            pair_esym=(pair2, None, pairn_pre), fused=fused,
        )
    )
    out = []
    for m, params in enumerate(params_list):
        K = params.n_states
        alphas = fb_onehot.scatter_streams(al_list[m], gts[m], esym2, K)
        third = (
            third_list[m]
            if conf_masks is not None
            else fb_onehot.scatter_streams(third_list[m], gts[m], esym2, K)
        )
        out.append((alphas, cs_list[m], third))
    return out, steps2, lens2, Tt


def _seq_posterior_core_stacked(
    params_list,
    obs: jnp.ndarray,
    length,
    island_masks,
    lane_T: int,
    t_tile: int,
    axis,
    want_path: bool = False,
    prepared=None,
    fused: bool = True,
):
    """Stacked :func:`_seq_posterior_core`: M members' island-confidence
    (and MPM path) tracks over ONE record in one stacked launch set.
    Per-member numerics are the single-model core's — bit-identical to M
    sequential calls over the same placed input.  Returns (conf [M, T],
    path [M, T] — zeros unless want_path)."""
    T = obs.shape[0]
    M = len(params_list)
    exit_dirs = [
        jnp.full((p.n_states,), 1.0 / p.n_states, jnp.float32)
        for p in params_list
    ]
    if not want_path:
        streams, _, _, _ = _lane_streams_stacked(
            params_list, obs, length, lane_T, t_tile, axis,
            exit_dirs=exit_dirs, conf_masks=island_masks,
            prepared=prepared, fused=fused,
        )
        conf = jnp.stack(
            [conf2.T.reshape(-1)[:T] for _, _, conf2 in streams]
        )
        return conf, jnp.zeros((M, T), jnp.int32)
    streams, _, lens2, _ = _lane_streams_stacked(
        params_list, obs, length, lane_T, t_tile, axis,
        exit_dirs=exit_dirs, prepared=prepared, fused=fused,
    )
    confs, paths = [], []
    for m, (alphas, _cs, betas) in enumerate(streams):
        conf2, path2 = _conf_path_from_streams(
            alphas, betas, lens2, island_masks[m]
        )
        confs.append(conf2.T.reshape(-1)[:T])
        paths.append(path2.T.reshape(-1)[:T])
    return jnp.stack(confs), jnp.stack(paths)


@functools.partial(
    jax.jit,
    static_argnames=("lane_T", "t_tile", "want_path", "fused"),
)
def seq_posterior_pallas_stacked(
    params_list,
    obs: jnp.ndarray,
    length,
    island_masks,
    want_path: bool = False,
    lane_T: int = DEFAULT_LANE_T,
    t_tile: int = DEFAULT_T_TILE,
    prepared=None,
    fused: bool = True,
):
    """Single-device stacked posterior: M members' (conf, path) tracks off
    one record in one stacked launch set (first spans; the comparison
    workload's record unit)."""
    return _seq_posterior_core_stacked(
        tuple(params_list), obs, length, tuple(island_masks), lane_T,
        t_tile, axis=None, want_path=want_path, prepared=prepared,
        fused=fused,
    )


@functools.partial(jax.jit, static_argnames=("t_tile", "fused"))
def batch_stats_pallas_stacked(
    params_list,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    t_tile: int = DEFAULT_T_TILE,
    prepared=None,
    fused: bool = True,
) -> tuple:
    """Stacked multi-model chunked E-step: M members' batch-summed
    SuffStats from ONE stacked launch set over a shared [N, T] batch.

    The model-family training lever of ROADMAP item 2: the symbol-only
    lane layout + pair stream build once (``prepared`` shares them across
    EM iterations like the single-model path), the M fused fwd/bwd chains
    co-schedule in ONE kernel launch, and the count reductions run through
    the stacked z-normalized stats kernel — so a K-member family scan pays
    ~one member's T-scaling passes.  Per-member results are BIT-IDENTICAL
    to ``batch_stats_pallas(params_list[m], ..., onehot=True, fused=...)``
    (pinned in tests/test_multimodel.py).  Members must share a
    power-of-two alphabet and be reduced-eligible (callers gate via
    family.reduced_stats_eligible).  Returns a tuple of SuffStats.
    """
    from cpgisland_tpu.ops import fb_onehot
    from cpgisland_tpu.ops import prepared as prep_mod

    params_list = tuple(params_list)
    M = len(params_list)
    S = fb_onehot.check_stacked_members(params_list)
    if S & (S - 1):
        raise ValueError(
            "stacked E-step needs a power-of-two alphabet (the z-normalized "
            "stats lowering; family.reduced_stats_eligible gates this)"
        )
    N, T = chunks.shape
    if prepared is None:
        prep = prep_mod.prepare_chunked(
            S, chunks, lengths, t_tile=t_tile, onehot=True
        )
    else:
        prep_mod.check_chunked(prepared, S, N, T, t_tile, True)
        prep = prepared
    steps2, lens2, Tt = prep.steps2, prep.lens2, prep.Tt
    valid0 = lens2[0] > 0
    NL = steps2.shape[1]

    As, gts, a0_raws, beta0s = [], [], [], []
    for params in params_list:
        K = params.n_states
        A = jnp.exp(params.log_A).astype(jnp.float32)
        B = jnp.exp(params.log_B).astype(jnp.float32)
        pi = jnp.exp(params.log_pi).astype(jnp.float32)
        B0 = _emit_sel(B, steps2[0, :], K, S)
        a0_raws.append(
            jnp.where(valid0[None, :], pi[:, None] * B0, jnp.ones((K, NL)) / K)
        )
        beta0s.append(jnp.ones((K, NL), jnp.float32))
        As.append(A)
        gts.append(fb_onehot._groups(params))

    al_list, _cs_list, b_list, esym2 = (
        fb_onehot.run_fb_kernels_onehot_stacked(
            params_list, prep.sel2, jnp.int32(0), lens2, a0_raws, beta0s,
            Tt, T, pair_esym=(prep.pair2, prep.esym2, prep.pairn2),
            fused=fused,
        )
    )
    if fused:
        # Z-normalized stats over the fused self-normalized streams; zero
        # enters + an all-zero pair0 mask = independent records per lane
        # (the single-model fused chunked convention).
        same_K = len({p.n_states for p in params_list}) == 1
        if same_K or jax.default_backend() != "tpu":
            stats_l = fb_onehot.run_seq_stats_onehot_stacked(
                params_list, al_list, b_list, prep.pair2, lens2, gts,
                [jnp.zeros((fb_onehot.GROUP, NL), jnp.float32)] * M,
                [
                    jnp.zeros((p.n_states, NL), jnp.float32)
                    for p in params_list
                ],
                jnp.zeros((1, NL), jnp.float32),
                Tt,
            )
        else:
            # Mixed-K member sets on chip: the stacked stats kernel slices
            # per-member VMEM rows statically, so fall back to per-member
            # stats passes (throughput contractions — the stacked chain
            # launches above still carry the fixed-cost win).
            stats_l = [
                fb_onehot.run_seq_stats_onehot(
                    params_list[m], al_list[m], b_list[m], prep.pair2,
                    lens2, gts[m],
                    jnp.zeros((fb_onehot.GROUP, NL), jnp.float32),
                    jnp.zeros((params_list[m].n_states, NL), jnp.float32),
                    jnp.zeros((1, NL), jnp.float32),
                    Tt,
                )
                for m in range(M)
            ]
    else:
        # The split arm's cs-scaled betas pair with the chunked reduced
        # stats kernel, exactly like the single-model fused=False route.
        stats_l = [
            fb_onehot.run_stats_onehot(
                params_list[m], al_list[m], b_list[m], prep.pair2, lens2,
                gts[m], Tt,
                betas_scale=fb_onehot.beta_scale_of(fused=fused),
            )
            for m in range(M)
        ]
    out = []
    for m, params in enumerate(params_list):
        macc, emit_red, ll = stats_l[m]
        trans, emit, loglik = _assemble_reduced_stats(
            params, As[m], gts[m], macc, emit_red, ll
        )
        init_l = jnp.where(
            valid0[None, :],
            _gamma0_full(al_list[m], b_list[m], gts[m], esym2,
                         params.n_states),
            0.0,
        )
        out.append(
            SuffStats(
                init=jnp.sum(init_l, axis=1),
                trans=trans,
                emit=emit,
                loglik=loglik,
                n_seqs=jnp.sum(valid0.astype(jnp.int32)),
            )
        )
    return tuple(out)


# graftscale (Layer 6) declarations — see fb_onehot.SCALE_TAGS for the
# convention.  The fused posterior's gamma-normalize + MPM argmax must
# erase any per-position beta scale (the r9 self-normalized backward).
SCALE_TAGS = {
    "_conf_path_from_streams": {
        "tagged": "betas", "mode": "linear",
        "outputs": {"conf": "free", "path": "free"},
    },
}
