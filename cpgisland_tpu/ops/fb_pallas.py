"""Pallas TPU kernels for the forward-backward E-step (rescaled numerics).

The XLA E-step (ops.forward_backward._chunk_stats_rescaled) vmaps a
[K]-carry `lax.scan` over the chunk batch; with K=8 riding the minor dimension
that leaves the VPU lanes mostly idle.  These kernels put the chunk batch on
the 128-wide lane dimension (one chunk per lane, like ops.viterbi_pallas) and
fuse the per-step emission select, normalize, and statistics accumulation:

- **forward kernel** — per t-tile: alpha recurrence with Rabiner per-step
  rescaling; streams alphas [T, K, lanes] and normalizers [T, lanes] to HBM
  (36 B/symbol — far under HBM bandwidth at these op intensities; no
  checkpoint/recompute needed at K=8).
- **backward kernel** — walks t-tiles in reverse (reversed index_map),
  consuming the stored alphas and accumulating the [K,K] transition and
  [K,S] emission expected counts in VMEM scratch; per-tile boundary values
  (o_{t+1}, c_{t+1}) carry through scratch.

Grid order note: the t-tile dimension is the innermost grid axis, so each
lane-tile's t-tiles run consecutively and VMEM scratch carries state between
them (the canonical multi-pass reduction pattern).

Semantics match the rescaled XLA path to float tolerance (same masking rules:
invalid steps are identity, empty chunks contribute exactly-zero statistics).
The reference equivalent is Mahout's Hadoop Baum-Welch mapper
(CpGIslandFinder.java:200-201, the "rescaling" numerics at :92).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.ops.viterbi_pallas import MAX_PACK_STATES, _interpret, _vspec

LANE_TILE = 128
DEFAULT_T_TILE = 512


def supports(params: HmmParams) -> bool:
    # No packing constraint here, but keep the same "small state space on
    # sublanes" envelope as the decode kernels.
    return params.n_states <= MAX_PACK_STATES


def _emit_sel(B, syms, K, S):
    """Bsel[k, :] = B[k, syms[:]] via an unrolled compare-select tree."""
    out = jnp.zeros((K, syms.shape[-1]), jnp.float32)
    for s in range(S):
        out = jnp.where((syms == s)[None, :], B[:, s][:, None], out)
    return out


def _fwd_kernel(steps_ref, lens_ref, alpha0_ref, c0_ref, A_ref, B_ref,
                alphas_ref, cs_ref, carry_ref, *, K, S, Tt):
    j = pl.program_id(1)
    A = A_ref[:, :]
    B = B_ref[:, :]
    lens = lens_ref[0, :]
    alpha_in = jnp.where(j == 0, alpha0_ref[:, :], carry_ref[:, :])

    def body(tl, alpha):
        t = j * Tt + tl
        o_t = steps_ref[tl, :]
        v_t = t < lens
        raw = jnp.sum(alpha[:, None, :] * A[:, :, None], axis=0) * _emit_sel(B, o_t, K, S)
        c = jnp.sum(raw, axis=0)
        new = raw / c
        new = jnp.where(v_t[None, :], new, alpha)
        c = jnp.where(v_t, c, 1.0)
        # t == 0 has no incoming transition: its (alpha, c) come precomputed.
        new = jnp.where(t == 0, alpha0_ref[:, :], new)
        c = jnp.where(t == 0, c0_ref[0, :], c)
        alphas_ref[tl, :, :] = new
        cs_ref[tl, :] = c
        return new

    carry_ref[:, :] = jax.lax.fori_loop(0, Tt, body, alpha_in)


def _bwd_kernel(steps_ref, lens_ref, A_ref, B_ref, alphas_ref, cs_ref,
                trans_ref, emit_ref, beta0_ref,
                beta_scr, onext_scr, cnext_scr,
                *, K, S, Tt, T):
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = steps_ref.shape[1]
    A = A_ref[:, :]
    B = B_ref[:, :]
    lens = lens_ref[0, :]
    t0 = (n_t - 1 - j) * Tt

    @pl.when(j == 0)
    def _init():
        beta_scr[:, :] = jnp.ones((K, lt), jnp.float32)
        trans_ref[:, :] = jnp.zeros((K * K, lt), jnp.float32)
        emit_ref[:, :] = jnp.zeros((K * S, lt), jnp.float32)
        onext_scr[0, :] = jnp.zeros((lt,), jnp.int32)
        cnext_scr[0, :] = jnp.ones((lt,), jnp.float32)

    def body(tl_rev, carry):
        beta_next, trans, emit = carry
        tl = Tt - 1 - tl_rev
        t = t0 + tl
        # The XLA bstep covers t in [0, T-2]; position T-1 only seeds carries.
        active = t <= T - 2
        o_t = steps_ref[tl, :]
        alpha_t = alphas_ref[tl, :, :]
        at_edge = tl == Tt - 1
        tl_n = jnp.minimum(tl + 1, Tt - 1)
        o_next = jnp.where(at_edge, onext_scr[0, :], steps_ref[tl_n, :])
        c_next = jnp.where(at_edge, cnext_scr[0, :], cs_ref[tl_n, :])
        v_t = t < lens
        v_next = (t + 1) < lens

        w = _emit_sel(B, o_next, K, S) * beta_next / c_next  # [K, lt]
        xi = alpha_t[:, None, :] * (A[:, :, None] * w[None, :, :])
        trans = trans + jnp.where((active & v_next)[None, None, :], xi, 0.0)
        beta_t = jnp.sum(A[:, :, None] * w[None, :, :], axis=1)
        beta_t = jnp.where((active & v_next)[None, :], beta_t, beta_next)
        gamma_t = alpha_t * beta_t
        gamma_t = gamma_t / jnp.maximum(jnp.sum(gamma_t, axis=0), 1e-30)
        gamma_t = jnp.where((active & v_t)[None, :], gamma_t, 0.0)
        sel = jnp.stack([(o_t == s).astype(jnp.float32) for s in range(S)], axis=0)
        emit = emit + gamma_t[:, None, :] * sel[None, :, :]  # [K, S, lt]
        return beta_t, trans, emit

    beta, trans, emit = jax.lax.fori_loop(
        0,
        Tt,
        body,
        (
            beta_scr[:, :],
            trans_ref[:, :].reshape(K, K, lt),
            emit_ref[:, :].reshape(K, S, lt),
        ),
    )
    beta_scr[:, :] = beta
    trans_ref[:, :] = trans.reshape(K * K, lt)
    emit_ref[:, :] = emit.reshape(K * S, lt)
    onext_scr[0, :] = steps_ref[0, :]
    cnext_scr[0, :] = cs_ref[0, :]

    @pl.when(j == n_t - 1)
    def _finish():
        beta0_ref[:, :] = beta


def _pad_axis(x, size, axis, fill):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


@functools.partial(jax.jit, static_argnames=("t_tile",))
def batch_stats_pallas(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    t_tile: int = DEFAULT_T_TILE,
) -> SuffStats:
    """Pallas twin of ops.forward_backward.batch_stats(mode="rescaled").

    chunks: [N, T] (padded), lengths: [N].  Returns batch-summed SuffStats.
    """
    K, S = params.n_states, params.n_symbols
    N, T = chunks.shape
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    pi = jnp.exp(params.log_pi).astype(jnp.float32)

    lengths = lengths.astype(jnp.int32)
    obs_c = jnp.where(
        jnp.arange(T)[None, :] < lengths[:, None],
        jnp.minimum(chunks.astype(jnp.int32), S - 1),
        0,
    )

    NL = -(-N // LANE_TILE) * LANE_TILE
    Tt = min(t_tile, T)
    n_t = -(-T // Tt)
    Tp = n_t * Tt
    steps2 = _pad_axis(_pad_axis(obs_c.T, Tp, 0, 0), NL, 1, 0)  # [Tp, NL]
    lens2 = _pad_axis(lengths[None, :], NL, 1, 0)  # [1, NL]
    valid0 = lens2[0] > 0  # [NL]

    # alpha0 in JAX (one position; the kernels handle t >= 1).
    B0 = _emit_sel(B, steps2[0, :], K, S)  # [K, NL]
    a0_raw = jnp.where(valid0[None, :], pi[:, None] * B0, jnp.ones((K, NL)) / K)
    c0 = jnp.sum(a0_raw, axis=0)
    alpha0 = a0_raw / c0

    n_lt = NL // LANE_TILE
    grid = (n_lt, n_t)
    interpret = _interpret()
    mat_spec = _vspec((K, K), lambda i, j: (0, 0))
    emitmat_spec = _vspec((K, S), lambda i, j: (0, 0))
    lane_spec = _vspec((1, LANE_TILE), lambda i, j: (0, i))
    klane_spec = _vspec((K, LANE_TILE), lambda i, j: (0, i))
    step_spec = _vspec((Tt, LANE_TILE), lambda i, j: (j, i))

    alphas, cs = pl.pallas_call(
        functools.partial(_fwd_kernel, K=K, S=S, Tt=Tt),
        grid=grid,
        in_specs=[step_spec, lane_spec, klane_spec, lane_spec, mat_spec, emitmat_spec],
        out_specs=[
            _vspec((Tt, K, LANE_TILE), lambda i, j: (j, 0, i)),
            step_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Tp, K, NL), jnp.float32),
            jax.ShapeDtypeStruct((Tp, NL), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((K, LANE_TILE), jnp.float32)],
        interpret=interpret,
    )(steps2, lens2, alpha0, c0[None, :], A, B)

    # Reversed t-walk: input/output t-blocks indexed by (n_t-1-j).
    rev_step_spec = _vspec((Tt, LANE_TILE), lambda i, j: (n_t - 1 - j, i))
    trans_l, emit_l, beta0 = pl.pallas_call(
        functools.partial(_bwd_kernel, K=K, S=S, Tt=Tt, T=T),
        grid=grid,
        in_specs=[
            rev_step_spec,
            lane_spec,
            mat_spec,
            emitmat_spec,
            _vspec((Tt, K, LANE_TILE), lambda i, j: (n_t - 1 - j, 0, i)),
            rev_step_spec,
        ],
        out_specs=[
            _vspec((K * K, LANE_TILE), lambda i, j: (0, i)),
            _vspec((K * S, LANE_TILE), lambda i, j: (0, i)),
            klane_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K * K, NL), jnp.float32),
            jax.ShapeDtypeStruct((K * S, NL), jnp.float32),
            jax.ShapeDtypeStruct((K, NL), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((K, LANE_TILE), jnp.float32),
            pltpu.VMEM((1, LANE_TILE), jnp.int32),
            pltpu.VMEM((1, LANE_TILE), jnp.float32),
        ],
        interpret=interpret,
    )(steps2, lens2, A, B, alphas, cs)

    # Assembly in JAX (cheap, [NL]-sized): loglik, gamma0, tail-emission fix,
    # empty-lane zeroing, lane-sum reduction.
    tmask = jnp.arange(Tp)[:, None] < lens2  # [Tp, NL]
    loglik = jnp.sum(jnp.where(tmask & valid0[None, :], jnp.log(cs), 0.0))

    gamma0 = alpha0 * beta0
    gamma0 = gamma0 / jnp.maximum(jnp.sum(gamma0, axis=0), 1e-30)
    init_l = jnp.where(valid0[None, :], gamma0, 0.0)  # [K, NL]

    # Final-position emission: the backward walk stops at T-2; position
    # length-1 is covered there for padded chunks (identity pad steps give it
    # beta = beta_next), so only unpadded chunks (length == T) need the fix —
    # mirroring the XLA path's (length == T) correction.
    alphaT = alphas[T - 1]  # [K, NL] — alpha at the last real row
    gl = alphaT / jnp.maximum(jnp.sum(alphaT, axis=0), 1e-30)
    is_full = (lens2[0] == T) & valid0
    oT = steps2[T - 1, :]
    selT = _emit_sel(jnp.eye(S, dtype=jnp.float32), oT, S, S)  # [S, NL] one-hot
    emit_l = emit_l.reshape(K, S, NL) + (
        gl[:, None, :] * selT[None, :, :] * is_full[None, None, :]
    )

    return SuffStats(
        init=jnp.sum(init_l, axis=1),
        trans=jnp.sum(trans_l.reshape(K, K, NL), axis=2),
        emit=jnp.sum(emit_l, axis=2),
        loglik=loglik,
        n_seqs=jnp.sum(valid0.astype(jnp.int32)),
    )
