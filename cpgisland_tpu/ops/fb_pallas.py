"""Pallas TPU kernels for the forward-backward E-step (rescaled numerics).

The XLA E-step (ops.forward_backward._chunk_stats_rescaled) vmaps a
[K]-carry `lax.scan` over the chunk batch; with K=8 riding the minor dimension
that leaves the VPU lanes mostly idle.  These kernels put the chunk batch on
the 128-wide lane dimension (one chunk per lane, like ops.viterbi_pallas) and
fuse the per-step emission select, normalize, and statistics accumulation:

- **forward kernel** — per t-tile: alpha recurrence with DEFERRED Rabiner
  rescaling (stored v_t = alpha-hat_t * c_t; each step divides by the
  previous step's sum, so the sum computes off the sequential critical
  path); streams only the v's [T, K, lanes] to HBM (32 B/symbol — far under
  HBM bandwidth at these op intensities; no checkpoint/recompute needed at
  K=8).  The scale factors come back as time-parallel row sums in JAX.
- **backward kernel** — walks t-tiles in reverse (reversed index_map),
  storing ONLY the scaled beta vectors; per-tile boundary values
  (o_{t+1}, c_{t+1}) carry through scratch.  The [K,K]/[K,S] expected-count
  tensors are then TIME-PARALLEL contractions over the streamed
  alphas/betas in the JAX assembly (two einsums + S masked sums) — moving
  them out of the sequential per-step loop bought ~17% end to end.

Grid order note: the t-tile dimension is the innermost grid axis, so each
lane-tile's t-tiles run consecutively and VMEM scratch carries state between
them (the canonical multi-pass reduction pattern).

Semantics match the rescaled XLA path to float tolerance (same masking rules:
invalid steps are identity, empty chunks contribute exactly-zero statistics).
The reference equivalent is Mahout's Hadoop Baum-Welch mapper
(CpGIslandFinder.java:200-201, the "rescaling" numerics at :92).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.ops.viterbi_pallas import MAX_PACK_STATES, _interpret, _vspec

LANE_TILE = 128
DEFAULT_T_TILE = 512


def supports(params: HmmParams) -> bool:
    # No packing constraint here, but keep the same "small state space on
    # sublanes" envelope as the decode kernels.
    return params.n_states <= MAX_PACK_STATES


def _emit_sel(B, syms, K, S):
    """Bsel[k, :] = B[k, syms[:]] via an unrolled compare-select tree."""
    out = jnp.zeros((K, syms.shape[-1]), jnp.float32)
    for s in range(S):
        out = jnp.where((syms == s)[None, :], B[:, s][:, None], out)
    return out


def _emit_sel_cols(B, syms, K):
    """Bsel[t, k, n] = B[k, syms[t, n]] — the [Tp, NL] batch variant."""
    out = jnp.zeros((syms.shape[0], K, syms.shape[1]), jnp.float32)
    for s in range(B.shape[1]):
        out = jnp.where((syms == s)[:, None, :], B[:, s][None, :, None], out)
    return out


ROW_TILE = 8  # sublane count of an (8, 128) f32/i32 VMEM tile


def _fwd_kernel(steps_ref, lens_ref, alpha0raw_ref, A_ref, B_ref,
                alphas_ref, carry_ref, *, K, S, Tt):
    # Row-tiled walk: dynamic sublane offsets into (8,128)-tiled VMEM must be
    # 8-aligned for Mosaic's fast path (see the ROW_TILE note in
    # viterbi_pallas.py), so steps move as aligned [8, lt] tiles with the
    # per-row recurrence unrolled — the per-step misaligned row load/store
    # was >3x the arithmetic cost of the recurrence itself.
    #
    # Deferred normalization: the stored value is v_t = raw_t / sum(v_{t-1}),
    # i.e. alpha-hat_t SCALED BY the Rabiner factor c_t (v_0 = pi*B[:,o_0]
    # unnormalized, so sum(v_0) = c_0; inductively sum(v_t) = c_t).  Values
    # stay O(1), the JAX assembly recovers cs as plain row sums, and the
    # step's own sum leaves the sequential dependency chain: 1/sum(v_{t-1})
    # computes concurrently with step t's multiply-add tree instead of
    # serializing normalize -> next step.
    j = pl.program_id(1)
    A = A_ref[:, :]
    B = B_ref[:, :]
    lens = lens_ref[0, :]
    v_in = jnp.where(j == 0, alpha0raw_ref[:, :], carry_ref[:, :])

    def body(tile_i, v):
        base = tile_i * ROW_TILE
        o_tile = steps_ref[pl.ds(base, ROW_TILE), :]  # aligned [8, lt]
        for r in range(ROW_TILE):
            t = j * Tt + base + r
            o_t = o_tile[r, :]
            v_t = t < lens
            raw = jnp.sum(v[:, None, :] * A[:, :, None], axis=0) * _emit_sel(B, o_t, K, S)
            new = raw * (1.0 / jnp.sum(v, axis=0))
            new = jnp.where(v_t[None, :], new, v)
            # t == 0 has no incoming transition: v_0 is the precomputed init.
            new = jnp.where(t == 0, alpha0raw_ref[:, :], new)
            alphas_ref[base + r, :, :] = new  # [K, lt] = one full tile row
            v = new
        return v

    carry_ref[:, :] = jax.lax.fori_loop(0, Tt // ROW_TILE, body, v_in)


def _bwd_kernel(steps_ref, lens_ref, A_ref, B_ref, cs_ref,
                betas_ref,
                beta_scr, onext_scr, cnext_scr,
                *, K, S, Tt, T):
    """Reverse t-walk storing ONLY the scaled beta vectors.

    The count tensors are NOT accumulated here (an earlier version did and
    spent ~60 vreg ops/step on xi/gamma outer products inside the sequential
    loop) — they become time-parallel contractions over the stored
    alphas/betas in the JAX assembly below, where the MXU/VPU can batch them.
    Per-step work is just the beta recurrence, comparable to the forward.
    """
    j = pl.program_id(1)
    n_t = pl.num_programs(1)
    lt = steps_ref.shape[1]
    A = A_ref[:, :]
    B = B_ref[:, :]
    lens = lens_ref[0, :]
    t0 = (n_t - 1 - j) * Tt

    @pl.when(j == 0)
    def _init():
        beta_scr[:, :] = jnp.ones((K, lt), jnp.float32)
        onext_scr[0, :] = jnp.zeros((lt,), jnp.int32)
        cnext_scr[0, :] = jnp.ones((lt,), jnp.float32)

    # NOTE: not row-tiled like the forward — the 8-row reversed unroll with
    # cross-row (o_next, c_next) carries hits a TPU compiler abort (SIGABRT
    # in the Mosaic pipeline); the per-step dynamic row reads here cost ~25%
    # of the pass, acceptable until the toolchain moves.
    def body(tl_rev, beta_next):
        tl = Tt - 1 - tl_rev
        t = t0 + tl
        # beta_{T-1} = 1 (the init); the recurrence covers t <= T-2.
        active = t <= T - 2
        at_edge = tl == Tt - 1
        tl_n = jnp.minimum(tl + 1, Tt - 1)
        o_next = jnp.where(at_edge, onext_scr[0, :], steps_ref[tl_n, :])
        c_next = jnp.where(at_edge, cnext_scr[0, :], cs_ref[tl_n, :])
        v_next = (t + 1) < lens

        w = _emit_sel(B, o_next, K, S) * beta_next / c_next  # [K, lt]
        beta_t = jnp.sum(A[:, :, None] * w[None, :, :], axis=1)
        beta_t = jnp.where((active & v_next)[None, :], beta_t, beta_next)
        betas_ref[tl, :, :] = beta_t
        return beta_t

    beta = jax.lax.fori_loop(0, Tt, body, beta_scr[:, :])
    beta_scr[:, :] = beta
    onext_scr[0, :] = steps_ref[0, :]
    cnext_scr[0, :] = cs_ref[0, :]


def _pad_axis(x, size, axis, fill):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


@functools.partial(jax.jit, static_argnames=("t_tile",))
def batch_stats_pallas(
    params: HmmParams,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    t_tile: int = DEFAULT_T_TILE,
) -> SuffStats:
    """Pallas twin of ops.forward_backward.batch_stats(mode="rescaled").

    chunks: [N, T] (padded), lengths: [N].  Returns batch-summed SuffStats.
    """
    K, S = params.n_states, params.n_symbols
    N, T = chunks.shape
    A = jnp.exp(params.log_A).astype(jnp.float32)
    B = jnp.exp(params.log_B).astype(jnp.float32)
    pi = jnp.exp(params.log_pi).astype(jnp.float32)

    lengths = lengths.astype(jnp.int32)
    obs_c = jnp.where(
        jnp.arange(T)[None, :] < lengths[:, None],
        jnp.minimum(chunks.astype(jnp.int32), S - 1),
        0,
    )

    NL = -(-N // LANE_TILE) * LANE_TILE
    # Round the t-tile up to a ROW_TILE multiple: the row-tiled forward walks
    # whole 8-row tiles, and Tp-padding (pad rows are invalid -> identity /
    # masked) absorbs the excess when T itself is not a multiple.
    Tt = -(-min(t_tile, T) // ROW_TILE) * ROW_TILE
    n_t = -(-T // Tt)
    Tp = n_t * Tt
    steps2 = _pad_axis(_pad_axis(obs_c.T, Tp, 0, 0), NL, 1, 0)  # [Tp, NL]
    lens2 = _pad_axis(lengths[None, :], NL, 1, 0)  # [1, NL]
    valid0 = lens2[0] > 0  # [NL]

    # v_0 in JAX (one position, UNnormalized so sum(v_0) = c_0; the kernel
    # handles t >= 1 with deferred normalization — see _fwd_kernel).
    B0 = _emit_sel(B, steps2[0, :], K, S)  # [K, NL]
    a0_raw = jnp.where(valid0[None, :], pi[:, None] * B0, jnp.ones((K, NL)) / K)

    n_lt = NL // LANE_TILE
    grid = (n_lt, n_t)
    interpret = _interpret()
    mat_spec = _vspec((K, K), lambda i, j: (0, 0))
    emitmat_spec = _vspec((K, S), lambda i, j: (0, 0))
    lane_spec = _vspec((1, LANE_TILE), lambda i, j: (0, i))
    klane_spec = _vspec((K, LANE_TILE), lambda i, j: (0, i))
    step_spec = _vspec((Tt, LANE_TILE), lambda i, j: (j, i))

    (alphas,) = pl.pallas_call(
        functools.partial(_fwd_kernel, K=K, S=S, Tt=Tt),
        grid=grid,
        in_specs=[step_spec, lane_spec, klane_spec, mat_spec, emitmat_spec],
        out_specs=[
            _vspec((Tt, K, LANE_TILE), lambda i, j: (j, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Tp, K, NL), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((K, LANE_TILE), jnp.float32)],
        interpret=interpret,
    )(steps2, lens2, a0_raw, A, B)

    # The stored v_t = alpha-hat_t * c_t, so the Rabiner scale factors are
    # plain (time-parallel) row sums — they never sat on the kernel's
    # sequential critical path.
    cs = jnp.sum(alphas, axis=1)  # [Tp, NL]

    # Reversed t-walk: input/output t-blocks indexed by (n_t-1-j).
    rev_step_spec = _vspec((Tt, LANE_TILE), lambda i, j: (n_t - 1 - j, i))
    (betas,) = pl.pallas_call(
        functools.partial(_bwd_kernel, K=K, S=S, Tt=Tt, T=T),
        grid=grid,
        in_specs=[
            rev_step_spec,
            lane_spec,
            mat_spec,
            emitmat_spec,
            rev_step_spec,
        ],
        out_specs=[
            _vspec((Tt, K, LANE_TILE), lambda i, j: (n_t - 1 - j, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Tp, K, NL), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((K, LANE_TILE), jnp.float32),
            pltpu.VMEM((1, LANE_TILE), jnp.int32),
            pltpu.VMEM((1, LANE_TILE), jnp.float32),
        ],
        interpret=interpret,
    )(steps2, lens2, A, B, cs)

    # Count-tensor assembly: TIME-PARALLEL contractions over the streamed
    # alphas/betas — the expensive per-step outer products the old backward
    # kernel accumulated sequentially are now two einsums and S masked sums
    # that XLA batches over all (t, lane) at once.
    tmask = jnp.arange(Tp)[:, None] < lens2  # [Tp, NL]
    vmask = tmask & valid0[None, :]
    loglik = jnp.sum(jnp.where(vmask, jnp.log(cs), 0.0))

    # gamma_t = normalize(alpha_t * beta_t) at every valid position; the
    # stored beta at the last valid position is exactly 1 (pass-through from
    # the init), so position length-1's emission needs no special casing.
    graw = alphas * betas  # [Tp, K, NL]
    gamma = graw / jnp.maximum(jnp.sum(graw, axis=1, keepdims=True), 1e-30)
    gamma = jnp.where(vmask[:, None, :], gamma, 0.0)

    emit = jnp.stack(
        [jnp.sum(gamma * (steps2 == s)[:, None, :], axis=(0, 2)) for s in range(S)],
        axis=1,
    )  # [K, S]

    # xi(pair t-1 -> t) = alpha-hat_{t-1} (x) (B[:,o_t] * beta_t / c_t)
    # elementwise A: summing the outer products over (t, lane) is one
    # [K, T*N] x [T*N, K] dot.  Shifted SLICES (not a concatenated copy) —
    # position 0 has no incoming transition, so pairs are (t-1, t) for t >= 1
    # masked by v_t.  The stored v's carry a c_t scale, so a_prev divides it
    # back out (w's own /c_t is the formula's, not a descaling).
    w = _emit_sel_cols(B, steps2, K) * betas / cs[:, None, :]  # [Tp, K, NL]
    a_prev = jnp.where(
        vmask[1:, None, :], alphas[:-1] / cs[:-1, None, :], 0.0
    )
    trans = A * jnp.einsum("tin,tjn->ij", a_prev, w[1:], precision=jax.lax.Precision.HIGHEST)

    init_l = jnp.where(valid0[None, :], gamma[0], 0.0)  # [K, NL]

    return SuffStats(
        init=jnp.sum(init_l, axis=1),
        trans=trans,
        emit=emit,
        loglik=loglik,
        n_seqs=jnp.sum(valid0.astype(jnp.int32)),
    )
