"""One-hot-emission reduced Viterbi engine: 2x2 max-plus Pallas kernels.

The flagship 8-state CpG model (models.presets.durbin_cpg8, the reference's
hardcoded tables at CpGIslandFinder.java:155-173) has ONE-HOT emissions: state
X+/X- emits exactly symbol x (`:166-173`), and one-hot rows are EM fixed
points, so trained models keep the structure.  That structure collapses the
Viterbi DP: at time t the score vector is LOG_ZERO outside the (at most
G = K / n_symbols) states whose emission supports o_t, so the K-state
recurrence is EXACTLY a G-state recurrence whose per-step transition matrix is
the [G, G] slice of log A between the previous symbol's state group and the
current symbol's group.  For the 8-state model G = 2: the per-step work drops
from ~K^2 max/add lanes to 2x2, and backpointers pack 2 bits/step instead of
3 bits x 8 states — the "cheaper exact boundary-message scheme" the roofline
analysis in BASELINE.md calls for.

This module is the third `get_passes` engine ("onehot", next to "xla" and
"pallas").  Same three-pass contract as ops.viterbi_parallel — the kernels
run in the reduced space and tiny per-block scatters rebuild the full-K
interfaces (block products [nb, K, K], exit vectors [nb, K], composition
tables [nb, K]), so the shared stitching (`scan_block_products`,
`_enter_vectors`, `_suffix_compositions`, the shard_map bodies in
parallel.decode) is untouched.  Exactness vs the generic engines: the
reduced arithmetic performs the same f32 adds/maxes on the same values in
the same order and skips only candidates the generic engine computes at
~-1e30 and then discards — but the generic block products also carry finite
ANY-PREDECESSOR rows outside the entry group (irrelevant once composed with
an in-group entering vector, yet able to set the per-block normalizer), so
cross-engine results agree exactly as REAL numbers while f32 rounding of
the normalizer subtraction can differ in the last ulp.  Consequence: scores
match to ~1e-7 relative and paths match except where two path scores tie
within that rounding (both then being true argmaxes); the parity tests pin
exactly this contract.

Exactness domain (enforced by callers, see `supports` / resolve_engine):
- emissions one-hot with EQUAL group size G == 2 (each symbol emitted by
  exactly two states);
- the symbol BEFORE each segment's first step is known and real (`prev0`).
  Mid-sequence and tail PAD symbols are fully supported (identity steps, the
  forward-fill below); only a segment whose very first position has no real
  emission is outside the reduced representation — host entry points route
  those records to the generic engine.

Layout notes (the Mosaic constraints recorded in CLAUDE.md): all in-kernel
values are rank-2 (sublane, lane); dynamic row offsets are multiples of 8 —
backpointer words pack 8 steps each, and the packed-row loops work in
64-step outer tiles so every dynamic store lands 8-aligned.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pragma: no cover - mirrors ops.viterbi_pallas
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from cpgisland_tpu.models.hmm import LOG_ZERO, HmmParams
from cpgisland_tpu.ops.viterbi_parallel import scan_block_products

from cpgisland_tpu.family.partition import REDUCED_GROUP

LANE_TILE = 128
ROW_TILE = 8  # steps per packed backpointer word
OUTER_TILE = 64  # steps per aligned packed-row store (8 words of 8 steps)
# Reduced state dimension (2 bits of backpointer per step) — the family
# partition oracle's block size (one definition, family.partition).
GROUP = REDUCED_GROUP


def _vspec(block_shape=None, index_map=None):
    if _VMEM is None:
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _default_block_size(block_size, scores: bool, stacked_m: int = 1) -> int:
    """Resolve a ``block_size=None`` default through the graftune winner
    table (fresh applied ``flat.block`` winner -> table value; absent /
    stale / fingerprint-drifted -> the hard-coded 4096, bit for bit).
    Host-side only — explicit caller values pass through untouched, and
    the jit entries receive a concrete int."""
    if block_size is not None:
        return int(block_size)
    from cpgisland_tpu import tune

    return tune.default_block_size(scores=scores, stacked_m=stacked_m)


def _stacked_block_for(stacked_m: int, block_size: int, scores: bool) -> int:
    """Clamp a stacked flat decode's block size to the VMEM model's cap.

    The per-member score/path rows scale the kernel working set by M
    (viterbi_onehot VMEM note), so the shipped default bk=4096 does not
    fit M>=3 on chip — without this clamp every >=3-model stacked flush
    would trip the guard and permanently degrade to sequential dispatch,
    losing exactly the occupancy win PR 12 shipped.  TPU-only (the
    off-TPU XLA twins have no VMEM bound, and the bit-identity tests
    compare stacked vs single-model at the SAME block size there)."""
    if _interpret():
        return block_size
    from cpgisland_tpu.analysis import memmodel

    cap = memmodel.stacked_block_cap(stacked_m, scores=scores)
    if cap < block_size:
        from cpgisland_tpu import obs

        obs.event(
            "mem_clamp", _dedupe=True, site="decode_flat_stacked",
            requested=block_size, clamped=cap, stacked_m=stacked_m,
            scores=scores,
        )
        return cap
    return block_size


def _check_flat_block(bk: int, scores: bool, stacked_m: int = 1) -> None:
    """Static VMEM guard on the flat-decode block size (graftmem Layer 5).

    A too-large ``bk`` historically surfaced as an opaque scoped-VMEM
    compile failure minutes into a relay round trip (CLAUDE.md r5:
    bk >= 8192 on the batched route); the footprint model rejects it up
    front with the offending buffers named and a max-fit suggestion.
    TPU-only: the off-TPU XLA twins have no VMEM bound (and tests
    exercise large blocks there)."""
    if _interpret():
        return
    from cpgisland_tpu.analysis import memmodel

    f = memmodel.flat_block_feasibility(bk, scores=scores,
                                        stacked_m=stacked_m)
    if not f.ok:
        cap = memmodel.stacked_block_cap(stacked_m, scores=scores)
        from cpgisland_tpu import obs

        obs.event(
            "mem_reject", site="decode_flat_block", block_size=bk,
            stacked_m=stacked_m, predicted_bytes=f.total,
            vmem_limit_bytes=f.limit, max_fit_block=cap,
        )
        raise ValueError(
            f"decode_batch_flat: block_size={bk}"
            + (f" with {stacked_m} stacked members" if stacked_m > 1
               else "")
            + f" does not fit the VMEM model — {f.reason}; largest "
            f"feasible block here is {cap}"
        )


# ---------------------------------------------------------------------------
# Structure detection — thin wrappers over the family partition oracle
# (cpgisland_tpu.family.partition, the ONE copy of the eligibility logic
# the engine routers also consult).


def supports_concrete(params: HmmParams):
    """Tri-state eligibility: True/False on concrete params, None when the
    params are traced (undecidable at trace time — validation sites treat
    None as "trust the caller", auto-selection sites as "don't upgrade").
    Thin wrapper over family.partition.reduced_eligible_concrete."""
    from cpgisland_tpu.family.partition import reduced_eligible_concrete

    return reduced_eligible_concrete(params)


def supports(params: HmmParams) -> bool:
    """Host-side eligibility: the emission support partitions the states
    into one-hot blocks of exactly GROUP states per symbol
    (family.partition.reduced_eligible).  False under tracing — engine
    selection is a host decision; see parallel.decode.resolve_engine."""
    from cpgisland_tpu.family.partition import reduced_eligible

    return reduced_eligible(params)


def _groups(params: HmmParams) -> jnp.ndarray:
    """[S, GROUP] int32 group table (traced-params safe): gt[s] = the two
    state ids whose emission SUPPORT covers symbol s, ascending — the order
    that reproduces the generic engines' first-max tie-breaking.  The
    traced twin of family.partition's ``group_table`` metadata, derived
    from the support structure directly (not per-state argmax), so it is
    valid for any partition the oracle admits."""
    K, S = params.n_states, params.n_symbols
    supp = params.log_B > LOG_ZERO / 2  # [K, S]
    ar = jnp.arange(K, dtype=jnp.int32)
    low = jnp.min(jnp.where(supp.T, ar[None, :], K), axis=1)
    high = jnp.max(jnp.where(supp.T, ar[None, :], -1), axis=1)
    return jnp.stack([low, high], axis=1).astype(jnp.int32)


def pair_exit_syms(S: int) -> jnp.ndarray:
    """[S*S + S] exit symbol per pair index — THE pair-index encoding
    (p = s_prev * S + s_cur for real steps; S*S + carried symbol for PADs).
    Shared by the max-plus backtrace id table, the probability-space
    conf-mask table (ops.fb_onehot), and any future pair-indexed table, so
    the encoding cannot drift between them."""
    return jnp.concatenate(
        [jnp.tile(jnp.arange(S, dtype=jnp.int32), (S,)),
         jnp.arange(S, dtype=jnp.int32)]
    )


def _pair_table(params: HmmParams, gt: jnp.ndarray):
    """Per-pair reduced step matrices, flattened for the in-kernel select tree.

    Row p < S*S (p = s_prev * S + s_cur) holds the real-step matrix
    [T00, T01, T10, T11] with T[a, c] = logA[gt[s_prev, a], gt[s_cur, c]] +
    logB[gt[s_cur, c], s_cur] — the same two-term sum the generic kernels
    compute per lane, so values are bit-identical.  Rows S*S + e (one per
    carried symbol e) are the max-plus identity: PAD steps encode the carried
    symbol in their pair index so the backtrace can map bits to state ids at
    PAD positions too.

    Returns (tab [S*S + S, 4] f32, idtab [S*S + S, GROUP] i32) — idtab maps a
    pair index to the state ids of its EXIT group (the symbol emitted after
    the step), consumed by the backtrace kernel.
    """
    S = params.n_symbols
    A_red = params.log_A[gt[:, :, None, None], gt[None, None, :, :]]  # [S,2,S,2]
    B_red = params.log_B[gt, jnp.arange(S)[:, None]]  # [S, 2]
    M = A_red + B_red[None, None, :, :]  # [sp, a, sc, c]
    real = jnp.transpose(M, (0, 2, 1, 3)).reshape(S * S, 4).astype(jnp.float32)
    ident = jnp.broadcast_to(
        jnp.asarray([0.0, LOG_ZERO, LOG_ZERO, 0.0], jnp.float32), (S, 4)
    )
    tab = jnp.concatenate([real, ident], axis=0)
    idtab = gt[pair_exit_syms(S)]  # [S*S + S, GROUP]
    return tab, idtab


def _reset_rows(params: HmmParams, gt: jnp.ndarray):
    """RESET step matrices, one per record-start symbol o (the flat batch
    decoder, decode_batch_flat): T[a, c] = log_pi[gt[o, c]] +
    log_B[gt[o, c], o] for EVERY entering a — rank-one in max-plus, so
    (v ⊗ T)[c] = max(v) + v0red[c]: the chain restarts at record o's initial
    scores up to an additive constant, which argmax paths never see, and the
    backpointer compare a1 > a0 reduces to d1 > d0 — the previous record's
    true exit argmax.  _prepared inserts these at pair indices
    [S*S, S*S + S) — INSIDE the select tree's nreal range — and renumbers
    the PAD carries up to [S*S + S, S*S + 2S), where they stay select-tree
    defaults.
    """
    S = params.n_symbols
    v0red = params.log_pi[gt] + params.log_B[gt, jnp.arange(S)[:, None]]  # [S, 2]
    rows = jnp.concatenate([v0red, v0red], axis=1).astype(jnp.float32)  # [S, 4]
    return rows, gt  # idtab rows: exit group of symbol o = gt[o]


def device_entry_sym(obs_c: jnp.ndarray, pad_sym: int, axis: str,
                     prev0: jnp.ndarray) -> jnp.ndarray:
    """Symbol emitted by the state entering THIS device's shard (shard_map).

    The last real symbol on any earlier device, else the segment-level
    ``prev0``.  One tiny scalar all_gather; used by every reduced engine
    (max-plus decode and probability-space FB) — the reduced chains are
    conditioned on the entering symbol's state group."""
    L = obs_c.shape[0]
    iota = jnp.arange(L, dtype=jnp.int32)
    # Position and symbol tracked SEPARATELY: a combined iota*S+sym int32
    # key silently overflows for shards past 2**31/S (~537 Mi) symbols.
    pos = jnp.max(jnp.where(obs_c < pad_sym, iota, -1))
    symloc = jnp.where(
        pos >= 0, obs_c[jnp.maximum(pos, 0)].astype(jnp.int32), -1
    )
    syms = jax.lax.all_gather(symloc, axis)  # [D] scalars, -1 = all-PAD shard
    didx = jnp.arange(syms.shape[0], dtype=jnp.int32)
    d = jax.lax.axis_index(axis)
    gkey = jnp.where((didx < d) & (syms >= 0), didx * (pad_sym + 1) + syms, -1)
    m = jnp.max(gkey)
    return jnp.where(
        m >= 0, m - (m // (pad_sym + 1)) * (pad_sym + 1), prev0
    ).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Pair-stream glue (shared by all three passes; identical HLO -> CSE in-jit)


def _pair_stream(params: HmmParams, steps2: jnp.ndarray, prev0: jnp.ndarray):
    """Params-flavored wrapper of :func:`pair_stream` (kept for callers that
    hold a model; the stream itself is SYMBOL-ONLY — it reads nothing from
    the params but the alphabet size, which is static shape info)."""
    return pair_stream(params.n_symbols, steps2, prev0)


def pair_stream(S: int, steps2: jnp.ndarray, prev0: jnp.ndarray):
    """Per-step pair indices + per-block boundary symbols (symbol-only).

    steps2: [bk, nb] int32 transition symbols in block layout (global step
    b*bk + k at [k, b]); prev0: [] int32, the symbol emitted before step 0.

    Returns (pair2 [bk, nb] i32, e_in [nb], e_out [nb]) where e_in[b]/e_out[b]
    are the symbols emitted by the states entering/exiting block b (PADs
    resolved by forward-fill).  The fill is two-level so nothing T-sized and
    sequential is built: a cummax along the block axis resolves in-block PAD
    runs, and a tiny [nb] cummax threads the last real symbol across blocks.
    Depends only on the symbols and the alphabet size — the piece
    ops.prepared amortizes across EM iterations and pipeline passes.
    """
    bk, nb = steps2.shape
    real = steps2 < S
    iota = jax.lax.broadcasted_iota(jnp.int32, (bk, nb), 0)
    key = jnp.where(real, iota * S + steps2, -1)
    ckey = jax.lax.cummax(key, axis=0)
    in_sym = ckey - (ckey // S) * S  # valid where ckey >= 0
    # Cross-block seed: last real symbol of any earlier block, else prev0.
    last_key = jnp.where(ckey[-1] >= 0, in_sym[-1], -1)  # [nb]
    prev_blocks = jnp.concatenate([jnp.full((1,), -1, jnp.int32), last_key[:-1]])
    seed_key = jnp.where(
        prev_blocks >= 0, jnp.arange(nb, dtype=jnp.int32) * (S + 1) + prev_blocks, -1
    )
    seed_c = jax.lax.cummax(seed_key, axis=0)
    # prev0 is clamped so an out-of-domain PAD prev0 (first position has no
    # real emission — callers demote those records, see
    # parallel.decode._engine_for_record) still indexes inside the pair
    # table: behavior is then deterministic-but-approximate, never UB.
    seed = jnp.where(
        seed_c >= 0,
        seed_c - (seed_c // (S + 1)) * (S + 1),
        jnp.minimum(prev0, S - 1),
    )  # [nb]
    esym = jnp.where(ckey >= 0, in_sym, seed[None, :])  # [bk, nb]
    prev_esym = jnp.concatenate([seed[None, :], esym[:-1]], axis=0)
    pair2 = jnp.where(real, prev_esym * S + steps2, S * S + esym)
    return pair2.astype(jnp.int32), seed.astype(jnp.int32), esym[-1].astype(jnp.int32)


def _pad_lanes(x, nb_pad, fill):
    pad = nb_pad - x.shape[-1]
    if pad == 0:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)], constant_values=fill)


def _pad_pair_rows(pair2: jnp.ndarray, e_out: jnp.ndarray, ident_base: int):
    """Pad the step axis to a multiple of OUTER_TILE with per-lane identity
    pairs (ident_base + carried symbol — ident_base is S*S, or S*S + S for
    batch streams whose RESET rows occupy [S*S, S*S + S)), so padded steps
    stay PAD semantics AND keep the carried symbol decodable."""
    bk, nb = pair2.shape
    bk_pad = -(-bk // OUTER_TILE) * OUTER_TILE
    if bk_pad == bk:
        return pair2, bk_pad
    tail = jnp.broadcast_to((ident_base + e_out)[None, :], (bk_pad - bk, nb))
    return jnp.concatenate([pair2, tail], axis=0), bk_pad


def _select4(tile, tab_ref, nreal, ident=(0.0, LOG_ZERO, LOG_ZERO, 0.0),
             base=0):
    """In-kernel select tree: pair tile [8, LT] -> the 4 matrix-entry tiles.

    ``tab_ref`` is the lane-broadcast table [(nreal)*4, LANE_TILE] (row
    p*4 + j holds matrix entry j of pair p replicated across lanes — Mosaic
    supports [1, LT] sublane broadcasts but not [1, 1] scalar broadcasts).
    One compare per table row shared by all four selects; PAD pairs
    (p >= S*S) all carry the identity, so they fold into the ``ident``
    defaults — the max-plus identity here, the (+, x) identity (1, 0, 0, 1)
    for the probability-space twin (ops.fb_onehot).  ``base`` (static row
    offset) keys a MODEL's slice of a stacked multi-model table — member m
    of a stacked launch reads rows [base, base + 4*nreal) where
    base = m * 4 * nreal; the per-model arithmetic is unchanged, so stacked
    launches are bit-identical to the single-model kernels.
    """
    t00 = jnp.full(tile.shape, ident[0], jnp.float32)
    t01 = jnp.full(tile.shape, ident[1], jnp.float32)
    t10 = jnp.full(tile.shape, ident[2], jnp.float32)
    t11 = jnp.full(tile.shape, ident[3], jnp.float32)
    for p in range(nreal):
        cmp = tile == p
        r = base + 4 * p
        t00 = jnp.where(cmp, tab_ref[r : r + 1, :], t00)
        t01 = jnp.where(cmp, tab_ref[r + 1 : r + 2, :], t01)
        t10 = jnp.where(cmp, tab_ref[r + 2 : r + 3, :], t10)
        t11 = jnp.where(cmp, tab_ref[r + 3 : r + 4, :], t11)
    return t00, t01, t10, t11


def _bcast_tab(tab: jnp.ndarray, width: int = LANE_TILE) -> jnp.ndarray:
    """[n, m] table -> [n*m, width] lane-broadcast kernel operand (width =
    the consuming kernel's lane-tile size)."""
    flat = tab.reshape(-1)
    return jnp.broadcast_to(flat[:, None], (flat.shape[0], width))


# ---------------------------------------------------------------------------
# Kernels


def _oh_products_kernel(pair_ref, tab_ref, out_ref, *, nreal, bk):
    """Pass A: reduced max-plus product of the lane's steps -> [4, LT]
    (rows C00, C01, C10, C11 of the 2x2 block product)."""
    lt = pair_ref.shape[1]
    z = jnp.zeros((1, lt), jnp.float32)
    lz = jnp.full((1, lt), LOG_ZERO, jnp.float32)
    C = (z, lz, lz, z)  # identity

    def body(c, C):
        c00, c01, c10, c11 = C
        tile = pair_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]
        t00, t01, t10, t11 = _select4(tile, tab_ref, nreal)
        for r in range(ROW_TILE):
            a00 = t00[r : r + 1, :]
            a01 = t01[r : r + 1, :]
            a10 = t10[r : r + 1, :]
            a11 = t11[r : r + 1, :]
            # new[i, c] = max(C[i, 0] + T[0, c], C[i, 1] + T[1, c]); the
            # jnp.maximum(first, second) order matches the generic kernels'
            # ascending-m reduce, preserving bit-identical rounding.
            n00 = jnp.maximum(c00 + a00, c01 + a10)
            n01 = jnp.maximum(c00 + a01, c01 + a11)
            n10 = jnp.maximum(c10 + a00, c11 + a10)
            n11 = jnp.maximum(c10 + a01, c11 + a11)
            c00, c01, c10, c11 = n00, n01, n10, n11
        return c00, c01, c10, c11

    c00, c01, c10, c11 = jax.lax.fori_loop(0, bk // ROW_TILE, body, C)
    out_ref[0:1, :] = c00
    out_ref[1:2, :] = c01
    out_ref[2:3, :] = c10
    out_ref[3:4, :] = c11


def _oh_backpointers_kernel(
    pair_ref, venter_ref, tab_ref, bp_ref, dexit_ref, ebits_ref, *, nreal, bk
):
    """Pass B: reduced forward delta recursion with true entering vectors.

    Per step, 2 bits of backpointer (entry index per exit index) pack 8 steps
    to an int32 word; the exit->entry composition E packs GROUP bits."""
    lt = pair_ref.shape[1]
    d0 = venter_ref[0:1, :]
    d1 = venter_ref[1:2, :]
    E = jnp.full((1, lt), 0b10, jnp.int32)  # identity: exit c -> entry c

    def body(c, carry):
        d0, d1, E = carry
        words = []
        for t8 in range(OUTER_TILE // ROW_TILE):
            tile = pair_ref[pl.ds(c * OUTER_TILE + t8 * ROW_TILE, ROW_TILE), :]
            t00, t01, t10, t11 = _select4(tile, tab_ref, nreal)
            word = jnp.zeros((1, lt), jnp.int32)
            for r in range(ROW_TILE):
                a0 = d0 + t00[r : r + 1, :]
                a1 = d1 + t10[r : r + 1, :]
                b0 = d0 + t01[r : r + 1, :]
                b1 = d1 + t11[r : r + 1, :]
                # Strict > reproduces argmax first-max tie-breaking (prefer
                # the lower in-group state id, = the generic engines' choice).
                bp0 = (a1 > a0).astype(jnp.int32)
                bp1 = (b1 > b0).astype(jnp.int32)
                d0 = jnp.maximum(a0, a1)
                d1 = jnp.maximum(b0, b1)
                word = word | ((bp0 | (bp1 << 1)) << (2 * r))
                E = (jnp.right_shift(E, bp0) & 1) | (
                    ((jnp.right_shift(E, bp1) & 1)) << 1
                )
            words.append(word)
        bp_ref[pl.ds(c * (OUTER_TILE // ROW_TILE), OUTER_TILE // ROW_TILE), :] = (
            jnp.concatenate(words, axis=0)
        )
        return d0, d1, E

    d0, d1, E = jax.lax.fori_loop(0, bk // OUTER_TILE, body, (d0, d1, E))
    dexit_ref[0:1, :] = d0
    dexit_ref[1:2, :] = d1
    ebits_ref[:, :] = E


def _oh_backpointers_score_kernel(
    pair_ref, venter_ref, tab_ref, bp_ref, dexit_ref, ebits_ref, dmax_ref,
    *, nreal, bk
):
    """Pass B variant EMITTING the running chain max (score threading).

    Identical delta recursion to :func:`_oh_backpointers_kernel` plus one
    f32 row per step: dmax[k] = max(d0, d1) AFTER step k, relative to the
    block's normalized entering vector.  The flat batch decoder reads it
    back at each record's exit step — true chain max there = dmax +
    enter_offs[block] — and recovers exact per-record scores as first
    differences (the reset constants C_r telescope: C_r = sum of earlier
    records' scores = the chain max just before record r's reset).
    Score-only opt-in: the extra 4 B/step write is why the path-only
    decode keeps the 2-bit-only kernel.
    """
    lt = pair_ref.shape[1]
    d0 = venter_ref[0:1, :]
    d1 = venter_ref[1:2, :]
    E = jnp.full((1, lt), 0b10, jnp.int32)

    def body(c, carry):
        d0, d1, E = carry
        words = []
        for t8 in range(OUTER_TILE // ROW_TILE):
            tile = pair_ref[pl.ds(c * OUTER_TILE + t8 * ROW_TILE, ROW_TILE), :]
            t00, t01, t10, t11 = _select4(tile, tab_ref, nreal)
            word = jnp.zeros((1, lt), jnp.int32)
            drows = [None] * ROW_TILE
            for r in range(ROW_TILE):
                a0 = d0 + t00[r : r + 1, :]
                a1 = d1 + t10[r : r + 1, :]
                b0 = d0 + t01[r : r + 1, :]
                b1 = d1 + t11[r : r + 1, :]
                bp0 = (a1 > a0).astype(jnp.int32)
                bp1 = (b1 > b0).astype(jnp.int32)
                d0 = jnp.maximum(a0, a1)
                d1 = jnp.maximum(b0, b1)
                word = word | ((bp0 | (bp1 << 1)) << (2 * r))
                E = (jnp.right_shift(E, bp0) & 1) | (
                    ((jnp.right_shift(E, bp1) & 1)) << 1
                )
                drows[r] = jnp.maximum(d0, d1)
            words.append(word)
            dmax_ref[pl.ds(c * OUTER_TILE + t8 * ROW_TILE, ROW_TILE), :] = (
                jnp.concatenate(drows, axis=0)
            )
        bp_ref[pl.ds(c * (OUTER_TILE // ROW_TILE), OUTER_TILE // ROW_TILE), :] = (
            jnp.concatenate(words, axis=0)
        )
        return d0, d1, E

    d0, d1, E = jax.lax.fori_loop(0, bk // OUTER_TILE, body, (d0, d1, E))
    dexit_ref[0:1, :] = d0
    dexit_ref[1:2, :] = d1
    ebits_ref[:, :] = E


def _oh_backtrace_kernel(bp_ref, pair_ref, idtab_ref, exit_ref, path_ref, *, nP, bk):
    """Pass C: walk 2-bit backpointers from the anchored exit bit, emitting
    full STATE IDS (the pair index decodes the per-position exit group)."""
    nc = bk // OUTER_TILE

    def body(i, bit):
        c = nc - 1 - i
        words = bp_ref[pl.ds(c * (OUTER_TILE // ROW_TILE), OUTER_TILE // ROW_TILE), :]
        for t8 in range(OUTER_TILE // ROW_TILE - 1, -1, -1):
            tile = pair_ref[pl.ds(c * OUTER_TILE + t8 * ROW_TILE, ROW_TILE), :]
            # Per-position exit-group state ids via the lane-broadcast
            # pair->ids table (rows 2p / 2p+1 = low/high id of pair p).
            glow = jnp.zeros(tile.shape, jnp.int32)
            ghigh = jnp.zeros(tile.shape, jnp.int32)
            for p in range(nP):
                cmp = tile == p
                glow = jnp.where(cmp, idtab_ref[2 * p : 2 * p + 1, :], glow)
                ghigh = jnp.where(cmp, idtab_ref[2 * p + 1 : 2 * p + 2, :], ghigh)
            word = words[t8 : t8 + 1, :]
            rows = [None] * ROW_TILE
            for r in range(ROW_TILE - 1, -1, -1):
                rows[r] = jnp.where(bit == 0, glow[r : r + 1, :], ghigh[r : r + 1, :])
                bit = jnp.right_shift(word, 2 * r + bit) & 1
            path_ref[pl.ds(c * OUTER_TILE + t8 * ROW_TILE, ROW_TILE), :] = (
                jnp.concatenate(rows, axis=0)
            )
        return bit

    jax.lax.fori_loop(0, nc, body, exit_ref[:, :])


# ---------------------------------------------------------------------------
# Scatter glue: reduced block results -> full-K interfaces


def _scatter_products(red, gt, e_in, e_out, K, fill=LOG_ZERO):
    """[nb, 2, 2] reduced block products -> [nb, K, K] full.

    ``fill`` is the semiring zero: LOG_ZERO for max-plus, 0.0 for the
    probability-space twin (ops.fb_onehot)."""
    nb = red.shape[0]
    gin = gt[e_in]  # [nb, 2]
    gout = gt[e_out]  # [nb, 2]
    iK = jnp.arange(K, dtype=jnp.int32)
    full = jnp.full((nb, K, K), fill, jnp.float32)
    for a in range(GROUP):
        for c in range(GROUP):
            mask = (iK[None, :, None] == gin[:, a, None, None]) & (
                iK[None, None, :] == gout[:, c, None, None]
            )
            full = jnp.where(mask, red[:, a, c][:, None, None], full)
    return full


def _scatter_vec(red, gt, e_out, K):
    """[nb, 2] reduced exit vectors -> [nb, K] full (LOG_ZERO fill)."""
    gout = gt[e_out]
    iK = jnp.arange(K, dtype=jnp.int32)
    full = jnp.full((red.shape[0], K), LOG_ZERO, jnp.float32)
    for c in range(GROUP):
        full = jnp.where(iK[None, :] == gout[:, c, None], red[:, c, None], full)
    return full


def _scatter_ftab(ebits, gt, e_in, e_out, K):
    """Packed exit->entry bits -> [nb, K] state-id composition tables.

    Out-of-exit-group rows get the entry group's low state — they are never
    read (compositions only chase states that are valid exits; see the
    stitching in ops.viterbi_parallel / parallel.decode)."""
    gin = gt[e_in]  # [nb, 2]
    gout = gt[e_out]
    e0 = (ebits & 1).astype(jnp.int32)  # entry index reached from exit 0
    e1 = ((ebits >> 1) & 1).astype(jnp.int32)
    val0 = jnp.take_along_axis(gin, e0[:, None], axis=1)[:, 0]  # [nb]
    val1 = jnp.take_along_axis(gin, e1[:, None], axis=1)[:, 0]
    iK = jnp.arange(K, dtype=jnp.int32)
    full = jnp.broadcast_to(gin[:, 0, None], (gin.shape[0], K)).astype(jnp.int32)
    full = jnp.where(iK[None, :] == gout[:, 0, None], val0[:, None], full)
    full = jnp.where(iK[None, :] == gout[:, 1, None], val1[:, None], full)
    return full


# ---------------------------------------------------------------------------
# XLA lowering of the reduced passes (non-TPU backends).
#
# The Pallas interpreter executes these kernels pathologically slowly (the
# per-step select-derived backpointer chains blow up its evaluation; measured
# minutes for a 2000-symbol toy decode on CPU), so off-TPU the same reduced
# recurrences run as lax.scan over the lane axis instead.  The two lowerings
# are bit-identical: the one-hot table contraction at HIGHEST precision is an
# exact selection, and every add/max happens on the same values in the same
# order as in the kernels — the CPU suite certifies the algorithm against the
# generic engines, the TPU suite run certifies the kernels against the same
# tests.


def _sel_rows(tab: jnp.ndarray, pk: jnp.ndarray) -> jnp.ndarray:
    """Exact row selection tab[pk] as a one-hot contraction ([n] -> [n, m])."""
    oh = jax.nn.one_hot(pk, tab.shape[0], dtype=tab.dtype)
    return jnp.matmul(oh, tab, precision=jax.lax.Precision.HIGHEST)


def _xla_products(tab: jnp.ndarray, pair2: jnp.ndarray) -> jnp.ndarray:
    """Reduced per-block products [nb, 2, 2] via lax.scan over steps."""
    nb = pair2.shape[1]
    C0 = jnp.broadcast_to(
        jnp.asarray([0.0, LOG_ZERO, LOG_ZERO, 0.0], jnp.float32), (nb, 4)
    ) + (pair2[0, :, None] * 0).astype(jnp.float32)

    def step(C, pk):
        T = _sel_rows(tab, pk)  # [nb, 4] = (T00, T01, T10, T11)
        n00 = jnp.maximum(C[:, 0] + T[:, 0], C[:, 1] + T[:, 2])
        n01 = jnp.maximum(C[:, 0] + T[:, 1], C[:, 1] + T[:, 3])
        n10 = jnp.maximum(C[:, 2] + T[:, 0], C[:, 3] + T[:, 2])
        n11 = jnp.maximum(C[:, 2] + T[:, 1], C[:, 3] + T[:, 3])
        return jnp.stack([n00, n01, n10, n11], axis=1), None

    C, _ = jax.lax.scan(step, C0, pair2)
    return C.reshape(nb, GROUP, GROUP)


def _xla_backpointers(tab: jnp.ndarray, v_red: jnp.ndarray, pair2: jnp.ndarray):
    """Reduced delta recursion; returns (dexit [nb, 2], ebits [nb], bp2
    [bk, nb] int32 rows of bp0 | bp1 << 1)."""
    nb = pair2.shape[1]
    E0 = jnp.full((nb,), 0b10, jnp.int32)

    def step(carry, pk):
        d0, d1, E = carry
        T = _sel_rows(tab, pk)
        a0 = d0 + T[:, 0]
        a1 = d1 + T[:, 2]
        b0 = d0 + T[:, 1]
        b1 = d1 + T[:, 3]
        bp0 = (a1 > a0).astype(jnp.int32)
        bp1 = (b1 > b0).astype(jnp.int32)
        E = (jnp.right_shift(E, bp0) & 1) | ((jnp.right_shift(E, bp1) & 1) << 1)
        return (jnp.maximum(a0, a1), jnp.maximum(b0, b1), E), bp0 | (bp1 << 1)

    (d0, d1, E), bp2 = jax.lax.scan(step, (v_red[:, 0], v_red[:, 1], E0), pair2)
    return jnp.stack([d0, d1], axis=1), E, bp2


def _xla_backpointers_scores(tab: jnp.ndarray, v_red: jnp.ndarray, pair2: jnp.ndarray):
    """Score-threading twin of :func:`_xla_backpointers`: additionally emits
    dmax2 [bk, nb] = max(d0, d1) after each step (same recursion, same
    rounding — the extra max hangs off the chain)."""
    nb = pair2.shape[1]
    E0 = jnp.full((nb,), 0b10, jnp.int32)

    def step(carry, pk):
        d0, d1, E = carry
        T = _sel_rows(tab, pk)
        a0 = d0 + T[:, 0]
        a1 = d1 + T[:, 2]
        b0 = d0 + T[:, 1]
        b1 = d1 + T[:, 3]
        bp0 = (a1 > a0).astype(jnp.int32)
        bp1 = (b1 > b0).astype(jnp.int32)
        E = (jnp.right_shift(E, bp0) & 1) | ((jnp.right_shift(E, bp1) & 1) << 1)
        d0n = jnp.maximum(a0, a1)
        d1n = jnp.maximum(b0, b1)
        return (d0n, d1n, E), (bp0 | (bp1 << 1), jnp.maximum(d0n, d1n))

    (d0, d1, E), (bp2, dmax2) = jax.lax.scan(
        step, (v_red[:, 0], v_red[:, 1], E0), pair2
    )
    return jnp.stack([d0, d1], axis=1), E, bp2, dmax2


def _xla_backtrace_bits(bp2, exit_bits):
    """The bit walk of the reduced backtrace (ONE reverse scan): 2-bit rows
    [bk, nb] + exit bits [nb] -> per-position entry bits [bk, nb].  Shared
    by the single-model twin and the stacked lane-concatenated twin (the
    walk is elementwise across lanes, so concatenating members along the
    lane axis changes no member's arithmetic)."""

    def back(bit, row):
        return jnp.right_shift(row, bit) & 1, bit

    _, bits = jax.lax.scan(back, exit_bits, bp2, reverse=True)
    return bits


def _xla_backtrace(bp2, pair2, idtab, exit_bits):
    """Walk the 2-bit rows from the exit bits, emitting state ids [bk, nb]."""
    glow2 = jnp.take(idtab[:, 0], pair2)
    ghigh2 = jnp.take(idtab[:, 1], pair2)
    bits = _xla_backtrace_bits(bp2, exit_bits)
    return jnp.where(bits == 0, glow2, ghigh2)


# ---------------------------------------------------------------------------
# Pass-level API (the "onehot" engine for viterbi_parallel.get_passes)


def prepare_pairs(S: int, steps2: jnp.ndarray, prev0, resets=None):
    """Symbol-only pair stream for the decode passes, reset-renumbered.

    Returns (pair2, e_in, e_out, nreal) — everything `_prepared` derives
    from the symbols alone, factored out so a caller (or ops.prepared's
    cache) can amortize it across the three passes and across calls; the
    params-dependent tables stay in `_prepared`.

    ``resets`` (flat batch decoding): a [bk, nb] bool mask — step [k, b]
    (global step b*bk + k) is a RESET step into a record whose start symbol
    is steps2[k, b] (see _reset_rows).  RESET pairs renumber to
    [S*S, S*S + S) so they sit INSIDE the select tree's nreal range while
    PAD carries move up to [S*S + S, S*S + 2S) and stay tree DEFAULTS — 20
    compares, not 24.  ``resets`` is elementwise (fuses into the
    pair-stream computation — an .at[].set scatter here copied the whole
    4 B/step stream and measured ~19% of the batch decode).
    """
    if prev0 is None:
        raise ValueError("the onehot engine requires prev0 (the symbol before step 0)")
    steps2 = steps2.astype(jnp.int32)
    pair2, e_in, e_out = pair_stream(S, steps2, jnp.asarray(prev0, jnp.int32))
    nreal = S * S
    if resets is not None:
        is_pad = pair2 >= S * S
        pair2 = jnp.where(is_pad, pair2 + S, pair2)
        pair2 = jnp.where(
            resets, S * S + jnp.minimum(steps2, S - 1), pair2
        )
        nreal = S * S + S
    return pair2, e_in, e_out, nreal


def _prepared(params: HmmParams, steps2: jnp.ndarray, prev0, resets=None,
              pre=None):
    """Tables + pair stream for the passes.

    ``pre`` (from :func:`prepare_pairs`, possibly cached by ops.prepared):
    the symbol-only (pair2, e_in, e_out, nreal) tuple — it must have been
    built with the SAME ``resets`` mask, which still selects the reset-row
    table extension here.
    """
    S = params.n_symbols
    gt = _groups(params)
    tab, idtab = _pair_table(params, gt)
    if pre is None:
        pre = prepare_pairs(S, steps2, prev0, resets)
    pair2, e_in, e_out, nreal = pre
    if resets is not None:
        if nreal != S * S + S:
            raise ValueError(
                "prepared pair stream was built without the resets mask "
                "this call passes (nreal mismatch)"
            )
        rrows, rgt = _reset_rows(params, gt)
        tab = jnp.concatenate([tab[: S * S], rrows, tab[S * S :]], axis=0)
        idtab = jnp.concatenate([idtab[: S * S], rgt, idtab[S * S :]], axis=0)
    elif nreal != S * S:
        raise ValueError(
            "prepared pair stream carries reset renumbering but this call "
            "passes no resets mask"
        )
    return S, gt, tab, idtab, pair2, e_in, e_out, nreal


def pass_products(params: HmmParams, steps2: jnp.ndarray, prev0=None, resets=None,
                  pre=None):
    """Onehot twin of viterbi_parallel._pass_products: (incl, offs, total).

    ``pre``: a prepared (pair2, e_in, e_out, nreal) from :func:`prepare_pairs`
    (optional — inline prep otherwise; the same contract on every pass)."""
    K = params.n_states
    S, gt, tab, _, pair2, e_in, e_out, nreal = _prepared(
        params, steps2, prev0, resets, pre
    )
    nb = steps2.shape[1]
    if _interpret():
        red = _xla_products(tab, pair2)
    else:
        nb_pad = -(-nb // LANE_TILE) * LANE_TILE
        pair2 = _pad_lanes(pair2, nb_pad, jnp.int32(nreal))
        pair2, bk = _pad_pair_rows(pair2, _pad_lanes(e_out, nb_pad, 0), nreal)
        tabb = _bcast_tab(tab[:nreal])
        red_flat = pl.pallas_call(
            functools.partial(_oh_products_kernel, nreal=nreal, bk=bk),
            grid=(nb_pad // LANE_TILE,),
            in_specs=[
                _vspec((bk, LANE_TILE), lambda i: (0, i)),
                _vspec(tabb.shape, lambda i: (0, 0)),
            ],
            out_specs=_vspec((4, LANE_TILE), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((4, nb_pad), jnp.float32),
        )(pair2, tabb)
        red = red_flat.T.reshape(nb_pad, GROUP, GROUP)[:nb]
    P = _scatter_products(red, gt, e_in, e_out, K)
    incl, offs = scan_block_products(P)
    return incl, offs, incl[-1]


def _pass_backpointers_impl(params: HmmParams, v_enter: jnp.ndarray,
                            steps2: jnp.ndarray, prev0, resets, pre,
                            want_scores: bool):
    """The ONE pass-B wrapper (prep unpack, lane/row padding, pallas
    plumbing, scatter/blob assembly) behind both public variants —
    ``want_scores`` selects the score-threading kernel and its extra
    dmax2 [bk, nb] output (block-normalized per-step chain max)."""
    K = params.n_states
    S, gt, tab, idtab, pair2, e_in, e_out, nreal = _prepared(
        params, steps2, prev0, resets, pre
    )
    bk_real, nb = steps2.shape
    v_red = jnp.take_along_axis(v_enter, gt[e_in], axis=1)  # [nb, 2]
    ghigh_end = gt[e_out, 1]  # [nb] — exit-bit anchor conversion
    if _interpret():
        if want_scores:
            dexit_red, ebits_nb, bp2, dmax2 = _xla_backpointers_scores(
                tab, v_red.astype(jnp.float32), pair2
            )
        else:
            dexit_red, ebits_nb, bp2 = _xla_backpointers(
                tab, v_red.astype(jnp.float32), pair2
            )
            dmax2 = None
        delta_exit = _scatter_vec(dexit_red, gt, e_out, K)
        F = _scatter_ftab(ebits_nb, gt, e_in, e_out, K)
        blob = ("xla", bp2, pair2, idtab, ghigh_end, bk_real, nb)
        return delta_exit, F, blob, dmax2
    nb_pad = -(-nb // LANE_TILE) * LANE_TILE
    pair2 = _pad_lanes(pair2, nb_pad, jnp.int32(nreal))
    pair2, bk = _pad_pair_rows(pair2, _pad_lanes(e_out, nb_pad, 0), nreal)
    v_red2 = _pad_lanes(v_red.T.astype(jnp.float32), nb_pad, 0.0)
    tabb = _bcast_tab(tab[:nreal])
    kernel = (
        _oh_backpointers_score_kernel if want_scores else _oh_backpointers_kernel
    )
    out_specs = [
        _vspec((bk // ROW_TILE, LANE_TILE), lambda i: (0, i)),
        _vspec((GROUP, LANE_TILE), lambda i: (0, i)),
        _vspec((1, LANE_TILE), lambda i: (0, i)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((bk // ROW_TILE, nb_pad), jnp.int32),
        jax.ShapeDtypeStruct((GROUP, nb_pad), jnp.float32),
        jax.ShapeDtypeStruct((1, nb_pad), jnp.int32),
    ]
    if want_scores:
        out_specs.append(_vspec((bk, LANE_TILE), lambda i: (0, i)))
        out_shape.append(jax.ShapeDtypeStruct((bk, nb_pad), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(kernel, nreal=nreal, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((GROUP, LANE_TILE), lambda i: (0, i)),
            _vspec(tabb.shape, lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
    )(pair2, v_red2, tabb)
    bp_packed, dexit_red, ebits = outs[:3]
    delta_exit = _scatter_vec(dexit_red.T[:nb], gt, e_out, K)
    F = _scatter_ftab(ebits[0, :nb], gt, e_in, e_out, K)
    blob = ("pallas", bp_packed, pair2, idtab, ghigh_end, bk_real, nb)
    dmax2 = outs[3][:bk_real, :nb] if want_scores else None
    return delta_exit, F, blob, dmax2


def pass_backpointers(params: HmmParams, v_enter: jnp.ndarray, steps2: jnp.ndarray,
                      prev0=None, resets=None, pre=None):
    """Onehot twin of viterbi_parallel._pass_backpointers.

    Returns (delta_blocks [nb, K], F [nb, K], blob); the blob carries the
    packed 2-bit pointers plus the pair stream for the backtrace's bit->state
    mapping."""
    delta_exit, F, blob, _ = _pass_backpointers_impl(
        params, v_enter, steps2, prev0, resets, pre, want_scores=False
    )
    return delta_exit, F, blob


def pass_backpointers_scores(params: HmmParams, v_enter: jnp.ndarray,
                             steps2: jnp.ndarray, prev0=None, resets=None,
                             pre=None):
    """:func:`pass_backpointers` variant that also returns the per-step
    chain max dmax2 [bk, nb] (block-normalized — add the block's
    enter-offset for true values).  The flat batch decoder's score path."""
    return _pass_backpointers_impl(
        params, v_enter, steps2, prev0, resets, pre, want_scores=True
    )


def pass_backtrace(blob, exits: jnp.ndarray) -> jnp.ndarray:
    """Onehot twin of viterbi_parallel._pass_backtrace -> [bk*nb] state ids."""
    kind, bp, pair2, idtab, ghigh_end, bk_real, nb = blob
    exit_bits = (exits == ghigh_end).astype(jnp.int32)
    if kind == "xla":
        return _xla_backtrace(bp, pair2, idtab, exit_bits).T.reshape(-1)
    bk = pair2.shape[0]
    nb_pad = pair2.shape[1]
    nP = idtab.shape[0]
    exits2 = _pad_lanes(exit_bits[None, :], nb_pad, 0)
    idtabb = _bcast_tab(idtab)
    path2 = pl.pallas_call(
        functools.partial(_oh_backtrace_kernel, nP=nP, bk=bk),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk // ROW_TILE, LANE_TILE), lambda i: (0, i)),
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec(idtabb.shape, lambda i: (0, 0)),
            _vspec((1, LANE_TILE), lambda i: (0, i)),
        ],
        out_specs=_vspec((bk, LANE_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((bk, nb_pad), jnp.int32),
    )(bp, pair2, idtabb, exits2)
    return path2[:bk_real, :nb].T.reshape(-1)


# ---------------------------------------------------------------------------
# Flat batched decode (one kernel grid for N records — no vmap-of-pallas)


def prepare_decode_flat(
    S: int, chunks: jnp.ndarray, lengths: jnp.ndarray, block_size=None
):
    """Symbol-only prep of the flat batched decode.

    Returns (concat [N*T] clamped symbols, padded [nb*bk] step stream,
    resets [bk, nb] bool mask, bk, pre) where ``pre`` is the
    reset-renumbered :func:`prepare_pairs` tuple — exactly what
    :func:`decode_batch_flat` unpacks.  Mirrors its own derivation (it
    delegates here), so prepared-vs-inline decodes are bit-identical."""
    N, T = chunks.shape
    block_size = _default_block_size(block_size, scores=False)
    obs_c = jnp.where(
        jnp.arange(T)[None, :] >= lengths[:, None],
        S,
        jnp.minimum(chunks.astype(jnp.int32), S),
    )
    concat = obs_c.reshape(-1)
    Np = N * T
    n_steps = Np - 1
    bk = min(block_size, max(8, n_steps))
    nb = -(-n_steps // bk)
    padded = jnp.concatenate(
        [concat[1:], jnp.full(nb * bk - n_steps, S, jnp.int32)]
    )
    # Step r*T - 1 is the reset entering record r's position 0 — expressed
    # as an iota mask (elementwise; an index scatter on the [bk, nb] pair
    # stream copied 4 B/step and measured ~19% of the batch decode).  The
    # reset pair needs the record's FIRST symbol, which IS that step's own
    # symbol, so the mask alone is enough.  Layout matches _block_passes's
    # steps.reshape(nb, bk).T: entry [k, b] is global step b*bk + k.
    kk = jax.lax.broadcasted_iota(jnp.int32, (bk, nb), 0)
    bb = jax.lax.broadcasted_iota(jnp.int32, (bk, nb), 1)
    gstep = bb * bk + kk
    resets = ((gstep + 1) % T == 0) & (gstep + 1 < Np)
    steps2 = padded.reshape(nb, bk).T
    pre = prepare_pairs(S, steps2, concat[0], resets)
    return concat, padded, resets, bk, pre


def decode_batch_flat(
    params: HmmParams, chunks: jnp.ndarray, lengths: jnp.ndarray,
    block_size=None,
    prepared=None,
    return_score: bool = False,
):
    """Decode an [N, T] batch as ONE flat stream with RESET steps.

    The r4 batched path vmapped viterbi_parallel over records, and
    vmap-of-pallas loads batch-wide operand slabs into VMEM (measured 1004
    vs 1635 Msym/s single-stream at the same 64 MiB total; block sizes
    >= 8192 fail scoped-VMEM compile outright).  Instead the records
    concatenate into one sequence whose step into each record's position 0
    is a rank-one RESET matrix (_reset_rows): in max-plus, (v ⊗ reset)[c]
    = max(v) + v0red[c] — the chain restarts at the record's initial
    scores up to an additive constant that argmax paths cannot see, and
    the backpointer at the reset is the previous record's true exit
    argmax.  Every kernel then runs at single-stream occupancy.

    ``return_score=True`` additionally returns EXACT per-record Viterbi
    scores [N] from the flat stream itself (r6 — previously the vmap
    route's job, with its bk>=8192 scoped-VMEM compile failure): the
    reset constants TELESCOPE.  The true chain value inside record r is
    the record's own delta plus C_r = sum of earlier records' scores (a
    reset sets v = max(v_prev) + v0red, and max(v_prev) at record r-1's
    exit is score_{r-1} + C_{r-1}), so with M_r = the true chain max at
    record r's last position (its per-step block max from the
    score-threading backpointers kernel + that block's entering-offset
    from the normalized prefix scan), score_0 = M_0 and score_r =
    M_r - M_{r-1}.  f32 precision caveat — WORSE than vmap for late
    records: M_r carries the accumulated magnitude of ALL earlier records
    (~1.4 nats/symbol of concatenated stream), so record r's score
    quantizes at ulp(1.4 * sum of earlier lengths) — e.g. ~+-8 absolute
    64 MiB into a stream — where the vmap route's offsets accumulate only
    within the record (~ulp(1.4 * T_r)).  Exact in real arithmetic either
    way; batches needing per-record-magnitude score precision deep into a
    large batch should use the vmap opt-in (viterbi_parallel_batch's
    vmap_records=True) or per-record decodes.  The parity tests bound the
    flat error at the stream-ulp class.

    Paths equal the standalone/vmap onehot route modulo the engine's
    pinned rounding-tie contract (PARITY.md C10): the reset folds the
    previous record's max(v) constant into later f32 additions, so a
    tie-prone model can round near-ties differently — tie-free models
    decode identically, and any mismatch re-scores f64-identically.  Same
    first-symbol contract as the engine: records whose position 0 is PAD
    decode approximately (host entry points demote those to a dense
    engine).
    Returns paths [N, T] (positions >= lengths[r] carry the exit state,
    like viterbi_padded), or (paths, scores [N]) with ``return_score``.
    ``prepared`` (from :func:`prepare_decode_flat`): the symbol-only
    stream/reset/pair prep — build it once per batch when decoding the
    same placed batch repeatedly.
    """
    from cpgisland_tpu.ops.viterbi_parallel import _block_passes, _step_tables

    S = params.n_symbols
    N, T = chunks.shape
    if T < 2:
        raise ValueError("decode_batch_flat needs records of at least 2 symbols")
    if block_size is None and prepared is not None:
        # A caller-built prep pins the geometry: adopt ITS block rather
        # than re-consulting the tuned default (the flat.block and
        # flat.block.scores winners are separate swept tasks and may
        # legitimately diverge — an all-defaults prepared call must not
        # trip the stale-prep gate over that).
        block_size = prepared[3]
    block_size = _default_block_size(block_size, scores=return_score)
    if prepared is None:
        prepared = prepare_decode_flat(S, chunks, lengths, block_size)
    concat, padded, resets, bk, pre = prepared
    Np = N * T
    n_steps = Np - 1
    # A stale prep (different batch shape or block size) must raise, not
    # silently decode with a mismatched reset layout — the same gate as
    # ops.prepared.check_chunked for the other prepared consumers.
    want_bk = min(block_size, max(8, n_steps))
    if concat.shape[0] != Np or bk != want_bk:
        raise ValueError(
            f"prepared decode stream was built for {concat.shape[0]} "
            f"symbols / bk={bk}; this call needs {Np} symbols / "
            f"bk={want_bk} — rebuild it with prepare_decode_flat for this "
            "batch and block_size"
        )
    _check_flat_block(bk, scores=return_score)
    _, emit_ext = _step_tables(params)
    v0 = params.log_pi + emit_ext[concat[0]]

    dec = _block_passes(
        params, v0, padded, bk, engine="onehot", prev0=concat[0],
        resets=resets, pre=pre, want_scores=return_score,
    )
    s0 = dec.ftable[jnp.argmax(dec.delta_exit)]
    full = jnp.concatenate([s0[None], dec.path[:n_steps]])
    if not return_score:
        return full.reshape(N, T)

    # Record r's last position = global step (r+1)*T - 2's output; its true
    # chain max M_r = the block-relative running max + the block's entering
    # offset.  Scores are first differences (the reset constants telescope).
    e = (jnp.arange(N, dtype=jnp.int32) + 1) * T - 2
    b = e // bk
    M = dec.dmax2[e - b * bk, b] + dec.enter_offs[b]
    scores = jnp.concatenate([M[:1], M[1:] - M[:-1]])
    return full.reshape(N, T), scores


# ---------------------------------------------------------------------------
# Stacked multi-model passes: N members' reduced chains in ONE kernel launch.
#
# The r8 cost attribution proved the fixed per-pass cost is chain-drain
# LATENCY, not arithmetic, and the r9 fused fwd/bwd kernel proved two
# independent 2x2 chains interleave in one kernel with both filling VPU
# issue slots while either stalls.  Different MEMBERS' chains over the SAME
# pair stream are exactly as independent: the stacked kernels below carry M
# members' state (2 rows each) through one grid walk, selecting each
# member's step matrix from its slice of a stacked lane-broadcast table
# (``_select4``'s ``base`` offset — tables stacked as extra rows, broadcast
# OUTSIDE the kernel per the Mosaic rule).  The per-member arithmetic is
# the single-model kernel's, op for op, so member m's outputs are
# BIT-IDENTICAL to a single-model launch over the same stream.
#
# Off-TPU the twins reuse the single-model one-scan XLA twins over
# LANE-CONCATENATED streams: member m's pair indices offset by m * n_rows
# into a row-concatenated table, members side by side on the lane axis —
# one scan for all members, and exact (the one-hot table contraction adds
# only exact zeros; every chain op is elementwise across lanes).


def stacked_prepared(params_list, steps2, prev0, resets=None, pre=None):
    """The stacked twin of :func:`_prepared`: ONE shared symbol-only pair
    stream + per-member tables.  Returns (S, gts, tabs, idtabs, pair2,
    e_in, e_out, nreal) where gts/tabs/idtabs are per-member lists (reset
    rows spliced per member when ``resets`` is given — every member shares
    the reset MASK, each restarts into its own pi/emission scores)."""
    S = params_list[0].n_symbols
    for p in params_list[1:]:
        if p.n_symbols != S:
            raise ValueError(
                "stacked members must share one alphabet (pair stream); got "
                f"n_symbols {[int(q.n_symbols) for q in params_list]}"
            )
    if pre is None:
        pre = prepare_pairs(S, steps2, prev0, resets)
    pair2, e_in, e_out, nreal = pre
    want = S * S + (S if resets is not None else 0)
    if nreal != want:
        raise ValueError(
            "prepared pair stream's reset renumbering does not match this "
            f"call (nreal {nreal} != {want})"
        )
    gts, tabs, idtabs = [], [], []
    for p in params_list:
        gt = _groups(p)
        tab, idtab = _pair_table(p, gt)
        if resets is not None:
            rrows, rgt = _reset_rows(p, gt)
            tab = jnp.concatenate([tab[: S * S], rrows, tab[S * S :]], axis=0)
            idtab = jnp.concatenate(
                [idtab[: S * S], rgt, idtab[S * S :]], axis=0
            )
        gts.append(gt)
        tabs.append(tab)
        idtabs.append(idtab)
    return S, gts, tabs, idtabs, pair2, e_in, e_out, nreal


def _xla_products_stacked(tabs, pair2: jnp.ndarray) -> list:
    """ONE scan over M members' reduced max-plus block products —
    per-member arithmetic = :func:`_xla_products` (the shared one-hot row
    select contributes only exact zeros)."""
    M = len(tabs)
    nb = pair2.shape[1]
    C0 = tuple(
        jnp.broadcast_to(
            jnp.asarray([0.0, LOG_ZERO, LOG_ZERO, 0.0], jnp.float32), (nb, 4)
        )
        + (pair2[0, :, None] * 0).astype(jnp.float32)
        for _ in range(M)
    )

    def step(Cs, pk):
        new = []
        for m in range(M):
            T = _sel_rows(tabs[m], pk)
            C = Cs[m]
            n00 = jnp.maximum(C[:, 0] + T[:, 0], C[:, 1] + T[:, 2])
            n01 = jnp.maximum(C[:, 0] + T[:, 1], C[:, 1] + T[:, 3])
            n10 = jnp.maximum(C[:, 2] + T[:, 0], C[:, 3] + T[:, 2])
            n11 = jnp.maximum(C[:, 2] + T[:, 1], C[:, 3] + T[:, 3])
            new.append(jnp.stack([n00, n01, n10, n11], axis=1))
        return tuple(new), None

    Cs, _ = jax.lax.scan(step, C0, pair2)
    return [C.reshape(nb, GROUP, GROUP) for C in Cs]


def _xla_backpointers_stacked(tabs, v_reds, pair2, want_scores: bool):
    """ONE scan over M members' reduced delta recursions — per-member
    arithmetic = :func:`_xla_backpointers`(_scores).  Returns per-member
    (dexit [nb, 2], ebits [nb], bp2 [bk, nb], dmax2-or-None) tuples."""
    M = len(tabs)
    nb = pair2.shape[1]
    E0 = jnp.full((nb,), 0b10, jnp.int32)

    def step(carry, pk):
        new, ys = [], []
        for m in range(M):
            d0, d1, E = carry[m]
            T = _sel_rows(tabs[m], pk)
            a0 = d0 + T[:, 0]
            a1 = d1 + T[:, 2]
            b0 = d0 + T[:, 1]
            b1 = d1 + T[:, 3]
            bp0 = (a1 > a0).astype(jnp.int32)
            bp1 = (b1 > b0).astype(jnp.int32)
            E = (jnp.right_shift(E, bp0) & 1) | (
                (jnp.right_shift(E, bp1) & 1) << 1
            )
            d0n = jnp.maximum(a0, a1)
            d1n = jnp.maximum(b0, b1)
            new.append((d0n, d1n, E))
            bp = bp0 | (bp1 << 1)
            ys.append(
                (bp, jnp.maximum(d0n, d1n)) if want_scores else bp
            )
        return tuple(new), tuple(ys)

    carries, ys = jax.lax.scan(
        step,
        tuple((v[:, 0], v[:, 1], E0) for v in v_reds),
        pair2,
    )
    out = []
    for m in range(M):
        d0, d1, E = carries[m]
        if want_scores:
            bp2, dmax2 = ys[m]
        else:
            bp2, dmax2 = ys[m], None
        out.append((jnp.stack([d0, d1], axis=1), E, bp2, dmax2))
    return out


def _xla_backtrace_bits_stacked(bp2_list, exit_bits_list) -> list:
    """ONE reverse scan walking M members' 2-bit rows — each member's walk
    is :func:`_xla_backtrace_bits`, bit for bit."""
    M = len(bp2_list)

    def back(bits, rows):
        return (
            tuple(jnp.right_shift(rows[m], bits[m]) & 1 for m in range(M)),
            bits,
        )

    _, bits_seq = jax.lax.scan(
        back, tuple(exit_bits_list), tuple(bp2_list), reverse=True
    )
    return list(bits_seq)


def _oh_products_stacked_kernel(pair_ref, tab_ref, out_ref, *, nreal, bk, M):
    """Stacked pass A: M members' reduced max-plus products -> [M*4, LT]
    (member m's C00, C01, C10, C11 at rows 4m..4m+3).  One pair-tile read
    feeds every member's select; the M 2x2 recurrences interleave per step."""
    lt = pair_ref.shape[1]
    z = jnp.zeros((1, lt), jnp.float32)
    lz = jnp.full((1, lt), LOG_ZERO, jnp.float32)
    C0 = tuple((z, lz, lz, z) for _ in range(M))

    def body(c, Cs):
        tile = pair_ref[pl.ds(c * ROW_TILE, ROW_TILE), :]
        sels = [
            _select4(tile, tab_ref, nreal, base=m * 4 * nreal)
            for m in range(M)
        ]
        out = []
        for m in range(M):
            c00, c01, c10, c11 = Cs[m]
            t00, t01, t10, t11 = sels[m]
            for r in range(ROW_TILE):
                a00 = t00[r : r + 1, :]
                a01 = t01[r : r + 1, :]
                a10 = t10[r : r + 1, :]
                a11 = t11[r : r + 1, :]
                n00 = jnp.maximum(c00 + a00, c01 + a10)
                n01 = jnp.maximum(c00 + a01, c01 + a11)
                n10 = jnp.maximum(c10 + a00, c11 + a10)
                n11 = jnp.maximum(c10 + a01, c11 + a11)
                c00, c01, c10, c11 = n00, n01, n10, n11
            out.append((c00, c01, c10, c11))
        return tuple(out)

    Cs = jax.lax.fori_loop(0, bk // ROW_TILE, body, C0)
    for m in range(M):
        for i in range(4):
            out_ref[4 * m + i : 4 * m + i + 1, :] = Cs[m][i]


def pass_products_stacked(params_list, steps2, prev0=None, resets=None,
                          pre=None):
    """Stacked :func:`pass_products`: ONE launch computes every member's
    block products over the shared pair stream.  Returns a per-member list
    of (incl, offs, total) — each bit-identical to the member's own
    single-model pass over the same ``steps2``."""
    M = len(params_list)
    S, gts, tabs, _, pair2, e_in, e_out, nreal = stacked_prepared(
        params_list, steps2, prev0, resets, pre
    )
    nb = steps2.shape[1]
    if _interpret():
        reds = _xla_products_stacked(tabs, pair2)
    else:
        nb_pad = -(-nb // LANE_TILE) * LANE_TILE
        pair2p = _pad_lanes(pair2, nb_pad, jnp.int32(nreal))
        pair2p, bk = _pad_pair_rows(
            pair2p, _pad_lanes(e_out, nb_pad, 0), nreal
        )
        tabb = _bcast_tab(jnp.concatenate([t[:nreal] for t in tabs], axis=0))
        red_flat = pl.pallas_call(
            functools.partial(
                _oh_products_stacked_kernel, nreal=nreal, bk=bk, M=M
            ),
            grid=(nb_pad // LANE_TILE,),
            in_specs=[
                _vspec((bk, LANE_TILE), lambda i: (0, i)),
                _vspec(tabb.shape, lambda i: (0, 0)),
            ],
            out_specs=_vspec((4 * M, LANE_TILE), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((4 * M, nb_pad), jnp.float32),
        )(pair2p, tabb)
        reds = [
            red_flat[4 * m : 4 * m + 4].T.reshape(nb_pad, GROUP, GROUP)[:nb]
            for m in range(M)
        ]
    out = []
    for m in range(M):
        P = _scatter_products(
            reds[m], gts[m], e_in, e_out, params_list[m].n_states
        )
        incl, offs = scan_block_products(P)
        out.append((incl, offs, incl[-1]))
    return out


def _oh_backpointers_stacked_kernel(
    pair_ref, venter_ref, tab_ref, bp_ref, dexit_ref, ebits_ref, *rest,
    nreal, bk, M, want_scores
):
    """Stacked pass B: M members' delta recursions in one launch.

    venter_ref rows 2m..2m+1 = member m's entering vector; bp_ref rows
    [m*bk/8, (m+1)*bk/8) = member m's packed words; dexit rows 2m..2m+1,
    ebits row m.  ``want_scores`` adds dmax_ref (member m's per-step chain
    max at rows [m*bk, (m+1)*bk)) — the stacked flat-batch score feed.
    Per-member arithmetic = _oh_backpointers(_score)_kernel, op for op.
    """
    dmax_ref = rest[0] if want_scores else None
    lt = pair_ref.shape[1]
    state0 = tuple(
        (
            venter_ref[2 * m : 2 * m + 1, :],
            venter_ref[2 * m + 1 : 2 * m + 2, :],
            jnp.full((1, lt), 0b10, jnp.int32),
        )
        for m in range(M)
    )

    def body(c, states):
        out = []
        tiles = [
            pair_ref[pl.ds(c * OUTER_TILE + t8 * ROW_TILE, ROW_TILE), :]
            for t8 in range(OUTER_TILE // ROW_TILE)
        ]
        for m in range(M):
            d0, d1, E = states[m]
            words = []
            for t8 in range(OUTER_TILE // ROW_TILE):
                t00, t01, t10, t11 = _select4(
                    tiles[t8], tab_ref, nreal, base=m * 4 * nreal
                )
                word = jnp.zeros((1, lt), jnp.int32)
                drows = [None] * ROW_TILE
                for r in range(ROW_TILE):
                    a0 = d0 + t00[r : r + 1, :]
                    a1 = d1 + t10[r : r + 1, :]
                    b0 = d0 + t01[r : r + 1, :]
                    b1 = d1 + t11[r : r + 1, :]
                    bp0 = (a1 > a0).astype(jnp.int32)
                    bp1 = (b1 > b0).astype(jnp.int32)
                    d0 = jnp.maximum(a0, a1)
                    d1 = jnp.maximum(b0, b1)
                    word = word | ((bp0 | (bp1 << 1)) << (2 * r))
                    E = (jnp.right_shift(E, bp0) & 1) | (
                        ((jnp.right_shift(E, bp1) & 1)) << 1
                    )
                    if want_scores:
                        drows[r] = jnp.maximum(d0, d1)
                words.append(word)
                if want_scores:
                    # Offsets written as (...) * ROW_TILE so Mosaic's
                    # 8-aligned fast path is provable (m/bk/t8 are
                    # Python-static; c is the fori counter).
                    dmax_ref[
                        pl.ds(
                            (m * (bk // ROW_TILE)
                             + c * (OUTER_TILE // ROW_TILE) + t8) * ROW_TILE,
                            ROW_TILE,
                        ),
                        :,
                    ] = jnp.concatenate(drows, axis=0)
            bp_ref[
                pl.ds(
                    (m * (bk // OUTER_TILE) + c) * (OUTER_TILE // ROW_TILE),
                    OUTER_TILE // ROW_TILE,
                ),
                :,
            ] = jnp.concatenate(words, axis=0)
            out.append((d0, d1, E))
        return tuple(out)

    states = jax.lax.fori_loop(0, bk // OUTER_TILE, body, state0)
    for m in range(M):
        d0, d1, E = states[m]
        dexit_ref[2 * m : 2 * m + 1, :] = d0
        dexit_ref[2 * m + 1 : 2 * m + 2, :] = d1
        ebits_ref[m : m + 1, :] = E


def pass_backpointers_stacked(params_list, v_enters, steps2, prev0=None,
                              resets=None, pre=None,
                              want_scores: bool = False):
    """Stacked :func:`pass_backpointers` / ``_scores``: M members' delta
    recursions in ONE launch over the shared pair stream.  ``v_enters`` is
    the per-member [nb, K_m] entering-vector list; returns per-member
    (delta_exit, F, dmax2-or-None) lists plus ONE stacked blob for
    :func:`pass_backtrace_stacked`."""
    M = len(params_list)
    S, gts, tabs, idtabs, pair2, e_in, e_out, nreal = stacked_prepared(
        params_list, steps2, prev0, resets, pre
    )
    bk_real, nb = steps2.shape
    v_reds = [
        jnp.take_along_axis(v_enters[m], gts[m][e_in], axis=1)
        for m in range(M)
    ]
    ghigh_ends = [gts[m][e_out, 1] for m in range(M)]
    if _interpret():
        res = _xla_backpointers_stacked(
            tabs, [v.astype(jnp.float32) for v in v_reds], pair2,
            want_scores,
        )
        outs = []
        bp_list = []
        for m, (dexit_red, ebits_nb, bp2, dmax2) in enumerate(res):
            delta_exit = _scatter_vec(
                dexit_red, gts[m], e_out, params_list[m].n_states
            )
            F = _scatter_ftab(
                ebits_nb, gts[m], e_in, e_out, params_list[m].n_states
            )
            outs.append((delta_exit, F, dmax2))
            bp_list.append(bp2)
        blob = ("xla", tuple(bp_list), pair2, idtabs, ghigh_ends, bk_real, nb)
        return outs, blob
    nb_pad = -(-nb // LANE_TILE) * LANE_TILE
    pair2p = _pad_lanes(pair2, nb_pad, jnp.int32(nreal))
    pair2p, bk = _pad_pair_rows(pair2p, _pad_lanes(e_out, nb_pad, 0), nreal)
    v_red2 = jnp.concatenate(
        [
            _pad_lanes(v.T.astype(jnp.float32), nb_pad, 0.0)
            for v in v_reds
        ],
        axis=0,
    )  # [M*GROUP, nb_pad]
    tabb = _bcast_tab(jnp.concatenate([t[:nreal] for t in tabs], axis=0))
    out_specs = [
        _vspec((M * (bk // ROW_TILE), LANE_TILE), lambda i: (0, i)),
        _vspec((M * GROUP, LANE_TILE), lambda i: (0, i)),
        _vspec((M, LANE_TILE), lambda i: (0, i)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((M * (bk // ROW_TILE), nb_pad), jnp.int32),
        jax.ShapeDtypeStruct((M * GROUP, nb_pad), jnp.float32),
        jax.ShapeDtypeStruct((M, nb_pad), jnp.int32),
    ]
    if want_scores:
        out_specs.append(_vspec((M * bk, LANE_TILE), lambda i: (0, i)))
        out_shape.append(
            jax.ShapeDtypeStruct((M * bk, nb_pad), jnp.float32)
        )
    kouts = pl.pallas_call(
        functools.partial(
            _oh_backpointers_stacked_kernel, nreal=nreal, bk=bk, M=M,
            want_scores=want_scores,
        ),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec((M * GROUP, LANE_TILE), lambda i: (0, i)),
            _vspec(tabb.shape, lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
    )(pair2p, v_red2, tabb)
    bp_packed, dexit_red, ebits = kouts[:3]
    outs = []
    for m in range(M):
        delta_exit = _scatter_vec(
            dexit_red[2 * m : 2 * m + 2].T[:nb], gts[m], e_out,
            params_list[m].n_states,
        )
        F = _scatter_ftab(
            ebits[m, :nb], gts[m], e_in, e_out, params_list[m].n_states
        )
        dmax2 = (
            kouts[3][m * bk : m * bk + bk_real, :nb] if want_scores else None
        )
        outs.append((delta_exit, F, dmax2))
    blob = ("pallas", bp_packed, pair2p, idtabs, ghigh_ends, bk_real, nb)
    return outs, blob


def _oh_backtrace_stacked_kernel(bp_ref, pair_ref, idtab_ref, exit_ref,
                                 path_ref, *, nP, bk, M):
    """Stacked pass C: M members' bit walks from their anchored exit bits,
    one pair-tile read per step feeding every member's id select (member
    m's ids at idtab rows [m*2*nP, (m+1)*2*nP), path rows [m*bk, (m+1)*bk))."""
    nc = bk // OUTER_TILE

    def body(i, bits):
        c = nc - 1 - i
        out = []
        for m in range(M):
            bit = bits[m]
            words = bp_ref[
                pl.ds(
                    (m * (bk // OUTER_TILE) + c) * (OUTER_TILE // ROW_TILE),
                    OUTER_TILE // ROW_TILE,
                ),
                :,
            ]
            for t8 in range(OUTER_TILE // ROW_TILE - 1, -1, -1):
                tile = pair_ref[
                    pl.ds(c * OUTER_TILE + t8 * ROW_TILE, ROW_TILE), :
                ]
                glow = jnp.zeros(tile.shape, jnp.int32)
                ghigh = jnp.zeros(tile.shape, jnp.int32)
                for p in range(nP):
                    cmp = tile == p
                    r0 = m * 2 * nP + 2 * p
                    glow = jnp.where(cmp, idtab_ref[r0 : r0 + 1, :], glow)
                    ghigh = jnp.where(
                        cmp, idtab_ref[r0 + 1 : r0 + 2, :], ghigh
                    )
                word = words[t8 : t8 + 1, :]
                rows = [None] * ROW_TILE
                for r in range(ROW_TILE - 1, -1, -1):
                    rows[r] = jnp.where(
                        bit == 0, glow[r : r + 1, :], ghigh[r : r + 1, :]
                    )
                    bit = jnp.right_shift(word, 2 * r + bit) & 1
                path_ref[
                    pl.ds(
                        (m * (bk // ROW_TILE)
                         + c * (OUTER_TILE // ROW_TILE) + t8) * ROW_TILE,
                        ROW_TILE,
                    ),
                    :,
                ] = jnp.concatenate(rows, axis=0)
            out.append(bit)
        return tuple(out)

    jax.lax.fori_loop(
        0, nc, body,
        tuple(exit_ref[m : m + 1, :] for m in range(M)),
    )


def pass_backtrace_stacked(blob, exits_list) -> list:
    """Stacked :func:`pass_backtrace`: every member's path off the shared
    packed pointers in ONE launch.  ``exits_list``: per-member exit-state
    anchors [nb].  Returns per-member [bk*nb] state-id paths."""
    kind, bp, pair2, idtabs, ghigh_ends, bk_real, nb = blob
    M = len(idtabs)
    exit_bits = [
        (exits_list[m] == ghigh_ends[m]).astype(jnp.int32) for m in range(M)
    ]
    if kind == "xla":
        # One reverse scan walks every member's bit rows; the pair->id
        # mapping differs per member, so ids resolve per member after.
        bits_list = _xla_backtrace_bits_stacked(list(bp), exit_bits)
        out = []
        for m in range(M):
            glow2 = jnp.take(idtabs[m][:, 0], pair2)
            ghigh2 = jnp.take(idtabs[m][:, 1], pair2)
            out.append(
                jnp.where(bits_list[m] == 0, glow2, ghigh2).T.reshape(-1)
            )
        return out
    bk = pair2.shape[0]
    nb_pad = pair2.shape[1]
    nP = idtabs[0].shape[0]
    exits2 = jnp.concatenate(
        [_pad_lanes(b[None, :], nb_pad, 0) for b in exit_bits], axis=0
    )
    idtabb = _bcast_tab(jnp.concatenate(idtabs, axis=0))
    path2 = pl.pallas_call(
        functools.partial(_oh_backtrace_stacked_kernel, nP=nP, bk=bk, M=M),
        grid=(nb_pad // LANE_TILE,),
        in_specs=[
            _vspec((M * (bk // ROW_TILE), LANE_TILE), lambda i: (0, i)),
            _vspec((bk, LANE_TILE), lambda i: (0, i)),
            _vspec(idtabb.shape, lambda i: (0, 0)),
            _vspec((M, LANE_TILE), lambda i: (0, i)),
        ],
        out_specs=_vspec((M * bk, LANE_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((M * bk, nb_pad), jnp.int32),
    )(bp, pair2, idtabb, exits2)
    return [
        path2[m * bk : m * bk + bk_real, :nb].T.reshape(-1)
        for m in range(M)
    ]


def _block_passes_stacked(params_list, v0s, padded, bk, resets, pre,
                          want_scores: bool = False):
    """The stacked twin of viterbi_parallel._block_passes (onehot engine):
    ONE launch per T-scaling pass for every member; the model-sized
    stitching (prefix scans, enter vectors, suffix compositions) loops
    members in XLA.  Returns a per-member list of BlockDecode."""
    from cpgisland_tpu.ops.viterbi_parallel import (
        BlockDecode,
        _enter_vectors,
        _suffix_compositions,
    )

    nb = padded.shape[0] // bk
    steps2 = padded.reshape(nb, bk).T
    prods = pass_products_stacked(
        params_list, steps2, None, resets=resets, pre=pre
    )
    v_enters, enter_offs = [], []
    for m, (incl, offs, _total) in enumerate(prods):
        v, off = _enter_vectors(v0s[m], incl, offs)
        v_enters.append(v)
        enter_offs.append(off)
    bps, blob = pass_backpointers_stacked(
        params_list, v_enters, steps2, None, resets=resets, pre=pre,
        want_scores=want_scores,
    )
    exits_list, Gsufs = [], []
    for m, (delta_blocks, F, _dmax2) in enumerate(bps):
        s_exit = jnp.argmax(delta_blocks[-1]).astype(jnp.int32)
        Gsuf = _suffix_compositions(F)
        exits_list.append(
            jnp.concatenate([Gsuf[1:, :][:, s_exit], s_exit[None]])
        )
        Gsufs.append(Gsuf)
    paths = pass_backtrace_stacked(blob, exits_list)
    out = []
    for m, (delta_blocks, _F, dmax2) in enumerate(bps):
        _incl, _offs, total = prods[m]
        out.append(
            BlockDecode(
                path=paths[m], delta_exit=delta_blocks[-1], total=total,
                ftable=Gsufs[m][0], score_offset=enter_offs[m][-1],
                enter_offs=enter_offs[m] if want_scores else None,
                dmax2=dmax2,
            )
        )
    return out


def decode_batch_flat_stacked(
    params_list,
    chunks: jnp.ndarray,
    lengths: jnp.ndarray,
    block_size=None,
    prepared=None,
    return_score: bool = False,
):
    """Decode ONE [N, T] batch under M models in ONE stacked launch set.

    The multi-model twin of :func:`decode_batch_flat`: the flat reset-step
    stream is symbol-only, so every member shares it (and its prep), and
    the three T-scaling passes run stacked — M members' chains pay ONE
    pass set of fixed cost instead of M.  Member m's paths (and scores,
    with ``return_score``) are BIT-IDENTICAL to
    ``decode_batch_flat(params_list[m], chunks, lengths, block_size)`` —
    same stream, same constants, same rounding (the stacked kernels run
    the single-model arithmetic per member).  Same exactness domain as the
    flat decoder (records' first positions must be real symbols; callers
    demote pad-FIRST records).  VMEM note: the per-member score/path rows
    scale the kernel working set by M — on TPU ``block_size`` CLAMPS to
    graftmem's ``memmodel.stacked_block_cap(M)`` (``mem_clamp`` obs
    event; knob to re-sweep at capture, BASELINE.md), so the M-member
    bit-identity contract vs ``decode_batch_flat(..., block_size)`` holds
    at the CLAMPED block there.

    Returns paths [M, N, T] (or (paths, scores [M, N])).
    """
    S = params_list[0].n_symbols
    N, T = chunks.shape
    if T < 2:
        raise ValueError(
            "decode_batch_flat_stacked needs records of at least 2 symbols"
        )
    if block_size is None and prepared is not None:
        # Same rule as decode_batch_flat: a caller-built prep pins the
        # block (the stacked clamp below still applies; an unclamped prep
        # fails the stale-prep gate with rebuild advice, as before).
        block_size = prepared[3]
    block_size = _default_block_size(
        block_size, scores=return_score, stacked_m=len(params_list)
    )
    # On TPU the block clamps to the stacked VMEM cap BEFORE prep builds
    # (graftmem: M>=3 at the flat default bk=4096 does not fit; a caller-
    # supplied `prepared` built at an unclamped block fails the stale-prep
    # gate below with rebuild advice rather than tripping the guard).
    block_size = _stacked_block_for(
        len(params_list), block_size, scores=return_score
    )
    if prepared is None:
        prepared = prepare_decode_flat(S, chunks, lengths, block_size)
    concat, padded, resets, bk, pre = prepared
    Np = N * T
    n_steps = Np - 1
    want_bk = min(block_size, max(8, n_steps))
    if concat.shape[0] != Np or bk != want_bk:
        raise ValueError(
            f"prepared decode stream was built for {concat.shape[0]} "
            f"symbols / bk={bk}; this call needs {Np} symbols / "
            f"bk={want_bk} — rebuild it with prepare_decode_flat"
        )
    _check_flat_block(bk, scores=return_score, stacked_m=len(params_list))
    from cpgisland_tpu.ops.viterbi_parallel import _step_tables

    v0s = []
    for p in params_list:
        _, emit_ext = _step_tables(p)
        v0s.append(p.log_pi + emit_ext[concat[0]])
    decs = _block_passes_stacked(
        params_list, v0s, padded, bk, resets, pre, want_scores=return_score
    )
    paths, scores = [], []
    for dec in decs:
        s0 = dec.ftable[jnp.argmax(dec.delta_exit)]
        full = jnp.concatenate([s0[None], dec.path[:n_steps]])
        paths.append(full.reshape(N, T))
        if return_score:
            e = (jnp.arange(N, dtype=jnp.int32) + 1) * T - 2
            b = e // bk
            Mx = dec.dmax2[e - b * bk, b] + dec.enter_offs[b]
            scores.append(jnp.concatenate([Mx[:1], Mx[1:] - Mx[:-1]]))
    if not return_score:
        return jnp.stack(paths)
    return jnp.stack(paths), jnp.stack(scores)


@functools.partial(jax.jit, static_argnames=("block_size", "return_score"))
def _decode_batch_flat_stacked_traced(
    params_list, chunks, lengths, block_size: int = 4096,
    return_score: bool = False,
):
    return decode_batch_flat_stacked(
        tuple(params_list), chunks, lengths, block_size=block_size,
        return_score=return_score,
    )


def decode_batch_flat_stacked_jit(
    params_list, chunks, lengths, block_size=None,
    return_score: bool = False,
):
    """One-dispatch entry for :func:`decode_batch_flat_stacked` (the serve
    broker's mixed-model flush unit; prep builds in-graph — per-flush
    record sets never repeat, so there is nothing to amortize).  The
    ``block_size=None`` default resolves through the graftune table HERE,
    host-side, so the tuned value is a concrete static arg (never a
    trace-time consultation frozen into a jit cache)."""
    block_size = _default_block_size(
        block_size, scores=return_score, stacked_m=len(params_list)
    )
    return _decode_batch_flat_stacked_traced(
        tuple(params_list), chunks, lengths, block_size=block_size,
        return_score=return_score,
    )


# graftscale (Layer 6) declarations — see fb_onehot.SCALE_TAGS for the
# convention.  The true-score contract runs in MAX-PLUS mode: an additive
# log_pi offset is the max-plus analogue of a multiplicative scale —
# scores shift by exactly the offset (degree 1), decoded paths are
# offset-invariant (argmax collapse).  Derived through the single-record
# viterbi_parallel onehot route; the flat batched decoder accumulates
# reset constants per record (genuinely position-dependent — its exact
# per-record scores telescope at runtime, pinned by parity tests, not by
# a homogeneity signature).
SCALE_TAGS = {
    "viterbi_parallel.onehot": {
        "tagged": "log_pi offset", "mode": "maxplus",
        "outputs": {"path": "free", "score": "deg:1"},
    },
}
