"""Failure detection and elastic re-execution for the E-step (SURVEY.md §5).

The reference inherits all fault tolerance from Hadoop MapReduce: a failed map
task is re-executed up to mapreduce.map.maxattempts times, and the job can be
configured to skip bad records (nothing in the driver itself,
CpGIslandFinder.java:200-201).  JAX has no such substrate, so this module
provides the TPU-native equivalent as a wrapper around any chunked
:class:`~cpgisland_tpu.train.backends.EStepBackend`:

- the chunk batch is split into ``micro_batches`` independent slices (the
  "tasks"); sufficient statistics are additive, so the reduce is a plain sum;
- each slice is synced to host and checked finite — a device-side numerics
  blowup or runtime error (OOM, preemption, interconnect fault surfaces as an
  exception from `block_until_ready`) is detected per-slice, not per-epoch;
- failed slices are retried up to ``max_retries`` times (task re-execution);
  with ``on_failure="skip"`` a persistently failing slice is dropped and
  recorded (skip-bad-records) instead of killing the run — EM degrades
  gracefully to the statistics of the surviving shards.

Recovery above the E-step (numerics fallback mid-training) lives in
``train.baum_welch.fit(fallback_backend=...)``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.train.backends import EStepBackend
from cpgisland_tpu.utils import chunking, profiling

log = logging.getLogger(__name__)


@dataclass
class SliceFailure:
    """Record of one micro-batch that exhausted its retries."""

    batch_index: int
    start: int
    stop: int
    attempts: int
    error: str


@dataclass
class ElasticEStep(EStepBackend):
    """Micro-batched, retrying E-step runner (Hadoop task-retry equivalent).

    Wraps a chunked backend (Local or Spmd).  ``prepare``/``place`` keep the
    batch on host so each micro-batch is placed independently — a slice that
    kills a device buffer cannot take the whole epoch's input with it.
    """

    inner: EStepBackend
    micro_batches: int = 8
    max_retries: int = 2
    on_failure: str = "raise"  # or "skip"
    metrics: Optional[profiling.MetricsLogger] = None
    failures: List[SliceFailure] = field(default_factory=list)

    def __post_init__(self):
        if self.on_failure not in ("raise", "skip"):
            raise ValueError(f"on_failure must be 'raise' or 'skip', got {self.on_failure!r}")
        # (start, stop) ranges that exhausted retries in skip mode — like
        # Hadoop's skip-bad-records blacklist, they are never re-attempted in
        # later EM iterations.
        self._blacklist: set = set()

    def prepare(self, chunked: chunking.Chunked) -> chunking.Chunked:
        return chunked

    def place(self, chunks, lengths):
        # Host-side on purpose: slices are placed per micro-batch call.
        return np.asarray(chunks), np.asarray(lengths)

    def __call__(self, params, chunks, lengths) -> SuffStats:
        chunks = np.asarray(chunks)
        lengths = np.asarray(lengths)
        n = chunks.shape[0]
        micro = max(1, -(-n // self.micro_batches))
        n_slices = -(-n // micro)
        total: Optional[SuffStats] = None
        for i, start in enumerate(range(0, n, micro)):
            stop = min(start + micro, n)
            if (start, stop) in self._blacklist:
                continue  # skip-bad-records: known-bad range, don't re-attempt
            stats = self._run_slice(params, chunks[start:stop], lengths[start:stop], i, start, stop)
            if stats is not None:
                total = stats if total is None else total + stats
        if total is None:
            raise RuntimeError(
                f"all {n_slices} E-step micro-batches failed; see .failures"
            )
        return total

    def _run_slice(self, params, chunks, lengths, idx, start, stop) -> Optional[SuffStats]:
        sub = chunking.Chunked(
            chunks=chunks, lengths=lengths, total=int(np.asarray(lengths).sum())
        )
        sub = self.inner.prepare(sub)
        last_err: Exception = RuntimeError("unreachable")
        for attempt in range(1, self.max_retries + 2):
            try:
                stats = self.inner(params, jnp.asarray(sub.chunks), jnp.asarray(sub.lengths))
                # Sync to host: surfaces asynchronous device errors here, and
                # makes the finite-check see real values.
                host = jax.tree_util.tree_map(np.asarray, stats)
                profiling.check_finite(host, where=f"E-step slice {idx}")
                if attempt > 1 and self.metrics is not None:
                    self.metrics.log("estep_slice_recovered", slice=idx, attempts=attempt)
                return host
            except (RuntimeError, FloatingPointError) as e:
                # Fault-shaped errors only (XlaRuntimeError subclasses
                # RuntimeError; check_finite raises FloatingPointError) —
                # matches baum_welch.fit's retry policy.  Deterministic
                # programming errors (ValueError/TypeError from a shape bug)
                # propagate immediately instead of being retried or silently
                # dropped as "bad records".
                last_err = e
                log.warning(
                    "E-step slice %d (chunks %d:%d) attempt %d/%d failed: %s",
                    idx, start, stop, attempt, self.max_retries + 1, e,
                )
                if self.metrics is not None:
                    self.metrics.log(
                        "estep_slice_failure", slice=idx, attempt=attempt, error=str(e)
                    )
        failure = SliceFailure(
            batch_index=idx, start=start, stop=stop,
            attempts=self.max_retries + 1, error=str(last_err),
        )
        self.failures.append(failure)
        if self.on_failure == "skip":
            # Only skip mode may drop data; raise mode must keep failing on
            # every retry so training never silently runs on partial stats.
            self._blacklist.add((start, stop))
        if self.on_failure == "raise":
            raise RuntimeError(
                f"E-step slice {idx} (chunks {start}:{stop}) failed "
                f"{failure.attempts} times: {last_err}"
            ) from last_err
        log.error("dropping E-step slice %d after %d attempts (on_failure='skip')",
                  idx, failure.attempts)
        return None
