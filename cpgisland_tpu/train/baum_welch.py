"""Baum-Welch EM: M-step and the convergence-driven training loop.

The reference's trainer is Mahout's Hadoop Baum-Welch driver: per iteration one
MR job (mappers: forward-backward counts; reducers: sum + normalize), looping
until |model_{t+1} - model_t| < convergence or numIter is reached
(BaumWelchDriver.runBaumWelchMR, CpGIslandFinder.java:200-201; convergence
".005" at :96).  Here the E-step runs through an
:class:`~cpgisland_tpu.train.backends.EStepBackend` (local vmap or mesh-sharded
`psum`), the M-step is a normalize on replicated [K]/[K,K]/[K,M] tensors, and
the loop is host-side Python (one device sync per iteration — exactly the
reference's one-job-per-iteration cadence, minus the JVM startup).

Structural zeros (e.g. the one-hot emission rows of the CpG model,
CpGIslandFinder.java:166-173) are preserved automatically: a zero-probability
emission accumulates exactly zero expected count, so EM is a fixed point in
those coordinates (SURVEY.md C5).  Rows with zero total count keep their
previous distribution rather than dividing by zero.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.train.backends import EStepBackend, get_backend
from cpgisland_tpu.utils import checkpoint as ckpt
from cpgisland_tpu.utils import chunking
from cpgisland_tpu.utils import profiling

log = logging.getLogger(__name__)


@jax.jit
def mstep(params: HmmParams, stats: SuffStats) -> HmmParams:
    """Normalize expected counts into the next model (the reducer's normalize).

    Zero-count rows retain the previous distribution.
    """

    def normalize(counts, prev_probs):
        row = jnp.sum(counts, axis=-1, keepdims=True)
        safe = jnp.where(row > 0, counts / jnp.maximum(row, 1e-30), prev_probs)
        return safe

    pi = normalize(stats.init, jnp.exp(params.log_pi))
    A = normalize(stats.trans, jnp.exp(params.log_A))
    B = normalize(stats.emit, jnp.exp(params.log_B))
    return HmmParams.from_probs(pi, A, B)


@dataclasses.dataclass
class FitResult:
    params: HmmParams
    iterations: int
    logliks: list
    converged: bool
    deltas: list


def fit(
    params: HmmParams,
    chunked: chunking.Chunked,
    *,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: EStepBackend | str = "local",
    mode: str = "rescaled",
    engine: str = "auto",
    checkpoint_dir: Optional[str] = None,
    callback: Optional[Callable[[int, float, float], None]] = None,
    start_iteration: int = 0,
    metrics: Optional[profiling.MetricsLogger] = None,
) -> FitResult:
    """Run Baum-Welch EM until convergence or ``num_iters``.

    Matches the reference driver-loop semantics: stop when the max-abs change in
    any model probability drops below ``convergence`` (the MR driver's model
    delta check) or after ``num_iters`` jobs.  Each iteration optionally writes
    an npz checkpoint (the reference persists the model to HDFS per iteration,
    CpGIslandFinder.java:64-89).
    """
    if isinstance(backend, str):
        backend = get_backend(backend, mode=mode, engine=engine)
    chunked = backend.prepare(chunked)
    chunks, lengths = backend.place(chunked.chunks, chunked.lengths)

    logliks: list[float] = []
    deltas: list[float] = []
    converged = False
    it = 0
    for it in range(start_iteration + 1, start_iteration + num_iters + 1):
        t0 = time.perf_counter()
        stats = backend(params, chunks, lengths)
        new_params = mstep(params, stats)
        delta = float(new_params.max_abs_diff(params))
        ll = float(stats.loglik)
        params = new_params
        logliks.append(ll)
        deltas.append(delta)
        dt = time.perf_counter() - t0
        log.info("em iter=%d loglik=%.4f delta=%.6f wall=%.3fs", it, ll, delta, dt)
        # Failure detection (SURVEY.md §5): a numerics blowup surfaces here as
        # a clear error instead of silently corrupting later iterations; the
        # per-iteration checkpoint below is the matching restart point.
        profiling.check_finite(
            {"pi": params.log_pi, "A": params.log_A, "B": params.log_B, "loglik": ll},
            where=f"em iter {it}",
        )
        if metrics is not None:
            metrics.log("em_iter", iteration=it, loglik=ll, delta=delta, wall_s=dt)
        if callback is not None:
            callback(it, ll, delta)
        if checkpoint_dir is not None:
            ckpt.save(
                ckpt.checkpoint_path(checkpoint_dir, it),
                ckpt.TrainState(params=params, iteration=it, logliks=logliks),
            )
        if delta < convergence:
            converged = True
            break
    return FitResult(
        params=params, iterations=it, logliks=logliks, converged=converged, deltas=deltas
    )


def resume(
    checkpoint_dir: str,
    chunked: chunking.Chunked,
    *,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: EStepBackend | str = "local",
    mode: str = "rescaled",
) -> FitResult:
    """Resume training from the latest checkpoint in a directory.

    The reference has no resume path (its per-iteration HDFS model dumps are
    write-only); this makes the natural EM restart point first-class
    (SURVEY.md §5 failure detection / elastic recovery).
    """
    path = ckpt.latest(checkpoint_dir)
    if path is None:
        raise FileNotFoundError(f"no checkpoints under {checkpoint_dir}")
    state = ckpt.load(path)
    remaining = max(0, num_iters - state.iteration)
    result = fit(
        state.params,
        chunked,
        num_iters=remaining,
        convergence=convergence,
        backend=backend,
        mode=mode,
        checkpoint_dir=checkpoint_dir,
        start_iteration=state.iteration,
    )
    return dataclasses.replace(result, logliks=list(state.logliks) + result.logliks)
