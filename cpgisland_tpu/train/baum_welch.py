"""Baum-Welch EM: M-step and the convergence-driven training loop.

The reference's trainer is Mahout's Hadoop Baum-Welch driver: per iteration one
MR job (mappers: forward-backward counts; reducers: sum + normalize), looping
until |model_{t+1} - model_t| < convergence or numIter is reached
(BaumWelchDriver.runBaumWelchMR, CpGIslandFinder.java:200-201; convergence
".005" at :96).  Here the E-step runs through an
:class:`~cpgisland_tpu.train.backends.EStepBackend` (local vmap or mesh-sharded
`psum`), the M-step is a normalize on replicated [K]/[K,K]/[K,M] tensors, and
the loop is host-side Python (one device sync per iteration — exactly the
reference's one-job-per-iteration cadence, minus the JVM startup).

Structural zeros (e.g. the one-hot emission rows of the CpG model,
CpGIslandFinder.java:166-173) are preserved automatically: a zero-probability
emission accumulates exactly zero expected count, so EM is a fixed point in
those coordinates (SURVEY.md C5).  Rows with zero total count keep their
previous distribution rather than dividing by zero.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import time
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu import obs
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.train.backends import EStepBackend, get_backend
from cpgisland_tpu.utils import checkpoint as ckpt
from cpgisland_tpu.utils import chunking
from cpgisland_tpu.utils import profiling

log = logging.getLogger(__name__)


@jax.jit
def mstep(params: HmmParams, stats: SuffStats) -> HmmParams:
    """Normalize expected counts into the next model (the reducer's normalize).

    Zero-count rows retain the previous distribution.
    """

    def normalize(counts, prev_probs):
        row = jnp.sum(counts, axis=-1, keepdims=True)
        safe = jnp.where(row > 0, counts / jnp.maximum(row, 1e-30), prev_probs)
        return safe

    pi = normalize(stats.init, jnp.exp(params.log_pi))
    A = normalize(stats.trans, jnp.exp(params.log_A))
    B = normalize(stats.emit, jnp.exp(params.log_B))
    return HmmParams.from_probs(pi, A, B)


@jax.jit
def em_update(params: HmmParams, stats: SuffStats):
    """Fused iteration epilogue: M-step normalize + convergence delta as ONE
    compact section over model-sized tensors -> (new_params, delta).

    The host loop previously dispatched the M-step and the max-abs-diff as
    two separate programs per iteration (two relay round trips of launch
    latency); inside the fused while_loop the same fusion keeps the whole
    epilogue — normalize, probability reconstruction, delta reduction — in
    registers between the E-step's one lane reduce and the convergence
    test, with no intermediate HBM round trip of anything bigger than the
    model.
    """
    new_params = mstep(params, stats)
    return new_params, new_params.max_abs_diff(params)


@functools.lru_cache(maxsize=32)
def _fused_em_fn(stats_fn, num_iters: int, with_prep: bool = False):
    """ONE compiled program running up to ``num_iters`` EM iterations.

    The host loop in :func:`fit` keeps the reference's one-job-per-iteration
    cadence: every iteration blocks on the delta/loglik fetch, which on a
    relayed TPU costs a 50-100 ms round trip — pure latency the device
    spends idle.  EM iterations are data-independent (the chunk batch never
    changes), so the whole convergence-checked loop is fusable: this wraps
    the E-step + M-step + on-device model-delta convergence test in a
    ``lax.while_loop``, carrying the model and the per-iteration
    loglik/delta trajectories.  K steady-state iterations then cost ONE
    blocking fetch (the final carry) instead of K+ round trips, and the
    ~8-11 ms fixed in-graph cost per whole-sequence iteration (BASELINE.md)
    amortizes across the loop.

    Cache key = (stats_fn identity, num_iters, with_prep): backends return
    STABLE routing callables (see EStepBackend.fused_stats_fn), so repeated
    fits reuse the compiled loop; params/convergence/prepared are traced
    arguments.  ``with_prep``: the stats fn takes the prepared symbol
    streams (ops.prepared) as an explicit argument — resolved ONCE here,
    outside the while_loop body, so no gather/one-hot/reshape of the
    symbol stream executes per iteration (the ``em.body.invariant-free``
    graftcheck contract traces exactly this program).
    """

    def run(params, chunks, lengths, convergence, prepared):
        def cond(carry):
            it, _p, converged, _lls, _dls = carry
            return jnp.logical_and(it < num_iters, jnp.logical_not(converged))

        def body(carry):
            it, p, _, lls, dls = carry
            stats = (
                stats_fn(p, chunks, lengths, prepared=prepared)
                if with_prep
                else stats_fn(p, chunks, lengths)
            )
            new_p, delta = em_update(p, stats)
            lls = lls.at[it].set(stats.loglik.astype(jnp.float32))
            dls = dls.at[it].set(delta.astype(jnp.float32))
            return (it + jnp.int32(1), new_p, delta < convergence, lls, dls)

        init = (
            jnp.int32(0),
            params,
            jnp.asarray(False),
            jnp.full((num_iters,), jnp.nan, jnp.float32),
            jnp.full((num_iters,), jnp.nan, jnp.float32),
        )
        return jax.lax.while_loop(cond, body, init)

    return jax.jit(run)


def _em_breaker_key(backend, params: HmmParams) -> Optional[str]:
    """Breaker key for the backend's currently-resolved E-step engine
    (``em.onehot``/``em.pallas``/``em.xla``), or None for duck-typed
    backends without routing attributes.  Resolved at FAULT time so a trip
    attributes to the engine that actually ran — and since backends
    re-resolve per call, the recorded trip reroutes the next iteration."""
    eng = getattr(backend, "engine", None)
    mode = getattr(backend, "mode", None)
    if not isinstance(eng, str) or not isinstance(mode, str):
        return None
    try:
        from cpgisland_tpu.train.backends import resolve_fb_engine

        return f"em.{resolve_fb_engine(eng, params, mode)}"
    except Exception:
        return None


def _fuse_blocked_reason(
    checkpoint_dir, callback, fallback_backend, start_iteration
) -> Optional[str]:
    """Why the fused loop cannot serve this fit (None = eligible).

    These are exactly the host-cadence features: per-iteration snapshots,
    user callbacks, and the retry/fallback recovery path all need the model
    on the host every iteration, which is the round trip fusing removes.
    """
    if checkpoint_dir is not None:
        return "per-iteration checkpointing"
    if callback is not None:
        return "per-iteration callback"
    if fallback_backend is not None:
        return "fallback-backend recovery"
    if start_iteration:
        return "resumed iteration numbering"
    return None


def _fit_fused(
    params: HmmParams,
    stats_fn,
    chunks,
    lengths,
    *,
    num_iters: int,
    convergence: float,
    n_sym: float,
    metrics,
    prepared=None,
) -> "FitResult":
    """Run the compiled K-iteration EM program and unpack its one fetch."""
    t0 = time.perf_counter()
    fn = _fused_em_fn(stats_fn, num_iters, prepared is not None)
    with obs.span("em_fused", items=n_sym, unit="sym", max_iters=num_iters) as sp:
        out = fn(
            # The loop carry is f32 (mstep output dtype); cast the entry so
            # f64-initialized params don't fail the while_loop dtype check.
            params.astype(jnp.float32),
            chunks,
            lengths,
            jnp.float32(convergence),
            prepared,
        )
        # THE one blocking round trip of the whole loop (counted by the obs
        # ledger's device_get hook).
        # graftcheck: allow(hot-path-host-sync) -- the fused EM loop's single designed round trip; ledger-counted via the device_get hook (note_fetch would double-count)
        it_a, p, converged_a, lls, dls = jax.device_get(out)
        if sp is not None:
            sp.items = float(n_sym) * float(it_a)
    it = int(it_a)
    logliks = [float(x) for x in lls[:it]]
    deltas = [float(x) for x in dls[:it]]
    dt = time.perf_counter() - t0
    # The host loop validates per iteration; here corrupt statistics can
    # only be detected after the fact — the fused path trades mid-loop
    # recovery for latency, so a blowup is a hard error advising the
    # host-cadence features (fit auto-selects the host loop when any of
    # them is requested).
    profiling.check_finite(
        {"pi": p.log_pi, "A": p.log_A, "B": p.log_B,
         "logliks": np.asarray(logliks, np.float64)},
        where=f"fused em ({it} iterations)",
    )
    for i, (ll, d) in enumerate(zip(logliks, deltas)):
        log.info("em iter=%d loglik=%.4f delta=%.6f (fused)", i + 1, ll, d)
        if metrics is not None:
            metrics.log("em_iter", iteration=i + 1, loglik=ll, delta=d)
    log.info(
        "em fused: %d iteration(s) in %.3fs (one blocking fetch), converged=%s",
        it, dt, bool(converged_a),
    )
    if metrics is not None:
        metrics.log(
            "em_fused", iterations=it, wall_s=dt, converged=bool(converged_a),
        )
    return FitResult(
        params=p, iterations=it, logliks=logliks,
        converged=bool(converged_a), deltas=deltas, recoveries=[],
    )


@dataclasses.dataclass
class FitResult:
    params: HmmParams
    iterations: int
    logliks: list
    converged: bool
    deltas: list
    # (iteration, reason) records of mid-training recoveries (SURVEY.md §5
    # failure detection); empty on a clean run.
    recoveries: list = dataclasses.field(default_factory=list)


def fit(
    params: HmmParams,
    chunked: chunking.Chunked,
    *,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: EStepBackend | str = "local",
    mode: str = "rescaled",
    engine: str = "auto",
    checkpoint_dir: Optional[str] = None,
    callback: Optional[Callable[[int, float, float], None]] = None,
    start_iteration: int = 0,
    metrics: Optional[profiling.MetricsLogger] = None,
    fallback_backend: Optional[EStepBackend] = None,
    checkpoint_format: str = "npz",
    fuse: Union[bool, str] = "auto",
) -> FitResult:
    """Run Baum-Welch EM until convergence or ``num_iters``.

    Matches the reference driver-loop semantics: stop when the max-abs change in
    any model probability drops below ``convergence`` (the MR driver's model
    delta check) or after ``num_iters`` jobs.  Each iteration optionally writes
    an npz checkpoint (the reference persists the model to HDFS per iteration,
    CpGIslandFinder.java:64-89).

    ``fuse`` selects the EM loop execution:

    - ``"auto"`` (default) — run ALL iterations inside one compiled
      ``lax.while_loop`` with the convergence test on device
      (:func:`_fused_em_fn`): K steady-state iterations pay ONE blocking
      round trip instead of K+ (each worth 50-100 ms on a relayed TPU).
      The host loop is kept automatically whenever a host-cadence feature
      is requested (checkpointing, callback, fallback recovery, resumed
      numbering) or the backend cannot provide a traceable stats fn.
    - ``True`` — require the fused loop; raises ValueError when a
      host-cadence feature or the backend makes it impossible.
    - ``False`` — always the host loop (the reference's
      one-job-per-iteration cadence).

    Failure recovery (SURVEY.md §5): if an iteration's statistics come back
    non-finite (numerics blowup) or the E-step raises a runtime error
    (device fault), the iteration is retried once on the same backend; if it
    fails again and ``fallback_backend`` is given (e.g. a log-numerics
    LocalBackend, or an :class:`~cpgisland_tpu.train.elastic.ElasticEStep`),
    training switches to it for the remaining iterations — the model is never
    updated from corrupt statistics.  Without a fallback the error propagates
    after the retry.
    """
    if checkpoint_format not in ("npz", "orbax"):
        # Validate up front — failing at the first save would waste a full
        # EM iteration first.
        raise ValueError(f"unknown checkpoint_format {checkpoint_format!r} (npz|orbax)")
    if fuse not in (True, False, "auto", "on", "off"):
        raise ValueError(f"fuse must be auto|True|False, got {fuse!r}")
    fuse = {"on": True, "off": False}.get(fuse, fuse)
    if not isinstance(fuse, str):
        # Normalize int-ish flags: 0/1 pass the membership check via ==,
        # but the cadence selection below uses identity (`is False` /
        # `is True`) — bool() keeps fuse=0 meaning "host loop" and fuse=1
        # meaning "require fused" rather than both degrading to auto.
        fuse = bool(fuse)
    if isinstance(backend, str):
        backend = get_backend(backend, mode=mode, engine=engine)
    chunked0 = chunked
    chunked = backend.prepare(chunked0)
    chunks, lengths = backend.place(chunked.chunks, chunked.lengths)

    if fuse is not False and num_iters > 0:
        blocked = _fuse_blocked_reason(
            checkpoint_dir, callback, fallback_backend, start_iteration
        )
        # getattr: a duck-typed backend that never subclassed EStepBackend
        # simply keeps the host loop rather than crashing here.
        prep_resolver = getattr(backend, "fused_stats_with_prep", None)
        fused_resolver = getattr(backend, "fused_stats_fn", None)
        stats_fn, fused_prep = None, None
        if blocked is None:
            if prep_resolver is not None:
                # Symbol-only stream prep resolves ONCE, against the placed
                # arrays, and rides into the compiled loop as an explicit
                # argument — zero per-iteration re-preparation.
                stats_fn, fused_prep = prep_resolver(params, chunks, lengths)
            elif fused_resolver is not None:
                stats_fn = fused_resolver(params, chunks, lengths)
        if fuse is True and blocked is not None:
            raise ValueError(
                f"fuse=True is incompatible with {blocked} (those need the "
                "host-loop cadence; use fuse='auto' or False)"
            )
        if fuse is True and stats_fn is None:
            raise ValueError(
                f"{type(backend).__name__} does not provide a fused "
                "(jit-traceable) E-step; use fuse='auto' or False"
            )
        obs.engine_decision(
            site="train.em_loop",
            choice="fused" if stats_fn is not None else "host",
            requested=str(fuse),
            **({} if blocked is None else {"blocked": blocked}),
        )
        if stats_fn is not None:
            try:
                return _fit_fused(
                    params, stats_fn, chunks, lengths,
                    num_iters=num_iters, convergence=convergence,
                    n_sym=float(getattr(chunked, "total", 0.0)), metrics=metrics,
                    prepared=fused_prep,
                )
            except (RuntimeError, FloatingPointError) as e:
                # Fault-shaped failures only (XlaRuntimeError is a
                # RuntimeError; FloatingPointError is the post-hoc
                # check_finite) — the same set the host loop's recovery
                # handles.  fuse='auto' must not cost callers that
                # recovery: the model was never updated from the failed
                # fused run (params are still the caller's), so falling
                # through to the host loop below re-runs from scratch with
                # per-iteration retry/validation intact.  Explicit
                # fuse=True keeps the hard error (the caller asked for the
                # one-program cadence specifically).
                if fuse is True:
                    raise
                log.warning(
                    "fused EM failed (%s: %s); falling back to the "
                    "host-loop cadence with per-iteration recovery",
                    type(e).__name__, e,
                )
                obs.event("em_fused_fallback", error=str(e)[:200])
                if metrics is not None:
                    metrics.log("em_fused_fallback", error=str(e))
    else:
        obs.engine_decision(
            site="train.em_loop", choice="host", requested=str(fuse)
        )

    logliks: list[float] = []
    deltas: list[float] = []
    recoveries: list[tuple[int, str]] = []
    converged = False
    it = 0
    n_sym = float(getattr(chunked, "total", 0.0))
    for it in range(start_iteration + 1, start_iteration + num_iters + 1):
        t0 = time.perf_counter()
        stats = None
        with obs.span("em_iter", items=n_sym, unit="sym", iteration=it):
            for attempt in range(3):
                try:
                    cand = backend(params, chunks, lengths)
                    profiling.check_finite(cand, where=f"E-step iter {it}")
                    stats = cand
                    key = _em_breaker_key(backend, params)
                    if key is not None:
                        from cpgisland_tpu import resilience

                        resilience.get_breaker().record_success(key)
                    break
                # Only fault-shaped errors are retried/recovered: RuntimeError
                # covers jaxlib's XlaRuntimeError (OOM, preemption,
                # interconnect), FloatingPointError is check_finite.
                # Programming errors (ValueError/TypeError) must surface, not
                # reroute to a fallback.
                except (RuntimeError, FloatingPointError) as e:
                    # Feed the engine breaker: repeated kernel-shaped faults
                    # trip the engine, and the per-call re-resolution above
                    # then demotes the NEXT iteration to the parity twin.
                    key = _em_breaker_key(backend, params)
                    if key is not None:
                        from cpgisland_tpu import resilience

                        resilience.get_breaker().record_fault(key, error=e)
                    reason = f"iter {it} attempt {attempt + 1}: {e}"
                    log.warning("E-step failed (%s)", reason)
                    if metrics is not None:
                        metrics.log("em_estep_failure", iteration=it,
                                    attempt=attempt + 1, error=str(e))
                    if attempt == 0:
                        continue  # transient-fault retry on the same backend
                    if attempt == 1 and fallback_backend is not None:
                        log.warning("switching to fallback E-step backend at iter %d", it)
                        recoveries.append((it, reason))
                        backend = fallback_backend
                        chunked = backend.prepare(chunked0)
                        chunks, lengths = backend.place(chunked.chunks, chunked.lengths)
                        continue
                    raise
            # Fused epilogue even on the host cadence: M-step + delta in one
            # program (was two dispatches per iteration).
            new_params, delta_dev = em_update(params, stats)
            # The float() materializations below are THE per-iteration host
            # sync of the reference cadence (one blocking round trip per MR
            # job); note_fetch makes the ledger see it, so a fused-vs-host
            # dispatch comparison reads straight off the obs summary.
            delta = float(obs.note_fetch(delta_dev))
            ll = float(obs.note_fetch(stats.loglik))
        params = new_params
        logliks.append(ll)
        deltas.append(delta)
        dt = time.perf_counter() - t0
        log.info("em iter=%d loglik=%.4f delta=%.6f wall=%.3fs", it, ll, delta, dt)
        # A blowup in the normalize itself (not the stats) still surfaces as a
        # hard error — the model is the restart point, so it must stay clean.
        profiling.check_finite(
            {"pi": params.log_pi, "A": params.log_A, "B": params.log_B, "loglik": ll},
            where=f"em iter {it}",
        )
        if metrics is not None:
            metrics.log("em_iter", iteration=it, loglik=ll, delta=delta, wall_s=dt)
        if callback is not None:
            callback(it, ll, delta)
        if checkpoint_dir is not None:
            ckpt.save(
                ckpt.checkpoint_path(checkpoint_dir, it, format=checkpoint_format),
                ckpt.TrainState(params=params, iteration=it, logliks=logliks),
                format=checkpoint_format,
            )
        if delta < convergence:
            converged = True
            break
    return FitResult(
        params=params, iterations=it, logliks=logliks, converged=converged,
        deltas=deltas, recoveries=recoveries,
    )


def resume(
    checkpoint_dir: str,
    chunked: chunking.Chunked,
    *,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: EStepBackend | str = "local",
    mode: str = "rescaled",
) -> FitResult:
    """Resume training from the latest checkpoint in a directory.

    The reference has no resume path (its per-iteration HDFS model dumps are
    write-only); this makes the natural EM restart point first-class
    (SURVEY.md §5 failure detection / elastic recovery).
    """
    path = ckpt.latest(checkpoint_dir)
    if path is None:
        raise FileNotFoundError(f"no checkpoints under {checkpoint_dir}")
    state = ckpt.load(path)
    remaining = max(0, num_iters - state.iteration)
    result = fit(
        state.params,
        chunked,
        num_iters=remaining,
        convergence=convergence,
        backend=backend,
        mode=mode,
        checkpoint_dir=checkpoint_dir,
        start_iteration=state.iteration,
        # Continue in the format the run was using (Orbax snapshots are
        # directories) — a resumed Orbax run must not switch to npz.
        checkpoint_format="orbax" if os.path.isdir(path) else "npz",
    )
    return dataclasses.replace(result, logliks=list(state.logliks) + result.logliks)
