"""Baum-Welch EM: M-step and the convergence-driven training loop.

The reference's trainer is Mahout's Hadoop Baum-Welch driver: per iteration one
MR job (mappers: forward-backward counts; reducers: sum + normalize), looping
until |model_{t+1} - model_t| < convergence or numIter is reached
(BaumWelchDriver.runBaumWelchMR, CpGIslandFinder.java:200-201; convergence
".005" at :96).  Here the E-step runs through an
:class:`~cpgisland_tpu.train.backends.EStepBackend` (local vmap or mesh-sharded
`psum`), the M-step is a normalize on replicated [K]/[K,K]/[K,M] tensors, and
the loop is host-side Python (one device sync per iteration — exactly the
reference's one-job-per-iteration cadence, minus the JVM startup).

Structural zeros (e.g. the one-hot emission rows of the CpG model,
CpGIslandFinder.java:166-173) are preserved automatically: a zero-probability
emission accumulates exactly zero expected count, so EM is a fixed point in
those coordinates (SURVEY.md C5).  Rows with zero total count keep their
previous distribution rather than dividing by zero.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu import obs
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.train.backends import EStepBackend, get_backend
from cpgisland_tpu.utils import checkpoint as ckpt
from cpgisland_tpu.utils import chunking
from cpgisland_tpu.utils import profiling

log = logging.getLogger(__name__)


@jax.jit
def mstep(params: HmmParams, stats: SuffStats) -> HmmParams:
    """Normalize expected counts into the next model (the reducer's normalize).

    Zero-count rows retain the previous distribution.
    """

    def normalize(counts, prev_probs):
        row = jnp.sum(counts, axis=-1, keepdims=True)
        safe = jnp.where(row > 0, counts / jnp.maximum(row, 1e-30), prev_probs)
        return safe

    pi = normalize(stats.init, jnp.exp(params.log_pi))
    A = normalize(stats.trans, jnp.exp(params.log_A))
    B = normalize(stats.emit, jnp.exp(params.log_B))
    return HmmParams.from_probs(pi, A, B)


@dataclasses.dataclass
class FitResult:
    params: HmmParams
    iterations: int
    logliks: list
    converged: bool
    deltas: list
    # (iteration, reason) records of mid-training recoveries (SURVEY.md §5
    # failure detection); empty on a clean run.
    recoveries: list = dataclasses.field(default_factory=list)


def fit(
    params: HmmParams,
    chunked: chunking.Chunked,
    *,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: EStepBackend | str = "local",
    mode: str = "rescaled",
    engine: str = "auto",
    checkpoint_dir: Optional[str] = None,
    callback: Optional[Callable[[int, float, float], None]] = None,
    start_iteration: int = 0,
    metrics: Optional[profiling.MetricsLogger] = None,
    fallback_backend: Optional[EStepBackend] = None,
    checkpoint_format: str = "npz",
) -> FitResult:
    """Run Baum-Welch EM until convergence or ``num_iters``.

    Matches the reference driver-loop semantics: stop when the max-abs change in
    any model probability drops below ``convergence`` (the MR driver's model
    delta check) or after ``num_iters`` jobs.  Each iteration optionally writes
    an npz checkpoint (the reference persists the model to HDFS per iteration,
    CpGIslandFinder.java:64-89).

    Failure recovery (SURVEY.md §5): if an iteration's statistics come back
    non-finite (numerics blowup) or the E-step raises a runtime error
    (device fault), the iteration is retried once on the same backend; if it
    fails again and ``fallback_backend`` is given (e.g. a log-numerics
    LocalBackend, or an :class:`~cpgisland_tpu.train.elastic.ElasticEStep`),
    training switches to it for the remaining iterations — the model is never
    updated from corrupt statistics.  Without a fallback the error propagates
    after the retry.
    """
    if checkpoint_format not in ("npz", "orbax"):
        # Validate up front — failing at the first save would waste a full
        # EM iteration first.
        raise ValueError(f"unknown checkpoint_format {checkpoint_format!r} (npz|orbax)")
    if isinstance(backend, str):
        backend = get_backend(backend, mode=mode, engine=engine)
    chunked0 = chunked
    chunked = backend.prepare(chunked0)
    chunks, lengths = backend.place(chunked.chunks, chunked.lengths)

    logliks: list[float] = []
    deltas: list[float] = []
    recoveries: list[tuple[int, str]] = []
    converged = False
    it = 0
    n_sym = float(getattr(chunked, "total", 0.0))
    for it in range(start_iteration + 1, start_iteration + num_iters + 1):
        t0 = time.perf_counter()
        stats = None
        with obs.span("em_iter", items=n_sym, unit="sym", iteration=it):
            for attempt in range(3):
                try:
                    cand = backend(params, chunks, lengths)
                    profiling.check_finite(cand, where=f"E-step iter {it}")
                    stats = cand
                    break
                # Only fault-shaped errors are retried/recovered: RuntimeError
                # covers jaxlib's XlaRuntimeError (OOM, preemption,
                # interconnect), FloatingPointError is check_finite.
                # Programming errors (ValueError/TypeError) must surface, not
                # reroute to a fallback.
                except (RuntimeError, FloatingPointError) as e:
                    reason = f"iter {it} attempt {attempt + 1}: {e}"
                    log.warning("E-step failed (%s)", reason)
                    if metrics is not None:
                        metrics.log("em_estep_failure", iteration=it,
                                    attempt=attempt + 1, error=str(e))
                    if attempt == 0:
                        continue  # transient-fault retry on the same backend
                    if attempt == 1 and fallback_backend is not None:
                        log.warning("switching to fallback E-step backend at iter %d", it)
                        recoveries.append((it, reason))
                        backend = fallback_backend
                        chunked = backend.prepare(chunked0)
                        chunks, lengths = backend.place(chunked.chunks, chunked.lengths)
                        continue
                    raise
            new_params = mstep(params, stats)
            delta = float(new_params.max_abs_diff(params))
            ll = float(stats.loglik)
        params = new_params
        logliks.append(ll)
        deltas.append(delta)
        dt = time.perf_counter() - t0
        log.info("em iter=%d loglik=%.4f delta=%.6f wall=%.3fs", it, ll, delta, dt)
        # A blowup in the normalize itself (not the stats) still surfaces as a
        # hard error — the model is the restart point, so it must stay clean.
        profiling.check_finite(
            {"pi": params.log_pi, "A": params.log_A, "B": params.log_B, "loglik": ll},
            where=f"em iter {it}",
        )
        if metrics is not None:
            metrics.log("em_iter", iteration=it, loglik=ll, delta=delta, wall_s=dt)
        if callback is not None:
            callback(it, ll, delta)
        if checkpoint_dir is not None:
            ckpt.save(
                ckpt.checkpoint_path(checkpoint_dir, it, format=checkpoint_format),
                ckpt.TrainState(params=params, iteration=it, logliks=logliks),
                format=checkpoint_format,
            )
        if delta < convergence:
            converged = True
            break
    return FitResult(
        params=params, iterations=it, logliks=logliks, converged=converged,
        deltas=deltas, recoveries=recoveries,
    )


def resume(
    checkpoint_dir: str,
    chunked: chunking.Chunked,
    *,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: EStepBackend | str = "local",
    mode: str = "rescaled",
) -> FitResult:
    """Resume training from the latest checkpoint in a directory.

    The reference has no resume path (its per-iteration HDFS model dumps are
    write-only); this makes the natural EM restart point first-class
    (SURVEY.md §5 failure detection / elastic recovery).
    """
    path = ckpt.latest(checkpoint_dir)
    if path is None:
        raise FileNotFoundError(f"no checkpoints under {checkpoint_dir}")
    state = ckpt.load(path)
    remaining = max(0, num_iters - state.iteration)
    result = fit(
        state.params,
        chunked,
        num_iters=remaining,
        convergence=convergence,
        backend=backend,
        mode=mode,
        checkpoint_dir=checkpoint_dir,
        start_iteration=state.iteration,
        # Continue in the format the run was using (Orbax snapshots are
        # directories) — a resumed Orbax run must not switch to npz.
        checkpoint_format="orbax" if os.path.isdir(path) else "npz",
    )
    return dataclasses.replace(result, logliks=list(state.logliks) + result.logliks)
