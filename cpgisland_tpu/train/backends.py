"""E-step execution backends behind the reference's mapper/reducer contract.

The reference trains by submitting one Hadoop MR job per EM iteration: mappers
run forward-backward over 65,536-symbol chunks and emit expected-count
statistics, the shuffle+reduce phase sums them, and the driver loops
(BaumWelchDriver.runBaumWelchMR, CpGIslandFinder.java:200-201).  That contract —
*map chunks to SuffStats, reduce by summation* — survives here as
:class:`EStepBackend`, with two implementations selected by a flag:

- ``local`` — one device: `vmap` the mapper over the chunk batch, `sum` reduce.
- ``spmd``  — a `jax.sharding.Mesh`: chunks are sharded over the ``data`` axis,
  each device maps its shard, and the reduce is a single `psum` over ICI —
  the all-reduce that replaces Hadoop's shuffle+reduce, with model replication
  replacing the distributed cache (SURVEY.md §5 "Distributed comms backend").

Both backends produce bit-identical statistics up to float reduction order.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cpgisland_tpu import obs
from cpgisland_tpu.analysis import memmodel
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import fb_pallas
from cpgisland_tpu.ops.forward_backward import SuffStats, batch_stats
from cpgisland_tpu.parallel import fb_sharded
from cpgisland_tpu.parallel.mesh import make_mesh
from cpgisland_tpu.utils import chunking


def _onehot_envelope_ok(params: HmmParams) -> bool:
    """The reduced engines' state envelope (fb_onehot.ONEHOT_MAX_STATES) —
    the chains are K-free; [K*K] stats accumulators bound K at 32 (the
    dinucleotide member's size, ROADMAP item 2's K<=8 lift)."""
    from cpgisland_tpu.ops.fb_onehot import ONEHOT_MAX_STATES

    return params.n_states <= ONEHOT_MAX_STATES


def _em_engine_twin(engine: str, params: HmmParams) -> "Optional[str]":
    """Parity-twin ladder for the E-step engines (the resilience breaker's
    fallback map, keyed ``em.<engine>`` — the shared
    resilience.breaker.kernel_ladder with the E-step eligibility)."""
    from cpgisland_tpu.resilience.breaker import kernel_ladder

    return kernel_ladder(
        jax.default_backend() == "tpu" and fb_pallas.supports(params)
    )(engine)


def resolve_fb_engine(engine: str, params: HmmParams, mode: str) -> str:
    """'auto' picks the Pallas E-step kernels on TPU for rescaled numerics
    (the only mode they implement), the XLA scans otherwise.  Under
    'auto', engines tripped by the resilience breaker demote down the
    parity-twin ladder for the cooldown window — ``fit``'s host-loop
    recovery records the faults, and backends re-resolve per call, so a
    trip reroutes the NEXT iteration.  Explicit engine requests are
    honored as-is (see parallel.decode.resolve_engine)."""
    from cpgisland_tpu import resilience

    def _degrade(resolved: str) -> str:
        # xla implements both numerics modes, so the ladder is always
        # mode-eligible (the tripped rungs are the rescaled-only kernels).
        return resilience.get_breaker().degrade(
            "em", resolved, lambda e: _em_engine_twin(e, params)
        )

    if engine == "auto":
        resolved = "xla"
        if jax.default_backend() == "tpu" and mode == "rescaled":
            from cpgisland_tpu.family import partition as family_partition

            # The reduced one-hot path needed its own stats kernel to win
            # here: with the dense stats pass (streams scattered back to
            # dense) it REGRESSED 923 -> 809 Msym/s/iter, and with the
            # reduced-stream stats kernel (fb_onehot._oh_stats_kernel,
            # 16 B/symbol read, in-register scatter) it measured
            # 977 -> 1340.  That kernel lowers only for power-of-two
            # n_symbols, which the one-hot eligibility alone does not
            # guarantee — family.reduced_stats_eligible gates on both
            # (the one copy of this check, shared with the other routers).
            # The reduced chains are K-free, so the envelope here is the
            # reduced one (fb_onehot.ONEHOT_MAX_STATES — the K<=8 lift of
            # ROADMAP item 2: the 32-state dinuc member now trains through
            # the reduced stats path); the dense fused kernels keep their
            # n_states <= 8 lane packing.
            if (
                family_partition.reduced_stats_eligible(params)
                and _onehot_envelope_ok(params)
            ):
                resolved = "onehot"
            elif fb_pallas.supports(params):
                resolved = "pallas"
        # Tuned engine choice (graftune): a fresh applied winner inside
        # the currently-eligible ladder overrides auto's hard-coded pick;
        # absent/stale keeps it bit for bit.  Eligibility is never
        # relaxed — a winner the model cannot run is refused in-domain.
        from cpgisland_tpu import tune

        eligible = {"xla", resolved}
        resolved = tune.default_engine("fb_chunked", resolved, eligible)
        obs.engine_decision(
            site="train.resolve_fb_engine", choice=resolved,
            requested=engine, mode=mode,
        )
        return _degrade(resolved)
    if engine not in ("xla", "pallas", "onehot"):
        raise ValueError(
            f"unknown engine {engine!r}; expected auto|xla|pallas|onehot"
        )
    if engine in ("pallas", "onehot") and mode != "rescaled":
        raise ValueError(f"{engine} E-step implements rescaled numerics only")
    if engine == "onehot":
        from cpgisland_tpu.family import partition as family_partition

        if not _onehot_envelope_ok(params):
            from cpgisland_tpu.ops.fb_onehot import ONEHOT_MAX_STATES

            raise ValueError(
                f"onehot E-step kernels need n_states <= "
                f"{ONEHOT_MAX_STATES}, got {params.n_states}"
            )
        if family_partition.reduced_eligible_concrete(params) is False:
            raise ValueError(
                "engine='onehot' needs a one-hot emission-support "
                "partition with 2 states per symbol (family.partition_of)"
            )
    return engine


@functools.lru_cache(maxsize=None)
def _local_stats_fn(engine: str, mode: str, fuse_fb: bool = True):
    """(params, chunks, lengths) -> batch-summed SuffStats, engine-lowered.

    lru_cached so the SAME callable comes back for the same routing — the
    fused EM driver (train.baum_welch._fused_em_fn) keys its compiled
    K-iteration program on this object's identity.  ``fuse_fb``: the r9
    co-scheduled fwd/bwd pass (onehot only; False = the split 3-kernel
    A/B arm, tools/bench_passfusion.py).
    """
    if engine == "pallas":
        return fb_pallas.batch_stats_pallas
    if engine == "onehot":
        return partial(fb_pallas.batch_stats_pallas, onehot=True, fused=fuse_fb)
    return partial(batch_stats, mode=mode)


class EStepBackend:
    """Protocol: __call__(params, chunks [N,T], lengths [N]) -> SuffStats."""

    def __call__(self, params: HmmParams, chunks, lengths) -> SuffStats:
        raise NotImplementedError

    def prepare(self, chunked: chunking.Chunked) -> chunking.Chunked:
        """Adjust a chunk batch to the backend's layout requirements.

        The specialized containers are rejected here so a backend that does
        not understand them can never silently mistrain — a LocalShard is
        1/P of the data (only SpmdBackend assembles the global array from
        it), and a Bucketed batch needs the per-group meshes only
        Seq2DBackend builds.  This matters for fit()'s fallback-backend
        switch: the fallback re-prepares the ORIGINAL input.
        """
        if isinstance(chunked, (chunking.LocalShard, chunking.Bucketed)):
            raise ValueError(
                f"{type(self).__name__} does not support "
                f"{type(chunked).__name__} input ({'SpmdBackend' if isinstance(chunked, chunking.LocalShard) else 'Seq2DBackend'} does)"
            )
        return chunked

    def place(self, chunks, lengths):
        """Device-place a chunk batch once, before the iteration loop.

        Training data never changes across EM iterations, so the trainer calls
        this once and then reuses the placed arrays — one host->device (and
        cross-device shard) transfer per run, not per iteration.
        """
        return jnp.asarray(chunks), jnp.asarray(lengths)

    def fused_stats_fn(
        self, params: HmmParams, chunks, lengths
    ) -> Optional[Callable]:
        """A jit-traceable ``(params, chunks, lengths) -> SuffStats`` for the
        fused multi-iteration EM loop, or None when the backend cannot fuse.

        All host-side routing (engine resolution, shape validation) is
        resolved HERE against the concrete initial ``params`` and the placed
        arrays — the returned callable must be pure in its traced arguments
        so K iterations can run inside one compiled ``lax.while_loop``
        (train.baum_welch).  Resolving once is semantically safe: the
        routing depends only on emission STRUCTURE (one-hot zero pattern),
        which EM preserves (structural zeros are fixed points).  Contract
        for implementers: return a STABLE callable (cached per routing) so
        repeated ``fit`` calls reuse the compiled loop.
        """
        return None

    def prepare_streams(self, params: HmmParams, chunks, lengths):
        """Symbol-only prepared streams (ops.prepared) for the PLACED input,
        or None when the routing has no prepared form.

        The result is passed to the stats fn as an EXPLICIT argument
        (``prepared=``) — never closed over — so the fused EM while_loop
        body performs zero symbol-stream prep per iteration (the
        ``em.body.invariant-free`` graftcheck contract).  Implementations
        must return None for traced inputs (prep is a host-side cache; a
        tracer must fall back to inline prep in-graph).
        """
        return None

    def fused_stats_with_prep(self, params: HmmParams, chunks, lengths):
        """(stats_fn, prepared) for the fused EM driver.

        When ``prepared`` is not None the returned callable accepts
        ``(params, chunks, lengths, prepared=...)``; otherwise it has the
        plain :meth:`fused_stats_fn` signature.  Default: no prep.
        """
        return self.fused_stats_fn(params, chunks, lengths), None


class LocalBackend(EStepBackend):
    """Single-device vmap mapper + sum reducer.

    ``fuse_fb=False`` keeps the split (r4) fwd/bwd kernel structure on the
    onehot routing — the pass-fusion A/B arm; ``None`` (default) consults
    the graftune winner table (``fused.em_chunked``) and falls back to
    the shipped co-scheduled True; an explicit bool always wins.

    There is no ``one_pass`` knob here: the chunked layout never ran a
    standalone products pass, so the fused chunked route is already ONE
    T-scaling pass (see fb_pallas.batch_posterior_pallas)."""

    def __init__(self, mode: str = "rescaled", engine: str = "auto",
                 fuse_fb: Optional[bool] = None):
        from cpgisland_tpu import tune

        self.mode = mode
        self.engine = engine
        self.fuse_fb = (
            tune.default_fused("em_chunked") if fuse_fb is None
            else bool(fuse_fb)
        )

    def prepare_streams(self, params, chunks, lengths):
        if isinstance(chunks, jax.core.Tracer):
            # Under an outer trace (e.g. bench's chained harness) there is
            # nothing host-cacheable — the inline in-graph prep is the same
            # HLO.
            return None
        engine = resolve_fb_engine(self.engine, params, self.mode)
        if engine not in ("pallas", "onehot"):
            return None
        from cpgisland_tpu.ops import prepared as prep_mod

        return prep_mod.for_chunked(
            params.n_symbols, jnp.asarray(chunks), jnp.asarray(lengths),
            t_tile=fb_pallas.DEFAULT_T_TILE, onehot=engine == "onehot",
        )

    def __call__(self, params, chunks, lengths):
        fn = _local_stats_fn(
            resolve_fb_engine(self.engine, params, self.mode), self.mode,
            self.fuse_fb,
        )
        chunks, lengths = jnp.asarray(chunks), jnp.asarray(lengths)
        prep = self.prepare_streams(params, chunks, lengths)
        if prep is not None:
            return fn(params, chunks, lengths, prepared=prep)
        return fn(params, chunks, lengths)

    def fused_stats_fn(self, params, chunks, lengths):
        return _local_stats_fn(
            resolve_fb_engine(self.engine, params, self.mode), self.mode,
            self.fuse_fb,
        )

    def fused_stats_with_prep(self, params, chunks, lengths):
        return (
            self.fused_stats_fn(params, chunks, lengths),
            self.prepare_streams(params, chunks, lengths),
        )


class SpmdBackend(EStepBackend):
    """Mesh-sharded mapper + `psum` reducer over the ``data`` axis.

    The chunk batch [N, T] is sharded N-ways over the mesh's data axis (N must
    be a multiple of the axis size — use :meth:`prepare`, which pads with
    zero-length chunks contributing exactly-zero statistics).  The model is
    replicated, mirroring the reference's distributed-cache broadcast.

    Like LocalBackend there is no ``one_pass`` knob — the chunked layout
    is already one T-scaling pass when fused.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        mode: str = "rescaled",
        axis: str = "data",
        engine: str = "auto",
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.mode = mode
        self.axis = axis
        self.engine = engine
        self._estep_cache = {}
        self._prep_fn_cache = {}

    def _estep_for(self, params, prep_meta=None):
        engine = resolve_fb_engine(self.engine, params, self.mode)
        key = (engine, prep_meta)
        if key not in self._estep_cache:
            local_fn = _local_stats_fn(engine, self.mode)

            if prep_meta is not None:
                from cpgisland_tpu.ops import prepared as prep_mod

                S, N_local, T, t_tile, onehot = prep_meta

                def estep(params, chunks, lengths, prepared):
                    # Same mapper + psum, with this device's prepared block
                    # arriving as a sharded ARGUMENT (resolved once outside
                    # the fused loop, never re-derived per iteration).
                    return jax.lax.psum(
                        local_fn(params, chunks, lengths, prepared=prepared),
                        axis_name=self.axis,
                    )

                in_specs = (
                    P(), P(self.axis), P(self.axis),
                    prep_mod.chunked_spec_tree(
                        S, N_local, T, t_tile, onehot, self.axis
                    ),
                )
            else:
                def estep(params, chunks, lengths):
                    # mapper (per-shard batch stats) + the psum all-reduce
                    # that replaces Hadoop's shuffle+reduce.
                    return jax.lax.psum(
                        local_fn(params, chunks, lengths), axis_name=self.axis
                    )

                in_specs = (P(), P(self.axis), P(self.axis))

            compiled = jax.jit(
                jax.shard_map(
                    estep,
                    mesh=self.mesh,
                    in_specs=in_specs,
                    out_specs=P(),
                    check_vma=engine == "xla",
                )
            )
            if prep_meta is not None:
                # Keyword-normalizing shim, cached here (not per fused
                # call) so the fused driver's lru key stays stable.
                from cpgisland_tpu.ops import prepared as prep_mod

                compiled = prep_mod.kw_prepared_shim(compiled)
            self._estep_cache[key] = compiled
        return self._estep_cache[key]

    def prepare_streams(self, params, chunks, lengths):
        out = self._prepare_with_meta(params, chunks, lengths)
        return None if out is None else out[0]

    def _prepare_with_meta(self, params, chunks, lengths):
        """Per-device prepared blocks, built IN PLACE by a sharded builder
        (one jitted shard_map dispatch over the already-placed batch — no
        host round trip of the symbols) and cached on the placed arrays'
        identity like the single-device layouts.  Returns (prep, meta) —
        meta keys the matching prep-aware estep's in_specs."""
        if isinstance(chunks, jax.core.Tracer):
            return None
        engine = resolve_fb_engine(self.engine, params, self.mode)
        if engine not in ("pallas", "onehot"):
            return None
        from cpgisland_tpu.ops import prepared as prep_mod

        S = params.n_symbols
        T = int(chunks.shape[1])
        t_tile = fb_pallas.DEFAULT_T_TILE
        onehot = engine == "onehot"
        N_local = int(chunks.shape[0]) // self.mesh.shape[self.axis]
        fkey = (S, N_local, T, t_tile, onehot)
        if fkey not in self._prep_fn_cache:
            self._prep_fn_cache[fkey] = prep_mod.sharded_chunked_builder(
                self.mesh, self.axis, (P(self.axis), P(self.axis)),
                S, N_local, T, t_tile, onehot,
            )
        builder = self._prep_fn_cache[fkey]
        prep = prep_mod.cached_build(
            "chunked-spmd", (chunks, lengths),
            fkey + (str(self.mesh),),
            lambda: builder(chunks, lengths),
        )
        return prep, fkey

    def prepare(self, chunked):
        if isinstance(chunked, chunking.Bucketed):
            raise ValueError(
                "SpmdBackend does not support Bucketed input (Seq2DBackend does)"
            )
        if isinstance(chunked, chunking.LocalShard):
            # Per-process pre-sharded input (chunking.distributed_chunked —
            # no host ever held the global batch).  Row padding already
            # matches this mesh when pad_multiple was the axis size.
            n_dev = self.mesh.shape[self.axis]
            if chunked.global_rows % n_dev:
                raise ValueError(
                    f"LocalShard global_rows {chunked.global_rows} not "
                    f"divisible by mesh axis size {n_dev}; build it with "
                    f"pad_multiple={n_dev}"
                )
            self._local_rows = (chunked.num_chunks, chunked.global_rows)
            return chunked
        self._local_rows = None
        return chunking.pad_to_multiple(chunked, self.mesh.shape[self.axis])

    def place(self, chunks, lengths):
        """Device-place a prepared GLOBAL batch on the mesh.

        Single-process: one device_put with the data-axis sharding.
        Multi-host (jax.process_count() > 1, after initialize_multihost):
        every process passes the same global batch; this host keeps only its
        contiguous block (utils.chunking.process_shard — the HDFS-input-split
        equivalent, CpGIslandFinder.java:108-147) and assembles the global
        array from the local shard, so no host uploads rows it doesn't own.
        A prepared LocalShard (each host built ONLY its block from its byte
        range of the file) goes straight to the global-array assembly.
        """
        local = getattr(self, "_local_rows", None)
        if local is not None:
            n_local, global_rows = local
            chunks = np.asarray(chunks)
            lengths = np.asarray(lengths)
            if chunks.shape[0] != n_local:
                raise ValueError(
                    f"placed rows {chunks.shape[0]} != prepared LocalShard "
                    f"rows {n_local}; prepare() and place() must see the "
                    "same shard"
                )
            sharding = NamedSharding(self.mesh, P(self.axis))
            return (
                jax.make_array_from_process_local_data(
                    sharding, chunks, (global_rows, chunks.shape[1])
                ),
                jax.make_array_from_process_local_data(
                    sharding, lengths, (global_rows,)
                ),
            )
        self._check_divisible(chunks)
        sharding = NamedSharding(self.mesh, P(self.axis))
        if jax.process_count() > 1:
            chunks = np.asarray(chunks)
            lengths = np.asarray(lengths)
            local = chunking.process_shard(
                chunking.Chunked(
                    chunks=chunks, lengths=lengths, total=int(lengths.sum())
                ),
                jax.process_index(),
                jax.process_count(),
            )
            return (
                jax.make_array_from_process_local_data(
                    sharding, local.chunks, chunks.shape
                ),
                jax.make_array_from_process_local_data(
                    sharding, local.lengths, lengths.shape
                ),
            )
        return (
            jax.device_put(jnp.asarray(chunks), sharding),
            jax.device_put(jnp.asarray(lengths), sharding),
        )

    def _check_divisible(self, chunks):
        n_dev = self.mesh.shape[self.axis]
        if chunks.shape[0] % n_dev != 0:
            raise ValueError(
                f"chunk count {chunks.shape[0]} not divisible by mesh axis "
                f"'{self.axis}' size {n_dev}; call prepare() first"
            )

    def __call__(self, params, chunks, lengths):
        self._check_divisible(chunks)
        out = self._prepare_with_meta(params, chunks, lengths)
        if out is not None:
            prep, meta = out
            return self._estep_for(params, meta)(params, chunks, lengths, prep)
        # Already-placed arrays (from place()) pass through; anything else is
        # resharded by jit according to the shard_map in_specs.
        return self._estep_for(params)(params, chunks, lengths)

    def fused_stats_fn(self, params, chunks, lengths):
        self._check_divisible(chunks)
        # The cached jit(shard_map) estep traces inline under the fused
        # loop; the psum all-reduce runs inside each while_loop iteration,
        # so the multi-iteration program is still ONE dispatch per fit.
        return self._estep_for(params)

    def fused_stats_with_prep(self, params, chunks, lengths):
        self._check_divisible(chunks)
        out = self._prepare_with_meta(params, chunks, lengths)
        if out is None:
            return self._estep_for(params), None
        prep, meta = out
        return self._estep_for(params, meta), prep


def _check_seq_engine(engine: str) -> None:
    if engine not in ("auto", "xla", "pallas", "onehot"):
        raise ValueError(
            f"sequence-parallel engine must be auto|xla|pallas|onehot, "
            f"got {engine!r}"
        )


# Largest per-shard whole-sequence E-step a single 16 GB v5e chip can
# compile and run: measured r4 — 120 Mi compiled and ran, 128 Mi failed
# remote compile, and the XLA lane path at 128 Mi did not finish
# compiling in 10 min.  Since graftmem (Layer 5) the budget is DERIVED
# from the static HBM model (memmodel.SEQ_STREAM_BYTES x symbols against
# the 16 GB chip minus the runtime reserve, floored to the 16 Mi
# granule); the derivation lands on the same 112 Mi the measurements
# pinned — routing parity enforced by tests/test_graftmem.py and the
# mem.seq-shard-budget contract.  This is PER SHARD: a v5e-8 mesh trains
# an 8x longer sequence, and seq2d's per-record rows shard each record's
# time axis the same way.
SEQ_SHARD_BUDGET = memmodel.max_seq_shard()

# Largest record class the 2-D backend routes to the whole-record-per-lane
# chunked fast path (sharded_stats2d_rows_fn): 64 Ki is the chunked
# kernels' production lane shape (the reference's own chunk size).
SMALL_RECORD_ROWS_MAX = 1 << 16


def _check_seq_shard(shard_len: int, what: str) -> None:
    """Fail oversize whole-sequence shards with advice, not an opaque
    compiler HTTP 500 after minutes of upload."""
    if shard_len > SEQ_SHARD_BUDGET:
        alt = (
            "a bigger seq axis in the group meshes"
            if what == "Seq2DBackend"
            else "a bigger mesh, or per-record rows with backend='seq2d'"
        )
        report = memmodel.seq_shard_report(shard_len)
        obs.event(
            "seq_shard_budget_reject", shard_len=shard_len, backend=what,
            budget=SEQ_SHARD_BUDGET,
        )
        obs.event(
            "mem_reject", site="seq_shard", backend=what,
            shard_len=shard_len,
            predicted_bytes=report["predicted_bytes"],
            hbm_limit_bytes=report["hbm_limit_bytes"],
            max_fit_symbols=report["max_fit_symbols"],
        )
        raise ValueError(
            f"{what}: per-device shard of {shard_len} symbols exceeds the "
            f"~{SEQ_SHARD_BUDGET >> 20} Mi single-chip whole-sequence "
            f"E-step budget (modeled footprint "
            f"~{report['predicted_bytes'] >> 30} GiB at "
            f"{report['bytes_per_symbol']} B/symbol vs "
            f"~{report['hbm_limit_bytes'] >> 30} GiB usable HBM; max fit "
            f"{report['max_fit_symbols'] >> 20} Mi symbols/shard) — shard "
            f"time across more devices ({alt}), or use the chunked "
            "'spmd' backend (the reference's own framing)"
        )


def _use_fused_seq(engine: str, params: HmmParams, shard_len: int) -> bool:
    """Route a whole-sequence E-step to the fused Pallas lowering?

    auto gates on TPU + a big-enough per-device shard; an explicit "pallas"
    always takes the fused path (interpreted off-TPU), erroring on models the
    kernels don't support rather than silently falling back.
    """
    if engine == "xla":
        return False
    if engine in ("pallas", "onehot"):
        if engine == "pallas" and not fb_pallas.supports(params):
            raise ValueError(
                f"engine='pallas' but the fused kernels do not support "
                f"{params.n_states} states"
            )
        if engine == "onehot":
            from cpgisland_tpu.family import partition as family_partition

            # The reduced route's envelope, not the dense lane packing
            # (the K<=8 lift: K=32 dinuc trains reduced).
            if not _onehot_envelope_ok(params):
                raise ValueError(
                    f"engine='onehot' but {params.n_states} states exceed "
                    "the reduced envelope (fb_onehot.ONEHOT_MAX_STATES)"
                )
            # None = traced params (undecidable): trust the explicit choice.
            if family_partition.reduced_eligible_concrete(params) is False:
                raise ValueError(
                    "engine='onehot' needs a one-hot emission-support "
                    "partition with 2 states per symbol "
                    "(family.partition_of)"
                )
        return True
    if shard_len < (1 << 20) or jax.default_backend() != "tpu":
        return False
    if fb_pallas.supports(params):
        return True
    # Dense kernels can't take it, but the reduced route can: auto admits
    # big one-hot members (the dinuc pair-lift) when _seq_onehot will
    # route them reduced end to end.
    from cpgisland_tpu.family import partition as family_partition

    return (
        family_partition.reduced_eligible(params)
        and _onehot_envelope_ok(params)
    )


def _seq_onehot(engine: str, params: HmmParams) -> bool:
    """Route a fused whole-sequence E-step through the reduced one-hot
    kernels?  Explicit 'onehot' always (validated in _use_fused_seq);
    'auto' when the model's emission structure supports them."""
    if engine == "onehot":
        return True
    if engine == "auto":
        from cpgisland_tpu.family import partition as family_partition

        return family_partition.reduced_eligible(params) and _onehot_envelope_ok(
            params
        )
    return False


@functools.lru_cache(maxsize=32)
def _seq_single_stats_fn(lane_T: int, t_tile: int, onehot: bool,
                         fuse_fb: bool = True, one_pass: bool = False):
    """Stable single-device whole-sequence stats fn (fused-EM cacheable)."""

    def fn(params, obs_flat, lengths, prepared=None):
        return fb_pallas.seq_stats_pallas(
            params, obs_flat, jnp.sum(lengths),
            lane_T=lane_T, t_tile=t_tile, onehot=onehot, prepared=prepared,
            fused=fuse_fb, one_pass=one_pass,
        )

    return fn


class SeqBackend(EStepBackend):
    """Exact whole-sequence E-step, sequence-parallel over the mesh.

    Treats the ENTIRE training input as ONE contiguous sequence (n_seqs == 1),
    sharded along time across devices with boundary-message exchange
    (parallel.fb_sharded) — no 65,536-symbol independence approximation and no
    dropped boundary transition pairs, unlike the reference's chunked mapper
    contract (CpGIslandFinder.java:130-141).  Numerics are rescaled
    probability-space (the scale-free boundary trick needs them — no ``mode``
    knob); ``engine`` picks the block-pass lowering (auto / xla / pallas, see
    __init__).
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        block_size: Optional[int] = None,
        axis: str = "seq",
        pad_value: int = chunking.PAD_SYMBOL,
        engine: str = "auto",
        lane_T: Optional[int] = None,
        t_tile: Optional[int] = None,
        fuse_fb: Optional[bool] = None,
        one_pass: Optional[bool] = None,
    ):
        from cpgisland_tpu import tune

        _check_seq_engine(engine)
        self.fuse_fb = (
            tune.default_fused("em_seq") if fuse_fb is None
            else bool(fuse_fb)
        )
        # True one-pass reduced arm (matrix-carried fwd/bwd, the products
        # pass folded in).  None consults the graftune ``one_pass.em_seq``
        # winner (shipped default False); explicit always wins.  Only the
        # kernel-stats one-hot route honors it — elsewhere it silently
        # falls back to the fused 2-pass arm bit-for-bit (fb_pallas gate).
        self.one_pass = (
            tune.default_one_pass("em_seq") if one_pass is None
            else bool(one_pass)
        )
        self.mesh = mesh if mesh is not None else make_mesh(axis=axis)
        self.block_size = block_size if block_size is not None else fb_sharded.DEFAULT_BLOCK
        self.axis = self.mesh.axis_names[0]
        # Must be >= the model's n_symbols (fb_sharded's PAD contract); the
        # default matches the 4-symbol DNA alphabet — pass n_symbols for
        # bigger alphabets.
        self.pad_value = pad_value
        # auto: fused kernels on big-enough TPU shards, XLA lanes otherwise;
        # xla / pallas force one lowering.  lane_T / t_tile tune the fused
        # kernels (default: fb_pallas.pick_lane_T by shard size / the
        # graftune ``t_tile.em_seq`` winner falling back to
        # DEFAULT_T_TILE); explicit values always win.
        self.engine = engine
        self.lane_T = lane_T
        self.t_tile = (
            t_tile if t_tile is not None
            else tune.default_t_tile("em_seq", fb_pallas.DEFAULT_T_TILE)
        )

    def prepare(self, chunked: chunking.Chunked) -> chunking.Chunked:
        """Re-frame any chunk batch as one stream sharded across the mesh."""
        if isinstance(chunked, (chunking.LocalShard, chunking.Bucketed)):
            raise ValueError(
                f"SeqBackend does not support {type(chunked).__name__} input"
            )
        stream = np.concatenate(
            [np.asarray(c[:l]) for c, l in zip(chunked.chunks, chunked.lengths)]
        ) if chunked.num_chunks else np.zeros(0, np.uint8)
        n_dev = self.mesh.shape[self.axis]
        obs_p, lengths = fb_sharded.shard_sequence(
            stream, n_dev, self.block_size, pad_value=self.pad_value
        )
        return chunking.Chunked(
            chunks=obs_p.reshape(n_dev, -1), lengths=lengths, total=int(stream.shape[0])
        )

    def place(self, chunks, lengths):
        sharding = NamedSharding(self.mesh, P(self.axis))
        return (
            jax.device_put(jnp.asarray(np.asarray(chunks).reshape(-1)), sharding),
            jax.device_put(jnp.asarray(lengths), sharding),
        )

    def _stats_fn_for(self, params, obs_flat) -> Callable:
        """Validate a placed stream and resolve its traceable stats fn.

        The ONE routing point behind __call__ and fused_stats_fn: engine
        choice and shape checks run here (concrete params + placed shapes);
        the returned callable is pure in (params, obs_flat, lengths) and
        stable per routing (lru-cached factories), so the fused EM driver
        can key its compiled loop on it.
        """
        n_dev = self.mesh.shape[self.axis]
        if getattr(obs_flat, "ndim", 1) != 1:
            raise ValueError(
                f"SeqBackend expects a flat placed [D*L] stream, got shape "
                f"{obs_flat.shape}; run prepare() + place() first"
            )
        if obs_flat.shape[0] % (n_dev * self.block_size) != 0:
            raise ValueError(
                f"stream length {obs_flat.shape[0]} not a multiple of "
                f"devices*block_size = {n_dev}*{self.block_size}; run prepare() first"
            )
        _check_seq_shard(obs_flat.shape[0] // n_dev, "SeqBackend")
        # On TPU the fused-kernel whole-sequence path (exact boundary
        # messages from the lane-products kernel) runs ~15x the XLA lane
        # machinery: single-device directly, multi-device through the
        # shard_map twin whose collectives exchange the messages across
        # chips.  auto gates on shard size (under ~1M symbols the kernels'
        # full 128-lane padded pass dwarfs tiny inputs) — an explicit
        # engine always wins.
        if _use_fused_seq(self.engine, params, obs_flat.shape[0] // n_dev):
            oh, lane_T = self._fused_geometry(params, obs_flat, n_dev)
            obs.engine_decision(
                site="seq_backend", choice="onehot" if oh else "pallas",
                requested=self.engine, n_dev=n_dev,
            )
            if n_dev == 1:
                return _seq_single_stats_fn(
                    lane_T, self.t_tile, oh, self.fuse_fb, self.one_pass
                )
            return fb_sharded.sharded_stats_pallas_fn(
                self.mesh, lane_T, self.t_tile, oh, self.fuse_fb,
                self.one_pass,
            )
        obs.engine_decision(
            site="seq_backend", choice="xla", requested=self.engine, n_dev=n_dev
        )
        return fb_sharded.sharded_stats_fn(self.mesh, self.block_size)

    def _fused_geometry(self, params, obs_flat, n_dev):
        """(onehot, lane_T) of the fused route — the ONE derivation shared
        by the stats fn and prepare_streams so their geometries cannot
        diverge."""
        oh = _seq_onehot(self.engine, params)
        # 131072 lanes are safe only when the kernelized seq stats runs
        # (power-of-two n_symbols — n_symbols is static shape info).
        long_ok = oh and params.n_symbols & (params.n_symbols - 1) == 0
        lane_T = (
            self.lane_T
            if self.lane_T is not None
            else fb_pallas.pick_lane_T(
                obs_flat.shape[0] // n_dev, onehot=oh, long_lanes=long_ok
            )
        )
        return oh, lane_T

    def prepare_streams(self, params, obs_flat, lengths):
        """Single-device PreparedSeq (the sharded seq paths keep inline
        prep — their prev-symbol/boundary threading needs the mesh
        collectives at build time)."""
        if isinstance(obs_flat, jax.core.Tracer):
            return None
        n_dev = self.mesh.shape[self.axis]
        if n_dev != 1 or getattr(obs_flat, "ndim", 1) != 1:
            return None
        if obs_flat.shape[0] % (n_dev * self.block_size) != 0:
            return None
        if not _use_fused_seq(self.engine, params, obs_flat.shape[0]):
            return None
        oh, lane_T = self._fused_geometry(params, obs_flat, n_dev)
        from cpgisland_tpu.ops import prepared as prep_mod

        # The prep key needs the concrete total length — one tiny scalar
        # fetch, MEMOIZED on the placed lengths array's identity so the
        # host-loop cadence pays the relay round trip once per placed
        # input, not once per EM iteration (ledger-counted when it does).
        length = prep_mod.cached_build(
            "seq-length", (lengths,), (),
            lambda: int(np.asarray(obs.note_fetch(lengths)).sum()),
        )
        return prep_mod.for_seq(
            params.n_symbols, obs_flat, length, lane_T=lane_T,
            t_tile=self.t_tile, onehot=oh,
        )

    def __call__(self, params, obs_flat, lengths):
        fn = self._stats_fn_for(params, obs_flat)
        prep = self.prepare_streams(params, obs_flat, lengths)
        if prep is not None:
            return fn(params, obs_flat, lengths, prepared=prep)
        return fn(params, obs_flat, lengths)

    def fused_stats_fn(self, params, chunks, lengths):
        return self._stats_fn_for(params, chunks)

    def fused_stats_with_prep(self, params, chunks, lengths):
        return (
            self._stats_fn_for(params, chunks),
            self.prepare_streams(params, chunks, lengths),
        )


class Seq2DBackend(EStepBackend):
    """Batch-of-sequences E-step on a 2-D (data x seq) mesh.

    Each input chunk row is treated as ONE whole sequence (e.g. one
    chromosome): rows are distributed over the ``data`` axis and each row's
    time dimension is sharded over the ``seq`` axis — dp x sp composed on one
    mesh.  Statistics are the exact per-sequence whole-sequence counts,
    summed; like SeqBackend there is no within-sequence chunk-independence
    approximation.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        block_size: Optional[int] = None,
        pad_value: int = chunking.PAD_SYMBOL,
        engine: str = "auto",
        lane_T: Optional[int] = None,
        t_tile: Optional[int] = None,
        one_pass: Optional[bool] = None,
    ):
        from cpgisland_tpu import tune

        if mesh is not None and len(mesh.axis_names) != 2:
            raise ValueError(f"Seq2DBackend needs a 2-D mesh, got axes {mesh.axis_names}")
        _check_seq_engine(engine)
        # mesh=None defers the dp x sp split to prepare(), which knows the
        # sequence count (parallel.mesh.auto_mesh2d).
        self.mesh = mesh
        self.block_size = block_size if block_size is not None else fb_sharded.DEFAULT_BLOCK
        self.pad_value = pad_value
        self.engine = engine
        self.lane_T = lane_T
        self.t_tile = t_tile
        # One-pass matrix arm for the onehot whole-seq route (same consult
        # as SeqBackend; the rows-chunked route is already 1-pass and
        # ignores it).
        self.one_pass = (
            tune.default_one_pass("em_seq") if one_pass is None
            else bool(one_pass)
        )

    @property
    def data_axis(self) -> str:
        return self.mesh.axis_names[0]

    @property
    def seq_axis(self) -> str:
        return self.mesh.axis_names[1]

    def prepare(self, chunked):
        """Pad rows (sequences) to dp multiples and columns to sp*block.

        A :class:`~cpgisland_tpu.utils.chunking.Bucketed` input (the
        host-memory-bounded layout pipeline.train_file builds) keeps its
        groups separate, and EACH group gets its own dp x sp mesh split
        sized to its row count — many-row scaffold groups run data-parallel,
        single-row chromosome groups run fully sequence-parallel.
        """
        if isinstance(chunked, chunking.LocalShard):
            raise ValueError(
                "Seq2DBackend does not support LocalShard input (SpmdBackend does)"
            )
        if isinstance(chunked, chunking.Bucketed):
            from cpgisland_tpu.parallel.mesh import auto_mesh2d

            self._group_meshes = []
            groups_c = []
            groups_l = []
            for rows, lens in zip(chunked.chunks, chunked.lengths):
                mesh_g = auto_mesh2d(rows.shape[0]) if self.mesh is None else self.mesh
                self._group_meshes.append(mesh_g)
                obs, lengths = fb_sharded.pad_batch2d(
                    rows, lens,
                    mesh_g.shape[mesh_g.axis_names[0]],
                    mesh_g.shape[mesh_g.axis_names[1]],
                    self.block_size, self.pad_value,
                )
                groups_c.append(obs)
                groups_l.append(lengths)
            return chunking.Bucketed(
                chunks=tuple(groups_c), lengths=tuple(groups_l),
                total=chunked.total,
            )
        if self.mesh is None:
            from cpgisland_tpu.parallel.mesh import auto_mesh2d

            self.mesh = auto_mesh2d(chunked.num_chunks)
        obs, lengths = fb_sharded.pad_batch2d(
            chunked.chunks,
            chunked.lengths,
            self.mesh.shape[self.data_axis],
            self.mesh.shape[self.seq_axis],
            self.block_size,
            self.pad_value,
        )
        if obs is chunked.chunks:
            return chunked
        return chunking.Chunked(chunks=obs, lengths=lengths, total=chunked.total)

    def _meshes_for(self, chunks: tuple) -> list:
        meshes = getattr(self, "_group_meshes", None)
        if meshes is None or len(meshes) != len(chunks):
            raise ValueError(
                "bucketed input: run prepare() + place() on THIS backend "
                "instance first (per-group meshes are assigned at prepare)"
            )
        return meshes

    def place(self, chunks, lengths):
        if isinstance(chunks, tuple):
            placed = [
                fb_sharded.place_batch2d(mesh_g, c, l)
                for mesh_g, c, l in zip(self._meshes_for(chunks), chunks, lengths)
            ]
            return tuple(p[0] for p in placed), tuple(p[1] for p in placed)
        return fb_sharded.place_batch2d(self.mesh, chunks, lengths)

    def _group_stats_fn(self, params, mesh, chunks) -> Callable:
        # Same routing policy as SeqBackend (_use_fused_seq): auto gates on
        # big-enough TPU shards; an explicit engine always wins.  Resolves
        # against concrete params/shapes and returns the (lru-cached,
        # stable) traceable per-group stats fn.
        sp = mesh.shape[mesh.axis_names[1]]
        _check_seq_shard(chunks.shape[1] // sp, "Seq2DBackend")
        if sp == 1 and chunks.shape[1] <= SMALL_RECORD_ROWS_MAX:
            # Whole records fit single kernel lanes: the chunked kernels are
            # already EXACT per record and skip the per-row scan of
            # sequence-parallel programs (fb_sharded.sharded_stats2d_rows_fn).
            # Engine routing/validation = LocalBackend's resolver (this IS a
            # chunked path — the whole-seq 1 Mi fused gate does not apply).
            eng = resolve_fb_engine(self.engine, params, "rescaled")
            obs.engine_decision(
                site="seq2d_backend", choice=f"rows-chunked:{eng}",
                requested=self.engine,
            )
            return fb_sharded.sharded_stats2d_rows_fn(
                mesh, eng,
                self.t_tile if self.t_tile is not None else fb_pallas.DEFAULT_T_TILE,
            )
        engine = (
            ("onehot" if _seq_onehot(self.engine, params) else "pallas")
            if _use_fused_seq(self.engine, params, chunks.shape[1] // sp)
            else "xla"
        )
        obs.engine_decision(
            site="seq2d_backend", choice=engine, requested=self.engine, sp=sp
        )
        # The XLA body ignores the kernel tile knobs — normalize them out of
        # the compile-cache key so differently-tuned backends share one
        # compiled program.  (Both fused engines consume them; r4 dropped
        # them for 'onehot', making the seq2d tile knobs untunable.)
        lane_T, t_tile = (
            (self.lane_T, self.t_tile)
            if engine in ("pallas", "onehot")
            else (None, None)
        )
        if engine in ("pallas", "onehot") and lane_T is None:
            # Resolve the tuned lane winner HOST-side (per-shard length is
            # static here) and pass it explicitly: consultation inside the
            # shard_map'd body would freeze the winner into the jit cache
            # (the R8 trace-time-consult bug class) — the body's own
            # fallback is the pure legacy heuristic only.
            lane_T = fb_pallas.pick_lane_T(
                chunks.shape[1] // sp, onehot=engine == "onehot",
                long_lanes=False,
            )
        return fb_sharded.sharded_stats2d_fn(
            mesh, self.block_size, engine, lane_T, t_tile, self.one_pass
        )

    def _group_stats(self, params, mesh, chunks, lengths):
        return self._group_stats_fn(params, mesh, chunks)(params, chunks, lengths)

    def __call__(self, params, chunks, lengths):
        if isinstance(chunks, tuple):
            total = None
            for mesh_g, c, l in zip(self._meshes_for(chunks), chunks, lengths):
                st = self._group_stats(params, mesh_g, c, l)
                total = st if total is None else total + st
            return total
        if self.mesh is None or getattr(chunks, "ndim", 0) != 2 or getattr(lengths, "ndim", 0) != 2:
            raise ValueError(
                "Seq2DBackend expects placed [N, T] sequences and [N, sp] shard "
                "lengths; run prepare() + place() first"
            )
        return self._group_stats(params, self.mesh, chunks, lengths)

    def fused_stats_fn(self, params, chunks, lengths):
        if isinstance(chunks, tuple):
            # Bucketed input: one composite fn summing the per-group stats.
            # Cached per (group shapes x resolved fns) on THIS instance so
            # repeated fit() calls hand the fused driver the same callable
            # (= the same compiled K-iteration program).
            meshes = self._meshes_for(chunks)
            fns = tuple(
                self._group_stats_fn(params, m, c)
                for m, c in zip(meshes, chunks)
            )
            key = (tuple(c.shape for c in chunks), fns)
            cache = getattr(self, "_fused_cache", None)
            if cache is None:
                cache = self._fused_cache = {}
            if key not in cache:

                def run(p, cs, ls):
                    total = None
                    for fn, c, l in zip(fns, cs, ls):
                        st = fn(p, c, l)
                        total = st if total is None else total + st
                    return total

                cache[key] = run
            return cache[key]
        if (
            self.mesh is None
            or getattr(chunks, "ndim", 0) != 2
            or getattr(lengths, "ndim", 0) != 2
        ):
            raise ValueError(
                "Seq2DBackend expects placed [N, T] sequences and [N, sp] shard "
                "lengths; run prepare() + place() first"
            )
        return self._group_stats_fn(params, self.mesh, chunks)

    def _rows_prep_meta(self, params, chunks):
        """(S, T, t_tile, onehot) when this (non-bucketed) input routes to
        the whole-record-per-lane chunked fast path with a kernel engine —
        the only seq2d route with a prepared form (the sequence-parallel
        bodies' collective threading preps inline; bucketed groups keep
        inline prep too)."""
        if (
            self.mesh is None
            or isinstance(chunks, (tuple, jax.core.Tracer))
            or getattr(chunks, "ndim", 0) != 2
        ):
            return None
        sp = self.mesh.shape[self.seq_axis]
        if not (sp == 1 and chunks.shape[1] <= SMALL_RECORD_ROWS_MAX):
            return None
        eng = resolve_fb_engine(self.engine, params, "rescaled")
        if eng not in ("pallas", "onehot"):
            return None
        tt = self.t_tile if self.t_tile is not None else fb_pallas.DEFAULT_T_TILE
        n_local = int(chunks.shape[0]) // self.mesh.shape[self.data_axis]
        return (
            params.n_symbols, n_local, int(chunks.shape[1]), tt,
            eng == "onehot",
        ), eng

    def prepare_streams(self, params, chunks, lengths):
        out = self._rows_prep_meta(params, chunks)
        if out is None:
            return None
        (S, N_local, T, tt, onehot), _eng = out
        from cpgisland_tpu.ops import prepared as prep_mod

        da, sa = self.mesh.axis_names
        fkey = (S, N_local, T, tt, onehot)
        cache = getattr(self, "_prep_fn_cache", None)
        if cache is None:
            cache = self._prep_fn_cache = {}
        if fkey not in cache:
            cache[fkey] = prep_mod.sharded_chunked_builder(
                self.mesh, da, (P(da, sa), P(da, sa)),
                S, N_local, T, tt, onehot, lengths_2d=True,
            )
        builder = cache[fkey]
        return prep_mod.cached_build(
            "chunked-seq2d", (chunks, lengths),
            fkey + (str(self.mesh),),
            lambda: builder(chunks, lengths),
        )

    def fused_stats_with_prep(self, params, chunks, lengths):
        out = self._rows_prep_meta(params, chunks)
        if out is None:
            return self.fused_stats_fn(params, chunks, lengths), None
        prep = self.prepare_streams(params, chunks, lengths)
        if prep is None:
            return self.fused_stats_fn(params, chunks, lengths), None
        meta, eng = out
        obs.engine_decision(
            site="seq2d_backend", choice=f"rows-chunked:{eng}",
            requested=self.engine,
        )
        return fb_sharded.sharded_stats2d_rows_fn(
            self.mesh, eng, meta[3], prep_meta=meta
        ), prep


class FamilyEStep:
    """Stacked multi-model chunked E-step: M members' statistics from ONE
    stacked launch set over a shared [N, T] batch.

    ROADMAP item 2's training lever: a model-family scan (several
    same-alphabet reduced members over one corpus — restarts, perturbed
    inits, alternative priors) previously paid M sequential E-steps per
    iteration; the stacked kernels (ops.fb_onehot) carry all M members'
    chains through ONE pass set, so the per-iteration fixed cost is ~one
    member's.  Per-member statistics are BIT-IDENTICAL to
    ``LocalBackend(engine='onehot')`` on the same placed batch (pinned in
    tests/test_multimodel.py).

    Domain: every member reduced-stats-eligible
    (family.reduced_stats_eligible — one-hot partition, pow2 alphabet)
    with a shared alphabet, inside the reduced state envelope.
    ``fuse_fb=False`` keeps the split (r4-shaped) chain structure per
    member — the A/B arm, same knob as LocalBackend.  ``stacked=False``
    runs M sequential single-model E-steps instead of the one stacked
    launch set (bit-identical per member — the pinned contract — just
    M passes' fixed cost): the multi-model A/B escape hatch.  Both
    ``None`` defaults consult the graftune winner table
    (``fused.em_family`` / ``stacked.em_family``) and fall back to the
    shipped True; explicit bools always win.
    """

    def __init__(self, t_tile: Optional[int] = None,
                 fuse_fb: Optional[bool] = None,
                 stacked: Optional[bool] = None):
        from cpgisland_tpu import tune

        self.t_tile = (
            t_tile if t_tile is not None else fb_pallas.DEFAULT_T_TILE
        )
        self.fuse_fb = (
            tune.default_fused("em_family") if fuse_fb is None
            else bool(fuse_fb)
        )
        self.stacked = (
            tune.default_stacked("em_family") if stacked is None
            else bool(stacked)
        )

    def validate(self, params_list) -> None:
        from cpgisland_tpu.family import partition as family_partition
        from cpgisland_tpu.ops import fb_onehot

        fb_onehot.check_stacked_members(params_list)
        for p in params_list:
            if not family_partition.reduced_stats_eligible(p):
                raise ValueError(
                    "FamilyEStep members must be reduced-stats-eligible "
                    "(one-hot emission-support partition, power-of-two "
                    "alphabet — family.reduced_stats_eligible)"
                )

    def place(self, chunks, lengths):
        return jnp.asarray(chunks), jnp.asarray(lengths)

    def prepare_streams(self, params_list, chunks, lengths):
        """ONE shared symbol-only prep for every member (the pair stream
        depends only on the symbols/alphabet, so members share it —
        identity-cached like the single-model layouts)."""
        if isinstance(chunks, jax.core.Tracer):
            return None
        from cpgisland_tpu.ops import prepared as prep_mod

        return prep_mod.for_chunked(
            params_list[0].n_symbols, jnp.asarray(chunks),
            jnp.asarray(lengths), t_tile=self.t_tile, onehot=True,
        )

    def __call__(self, params_list, chunks, lengths) -> tuple:
        params_list = tuple(params_list)
        self.validate(params_list)
        chunks, lengths = jnp.asarray(chunks), jnp.asarray(lengths)
        if not self.stacked:
            # The sequential A/B arm: M single-model reduced E-steps over
            # the same placed batch — per-member statistics BIT-IDENTICAL
            # to the stacked launch (the tests' pinned contract), at M
            # pass sets' fixed cost.
            obs.engine_decision(
                site="family_estep", choice="onehot.sequential",
                n_members=len(params_list),
            )
            return tuple(
                fb_pallas.batch_stats_pallas(
                    p, chunks, lengths, t_tile=self.t_tile, onehot=True,
                    fused=self.fuse_fb,
                )
                for p in params_list
            )
        prep = self.prepare_streams(params_list, chunks, lengths)
        obs.engine_decision(
            site="family_estep", choice="onehot.stacked",
            n_members=len(params_list),
        )
        return fb_pallas.batch_stats_pallas_stacked(
            params_list, chunks, lengths, t_tile=self.t_tile,
            prepared=prep, fused=self.fuse_fb,
        )


def fit_family(
    params_list,
    chunks,
    lengths,
    *,
    n_iter: int = 10,
    estep: Optional[FamilyEStep] = None,
):
    """Train M family members in LOCKSTEP over one chunk batch: each
    iteration runs ONE stacked E-step (all members' chains in one launch
    set) and M model-sized M-steps.  Per-member trajectories are
    bit-identical to M independent ``baum_welch.fit`` host-loop runs with
    the chunked onehot backend on the same placed batch.  Returns
    (trained params list, logliks [n_iter, M])."""
    from cpgisland_tpu.train.baum_welch import mstep

    estep = estep if estep is not None else FamilyEStep()
    params_list = [p.astype(jnp.float32) for p in params_list]
    chunks, lengths = estep.place(chunks, lengths)
    hist_dev = []
    for _ in range(int(n_iter)):
        stats = estep(tuple(params_list), chunks, lengths)
        # Device scalars only — NO per-iteration host sync (each blocking
        # fetch is a ~50-100 ms relay round trip, more than the fixed cost
        # the stacked E-step saves); one fetch after the loop.
        hist_dev.append(jnp.stack([st.loglik for st in stats]))
        params_list = [
            mstep(p, st) for p, st in zip(params_list, stats)
        ]
    hist = obs.note_fetch(
        np.asarray(jnp.stack(hist_dev)).astype(np.float64)
    ) if hist_dev else np.zeros((0, len(params_list)), np.float64)
    return params_list, hist


def get_backend(
    name: str = "local",
    *,
    mode: str = "rescaled",
    mesh: Optional[Mesh] = None,
    engine: str = "auto",
) -> EStepBackend:
    """Backend factory — the runtime flag the north star asks for."""
    if name == "local":
        return LocalBackend(mode=mode, engine=engine)
    if name == "spmd":
        return SpmdBackend(mesh=mesh, mode=mode, engine=engine)
    if name in ("seq", "seq2d"):
        # The whole-sequence backends have fixed rescaled numerics — reject
        # the knob they would otherwise silently ignore; engine passes
        # through (auto / xla / pallas, validated by the backend).
        if mode != "rescaled":
            raise ValueError(f"backend {name!r} implements rescaled numerics only")
        if name == "seq":
            return SeqBackend(mesh=mesh, engine=engine)
        return Seq2DBackend(mesh=mesh, engine=engine)
    raise ValueError(
        f"unknown backend {name!r} (expected 'local', 'spmd', 'seq', or 'seq2d')"
    )
