"""Worker loop: the broker's single flush-executing consumer thread.

The overlap story (the daemon's RecordPrefetcher pattern): the TRANSPORT
thread parses and encodes incoming requests and runs admission — pure host
work — while THIS thread executes flush n's device compute.  The broker's
queue (bounded by the per-tenant admission caps) is the hand-off buffer,
so host-side prep of flush n+1 naturally overlaps device compute of flush
n without any extra machinery; stopping the loop drains nothing by itself
(close the broker and call drain for an orderly shutdown).

The loop's cadence is the broker's bounded-latency flush policy: it wakes
when the symbol budget fills (submit notifies) or when the oldest queued
request's deadline expires, whichever first.  A deadline firing on an
empty queue is a no-op — the loop just re-arms.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from cpgisland_tpu.serve.broker import RequestBroker, ServeResult

log = logging.getLogger(__name__)

__all__ = ["ServeLoop"]


class ServeLoop:
    """Daemon thread draining ``broker``; each result is handed to
    ``on_result`` (the transport's writer — called on THIS thread, so the
    writer must be thread-safe with respect to its own output stream)."""

    # Idle re-arm bound: with an empty queue there is no deadline to wait
    # for, so the loop parks on the condition variable up to this long
    # (submit notifies it awake immediately — this only bounds staleness
    # of the closed-flag check).
    IDLE_WAIT_S = 0.5

    def __init__(
        self,
        broker: RequestBroker,
        on_result: Callable[[ServeResult], None],
    ) -> None:
        self.broker = broker
        self.on_result = on_result
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="cpgisland-serve", daemon=True
        )

    def start(self) -> "ServeLoop":
        self._thread.start()
        return self

    def stop(self, join: bool = True) -> None:
        self._stop.set()
        # Wake the loop if it is parked on the broker's condition.
        with self.broker._cv:
            self.broker._cv.notify_all()
        if join and self._thread.is_alive():
            self._thread.join()

    def _run(self) -> None:
        broker = self.broker
        while not self._stop.is_set() and not broker.closed:
            # The shared wait cadence (broker.poll_flush): budget fill or
            # oldest-request deadline, whichever first — the same step the
            # fleet's device workers run.
            if not broker.poll_flush(self.IDLE_WAIT_S):
                continue
            try:
                for result in broker.flush_once():
                    self.on_result(result)
            except Exception:
                # A flush-level failure (broker internals, not a request
                # unit — those are caught per request) must not kill the
                # daemon thread silently.
                log.exception("serve loop: flush failed")
