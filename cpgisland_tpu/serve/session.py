"""Session/engine layer: the long-lived state every serving entry drives.

Before this module, each ``pipeline.decode_file`` / ``posterior_file`` call
rebuilt the full serving context from scratch: a fresh DispatchSupervisor,
a fresh island cap box (re-learning overflow sizes every run), engine
resolution against the process-global breaker, and no handle tying the
prepared-stream cache to an owner.  A batch CLI run tolerates that; a
daemon serving many requests must not — and two copies of the context
logic (pipeline + server) would drift.  :class:`Session` is the ONE place
that state lives:

- the model params (placed implicitly by jit on first use — jax caches
  executables per shape, so a session's repeat geometries are warm);
- the requested engine strings and their resolution (walked down the
  breaker's parity-twin ladder at routing time, so resolution stays
  current with fault state);
- a per-session :class:`~cpgisland_tpu.resilience.policy.DispatchSupervisor`
  and optionally a PRIVATE :class:`~cpgisland_tpu.resilience.breaker.
  EngineBreaker` (``private_breaker=True``): the daemon gives each session
  its own, so one tenant's kernel-shaped faults demote engines for that
  session only, not the whole process;
- the :class:`~cpgisland_tpu.ops.prepared.PreparedStreams` handle (all
  span/prep cache lookups book against it; ``close()`` releases the prep
  trees promptly);
- the learned island cap (one overflow teaches every later flush).

``pipeline.decode_file`` / ``posterior_file`` accept ``session=`` and
construct an ephemeral one when not given — byte-identical behavior to the
pre-session code.  The broker (``serve/broker.py``) and bench's serve
phase construct explicit long-lived ones.
"""

from __future__ import annotations

import functools
import threading
from typing import Optional

from cpgisland_tpu import resilience
from cpgisland_tpu.models.hmm import HmmParams

__all__ = ["Session", "ModelRegistry"]


class Session:
    """Long-lived serving context for ONE model (see module docstring).

    Thread-safety: engine resolution and the cap box are guarded by a lock
    (the worker loop and a transport thread may both touch the session);
    the supervisor itself is single-consumer like the pipeline's — only
    the flush-executing thread dispatches.
    """

    def __init__(
        self,
        params: HmmParams,
        *,
        engine: str = "auto",
        island_engine: str = "auto",
        island_cap: Optional[int] = None,
        integrity_check: bool = False,
        name: str = "session",
        private_breaker: bool = False,
        breaker=None,
        retry_policy=None,
        monitor=None,
        now_fn=None,
    ) -> None:
        self.params = params
        self.engine = engine
        self.island_engine = island_engine
        self.name = name
        if breaker is None:
            breaker = (
                resilience.EngineBreaker(now_fn=now_fn) if private_breaker
                else resilience.get_breaker()
            )
        self.breaker = breaker
        # monitor: the fleet's per-device health listener, threaded into
        # every supervised dispatch of this session (see policy.
        # DispatchSupervisor); now_fn: deterministic clock for the private
        # breaker's cooldown (and remembered for for_device clones).
        self.supervisor = resilience.DispatchSupervisor(
            retry_policy,
            name=name,
            sentinel=(
                resilience.IntegritySentinel() if integrity_check else None
            ),
            breaker=breaker,
            monitor=monitor,
        )
        self._integrity_check = bool(integrity_check)
        self._retry_policy = retry_policy
        self._now_fn = now_fn
        self._island_cap = island_cap
        self._cap_box: Optional[list] = None
        self._lock = threading.Lock()
        from cpgisland_tpu.ops.prepared import PreparedStreams

        self.streams = PreparedStreams(params.n_symbols)

    def for_device(self, label: str, *, monitor=None, now_fn=None) -> "Session":
        """A per-device clone for the fleet (``serve/fleet.py``): the SAME
        model and routing config, but its OWN private breaker, supervisor
        (the single-dispatcher rule holds per device worker), prepared-
        stream handle, and island cap box — one device's faults demote
        engines and grow caps for that device only.  ``monitor`` is the
        device's health state machine; the clone's supervisor feeds it."""
        return Session(
            self.params,
            engine=self.engine,
            island_engine=self.island_engine,
            island_cap=self._island_cap,
            integrity_check=self._integrity_check,
            name=f"{self.name}@{label}",
            private_breaker=True,
            retry_policy=self._retry_policy,
            monitor=monitor,
            now_fn=now_fn if now_fn is not None else self._now_fn,
        )

    def for_host(self, label: str, *, monitor=None, now_fn=None) -> "Session":
        """A per-HOST clone for the routing tier (``serve/router.py``):
        identical isolation contract one fault-domain level up — each
        routed host gets its own breaker/supervisor/prep state, and the
        ``@label`` session-name suffix is what graftfault host plans
        target (``match="@host0"`` matches this clone's supervised
        dispatch tags).  ``monitor`` is the host's health machine."""
        return self.for_device(label, monitor=monitor, now_fn=now_fn)

    # -- pipeline integration -----------------------------------------------

    def check_call(
        self,
        params: HmmParams,
        *,
        engine: str = "auto",
        island_engine: str = "auto",
        island_cap: Optional[int] = None,
        integrity_check: bool = False,
    ) -> None:
        """Gate a pipeline call made WITH an explicit session: the session
        owns the model and the routing config, so per-call overrides that
        silently disagreed with it would serve with the wrong state.  The
        pipeline entries call this before using the session."""
        if params is not None and params is not self.params:
            raise ValueError(
                "decode/posterior called with a session bound to different "
                "params — one Session serves ONE model; build another "
                "Session (or pass this session's params)"
            )
        for what, got, default in (
            ("engine", engine, "auto"),
            ("island_engine", island_engine, "auto"),
            ("island_cap", island_cap, None),
            ("integrity_check", integrity_check, False),
        ):
            if got != default:
                raise ValueError(
                    f"{what}={got!r} was passed alongside session=; routing "
                    "config lives ON the session — construct the Session "
                    f"with {what}={got!r} instead"
                )

    # -- engine resolution (breaker-aware, re-walked per flush) -------------

    def decode_engine(self) -> str:
        """The concrete decode engine for the next unit of work — resolved
        now, against THIS session's breaker, so a mid-run trip demotes the
        next flush without touching other sessions."""
        from cpgisland_tpu.parallel.decode import resolve_engine

        with self._lock:
            return resolve_engine(self.engine, self.params, breaker=self.breaker)

    def fb_engine(self) -> str:
        """decode_engine's forward-backward twin."""
        from cpgisland_tpu.parallel.posterior import resolve_fb_engine

        with self._lock:
            return resolve_fb_engine(
                self.engine, self.params, breaker=self.breaker
            )

    def batch_decode_fn(self, eng: str):
        """The batched-decode callable for a resolved engine — THE one copy
        of decode_file's engine -> batch lowering choice (flat reset-step
        stream for onehot, the dense batch entries otherwise)."""
        from cpgisland_tpu.ops.viterbi_pallas import viterbi_pallas_batch
        from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel_batch

        if eng == "pallas":
            return viterbi_pallas_batch
        if eng == "onehot":
            # Batches run the FLAT reset-step decoder (one kernel grid for
            # all records, viterbi_onehot.decode_batch_flat); zero-length /
            # pad-FIRST lanes are demoted by the host entry points before
            # they reach it.
            return functools.partial(viterbi_parallel_batch, engine="onehot")
        return viterbi_parallel_batch

    def island_policy(self, *, device_eligible: bool, ineligible_msg: str):
        """(use_device_islands, cap_box) via the shared pipeline policy,
        with this session's breaker and its PERSISTENT cap box — an island
        cap grown by one request's overflow is learned for every later
        flush of the session, not just one file run."""
        from cpgisland_tpu import pipeline

        with self._lock:
            start_cap = (
                self._cap_box[0] if self._cap_box is not None
                else self._island_cap
            )
            use_device, cap_box = pipeline._resolve_island_engine(
                self.island_engine,
                device_eligible=device_eligible,
                ineligible_msg=ineligible_msg,
                island_cap=start_cap,
                breaker=self.breaker,
            )
            if self._cap_box is None:
                self._cap_box = cap_box
            return use_device, self._cap_box

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Release session-held prepared-stream cache entries promptly
        (the daemon's drop-a-tenant hook; see ops.prepared.evict)."""
        self.streams.clear_session()


class ModelRegistry:
    """Named-model registry: one daemon serving a model FAMILY.

    Maps a model name to its (family Member metadata, Session) pair.  The
    DEFAULT session serves requests that carry no ``model=`` field —
    byte-identical single-model behavior; every registered member gets its
    OWN Session with a PRIVATE breaker, so one model's kernel-shaped
    faults demote engines for that model only (the same isolation rule as
    per-tenant sessions, applied per model).  Duplicate names are rejected
    at registration; unknown names are rejected at broker ADMISSION
    (``RequestBroker.submit`` looks sessions up here).

    Thread contract: ``register`` and the lookups are lock-guarded (a
    transport thread admits while the worker flushes); Sessions keep
    their own locking.
    """

    def __init__(self, default: Session) -> None:
        self._lock = threading.Lock()
        self._default = default
        self._entries: dict = {}  # name -> (Member | None, Session)
        # ONE PreparedStreams handle per alphabet for the STACKED compare
        # dispatch: members of one stream share a symbol-only prep (the
        # pair stream reads nothing from any member's params), so the
        # artifact belongs to the registry — one handle across members —
        # not to any single member session.  close() releases them.
        self._compare_streams: dict = {}  # n_symbols -> PreparedStreams

    @property
    def default(self) -> Session:
        return self._default

    def register(
        self,
        member,
        *,
        engine: str = "auto",
        island_engine: str = "auto",
        session: "Optional[Session]" = None,
        **session_kw,
    ) -> Session:
        """Register one family member (``family.Member``).  Builds a
        private-breaker Session for it unless ``session`` is given.
        Raises ValueError on a duplicate name."""
        name = member.name
        if session is None:
            session = Session(
                member.params, engine=engine, island_engine=island_engine,
                name=f"model:{name}", private_breaker=True, **session_kw,
            )
        with self._lock:
            if name in self._entries:
                raise ValueError(
                    f"duplicate model name {name!r} in the registry"
                )
            self._entries[name] = (member, session)
        return session

    def session(self, name: str = "") -> Session:
        """The session serving ``name`` ('' = the default model).
        KeyError on unknown names — admission surfaces it as a reject."""
        if not name:
            return self._default
        with self._lock:
            try:
                return self._entries[name][1]
            except KeyError:
                raise KeyError(f"unknown model {name!r}") from None

    def member(self, name: str):
        """The family Member registered under ``name`` (KeyError when
        unknown — the default session has no member metadata unless it
        was also registered by name)."""
        with self._lock:
            try:
                m = self._entries[name][0]
            except KeyError:
                raise KeyError(f"unknown model {name!r}") from None
        if m is None:
            raise KeyError(f"model {name!r} has no member metadata")
        return m

    def names(self) -> tuple:
        with self._lock:
            return tuple(self._entries)

    def entries(self) -> tuple:
        """(name, member, session) snapshot — the fleet's clone source
        (``serve/fleet.py`` builds one registry per device from it)."""
        with self._lock:
            return tuple(
                (name, m, s) for name, (m, s) in self._entries.items()
            )

    def sessions_for(self, names) -> dict:
        """name -> Session map for a compare request's member set."""
        return {n: self.session(n) for n in names}

    def compare_streams(self, n_symbols: int):
        """The registry's shared PreparedStreams handle for ``n_symbols``
        (created on first use) — family.compare_record's
        ``streams_handle`` provider: one handle per stream alphabet,
        shared across every member of a stacked group."""
        from cpgisland_tpu.ops.prepared import PreparedStreams

        with self._lock:
            handle = self._compare_streams.get(int(n_symbols))
            if handle is None:
                handle = PreparedStreams(int(n_symbols))
                self._compare_streams[int(n_symbols)] = handle
            return handle

    def close(self) -> None:
        """Release every registered session's prepared-stream entries and
        the registry-owned compare handles (the default session belongs to
        the caller)."""
        with self._lock:
            entries = list(self._entries.values())
            shared = list(self._compare_streams.values())
            self._compare_streams.clear()
        for handle in shared:
            handle.clear_session()
        for _, sess in entries:
            sess.close()
