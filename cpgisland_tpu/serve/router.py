"""Pod-scale serve: a routing tier over N per-host brokers.

PR 15 built the fault domain INSIDE one host (per-device health, intact
flush requeue, the two-phase admission journal); ROADMAP item 2 left the
level above as residue — a dead HOST still stranded every request its
broker had admitted, and ``Backpressure.retry_after_s`` was a wire hint
nothing enforced.  This module is the reference's Hadoop story rebuilt
one more level up: where MAHOUT-627 re-executed a failed node's tasks
from the JobTracker's ledger, the router re-executes a dead host's
journaled admissions on a survivor — and because the flat reset-step
decode stream is geometry-independent (CLAUDE.md r5), the failed-over
work runs bit-identically on any surviving host's device count with
ZERO new kernels.

Topology: one :class:`RequestRouter` fronts N :class:`RouterHost`\\ s.
Each host is one existing broker (plus optionally its
:class:`~cpgisland_tpu.serve.fleet.DevicePool`); in-process hosts get a
:class:`_HostWorker` flush thread, so the router composes with the
transport exactly like a broker+pool pair:
``serve_socket(path, router, pool=router)``.

Three contracts:

- **Host health** (:class:`HostHealth`): the DeviceHealth state machine
  (healthy -> suspect -> quarantined -> half-open probe -> restored)
  mirrored at host granularity, fed by the signals that exist one level
  up: connection faults (``record_fault``), journal-replay divergence
  (``record_divergence`` — an adopted admit whose recomputed identity
  key no longer matches its journal line), and sustained backpressure
  (``record_backpressure``).  Plus one terminal state devices don't
  have: DEAD (``mark_dead`` — a host process is gone; only an operator
  builds a new RouterHost for its replacement).
- **Elastic load shedding**: admission routes to the least-loaded
  serveable host (``queue_depth()`` ordering, sticky per request id so
  duplicates/replays arbitrate on one host).  A host that refuses with
  :class:`~cpgisland_tpu.serve.broker.Backpressure` takes a strike and
  the next host is tried; when ALL refuse, the router raises
  Backpressure whose ``retry_after_s`` is the MINIMUM of the hosts'
  measured-flush-wall hints — a machine-readable shed the client obeys
  (``tools/serve_client.py``).  A quarantined host keeps DRAINING its
  queue (its worker has no health gate — quarantine gates routing, not
  completion), which is the drain-via-quarantine-hooks semantics.
- **Cross-host flush failover**: when a host dies mid-flush, its
  write-ahead journal already holds an admit line (with the re-
  executable payload) for every accepted-but-incomplete request.  The
  router scans that journal from DISK (:meth:`RunManifest.
  scan_incomplete` — the live object's stubs are payload-free by
  design), re-routes each admission to a survivor, and when the result
  lands appends the completion line to the DEAD host's journal — so the
  dead host's restart finds zero incomplete admits (the superseding
  rule) and nothing ever re-executes twice.

Thread contract (graftsync Layer 4): any thread submits; each in-process
host has ONE worker thread (the broker's single-consumer rule holds per
host); host death spawns one tracked failover thread (joined in
``stop``).  ``RequestRouter._lock`` guards the owner/adopted maps and
counters and is a LEAF: it is never held across broker, manifest,
health, or faultplan calls.  Each ``HostHealth._lock`` is a leaf except
for obs/scope emission (the DeviceHealth shape).  The dead-journal
completion write in ``_finish`` happens OUTSIDE every router lock (the
manifest lock stays a global leaf).  ``hosts``/``_host_by_label`` are
immutable after construction — read without a lock.
"""

from __future__ import annotations

import base64
import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

import numpy as np

from cpgisland_tpu import obs
from cpgisland_tpu.obs import ledger as ledger_mod
from cpgisland_tpu.obs import scope as scope_mod
from cpgisland_tpu.resilience import faultplan
from cpgisland_tpu.resilience.manifest import RunManifest
from cpgisland_tpu.serve.broker import Backpressure, RequestBroker
from cpgisland_tpu.serve.fleet import (
    HEALTHY,
    PROBING,
    QUARANTINED,
    SUSPECT,
)

log = logging.getLogger(__name__)

__all__ = ["HostHealth", "RequestRouter", "RouterConfig", "RouterHost",
           "DEAD"]

DEAD = "dead"


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Health/elasticity/failover policy for one :class:`RequestRouter`.

    ``fault_threshold``: consecutive connection faults that quarantine a
    host.  ``divergence_threshold``: journal-replay divergences that
    quarantine (default 1 — a journal whose lines stop matching their
    recomputed identity keys is corruption evidence, not a transient).
    ``backpressure_threshold``: consecutive admission refusals that
    quarantine (routing then drains the host via the quarantine hooks
    until its cooldown probe).  ``cooldown_s``/``now_fn``: the half-open
    probe clock, deterministic in tests.  ``idle_wait_s``: the host
    worker's poll cadence.  ``failover_attempts``/``failover_retry_s``:
    the bounded resubmission loop for a dead host's adopted admissions —
    past the budget an admission is left for the dead host's own restart
    re-execution (zero drops either way; the budget only bounds how long
    the failover thread shops it around a saturated pod).
    """

    fault_threshold: int = 3
    divergence_threshold: int = 1
    backpressure_threshold: int = 3
    cooldown_s: float = 30.0
    idle_wait_s: float = 0.05
    failover_attempts: int = 40
    failover_retry_s: float = 0.05
    now_fn: Callable[[], float] = time.monotonic


class HostHealth:
    """Per-host health state machine — :class:`~cpgisland_tpu.serve.
    fleet.DeviceHealth` mirrored one fault-domain level up, plus the
    terminal DEAD state.  All state is guarded by ``_lock`` (a leaf
    except for obs/scope emission, the DeviceHealth shape).  Unlike a
    device's, ``can_serve`` is consulted by ANY submitting thread, so
    the half-open admission is best-effort: a few concurrent submits may
    all land on a probing host — each is an independent success/fault
    sample, which only speeds the verdict."""

    def __init__(
        self,
        label: str,
        *,
        fault_threshold: int = 3,
        divergence_threshold: int = 1,
        backpressure_threshold: int = 3,
        cooldown_s: float = 30.0,
        now_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        self.label = label
        self.fault_threshold = int(fault_threshold)
        self.divergence_threshold = int(divergence_threshold)
        self.backpressure_threshold = int(backpressure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.now_fn = now_fn
        self._lock = threading.Lock()
        self._state = HEALTHY
        self._consecutive_faults = 0
        self._divergences = 0
        self._backpressure_strikes = 0
        self._quarantined_at: Optional[float] = None
        self.quarantines = 0
        self.restores = 0
        self.dead_reason: Optional[str] = None

    # -- signals --------------------------------------------------------------

    def record_fault(self, error: Optional[BaseException] = None) -> None:
        """A connection-shaped failure reaching this host (submit raised
        OSError, a flush failed at the transport boundary)."""
        with self._lock:
            if self._state == DEAD:
                return
            self._consecutive_faults += 1
            if self._state == PROBING:
                self._quarantine_locked("probe_failed", error)
            elif self._state == QUARANTINED:
                pass  # already out of rotation; nothing escalates further
            elif self._consecutive_faults >= self.fault_threshold:
                self._quarantine_locked("faults", error)
            else:
                self._state = SUSPECT

    def record_divergence(self, detail: str = "") -> None:
        """An adopted journal entry whose recomputed identity key no
        longer matches its admit line — replay-divergence evidence."""
        with self._lock:
            if self._state in (DEAD, QUARANTINED):
                self._divergences += 1
                return
            self._divergences += 1
            if self._divergences >= self.divergence_threshold:
                self._quarantine_locked(
                    "journal_divergence",
                    RuntimeError(detail) if detail else None,
                )
            else:
                self._state = SUSPECT

    def record_backpressure(self) -> None:
        """This host refused an admission (queue caps).  Consecutive
        refusals quarantine it out of the routing rotation — its worker
        keeps draining (quarantine gates routing, not completion), and
        the cooldown probe readmits it once a submit succeeds."""
        with self._lock:
            if self._state in (DEAD, QUARANTINED):
                return
            if self._state == PROBING:
                # A probe submit that bounced is not a recovery.
                self._quarantine_locked("backpressure", None)
                return
            self._backpressure_strikes += 1
            if self._backpressure_strikes >= self.backpressure_threshold:
                self._quarantine_locked("backpressure", None)
            else:
                self._state = SUSPECT

    def record_success(self) -> None:
        """A submit this host accepted — the connection-level healthy
        signal.  Every strike family is consecutive-evidence (the
        DeviceHealth contract): one accepted admission clears them."""
        with self._lock:
            if self._state in (DEAD, QUARANTINED):
                return
            self._consecutive_faults = 0
            self._backpressure_strikes = 0
            self._divergences = 0
            if self._state == PROBING:
                self._state = HEALTHY
                self._quarantined_at = None
                self.restores += 1
                obs.event(
                    "host_restored", host=self.label,
                    quarantines=self.quarantines,
                )
                scope_mod.record("host_restored", host=self.label,
                                 quarantines=self.quarantines)
                log.info(
                    "router: host %s restored (half-open probe admission "
                    "accepted)", self.label,
                )
            elif self._state == SUSPECT:
                self._state = HEALTHY

    def mark_dead(self, reason: str = "") -> None:
        """Terminal: the host process is gone.  Idempotent; only a new
        RouterHost (operator action) replaces a dead host."""
        with self._lock:
            if self._state == DEAD:
                return
            self._state = DEAD
            self.dead_reason = str(reason)[:200] or None
            obs.event("host_died", host=self.label, reason=self.dead_reason)
            scope_mod.record("host_died", host=self.label,
                             reason=self.dead_reason)
            log.warning(
                "router: host %s DEAD (%s); failing its journaled "
                "admissions over to the survivors", self.label,
                self.dead_reason,
            )

    # -- router-side gating ---------------------------------------------------

    def can_serve(self) -> bool:
        """May the router route a fresh admission here now?  DEAD never;
        after the cooldown a quarantined host flips to PROBING and the
        next submit is its probe."""
        with self._lock:
            if self._state == DEAD:
                return False
            if self._state in (HEALTHY, SUSPECT, PROBING):
                return True
            if (
                self._quarantined_at is not None
                and self.now_fn() - self._quarantined_at >= self.cooldown_s
            ):
                self._state = PROBING
                log.info(
                    "router: host %s cooldown elapsed; admitting a "
                    "half-open probe submission", self.label,
                )
                return True
            return False

    def force_quarantine(self, reason: str = "operator") -> None:
        """Pull a host out of the routing rotation directly (ops drain
        hook; its worker keeps draining the already-admitted queue)."""
        with self._lock:
            if self._state not in (QUARANTINED, DEAD):
                self._quarantine_locked(reason, None)

    def _quarantine_locked(self, reason: str, error) -> None:
        # _locked suffix: callers hold self._lock (the graftsync convention).
        self._state = QUARANTINED
        self._quarantined_at = self.now_fn()
        self.quarantines += 1
        faults = self._consecutive_faults
        self._consecutive_faults = 0
        self._backpressure_strikes = 0
        obs.event(
            "host_quarantined",
            host=self.label,
            reason=reason,
            consecutive_faults=faults,
            cooldown_s=self.cooldown_s,
            error=(f"{type(error).__name__}: {error}"[:200] if error else None),
        )
        scope_mod.record(
            "host_quarantined", host=self.label, reason=reason,
            consecutive_faults=faults, cooldown_s=self.cooldown_s,
        )
        log.warning(
            "router: host %s QUARANTINED (%s) for %.0f s; routing around "
            "it while its worker drains, a half-open probe follows the "
            "cooldown", self.label, reason, self.cooldown_s,
        )

    def eta_s(self) -> float:
        """Seconds until this host could plausibly serve again: 0 while
        serveable, the remaining cooldown while quarantined, +inf when
        dead — the all-hosts-down retry-after hint's input."""
        with self._lock:
            if self._state == DEAD:
                return float("inf")
            if self._state != QUARANTINED or self._quarantined_at is None:
                return 0.0
            return max(
                0.0,
                self.cooldown_s - (self.now_fn() - self._quarantined_at),
            )

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_faults": self._consecutive_faults,
                "divergences": self._divergences,
                "backpressure_strikes": self._backpressure_strikes,
                "quarantines": self.quarantines,
                "restores": self.restores,
                "dead_reason": self.dead_reason,
            }


class RouterHost:
    """One routed host: a broker (+ optional DevicePool) under a label.

    Construction stamps ``host_label`` on the broker (its flush.enter
    fault tags gain the ``@label`` suffix — what host-granularity chaos
    plans match) and the pool (per-host ledger attribution).  Hosts with
    a pool run the pool's own workers; hosts without one get a
    :class:`_HostWorker` when the router starts.  Host-death
    auto-detection (worker thread killed -> failover) is the
    _HostWorker path; pool-backed hosts fail over via
    :meth:`RequestRouter.fail_host` (the pool's per-device failover
    already absorbs intra-host deaths)."""

    def __init__(self, label: str, broker: RequestBroker, *,
                 pool=None, health: Optional[HostHealth] = None) -> None:
        self.label = str(label)
        self.broker = broker
        self.pool = pool
        self.health = health  # None -> the router builds one from its config
        self.worker: Optional[_HostWorker] = None
        broker.host_label = self.label
        if pool is not None:
            pool.host_label = self.label


class _HostWorker:
    """One in-process host's flush loop.  Deliberately WITHOUT a
    quarantine gate: quarantine sheds new admissions at routing time
    while this loop keeps draining what was already admitted (the
    drain-via-quarantine contract).  DEAD is different — it means the
    host process is gone, so the loop exits at the next boundary (the
    failover joins it before scanning the journal).  A SimulatedKill
    (or any other unhandled death) escapes through ``_run_guarded``,
    which marks the host dead and hands its journal to the router's
    failover."""

    def __init__(self, router: "RequestRouter", host: RouterHost) -> None:
        self.router = router
        self.host = host
        self.flushes = 0  # this host's finished flushes (stats; own thread)
        self._thread = threading.Thread(
            target=self._run_guarded,
            name=f"cpgisland-router-{host.label}", daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _run_guarded(self) -> None:
        # Unhandled worker death IS host death at this tier: persist the
        # flight recorder, mark the host dead, fail its journal over to a
        # survivor, then re-raise (daemon thread; nothing else may run
        # here — SIGKILL semantics).
        try:
            self._run()
        except BaseException as e:
            scope_mod.on_worker_death(self.host.label, e)
            self.router._on_host_death(self.host, e)
            raise

    # graftcheck: hot-path
    def _run(self) -> None:
        router = self.router
        host = self.host
        broker = host.broker
        cfg = router.config
        while (
            not router._stop.is_set()
            and not broker.closed
            and host.health.state() != DEAD
        ):
            if not broker.poll_flush(cfg.idle_wait_s):
                continue
            # graftfault host kill point: host-granularity SIGKILL before
            # the flush is taken (the journal holds admits only).
            faultplan.check("host.flush", tag=host.label)
            with ledger_mod.host_scope(host.label):
                for r in broker.flush_once():
                    router._deliver(host, r)
            self.flushes += 1
        log.debug("router: host worker %s exiting", host.label)


class RequestRouter:
    """See module docstring.  Duck-types as BOTH the broker and the pool
    of the transport layer's contract
    (``serve_socket(path, router, pool=router)``): any thread calls
    :meth:`submit`/:meth:`backpressure`/:meth:`stats`; :meth:`start`
    spins the per-host workers and :meth:`stop` joins every thread it
    started (workers + failover threads)."""

    def __init__(self, hosts, config: Optional[RouterConfig] = None) -> None:
        if not hosts:
            raise ValueError("RequestRouter needs at least one host")
        self.config = config if config is not None else RouterConfig()
        cfg = self.config
        self.hosts: list = list(hosts)
        for h in self.hosts:
            if h.health is None:
                h.health = HostHealth(
                    h.label,
                    fault_threshold=cfg.fault_threshold,
                    divergence_threshold=cfg.divergence_threshold,
                    backpressure_threshold=cfg.backpressure_threshold,
                    cooldown_s=cfg.cooldown_s,
                    now_fn=cfg.now_fn,
                )
        labels = [h.label for h in self.hosts]
        if len(set(labels)) != len(labels):
            raise ValueError(f"duplicate host labels: {labels}")
        # Immutable after construction (read lock-free everywhere).
        self._host_by_label = {h.label: h for h in self.hosts}
        self._lock = threading.Lock()
        # request id -> owning host label, while queued/executing there
        # (sticky routing: duplicates and replays arbitrate on ONE host).
        self._owner: dict[int, str] = {}
        # request id -> (dead RouterHost, identity key) for admissions
        # adopted off a dead host's journal: the completion is appended
        # to the DEAD host's journal when the survivor's result lands.
        self._adopted: dict[int, tuple] = {}
        self._failover_threads: list = []
        self._closed = False
        self._stop = threading.Event()
        self.on_result: Optional[Callable] = None
        self.failovers = 0  # dead hosts failed over (guarded by _lock)
        self.failed_over_requests = 0  # admissions adopted (guarded)

    # -- admission (any thread) ----------------------------------------------

    def submit(
        self,
        *,
        request_id: int,
        tenant: str,
        kind: str,
        symbols: np.ndarray,
        name: str = "",
        model: str = "",
        models=None,
    ) -> None:
        """Route one admission (the broker ``submit`` contract: raises
        :class:`Backpressure` when every serveable host refuses — with
        the minimum measured-wall retry hint — RuntimeError once closed,
        ValueError on malformed/duplicate requests, surfaced from the
        arbitrating host)."""
        self._route(
            request_id=int(request_id), tenant=str(tenant), kind=str(kind),
            symbols=symbols, name=name, model=str(model or ""), models=models,
        )

    # graftcheck: hot-path
    def _route(self, *, request_id: int, tenant: str, kind: str, symbols,
               name: str, model: str, models=None, exclude=(),
               failover: bool = False) -> None:
        rid = int(request_id)
        with self._lock:
            if self._closed:
                raise RuntimeError("router is closed")
            owner = self._owner.get(rid)
        symbols = np.ascontiguousarray(symbols, dtype=np.uint8)
        # The broker's manifest identity key, recomputed here for replay
        # affinity (same format string as RequestBroker._manifest_key).
        key = f"{kind}:{tenant}:{len(model)}:{model}:{name}"
        targets = None
        if owner is not None:
            h = self._host_by_label.get(owner)
            if h is not None and h.health.state() != DEAD:
                # Sticky: the id is queued/executing there — re-routing it
                # would put two live copies in flight.
                targets = [h]
        if targets is None:
            # Replay affinity: a host whose journal completed this exact
            # request serves it with zero device work.
            for h in self.hosts:
                if h in exclude or h.health.state() == DEAD:
                    continue
                m = h.broker.manifest
                if m is not None and m.has_completion(
                    rid, key, int(symbols.size)
                ):
                    targets = [h]
                    break
        if targets is None:
            targets = self._targets(exclude)
        if not targets:
            eta = min(
                (h.health.eta_s() for h in self.hosts
                 if h.health.state() != DEAD),
                default=float("inf"),
            )
            raise Backpressure(
                "no healthy host (every host dead or cooling down)",
                "no_healthy_host",
                retry_after_s=round(min(5.0, max(0.05, eta)), 3),
            )
        hints: list = []
        conn_errors = 0
        for h in targets:
            # Lineage BEFORE the attempt: a failed-over request's trace
            # shows BOTH host memberships even when the submit dies here.
            if failover:
                scope_mod.hop(rid, "host", host=h.label, failover=True)
            else:
                scope_mod.hop(rid, "host", host=h.label)
            try:
                # graftfault host partition point: the router -> host
                # transport boundary.
                faultplan.check("host.submit", tag=h.label)
                h.broker.submit(
                    request_id=rid, tenant=tenant, kind=kind,
                    symbols=symbols, name=name, model=model, models=models,
                )
            except Backpressure as e:
                h.health.record_backpressure()
                hints.append(
                    e.retry_after_s if e.retry_after_s else 0.05
                )
                scope_mod.hop(rid, "host.reject", host=h.label,
                              reason=e.reason)
                continue
            except OSError as e:
                # Transport partition: strike the host, shed to the next.
                h.health.record_fault(e)
                conn_errors += 1
                scope_mod.hop(rid, "host.reject", host=h.label,
                              reason="connection")
                continue
            # ValueError (duplicate/malformed) propagates: the owning
            # host's arbitration must stay visible to the client.
            h.health.record_success()
            with self._lock:
                self._owner[rid] = h.label
            return
        if hints:
            raise Backpressure(
                f"all {len(targets)} serveable host(s) refused admission",
                "all_hosts_saturated",
                retry_after_s=round(min(hints), 3),
            )
        raise Backpressure(
            f"no reachable host ({conn_errors} connection failure(s))",
            "no_reachable_host", retry_after_s=0.25,
        )

    def _targets(self, exclude=()) -> list:
        """Serveable hosts, least-loaded first (queued symbols, then
        label for a stable total order)."""
        avail = [
            h for h in self.hosts
            if h not in exclude and h.health.can_serve()
        ]
        return sorted(
            avail, key=lambda h: (h.broker.queue_depth()[1], h.label)
        )

    def backpressure(self) -> bool:
        """The pod-level soft signal the transport mirrors to clients:
        True only when every serveable host is backpressured (or none is
        serveable)."""
        live = [h for h in self.hosts if h.health.can_serve()]
        if not live:
            return True
        return all(h.broker.backpressure() for h in live)

    def pending(self) -> int:
        return sum(
            h.broker.pending() for h in self.hosts
            if h.health.state() != DEAD
        )

    # -- results --------------------------------------------------------------

    # graftcheck: hot-path
    def _deliver(self, host: RouterHost, r) -> None:
        self._finish(host, r)
        cb = self.on_result
        if cb is None:
            return
        try:
            cb(r)
        except Exception:
            log.exception("router: on_result failed for request %s", r.id)

    def _finish(self, host: RouterHost, r) -> None:
        """Routing bookkeeping for one finished result: release the
        sticky owner, and if this id was adopted off a dead host's
        journal, append the completion to the DEAD journal (outside
        every router lock — the manifest lock stays a leaf) so the dead
        host's restart finds zero incomplete admits."""
        with self._lock:
            self._owner.pop(r.id, None)
            adopted = self._adopted.pop(r.id, None)
        if adopted is None:
            return
        dead_host, key = adopted
        m = dead_host.broker.manifest
        if m is None:
            return
        try:
            if r.ok:
                m.record_done(
                    r.id, key, int(r.n_symbols),
                    calls=r.calls, conf_sum=r.conf_sum,
                )
            else:
                m.record_failed(r.id)
            # Flight-recorder event, NOT a hop: the trace was already
            # completed by the serving broker's finish_flush — a hop here
            # would open a stray live trace for a finished id.
            scope_mod.record("journal_adopted", id=r.id,
                             host=dead_host.label)
        except Exception:
            # The dead journal may be gone with its host; the result is
            # already correct and delivered — at worst the dead host's
            # restart re-executes (idempotent via ITS manifest replay).
            log.exception(
                "router: could not journal adopted completion %s into "
                "dead host %s", r.id, dead_host.label,
            )

    # -- host death + cross-host failover -------------------------------------

    def _on_host_death(self, host: RouterHost, exc: BaseException) -> None:
        """Called from the dying worker thread (must not raise): mark the
        host dead and hand its journal to a tracked failover thread —
        the dying thread itself may not touch surviving brokers
        (SIGKILL semantics: nothing else runs on the dead host)."""
        try:
            host.health.mark_dead(repr(exc))
            with self._lock:
                if self._closed or self._stop.is_set():
                    return
                t = threading.Thread(
                    target=self._failover_guarded, args=(host,),
                    name=f"cpgisland-router-failover-{host.label}",
                    daemon=True,
                )
                self._failover_threads.append(t)
            t.start()
        except Exception:
            log.exception(
                "router: host-death handling for %s failed", host.label
            )

    def _failover_guarded(self, host: RouterHost) -> None:
        try:
            self._failover(host)
        except Exception:
            log.exception("router: failover off host %s failed", host.label)

    def fail_host(self, label: str, reason: str = "operator") -> None:
        """Declare a host dead and fail its journal over synchronously
        (ops hook; tests use it for pool-backed hosts and for deaths the
        worker guard cannot see, e.g. a kill between journal.admit and
        queue visibility)."""
        host = self._host_by_label[label]
        host.health.mark_dead(reason)
        self._failover(host)

    def failover(self, label: str) -> None:
        """Synchronous failover of an already-dead host (tests join on
        the outcome instead of polling the background thread)."""
        self._failover(self._host_by_label[label])

    def _failover(self, host: RouterHost) -> None:
        """Adopt every admitted-but-incomplete id from ``host``'s journal
        onto survivors.  Reads the journal from DISK: the live manifest
        keeps payload-free admit stubs, only the file has the
        re-executable payloads (flushed per line — the write-ahead
        contract is exactly what makes this scan sufficient)."""
        m = host.broker.manifest
        if m is None:
            log.warning(
                "router: dead host %s has no journal — its in-flight "
                "admissions are not recoverable (run hosts with "
                "manifest_path for failover)", host.label,
            )
            return
        # Quiesce before scanning: a fail_host on a still-running worker
        # must let its in-progress flush finish journaling (mark_dead
        # already stopped the loop at its next boundary) — otherwise the
        # disk snapshot could adopt an id that is completing concurrently
        # and double-execute it.  When called FROM the dying worker's own
        # failover thread the join just waits out its final raise.
        w = host.worker
        if w is not None and threading.current_thread() is not w._thread:
            w.join(timeout=60.0)
        pending = RunManifest.scan_incomplete(m.path)
        adopted = 0
        for rec in pending:
            rid = int(rec["index"])
            pay = rec.get("payload")
            if not pay:
                log.warning(
                    "router: dead host %s admit %s has no payload; its "
                    "own restart must re-execute it", host.label, rid,
                )
                continue
            symbols = np.frombuffer(
                base64.b64decode(pay["symbols"]), dtype=np.uint8
            ).copy()
            tenant = str(pay["tenant"])
            kind = str(pay["kind"])
            name = str(pay["name"])
            model = str(pay.get("model", ""))
            key = f"{kind}:{tenant}:{len(model)}:{model}:{name}"
            if key != rec.get("name"):
                host.health.record_divergence(
                    f"admit {rid}: key {key!r} vs journal {rec.get('name')!r}"
                )
                log.warning(
                    "router: dead host %s admit %s diverged from its "
                    "journal line; skipping adoption", host.label, rid,
                )
                continue
            # Register the adoption BEFORE the submit: whichever live
            # copy completes (ours, or a client's own retry racing us)
            # resolves the dead admit through _finish.
            with self._lock:
                self._adopted[rid] = (host, key)
                self._owner.pop(rid, None)
            if self._failover_submit(
                rid, tenant=tenant, kind=kind, name=name, model=model,
                symbols=symbols, dead=host,
            ):
                adopted += 1
            else:
                with self._lock:
                    self._adopted.pop(rid, None)
                log.error(
                    "router: could not fail admission %s over off dead "
                    "host %s; its restart will re-execute it (zero "
                    "drops — delivery just waits for the restart)",
                    rid, host.label,
                )
        with self._lock:
            self.failovers += 1
            self.failed_over_requests += adopted
        obs.event(
            "host_failover", host=host.label,
            n_pending=len(pending), n_adopted=adopted,
        )
        scope_mod.record(
            "host_failover", host=host.label,
            n_pending=len(pending), n_adopted=adopted,
        )
        log.warning(
            "router: host %s failed over — %d/%d journaled admission(s) "
            "adopted by survivors", host.label, adopted, len(pending),
        )

    def _failover_submit(self, rid: int, *, tenant: str, kind: str,
                         name: str, model: str, symbols, dead: RouterHost,
                         ) -> bool:
        """Bounded resubmission of one adopted admission.  Backpressure
        waits out the shed window; a duplicate ValueError means a live
        copy of the id exists on a survivor — drop the adoption for this
        attempt (its completion must not be journaled under the dead
        admit's key unless identities match) and retry: an
        identical-identity copy completes and the next attempt replays
        it (then _finish journals the dead admit with the correct
        bytes); a persistently colliding DIFFERENT identity gives up and
        leaves the admit for the dead host's own restart."""
        cfg = self.config
        for attempt in range(cfg.failover_attempts):
            if attempt:
                time.sleep(cfg.failover_retry_s)
            with self._lock:
                if self._closed:
                    return False
                if rid not in self._adopted:
                    self._adopted[rid] = (
                        dead, f"{kind}:{tenant}:{len(model)}:{model}:{name}"
                    )
            try:
                self._route(
                    request_id=rid, tenant=tenant, kind=kind,
                    symbols=symbols, name=name, model=model,
                    exclude=(dead,), failover=True,
                )
                return True
            except Backpressure:
                continue
            except ValueError:
                with self._lock:
                    self._adopted.pop(rid, None)
                continue
            except RuntimeError:
                return False  # router/hosts closed mid-failover
        return False

    # -- lifecycle (transport pool contract) ----------------------------------

    def start(self, on_result: Callable) -> "RequestRouter":
        self.on_result = on_result
        for h in self.hosts:
            if h.pool is not None:
                h.pool.start(self._pool_sink(h))
            else:
                h.worker = _HostWorker(self, h)
                h.worker.start()
        log.info(
            "router: started over %d host(s): %s",
            len(self.hosts), ", ".join(h.label for h in self.hosts),
        )
        return self

    def _pool_sink(self, host: RouterHost) -> Callable:
        def sink(r) -> None:
            self._deliver(host, r)
        return sink

    def stop(self, join: bool = True) -> None:
        self._stop.set()
        for h in self.hosts:
            # Wake workers parked on their broker's flush condition.
            with h.broker._cv:
                h.broker._cv.notify_all()
        for h in self.hosts:
            if h.pool is not None:
                h.pool.stop(join=join)
            elif h.worker is not None and join:
                h.worker.join()
        if join:
            with self._lock:
                threads = list(self._failover_threads)
            for t in threads:
                t.join(timeout=60.0)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def close(self) -> None:
        with self._lock:
            self._closed = True
        for h in self.hosts:
            h.broker.close()

    def release(self) -> None:
        for h in self.hosts:
            h.broker.release()
            if h.pool is not None:
                h.pool.close()

    def drain(self) -> list:
        """Drain every surviving host's queue inline (the transport's
        shutdown path); each result still runs the routing bookkeeping
        (_finish) so adopted completions land in their dead journals."""
        out: list = []
        for h in self.hosts:
            if h.health.state() == DEAD:
                continue
            for r in h.broker.drain():
                self._finish(h, r)
                out.append(r)
        return out

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            failovers = self.failovers
            failed_over = self.failed_over_requests
            adopted_pending = len(self._adopted)
            routed = len(self._owner)
        hosts: dict = {}
        for h in self.hosts:
            n_req, n_sym = h.broker.queue_depth()
            ent = {
                "health": h.health.snapshot(),
                "queued_requests": n_req,
                "queued_symbols": n_sym,
            }
            if h.worker is not None:
                ent["flushes"] = h.worker.flushes
            if h.pool is not None:
                ent["fleet"] = h.pool.stats()
            hosts[h.label] = ent
        return {
            "hosts": hosts,
            "failovers": failovers,
            "failed_over_requests": failed_over,
            "adopted_pending": adopted_pending,
            "routed_inflight": routed,
        }
