"""Persistent serving subsystem: sessions, request broker, worker, transport.

The reference is a one-shot Hadoop batch job; ROADMAP item 1 is the
"millions of users" direction — a long-lived multi-tenant service that
never re-pays process startup, jit compile, or stream prep per request.
This package composes the ingredients earlier PRs built for exactly this:

- :mod:`~cpgisland_tpu.serve.session` — the **session/engine layer**
  extracted from ``pipeline.py``: a :class:`~cpgisland_tpu.serve.session.
  Session` owns the model params, resolved engine state, the per-session
  dispatch supervisor + circuit breaker, the prepared-stream cache handle,
  and the learned island cap.  ``decode_file``/``posterior_file``, bench,
  and the daemon all drive the same object, so the batch CLI paths and the
  server cannot diverge.
- :mod:`~cpgisland_tpu.serve.broker` — the **request broker**: admission
  control with per-tenant queue caps, a bounded-latency flush policy
  (symbol budget or deadline, whichever first), coalescing of heterogeneous
  decode requests into the flat reset-step stream
  (``viterbi_onehot.decode_batch_flat`` via the shared
  ``pipeline._decode_small_batch``), per-tenant obs accounting, and
  optional PR 5 manifest-backed replay for restarted daemons.
- :mod:`~cpgisland_tpu.serve.worker` — the **worker loop**: a background
  thread draining the broker so transport-side parse/encode of flush n+1
  overlaps device compute of flush n (the RecordPrefetcher pattern, with
  the admission caps as the bounded queue).
- :mod:`~cpgisland_tpu.serve.fleet` — the **device pool** (``--fleet``):
  one cloned session set + flush worker per local device under the one
  broker, with per-device health state machines (healthy -> suspect ->
  quarantined -> half-open probe -> restored), flush failover (a flush
  whose device faults past the retry budget requeues intact onto a
  healthy device), and the never-kill slow-dispatch quarantine.  The
  single fault domain of PRs 8-9 (one worker, one device) becomes N.
- :mod:`~cpgisland_tpu.serve.transport` — the thin **wire layer**
  (stdin/stdout JSONL, or the multi-connection socket mux — AF_UNIX
  and/or a TCP side door, one shared accept loop: concurrent client
  connections, one reader thread each, results routed back to the
  owning connection by request id), kept separate from the broker so
  tests (and the graftcheck contract) drive the broker in-process.
- :mod:`~cpgisland_tpu.serve.router` — the **pod-scale routing tier**
  (ROADMAP item 2): N per-host brokers behind one front that duck-types
  as broker+pool for the transport, with per-host health state machines
  (the fleet's model one fault-domain level up, plus terminal DEAD),
  least-loaded elastic load shedding driven by measured flush walls
  (``Backpressure.retry_after_s`` becomes a real contract), and
  cross-host flush failover off a dead host's write-ahead journal.

Thread contract (machine-checked by graftsync, LINT.md Layer 4): any
thread may submit; ONE worker loop executes flushes per broker; every
shared field is guarded by its owner's lock, lock nesting follows the
global order (router -> connection; session -> breaker; the request
router's and each host health's locks are leaves), and nothing blocks
while holding a registered lock.

Import note: this package pulls in jax via the pipeline — the CLI imports
it lazily inside the ``serve`` subcommand, after platform selection.
"""

from __future__ import annotations

from cpgisland_tpu.serve.broker import (  # noqa: F401
    Backpressure,
    BrokerConfig,
    RequestBroker,
    ServeRequest,
    ServeResult,
)
from cpgisland_tpu.serve.fleet import (  # noqa: F401
    DeviceHealth,
    DevicePool,
    FleetConfig,
)
from cpgisland_tpu.serve.router import (  # noqa: F401
    HostHealth,
    RequestRouter,
    RouterConfig,
    RouterHost,
)
from cpgisland_tpu.serve.session import Session  # noqa: F401
from cpgisland_tpu.serve.worker import ServeLoop  # noqa: F401
