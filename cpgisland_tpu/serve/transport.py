"""Thin wire layer: JSONL over stdin/stdout or a local (Unix) socket mux.

Kept deliberately separate from the broker so tests and the graftcheck
contract drive the broker in-process; this module only parses lines,
encodes sequence text to symbols (on the transport thread — that host
work is exactly what overlaps the worker's device compute), and writes
result lines.

Socket mode is a **multi-connection mux** (the ROADMAP response-muxing
item): each client connection gets its own reader thread (parse + encode +
admission), all feeding the ONE broker whose single worker loop executes
flushes — the single-dispatcher rule is preserved because only the
:class:`ResponseRouter` sits between the worker and the sockets.  Every
result is routed back to the connection that submitted its request id;
request ids therefore share one daemon-wide space, and concurrent clients
must use disjoint id ranges (a colliding id is rejected at admission like
any duplicate).  Per-connection drain-on-death is preserved by routing: a
dead client's admitted requests still complete (keeping the shared queue
clean) and their results are dropped with a log line — never flushed into
another client's stream.

## Protocol (one JSON object per line)

Requests::

    {"id": 1, "kind": "decode",    "seq": "ACGT...", "tenant": "t0",
     "name": "chr1", "want_conf": false}
    {"id": 2, "kind": "posterior", "seq": "..."}
    {"id": 3, "kind": "decode",    "seq": "...", "model": "two_state"}
    {"id": 4, "kind": "compare",   "seq": "...",
     "models": ["durbin8", "two_state", "null"]}
    {"op": "stats"}
    {"op": "shutdown"}

``model`` routes a decode/posterior request to a named family member
registered at daemon startup (``--family``; unknown names are rejected at
admission); ``compare`` evaluates the named members over one stream and
responds with per-model log-odds plus the winner track as island records.

``id`` must be a client-unique integer (it keys the resume manifest).
``tenant`` defaults to ``"default"``; ``name`` defaults to ``req<id>``.
``want_conf`` (posterior) includes the full per-symbol confidence list in
the response — off by default (it is 4 B/symbol of JSON-escaped floats).
A replayed manifest hit (daemon restarted with ``--resume``) cannot
recover per-symbol conf (the manifest journals calls + conf_sum only):
such a response carries ``"conf_unavailable": true`` instead of
``"conf"``.

Responses (completion order, not submission order)::

    {"id": 1, "ok": true, "kind": "decode", "tenant": "t0",
     "islands": {...bit-exact wire form...}, "islands_text": "beg end ...",
     "n_symbols": 12345, "queue_s": 0.01, "serve_s": 0.2,
     "route": "flat", "replayed": false, "backpressure": false}
    {"id": 2, "ok": true, "kind": "posterior", "mean_conf": 0.123,
     "conf_sum": "0x1.9p+3", ...}
    {"id": 7, "ok": false, "error": "Backpressure: ...",
     "backpressure": true}

``islands`` uses the PR 5 manifest wire form (ints exact, floats as
``float.hex()``), so a client can reconstruct calls bit-identically;
``islands_text`` is the reference's ``beg end len gc oe`` line format.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import IO

import numpy as np

from cpgisland_tpu.resilience import faultplan as faultplan_mod
from cpgisland_tpu.serve.broker import Backpressure, RequestBroker, ServeResult
from cpgisland_tpu.serve.worker import ServeLoop

log = logging.getLogger(__name__)

__all__ = [
    "result_to_wire",
    "serve_stream",
    "serve_socket",
    "serve_tcp",
    "serve_main",
    "ResponseRouter",
]


def result_to_wire(r: ServeResult, *, backpressure: bool = False,
                   want_conf: bool = False) -> dict:
    """ServeResult -> JSON-safe response dict (see module docstring)."""
    from cpgisland_tpu.resilience.manifest import calls_to_wire

    out: dict = {
        "id": r.id, "ok": r.ok, "kind": r.kind, "tenant": r.tenant,
        "n_symbols": r.n_symbols, "route": r.route, "replayed": r.replayed,
        "queue_s": round(r.queue_s, 6), "serve_s": round(r.serve_s, 6),
        "backpressure": backpressure,
    }
    if not r.ok:
        out["error"] = r.error
        return out
    if r.calls is not None:
        out["islands"] = calls_to_wire(r.calls)
        out["islands_text"] = r.calls.format_lines()
    if r.compare is not None:
        # compare: per-model loglik/log-odds; the winner track already
        # rides in islands/islands_text above (member names in the name
        # column).
        out["compare"] = r.compare
    if r.kind == "posterior":
        if r.conf_sum is not None:
            out["conf_sum"] = float(r.conf_sum).hex()
            out["mean_conf"] = (
                r.conf_sum / r.n_symbols if r.n_symbols else 0.0
            )
        if want_conf:
            if r.conf is not None:
                out["conf"] = [float(v) for v in np.asarray(r.conf)]
            else:
                # Replayed manifest hits carry calls + conf_sum only —
                # per-symbol conf is not journaled.  Say so instead of
                # silently dropping the key the client asked for.
                out["conf_unavailable"] = True
    return out


def _parse_request(line: str) -> dict:
    req = json.loads(line)
    if not isinstance(req, dict):
        raise ValueError("request must be a JSON object")
    return req


def _admit_request(
    req: dict,
    broker: RequestBroker,
    *,
    invalid_symbols: str,
    write,
    claim,
    unclaim,
) -> None:
    """The shared parse -> encode -> claim -> submit core of both the stdio
    stream and the socket mux (ONE copy, so the two transports cannot
    drift).  ``claim(rid, req)`` registers delivery state (the stream's
    want_conf flag / the mux route) BEFORE submit — the worker may deliver
    the result immediately after submit returns — and may raise ValueError
    to reject the request itself; ``unclaim(rid)`` rolls that registration
    back when submit rejects, so a refused request can't leak state onto a
    later reuse of its id.  Rejections (including a RuntimeError from a
    broker another client already shut down) become machine-readable error
    lines; the echoed id is the parsed rid when one exists, else the raw
    field."""
    from cpgisland_tpu.utils import codec

    rid = None
    try:
        rid = int(req["id"])
        kind = req["kind"]
        symbols = codec.encode(req["seq"], invalid=invalid_symbols)
        claim(rid, req)
        try:
            broker.submit(
                request_id=rid,
                tenant=str(req.get("tenant", "default")),
                kind=kind,
                symbols=symbols,
                name=str(req.get("name", f"req{rid}")),
                model=str(req.get("model", "")),
                models=req.get("models"),
            )
        except BaseException:
            unclaim(rid)
            raise
    except Backpressure as e:
        write({
            "id": rid if rid is not None else req.get("id"), "ok": False,
            "error": f"Backpressure: {e}", "reason": e.reason,
            "backpressure": True,
            # Queue-depth-derived backoff hint: a reconnecting client
            # sleeps this long instead of hot-looping on a saturated
            # fleet (tools/serve_client.py honors it).
            "retry_after_s": e.retry_after_s,
        })
    except (KeyError, ValueError, TypeError, RuntimeError) as e:
        write({
            "id": rid if rid is not None else req.get("id"), "ok": False,
            "error": f"{type(e).__name__}: {e}",
            "backpressure": broker.backpressure(),
        })


def _stats_wire(req: dict, broker: RequestBroker, pool=None,
                router=None) -> dict:
    """Response for a ``kind=stats`` request: broker queue stats + the
    graftscope SLO snapshot (latency/flush histograms, per-tenant/-model/
    -device throughput) + fleet health when a DevicePool drives the broker.
    Pure host-side reads — a stats request never enters the flush queue."""
    from cpgisland_tpu.obs import scope as scope_mod

    out: dict = {"ok": True, "kind": "stats", "stats": broker.stats()}
    if req.get("id") is not None:
        out["id"] = req["id"]
    sc = scope_mod.active()
    out["slo"] = None if sc is None else sc.snapshot()
    if pool is not None:
        out["fleet"] = pool.stats()
    if router is not None:
        out["mux"] = router.stats()
    return out


def serve_stream(
    inp: IO[str],
    out: IO[str],
    broker: RequestBroker,
    *,
    use_worker: bool = True,
    invalid_symbols: str = "skip",
    pool=None,
) -> int:
    """Serve a line stream until EOF or ``{"op": "shutdown"}``.

    ``use_worker=True`` runs flushes on a background :class:`ServeLoop`
    (the daemon cadence: this thread's parse/encode overlaps the worker's
    device compute).  ``use_worker=False`` is the deterministic in-process
    mode (tests): flushes run inline on this thread whenever the broker
    reports ready, and the stream drains at EOF.  ``pool`` (a started-able
    :class:`~cpgisland_tpu.serve.fleet.DevicePool`) replaces the single
    ServeLoop with one flush worker per device.  Returns the number of
    requests served.
    """
    wlock = threading.Lock()
    served = [0]
    want_conf: dict[int, bool] = {}
    # Single-slot rollback state: claim/unclaim run back-to-back on THIS
    # thread inside one _admit_request call (never concurrently).
    pending_new_flag = [False]

    def write(obj: dict) -> None:
        with wlock:
            out.write(json.dumps(obj) + "\n")
            out.flush()

    def flag_claim(rid: int, req: dict) -> None:
        wants = bool(req.get("want_conf"))
        pending_new_flag[0] = wants and not want_conf.get(rid, False)
        if wants:
            want_conf[rid] = True

    def flag_unclaim(rid: int) -> None:
        if pending_new_flag[0]:
            want_conf.pop(rid, None)

    def on_result(r: ServeResult) -> None:
        served[0] += 1
        write(result_to_wire(
            r, backpressure=broker.backpressure(),
            want_conf=want_conf.pop(r.id, False),
        ))

    if pool is not None:
        loop = pool.start(on_result)
    else:
        loop = ServeLoop(broker, on_result).start() if use_worker else None
    try:
        for line in inp:
            line = line.strip()
            if not line:
                continue
            try:
                req = _parse_request(line)
            except (ValueError, json.JSONDecodeError) as e:
                write({"ok": False, "error": f"bad request line: {e}"})
                continue
            op = req.get("op")
            if op == "shutdown":
                # Stop admission now; everything already admitted is still
                # served by the drain below.  Closing the broker is what
                # the socket accept loop watches for.
                broker.close()
                break
            if op == "stats":
                write({"ok": True, "stats": broker.stats()})
                continue
            if req.get("kind") == "stats":
                # graftscope SLO snapshot: answered inline on this thread
                # (never queued — a monitoring poll must not ride the
                # flush path or pay its latency).
                write(_stats_wire(req, broker, pool))
                continue
            # Host-side encode + submit on THIS thread (the work that
            # overlaps the worker loop's device compute) via the shared
            # core.  claim sets the want_conf flag; unclaim rolls back
            # only the flag THIS request set, so a rejected duplicate id
            # can't clobber the flag an earlier still-queued request set.
            _admit_request(
                req, broker, invalid_symbols=invalid_symbols, write=write,
                claim=flag_claim, unclaim=flag_unclaim,
            )
            if loop is None:
                while broker.flush_ready():
                    for r in broker.flush_once():
                        on_result(r)
    finally:
        if loop is not None:
            loop.stop()
        # EOF / shutdown / connection death: serve everything already
        # admitted.  Draining in the finally keeps the shared broker's
        # queue empty even when THIS stream dies mid-read (socket mode
        # reuses one broker across connections — a skipped drain would
        # flush a dead client's requests into the NEXT client's stream).
        # Results a dead stream can no longer accept are completed and
        # dropped.
        for r in broker.drain():
            try:
                on_result(r)
            except (OSError, ValueError):
                # Broken pipe / closed makefile: keep draining so no
                # request leaks past this connection.
                log.warning("serve: dropping result for request %s "
                            "(client stream closed)", r.id)
    return served[0]


def _build_broker(args, params) -> RequestBroker:
    """CLI args -> Session (+ family ModelRegistry) + RequestBroker (the
    ONE construction shared by the stdio and socket servers)."""
    from cpgisland_tpu.serve.broker import BrokerConfig
    from cpgisland_tpu.serve.session import ModelRegistry, Session

    session = Session(
        params,
        engine=args.engine,
        island_engine=args.island_engine,
        island_cap=args.island_cap,
        integrity_check=args.integrity_check,
        name="serve",
        private_breaker=True,
    )
    registry = ModelRegistry(session)
    family_names = [
        t.strip() for t in (getattr(args, "family", "") or "").split(",")
        if t.strip()
    ]
    if family_names:
        from cpgisland_tpu import family as family_mod

        for member in family_mod.members_from_names(family_names):
            # One Session per member, private breaker: one model's faults
            # demote engines for that model only.
            registry.register(
                member,
                engine=args.engine,
                island_engine=args.island_engine,
                island_cap=args.island_cap,
                integrity_check=args.integrity_check,
            )
    config = BrokerConfig(
        flush_symbols=args.flush_symbols,
        flush_deadline_s=args.flush_deadline_ms / 1e3,
        tenant_max_requests=args.tenant_max_requests,
        tenant_max_symbols=args.tenant_max_symbols,
        min_len=args.min_len,
        island_states=args.island_states,
        stacked=not getattr(args, "no_stacked", False),
    )
    return RequestBroker(
        session, config, registry=registry,
        manifest_path=args.manifest, resume=args.resume,
    )


def serve_main(args, params) -> int:
    """The ``cpgisland serve`` entry: stdio JSONL by default, a local
    AF_UNIX multi-connection socket mux with ``--socket PATH`` (concurrent
    client connections, all feeding the one broker; responses routed back
    to the owning connection by request id).  ``--fleet N`` drives the
    broker with a :class:`~cpgisland_tpu.serve.fleet.DevicePool` over N
    local devices instead of the single worker loop."""
    import sys

    from cpgisland_tpu import obs as obs_mod
    from cpgisland_tpu.obs import scope as scope_mod

    broker = _build_broker(args, params)
    pool = None
    if getattr(args, "fleet", 0):
        from cpgisland_tpu.serve.fleet import DevicePool

        pool = DevicePool.build(broker, n_devices=args.fleet)
    # graftscope: request lineage + SLO histograms + flight recorder ride
    # along whenever the obs layer is on OR periodic emission was asked
    # for; the recorder persists next to the journal (<manifest>.flight.json)
    # on shutdown/SimulatedKill/worker death.  Off-by-default otherwise.
    interval = float(getattr(args, "metrics_interval", 0.0) or 0.0)
    scope = None
    emitter = None
    if obs_mod.enabled() or interval > 0:
        flight = f"{args.manifest}.flight.json" if args.manifest else None
        scope = scope_mod.install(scope_mod.Scope(flight_path=flight))
        if interval > 0:
            def _live_stats() -> dict:
                extra = {"stats": broker.stats()}
                if pool is not None:
                    extra["fleet"] = pool.stats()
                return extra

            emitter = scope_mod.SnapshotEmitter(
                scope, interval, extra_fn=_live_stats
            ).start()
    tcp_spec = str(getattr(args, "tcp", "") or "")
    try:
        if not args.socket and not tcp_spec:
            n = serve_stream(
                sys.stdin, sys.stdout, broker,
                invalid_symbols=args.invalid_symbols, pool=pool,
            )
            log.info("serve: %d request(s) served", n)
            return 0
        extra: list = []
        if tcp_spec:
            host, port = tcp_spec.rsplit(":", 1)
            if args.socket:
                # Both doors, ONE mux: the AF_UNIX path for local
                # consumers plus the TCP side door for cross-machine
                # ones (a routing tier on another box).
                srv = _bind_tcp(host, int(port))
                extra.append((srv, f"tcp:{host}:{srv.getsockname()[1]}"))
            else:
                return serve_tcp(
                    host, int(port), broker,
                    invalid_symbols=args.invalid_symbols, pool=pool,
                )
        return serve_socket(
            args.socket, broker, invalid_symbols=args.invalid_symbols,
            pool=pool, extra_servers=tuple(extra),
        )
    finally:
        broker.close()
        # The transports have drained by the time they return — NOW the
        # journal may close (closing it inside broker.close() would lose
        # the shutdown drain's completion lines).
        broker.release()
        broker.registry.close()
        if pool is not None:
            pool.close()
        if emitter is not None:
            emitter.stop()
        if scope is not None:
            scope_mod.uninstall(scope)
            scope.recorder.persist("shutdown")


# ---------------------------------------------------------------------------
# Multi-connection socket mux


class _MuxClient:
    """One connection's write side: a JSONL stream serialized by its own
    condition (reader-thread error/stats lines interleave with worker-thread
    results), an outstanding-request count for drain-on-death, and an alive
    flag flipped when the socket breaks.  All three fields are guarded by
    ``_cond``; socket writes happen under it too — that lock exists to
    serialize this connection's writes, and nothing else is ever acquired
    under it (a leaf in the lock-order graph)."""

    def __init__(self, cid: int, wf) -> None:
        self.cid = cid
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._wf = wf
        self._alive = True
        self._outstanding = 0
        self._served = 0

    def add_pending(self) -> None:
        with self._cond:
            self._outstanding += 1

    def fail_pending(self) -> None:
        """Retire a pending slot whose submit was rejected (no result will
        ever be delivered for it)."""
        with self._cond:
            self._outstanding -= 1
            self._cond.notify_all()

    def write_payload(self, obj: dict) -> bool:
        """Write one non-result line (errors, stats); False once dead."""
        with self._cond:
            return self._write_locked(obj)

    def write_result(self, obj: dict) -> bool:
        """Write one routed result line and retire its pending slot.  The
        slot retires even when the write fails — a dead client must not
        wedge its reader thread's drain wait."""
        with self._cond:
            ok = self._write_locked(obj)
            if ok:
                self._served += 1
            self._outstanding -= 1
            self._cond.notify_all()
            return ok

    def _write_locked(self, obj: dict) -> bool:
        if not self._alive:
            return False
        try:
            self._wf.write(json.dumps(obj) + "\n")
            self._wf.flush()
            return True
        except (OSError, ValueError):
            # Broken pipe / closed makefile: the connection is gone.  Keep
            # serving (results for it are dropped by callers with a log).
            self._alive = False
            self._cond.notify_all()
            return False

    def mark_dead(self) -> None:
        with self._cond:
            self._alive = False
            self._cond.notify_all()

    @property
    def alive(self) -> bool:
        with self._cond:
            return self._alive

    @property
    def served(self) -> int:
        with self._cond:
            return self._served

    def wait_drained(self, timeout_s: float) -> bool:
        """Block until every routed request of this connection has been
        delivered (or the connection died); the reader thread's last act
        before closing the socket, so a client that EOFs its write side
        still receives all of its results."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._outstanding > 0 and self._alive:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return self._outstanding <= 0


class ResponseRouter:
    """Request-id -> connection routing (the mux core).

    Reader threads :meth:`route` an id to their connection BEFORE
    submitting it to the broker (results can arrive immediately after
    ``submit`` returns); the worker loop delivers every flush result
    through :meth:`deliver`, which looks up and retires the route.  Routes
    for a dead connection deliver into a log line instead of a socket —
    requests are never re-queued into another client's stream.
    """

    def __init__(self, broker: RequestBroker) -> None:
        self.broker = broker
        self._lock = threading.Lock()
        self._routes: dict[int, tuple] = {}  # rid -> (client, want_conf)
        self.dropped = 0

    def route(self, rid: int, client: _MuxClient, want_conf: bool) -> bool:
        """Claim ``rid`` for ``client``; False when the id is already in
        flight (the existing route — and its want_conf flag — is left
        untouched, mirroring the broker's duplicate-id rejection)."""
        with self._lock:
            if rid in self._routes:
                return False
            self._routes[rid] = (client, want_conf)
        client.add_pending()
        return True

    def unroute(self, rid: int, client: _MuxClient) -> None:
        """Roll back a claim whose submit was rejected; only the claiming
        client's route is removed (a racing re-claim keeps its own)."""
        with self._lock:
            ent = self._routes.get(rid)
            if ent is None or ent[0] is not client:
                return
            del self._routes[rid]
        client.fail_pending()

    def deliver(self, r: ServeResult) -> None:
        """ServeLoop's on_result: route one result to its connection.
        Never raises — an undeliverable result is logged and dropped, not
        allowed to kill the worker loop or starve the rest of the flush."""
        with self._lock:
            ent = self._routes.pop(r.id, None)
        if ent is None:
            with self._lock:
                self.dropped += 1
            log.warning(
                "serve mux: dropping result for request %s (no live route "
                "— connection closed before submit completed?)", r.id,
            )
            return
        client, want_conf = ent
        try:
            wire = result_to_wire(
                r, backpressure=self.broker.backpressure(),
                want_conf=want_conf,
            )
        except Exception:
            log.exception("serve mux: encoding result %s failed", r.id)
            wire = {"id": r.id, "ok": False,
                    "error": "InternalError: result encoding failed"}
        if not client.write_result(wire):
            log.warning(
                "serve mux: dropping result for request %s (connection %d "
                "closed)", r.id, client.cid,
            )

    def stats(self) -> dict:
        with self._lock:
            return {"in_flight": len(self._routes), "dropped": self.dropped}


def _mux_read_loop(
    client: _MuxClient,
    rf,
    broker: RequestBroker,
    router: ResponseRouter,
    invalid_symbols: str,
    pool=None,
) -> None:
    """One connection's reader: parse + encode + route + submit (the
    shared ``_admit_request`` core with the router as the claim).  Pure
    host work on this thread (the overlap with the worker loop's device
    compute, same as stdio mode)."""

    def route_claim(rid: int, req: dict) -> None:
        if not router.route(rid, client, bool(req.get("want_conf"))):
            raise ValueError(
                f"request id {rid} is already in flight on this daemon "
                "— concurrent connections share one id space; use "
                "disjoint id ranges per client"
            )

    def route_unclaim(rid: int) -> None:
        router.unroute(rid, client)

    for line in rf:
        # graftfault injection point: a "disconnect" here models the
        # connection dying mid-stream — the OSError takes the same
        # drain-on-death path a real broken socket does.  (Placed OUTSIDE
        # any lock: _MuxClient's write lock is a documented leaf.)
        faultplan_mod.check("transport.read", tag=f"conn{client.cid}")
        line = line.strip()
        if not line:
            continue
        try:
            req = _parse_request(line)
        except (ValueError, json.JSONDecodeError) as e:
            client.write_payload({"ok": False, "error": f"bad request line: {e}"})
            continue
        op = req.get("op")
        if op == "shutdown":
            # Stop admission daemon-wide; everything already admitted is
            # still served (the accept loop watches broker.closed).
            broker.close()
            return
        if op == "stats":
            stats = broker.stats()
            stats["mux"] = router.stats()
            client.write_payload({"ok": True, "stats": stats})
            continue
        if req.get("kind") == "stats":
            # graftscope SLO snapshot (see serve_stream): inline, unqueued.
            client.write_payload(_stats_wire(req, broker, pool, router))
            continue
        _admit_request(
            req, broker, invalid_symbols=invalid_symbols,
            write=client.write_payload,
            claim=route_claim, unclaim=route_unclaim,
        )


def _mux_client_thread(
    client: _MuxClient,
    conn,
    rf,
    broker: RequestBroker,
    router: ResponseRouter,
    invalid_symbols: str,
    drain_timeout_s: float,
    pool=None,
) -> None:
    try:
        _mux_read_loop(client, rf, broker, router, invalid_symbols, pool)
    except OSError:
        log.info("serve mux: connection %d dropped mid-read", client.cid)
    except Exception:
        log.exception("serve mux: connection %d reader failed", client.cid)
    finally:
        # Drain-on-death, per connection: everything this client submitted
        # still completes and flows back here before the socket closes (a
        # client that EOF'd its write side is still reading).
        if not client.wait_drained(drain_timeout_s):
            log.warning(
                "serve mux: connection %d closed with undelivered results "
                "(drain timeout %.0f s)", client.cid, drain_timeout_s,
            )
        client.mark_dead()
        # Close the write-side makefile too: an unclosed wf holds a socket
        # io-ref, so conn.close() would defer the real close and the fd
        # would live until the accept loop reaps this connection.
        for closer in (rf, client._wf, conn):
            try:
                closer.close()
            except (OSError, ValueError):
                pass


def _set_send_timeout(conn, seconds: float) -> None:
    """Bound every send on an accepted connection (``SO_SNDTIMEO``): the
    ONE worker thread writes results under the owning connection's lock,
    so a client that stops reading must FAIL its write (and be marked
    dead, its later results dropped) instead of wedging result delivery
    for every other connection — the mux twin of the blocking-under-lock
    rule, below the layer the AST can see.  Send-side only: the reader
    thread's blocking recv on an idle-but-healthy client must NOT time
    out."""
    import socket
    import struct

    sec = int(seconds)
    try:
        conn.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDTIMEO,
            struct.pack("ll", sec, int((seconds - sec) * 1e6)),
        )
    except (OSError, AttributeError):
        log.warning(
            "serve mux: could not set a send timeout on this platform; a "
            "client that stops reading may stall result delivery"
        )


def _serve_mux(
    servers: list,
    broker: RequestBroker,
    *,
    invalid_symbols: str = "skip",
    accept_poll_s: float = 0.5,
    drain_timeout_s: float = 600.0,
    write_timeout_s: float = 60.0,
    pool=None,
) -> int:
    """The shared accept loop over a LIST of bound, listening sockets
    (``(socket, description)`` pairs) — ONE copy of the mux regardless of
    how many listeners feed it, so an AF_UNIX daemon and its TCP side
    door cannot drift.  One reader thread per accepted connection, ONE
    worker loop (or ``pool`` — a DevicePool, or a routing-tier
    :class:`~cpgisland_tpu.serve.router.RequestRouter`) executing
    flushes against the shared broker, one :class:`ResponseRouter`
    delivering results back to owning connections; all listeners share
    the daemon-wide request-id space.  ``accept_poll_s`` is the TOTAL
    shutdown-check cadence, split across listeners.  Closes the server
    sockets on exit (callers own path unlinking)."""
    import socket

    router = ResponseRouter(broker)
    if pool is not None:
        loop = pool.start(router.deliver)
    else:
        loop = ServeLoop(broker, router.deliver).start()
    conns: list[tuple] = []  # LIVE (thread, client, conn); dead are reaped
    n_served = 0
    per_poll = max(0.02, accept_poll_s / max(1, len(servers)))
    for srv, desc in servers:
        srv.settimeout(per_poll)
        log.info(
            "serve: listening on %s (JSONL mux, concurrent connections; "
            "send {\"op\": \"shutdown\"} to stop)", desc,
        )
    n_conns = 0
    try:
        while not broker.closed:
            for srv, _desc in servers:
                if broker.closed:
                    break
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                # Reap finished connections (their own finally closed the
                # sockets) so a long-lived daemon doesn't accumulate dead
                # thread/socket objects per served client.
                live = []
                for ent in conns:
                    if ent[0].is_alive():
                        live.append(ent)
                    else:
                        n_served += ent[1].served
                conns = live
                n_conns += 1
                _set_send_timeout(conn, write_timeout_s)
                client = _MuxClient(
                    n_conns, conn.makefile("w", encoding="utf-8")
                )
                rf = conn.makefile("r", encoding="utf-8")
                t = threading.Thread(
                    target=_mux_client_thread,
                    args=(client, conn, rf, broker, router, invalid_symbols,
                          drain_timeout_s, pool),
                    name=f"cpgisland-serve-conn{n_conns}",
                    daemon=True,
                )
                conns.append((t, client, conn))
                t.start()
    except KeyboardInterrupt:
        pass
    finally:
        broker.close()
        loop.stop()
        # Serve everything already admitted; routed results reach their
        # (still-reading) owners, dead routes are dropped with a log.
        for r in broker.drain():
            router.deliver(r)
        for t, client, conn in conns:
            client.mark_dead()
            try:
                conn.shutdown(socket.SHUT_RDWR)  # unblock a parked reader
            except OSError:
                pass
            t.join(timeout=10.0)
            try:
                conn.close()
            except OSError:
                pass
        for srv, _desc in servers:
            srv.close()
        n_served += sum(c.served for _t, c, _conn in conns)
        log.info(
            "serve: socket mux served %d connection(s), %d result(s) "
            "delivered", n_conns, n_served,
        )
    return 0


def _bind_tcp(host: str, port: int, backlog: int = 8):
    """A bound, listening AF_INET socket (SO_REUSEADDR — daemon restarts
    must not wait out TIME_WAIT).  Port 0 binds an ephemeral port; read
    it back with ``getsockname()[1]``."""
    import socket

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, int(port)))
    srv.listen(backlog)
    return srv


def serve_socket(
    path: str,
    broker: RequestBroker,
    *,
    invalid_symbols: str = "skip",
    backlog: int = 8,
    accept_poll_s: float = 0.5,
    drain_timeout_s: float = 600.0,
    write_timeout_s: float = 60.0,
    pool=None,
    extra_servers: tuple = (),
) -> int:
    """Concurrent AF_UNIX JSONL server (see the module docstring's mux
    notes): one reader thread per client connection, ONE worker loop
    executing flushes against the shared broker (or a fleet
    :class:`~cpgisland_tpu.serve.fleet.DevicePool` — one flush worker per
    device — when ``pool`` is given), results routed back by request id.
    ``{"op": "shutdown"}`` from any client stops the server after
    everything admitted has been served.  ``write_timeout_s`` bounds
    each result write (a non-reading client is marked dead rather than
    allowed to stall the worker).  ``extra_servers``: additional bound
    ``(socket, description)`` listeners (e.g. a :func:`_bind_tcp` side
    door) muxed into the same accept loop."""
    import os

    if os.path.exists(path):
        os.unlink(path)
    import socket

    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(path)
    srv.listen(backlog)
    try:
        return _serve_mux(
            [(srv, path)] + list(extra_servers), broker,
            invalid_symbols=invalid_symbols, accept_poll_s=accept_poll_s,
            drain_timeout_s=drain_timeout_s, write_timeout_s=write_timeout_s,
            pool=pool,
        )
    finally:
        if os.path.exists(path):
            os.unlink(path)


def serve_tcp(
    host: str,
    port: int,
    broker: RequestBroker,
    *,
    invalid_symbols: str = "skip",
    backlog: int = 8,
    accept_poll_s: float = 0.5,
    drain_timeout_s: float = 600.0,
    write_timeout_s: float = 60.0,
    pool=None,
) -> int:
    """The mux on a TCP listener — the cross-machine consumer's door
    (clients on other hosts reach this broker with
    ``tools/serve_client.py --connect tcp:HOST:PORT``).  Same protocol,
    same shared accept loop, same id space as the AF_UNIX mux."""
    srv = _bind_tcp(host, port, backlog)
    bound = srv.getsockname()[1]
    return _serve_mux(
        [(srv, f"tcp:{host}:{bound}")], broker,
        invalid_symbols=invalid_symbols, accept_poll_s=accept_poll_s,
        drain_timeout_s=drain_timeout_s, write_timeout_s=write_timeout_s,
        pool=pool,
    )
