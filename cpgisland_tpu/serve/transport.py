"""Thin wire layer: JSONL over stdin/stdout or a local (Unix) socket.

Kept deliberately separate from the broker so tests and the graftcheck
contract drive the broker in-process; this module only parses lines,
encodes sequence text to symbols (on the transport thread — that host
work is exactly what overlaps the worker's device compute), and writes
result lines.

## Protocol (one JSON object per line)

Requests::

    {"id": 1, "kind": "decode",    "seq": "ACGT...", "tenant": "t0",
     "name": "chr1", "want_conf": false}
    {"id": 2, "kind": "posterior", "seq": "..."}
    {"op": "stats"}
    {"op": "shutdown"}

``id`` must be a client-unique integer (it keys the resume manifest).
``tenant`` defaults to ``"default"``; ``name`` defaults to ``req<id>``.
``want_conf`` (posterior) includes the full per-symbol confidence list in
the response — off by default (it is 4 B/symbol of JSON-escaped floats).
A replayed manifest hit (daemon restarted with ``--resume``) cannot
recover per-symbol conf (the manifest journals calls + conf_sum only):
such a response carries ``"conf_unavailable": true`` instead of
``"conf"``.

Responses (completion order, not submission order)::

    {"id": 1, "ok": true, "kind": "decode", "tenant": "t0",
     "islands": {...bit-exact wire form...}, "islands_text": "beg end ...",
     "n_symbols": 12345, "queue_s": 0.01, "serve_s": 0.2,
     "route": "flat", "replayed": false, "backpressure": false}
    {"id": 2, "ok": true, "kind": "posterior", "mean_conf": 0.123,
     "conf_sum": "0x1.9p+3", ...}
    {"id": 7, "ok": false, "error": "Backpressure: ...",
     "backpressure": true}

``islands`` uses the PR 5 manifest wire form (ints exact, floats as
``float.hex()``), so a client can reconstruct calls bit-identically;
``islands_text`` is the reference's ``beg end len gc oe`` line format.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import IO

import numpy as np

from cpgisland_tpu.serve.broker import Backpressure, RequestBroker, ServeResult
from cpgisland_tpu.serve.worker import ServeLoop

log = logging.getLogger(__name__)

__all__ = ["result_to_wire", "serve_stream", "serve_main"]


def result_to_wire(r: ServeResult, *, backpressure: bool = False,
                   want_conf: bool = False) -> dict:
    """ServeResult -> JSON-safe response dict (see module docstring)."""
    from cpgisland_tpu.resilience.manifest import calls_to_wire

    out: dict = {
        "id": r.id, "ok": r.ok, "kind": r.kind, "tenant": r.tenant,
        "n_symbols": r.n_symbols, "route": r.route, "replayed": r.replayed,
        "queue_s": round(r.queue_s, 6), "serve_s": round(r.serve_s, 6),
        "backpressure": backpressure,
    }
    if not r.ok:
        out["error"] = r.error
        return out
    if r.calls is not None:
        out["islands"] = calls_to_wire(r.calls)
        out["islands_text"] = r.calls.format_lines()
    if r.kind == "posterior":
        if r.conf_sum is not None:
            out["conf_sum"] = float(r.conf_sum).hex()
            out["mean_conf"] = (
                r.conf_sum / r.n_symbols if r.n_symbols else 0.0
            )
        if want_conf:
            if r.conf is not None:
                out["conf"] = [float(v) for v in np.asarray(r.conf)]
            else:
                # Replayed manifest hits carry calls + conf_sum only —
                # per-symbol conf is not journaled.  Say so instead of
                # silently dropping the key the client asked for.
                out["conf_unavailable"] = True
    return out


def _parse_request(line: str) -> dict:
    req = json.loads(line)
    if not isinstance(req, dict):
        raise ValueError("request must be a JSON object")
    return req


def serve_stream(
    inp: IO[str],
    out: IO[str],
    broker: RequestBroker,
    *,
    use_worker: bool = True,
    invalid_symbols: str = "skip",
) -> int:
    """Serve a line stream until EOF or ``{"op": "shutdown"}``.

    ``use_worker=True`` runs flushes on a background :class:`ServeLoop`
    (the daemon cadence: this thread's parse/encode overlaps the worker's
    device compute).  ``use_worker=False`` is the deterministic in-process
    mode (tests): flushes run inline on this thread whenever the broker
    reports ready, and the stream drains at EOF.  Returns the number of
    requests served.
    """
    from cpgisland_tpu.utils import codec

    wlock = threading.Lock()
    served = [0]
    want_conf: dict[int, bool] = {}

    def write(obj: dict) -> None:
        with wlock:
            out.write(json.dumps(obj) + "\n")
            out.flush()

    def on_result(r: ServeResult) -> None:
        served[0] += 1
        write(result_to_wire(
            r, backpressure=broker.backpressure(),
            want_conf=want_conf.pop(r.id, False),
        ))

    loop = ServeLoop(broker, on_result).start() if use_worker else None
    try:
        for line in inp:
            line = line.strip()
            if not line:
                continue
            try:
                req = _parse_request(line)
            except (ValueError, json.JSONDecodeError) as e:
                write({"ok": False, "error": f"bad request line: {e}"})
                continue
            op = req.get("op")
            if op == "shutdown":
                # Stop admission now; everything already admitted is still
                # served by the drain below.  Closing the broker is what
                # the socket accept loop watches for.
                broker.close()
                break
            if op == "stats":
                write({"ok": True, "stats": broker.stats()})
                continue
            try:
                rid = int(req["id"])
                kind = req["kind"]
                seq = req["seq"]
                # Host-side encode on THIS thread — the work that overlaps
                # the worker loop's device compute.
                symbols = codec.encode(seq, invalid=invalid_symbols)
                # Flag BEFORE submit (the worker thread may deliver the
                # result immediately after submit returns), but roll back
                # on rejection so a refused id can't leak the flag onto a
                # later reuse of that id.  Only THIS request's flag is
                # rolled back: a rejected duplicate id must not clobber
                # the flag an earlier still-queued request set.
                this_wants = bool(req.get("want_conf"))
                had_flag = want_conf.get(rid, False)
                if this_wants:
                    want_conf[rid] = True
                try:
                    broker.submit(
                        request_id=rid,
                        tenant=str(req.get("tenant", "default")),
                        kind=kind,
                        symbols=symbols,
                        name=str(req.get("name", f"req{rid}")),
                    )
                except BaseException:
                    if this_wants and not had_flag:
                        want_conf.pop(rid, None)
                    raise
            except Backpressure as e:
                write({
                    "id": req.get("id"), "ok": False,
                    "error": f"Backpressure: {e}", "reason": e.reason,
                    "backpressure": True,
                })
            except (KeyError, ValueError, TypeError) as e:
                write({
                    "id": req.get("id"), "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                    "backpressure": broker.backpressure(),
                })
            if loop is None:
                while broker.flush_ready():
                    for r in broker.flush_once():
                        on_result(r)
    finally:
        if loop is not None:
            loop.stop()
        # EOF / shutdown / connection death: serve everything already
        # admitted.  Draining in the finally keeps the shared broker's
        # queue empty even when THIS stream dies mid-read (socket mode
        # reuses one broker across connections — a skipped drain would
        # flush a dead client's requests into the NEXT client's stream).
        # Results a dead stream can no longer accept are completed and
        # dropped.
        for r in broker.drain():
            try:
                on_result(r)
            except (OSError, ValueError):
                # Broken pipe / closed makefile: keep draining so no
                # request leaks past this connection.
                log.warning("serve: dropping result for request %s "
                            "(client stream closed)", r.id)
    return served[0]


def _build_broker(args, params) -> RequestBroker:
    """CLI args -> Session + RequestBroker (the ONE construction shared by
    the stdio and socket servers)."""
    from cpgisland_tpu.serve.broker import BrokerConfig
    from cpgisland_tpu.serve.session import Session

    session = Session(
        params,
        engine=args.engine,
        island_engine=args.island_engine,
        island_cap=args.island_cap,
        integrity_check=args.integrity_check,
        name="serve",
        private_breaker=True,
    )
    config = BrokerConfig(
        flush_symbols=args.flush_symbols,
        flush_deadline_s=args.flush_deadline_ms / 1e3,
        tenant_max_requests=args.tenant_max_requests,
        tenant_max_symbols=args.tenant_max_symbols,
        min_len=args.min_len,
        island_states=args.island_states,
    )
    return RequestBroker(
        session, config,
        manifest_path=args.manifest, resume=args.resume,
    )


def serve_main(args, params) -> int:
    """The ``cpgisland serve`` entry: stdio JSONL by default, a local
    AF_UNIX socket server with ``--socket PATH`` (one JSONL connection at
    a time per client thread, all feeding the one broker)."""
    import sys

    broker = _build_broker(args, params)
    try:
        if not args.socket:
            n = serve_stream(
                sys.stdin, sys.stdout, broker,
                invalid_symbols=args.invalid_symbols,
            )
            log.info("serve: %d request(s) served", n)
            return 0
        return _serve_socket(args, broker)
    finally:
        broker.close()


def _serve_socket(args, broker: RequestBroker) -> int:
    """Sequential AF_UNIX JSONL server: one client connection at a time,
    each served by :func:`serve_stream` against the ONE warm broker — the
    broker's flush-executing consumer must stay single (same rule as the
    pipeline supervisor), and serial connections keep that invariant
    without a response-routing mux.  The daemon stays warm across
    connections; ``{"op": "shutdown"}`` from any client stops the server
    after its stream drains."""
    import os
    import socket

    path = args.socket
    if os.path.exists(path):
        os.unlink(path)
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(path)
    srv.listen(8)
    srv.settimeout(0.5)
    log.info("serve: listening on %s (JSONL; send {\"op\": \"shutdown\"} "
             "to stop)", path)
    try:
        while not broker.closed:
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            with conn:
                rf = conn.makefile("r", encoding="utf-8")
                wf = conn.makefile("w", encoding="utf-8")
                try:
                    serve_stream(
                        rf, wf, broker, use_worker=True,
                        invalid_symbols=args.invalid_symbols,
                    )
                except Exception:
                    log.exception("serve: client connection failed")
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
        if os.path.exists(path):
            os.unlink(path)
    return 0
