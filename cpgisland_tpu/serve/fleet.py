"""Fleet fault domains: a multi-device serve pool with health-probed failover.

The reference's whole fault-tolerance story came from Hadoop MapReduce: a
failed task transparently re-executed on another node, so one bad machine
never killed a run (SURVEY.md §0).  The daemon built in PRs 8-9 had the
OPPOSITE shape — strong dispatch-level resilience (supervisor retries,
breakers, sentinel, manifests) but exactly ONE worker loop driving ONE
device: a single fault domain where a wedged device stalls every tenant.
This module is Hadoop's node-level story rebuilt at device granularity:

- :class:`DevicePool` — one cloned session set (``Session.for_device``:
  private breaker, own supervisor, own prepared-stream handle, own island
  cap) + one flush worker thread per local device, all draining the ONE
  existing :class:`~cpgisland_tpu.serve.broker.RequestBroker`.  Each
  worker pins its dispatches with ``jax.default_device``; the flat
  reset-step stream is geometry-independent (ROADMAP:93), so any device
  can take any flush with bit-identical results and ZERO new kernels.
  (Span-scale records still run the shared whole-mesh programs — the
  worker is the fault domain being isolated, not mesh membership.)
- :class:`DeviceHealth` — a per-device state machine (healthy -> suspect
  -> quarantined -> half-open probe -> restored) fed by the supervisor
  ``monitor`` hook, i.e. by the signals that already exist:
  ``dispatch_fault`` attempts, sentinel
  :class:`~cpgisland_tpu.resilience.sentinel.PhantomResult` detections,
  and the ``dispatch_slow`` escalation.  A slow device is QUARANTINED,
  never killed — the never-kill rule (CLAUDE.md: killing a JAX process
  mid-TPU-execution wedges the relay's tunnel claim) is load-bearing: the
  slow attempt always runs to completion and its results are delivered;
  only FUTURE flushes route away.
- **Flush failover** — a flush whose device faults past the supervisor's
  retry budget (device-shaped errors: the retryable RuntimeError/
  TimeoutError set) is requeued INTACT onto another device before any
  completion is journaled or accounting runs (the broker's
  take/run/finish split).  Requeues are ledger-counted
  (``flush_requeued`` obs events), bounded (``max_requeues``), and
  exclusion-tracked so the faulting device does not immediately take its
  own flush back; the target re-preps any prepared streams against ITS
  handles (per-device by construction — counted by the prepared cache,
  never silent).  Per-request isolation is preserved: a poisoned REQUEST
  (ValueError/TypeError) fails alone on whatever device runs it; a
  poisoned DEVICE moves its whole flush.

Thread contract (graftsync Layer 4): any thread submits to the broker; N
device workers are each a single dispatcher for THEIR session set.  Pool
state (the requeue deque, counters) lives under ``DevicePool._lock``;
each DeviceHealth has its own leaf lock; neither is ever held across
broker calls or dispatches.  Lock order: pool -> health (stats snapshot),
health -> obs (event emission, the breaker's existing shape).

graftfault (``resilience/faultplan.py``) drives all of the above
deterministically in CI: plans target devices through the supervisor tag
(session names embed the device label), and the chaos matrix asserts
bit-identity against the fault-free run with zero dropped admitted
requests.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

from cpgisland_tpu import obs
from cpgisland_tpu.obs import ledger as ledger_mod
from cpgisland_tpu.obs import scope as scope_mod
from cpgisland_tpu.resilience.sentinel import PhantomResult
from cpgisland_tpu.serve.broker import RequestBroker
from cpgisland_tpu.serve.session import ModelRegistry
from cpgisland_tpu.utils import profiling

log = logging.getLogger(__name__)

__all__ = ["DeviceHealth", "DevicePool", "FleetConfig"]

HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBING = "probing"


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Health/failover policy for one :class:`DevicePool`.

    ``fault_threshold``: consecutive device-shaped faults that quarantine
    (the supervisor's per-attempt ``record_fault`` feeds this, so one unit
    exhausting its retry budget is enough at the default).
    ``phantom_threshold``: sentinel phantom detections quarantine sooner —
    a device serving stale results is worse than one failing loudly.
    ``slow_threshold``: ``dispatch_slow`` escalations (attempts past the
    retry policy's ``slow_attempt_s``) that quarantine; the attempts
    themselves always run to completion (never-kill).
    ``cooldown_s``: quarantine length before a half-open probe flush is
    admitted; ``now_fn`` makes the cooldown deterministic in tests (and is
    forwarded to the per-device sessions' private breakers).
    ``max_requeues``: failover budget per flush — past it the flush's
    failures are DELIVERED (loudly) instead of bouncing forever.  The
    budget also bounds the cost of a DETERMINISTIC request-shaped
    RuntimeError that masquerades as a device fault (e.g. a record that
    OOMs on every device): at most ``max_requeues`` extra flush
    executions, then its failure is delivered and its co-batched
    successes stand — the same attempt-budget shape Hadoop used for the
    identical ambiguity.  ``requeue_horizon_s``: a flush is only requeued
    if some non-excluded device could serve within this window (otherwise
    failures are delivered rather than parking behind, say, an operator
    drain with an effectively-infinite cooldown).

    All strike thresholds count CONSECUTIVE evidence: any fast healthy
    dispatch resets fault, phantom, and slow strikes alike — isolated
    transients days apart can never accumulate into a quarantine.
    """

    fault_threshold: int = 3
    phantom_threshold: int = 2
    slow_threshold: int = 2
    cooldown_s: float = 30.0
    max_requeues: int = 2
    requeue_horizon_s: float = 300.0
    idle_wait_s: float = 0.05
    quarantine_poll_s: float = 0.05
    now_fn: Callable[[], float] = time.monotonic


class DeviceHealth:
    """Per-device health state machine (see module docstring).

    Implements the supervisor ``monitor`` contract (``record_fault`` /
    ``record_slow`` / ``record_success``), so a session cloned with this
    as its monitor feeds it from every supervised dispatch.  All state is
    guarded by ``_lock`` (a leaf except for obs event emission — the same
    shape as the engine breaker's).  ``can_serve`` is consulted only by
    the owning device's worker thread, so the half-open probe admission
    (one flush) needs no cross-thread token.
    """

    def __init__(
        self,
        label: str,
        *,
        fault_threshold: int = 3,
        phantom_threshold: int = 2,
        slow_threshold: int = 2,
        cooldown_s: float = 30.0,
        now_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        self.label = label
        self.fault_threshold = int(fault_threshold)
        self.phantom_threshold = int(phantom_threshold)
        self.slow_threshold = int(slow_threshold)
        self.cooldown_s = float(cooldown_s)
        self.now_fn = now_fn
        self._lock = threading.Lock()
        self._state = HEALTHY
        self._consecutive_faults = 0
        self._phantom_strikes = 0
        self._slow_strikes = 0
        self._quarantined_at: Optional[float] = None
        self.quarantines = 0
        self.restores = 0

    # -- supervisor monitor contract ----------------------------------------

    def record_fault(self, error: Optional[BaseException] = None) -> None:
        with self._lock:
            self._consecutive_faults += 1
            phantom = isinstance(error, PhantomResult)
            if phantom:
                self._phantom_strikes += 1
            if self._state == PROBING:
                self._quarantine_locked("probe_failed", error)
            elif self._state == QUARANTINED:
                pass  # already out of rotation; nothing escalates further
            elif self._consecutive_faults >= self.fault_threshold:
                self._quarantine_locked("faults", error)
            elif phantom and self._phantom_strikes >= self.phantom_threshold:
                self._quarantine_locked("phantom", error)
            else:
                self._state = SUSPECT

    def record_slow(self, wall_s: float) -> None:
        """A dispatch that SUCCEEDED but blew past the slow-attempt wall
        (the supervisor calls this INSTEAD of record_success for slow
        attempts, so slow strikes count CONSECUTIVE slow dispatches — a
        fast success in between resets them, and CLAUDE.md's occasional
        transient slowdown can never accumulate across days into a
        quarantine)."""
        with self._lock:
            self._consecutive_faults = 0  # the dispatch did succeed
            if self._state == QUARANTINED:
                return
            if self._state == PROBING:
                # A probe that crawls home is not a recovery: re-quarantine
                # for a fresh cooldown rather than restoring a device that
                # is still degraded.
                self._quarantine_locked("slow", None, wall_s=wall_s)
                return
            self._slow_strikes += 1
            if self._slow_strikes >= self.slow_threshold:
                # QUARANTINE instead of killing: the slow attempt already
                # ran to completion (never-kill rule) and its results are
                # delivered — only future flushes route away.
                self._quarantine_locked("slow", None, wall_s=wall_s)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_faults = 0
            # Every strike family is consecutive-evidence, not a lifetime
            # counter: a healthy fast dispatch clears them all.
            self._phantom_strikes = 0
            self._slow_strikes = 0
            if self._state == PROBING:
                self._state = HEALTHY
                self._quarantined_at = None
                self.restores += 1
                obs.event(
                    "device_restored", device=self.label,
                    quarantines=self.quarantines,
                )
                # graftscope flight recorder: health transitions are
                # postmortem-load-bearing.  Recorder lock is a leaf —
                # same health -> telemetry order as the obs event above.
                scope_mod.record("device_restored", device=self.label,
                                 quarantines=self.quarantines)
                log.info(
                    "fleet: device %s restored (half-open probe flush "
                    "succeeded)", self.label,
                )
            elif self._state == SUSPECT:
                self._state = HEALTHY

    # -- worker-side gating ---------------------------------------------------

    def can_serve(self) -> bool:
        """May the owning worker take a flush now?  After the cooldown the
        state flips quarantined -> probing and the NEXT flush the worker
        takes is the probe (whose success/fault then restores or
        re-quarantines).  PROBING keeps answering True: only the owning
        thread consults its own health, and it runs one flush at a time,
        so a single probe is structural — returning False here instead
        would park a probing worker forever when the queue happened to be
        empty at flip time."""
        with self._lock:
            if self._state in (HEALTHY, SUSPECT, PROBING):
                return True
            if (
                self._quarantined_at is not None
                and self.now_fn() - self._quarantined_at >= self.cooldown_s
            ):
                self._state = PROBING
                log.info(
                    "fleet: device %s cooldown elapsed; admitting a "
                    "half-open probe flush", self.label,
                )
                return True
            return False

    def force_quarantine(self, reason: str = "operator") -> None:
        """Pull a device out of rotation directly (ops drain hook; tests
        use it to stage deterministic failover scenarios)."""
        with self._lock:
            if self._state != QUARANTINED:
                self._quarantine_locked(reason, None)

    def _quarantine_locked(self, reason: str, error, *,
                           wall_s: Optional[float] = None) -> None:
        # _locked suffix: callers hold self._lock (the graftsync convention).
        self._state = QUARANTINED
        self._quarantined_at = self.now_fn()
        self.quarantines += 1
        faults = self._consecutive_faults
        self._consecutive_faults = 0
        self._phantom_strikes = 0
        self._slow_strikes = 0
        obs.event(
            "device_quarantined",
            device=self.label,
            reason=reason,
            consecutive_faults=faults,
            cooldown_s=self.cooldown_s,
            wall_s=None if wall_s is None else round(wall_s, 3),
            error=(f"{type(error).__name__}: {error}"[:200] if error else None),
        )
        scope_mod.record(
            "device_quarantined", device=self.label, reason=reason,
            consecutive_faults=faults, cooldown_s=self.cooldown_s,
        )
        log.warning(
            "fleet: device %s QUARANTINED (%s) for %.0f s; its flushes "
            "requeue onto healthy devices, a half-open probe follows the "
            "cooldown", self.label, reason, self.cooldown_s,
        )

    def eta_s(self) -> float:
        """Seconds until this device could plausibly serve again: 0 while
        healthy/suspect/probing, the remaining cooldown while quarantined.
        The pool's requeue eligibility check — a flush must never be
        parked behind a device that is effectively gone (an operator
        drain with a huge cooldown)."""
        with self._lock:
            if self._state != QUARANTINED or self._quarantined_at is None:
                return 0.0
            return max(
                0.0,
                self.cooldown_s - (self.now_fn() - self._quarantined_at),
            )

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_faults": self._consecutive_faults,
                "phantom_strikes": self._phantom_strikes,
                "slow_strikes": self._slow_strikes,
                "quarantines": self.quarantines,
                "restores": self.restores,
            }


@dataclasses.dataclass
class _PendingFlush:
    """A taken-but-unfinished flush riding the failover queue."""

    batch: list
    t_taken: float
    excluded: set = dataclasses.field(default_factory=set)
    requeues: int = 0


class _DeviceWorker:
    """One device's flush loop: a clone of the ServeLoop cadence with
    health gating in front and the requeue queue ahead of the broker."""

    def __init__(self, pool: "DevicePool", idx: int, device, label: str,
                 registry: ModelRegistry, health: DeviceHealth) -> None:
        self.pool = pool
        self.idx = idx
        self.device = device
        self.label = label
        self.registry = registry
        self.health = health
        self.flushes = 0  # this device's finished flushes (stats; own thread)
        self._timer = profiling.PhaseTimer()  # per-worker: no shared-timer race
        self._thread = threading.Thread(
            target=self._run_guarded, name=f"cpgisland-fleet-{label}",
            daemon=True,
        )

    def _run_guarded(self) -> None:
        # Unhandled worker death is a postmortem event: persist the flight
        # recorder before the thread dies (daemon threads leave no
        # traceback artifact otherwise), then re-raise.
        try:
            self._run()
        except BaseException as e:
            scope_mod.on_worker_death(self.label, e)
            raise

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout)

    # graftcheck: hot-path
    def _run(self) -> None:
        pool = self.pool
        broker = pool.broker
        cfg = pool.config
        while not pool._stop.is_set() and not broker.closed:
            if not self.health.can_serve():
                # Parked out of rotation: bounded wait so cooldown expiry
                # (possibly on an injected clock) is picked up promptly.
                with pool._cv:
                    pool._cv.wait(cfg.quarantine_poll_s)
                continue
            pf = pool._take_requeued(self)
            if pf is None:
                if not broker.poll_flush(cfg.idle_wait_s):
                    continue
                replayed, batch, t_taken = broker.take_flush()
                if replayed:
                    # Manifest replays carry no device work — finish and
                    # deliver them immediately, whatever happens to the
                    # batch next.
                    for r in broker.finish_flush(list(replayed), []):
                        pool._deliver(r)
                if not batch:
                    continue
                pf = _PendingFlush(batch, t_taken)
            self._execute(pf)
        log.debug("fleet: worker %s exiting", self.label)

    # graftcheck: hot-path
    def _execute(self, pf: _PendingFlush) -> None:
        import jax

        pool = self.pool
        broker = pool.broker
        was_probing = self.health.state() == PROBING
        try:
            # Pin this worker's dispatches to ITS device (thread-local
            # config: concurrent workers don't interfere).  The flat
            # stream is geometry-independent — any device, same bits.
            # device_scope attributes this thread's ledger counts + obs
            # events to this device (fleet attribution, thread-local);
            # host_scope adds the routing-tier host one level up (a no-op
            # outside a router, where pool.host_label stays "").
            with ledger_mod.host_scope(pool.host_label), \
                    ledger_mod.device_scope(self.label), \
                    jax.default_device(self.device):
                results = broker.run_batch(
                    pf.batch, pf.t_taken,
                    registry=self.registry, timer=self._timer,
                    device=self.label,
                )
        except Exception as e:
            # Flush-LEVEL failure (broker internals — per-request units
            # are isolated inside run_batch).  Treat like a device fault:
            # try another device, else fail the requests loudly (admitted
            # requests are never dropped).
            log.exception(
                "fleet: flush-level failure on %s", self.label
            )
            self.health.record_fault(e)
            results = broker.fail_batch(pf.batch, pf.t_taken, e)
        faulted = [r for r in results if r.fault]
        if faulted and pool._offer_requeue(pf, self):
            obs.event(
                "flush_requeued",
                device=self.label,
                n_requests=len(pf.batch),
                n_faulted=len(faulted),
                symbols=int(sum(r.symbols.size for r in pf.batch)),
                requeue=pf.requeues,
                error=(faulted[0].error or "")[:200],
            )
            # graftscope: the failover decision, per affected request (the
            # lineage hop) and as one recorder event naming the ids.
            for req in pf.batch:
                scope_mod.hop(
                    req.id, "requeue", device=self.label,
                    requeue=pf.requeues, n_faulted=len(faulted),
                )
            scope_mod.record(
                "flush_requeued", device=self.label,
                requeue=pf.requeues, n_faulted=len(faulted),
                request_ids=[req.id for req in pf.batch[:64]],
                error=(faulted[0].error or "")[:200],
            )
            log.warning(
                "fleet: requeueing flush of %d request(s) off %s "
                "(%d device-shaped failure(s); requeue %d/%d) — the "
                "target device re-preps against its own stream handles",
                len(pf.batch), self.label, len(faulted), pf.requeues,
                pool.config.max_requeues,
            )
            return
        if was_probing and not faulted:
            # A probe flush with no supervised unit (e.g. all-empty
            # records) would otherwise leave the state machine parked in
            # PROBING; a fault-free probe is a success by definition.
            self.health.record_success()
        for r in broker.finish_flush(results, pf.batch):
            pool._deliver(r)
        self.flushes += 1


class DevicePool:
    """One Session set + flush worker per local device under ONE broker
    (see module docstring).  ``start(on_result)``/``stop()`` mirror the
    single-loop :class:`~cpgisland_tpu.serve.worker.ServeLoop` so the
    transport layer swaps one for the other."""

    def __init__(self, broker: RequestBroker, devices,
                 config: Optional[FleetConfig] = None) -> None:
        if not devices:
            raise ValueError("DevicePool needs at least one device")
        self.broker = broker
        self.config = config if config is not None else FleetConfig()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._requeued: collections.deque = collections.deque()
        self._stop = threading.Event()
        self.on_result: Optional[Callable] = None
        # Host identity under a routing tier (serve/router.py): stamps the
        # per-host ledger scope around every worker's flush execution.
        # "" (no router) = legacy attribution, host_scope no-ops.
        self.host_label = ""
        self.requeues = 0  # guarded by _lock
        self.failed_over = 0  # flushes delivered after >=1 requeue (guarded)
        cfg = self.config
        self.workers: list = []
        for i, dev in enumerate(devices):
            label = f"dev{i}"
            health = DeviceHealth(
                label,
                fault_threshold=cfg.fault_threshold,
                phantom_threshold=cfg.phantom_threshold,
                slow_threshold=cfg.slow_threshold,
                cooldown_s=cfg.cooldown_s,
                now_fn=cfg.now_fn,
            )
            registry = self._registry_for(broker.registry, label, health)
            self.workers.append(
                _DeviceWorker(self, i, dev, label, registry, health)
            )

    @classmethod
    def build(cls, broker: RequestBroker, n_devices: Optional[int] = None,
              config: Optional[FleetConfig] = None) -> "DevicePool":
        """Pool over the first ``n_devices`` local devices (None = all)."""
        import jax

        devs = jax.local_devices()
        if n_devices is not None:
            if n_devices < 1 or n_devices > len(devs):
                raise ValueError(
                    f"--fleet {n_devices}: have {len(devs)} local device(s)"
                )
            devs = devs[:n_devices]
        return cls(broker, devs, config=config)

    def _registry_for(self, registry: ModelRegistry, label: str,
                      health: DeviceHealth) -> ModelRegistry:
        """Clone the broker's registry for one device: every session gets
        a device-scoped twin whose supervisor feeds this device's health."""
        cfg = self.config
        default = registry.default.for_device(
            label, monitor=health, now_fn=cfg.now_fn
        )
        reg = ModelRegistry(default)
        for name, member, sess in registry.entries():
            reg.register(
                member,
                session=sess.for_device(
                    label, monitor=health, now_fn=cfg.now_fn
                ),
            )
        return reg

    # -- lifecycle ------------------------------------------------------------

    def start(self, on_result: Callable) -> "DevicePool":
        self.on_result = on_result
        for w in self.workers:
            w.start()
        log.info(
            "fleet: device pool started (%d device(s): %s)",
            len(self.workers), ", ".join(w.label for w in self.workers),
        )
        return self

    def stop(self, join: bool = True) -> None:
        """Stop the workers; any flush still riding the failover queue is
        finished INLINE on this thread (single consumer again) so no
        admitted request is dropped at shutdown."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        # Wake workers parked on the broker's flush condition.
        with self.broker._cv:
            self.broker._cv.notify_all()
        if join:
            for w in self.workers:
                w.join()
        while True:
            with self._lock:
                pf = self._requeued.popleft() if self._requeued else None
            if pf is None:
                break
            try:
                results = self.broker.run_batch(pf.batch, pf.t_taken)
            except Exception as e:
                log.exception("fleet: shutdown drain of a requeued flush "
                              "failed")
                results = self.broker.fail_batch(pf.batch, pf.t_taken, e)
            for r in self.broker.finish_flush(results, pf.batch):
                self._deliver(r)

    def close(self) -> None:
        """Release every per-device session's prepared-stream entries
        (the pool owns its clones; the broker's own registry belongs to
        the caller)."""
        for w in self.workers:
            w.registry.close()
            w.registry.default.close()

    # -- failover plumbing ----------------------------------------------------

    def _offer_requeue(self, pf: _PendingFlush, worker: _DeviceWorker) -> bool:
        """Requeue ``pf`` off ``worker`` if the failover budget allows AND
        some other device could plausibly take it within one cooldown
        window; False = deliver the failures instead (loudly — a flush
        must never park behind a fleet with no coming-back device, e.g.
        an operator drain with an effectively-infinite cooldown)."""
        # Eligibility computed OUTSIDE the pool lock (health locks are
        # their own leaves): ``eligible`` = any OTHER device that could
        # serve within the horizon; ``takers`` = the not-yet-excluded
        # subset.
        excluded = pf.excluded | {worker.idx}
        horizon = self.config.requeue_horizon_s
        eligible = [
            w for w in self.workers
            if w.idx != worker.idx and w.health.eta_s() <= horizon
        ]
        takers = [w for w in eligible if w.idx not in excluded]
        with self._cv:
            if len(self.workers) < 2 or not eligible:
                return False
            if pf.requeues >= self.config.max_requeues:
                return False
            if takers:
                pf.excluded = excluded
            else:
                # Every eligible device has had (and fumbled) this flush —
                # the fault may be transient; keep only the freshest
                # faulter excluded so the bounded budget, not the
                # exclusion set, decides when to stop.
                pf.excluded = {worker.idx}
            pf.requeues += 1
            self._requeued.append(pf)
            self.requeues += 1
            if pf.requeues == 1:
                self.failed_over += 1  # distinct flushes that failed over
            self._cv.notify_all()
        # Wake workers parked on the broker condition so the requeued
        # flush is picked up without waiting out an idle poll.
        with self.broker._cv:
            self.broker._cv.notify_all()
        return True

    def _take_requeued(self, worker: _DeviceWorker):
        with self._lock:
            for i, pf in enumerate(self._requeued):
                if worker.idx not in pf.excluded:
                    del self._requeued[i]
                    return pf
        return None

    def _deliver(self, r) -> None:
        cb = self.on_result
        if cb is None:
            return
        try:
            cb(r)
        except Exception:
            log.exception("fleet: on_result failed for request %s", r.id)

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            requeues = self.requeues
            failed_over = self.failed_over
            pending_requeued = len(self._requeued)
        return {
            "devices": {
                w.label: dict(w.health.snapshot(), flushes=w.flushes)
                for w in self.workers
            },
            "requeues": requeues,
            "failed_over": failed_over,
            "pending_requeued": pending_requeued,
        }
