"""Request broker: admission control + continuous flat-stream batching.

The broker is the daemon's core and is deliberately TRANSPORT-FREE: tests,
the graftcheck dispatch-stability contract, and bench's serve phase all
drive it in-process; ``serve/transport.py`` is a thin wire layer on top.

Flow: clients :meth:`RequestBroker.submit` decode/posterior requests
(already-encoded symbol arrays — the transport does parse/encode on ITS
thread, which is what overlaps host work with device compute).  Admission
enforces per-tenant queue caps and rejects with :class:`Backpressure`.
Queued requests coalesce into a FLUSH under a bounded-latency policy:
flush when the queued symbols reach ``flush_symbols`` OR the oldest
request has waited ``flush_deadline_s``, whichever first.  One flush is
one obs span and (for batch-eligible decode requests under the onehot
engine) ONE flat reset-step decode stream — heterogeneous records
concatenate with rank-one RESET steps via the shared
``pipeline._decode_small_batch`` / ``viterbi_onehot.decode_batch_flat``
machinery, so the daemon's batching is the SAME code the batch CLI runs
and cannot diverge from it.  Records outside the flat path's domain route
per the existing host-entry rules: pad-FIRST/empty records demote to the
single-record dense path, and a record larger than the decode span
processes span-wise (``viterbi_sharded_spans``) without starving the
queue — it is one flush entry like any other.

Fault domain: every blocking unit runs under the session's PR 5 dispatch
supervisor, and faults feed the SESSION's breaker — one tenant session's
kernel faults demote engines for that session only.  Under a device pool
(``serve/fleet.py``) the flush cycle splits into :meth:`RequestBroker.
take_flush` / :meth:`run_batch` / :meth:`finish_flush` so a flush whose
DEVICE faults past the retry budget can be requeued intact onto a healthy
device before completion is journaled or accounting runs;
:meth:`flush_once` remains the single-consumer composition of the three.

Restart story: with ``manifest_path`` the manifest is a TWO-PHASE
admission journal.  Phase 1: ``submit`` journals every accepted request
(an ``admit`` line with the re-executable payload) BEFORE it becomes
visible to any flush consumer — write-ahead, so "submit returned" implies
"journaled".  Phase 2: ``finish_flush`` journals the completion.  A
restarted daemon (``resume=True``) replays completed requests
bit-identically without touching the device AND re-queues every
admitted-but-incomplete request for re-execution (``journal_replay``
event) — no accepted request is ever silently dropped.  A re-executed
request's id is released back to replay-eligibility on completion, so a
reconnecting client that re-submits it gets the manifest replay (while it
is still executing it gets the duplicate-id rejection and backs off —
see ``tools/serve_client.py``).
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import logging
import threading
import time
from typing import Optional

import numpy as np

from cpgisland_tpu import obs
from cpgisland_tpu import pipeline
from cpgisland_tpu.obs import scope as scope_mod
from cpgisland_tpu.obs.metrics import Histogram
from cpgisland_tpu.ops import islands as islands_mod
from cpgisland_tpu.ops.islands import IslandCalls
from cpgisland_tpu.resilience import faultplan
from cpgisland_tpu.serve.session import ModelRegistry, Session
from cpgisland_tpu.utils import profiling

log = logging.getLogger(__name__)

KINDS = ("decode", "posterior", "compare")

# Device-shaped error classes: mirrors RetryPolicy.retryable's defaults
# (RuntimeError covers jaxlib's XlaRuntimeError and PhantomResult) — the
# ONE copy both failure-classification sites consult, so the fleet's
# requeue trigger and the supervisor's retry set cannot drift casually.
# (A custom RetryPolicy.retryable is not consulted here: exotic retryable
# types simply don't trigger failover, which is the safe direction.)
FAULT_SHAPED = (RuntimeError, TimeoutError)


class Backpressure(RuntimeError):
    """Admission rejected a request (queue caps).  ``reason`` is the
    machine-readable cause the transport surfaces to the client;
    ``retry_after_s`` is a queue-depth-derived backoff hint (how long the
    currently queued symbols should take to drain) so a reconnecting
    client can back off instead of hot-looping on a saturated fleet."""

    def __init__(self, msg: str, reason: str,
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class BrokerConfig:
    """Flush policy + admission limits (all symbol counts in symbols).

    ``flush_symbols``: the flush budget — a flush closes when the queued
    symbols reach it.  ``flush_deadline_s``: bounded latency — a flush
    also closes when the OLDEST queued request has waited this long, even
    if the budget is not met.  A single request larger than the budget is
    admitted (up to the span-path limits) and forms its own flush entry —
    oversized records must not starve the queue.

    ``tenant_max_requests`` / ``tenant_max_symbols``: per-tenant queue
    caps; admission past either raises :class:`Backpressure`.

    ``decode_span``: records beyond it decode span-wise (exact,
    boundary-messaged — pipeline.CLEAN_DECODE_SPAN semantics).
    ``posterior_span``: posterior requests beyond it are REJECTED at
    admission (span-threaded soft decoding stays a batch-CLI workload).

    ``min_len`` / ``island_states``: island-calling config, broker-wide
    (the same knobs the decode/posterior CLIs take per run).

    ``stacked``: multi-model kernel occupancy (ROADMAP item 2) — compare
    flushes group reduced members into ONE stacked launch set
    (family.stacked; per-member results bit-identical to the sequential
    arm), and batch-eligible decode requests of DIFFERENT onehot models
    coalesce into one stacked flat stream (one launch set instead of one
    per model; per-record paths equal the sequential flush modulo the flat
    decoder's pinned rounding-tie contract).  False = the sequential
    per-model arm everywhere — the A/B escape hatch, same pattern as the
    kernel-level ``fused``/``stacked`` flags.  The ``None`` default
    consults the graftune winner table (``stacked.serve_decode``) at
    config construction and falls back to the shipped True; an explicit
    bool always wins.
    """

    flush_symbols: int = 8 << 20
    flush_deadline_s: float = 0.05
    tenant_max_requests: int = 256
    tenant_max_symbols: int = 512 << 20
    decode_span: int = pipeline.CLEAN_DECODE_SPAN
    posterior_span: int = pipeline.POSTERIOR_SPAN
    min_len: Optional[int] = None
    island_states: Optional[tuple] = None
    stacked: Optional[bool] = None

    def __post_init__(self):
        if self.stacked is None:
            from cpgisland_tpu import tune

            # frozen dataclass: resolve the consulted default in place.
            object.__setattr__(
                self, "stacked", tune.default_stacked("serve_decode")
            )


@dataclasses.dataclass
class ServeRequest:
    id: int
    tenant: str
    kind: str  # "decode" | "posterior" | "compare"
    name: str
    symbols: np.ndarray  # uint8 encoded symbols (codec.encode output)
    t_submit: float = 0.0
    # Named-model routing (ModelRegistry): "" = the daemon's default model.
    model: str = ""
    # compare only: the member names to evaluate (validated at admission).
    models: tuple = ()


@dataclasses.dataclass
class ServeResult:
    id: int
    tenant: str
    kind: str
    ok: bool = True
    calls: Optional[IslandCalls] = None
    conf: Optional[np.ndarray] = None  # posterior only (float32 per symbol)
    conf_sum: Optional[float] = None  # exact f64 sum of conf
    # compare only: {"baseline": ..., "models": {name: {"loglik", "log_odds",
    # "islands"}}} — the winner track rides in ``calls``.
    compare: Optional[dict] = None
    n_symbols: int = 0
    queue_s: float = 0.0  # submit -> taken into a flush
    serve_s: float = 0.0  # the flush's wall (shared by its requests)
    route: str = ""  # flat | record | span | posterior | replay
    error: Optional[str] = None
    replayed: bool = False
    # Failed with a DEVICE-shaped error (the supervisor's retryable set,
    # past its budget) — the fleet's requeue trigger.  A request-shaped
    # failure (ValueError/TypeError: malformed record, bad model) keeps
    # fault=False and fails alone wherever it runs.
    fault: bool = False


@dataclasses.dataclass
class _Tenant:
    queued_requests: int = 0
    queued_symbols: int = 0
    requests: int = 0
    symbols: int = 0
    results: int = 0
    rejected: int = 0
    replayed: int = 0
    queue_s: float = 0.0
    wall_s: float = 0.0

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__dataclass_fields__}


class RequestBroker:
    """See module docstring.  Thread contract: any thread may ``submit``;
    ONE consumer thread (the worker loop, or a test calling
    :meth:`flush_once` / :meth:`drain`) executes flushes — same
    single-dispatcher rule as the pipeline's supervisor."""

    def __init__(
        self,
        session: Session,
        config: Optional[BrokerConfig] = None,
        *,
        registry: Optional[ModelRegistry] = None,
        manifest_path: Optional[str] = None,
        resume: bool = False,
    ) -> None:
        self.session = session
        # Named-model routing: requests carrying model= resolve their
        # session here; the bare default registry serves the single-model
        # daemon byte-identically.
        self.registry = registry if registry is not None else ModelRegistry(session)
        if self.registry.default is not session:
            raise ValueError(
                "registry.default must be the broker's session (the "
                "model='' route)"
            )
        self.config = config if config is not None else BrokerConfig()
        params = session.params
        if self.config.island_states is None:
            err = pipeline.island_layout_error(params, None)
            if err:
                raise ValueError(err)
            self._post_states: tuple = tuple(range(params.n_symbols))
        else:
            self._post_states = tuple(sorted(self.config.island_states))
        self._obs_based = self.config.island_states is not None
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._queued_ids: set = set()
        self._inflight_ids: set = set()
        self._queued_symbols = 0
        self._replayed: list[ServeResult] = []
        self._tenants: dict[str, _Tenant] = {}
        self._timer = profiling.PhaseTimer()
        self.flushes = 0
        self.flushed_symbols = 0
        self._closed = False
        # Host identity under a routing tier (serve/router.py): stamps the
        # flush.enter fault tag (so chaos plans can target one host's
        # flushes by match="@<label>") and the per-host ledger scope.
        self.host_label = ""
        # Measured flush wall (histogram, own leaf lock): feeds the
        # retry_after_s load-shedding hint so the backoff tracks what a
        # flush ACTUALLY costs on this host, not the static deadline.
        self._flush_wall = Histogram()
        self.manifest = None
        self._seen_ids: set = set()
        # Ids re-queued from the admission journal on restart: released
        # from _seen_ids on completion so a reconnecting client's
        # re-submission gets the manifest replay.
        self._journal_requeued: set = set()
        if manifest_path is not None:
            from cpgisland_tpu.resilience import manifest as manifest_mod

            # Same header discipline as the file pipelines: every field
            # that affects result bytes (model digest + island config) —
            # there is no source file, the request stream IS the input, so
            # per-request identity lives in each line's (id, key, size).
            self.manifest = manifest_mod.RunManifest(
                manifest_path,
                header={
                    "mode": "serve",
                    "params": manifest_mod.params_digest(params),
                    "min_len": self.config.min_len,
                    "island_states": (
                        None if self.config.island_states is None
                        else sorted(self.config.island_states)
                    ),
                },
                resume=resume,
            )
            if resume:
                self._requeue_admitted()

    def _requeue_admitted(self) -> None:
        """Restart recovery, phase-1 side: re-queue every admitted-but-
        incomplete journal entry for re-execution (no client is attached —
        results are recomputed into the manifest, where a reconnecting
        client's re-submission finds them).  Requests re-enter the queue
        directly (they already passed admission in their first life; the
        tenant caps were paid then)."""
        pending = self.manifest.admitted_incomplete()
        requeued = 0
        with self._cv:
            for rec in pending:
                pay = rec.get("payload")
                if not pay:
                    log.warning(
                        "serve journal: admit record %s has no payload; "
                        "cannot re-execute it", rec.get("index"),
                    )
                    continue
                symbols = np.frombuffer(
                    base64.b64decode(pay["symbols"]), dtype=np.uint8
                ).copy()
                req = ServeRequest(
                    id=int(rec["index"]), tenant=str(pay["tenant"]),
                    kind=str(pay["kind"]), name=str(pay["name"]),
                    symbols=symbols, t_submit=time.monotonic(),
                    model=str(pay.get("model", "")),
                )
                if self._manifest_key(req) != rec.get("name"):
                    log.warning(
                        "serve journal: admit record %s no longer matches "
                        "its key (%r vs %r); skipping re-execution",
                        req.id, self._manifest_key(req), rec.get("name"),
                    )
                    continue
                t = self._tenants.setdefault(req.tenant, _Tenant())
                t.queued_requests += 1
                t.queued_symbols += symbols.size
                t.requests += 1
                self._queue.append(req)
                self._queued_ids.add(req.id)
                self._queued_symbols += symbols.size
                self._seen_ids.add(req.id)
                self._journal_requeued.add(req.id)
                scope_mod.hop(
                    req.id, "admit", tenant=req.tenant, kind=req.kind,
                    model=req.model, n_symbols=int(symbols.size),
                    journal_requeued=True,
                )
                requeued += 1
            self._cv.notify_all()
        if requeued or pending:
            obs.event(
                "journal_replay",
                n_reexecuted=requeued,
                n_completed=self.manifest.n_completed(),
            )
            log.info(
                "serve journal: re-queued %d admitted-but-incomplete "
                "request(s) for re-execution (%d completed request(s) "
                "replay from the manifest)",
                requeued, self.manifest.n_completed(),
            )

    # -- admission -----------------------------------------------------------

    def _retry_after_locked(self) -> float:
        """Load-derived backoff hint: roughly how long the queued symbols
        take to drain at one flush per window, floored so a client never
        busy-loops and capped so it never parks forever.  The per-flush
        window is the MEASURED median flush wall once flushes have run
        (the deadline only sets when a flush OPENS; the wall is what the
        device actually pays to drain one) and falls back to the static
        deadline heuristic while the histogram is empty.  Monotone in
        queue depth for a fixed histogram state — pinned in
        tests/test_serve_router.py."""
        depth = self._queued_symbols / float(max(1, self.config.flush_symbols))
        per_flush = max(self.config.flush_deadline_s, 0.01)
        # Histogram.quantile returns 0.0 when empty — max() keeps the
        # static floor until a measured wall exists.
        per_flush = max(per_flush, self._flush_wall.quantile(0.5))
        return round(min(5.0, max(0.05, depth * per_flush)), 3)

    def queue_depth(self) -> tuple:
        """(queued requests, queued symbols) — the router's least-loaded
        ordering key.  Replay-pending results are excluded: they cost no
        device time."""
        with self._lock:
            return len(self._queue), self._queued_symbols

    def _manifest_key(self, req: ServeRequest) -> str:
        # Tenant + kind + MODEL are part of the identity: a decode
        # completion must never replay for another tenant's, a posterior,
        # or another MODEL's request.  The model segment is length-prefixed
        # so arbitrary client-chosen names (which may contain ':') cannot
        # craft a default-model key that collides with a named-model one —
        # a collision would replay island calls computed under a different
        # model's params.  (This format supersedes the pre-registry
        # 3-field keys: manifests written before the registry don't
        # replay, they just re-execute.)
        return (
            f"{req.kind}:{req.tenant}:{len(req.model)}:{req.model}:"
            f"{req.name}"
        )

    def submit(
        self,
        *,
        request_id: int,
        tenant: str,
        kind: str,
        symbols: np.ndarray,
        name: str = "",
        model: str = "",
        models=None,
    ) -> None:
        """Admit one request (raises :class:`Backpressure` on queue caps,
        RuntimeError once closed, ValueError on malformed requests —
        including an unknown ``model``/``models`` name, which is
        admission-rejected against the registry).  Results are delivered
        by the flush-executing consumer (:meth:`flush_once` / the worker
        loop)."""
        if kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
        symbols = np.ascontiguousarray(symbols, dtype=np.uint8)
        if kind == "posterior" and symbols.size > self.config.posterior_span:
            raise ValueError(
                f"posterior request of {symbols.size} symbols exceeds the "
                f"posterior span ({self.config.posterior_span}); span-"
                "threaded soft decoding is a batch workload — use the "
                "posterior CLI"
            )
        model = str(model or "")
        try:
            self.registry.session(model)  # admission: unknown model rejects
            if model and kind != "compare":
                member = self.registry.member(model)
                if member.order != 1:
                    raise ValueError(
                        f"model {model!r} consumes the pair alphabet "
                        "(order-2) — serve it through compare requests, "
                        "which keep the base stream for composition"
                    )
                if member.is_null:
                    raise ValueError(
                        f"model {model!r} is a scoring-only member (no "
                        "island states) — decode/posterior requests have "
                        "no product for it; use it in compare requests'"
                        " models=[...] as a log-odds baseline"
                    )
        except KeyError as e:
            raise ValueError(
                f"{e.args[0]}; registered: "
                f"{', '.join(self.registry.names()) or '<none>'}"
            ) from None
        models_t: tuple = ()
        if kind == "compare":
            if model:
                raise ValueError(
                    "compare requests take models=[...] (the member set), "
                    "not model="
                )
            if models is not None and not isinstance(models, (list, tuple)):
                # A JSON string would iterate char-wise into baffling
                # "unknown model 'd'" rejects — demand an array.
                raise ValueError(
                    f"models must be a list of member names, got "
                    f"{type(models).__name__}"
                )
            models_t = tuple(str(m) for m in (models or ()))
            if not models_t:
                raise ValueError("compare requests need models=[...]")
            if len(set(models_t)) != len(models_t):
                raise ValueError(f"duplicate names in models={list(models_t)}")
            for m in models_t:
                try:
                    self.registry.member(m)  # needs full member metadata
                except KeyError as e:
                    raise ValueError(
                        f"{e.args[0]}; registered: "
                        f"{', '.join(self.registry.names()) or '<none>'}"
                    ) from None
            if symbols.size > self.config.posterior_span:
                raise ValueError(
                    f"compare request of {symbols.size} symbols exceeds "
                    f"the posterior span ({self.config.posterior_span}) — "
                    "use the compare CLI for span-scale records"
                )
            if self.manifest is not None:
                raise ValueError(
                    "compare requests are not manifest-replayable (the "
                    "manifest journals calls + conf_sum only) — run the "
                    "daemon without --manifest for compare traffic"
                )
        elif models:
            raise ValueError("models=[...] is compare-only; use model=")
        req = ServeRequest(
            id=int(request_id), tenant=str(tenant), kind=kind, name=name,
            symbols=symbols, t_submit=time.monotonic(),
            model=model, models=models_t,
        )
        # Journal payload built OUTSIDE the cv: the base64 encode is pure
        # CPU over the symbols, and holding the broker lock for it would
        # stall every flush consumer and concurrent submitter for the
        # duration (wasted for a rejected request, but rejection is the
        # rare path).  Replay-bound re-submissions (a reconnect storm's
        # common case) skip the encode via a side-effect-free peek — the
        # admit branch can't be reached for them.
        payload = None
        if self.manifest is not None and not self.manifest.has_completion(
            req.id, self._manifest_key(req), int(symbols.size)
        ):
            payload = {
                "tenant": req.tenant, "kind": req.kind,
                "name": req.name, "model": req.model,
                "symbols": base64.b64encode(symbols.tobytes()).decode("ascii"),
            }
        with self._cv:
            # Closed-check under the cv: _closed is written under it in
            # close(), and an unlocked read could admit a request into a
            # queue nothing will ever drain again.
            if self._closed:
                raise RuntimeError("broker is closed")
            t = self._tenants.setdefault(req.tenant, _Tenant())
            if self.manifest is not None:
                # Replay lookup FIRST: a reconnecting client re-submits an
                # id whose first life COMPLETED (its response was lost with
                # the connection) — that must replay from the manifest, not
                # hit the duplicate rejection below, or the client's
                # retry-on-duplicate loop never terminates.  The duplicate
                # rejection then guards ids that are journaled but NOT yet
                # completed (queued/executing/crash-requeued) — the states
                # where a second live copy would collide.  For an id seen
                # THIS life the lookup is a non-destructive peek: a
                # colliding submit with a DIFFERENT identity must be
                # rejected without destroying the legitimate owner's
                # replay entry (discard-on-mismatch stays for fresh-life
                # re-submissions, where changed content means recompute).
                hit = self.manifest.completed(
                    req.id, self._manifest_key(req), int(symbols.size),
                    discard_mismatch=req.id not in self._seen_ids,
                )
                if hit is None and req.id in self._seen_ids:
                    raise ValueError(
                        f"duplicate request id {req.id} (manifest mode needs "
                        "unique ids — they key the completion log)"
                    )
                if hit is not None:
                    from cpgisland_tpu.resilience.manifest import calls_from_wire

                    self._seen_ids.add(req.id)
                    t.requests += 1
                    t.replayed += 1
                    self._replayed.append(ServeResult(
                        id=req.id, tenant=req.tenant, kind=req.kind,
                        calls=calls_from_wire(hit["calls"]),
                        conf_sum=(
                            None if hit.get("conf_sum") is None
                            else float.fromhex(hit["conf_sum"])
                        ),
                        n_symbols=int(symbols.size),
                        route="replay", replayed=True,
                    ))
                    # graftscope lineage: replayed requests get a closed
                    # trace too (admit here, respond in finish_flush).  The
                    # scope lock is a leaf — safe under the cv.
                    scope_mod.hop(
                        req.id, "admit", tenant=req.tenant, kind=req.kind,
                        model=req.model, n_symbols=int(symbols.size),
                        replay=True,
                    )
                    self._cv.notify_all()
                    return
            if req.id in self._queued_ids or req.id in self._inflight_ids:
                # Two same-id requests alive at once would collide in the
                # per-flush results map or in the transport's per-id
                # bookkeeping (one result delivered twice, the other lost,
                # tenant ledger misattributed) — reject while the first is
                # still queued OR executing in a flush; an id may be
                # reused once its request completed.  (Manifest mode never
                # reaches here: _seen_ids already covers every queued id.)
                raise ValueError(
                    f"request id {req.id} is already queued — ids must be "
                    "unique among in-flight requests"
                )
            if t.queued_requests + 1 > self.config.tenant_max_requests:
                t.rejected += 1
                obs.event(
                    "serve_rejected", tenant=req.tenant,
                    reason="tenant_requests",
                )
                raise Backpressure(
                    f"tenant {req.tenant!r} queue is full "
                    f"({t.queued_requests} requests)", "tenant_requests",
                    retry_after_s=self._retry_after_locked(),
                )
            if t.queued_symbols + symbols.size > self.config.tenant_max_symbols:
                t.rejected += 1
                obs.event(
                    "serve_rejected", tenant=req.tenant,
                    reason="tenant_symbols",
                )
                raise Backpressure(
                    f"tenant {req.tenant!r} queued symbols would exceed "
                    f"{self.config.tenant_max_symbols}", "tenant_symbols",
                    retry_after_s=self._retry_after_locked(),
                )
            if self.manifest is not None:
                # Two-phase journal, phase 1 (write-ahead): the admit line
                # lands BEFORE the request is visible to any flush consumer
                # (we still hold the cv), so "submit returned" implies
                # "journaled" — a crash after this point re-executes the
                # request on restart instead of dropping it.  The line is a
                # buffered file write + flush, not in the graftsync
                # blocking set; the manifest lock is a leaf.
                faultplan.check("journal.pre_admit", tag=f"req{req.id}")
                self.manifest.record_admitted(
                    req.id, self._manifest_key(req), int(symbols.size),
                    payload=payload,
                )
                faultplan.check("journal.post_admit", tag=f"req{req.id}")
                self._seen_ids.add(req.id)
            t.queued_requests += 1
            t.queued_symbols += symbols.size
            t.requests += 1
            self._queue.append(req)
            self._queued_ids.add(req.id)
            self._queued_symbols += symbols.size
            # graftscope lineage: mint the trace INSIDE the cv, right after
            # the request becomes visible — hop order matches queue order.
            # Scope lock is a leaf (cv -> scope edge only, no cycle).
            scope_mod.hop(
                req.id, "admit", tenant=req.tenant, kind=req.kind,
                model=req.model, n_symbols=int(symbols.size),
            )
            if self.manifest is not None:
                scope_mod.hop(req.id, "journal.admit")
            self._cv.notify_all()

    def backpressure(self) -> bool:
        """Soft backpressure signal: more than two flushes' worth of
        admitted-but-unserved symbols are waiting.  The transport mirrors
        this to clients so well-behaved ones slow down BEFORE hitting the
        hard tenant caps."""
        with self._lock:
            return self._queued_symbols > 2 * self.config.flush_symbols

    # -- flush policy --------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._replayed)

    def flush_ready(self) -> bool:
        with self._lock:
            return self._ready_locked()

    def _ready_locked(self) -> bool:
        if self._replayed:
            return True
        if not self._queue:
            return False
        if self._queued_symbols >= self.config.flush_symbols:
            return True
        oldest = self._queue[0].t_submit
        return time.monotonic() - oldest >= self.config.flush_deadline_s

    def next_deadline_s(self) -> Optional[float]:
        """Seconds until the oldest queued request's deadline (<= 0 = now);
        None when the queue is empty."""
        with self._lock:
            if not self._queue:
                return None
            return (
                self._queue[0].t_submit + self.config.flush_deadline_s
                - time.monotonic()
            )

    def wait_ready(self, timeout: Optional[float]) -> bool:
        """Block until a flush is ready (or the broker closes / timeout).
        The worker loop's wait primitive."""
        with self._cv:
            if self._ready_locked() or self._closed:
                return self._ready_locked()
            self._cv.wait(timeout)
            return self._ready_locked()

    def poll_flush(self, idle_wait_s: float) -> bool:
        """One worker-loop wait step: park on the flush condition (bounded
        by the oldest request's deadline and ``idle_wait_s``) and report
        whether a flush should run now.  THE shared cadence of the
        single-loop worker (``serve/worker.py``) and every fleet device
        worker (``serve/fleet.py``) — one copy, so the two drivers cannot
        drift on deadline semantics."""
        deadline = self.next_deadline_s()
        timeout = (
            idle_wait_s if deadline is None
            else max(0.0, min(deadline, idle_wait_s))
        )
        if self.wait_ready(timeout):
            return True
        # Deadline may have just expired with work queued — let the
        # broker decide; an empty queue is a no-op flush.
        if self.next_deadline_s() is None:
            return False
        return self.flush_ready()

    def _take(self) -> tuple:
        """Pop (replayed results, flush batch) under the flush budget, in
        arrival order.  Always pops at least one queued request when any is
        waiting — a single record larger than the budget forms its own
        flush (it routes to the span path) instead of starving the queue."""
        with self._lock:
            replayed, self._replayed = self._replayed, []
            batch: list[ServeRequest] = []
            total = 0
            now = time.monotonic()
            while self._queue:
                # Keep taking while the batch is still under budget: the
                # budget is the CLOSE trigger, not a content cap — the
                # request that fills it ships in this flush (leaving it
                # queued would make it wait out the whole deadline after
                # the budget already fired).
                if batch and total >= self.config.flush_symbols:
                    break
                nxt = self._queue[0]
                self._queue.popleft()
                self._queued_ids.discard(nxt.id)
                # In-flight until flush_once returns its result: submit
                # keeps rejecting the id while the request executes.
                self._inflight_ids.add(nxt.id)
                batch.append(nxt)
                total += nxt.symbols.size
                t = self._tenants[nxt.tenant]
                t.queued_requests -= 1
                t.queued_symbols -= nxt.symbols.size
                t.queue_s += now - nxt.t_submit
                # graftscope lineage: queue residency ends here.
                scope_mod.hop(
                    nxt.id, "taken", queue_s=round(now - nxt.t_submit, 6)
                )
            self._queued_symbols -= total
            return replayed, batch, now

    # -- flush execution -----------------------------------------------------

    # graftcheck: hot-path
    def flush_once(self) -> list:
        """Take and execute ONE flush; returns its results (possibly empty
        — a deadline firing on an empty queue is a no-op, not an error).
        The single-consumer composition of :meth:`take_flush` /
        :meth:`run_batch` / :meth:`finish_flush` (the fleet drives the
        three separately so a faulted flush can be requeued onto another
        device between run and finish)."""
        replayed, batch, t_taken = self._take()
        results = list(replayed)
        if batch:
            results.extend(self._run_flush(batch, t_taken))
        return self.finish_flush(results, batch)

    # graftcheck: hot-path
    def take_flush(self) -> tuple:
        """Pop (replayed results, batch, t_taken) under the flush budget —
        the fleet worker's take step (popped requests stay in-flight until
        :meth:`finish_flush` returns them)."""
        return self._take()

    # graftcheck: hot-path
    def run_batch(self, batch: list, t_taken: float, *, registry=None,
                  timer=None, device: str = "") -> list:
        """Execute one taken batch WITHOUT completing it (no journal
        completion, no tenant accounting): the fleet inspects the results
        for device-shaped faults and either requeues the batch intact on
        another device or hands everything to :meth:`finish_flush`.
        ``registry`` routes execution through a per-device session set
        (default: the broker's own); ``timer`` keeps per-worker phase
        accounting off the shared PhaseTimer; ``device`` labels the
        executing device in the lineage trace (fleet workers pass their
        pool label)."""
        return self._run_flush(batch, t_taken, registry=registry,
                               timer=timer, device=device)

    def fail_batch(self, batch: list, t_taken: float,
                   error: BaseException) -> list:
        """Synthesize failed results for a flush whose execution failed at
        the FLUSH level (a fleet requeue budget exhausted, broker
        internals) — admitted requests are never silently dropped, they
        fail loudly."""
        fault = isinstance(error, FAULT_SHAPED)
        return [
            ServeResult(
                id=req.id, tenant=req.tenant, kind=req.kind, ok=False,
                error=f"{type(error).__name__}: {error}",
                n_symbols=int(req.symbols.size),
                queue_s=t_taken - req.t_submit, fault=fault,
            )
            for req in batch
        ]

    # graftcheck: hot-path
    def finish_flush(self, results: list, batch: list) -> list:
        """Complete one flush: journal completions (two-phase journal,
        phase 2), release failed/journal-requeued ids, tenant accounting.
        Must be called exactly once per taken batch, with the full result
        list (replayed results may ride along; they skip the journal)."""
        if self.manifest is not None:
            for r in results:
                if r.ok and not r.replayed:
                    try:
                        req = self._req_of(batch, r.id)
                        # Non-replayed results are keyed from batch ids by
                        # construction — a miss would record a wrong
                        # (shared) key and replay another request's result
                        # on resume, so fail loudly instead.
                        assert req is not None, r.id
                        faultplan.check(
                            "journal.pre_complete", tag=f"req{r.id}"
                        )
                        self.manifest.record_done(
                            r.id, self._manifest_key(req),
                            r.n_symbols, calls=r.calls, conf_sum=r.conf_sum,
                        )
                        faultplan.check(
                            "journal.post_complete", tag=f"req{r.id}"
                        )
                        scope_mod.hop(r.id, "journal.complete")
                    except Exception:
                        # Journaling must never eat computed results: the
                        # clients still get their responses; the cost of a
                        # lost completion line is re-execution on restart.
                        log.exception(
                            "serve: manifest append failed for request %d "
                            "(result still delivered; a restarted daemon "
                            "will re-execute it)", r.id,
                        )
                        break
            # A FAILED request resolves its admit with a terminal "fail"
            # line (a restarted daemon must not re-execute known-failing
            # requests forever) and frees its id so the client can retry
            # with the same id — the retry writes a FRESH admit with the
            # new payload (the manifest keys replay by id, so minting a
            # new one would break restart identity).  A completed
            # journal-requeued id is ALSO released: its result now lives
            # in the manifest, and the reconnecting client's re-submission
            # must find the replay, not a duplicate reject.
            for r in results:
                if not r.ok:
                    try:
                        self.manifest.record_failed(r.id)
                    except Exception:
                        log.exception(
                            "serve: journaling the failure of request %d "
                            "failed (a restarted daemon may re-execute "
                            "it once)", r.id,
                        )
            with self._lock:
                for r in results:
                    if not r.ok:
                        self._journal_requeued.discard(r.id)
                        self._seen_ids.discard(r.id)
                    elif r.id in self._journal_requeued:
                        self._journal_requeued.discard(r.id)
                        self._seen_ids.discard(r.id)
        with self._lock:
            # Flush counters HERE, not in _run_flush: a fleet-requeued
            # flush executes more than once but completes exactly once —
            # counting per execution would inflate the serve stats.
            if batch:
                self.flushes += 1
                self.flushed_symbols += int(
                    sum(req.symbols.size for req in batch)
                )
            # Tenant accounting under the broker lock: submit (a transport
            # thread) mutates the same _Tenant rows concurrently with this
            # consumer-side tally — unlocked, the read-modify-writes tear.
            for r in results:
                self._inflight_ids.discard(r.id)
                t = self._tenants.setdefault(r.tenant, _Tenant())
                t.results += 1
                if not r.replayed:
                    t.symbols += r.n_symbols
                    t.wall_s += r.serve_s
        # graftscope lineage: close every trace OUTSIDE the broker lock
        # (completion folds histograms + emits the request_trace event,
        # which takes the observer's own lock and may write JSONL).
        if scope_mod.enabled():
            for r in results:
                scope_mod.complete(
                    r.id, ok=r.ok, route=r.route, fault=r.fault,
                    replayed=r.replayed, n_symbols=r.n_symbols,
                )
        return results

    @staticmethod
    def _req_of(batch: list, rid: int):
        for req in batch:
            if req.id == rid:
                return req
        return None

    def drain(self) -> list:
        """Flush until the queue is empty (in-process driver for tests,
        the smoke slice, and bench's serve phase)."""
        out: list = []
        while self.pending():
            out.extend(self.flush_once())
        return out

    # graftcheck: hot-path
    def _run_flush(self, batch: list, t_taken: float, *, registry=None,
                   timer=None, device: str = "") -> list:
        """Execute one coalesced flush: requests group by MODEL (the
        registry's per-model sessions — one model's faults stay in its
        own breaker domain), batch-eligible decode records of each model
        run as ONE flat reset-step stream through the shared pipeline
        helper, everything else runs its per-record shared unit, and
        compare requests fan over their member sessions.  All supervised,
        all against the owning session's breaker.  ``registry``/``timer``
        default to the broker's own; the fleet passes its per-device
        clones (sessions, breakers, prep handles all device-scoped)."""
        reg = registry if registry is not None else self.registry
        timer = timer if timer is not None else self._timer
        total = float(sum(r.symbols.size for r in batch))
        t0 = time.perf_counter()
        results: dict[int, ServeResult] = {}
        n_flat = n_singles = n_posts = 0
        compares: list = []
        # graftscope lineage: one flush id per EXECUTION (a requeued flush
        # gets a fresh id — the trace shows both memberships).
        fid = scope_mod.next_flush_id()
        if fid is not None:
            for req in batch:
                scope_mod.hop(req.id, "flush.enter", flush=fid,
                              device=device, n_requests=len(batch))
        with obs.span("serve.flush", items=total, unit="sym"):
            # graftfault kill point: "mid-flush" — after every admit line,
            # before any completion line.  Under a router the tag carries
            # the host label so host-granularity plans (match="@host0")
            # kill exactly one host's flushes.
            _tag = f"n{len(batch)}"
            if self.host_label:
                _tag += f"@{self.host_label}"
            faultplan.check("flush.enter", tag=_tag)

            def fail(req, e: BaseException) -> None:
                # The daemon outlives any one request: a unit whose
                # supervisor gave up (or a malformed record) fails THAT
                # request, loudly, and the flush continues.  fault= marks
                # device-shaped give-ups (the supervisor's retryable set)
                # so the fleet can move the flush; request-shaped errors
                # stay fault=False and fail alone wherever they run.
                log.error("serve: request %d (%s) failed: %s",
                          req.id, req.kind, e)
                results[req.id] = ServeResult(
                    id=req.id, tenant=req.tenant, kind=req.kind,
                    ok=False, error=f"{type(e).__name__}: {e}",
                    n_symbols=int(req.symbols.size),
                    fault=isinstance(e, FAULT_SHAPED),
                )

            by_model: dict = {}
            for req in batch:
                if req.kind == "compare":
                    compares.append(req)
                else:
                    by_model.setdefault(req.model, []).append(req)
            # Snapshot BEFORE the stacked path prunes fully-handled model
            # groups — the flush event reports the models SERVED.
            n_models = len(by_model)
            n_stacked = (
                self._flush_decode_stacked(by_model, results, reg, timer)
                if self.config.stacked and len(by_model) >= 2
                else 0
            )
            n_flat += n_stacked
            for model in sorted(by_model):
                if model:
                    # A registered member carries its own island labeling;
                    # composition comes from the observations (the
                    # pipelines' island_states contract).
                    isl = tuple(reg.member(model).island_states)
                    post_states, obs_based = isl, True
                else:
                    isl = self.config.island_states
                    post_states, obs_based = self._post_states, self._obs_based
                f, s, p = self._flush_group(
                    reg.session(model), by_model[model], results,
                    fail, island_states=isl, post_states=post_states,
                    obs_based=obs_based, timer=timer,
                )
                n_flat += f
                n_singles += s
                n_posts += p
            for req in compares:
                try:
                    results[req.id] = self._compare_record(req, reg)
                except Exception as e:
                    fail(req, e)
        wall = time.perf_counter() - t0
        self._flush_wall.observe(wall)
        obs.event(
            "serve_flush", n_requests=len(batch), n_flat=n_flat,
            n_singles=n_singles, n_posterior=n_posts,
            n_compare=len(compares), n_models=n_models,
            symbols=int(total), wall_s=round(wall, 4),
        )
        out = []
        for req in batch:
            r = results[req.id]
            r.queue_s = t_taken - req.t_submit
            r.serve_s = wall
            out.append(r)
        if fid is not None:
            scope_mod.flush_done(
                fid, device=device, n_requests=len(batch),
                symbols=int(total), wall_s=wall,
            )
            for r in out:
                scope_mod.hop(r.id, "executed", flush=fid, device=device,
                              route=r.route, ok=r.ok,
                              wall_s=round(wall, 6))
        return out

    # graftcheck: hot-path
    def _flush_group(self, sess: Session, batch: list, results: dict,
                     fail, *, island_states, post_states,
                     obs_based: bool, timer=None) -> tuple:
        """One model's slice of a flush (the pre-registry flush body, with
        the owning session and ITS island labeling threaded through).
        Returns (n_flat, n_singles, n_posterior) for the flush event."""
        cfg = self.config
        eng = sess.decode_engine()
        use_dev, cap_box = sess.island_policy(
            device_eligible=True,
            ineligible_msg="unreachable: serve requests no path dumps",
        )
        flat: list = []  # batch-eligible decode requests
        singles: list = []  # decode requests for the per-record path
        posts: list = []
        S = sess.params.n_symbols
        for req in batch:
            if req.kind == "posterior":
                posts.append(req)
            elif (
                0 < req.symbols.size <= pipeline.SMALL_RECORD_MAX
                and req.symbols.size <= cfg.flush_symbols
                # Pad-FIRST records fall outside the reduced flat
                # stream's exactness domain — demote to the per-record
                # path, whose _engine_for_record applies the existing
                # host-entry dense-demotion rule.
                and not (eng == "onehot" and int(req.symbols[0]) >= S)
            ):
                flat.append(req)
            else:
                singles.append(req)
        if len(flat) == 1:
            # Mirror decode_file's flush_small: a single record skips
            # the batch layout and decodes through the record path.
            singles.extend(flat)
            flat = []

        if flat:
            try:
                _nsp, parts, _paths = pipeline._decode_small_batch(
                    sess.params,
                    [(r.name or ".", r.symbols) for r in flat],
                    batch_decode=sess.batch_decode_fn(eng),
                    min_len=cfg.min_len,
                    island_states=island_states,
                    use_device_islands=use_dev,
                    cap_box=cap_box,
                    want_paths=False,
                    timer=timer if timer is not None else self._timer,
                    defer=False,
                    supervisor=sess.supervisor,
                    engine_label=eng,
                )
                for req, calls in zip(flat, parts):
                    results[req.id] = ServeResult(
                        id=req.id, tenant=req.tenant, kind=req.kind,
                        calls=calls, n_symbols=int(req.symbols.size),
                        route="flat",
                    )
            except Exception as e:
                for req in flat:
                    fail(req, e)
        for req in singles:
            try:
                calls, route = self._decode_record(
                    sess, req, eng, use_dev, cap_box, island_states
                )
                results[req.id] = ServeResult(
                    id=req.id, tenant=req.tenant, kind=req.kind,
                    calls=calls, n_symbols=int(req.symbols.size),
                    route=route,
                )
            except Exception as e:
                fail(req, e)
        fb_eng = sess.fb_engine() if posts else None
        for req in posts:
            try:
                conf, conf_sum, calls = self._posterior_record(
                    sess, req, fb_eng, use_dev, cap_box, post_states,
                    obs_based,
                )
                results[req.id] = ServeResult(
                    id=req.id, tenant=req.tenant, kind=req.kind,
                    calls=calls, conf=conf, conf_sum=conf_sum,
                    n_symbols=int(req.symbols.size), route="posterior",
                )
            except Exception as e:
                fail(req, e)
        return len(flat), len(singles), len(posts)

    # graftcheck: hot-path
    def _flush_decode_stacked(self, by_model: dict, results: dict,
                              reg, timer) -> int:
        """Mixed-model decode stacking: batch-eligible decode requests of
        >= 2 onehot models (one shared alphabet) coalesce into ONE stacked
        flat launch set; each record's calls come from its owning model's
        chains.  Mutates ``by_model`` (handled requests removed) and fills
        ``results``; returns the number of stacked requests.  A failing
        stacked unit handles NOTHING — the per-model sequential groups
        then serve every request under their own sessions (the fault-
        domain fallback, like family.compare's stacked arm)."""
        cfg = self.config
        cand = []
        for model in sorted(by_model):
            sess = reg.session(model)
            try:
                eng = sess.decode_engine()
            except Exception:
                # A session whose explicit engine no longer validates fails
                # ITS requests in its own flush group, not the stacked scan.
                continue
            if eng != "onehot":
                continue
            S = sess.params.n_symbols
            flat = [
                r for r in by_model[model]
                if r.kind == "decode"
                and 0 < r.symbols.size <= pipeline.SMALL_RECORD_MAX
                and r.symbols.size <= cfg.flush_symbols
                and int(r.symbols[0]) < S
            ]
            if flat:
                cand.append((model, sess, flat))
        if len(cand) < 2:
            return 0
        if len({c[1].params.n_symbols for c in cand}) != 1:
            return 0
        params_list, batch, owners, isl_list, use_list, caps, reqs = (
            [], [], [], [], [], [], []
        )
        for m, (model, sess, flat) in enumerate(cand):
            params_list.append(sess.params)
            if model:
                isl = tuple(reg.member(model).island_states)
            else:
                isl = cfg.island_states
            use_dev, cap_box = sess.island_policy(
                device_eligible=True,
                ineligible_msg="unreachable: serve requests no path dumps",
            )
            isl_list.append(isl)
            use_list.append(use_dev)
            caps.append(cap_box)
            for r in flat:
                batch.append((r.name or ".", r.symbols))
                owners.append(m)
                reqs.append(r)
        try:
            _B, parts = pipeline._decode_small_batch_stacked(
                params_list, batch, owners,
                min_len=cfg.min_len, island_states_list=isl_list,
                use_device_list=use_list, cap_boxes=caps,
                timer=timer,
                supervisor=reg.default.supervisor,
            )
        except Exception as e:
            log.error(
                "serve: stacked decode flush failed (%s: %s); falling back "
                "to per-model groups", type(e).__name__, e,
            )
            return 0
        handled = set()
        for req, calls in zip(reqs, parts):
            results[req.id] = ServeResult(
                id=req.id, tenant=req.tenant, kind=req.kind, calls=calls,
                n_symbols=int(req.symbols.size), route="flat-stacked",
            )
            handled.add(req.id)
        for model in list(by_model):
            rest = [r for r in by_model[model] if r.id not in handled]
            if rest:
                by_model[model] = rest
            else:
                del by_model[model]
        obs.event(
            "stacked_dispatch", _dedupe=True, kind="decode",
            n_members=len(cand), n_requests=len(handled),
        )
        return len(handled)

    # graftcheck: hot-path
    def _compare_record(self, req: ServeRequest, reg=None) -> ServeResult:
        """One compare request: the family comparison over the registry's
        member sessions (family.compare_record — the same record units the
        posterior path runs, each member under ITS model's session, so
        per-model breaker domains hold).  The winner track rides in the
        standard ``calls`` field; per-model log-odds in ``compare``."""
        from cpgisland_tpu import family

        reg = reg if reg is not None else self.registry
        members = [reg.member(n) for n in req.models]
        rc = family.compare_record(
            members, req.symbols, record=req.name or ".",
            min_len=self.config.min_len,
            sessions=reg.sessions_for(req.models),
            stacked=self.config.stacked,
            # ONE PreparedStreams handle per alphabet, shared across the
            # members of a stream — the stacked group's symbol-only prep
            # books against the registry, not any single member session.
            streams_handle=reg.compare_streams,
        )
        return ServeResult(
            id=req.id, tenant=req.tenant, kind=req.kind,
            calls=rc.winner_calls,
            compare={
                "baseline": rc.baseline,
                "models": {
                    m.name: {
                        "loglik": m.loglik,
                        "log_odds": m.log_odds,
                        "islands": len(m.calls),
                    }
                    for m in rc.members
                },
            },
            n_symbols=int(req.symbols.size), route="compare",
        )

    # graftcheck: hot-path
    def _decode_record(self, sess: Session, req: ServeRequest, eng: str,
                       use_dev: bool, cap_box: list, island_states):
        """One decode request outside the flat batch: the per-record shared
        path (viterbi_sharded, span-threaded beyond the decode span) —
        the same units decode_file's decode_one drives."""
        from cpgisland_tpu.parallel import decode as par_decode

        symbols = req.symbols
        span = self.config.decode_span
        route = "span" if symbols.size > span else "record"

        def dispatch():
            # Raw session engine string, NOT the flush-resolved name (the
            # same rule as decode_file): an explicit name would be honored
            # as-is on retries, so a supervisor re-dispatch after a trip
            # could never demote down the session breaker's ladder.
            if symbols.size == 0:
                return [np.zeros(0, dtype=np.int32)]
            if symbols.size > span:
                return par_decode.viterbi_sharded_spans(
                    sess.params, symbols, span=span, engine=sess.engine,
                    return_device=use_dev, supervisor=sess.supervisor,
                )
            return [
                par_decode.viterbi_sharded(
                    sess.params, symbols, engine=sess.engine,
                    return_device=use_dev, supervisor=sess.supervisor,
                )
            ]

        if use_dev:
            import jax
            import jax.numpy as jnp

            def record_unit():
                p = dispatch()
                f = p[0] if len(p) == 1 else jnp.concatenate(p)
                # Block INSIDE the supervised unit so a device fault
                # surfaces where the retry re-dispatches (decode_one's
                # contract).
                # graftcheck: allow(hot-path-host-sync) -- fault-surfacing block (comment above); the obs ledger counts it via its block_until_ready hook
                jax.block_until_ready(f)
                return f

            full = sess.supervisor.run(
                record_unit, what="serve.decode_record",
                engine=f"decode.{eng}", items=float(symbols.size),
            )
            calls = self._device_calls(
                sess, full, symbols, island_states, cap_box
            )
        else:
            pieces = dispatch()
            full = pieces[0] if len(pieces) == 1 else np.concatenate(pieces)
            calls = self._host_calls(full, symbols, island_states)
        return calls.with_names(req.name or "."), route

    # graftcheck: hot-path
    def _posterior_record(self, sess: Session, req: ServeRequest,
                          fb_eng: str, use_dev: bool, cap_box: list,
                          post_states, obs_based: bool):
        """One posterior request: the SAME shared record unit
        posterior_file's single-record path runs, then island calls from
        the MPM path — (conf host array, exact f64 conf sum, calls)."""
        symbols = req.symbols
        # engine = the raw session request (re-resolves per dispatch
        # against the session breaker, like posterior_file); fb_eng = the
        # flush-resolved name, labels only.
        conf, path = pipeline._posterior_record_unit(
            sess.params, symbols, post_states, engine=sess.engine,
            fb_eng=fb_eng, want_path=True, return_device=use_dev,
            sup=sess.supervisor,
        )
        if use_dev:
            from cpgisland_tpu.parallel.mesh import fetch_sharded_prefix

            conf = obs.note_fetch(
                fetch_sharded_prefix(conf, conf.shape[0], False)
            )
            calls = self._device_calls(
                sess, path, symbols,
                post_states if obs_based else None, cap_box,
            )
        else:
            calls = self._host_calls(
                path, symbols,
                post_states if obs_based else None,
            )
        # graftcheck: allow(hot-path-host-sync) -- conf is host on both branches (the device branch fetched it through obs.note_fetch above; the host branch's posterior_sharded fetched internally); coercion only
        conf = np.asarray(conf)
        # graftcheck: allow(hot-path-host-sync) -- conf is a host ndarray here (coerced above); exact-f64 sum, no device fetch
        conf_sum = float(conf.sum(dtype=np.float64))
        return conf, conf_sum, calls.with_names(req.name or ".")

    def _host_calls(self, path, symbols, island_states) -> IslandCalls:
        """Host island calling — the pipelines' exact host branches
        (``island_states=None`` = the built-in 2M-state caller, the
        posterior default labeling included, like posterior_file.call_rec)."""
        if island_states is not None:
            return islands_mod.call_islands_obs(
                np.asarray(path), np.asarray(symbols),
                island_states=island_states, min_len=self.config.min_len,
            )
        return islands_mod.call_islands(
            np.asarray(path), chunk=0, compat=False,
            min_len=self.config.min_len,
        )

    def _device_calls(self, sess: Session, path, symbols, island_states,
                      cap_box: list) -> IslandCalls:
        """Device island calling with the learned-cap overflow retry — the
        pipelines' serial device branch."""
        import jax.numpy as jnp

        from cpgisland_tpu.ops.islands_device import (
            call_islands_device,
            call_islands_device_obs,
        )

        if island_states is not None:
            return pipeline._device_calls_retry(
                call_islands_device_obs, path, jnp.asarray(symbols),
                island_states=island_states,
                min_len=self.config.min_len, cap_box=cap_box,
                supervisor=sess.supervisor,
            )
        return pipeline._device_calls_retry(
            call_islands_device, path, min_len=self.config.min_len,
            cap_box=cap_box, supervisor=sess.supervisor,
        )

    # -- introspection / lifecycle ------------------------------------------

    def tenant_stats(self) -> dict:
        with self._lock:
            return {name: t.as_dict() for name, t in self._tenants.items()}

    def stats(self) -> dict:
        from cpgisland_tpu.ops import prepared

        with self._lock:
            queued = len(self._queue)
            qsym = self._queued_symbols
            flushes = self.flushes
            flushed_symbols = self.flushed_symbols
        return {
            "flushes": flushes,
            "flushed_symbols": flushed_symbols,
            "queued_requests": queued,
            "queued_symbols": qsym,
            "backpressure": self.backpressure(),
            "tenants": self.tenant_stats(),
            "prepared_cache": prepared.cache_stats(),
        }

    def close(self) -> None:
        """Stop admitting.  The manifest stays OPEN: the transports drain
        everything already admitted AFTER close (shutdown-op semantics),
        and those completions must still reach the journal — closing it
        here silently dropped every post-shutdown completion line, so a
        restarted daemon re-executed work it had in fact finished.  Call
        :meth:`release` once the final drain is done.  (The session is
        the caller's — a daemon dropping a tenant also calls
        session.close() to evict its prepared-stream entries.)"""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def release(self) -> None:
        """Release the manifest (idempotent) — after the LAST drain."""
        if self.manifest is not None:
            self.manifest.close()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed
