"""Pallas Viterbi kernels vs. the XLA blockwise decoder and the oracle.

On the CPU test platform the kernels run through the Pallas interpreter
(identical math, same code path that compiles on TPU).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.viterbi import viterbi
from cpgisland_tpu.ops.viterbi_pallas import (
    supports,
    viterbi_pallas,
    viterbi_pallas_batch,
)
from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel, viterbi_parallel_batch

from tests.oracle import viterbi_oracle


def _tie_free_params(rng, K=8, S=4):
    """Random dense params with iid-perturbed logits — argmax ties have
    probability ~0, so exact path comparison is meaningful."""
    pi = rng.dirichlet(np.ones(K))
    A = rng.dirichlet(np.ones(K), size=K)
    B = rng.dirichlet(np.ones(S), size=K)
    return HmmParams.from_probs(pi, A, B)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_matches_oracle_small(rng):
    params = _tie_free_params(rng)
    obs = rng.integers(0, 4, size=301)
    path, score = viterbi_pallas(params, jnp.asarray(obs), block_size=16)
    o_path, o_score = viterbi_oracle(
        np.asarray(params.pi), np.asarray(params.A), np.asarray(params.B), obs
    )
    np.testing.assert_allclose(float(score), o_score, rtol=1e-5)
    path = np.asarray(path)
    if not np.array_equal(path, o_path):
        # "Tie-free" holds for the f64 dirichlet draw, but the kernel runs on
        # f32-QUANTIZED log tables, which can create exact ties the draw
        # doesn't have (observed on TPU: a 4-position detour with bit-equal
        # f64 score under the quantized tables).  Equal-scoring alternatives
        # are correct Viterbi output; judge by f64 path score, not identity.
        lp, lA, lB = (
            np.asarray(x, np.float64)
            for x in (params.log_pi, params.log_A, params.log_B)
        )

        def f64_score(p):
            return (
                lp[p[0]] + lB[p[0], obs[0]]
                + (lA[p[:-1], p[1:]] + lB[p[1:], obs[1:]]).sum()
            )

        assert f64_score(path) == pytest.approx(f64_score(o_path), abs=1e-9)
        assert (path == o_path).mean() > 0.9


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_matches_xla_parallel_exactly(rng):
    params = _tie_free_params(rng)
    obs = jnp.asarray(rng.integers(0, 4, size=8192))
    p1, s1 = viterbi_parallel(params, obs, block_size=64)
    p2, s2 = viterbi_pallas(params, obs, block_size=64)
    np.testing.assert_allclose(float(s1), float(s2), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_durbin_preset_score_parity(rng):
    # The flagship one-hot-emission model: exact ties are possible, so compare
    # achieved path scores (both must be optimal) and island-relevant strand.
    params = presets.durbin_cpg8()
    obs = jnp.asarray(rng.integers(0, 4, size=4096))
    p_seq, s_seq = viterbi(params, obs)
    p_pal, s_pal = viterbi_pallas(params, obs, block_size=128)
    np.testing.assert_allclose(float(s_seq), float(s_pal), rtol=1e-5)
    # One-hot emissions force state ≡ symbol (mod 4) everywhere on any optimal path.
    np.testing.assert_array_equal(np.asarray(p_pal) % 4, np.asarray(obs) % 4)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_pad_symbols_are_identity_steps(rng):
    params = _tie_free_params(rng)
    base = rng.integers(0, 4, size=500)
    padded = np.concatenate([base, np.full(124, 4)])
    p_base = viterbi_pallas(params, jnp.asarray(base), block_size=32, return_score=False)
    p_pad = viterbi_pallas(params, jnp.asarray(padded), block_size=32, return_score=False)
    np.testing.assert_array_equal(np.asarray(p_pad)[:500], np.asarray(p_base))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_matches_xla_batch(rng):
    params = _tie_free_params(rng)
    chunks = jnp.asarray(rng.integers(0, 4, size=(3, 1024)))
    lengths = jnp.asarray([1024, 700, 1])
    p1 = viterbi_parallel_batch(params, chunks, lengths, block_size=64, return_score=False)
    p2 = viterbi_pallas_batch(params, chunks, lengths, block_size=64, return_score=False)
    for i, n in enumerate([1024, 700, 1]):
        np.testing.assert_array_equal(np.asarray(p1)[i, :n], np.asarray(p2)[i, :n])


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_non_multiple_block_sizes(rng):
    params = _tie_free_params(rng)
    obs = jnp.asarray(rng.integers(0, 4, size=997))  # prime length
    p_ref = viterbi_parallel(params, obs, block_size=64, return_score=False)
    p_pal = viterbi_pallas(params, obs, block_size=64, return_score=False)
    np.testing.assert_array_equal(np.asarray(p_ref), np.asarray(p_pal))


def test_rejects_large_state_spaces(rng):
    params = _tie_free_params(rng, K=9)
    assert not supports(params)
    with pytest.raises(ValueError, match="n_states"):
        viterbi_pallas(params, jnp.zeros(16, jnp.int32))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_sharded_decode_pallas_engine(rng):
    """Pallas passes under shard_map on the 8-device mesh == XLA engine."""
    from conftest import require_devices

    from cpgisland_tpu.parallel.decode import viterbi_sharded
    from cpgisland_tpu.parallel.mesh import make_mesh

    require_devices(8)
    params = _tie_free_params(rng)
    obs = rng.integers(0, 4, size=8 * 512 + 77).astype(np.int32)
    mesh = make_mesh(8, axis="seq")
    p_xla = viterbi_sharded(params, obs, mesh=mesh, block_size=64, engine="xla")
    p_pal = viterbi_sharded(params, obs, mesh=mesh, block_size=64, engine="pallas")
    np.testing.assert_array_equal(p_xla, p_pal)
