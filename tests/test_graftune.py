"""graftune (PR 14): the fingerprint-keyed knob autotuner.

Pins, per the acceptance criteria:

- every consulting router falls back BIT-FOR-BIT to the hard-coded
  defaults when the winner table is absent, stale, or fingerprint-drifted
  — and follows a fresh applied winner when one matches;
- a tampered COSTS.json entry flips the dependent winners to stale
  (named in the ``--tune`` diff, the stale-waiver UX) while unrelated
  winners stay fresh;
- a planted absurd winner (lane_T=8) is refused by the router's domain
  check AND rejected by the sweep's apply-time parity gate before it can
  be written;
- the sweep driver completes a real prune -> parity-gate -> time ->
  persist cycle with the ledger asserting that zero memmodel-rejected
  tuples ever reached compile (slow-marked: the cycle compiles real
  programs);
- ``pick_lane_T``'s lru-cached feasibility filter keys on the table
  generation, so an in-process ``--update-tune`` takes effect
  immediately (the PR-13 staleness fix).
"""

import json
import shutil

import numpy as np
import pytest

import jax.numpy as jnp

from cpgisland_tpu import tune
from cpgisland_tpu.tune import sweep as tune_sweep
from cpgisland_tpu.tune import table as tune_table
from cpgisland_tpu.tune import tasks as tune_tasks


@pytest.fixture
def tmp_table(tmp_path):
    """Point the consultation machinery at a per-test table; restore the
    committed default afterwards."""
    path = str(tmp_path / "TUNING.json")
    tune.set_table_path(path)
    try:
        yield path
    finally:
        tune.set_table_path(None)
        tune.generation()  # refresh the cache back onto the default


@pytest.fixture
def absent_table(tmp_path):
    path = str(tmp_path / "no-such-TUNING.json")
    tune.set_table_path(path)
    try:
        yield path
    finally:
        tune.set_table_path(None)
        tune.generation()


def _plant(task, value, *, n_pow2=None, S=None, M=1, costs_entries,
           applied=True, fingerprint=None, platform="cpu"):
    key = tune_table.entry_key(task, n_pow2, S, M)
    entry = tune_table.make_entry(
        task, value, legacy=None, costs_entries=costs_entries,
        applied=applied, projection=True,
    )
    if fingerprint is not None:
        entry["costs_fingerprint"] = fingerprint
    tune_table.write_entries({key: entry}, platform=platform)
    return key


# -- fallback parity ----------------------------------------------------------


def test_absent_table_is_legacy_bit_for_bit(absent_table):
    from cpgisland_tpu.ops import fb_pallas

    for n in (1, 4096, 1 << 20, 16 << 20, 100 << 20):
        for onehot in (False, True):
            for long_lanes in (False, True) if onehot else ((False,)):
                assert fb_pallas.pick_lane_T(
                    n, onehot=onehot, long_lanes=long_lanes
                ) == fb_pallas.legacy_lane_T(
                    n, onehot=onehot, long_lanes=long_lanes
                )
    assert tune.default_fused("em_chunked") is True
    assert tune.default_stacked("compare") is True
    assert tune.default_block_size() == 4096
    assert tune.default_t_tile("em_seq", 512) == 512
    assert tune.default_engine("fb_chunked", "xla", {"xla", "onehot"}) \
        == "xla"


def test_fresh_lane_winner_consulted_per_bucket(tmp_table):
    from cpgisland_tpu.ops import fb_pallas

    n = 4 << 20
    _plant(
        "lane.onehot.long", 16384,
        n_pow2=tune_table.pow2_bucket(n),
        costs_entries=["posterior.onehot", "em.seq.onehot"],
    )
    assert fb_pallas.pick_lane_T(n, onehot=True, long_lanes=True) == 16384
    # A different geometry bucket has no winner: legacy, bit for bit.
    other = 1 << 20
    assert fb_pallas.pick_lane_T(other, onehot=True, long_lanes=True) == \
        fb_pallas.legacy_lane_T(other, onehot=True, long_lanes=True)


def test_update_tune_in_process_takes_effect_immediately(tmp_table):
    """The satellite fix: pick_lane_T's lru-cached feasibility filter
    keys on the table generation, so a winner written mid-session routes
    on the very next call (no stale pre-sweep cache)."""
    from cpgisland_tpu.ops import fb_pallas

    n = 8 << 20
    legacy = fb_pallas.legacy_lane_T(n, onehot=True, long_lanes=True)
    assert fb_pallas.pick_lane_T(n, onehot=True, long_lanes=True) == legacy
    _plant(
        "lane.onehot.long", 8192,
        n_pow2=tune_table.pow2_bucket(n),
        costs_entries=["posterior.onehot"],
    )
    assert fb_pallas.pick_lane_T(n, onehot=True, long_lanes=True) == 8192


def test_fingerprint_drift_falls_back_and_is_named(tmp_table):
    from cpgisland_tpu.ops import fb_pallas

    n = 4 << 20
    key = _plant(
        "lane.onehot.long", 16384,
        n_pow2=tune_table.pow2_bucket(n),
        costs_entries=["posterior.onehot"],
        fingerprint="sha256:deadbeefdeadbeef",
    )
    assert fb_pallas.pick_lane_T(n, onehot=True, long_lanes=True) == \
        fb_pallas.legacy_lane_T(n, onehot=True, long_lanes=True)
    rep = tune_table.table_report(platform="cpu")
    assert rep["stale"] == 1 and rep["fresh"] == 0
    assert rep["stale_entries"][0]["key"] == key
    assert "fingerprint drifted" in rep["stale_entries"][0]["reason"]


def test_tampered_costs_entry_flips_dependent_winners(tmp_table, tmp_path):
    """The whole point of fingerprint keying: a kernel reshape that moves
    the dependent COSTS.json entry stales exactly the winners swept
    through it; unrelated winners stay fresh."""
    _plant(
        "fused.em_chunked", True,
        costs_entries=["em.chunked.onehot"],
    )
    _plant(
        "fused.posterior", True,
        costs_entries=["posterior.onehot"],
    )
    clean = tune_table.table_report(platform="cpu")
    assert clean["fresh"] == 2 and clean["stale"] == 0

    tampered = tmp_path / "COSTS.json"
    shutil.copy(tune_table.default_costs_path(), tampered)
    lock = json.loads(tampered.read_text())
    entry = lock["platforms"]["cpu"]["entries"]["em.chunked.onehot"]
    entry["passes"] = entry["passes"] + 1  # the kernel "reshaped"
    tampered.write_text(json.dumps(lock))

    rep = tune_table.table_report(platform="cpu", costs_path=str(tampered))
    assert rep["stale"] == 1 and rep["fresh"] == 1
    assert "fused.em_chunked" in rep["stale_entries"][0]["key"]
    d = tune_table.lookup(
        "fused.em_chunked", platform="cpu", costs_path=str(tampered)
    )
    assert d.status == "stale" and "em.chunked.onehot" in d.reason


def test_absurd_winner_refused_by_router_and_apply_gate(tmp_table):
    """A planted lane_T=8 (outside the sweepable rate table) must never
    route — and the apply-time parity gate refuses to write it."""
    from cpgisland_tpu.ops import fb_pallas

    n = 4 << 20
    _plant(
        "lane.onehot.long", 8,
        n_pow2=tune_table.pow2_bucket(n),
        costs_entries=["posterior.onehot"],
    )
    assert fb_pallas.pick_lane_T(n, onehot=True, long_lanes=True) == \
        fb_pallas.legacy_lane_T(n, onehot=True, long_lanes=True)
    with pytest.raises(ValueError, match="parity gate"):
        tune_sweep.validate_entry("lane.onehot.long", 8)


def test_apply_gate_rejects_infeasible_values():
    # In-domain but memmodel-rejected: the feasibility oracle is part of
    # the apply gate too (a winner that stopped fitting after a model
    # recalibration cannot be re-applied).
    with pytest.raises(ValueError, match="feasibility"):
        tune_sweep.validate_entry("t_tile.em_seq", 4096)
    with pytest.raises(ValueError, match="feasibility"):
        tune_sweep.validate_entry("flat.block.scores", 16384)
    with pytest.raises(ValueError, match="parity gate"):
        tune_sweep.validate_entry("fused.em_chunked", "sideways")
    # Legacy values always pass.
    tune_sweep.validate_entry("t_tile.em_seq", 512)
    tune_sweep.validate_entry("flat.block.scores", 4096)
    tune_sweep.validate_entry("fused.em_chunked", True)


# -- per-path fused / stacked / block / engine consultation ------------------


def test_fused_default_consultation(tmp_table):
    from cpgisland_tpu.train.backends import LocalBackend

    assert LocalBackend().fuse_fb is True
    _plant("fused.em_chunked", False, costs_entries=["em.chunked.onehot"])
    assert LocalBackend().fuse_fb is False
    # Explicit always wins.
    assert LocalBackend(fuse_fb=True).fuse_fb is True


def test_seq_backend_fused_and_t_tile_consultation(tmp_table):
    from cpgisland_tpu.train.backends import SeqBackend

    b = SeqBackend()
    assert b.fuse_fb is True and b.t_tile == 512
    _plant("fused.em_seq", False, costs_entries=["em.seq.onehot"])
    _plant("t_tile.em_seq", 256, costs_entries=["em.seq.onehot"])
    b2 = SeqBackend()
    assert b2.fuse_fb is False and b2.t_tile == 256
    assert SeqBackend(fuse_fb=True, t_tile=1024).t_tile == 1024


def test_stacked_default_consultation(tmp_table):
    from cpgisland_tpu.serve.broker import BrokerConfig
    from cpgisland_tpu.train.backends import FamilyEStep

    assert FamilyEStep().stacked is True
    assert BrokerConfig().stacked is True
    _plant(
        "stacked.em_family", False,
        costs_entries=["em.chunked.onehot.stacked3"],
    )
    _plant(
        "stacked.serve_decode", False,
        costs_entries=["decode.batch_flat.onehot.stacked3"],
    )
    assert FamilyEStep().stacked is False
    assert BrokerConfig().stacked is False
    assert FamilyEStep(stacked=True).stacked is True
    assert BrokerConfig(stacked=True).stacked is True


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_family_estep_sequential_arm_bit_identical(tmp_table):
    """FamilyEStep(stacked=False) — the tuned fallback arm — must match
    the stacked launch per member bit for bit (the pinned contract the
    router relies on when a stacked winner goes stale)."""
    from cpgisland_tpu.train.backends import FamilyEStep

    members = tune_tasks._member_params(3)
    rng = np.random.default_rng(0)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(4, 512), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(4, 512, jnp.int32)
    stacked = FamilyEStep(stacked=True)(members, chunks, lengths)
    seq = FamilyEStep(stacked=False)(members, chunks, lengths)
    for a, b in zip(stacked, seq):
        np.testing.assert_array_equal(np.asarray(a.trans), np.asarray(b.trans))
        np.testing.assert_array_equal(np.asarray(a.emit), np.asarray(b.emit))


def test_flat_block_consultation(tmp_table):
    from cpgisland_tpu.ops import viterbi_onehot as OH

    assert tune.default_block_size() == 4096
    _plant(
        "flat.block", 2048,
        costs_entries=["decode.batch_flat.onehot"],
    )
    assert tune.default_block_size() == 2048
    # The prep derivation consults the same default: bk lands at the
    # tuned block for a big-enough stream.
    rng = np.random.default_rng(1)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(2, 4096), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(2, 4096, jnp.int32)
    _, _, _, bk, _ = OH.prepare_decode_flat(4, chunks, lengths)
    assert bk == 2048
    # Explicit block sizes pass through untouched.
    _, _, _, bk, _ = OH.prepare_decode_flat(4, chunks, lengths, 1024)
    assert bk == 1024


def test_engine_winner_respects_eligibility(tmp_table):
    """A tuned engine outside the currently-eligible ladder is refused:
    on CPU auto resolves to xla and 'onehot' is not in the ladder, so a
    planted onehot winner must NOT route (eligibility is never relaxed
    by the tuner)."""
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train.backends import resolve_fb_engine

    params = presets.durbin_cpg8()
    assert resolve_fb_engine("auto", params, "rescaled") == "xla"
    _plant(
        "engine.fb_chunked", "onehot",
        costs_entries=["em.chunked.onehot", "em.chunked.xla"],
    )
    assert resolve_fb_engine("auto", params, "rescaled") == "xla"


# -- the committed table ------------------------------------------------------


def test_committed_table_is_fresh_and_legacy_valued():
    """The committed TUNING.json's cpu section must stay fresh against
    the committed COSTS.json (a kernel reshape that re-baselines costs
    must re-sweep: tools/graftune.py --all --update-tune --apply), and —
    being a CPU projection sweep — every applied winner must equal its
    recorded legacy default, so the committed artifact changes NO routing
    (the chip knobs are earned on the capture platform only)."""
    data = tune_table.load_table(tune_table.default_table_path())
    assert data is not None, "TUNING.json missing from the repo"
    section = data["platforms"]["cpu"]
    assert section["entries"], "committed table has no cpu winners"
    rep = tune_table.table_report(
        platform="cpu", path=tune_table.default_table_path()
    )
    assert rep["stale"] == 0, rep["stale_entries"]
    for key, e in section["entries"].items():
        assert e["projection"] is True, key
        assert e["value"] == e["legacy"], (
            f"{key}: committed cpu winner {e['value']!r} != legacy "
            f"{e['legacy']!r} — projection sweeps must not move routing"
        )


def test_tune_report_cli_names_stale(tmp_table, capsys):
    from cpgisland_tpu.analysis import cli

    _plant(
        "fused.em_seq", True,
        costs_entries=["em.seq.onehot"],
        fingerprint="sha256:0000000000000000",
    )
    rc = cli.main(["--no-lint", "--tune", "--tune-file", tmp_table])
    err = capsys.readouterr().err
    assert rc == 0  # staleness is advisory (the stale-waiver UX)
    assert "tune stale" in err and "fused.em_seq" in err
    assert "graftune:" in err and "1 stale" in err


# -- the sweep round trip (slow: compiles real programs) ---------------------


@pytest.mark.slow
def test_sweep_cycle_prune_parity_time_persist(tmp_table):
    cfg = tune_tasks.SweepConfig(n=64 << 10, chain=2, reps=1, smoke=True)
    report = tune_sweep.run_sweep(
        names=["t_tile.em_seq", "fused.em_chunked"], cfg=cfg
    )
    ledger = report["ledger"]
    assert ledger["clean"]
    # The prune was real: the planted-infeasible t_tile=4096 candidate
    # was rejected by the memmodel BEFORE compile.
    pruned = {(r["task"], r["value"]) for r in ledger["pruned"]}
    assert ("t_tile.em_seq", "4096") in pruned
    timed = {(r["task"], r["value"]) for r in ledger["timed"]}
    assert not (pruned & timed)
    # Persist and re-consult: rows land fresh; CPU knob winners apply
    # only at the legacy value (projection rule).
    path = tune_sweep.persist(report, update_tune=True, apply_verdicts=True)
    assert path == tmp_table
    rep = tune_table.table_report(platform="cpu")
    assert rep["fresh"] == 2 and rep["stale"] == 0
    for t in report["tasks"]:
        assert t["applied_value"] == t["legacy"]
        assert t["decision"] == "keep"


@pytest.mark.slow
def test_sweep_ledger_raises_if_pruned_tuple_reaches_compile():
    ledger = tune_sweep.SweepLedger()
    ledger.prune("t_tile.em_seq", 4096, "too big")
    with pytest.raises(tune_sweep.PrunedTupleCompiled):
        ledger.check_compile("t_tile.em_seq", 4096)


@pytest.mark.slow
def test_graftune_cli_single_task_round_trip(tmp_path):
    """tools/graftune.py end to end on one cheap task: one JSON line on
    stdout, ledger clean, winners persisted to the given table."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    table_path = tmp_path / "TUNING.json"
    proc = subprocess.run(
        [
            sys.executable, str(repo / "tools" / "graftune.py"),
            "--platform", "cpu", "--smoke", "--kernel", "fused.em_chunked",
            "--update-tune", "--apply", "--tune-file", str(table_path),
        ],
        capture_output=True, text=True, cwd=str(repo), timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ledger"]["clean"]
    assert out["persisted"] == str(table_path)
    written = json.loads(table_path.read_text())
    keys = list(written["platforms"]["cpu"]["entries"])
    assert keys and all(k.startswith("fused.em_chunked") for k in keys)
