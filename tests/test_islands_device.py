"""Device island caller == clean-mode host caller, on every edge shape."""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.ops import islands as host_islands
from cpgisland_tpu.ops.islands_device import call_islands_device


def _assert_same(dev, host):
    """Device == host BIT-FOR-BIT: the device path compacts integer counts
    and re-evaluates gc/oe + thresholds in f64 with the host formulas."""
    np.testing.assert_array_equal(dev.beg, host.beg)
    np.testing.assert_array_equal(dev.end, host.end)
    np.testing.assert_array_equal(dev.length, host.length)
    np.testing.assert_array_equal(dev.gc_content, host.gc_content)
    np.testing.assert_array_equal(dev.oe_ratio, host.oe_ratio)


def _host(path, **kw):
    return host_islands.call_islands(path, compat=False, **kw)


def test_matches_host_random_paths(rng):
    for T in (1, 2, 7, 1000, 4097):
        path = rng.integers(0, 8, size=T).astype(np.int32)
        _assert_same(call_islands_device(path), _host(path))


def test_matches_host_islandy_paths(rng):
    """CpG-dense paths: long + runs rich in C/G states."""
    parts = []
    for _ in range(30):
        parts.append(rng.integers(4, 8, size=rng.integers(1, 300)))
        parts.append(rng.choice([1, 2], size=rng.integers(1, 400)))
    path = np.concatenate(parts).astype(np.int32)
    _assert_same(call_islands_device(path), _host(path))


def test_edge_runs(rng):
    # open at start, open at end, whole-path island, no islands, alternating
    cases = [
        np.array([1, 2, 1, 2, 4, 4], np.int32),
        np.array([4, 4, 1, 2, 1, 2], np.int32),  # open at end: clean emits it
        np.array([2, 1, 2, 1], np.int32),
        np.array([4, 5, 6, 7], np.int32),
        np.tile([1, 4], 50).astype(np.int32),
    ]
    for path in cases:
        _assert_same(call_islands_device(path), _host(path))


def test_min_len_and_offset(rng):
    path = np.concatenate(
        [rng.choice([1, 2], size=300), [4], rng.choice([1, 2], size=150), [4]]
    ).astype(np.int32)
    _assert_same(
        call_islands_device(path, min_len=200),
        _host(path, min_len=200, chunk=0),
    )
    # offset shifts 1-based coordinates
    base = call_islands_device(path, min_len=200)
    dev = call_islands_device(path, min_len=200, offset=1000)
    np.testing.assert_array_equal(dev.beg, base.beg + 1000)
    np.testing.assert_array_equal(dev.end, base.end + 1000)


def test_cap_overflow_raises(rng):
    """The direct API still raises (callers own the retry policy); the
    exception carries the true count for a one-shot sufficient retry."""
    from cpgisland_tpu.ops.islands_device import IslandCapOverflow

    path = np.tile([1, 2, 4], 100).astype(np.int32)  # many 2-long islands
    with pytest.raises(IslandCapOverflow, match="cap") as ei:
        call_islands_device(path, cap=4)
    assert ei.value.n == 100 and ei.value.cap == 4
    # retrying at the carried count succeeds and matches the host caller
    _assert_same(call_islands_device(path, cap=ei.value.n), _host(path))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_decode_file_survives_cap_overflow(tmp_path, rng, caplog, monkeypatch):
    """An island-saturated input must complete through decode_file with a
    tiny island_cap — the pipeline auto-raises the cap and re-runs only the
    calling pass (VERDICT r3 #5) — and emit exactly the host engine's calls,
    through BOTH the batched small-record path and the sharded large-record
    path."""
    import logging

    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    # Make the first record count as "large" so the sharded decode_one path
    # exercises the retry too (not just the batched flush).
    monkeypatch.setattr(pipeline, "SMALL_RECORD_MAX", 4000)
    fa = tmp_path / "sat.fa"
    with open(fa, "w") as f:
        # island-dense records: alternating pure-CG runs (gc=1.0, oe=2.0 —
        # unambiguous islands) and TA background runs
        for name, reps in (("big", 40), ("s1", 3), ("s2", 2)):
            f.write(f">{name}\n")
            s = ("cg" * 30 + "ta" * 30) * reps
            for i in range(0, len(s), 70):
                f.write(s[i : i + 70] + "\n")
    params = presets.durbin_cpg8()
    host = pipeline.decode_file(str(fa), params, compat=False,
                                island_engine="host")
    with caplog.at_level(logging.WARNING, logger="cpgisland_tpu.pipeline"):
        dev = pipeline.decode_file(str(fa), params, compat=False,
                                   island_engine="device", island_cap=8)
    assert len(dev.calls) == len(host.calls) > 8
    overflows = [r for r in caplog.records if "overflowed cap" in r.getMessage()]
    # The raised cap is LEARNED for the rest of the file: the big record
    # overflows once; the later small-record flush starts at the grown cap.
    assert len(overflows) == 1
    np.testing.assert_array_equal(dev.calls.names, host.calls.names)
    _assert_same(dev.calls, host.calls)


def test_cap_retry_ceiling(monkeypatch):
    """Beyond ISLAND_CAP_CEILING the retry refuses to escalate (a degenerate
    input must fail with the clear cap error, not an opaque device OOM)."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.ops.islands_device import IslandCapOverflow

    monkeypatch.setattr(pipeline, "ISLAND_CAP_CEILING", 16)
    path = np.tile([1, 2, 4], 100).astype(np.int32)  # 100 tiny islands
    box = [4]
    with pytest.raises(IslandCapOverflow, match="cap"):
        pipeline._device_calls_retry(call_islands_device, path, cap_box=box)
    assert box[0] == 4  # no escalation recorded past the refusal


def test_device_array_input(rng):
    path = rng.integers(0, 8, size=2048).astype(np.int32)
    _assert_same(call_islands_device(jnp.asarray(path)), _host(path))


def test_empty_path():
    out = call_islands_device(np.zeros(0, np.int32))
    assert len(out) == 0


def test_long_island_no_int32_overflow(rng):
    """A 120k-symbol GC-rich run has c*g ~ 3.6e9 > 2^31: the oe product must
    not wrap negative and silently drop the island (r2 review finding)."""
    path = np.concatenate(
        [[4], np.tile([1, 2], 60_000), [4]]
    ).astype(np.int32)
    dev = call_islands_device(path)
    host = _host(path)
    assert len(host) == 1
    assert len(dev) == 1
    np.testing.assert_array_equal(dev.beg, host.beg)
    np.testing.assert_allclose(dev.oe_ratio, host.oe_ratio, rtol=1e-5)


def _island_path(c, g, cg, length):
    """One island run realizing exact (C, G, CpG, len) counts.

    Layout [4] [G]*(g-cg) [C G]*cg [A] [C]*(c-cg) [A]*pad [4]: the only
    C->G adjacencies are the cg pairs.  Requires length >= c + g + 1.
    """
    pad = length - c - g - 1
    assert pad >= 0 and cg <= min(c, g)
    body = (
        [2] * (g - cg) + [1, 2] * cg + [0] + [1] * (c - cg) + [0] * pad
    )
    assert len(body) == length
    return np.array([4] + body + [4], np.int32)


@pytest.mark.parametrize(
    "c,g,cg,length,kept",
    [
        # f64 oe = 0.6000000397... > 0.6 but the f32 product chain lands
        # exactly ON f32(0.6): a pure-f32 device filter DROPS this true call.
        (2971, 1693, 629, 4798, True),
        # exact tie: oe == 0.6 in both precisions -> both callers drop.
        (25, 30, 5, 90, False),
        # one CpG short of the tie -> clearly below, dropped.
        (25, 30, 4, 90, False),
        # one CpG above the tie -> clearly above, kept.
        (25, 30, 6, 90, True),
    ],
)
def test_oe_threshold_near_boundary_bit_exact(c, g, cg, length, kept):
    """Near-threshold oe decisions match the host caller exactly (VERDICT r3
    #7): the device band-keeps borderline runs and the host f64 refine makes
    the final call, so no f32 rounding can flip an emit decision."""
    path = _island_path(c, g, cg, length)
    host = _host(path)
    dev = call_islands_device(path)
    assert len(host) == (1 if kept else 0)
    _assert_same(dev, host)


def test_gc_threshold_nondefault_band_refine(rng):
    """A non-0.5 gc threshold takes the banded-f32 + f64-refine route (the
    default is integer-exact on device); decisions must still match host."""
    # gc exactly 0.55: 11/20 C+G in a 20-long island.
    path = _island_path(6, 5, 3, 20)
    for thr in (0.55, 0.549999, 0.550001):
        host = _host(path, gc_threshold=thr)
        dev = call_islands_device(path, gc_threshold=thr)
        _assert_same(dev, host)


def test_blocked_scan_boundaries(rng):
    """The calling reduction is BLOCKED (lax.scan over time-blocks, r4: a
    whole-record formulation OOMed at 320 Mi symbols); with a tiny block
    width, runs and CpG pairs straddling block boundaries — including runs
    spanning several whole blocks — must come out identical to the host
    caller, for both the 8-state and the observation-based engines."""
    from cpgisland_tpu.ops.islands_device import (
        _device_calls,
        call_islands_device_obs,
    )

    # Random islandy path: many runs of random lengths around the 1 Ki
    # minimum block width would not cross blocks, so drive _device_calls
    # directly at block_w=1024 with multi-Ki runs.
    parts = []
    for _ in range(40):
        parts.append(rng.integers(4, 8, size=rng.integers(1, 700)))
        parts.append(rng.choice([1, 2], size=rng.integers(500, 3000)))
    path = np.concatenate(parts).astype(np.int32)
    cols = _device_calls(path, 1 << 17, None, 0.5, 0.6, block_w=1024)
    from cpgisland_tpu.ops.islands_device import _fetch_calls

    dev = _fetch_calls(cols, 1 << 17, 0, 0.5, 0.6)
    _assert_same(dev, _host(path))

    # A C at the last position of one block followed by G at the first of
    # the next must still count as ONE CpG event: build an exact fixture.
    W = 1024
    p = np.full(3 * W, 4, np.int32)
    p[W - 300 : W + 300] = 1  # C+ run crossing the 1st boundary
    p[W + 300 : W + 600] = 2  # then G+ (CG pair exactly inside the run)
    p[W - 1] = 1
    p[W] = 2  # explicit C|G straddling the boundary (inside the run)
    cols = _device_calls(p, 1 << 17, None, 0.5, 0.6, block_w=W)
    dev = _fetch_calls(cols, 1 << 17, 0, 0.5, 0.6)
    _assert_same(dev, _host(p))

    # Observation-based engine with runs >> block width (spanning multiple
    # whole blocks).
    T = 6000
    path2 = np.zeros(T, np.int32)
    path2[:200] = 1
    path2[5800:] = 1  # background heads/tails; 5600-long island run
    obs = rng.integers(0, 4, size=T).astype(np.uint8)
    from cpgisland_tpu.ops import islands as host_islands
    from cpgisland_tpu.ops.islands_device import _device_calls_obs

    cols = _device_calls_obs(
        jnp.asarray(path2), jnp.asarray(obs), (0,), 1 << 17, None, 0.5, 0.6,
        block_w=1024,
    )
    dev = _fetch_calls(cols, 1 << 17, 0, 0.5, 0.6)
    host = host_islands.call_islands_obs(path2, obs, island_states=(0,))
    _assert_same(dev, host)


def test_decode_file_island_engine_parity(tmp_path, rng):
    """decode_file(island_engine='device') == 'host' on a planted-island file."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">c\n")
        parts = []
        for _ in range(3):
            parts.append(rng.choice(list("acgt"), size=3000, p=[0.35, 0.15, 0.15, 0.35]))
            parts.append(rng.choice(list("acgt"), size=700, p=[0.08, 0.42, 0.42, 0.08]))
        s = "".join(np.concatenate(parts))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    host = pipeline.decode_file(str(fa), presets.durbin_cpg8(), compat=False,
                                island_engine="host")
    dev = pipeline.decode_file(str(fa), presets.durbin_cpg8(), compat=False,
                               island_engine="device")
    assert len(dev.calls) == len(host.calls) > 0
    np.testing.assert_array_equal(dev.calls.beg, host.calls.beg)
    np.testing.assert_array_equal(dev.calls.end, host.calls.end)
    np.testing.assert_allclose(dev.calls.gc_content, host.calls.gc_content, rtol=2e-6)
    np.testing.assert_allclose(dev.calls.oe_ratio, host.calls.oe_ratio, rtol=2e-6)


def _write_multiscaffold(tmp_path, rng, sizes):
    fa = tmp_path / "multi.fa"
    with open(fa, "w") as f:
        for i, n in enumerate(sizes):
            f.write(f">scaf{i}\n")
            parts = [rng.choice(list("acgt"), size=max(1, n - 700), p=[0.35, 0.15, 0.15, 0.35])]
            if n > 700:
                parts.append(rng.choice(list("acgt"), size=700, p=[0.08, 0.42, 0.42, 0.08]))
            s = "".join(np.concatenate(parts))[:n]
            for j in range(0, len(s), 70):
                f.write(s[j : j + 70] + "\n")
    return fa


def test_decode_file_small_record_batching_parity(tmp_path, rng):
    """Many small scaffolds take the batched vmap path; records must keep
    their order, names, per-record coordinates, and exactly the calls the
    one-record-at-a-time path produces (device and host island engines)."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    sizes = [1500, 4100, 900, 2300, 3700, 1100, 2900, 1700, 2100, 1300, 999]
    fa = _write_multiscaffold(tmp_path, rng, sizes)
    params = presets.durbin_cpg8()

    batched_host = pipeline.decode_file(str(fa), params, compat=False,
                                        island_engine="host")
    batched_dev = pipeline.decode_file(str(fa), params, compat=False,
                                       island_engine="device")
    # Reference: force the serial path by making every record "large".
    serial = pipeline.decode_file(str(fa), params, compat=False,
                                  island_engine="host", device_batch=1)
    for got in (batched_host, batched_dev):
        assert len(got.calls) == len(serial.calls) > 0
        np.testing.assert_array_equal(got.calls.names, serial.calls.names)
        np.testing.assert_array_equal(got.calls.beg, serial.calls.beg)
        np.testing.assert_array_equal(got.calls.end, serial.calls.end)
        np.testing.assert_allclose(got.calls.gc_content, serial.calls.gc_content, rtol=2e-6)
        np.testing.assert_allclose(got.calls.oe_ratio, serial.calls.oe_ratio, rtol=2e-6)


def test_decode_file_mixed_large_small_preserves_order(tmp_path, rng, monkeypatch):
    """A large record between small ones must flush the pending batch first
    so the output record order matches the file order."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    # Shrink the 'large' threshold so the middle record takes the sharded path.
    monkeypatch.setattr(pipeline, "SMALL_RECORD_MAX", 2000)
    sizes = [1500, 900, 5000, 1100, 1300]
    fa = _write_multiscaffold(tmp_path, rng, sizes)
    res = pipeline.decode_file(str(fa), presets.durbin_cpg8(), compat=False,
                               island_engine="host")
    names = list(dict.fromkeys(res.calls.names))
    expect = [f"scaf{i}" for i in range(5) if f"scaf{i}" in set(res.calls.names)]
    assert names == expect


def test_decode_file_state_path_out_through_batching(tmp_path, rng):
    """state_path_out forces the host island engine, but small records still
    take the batched vmap decode — the dumped path must equal the serial
    per-record decode concatenation."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    sizes = [1500, 900, 2300, 1100]
    fa = _write_multiscaffold(tmp_path, rng, sizes)
    params = presets.durbin_cpg8()
    p_batched = tmp_path / "batched.npy"
    p_serial = tmp_path / "serial.npy"
    pipeline.decode_file(str(fa), params, compat=False,
                         state_path_out=str(p_batched))
    pipeline.decode_file(str(fa), params, compat=False,
                         state_path_out=str(p_serial), device_batch=1)
    a, b = np.load(p_batched), np.load(p_serial)
    assert a.shape == b.shape == (sum(sizes),)
    np.testing.assert_array_equal(a, b)


def test_decode_file_island_engine_validation(tmp_path):
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    fa = tmp_path / "g.fa"
    fa.write_text(">c\nacgtacgt\n")
    with pytest.raises(ValueError, match="island_engine"):
        pipeline.decode_file(str(fa), presets.durbin_cpg8(), island_engine="gpu")
    # device caller can't reproduce compat quirks or dump the state path
    with pytest.raises(ValueError, match="clean-mode"):
        pipeline.decode_file(str(fa), presets.durbin_cpg8(), compat=True,
                             island_engine="device")
    with pytest.raises(ValueError, match="clean-mode"):
        pipeline.decode_file(
            str(fa), presets.durbin_cpg8(), compat=False,
            island_engine="device", state_path_out=str(tmp_path / "p.npy"),
        )


def test_obs_caller_matches_host_random(rng):
    """Device observation-based caller == host call_islands_obs: membership
    from arbitrary island_states, composition from the observations."""
    from cpgisland_tpu.ops.islands_device import call_islands_device_obs

    for T in (1, 7, 1000, 4097):
        path = rng.integers(0, 2, size=T).astype(np.int32)  # two_state model
        obs = rng.integers(0, 4, size=T).astype(np.uint8)
        dev = call_islands_device_obs(path, obs, island_states=(0,))
        host = host_islands.call_islands_obs(path, obs, island_states=(0,))
        _assert_same(dev, host)


def test_obs_caller_matches_host_islandy(rng):
    from cpgisland_tpu.ops.islands_device import call_islands_device_obs

    parts_p, parts_o = [], []
    for _ in range(25):
        n1, n2 = rng.integers(1, 300), rng.integers(1, 400)
        parts_p += [np.ones(n1, np.int32), np.zeros(n2, np.int32)]
        parts_o += [
            rng.choice([0, 3], size=n1),
            rng.choice([1, 2], size=n2),
        ]
    path = np.concatenate(parts_p)
    obs = np.concatenate(parts_o).astype(np.uint8)
    dev = call_islands_device_obs(
        path, obs, island_states=(0,), min_len=100, offset=7
    )
    host = host_islands.call_islands_obs(
        path, obs, island_states=(0,), min_len=100, offset=7
    )
    _assert_same(dev, host)


def test_obs_caller_multi_state_set(rng):
    """An 8-state model called through the obs-based device path with the
    island set (0,1,2,3) must agree with the host obs caller."""
    from cpgisland_tpu.ops.islands_device import call_islands_device_obs

    T = 3000
    path = rng.integers(0, 8, size=T).astype(np.int32)
    obs = rng.integers(0, 4, size=T).astype(np.uint8)
    dev = call_islands_device_obs(path, obs, island_states=(0, 1, 2, 3))
    host = host_islands.call_islands_obs(path, obs, island_states=(0, 1, 2, 3))
    _assert_same(dev, host)


def test_pipeline_two_state_device_engine(tmp_path, rng):
    """decode_file with the two_state preset + island_engine='device' equals
    the host engine end to end (VERDICT r2 #7), incl. the batched small-
    record path (two scaffolds) and a large record."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        for name, nlen in (("chrA", 9000), ("s1", 1200), ("s2", 800)):
            f.write(f">{name}\n")
            parts = []
            remaining = nlen
            while remaining > 0:
                bg = min(remaining, int(rng.integers(400, 1200)))
                parts.append(rng.choice(list("acgt"), size=bg, p=[.35,.15,.15,.35]))
                remaining -= bg
                if remaining <= 0:
                    break
                isl = min(remaining, int(rng.integers(200, 500)))
                parts.append(rng.choice(list("acgt"), size=isl, p=[.08,.42,.42,.08]))
                remaining -= isl
            s = "".join(np.concatenate(parts))
            for i in range(0, len(s), 70):
                f.write(s[i : i + 70] + "\n")
    params = presets.two_state_cpg()
    kw = dict(compat=False, island_states=(0,), device_batch=2)
    host = pipeline.decode_file(str(fa), params, island_engine="host", **kw)
    dev = pipeline.decode_file(str(fa), params, island_engine="device", **kw)
    assert len(host.calls) > 0
    _assert_same(dev.calls, host.calls)
    np.testing.assert_array_equal(dev.calls.names, host.calls.names)
