"""REAL multi-process distributed training test (2 processes over local TCP).

tests/test_multihost_input.py checks the shard-selection math in one process;
this test actually runs `jax.distributed` with two processes x 4 virtual CPU
devices each (Gloo collectives over loopback — the same code path a TPU pod
takes over DCN), through the framework's own entry points:

    initialize_multihost -> make_mesh (8 global devices)
    -> SpmdBackend.place (per-process input shard via process_shard
       + make_array_from_process_local_data)
    -> baum_welch.fit (shard_map E-step, psum all-reduce, M-step)

Both processes must converge to the SAME model, and that model must equal a
single-process 8-device run on the identical input — certifying that the
multi-host input-sharding + collective path changes nothing but the wiring.
Reference scope: the Hadoop cluster boundary, CpGIslandFinder.java:200-201.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from conftest import require_devices

from cpgisland_tpu.models import presets
from cpgisland_tpu.train import backends, baum_welch
from cpgisland_tpu.utils import chunking

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import json, os, sys

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.parallel.mesh import initialize_multihost, make_mesh
    from cpgisland_tpu.train import backends, baum_welch
    from cpgisland_tpu.utils import chunking

    coordinator, pid, fa_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    n_global = initialize_multihost(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert n_global == 8, n_global
    assert jax.process_count() == 2

    # Byte-range-sharded input: THIS process encodes only its ~half of the
    # file and assembles only its own chunk rows (a tiny count + boundary
    # spill exchange over the distributed client) — no process ever holds
    # the global batch (the file layer of the HDFS-input-split contract).
    shard = chunking.distributed_chunked(fa_path, 256, pad_multiple=8)
    assert shard.num_chunks * 2 == shard.global_rows
    backend = backends.SpmdBackend(mesh=make_mesh(8, axis="data"))
    res = baum_welch.fit(
        presets.durbin_cpg8(), shard, num_iters=2, convergence=0.0,
        backend=backend,
    )

    # The ORIGINAL global-batch path stays certified too: every process
    # holds the same global Chunked and place() keeps only its shard
    # (chunking.process_shard + make_array_from_process_local_data).
    from cpgisland_tpu.utils import codec

    chunked_global = chunking.frame(
        codec.encode_file(fa_path, skip_headers=True), 256
    )
    res_global = baum_welch.fit(
        presets.durbin_cpg8(), chunked_global, num_iters=2, convergence=0.0,
        backend=backends.SpmdBackend(mesh=make_mesh(8, axis="data")),
    )
    assert np.allclose(
        np.asarray(res_global.params.A), np.asarray(res.params.A),
        rtol=1e-6, atol=1e-8,
    ), "global-batch and byte-range-sharded inputs diverged"

    # USER ENTRY POINT routing (VERDICT r3 #1): pipeline.train_file itself —
    # not a hand-built LocalShard — must take the byte-range-sharded input
    # path in a multi-process job.  Instrumented: the whole-file encoders
    # are forbidden during the call, and THIS worker must have encoded at
    # most ~60% of the file (its own ~half plus line-boundary slack).
    from cpgisland_tpu import pipeline as pl
    from cpgisland_tpu.utils import codec as codec_mod

    total_syms = codec_mod.encode_file(fa_path, skip_headers=True).size
    encoded = []
    orig_ebr = codec_mod.encode_byte_range

    def spy_ebr(path, part, n_parts, **kw):
        out = orig_ebr(path, part, n_parts, **kw)
        encoded.append(out.size)
        return out

    def forbid(*a, **kw):
        raise AssertionError(
            "whole-file encode called in multi-host spmd train_file"
        )

    codec_mod.encode_byte_range = spy_ebr
    orig_ef, orig_efc = codec_mod.encode_file, codec_mod.encode_file_cached
    codec_mod.encode_file = codec_mod.encode_file_cached = forbid
    try:
        res_tf = pl.train_file(
            fa_path, compat=False, num_iters=2, convergence=0.0,
            backend=backends.SpmdBackend(mesh=make_mesh(8, axis="data")),
            chunk_size=256,
        )
    finally:
        codec_mod.encode_byte_range = orig_ebr
        codec_mod.encode_file, codec_mod.encode_file_cached = orig_ef, orig_efc
    assert sum(encoded) <= 0.6 * total_syms, (sum(encoded), total_syms)
    assert np.allclose(
        np.asarray(res_tf.params.A), np.asarray(res.params.A),
        rtol=1e-6, atol=1e-8,
    ), "train_file byte-range input diverged from the LocalShard fit"

    # Sequence-parallel decode across BOTH processes' devices: the host
    # materialization goes through process_allgather, so each process gets
    # the identical full path.
    from cpgisland_tpu.parallel.decode import viterbi_sharded

    rng = np.random.default_rng(42)
    obs = rng.integers(0, 4, size=8 * 512).astype(np.int32)
    path = viterbi_sharded(
        presets.durbin_cpg8(), obs, mesh=make_mesh(8, axis="seq"), block_size=128
    )

    # Sharded posterior across both processes' devices (soft decoding over
    # the DCN-path collectives; fetch uses the multi-host-safe gather).
    from cpgisland_tpu.parallel.posterior import posterior_sharded

    conf, _ = posterior_sharded(
        presets.durbin_cpg8(), obs.astype(np.uint8), (0, 1, 2, 3),
        mesh=make_mesh(8, axis="seq"), block_size=128,
    )
    assert conf.shape == obs.shape

    # DEVICE island calling on the multi-host global mesh (r4: the
    # single-process refusal is gone): the decoded path stays a
    # non-fully-addressable global array; only the compact [cap] call
    # columns are gathered — and they must equal the host caller run on
    # the (allgathered) same path.
    from jax.experimental import multihost_utils

    from cpgisland_tpu.ops import islands as host_islands
    from cpgisland_tpu.ops.islands_device import call_islands_device

    unit = np.array(([1] * 40 + [6] * 24) * 64, np.int32)  # planted runs
    obs_isl = np.where(unit == 1,
                       rng.integers(1, 3, size=unit.size),
                       rng.integers(0, 4, size=unit.size)).astype(np.int32)
    dev_path = viterbi_sharded(
        presets.durbin_cpg8(), obs_isl,
        mesh=make_mesh(8, axis="seq"), block_size=128, return_device=True,
    )
    assert not dev_path.is_fully_addressable  # really the global-mesh case
    dev_calls = call_islands_device(dev_path)
    host_calls = host_islands.call_islands(
        multihost_utils.process_allgather(dev_path, tiled=True), compat=False
    )
    assert len(dev_calls) > 0
    assert np.array_equal(dev_calls.beg, host_calls.beg)
    assert np.array_equal(dev_calls.end, host_calls.end)
    assert np.array_equal(dev_calls.oe_ratio, host_calls.oe_ratio)

    # posterior_file END-TO-END on the multi-host mesh with the device
    # island engine, confidence dump, and span threading all at once (r4
    # review: device engine + confidence_out used to crash fetching a
    # non-addressable conf array; spans exercise the transfer-total fetch
    # and the on-device int8 span concat too).
    import tempfile

    from cpgisland_tpu import pipeline as pl

    tdir = tempfile.mkdtemp()
    fa2 = os.path.join(tdir, "p.fa")
    nl = chr(10)
    with open(fa2, "w") as f:
        f.write(">c" + nl)
        s = ("cg" * 40 + "ta" * 40) * 30  # 4800 syms, unambiguous islands
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + nl)
    outs = {k: os.path.join(tdir, k) for k in
            ("cd.npy", "id.txt", "ch.npy", "ih.txt")}
    pl.posterior_file(fa2, presets.durbin_cpg8(),
                      confidence_out=outs["cd.npy"],
                      islands_out=outs["id.txt"],
                      island_engine="device", span=2048)
    pl.posterior_file(fa2, presets.durbin_cpg8(),
                      confidence_out=outs["ch.npy"],
                      islands_out=outs["ih.txt"],
                      island_engine="host", span=2048)
    isl_text = open(outs["id.txt"]).read()
    assert isl_text == open(outs["ih.txt"]).read()
    assert isl_text.count(nl) >= 2
    assert np.array_equal(np.load(outs["cd.npy"]), np.load(outs["ch.npy"]))

    print("RESULT " + json.dumps({
        "pid": pid,
        "A": np.asarray(res.params.A).tolist(),
        "pi": np.asarray(res.params.pi).tolist(),
        "logliks": [float(x) for x in res.logliks],
        "path_sum": int(np.asarray(path).sum()),
        "path_head": np.asarray(path)[:32].tolist(),
        "conf_sum": float(np.asarray(conf, np.float64).sum()),
        "n_dev_calls": len(dev_calls),
        "dev_beg": dev_calls.beg.tolist()[:16],
        "posterior_islands": isl_text.splitlines()[:4],
    }), flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_fit_matches_single_process(tmp_path):
    require_devices(8)
    from cpgisland_tpu.utils import compat

    if not compat.cpu_multiprocess_collectives():
        # jax 0.4.x XLA:CPU rejects cross-process computations
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"), which process_allgather — and the whole Gloo loopback
        # harness this test runs on — needs.  The code under test is
        # unchanged on TPU pods; this is a host-jax capability, not a
        # framework regression.
        import jax

        pytest.skip(
            f"jax {jax.__version__} CPU backend lacks multi-process "
            "collectives (process_allgather); needs jax >= 0.5"
        )
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    # The shared training FASTA both workers byte-range-shard.
    rng_fa = np.random.default_rng(7)
    fa = tmp_path / "train.fa"
    with open(fa, "w") as f:
        f.write(">train\n")
        s = "".join(np.array(list("acgt"))[rng_fa.integers(0, 4, size=16 * 256)])
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), coordinator, str(pid), str(fa)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in (0, 1)
    ]
    results = {}
    for pid, pr in enumerate(procs):
        out, _ = pr.communicate(timeout=540)
        assert pr.returncode == 0, f"proc {pid} failed:\n{out[-2000:]}"
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, f"proc {pid} printed no RESULT:\n{out[-2000:]}"
        results[pid] = json.loads(line[-1][len("RESULT "):])

    # Both processes agree bit-for-bit (they ran the same global program).
    np.testing.assert_array_equal(results[0]["A"], results[1]["A"])
    np.testing.assert_array_equal(results[0]["logliks"], results[1]["logliks"])
    assert results[0]["path_sum"] == results[1]["path_sum"]
    np.testing.assert_array_equal(results[0]["path_head"], results[1]["path_head"])

    # And match a single-process 8-device run on the identical input (the
    # file encoded whole — the layout the byte-range shards must reproduce).
    from cpgisland_tpu.utils import codec

    syms = codec.encode_file(str(fa), skip_headers=True)
    chunked = chunking.frame(syms, 256)
    from cpgisland_tpu.parallel.mesh import make_mesh

    ref = baum_welch.fit(
        presets.durbin_cpg8(), chunked, num_iters=2, convergence=0.0,
        backend=backends.SpmdBackend(mesh=make_mesh(8, axis="data")),
    )
    np.testing.assert_allclose(
        np.asarray(results[0]["A"]), np.asarray(ref.params.A), rtol=1e-6, atol=1e-8
    )
    np.testing.assert_allclose(
        np.asarray(results[0]["logliks"]), ref.logliks, rtol=1e-6
    )

    # The distributed decode equals the single-process sharded decode too
    # (same rng stream position as the workers' draw).
    from cpgisland_tpu.parallel.decode import viterbi_sharded
    from cpgisland_tpu.parallel.mesh import make_mesh as mk

    obs = np.random.default_rng(42).integers(0, 4, size=8 * 512).astype(np.int32)
    ref_path = viterbi_sharded(
        presets.durbin_cpg8(), obs, mesh=mk(8, axis="seq"), block_size=128
    )
    assert results[0]["path_sum"] == int(ref_path.sum())
    np.testing.assert_array_equal(results[0]["path_head"], ref_path[:32])

    # The distributed posterior agrees across processes and with the
    # single-process sharded run.
    from cpgisland_tpu.parallel.posterior import posterior_sharded

    assert results[0]["conf_sum"] == pytest.approx(results[1]["conf_sum"], rel=1e-9)
    ref_conf, _ = posterior_sharded(
        presets.durbin_cpg8(), obs.astype(np.uint8), (0, 1, 2, 3),
        mesh=mk(8, axis="seq"), block_size=128,
    )
    assert results[0]["conf_sum"] == pytest.approx(
        float(np.asarray(ref_conf, np.float64).sum()), rel=1e-5
    )

    # Device island calling on the global mesh: both processes fetched the
    # same compact call records (worker already asserted host parity).
    assert results[0]["n_dev_calls"] == results[1]["n_dev_calls"] > 0
    np.testing.assert_array_equal(results[0]["dev_beg"], results[1]["dev_beg"])
