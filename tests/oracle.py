"""Slow, obviously-correct NumPy oracles for HMM algorithms and island calling.

These implement textbook definitions (Rabiner 1989 / Durbin et al.) directly,
with no vectorization tricks, to pin down the semantics the JAX/Pallas code must
match (SURVEY.md §4 "Golden-model unit tests").  The island-caller oracle is a
faithful state machine with the reference's exact quirks (see
``islands_oracle`` docstring).
"""

from __future__ import annotations

import numpy as np


def viterbi_oracle(pi, A, B, obs):
    """Most-likely state path via textbook log-space Viterbi DP."""
    with np.errstate(divide="ignore"):
        lp, lA, lB = np.log(pi), np.log(A), np.log(B)
    T = len(obs)
    K = len(pi)
    delta = np.zeros((T, K))
    psi = np.zeros((T, K), dtype=np.int64)
    delta[0] = lp + lB[:, obs[0]]
    for t in range(1, T):
        for j in range(K):
            scores = delta[t - 1] + lA[:, j]
            psi[t, j] = np.argmax(scores)
            delta[t, j] = scores[psi[t, j]] + lB[j, obs[t]]
    path = np.zeros(T, dtype=np.int64)
    path[-1] = np.argmax(delta[-1])
    for t in range(T - 2, -1, -1):
        path[t] = psi[t + 1, path[t + 1]]
    return path, float(np.max(delta[-1]))


def forward_backward_oracle(pi, A, B, obs):
    """Scaled-space forward-backward (Rabiner scaling).

    Returns (gamma [T,K], xi_sum [K,K], loglik).
    """
    T = len(obs)
    K = len(pi)
    alpha = np.zeros((T, K))
    scale = np.zeros(T)
    alpha[0] = pi * B[:, obs[0]]
    scale[0] = alpha[0].sum()
    alpha[0] /= scale[0]
    for t in range(1, T):
        alpha[t] = (alpha[t - 1] @ A) * B[:, obs[t]]
        scale[t] = alpha[t].sum()
        alpha[t] /= scale[t]
    beta = np.zeros((T, K))
    beta[-1] = 1.0
    for t in range(T - 2, -1, -1):
        beta[t] = A @ (B[:, obs[t + 1]] * beta[t + 1])
        beta[t] /= scale[t + 1]
    gamma = alpha * beta
    gamma /= gamma.sum(axis=1, keepdims=True)
    xi_sum = np.zeros((K, K))
    for t in range(T - 1):
        xi = np.outer(alpha[t], B[:, obs[t + 1]] * beta[t + 1]) * A / scale[t + 1]
        xi_sum += xi
    return gamma, xi_sum, float(np.log(scale).sum())


def em_step_oracle(pi, A, B, sequences):
    """One Baum-Welch step over a list of independent sequences.

    Mirrors the Mahout MR contract (SURVEY.md C8): each sequence contributes
    expected initial/transition/emission counts (the mapper); counts are summed
    and row-normalized (the reducer).  Rows with zero expected count keep their
    previous distribution.
    """
    K, M = B.shape
    init_c = np.zeros(K)
    trans_c = np.zeros((K, K))
    emit_c = np.zeros((K, M))
    total_ll = 0.0
    for obs in sequences:
        gamma, xi_sum, ll = forward_backward_oracle(pi, A, B, obs)
        total_ll += ll
        init_c += gamma[0]
        trans_c += xi_sum
        for s in range(M):
            emit_c[:, s] += gamma[np.asarray(obs) == s].sum(axis=0)
    new_pi = init_c / init_c.sum() if init_c.sum() > 0 else pi
    new_A = A.copy()
    new_B = B.copy()
    for i in range(K):
        if trans_c[i].sum() > 0:
            new_A[i] = trans_c[i] / trans_c[i].sum()
        if emit_c[i].sum() > 0:
            new_B[i] = emit_c[i] / emit_c[i].sum()
    return new_pi, new_A, new_B, total_ll


def islands_oracle(path, chunk=0, chunk_size=0x100000):
    """Island calls from a state path — faithful port of the reference's inner
    state machine semantics (CpGIslandFinder.java:262-339), including quirks:

    - an island still open at the end of the path is never emitted (:269-339);
    - ``atC`` is NOT cleared when an island opens on a non-C state, so a CpG
      from the tail of the previous island can leak one spurious count (:325-331);
    - filters GC > 0.5 and O/E > 0.6; the len > 200 filter is commented out (:285).

    Returns list of (beg1, end1, length, gc_content, oe_ratio) with 1-based
    global coordinates beg + chunk*chunk_size + 1.
    """
    calls = []
    in_island = False
    beg = c_count = g_count = cg_count = island_len = 0
    at_c = False
    for i, val in enumerate(np.asarray(path)):
        if in_island:
            if val >= 4:
                in_island = False
                end = i - 1
                gc = (c_count + g_count) / island_len
                oe = 0.0
                if c_count != 0 and g_count != 0:
                    oe = (cg_count * island_len) / (c_count * g_count)
                if gc > 0.5 and oe > 0.6:
                    calls.append(
                        (beg + chunk * chunk_size + 1, end + chunk * chunk_size + 1, island_len, gc, oe)
                    )
            else:
                island_len += 1
                if val == 2:
                    g_count += 1
                    if at_c:
                        cg_count += 1
                if val == 1:
                    c_count += 1
                    at_c = True
                else:
                    at_c = False
        else:
            if val <= 3:
                in_island = True
                island_len = 1
                cg_count = 0
                beg = i
                if val == 1:
                    c_count = 1
                    at_c = True  # NB: at_c deliberately NOT reset otherwise (:325-331)
                else:
                    c_count = 0
                g_count = 1 if val == 2 else 0
    return calls
