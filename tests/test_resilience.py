"""Resilient serving layer: supervisor, breaker, sentinel, manifest, and
the satellite hardenings (checkpoint latest, prefetch shutdown, codec
invalid-symbol policy).

The in-jit fault-injection proofs against the decode/posterior FILE paths
live in tests/test_fault_injection.py (they need the pure_callback probe);
this file covers the resilience subsystems' own contracts plus the
killed-then-resumed manifest byte-identity.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from cpgisland_tpu import obs, pipeline, resilience
from cpgisland_tpu.models import presets
from cpgisland_tpu.resilience import (
    DispatchSupervisor,
    EngineBreaker,
    IntegritySentinel,
    PhantomResult,
    RetryPolicy,
)
from cpgisland_tpu.resilience import manifest as manifest_mod


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    """Breaker trips and default-supervisor state must not leak between
    tests (or into other modules)."""
    resilience.reset()
    yield
    resilience.reset()


FAST = RetryPolicy(backoff_base_s=0.0)


def _write_fasta(path, rng, n_records=6, scale=1):
    bases = np.array(list("acgt"))
    with open(path, "w") as f:
        for r in range(n_records):
            f.write(f">rec{r}\n")
            n = (512 + 768 * r) * scale
            bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
            bg[: n // 4] = rng.choice(4, size=n // 4, p=[0.1, 0.4, 0.4, 0.1])
            s = "".join(bases[bg])
            for i in range(0, len(s), 70):
                f.write(s[i : i + 70] + "\n")
    return str(path)


# ---------------------------------------------------------------------------
# Dispatch supervisor


def test_supervisor_retries_transient_fault():
    sup = DispatchSupervisor(FAST)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] <= 2:
            raise RuntimeError("transient")
        return 42

    assert sup.run(flaky, what="t") == 42
    assert sup.retries == 2


def test_supervisor_gives_up_and_reraises():
    sup = DispatchSupervisor(RetryPolicy(max_retries=2, backoff_base_s=0.0))
    state = {"n": 0}

    def always():
        state["n"] += 1
        raise RuntimeError("persistent")

    with pytest.raises(RuntimeError, match="persistent"):
        sup.run(always, what="t")
    assert state["n"] == 3  # 1 attempt + 2 retries


def test_supervisor_passes_programming_errors_through():
    sup = DispatchSupervisor(FAST)
    state = {"n": 0}

    def bad():
        state["n"] += 1
        raise ValueError("not fault-shaped")

    with pytest.raises(ValueError):
        sup.run(bad, what="t")
    assert state["n"] == 1  # no retry


def test_supervisor_fallback_takes_over_after_first_failure():
    sup = DispatchSupervisor(FAST)
    calls = {"thunk": 0, "fb": 0}

    def thunk():
        calls["thunk"] += 1
        raise RuntimeError("poisoned deferred buffer")

    def fallback():
        calls["fb"] += 1
        return "recomputed"

    assert sup.run(thunk, what="t", fallback=fallback) == "recomputed"
    assert calls == {"thunk": 1, "fb": 1}


def test_supervisor_emits_ledgered_fault_events():
    with obs.observe() as ob:
        sup = DispatchSupervisor(FAST)
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] == 1:
                raise RuntimeError("boom")
            return 1

        sup.run(flaky, what="decode.span", engine="decode.xla", items=100.0)
    ev = [e for e in ob.events if e["event"] == "dispatch_fault"]
    assert len(ev) == 1
    assert ev[0]["what"] == "decode.span"
    assert ev[0]["engine"] == "decode.xla"
    assert ev[0]["will_retry"] is True
    assert "boom" in ev[0]["error"]


def test_supervisor_backoff_is_bounded_and_jittered():
    pol = RetryPolicy(backoff_base_s=1.0, backoff_factor=4.0, backoff_max_s=5.0)
    import random

    rng = random.Random(0)
    for attempt, base in ((1, 1.0), (2, 4.0), (3, 5.0), (9, 5.0)):
        for _ in range(10):
            d = pol.delay_s(attempt, rng)
            assert base * (1 - pol.jitter) <= d <= base * (1 + pol.jitter)


# ---------------------------------------------------------------------------
# Engine breaker / degradation ladder


def _clocked_breaker(threshold=2, cooldown_s=10.0):
    t = [0.0]
    br = EngineBreaker(
        threshold=threshold, cooldown_s=cooldown_s, clock=lambda: t[0]
    )
    return br, t


def test_breaker_trips_cools_down_and_restores():
    with obs.observe() as ob:
        br, t = _clocked_breaker()
        br.record_fault("decode.onehot")
        assert br.allowed("decode.onehot")  # below threshold
        br.record_fault("decode.onehot")
        assert not br.allowed("decode.onehot")  # tripped
        t[0] = 11.0
        assert br.allowed("decode.onehot")  # half-open probe admitted
        br.record_success("decode.onehot")
        assert br.allowed("decode.onehot")  # restored
    names = [e["event"] for e in ob.events]
    assert "engine_degraded" in names and "engine_restored" in names


def test_breaker_failed_probe_retrips():
    br, t = _clocked_breaker()
    br.record_fault("x")
    br.record_fault("x")
    t[0] = 11.0
    assert br.allowed("x")  # probe
    br.record_fault("x")  # probe failed
    assert not br.allowed("x")  # fresh cooldown from t=11
    t[0] = 20.0
    assert not br.allowed("x")
    t[0] = 21.5
    assert br.allowed("x")


def test_breaker_success_resets_consecutive_count():
    br, _ = _clocked_breaker(threshold=2)
    br.record_fault("x")
    br.record_success("x")
    br.record_fault("x")
    assert br.allowed("x")  # never reached 2 consecutive


def test_degrade_walks_ladder_to_untripped_rung():
    br, _ = _clocked_breaker(threshold=1)
    ladder = {"onehot": "pallas", "pallas": "xla"}.get
    br.record_fault("decode.onehot")
    br.record_fault("decode.pallas")
    assert br.degrade("decode", "onehot", ladder) == "xla"
    # The last rung runs even when tripped (an exact answer beats none).
    br.record_fault("decode.xla")
    assert br.degrade("decode", "xla", ladder) == "xla"


@pytest.fixture
def fake_tpu(monkeypatch):
    """Routing-only TPU impersonation: the resolve_* functions consult
    jax.default_backend() and pure host-side supports() predicates — no
    device work happens, so the auto-routing demotion paths (whose fast
    rungs are TPU-only) are testable on the CPU mesh."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")


def test_resolve_engine_demotes_tripped_auto_choice(fake_tpu):
    from cpgisland_tpu.parallel import decode as decode_mod

    params = presets.durbin_cpg8()
    br, _ = _clocked_breaker(threshold=1)
    resilience.set_breaker(br)
    assert decode_mod.resolve_engine("auto", params) == "onehot"
    br.record_fault("decode.onehot")
    assert decode_mod.resolve_engine("auto", params) == "pallas"
    br.record_fault("decode.pallas")
    assert decode_mod.resolve_engine("auto", params) == "xla"
    # EXPLICIT requests bypass the breaker: a named engine must actually
    # run (bench/parity measurements certify that specific lowering).
    assert decode_mod.resolve_engine("onehot", params) == "onehot"


def test_resolve_fb_engine_demotes_tripped_auto_choice(fake_tpu):
    from cpgisland_tpu.parallel import posterior as posterior_mod

    params = presets.durbin_cpg8()
    br, _ = _clocked_breaker(threshold=1)
    resilience.set_breaker(br)
    assert posterior_mod.resolve_fb_engine("auto", params) == "onehot"
    br.record_fault("fb.onehot")
    assert posterior_mod.resolve_fb_engine("auto", params) == "pallas"
    assert posterior_mod.resolve_fb_engine("onehot", params) == "onehot"


def test_train_resolve_fb_engine_demotes_tripped_auto_choice(fake_tpu):
    from cpgisland_tpu.train import backends

    params = presets.durbin_cpg8()
    br, _ = _clocked_breaker(threshold=1)
    resilience.set_breaker(br)
    assert backends.resolve_fb_engine("auto", params, "rescaled") == "onehot"
    br.record_fault("em.onehot")
    assert backends.resolve_fb_engine("auto", params, "rescaled") == "pallas"
    assert backends.resolve_fb_engine("pallas", params, "rescaled") == "pallas"


def test_island_engine_demotes_to_host_when_tripped(fake_tpu):
    br, _ = _clocked_breaker(threshold=1)
    resilience.set_breaker(br)
    use_dev, _cap = pipeline._resolve_island_engine(
        "auto", device_eligible=True, ineligible_msg="x", island_cap=None
    )
    assert use_dev is True
    br.record_fault("islands.device")
    with obs.observe() as ob:
        use_dev, _cap = pipeline._resolve_island_engine(
            "auto", device_eligible=True, ineligible_msg="x", island_cap=None
        )
    assert use_dev is False  # parity twin: the host caller
    decisions = [e for e in ob.events if e["event"] == "engine_decision"]
    assert any(
        e.get("site") == "islands.breaker_demotion" for e in decisions
    )
    # An explicit 'device' request is honored even while tripped.
    use_dev, _cap = pipeline._resolve_island_engine(
        "device", device_eligible=True, ineligible_msg="x", island_cap=None
    )
    assert use_dev is True


# ---------------------------------------------------------------------------
# Integrity sentinel


def test_sentinel_passes_healthy_result():
    s = IntegritySentinel()
    s.verify(np.arange(8, dtype=np.float32), what="decode.record",
             items=8.0, seconds=1.0)
    assert s.checks == 1 and not s.violations


def test_sentinel_canary_detects_stale_and_supervisor_redispatches(monkeypatch):
    s = IntegritySentinel()
    real = s._canary_value
    state = {"n": 0}

    def stale_once(probe, seed):
        state["n"] += 1
        if state["n"] == 1:
            return -1.0  # a reply that cannot match the fresh seed fold
        return real(probe, seed)

    monkeypatch.setattr(s, "_canary_value", stale_once)
    with obs.observe() as ob:
        sup = DispatchSupervisor(FAST, sentinel=s)
        out = sup.run(lambda: np.arange(3), what="decode.record")
    np.testing.assert_array_equal(out, np.arange(3))
    assert sup.retries == 1
    assert s.violations and s.violations[0]["kind"] == "canary_mismatch"
    assert any(e["event"] == "integrity_violation" for e in ob.events)


def test_sentinel_flags_implausible_throughput():
    s = IntegritySentinel(canary=False)
    with pytest.raises(PhantomResult, match="implausible_throughput"):
        s.verify(
            np.zeros(4), what="decode.record", items=1e12, seconds=1e-6
        )


def test_sentinel_nan_result_is_flagged():
    s = IntegritySentinel()
    with pytest.raises(PhantomResult):
        s.verify(
            np.full(4, np.nan, np.float32), what="posterior.record",
            items=4.0, seconds=1.0,
        )


def test_decode_file_integrity_check_runs_clean(tmp_path, rng):
    """End-to-end: --integrity-check on a healthy run changes nothing but
    performs one canary check per supervised unit."""
    fa = _write_fasta(tmp_path / "g.fa", rng, n_records=4)
    params = presets.durbin_cpg8()

    def run(**kw):
        out = io.StringIO()
        pipeline.decode_file(
            fa, params, islands_out=out, compat=False, span=2048, **kw
        )
        return out.getvalue()

    plain = run()
    checked = run(integrity_check=True)
    assert plain == checked and plain.count("\n") >= 2


# ---------------------------------------------------------------------------
# Manifest: wire exactness + killed-then-resumed byte identity


def test_calls_wire_roundtrip_bit_exact(rng):
    from cpgisland_tpu.ops.islands import IslandCalls

    n = 57
    calls = IslandCalls(
        beg=rng.integers(1, 1 << 40, n).astype(np.int64),
        end=rng.integers(1, 1 << 40, n).astype(np.int64),
        length=rng.integers(1, 1 << 20, n).astype(np.int64),
        gc_content=rng.random(n),
        oe_ratio=rng.random(n) * 3.0,
    ).with_names("chrX")
    back = manifest_mod.calls_from_wire(
        json.loads(json.dumps(manifest_mod.calls_to_wire(calls)))
    )
    for f in ("beg", "end", "length", "gc_content", "oe_ratio"):
        a, b = getattr(calls, f), getattr(back, f)
        np.testing.assert_array_equal(a, b)
        assert a.dtype == b.dtype
    assert list(back.names) == list(calls.names)
    assert manifest_mod.calls_from_wire(None) is None


def test_manifest_header_mismatch_starts_fresh(tmp_path, caplog):
    p = str(tmp_path / "m.jsonl")
    with manifest_mod.RunManifest(p, header={"mode": "decode", "k": 1},
                                  resume=False) as m:
        m.record_done(0, "r0", 100, calls=None)
    with manifest_mod.RunManifest(p, header={"mode": "decode", "k": 2},
                                  resume=True) as m2:
        assert m2.completed(0, "r0", 100) is None  # discarded, recompute


def test_manifest_tolerates_truncated_tail(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with manifest_mod.RunManifest(p, header={"mode": "decode"},
                                  resume=False) as m:
        m.record_done(0, "r0", 100, calls=None, conf_sum=1.5)
        m.record_done(1, "r1", 200, calls=None)
    with open(p, "a") as f:
        f.write('{"kind": "record", "index": 2, "na')  # killed mid-append
    with manifest_mod.RunManifest(p, header={"mode": "decode"},
                                  resume=True) as m2:
        assert m2.completed(0, "r0", 100) is not None
        assert m2.completed(1, "r1", 200) is not None
        assert m2.completed(2, "r2", 300) is None
        # Identity mismatch on a completed index recomputes loudly.
        assert m2.completed(0, "OTHER", 100) is None
        # The resumed manifest TRUNCATED the partial tail before appending
        # — a new record must start on its own line, not merge into the
        # garbage and poison the NEXT resume's parse.
        m2.record_done(2, "r2", 300, calls=None)
    with manifest_mod.RunManifest(p, header={"mode": "decode"},
                                  resume=True) as m3:
        assert m3.completed(0, "r0", 100) is not None
        assert m3.completed(1, "r1", 200) is not None
        assert m3.completed(2, "r2", 300) is not None


def test_decode_killed_then_resumed_is_byte_identical(tmp_path, rng, monkeypatch):
    fa = _write_fasta(tmp_path / "g.fa", rng, n_records=6)
    params = presets.durbin_cpg8()
    man_full = str(tmp_path / "full.manifest.jsonl")

    def run(islands_path, manifest_path, resume):
        pipeline.decode_file(
            fa, params, islands_out=str(islands_path), compat=False,
            span=2048, device_batch=1, manifest_path=manifest_path,
            resume=resume,
        )
        return islands_path.read_text()

    full_txt = run(tmp_path / "full.txt", man_full, False)
    assert full_txt.count("\n") >= 3

    # Simulate a killed run: keep the header + the first 3 completed
    # records of the manifest.
    lines = open(man_full).read().splitlines(True)
    head, recs = lines[0], [ln for ln in lines[1:]
                            if json.loads(ln)["kind"] == "record"]
    man_part = tmp_path / "part.manifest.jsonl"
    man_part.write_text("".join([head] + recs[:3]))

    # Count decode dispatches: completed records must not recompute.
    from cpgisland_tpu.parallel import decode as decode_mod

    calls = {"n": 0}
    real_sharded = decode_mod.viterbi_sharded
    real_spans = decode_mod.viterbi_sharded_spans

    def count_sharded(*a, **k):
        calls["n"] += 1
        return real_sharded(*a, **k)

    def count_spans(*a, **k):
        calls["n"] += 1
        return real_spans(*a, **k)

    monkeypatch.setattr(pipeline, "viterbi_sharded", count_sharded)
    monkeypatch.setattr(pipeline, "viterbi_sharded_spans", count_spans)
    resumed_txt = run(tmp_path / "resumed.txt", str(man_part), True)
    assert resumed_txt == full_txt
    assert calls["n"] == 3  # only the 3 uncompleted records decoded
    # The resumed manifest now marks everything complete: a second resume
    # decodes nothing.
    calls["n"] = 0
    again = run(tmp_path / "again.txt", str(man_part), True)
    assert again == full_txt and calls["n"] == 0


def test_posterior_killed_then_resumed_identical(tmp_path, rng):
    fa = _write_fasta(tmp_path / "p.fa", rng, n_records=5)
    params = presets.durbin_cpg8()
    man_full = str(tmp_path / "p.manifest.jsonl")

    def run(manifest_path, resume):
        out = io.StringIO()
        res = pipeline.posterior_file(
            fa, params, islands_out=out, span=2048,
            manifest_path=manifest_path, resume=resume,
        )
        return out.getvalue(), res.mean_island_confidence

    full_txt, full_conf = run(man_full, False)
    lines = open(man_full).read().splitlines(True)
    head, recs = lines[0], [ln for ln in lines[1:]
                            if json.loads(ln)["kind"] == "record"]
    man_part = tmp_path / "pp.manifest.jsonl"
    man_part.write_text("".join([head] + recs[:2]))
    resumed_txt, resumed_conf = run(str(man_part), True)
    assert resumed_txt == full_txt
    assert resumed_conf == full_conf  # exact: per-record f64 sums replayed


def test_manifest_rejects_per_symbol_outputs(tmp_path, rng):
    fa = _write_fasta(tmp_path / "g.fa", rng, n_records=2)
    with pytest.raises(ValueError, match="per-symbol"):
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), islands_out=str(tmp_path / "i.txt"),
            compat=False, resume=True,
            state_path_out=str(tmp_path / "p.npy"),
        )
    with pytest.raises(ValueError, match="per-symbol"):
        pipeline.posterior_file(
            fa, presets.durbin_cpg8(), islands_out=str(tmp_path / "i.txt"),
            confidence_out=str(tmp_path / "c.npy"), resume=True,
        )


# ---------------------------------------------------------------------------
# Satellite: checkpoint corruption tolerance


def test_latest_skips_corrupt_checkpoints(tmp_path, caplog):
    from cpgisland_tpu.utils import checkpoint as ckpt

    st = ckpt.TrainState(params=presets.durbin_cpg8(), iteration=3,
                         logliks=[-10.0, -9.0])
    good = str(tmp_path / "ckpt_000003.npz")
    ckpt.save(good, st)
    # A newer but truncated snapshot (killed mid-write / unsynced pages).
    (tmp_path / "ckpt_000007.npz").write_bytes(b"PK\x03\x04garbage")
    import logging

    with caplog.at_level(logging.WARNING):
        assert ckpt.latest(str(tmp_path)) == good
    assert any("corrupt" in r.message for r in caplog.records)
    # Old name-only behavior stays available.
    assert ckpt.latest(str(tmp_path), validate=False).endswith("000007.npz")
    # All corrupt -> None (resume starts fresh instead of crashing).
    (tmp_path / "ckpt_000003.npz").write_bytes(b"")
    assert ckpt.latest(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# Satellite: prefetch shutdown determinism


def test_serial_closer_closes_generator_on_consumer_error():
    from cpgisland_tpu.utils.prefetch import maybe_prefetch

    closed = []

    def gen():
        try:
            for i in range(100):
                yield ("r", i)
        finally:
            closed.append(True)

    it, close = maybe_prefetch(gen(), 0, "x")
    assert next(iter(it))[1] == 0
    close()  # the consumer-error finally path
    assert closed


def test_stuck_producer_finalizer_closes_generator():
    from cpgisland_tpu.utils.prefetch import RecordPrefetcher

    release = threading.Event()
    closed = []

    def gen():
        try:
            yield ("r", 1)
            release.wait()  # producer stuck inside next(it)
            yield ("r", 2)
        finally:
            closed.append(True)

    pf = RecordPrefetcher(gen(), depth=1, join_timeout_s=0.3)
    assert next(pf)[1] == 1
    pf.close()  # join times out; a finalizer thread takes over
    assert not closed  # cannot close a generator another thread is running
    release.set()
    for _ in range(200):
        if closed:
            break
        time.sleep(0.02)
    assert closed


def test_prefetcher_close_drains_full_queue_producer():
    """A producer blocked on a FULL queue at close time exits promptly (the
    incremental drain+join), not via the timeout path."""
    from cpgisland_tpu.utils.prefetch import RecordPrefetcher

    def gen():
        for i in range(10_000):
            yield ("r", i)

    pf = RecordPrefetcher(gen(), depth=1, join_timeout_s=5.0)
    time.sleep(0.2)  # producer fills the queue and blocks on put
    t0 = time.perf_counter()
    pf.close()
    assert time.perf_counter() - t0 < 2.0
    assert not pf._thread.is_alive()


# ---------------------------------------------------------------------------
# Satellite: codec invalid-symbol policy


def test_codec_policies():
    from cpgisland_tpu.utils import codec

    assert codec.encode("AC\nN5GT").tolist() == [0, 1, 2, 3]  # skip (default)
    assert codec.encode("ACNGT", invalid="mask").tolist() == [0, 1, 4, 2, 3]
    with pytest.raises(codec.InvalidSymbolError) as ei:
        codec.encode("AC\nNGT", invalid="fail")
    assert ei.value.count == 1 and ei.value.first_byte == ord("N")
    # Whitespace is structural, never invalid.
    assert codec.encode("AC \t\nGT", invalid="fail").tolist() == [0, 1, 2, 3]
    with pytest.raises(ValueError, match="policy"):
        codec.encode("ACGT", invalid="nope")


def test_codec_policy_counts_surface_as_obs_event(tmp_path, rng):
    from cpgisland_tpu.utils import codec

    p = tmp_path / "n.fa"
    p.write_text(">r1\nACGNNTACGT\nNN\n>r2\nACGT\n")
    with obs.observe() as ob:
        recs = list(codec.iter_fasta_records(str(p), invalid="mask"))
    assert [n for n, _ in recs] == ["r1", "r2"]
    assert recs[0][1].tolist() == [0, 1, 2, 4, 4, 3, 0, 1, 2, 3, 4, 4]
    ev = [e for e in ob.events if e["event"] == "invalid_symbols"]
    assert len(ev) == 1 and ev[0]["count"] == 4 and ev[0]["policy"] == "mask"


def test_decode_file_invalid_symbol_policies(tmp_path, rng):
    """mask preserves FASTA coordinates (N -> PAD identity steps); fail
    aborts; compat rejects non-skip policies."""
    from cpgisland_tpu.utils import codec

    fa = tmp_path / "n.fa"
    body = "acgt" * 400 + "nnnn" + "cgcg" * 400
    fa.write_text(">chr\n" + "\n".join(
        body[i : i + 70] for i in range(0, len(body), 70)
    ) + "\n")
    params = presets.durbin_cpg8()

    def run(policy):
        out = io.StringIO()
        res = pipeline.decode_file(
            fa.as_posix(), params, islands_out=out, compat=False,
            invalid_symbols=policy,
        )
        return res, out.getvalue()

    res_skip, _ = run("skip")
    res_mask, _ = run("mask")
    assert res_mask.n_symbols == res_skip.n_symbols + 4  # Ns kept as PAD
    with pytest.raises(codec.InvalidSymbolError):
        run("fail")
    with pytest.raises(ValueError, match="clean mode"):
        pipeline.decode_file(
            fa.as_posix(), params, islands_out=io.StringIO(), compat=True,
            invalid_symbols="mask",
        )
