"""EM trainer: oracle parity, monotonicity, convergence, checkpoints, backends."""

import os

import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.train import backends, baum_welch
from cpgisland_tpu.utils import chunking
from tests import oracle


def _chunked(rng, n=4, t=64):
    syms = rng.integers(0, 4, size=n * t).astype(np.uint8)
    return chunking.frame(syms, t)


def _random_model(rng, k=3, m=4):
    pi = rng.dirichlet(np.ones(k))
    A = rng.dirichlet(np.ones(k), size=k)
    B = rng.dirichlet(np.ones(m), size=k)
    return pi, A, B


def test_single_em_step_matches_oracle(rng):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    ck = _chunked(rng, n=3, t=50)
    res = baum_welch.fit(params, ck, num_iters=1, convergence=0.0)
    opi, oA, oB, _ = oracle.em_step_oracle(pi, A, B, list(ck.chunks))
    np.testing.assert_allclose(np.asarray(res.params.pi), opi, atol=1e-3)
    np.testing.assert_allclose(np.asarray(res.params.A), oA, atol=1e-3)
    np.testing.assert_allclose(np.asarray(res.params.B), oB, atol=1e-3)


def test_loglik_monotone_nondecreasing(rng):
    pi, A, B = _random_model(rng, k=4)
    params = HmmParams.from_probs(pi, A, B)
    ck = _chunked(rng, n=4, t=128)
    res = baum_welch.fit(params, ck, num_iters=8, convergence=0.0)
    lls = res.logliks
    assert all(b >= a - 1e-2 for a, b in zip(lls, lls[1:])), lls


def test_convergence_stops_early(rng):
    params = presets.durbin_cpg8()
    ck = _chunked(rng, n=2, t=256)
    res = baum_welch.fit(params, ck, num_iters=50, convergence=0.01)
    assert res.converged
    assert res.iterations < 50
    assert res.deltas[-1] < 0.01


def test_structural_zeros_preserved_through_training(rng):
    params = presets.durbin_cpg8()
    ck = _chunked(rng, n=2, t=256)
    res = baum_welch.fit(params, ck, num_iters=3, convergence=0.0)
    B = np.asarray(res.params.B)
    B0 = np.asarray(params.B)
    assert (B[B0 == 0] == 0).all()
    np.testing.assert_allclose(B[B0 == 1.0], 1.0, atol=1e-6)


def test_spmd_backend_matches_local(rng):
    from conftest import require_devices

    require_devices(8)
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    ck = _chunked(rng, n=16, t=64)
    local = baum_welch.fit(params, ck, num_iters=2, convergence=0.0, backend="local")
    spmd = baum_welch.fit(params, ck, num_iters=2, convergence=0.0, backend="spmd")
    np.testing.assert_allclose(
        np.asarray(spmd.params.A), np.asarray(local.params.A), atol=1e-4
    )
    assert spmd.logliks[0] == pytest.approx(local.logliks[0], rel=1e-5)


def test_spmd_backend_pads_uneven_batches(rng):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    ck = _chunked(rng, n=5, t=64)  # 5 chunks over 8 devices -> padded to 8
    local = baum_welch.fit(params, ck, num_iters=1, convergence=0.0, backend="local")
    spmd = baum_welch.fit(params, ck, num_iters=1, convergence=0.0, backend="spmd")
    np.testing.assert_allclose(
        np.asarray(spmd.params.A), np.asarray(local.params.A), atol=1e-4
    )


def test_rescaled_mode_training_agrees_with_log(rng):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    ck = _chunked(rng, n=3, t=128)
    a = baum_welch.fit(params, ck, num_iters=2, convergence=0.0, mode="log")
    b = baum_welch.fit(params, ck, num_iters=2, convergence=0.0, mode="rescaled")
    np.testing.assert_allclose(np.asarray(a.params.A), np.asarray(b.params.A), atol=1e-3)


def test_checkpoint_and_resume(tmp_path, rng):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    ck = _chunked(rng, n=3, t=64)
    full = baum_welch.fit(params, ck, num_iters=4, convergence=0.0)
    partial = baum_welch.fit(
        params, ck, num_iters=2, convergence=0.0, checkpoint_dir=str(tmp_path)
    )
    assert len(list(tmp_path.glob("ckpt_*.npz"))) == 2
    resumed = baum_welch.resume(str(tmp_path), ck, num_iters=4, convergence=0.0)
    assert resumed.iterations == 4
    assert len(resumed.logliks) == 4
    np.testing.assert_allclose(
        np.asarray(resumed.params.A), np.asarray(full.params.A), atol=2e-4
    )


def test_mstep_zero_count_rows_keep_previous():
    from cpgisland_tpu.ops.forward_backward import SuffStats
    import jax.numpy as jnp

    params = presets.two_state_cpg()
    stats = SuffStats.zeros(2, 4)
    from conftest import tpu_atol

    new = baum_welch.mstep(params, stats)
    # TPU's approximate exp/log round trip costs ~2e-5 relative; CPU stays tight.
    np.testing.assert_allclose(np.asarray(new.A), np.asarray(params.A), atol=tpu_atol(1e-6))
    np.testing.assert_allclose(np.asarray(new.B), np.asarray(params.B), atol=tpu_atol(1e-6))


def test_long_chunk_loglik_monotone_rescaled(rng):
    """Regression: f32 log-mode E-step loses monotonicity on long chunks (the
    alpha+beta-loglik cancellation); the rescaled default must not.  46 Kbp of
    island/background mixture, full EM run, loglik strictly non-decreasing."""
    bg = rng.choice(4, size=40000, p=[0.3, 0.2, 0.2, 0.3])
    isl = rng.choice(4, size=6000, p=[0.1, 0.4, 0.4, 0.1])
    syms = np.concatenate([bg[:20000], isl, bg[20000:]]).astype(np.uint8)
    ck = chunking.frame(syms, 0x10000, drop_remainder=False)
    res = baum_welch.fit(
        presets.durbin_cpg8(), ck, num_iters=8, convergence=0.0, mode="rescaled"
    )
    lls = res.logliks
    assert all(b >= a - 1e-2 for a, b in zip(lls, lls[1:])), lls


def test_orbax_checkpoint_roundtrip_and_resume(tmp_path, rng):
    """Orbax-format checkpoints: save per iteration, auto-detected load,
    resume over a directory of orbax snapshots (SURVEY.md §5)."""
    from cpgisland_tpu.utils import checkpoint as ckpt

    params = presets.durbin_cpg8()
    syms = rng.integers(0, 4, size=8 * 512).astype(np.uint8)
    ck = chunking.frame(syms, 512)
    res = baum_welch.fit(
        params, ck, num_iters=2, convergence=0.0,
        checkpoint_dir=str(tmp_path), checkpoint_format="orbax",
    )
    path = ckpt.latest(str(tmp_path))
    assert path is not None and os.path.isdir(path)  # orbax = directory
    state = ckpt.load(path)
    assert state.iteration == 2
    # The save path materializes exp(log_A) on device, so the values pass
    # through TPU's approximate transcendentals once more than res's.
    from conftest import tpu_atol

    np.testing.assert_allclose(np.asarray(state.params.A), np.asarray(res.params.A), atol=tpu_atol(1e-6))
    assert state.logliks == pytest.approx(res.logliks)

    res2 = baum_welch.resume(str(tmp_path), ck, num_iters=4, convergence=0.0)
    assert res2.iterations == 4
    assert len(res2.logliks) == 4


def test_latest_prefers_highest_across_formats(tmp_path):
    from cpgisland_tpu.utils import checkpoint as ckpt

    params = presets.durbin_cpg8()
    ckpt.save(ckpt.checkpoint_path(str(tmp_path), 1), ckpt.TrainState(params, 1, [-5.0]))
    ckpt.save(
        ckpt.checkpoint_path(str(tmp_path), 2, format="orbax"),
        ckpt.TrainState(params, 2, [-5.0, -4.0]),
        format="orbax",
    )
    assert ckpt.load(ckpt.latest(str(tmp_path))).iteration == 2


def test_resume_preserves_orbax_format(tmp_path, rng):
    from cpgisland_tpu.utils import checkpoint as ckpt

    params = presets.durbin_cpg8()
    ck = chunking.frame(rng.integers(0, 4, size=4 * 512).astype(np.uint8), 512)
    baum_welch.fit(params, ck, num_iters=1, convergence=0.0,
                   checkpoint_dir=str(tmp_path), checkpoint_format="orbax")
    baum_welch.resume(str(tmp_path), ck, num_iters=2, convergence=0.0)
    latest = ckpt.latest(str(tmp_path))
    assert os.path.isdir(latest)  # iteration 2 written in orbax, not npz
    with pytest.raises(ValueError, match="checkpoint_format"):
        baum_welch.fit(params, ck, num_iters=1, checkpoint_dir=str(tmp_path),
                       checkpoint_format="orbx")


def _fit_pair(params, ck, **kw):
    """(host-loop result, fused result) on identical inputs."""
    host = baum_welch.fit(params, ck, fuse=False, **kw)
    fused = baum_welch.fit(params, ck, fuse=True, **kw)
    return host, fused


@pytest.mark.parametrize("engine", ["xla", "onehot"])
def test_fused_em_trajectory_matches_host_loop(rng, engine):
    """The fused lax.while_loop EM reproduces the host loop's full
    K-iteration param/loglik/delta trajectory (dense and reduced one-hot
    engines) — same math, one compiled program instead of K round trips."""
    params = presets.durbin_cpg8()
    ck = _chunked(rng, n=6, t=512)
    host, fused = _fit_pair(
        params, ck, num_iters=5, convergence=0.0, engine=engine
    )
    assert fused.iterations == host.iterations == 5
    np.testing.assert_allclose(fused.logliks, host.logliks, rtol=1e-5)
    np.testing.assert_allclose(fused.deltas, host.deltas, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(fused.params.A), np.asarray(host.params.A), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(fused.params.B), np.asarray(host.params.B), atol=1e-5
    )


def test_fused_em_convergence_early_exit(rng):
    """The on-device model-delta test stops the fused loop at the SAME
    iteration as the host loop's host-side check."""
    params = presets.durbin_cpg8()
    ck = _chunked(rng, n=2, t=256)
    host, fused = _fit_pair(params, ck, num_iters=50, convergence=0.01)
    assert fused.converged and host.converged
    assert fused.iterations == host.iterations < 50
    assert len(fused.logliks) == fused.iterations
    assert fused.deltas[-1] < 0.01
    np.testing.assert_allclose(fused.logliks, host.logliks, rtol=1e-5)


def test_fused_em_spmd_backend(rng):
    """The fused loop traces the shard_map E-step (psum all-reduce inside
    the while_loop) and matches the local host loop."""
    from conftest import require_devices

    require_devices(8)
    params = presets.durbin_cpg8()
    ck = _chunked(rng, n=16, t=128)
    host = baum_welch.fit(
        params, ck, num_iters=2, convergence=0.0, backend="local", fuse=False
    )
    fused = baum_welch.fit(
        params, ck, num_iters=2, convergence=0.0, backend="spmd", fuse=True
    )
    np.testing.assert_allclose(
        np.asarray(fused.params.A), np.asarray(host.params.A), atol=1e-4
    )
    assert fused.logliks[0] == pytest.approx(host.logliks[0], rel=1e-5)


def test_fused_em_ledger_dispatches_and_compiles(rng):
    """ACCEPTANCE (obs-ledger-asserted): 10 fused steady-state EM
    iterations compile once and pay <= 2 blocking dispatches, vs >= 10 on
    the host loop — the latency contract the fused driver exists for.
    The prepared-streams half of the acceptance (zero stream
    re-preparation in steady state) lives in
    tests/test_prepared.py::test_fused_em_steady_state_zero_repreps, on
    the reduced engine where a prepared form exists."""
    import jax.numpy as jnp

    from cpgisland_tpu import obs

    params = presets.durbin_cpg8()
    raw = _chunked(rng, n=4, t=512)
    # Pre-placed device arrays: the measured region is the loop cadence,
    # not the one-time training-data upload (which both cadences share).
    ck = chunking.Chunked(
        chunks=jnp.asarray(raw.chunks), lengths=jnp.asarray(raw.lengths),
        total=raw.total,
    )
    baum_welch.fit(params, ck, num_iters=10, convergence=0.0, fuse=True)
    baum_welch.fit(params, ck, num_iters=10, convergence=0.0, fuse=False)
    with obs.observe() as ob:
        snap = ob.ledger.snapshot()
        # Steady state: the warmed fused program must not recompile.
        with obs.no_new_compiles("fused-em-steady"):
            res = baum_welch.fit(
                params, ck, num_iters=10, convergence=0.0, fuse=True
            )
        fused_d = ob.ledger.delta(snap)
        snap = ob.ledger.snapshot()
        baum_welch.fit(params, ck, num_iters=10, convergence=0.0, fuse=False)
        host_d = ob.ledger.delta(snap)
    assert res.iterations == 10
    assert fused_d["dispatches"] <= 2, fused_d
    assert host_d["dispatches"] >= 10, host_d


def test_fused_em_requires_host_cadence_features_off(rng, tmp_path):
    """fuse=True conflicts with host-cadence features; fuse='auto' silently
    keeps the host loop for them (checkpoints still written)."""
    params = presets.durbin_cpg8()
    ck = _chunked(rng, n=2, t=128)
    with pytest.raises(ValueError, match="checkpointing"):
        baum_welch.fit(
            params, ck, num_iters=1, convergence=0.0, fuse=True,
            checkpoint_dir=str(tmp_path),
        )
    with pytest.raises(ValueError, match="callback"):
        baum_welch.fit(
            params, ck, num_iters=1, convergence=0.0, fuse=True,
            callback=lambda *a: None,
        )

    # A backend with no traceable stats fn: fuse=True errors, auto hosts.
    class OpaqueBackend(backends.EStepBackend):
        def __call__(self, params, chunks, lengths):
            return backends.LocalBackend()(params, chunks, lengths)

    with pytest.raises(ValueError, match="fused"):
        baum_welch.fit(
            params, ck, num_iters=1, convergence=0.0, fuse=True,
            backend=OpaqueBackend(),
        )
    res = baum_welch.fit(
        params, ck, num_iters=2, convergence=0.0, checkpoint_dir=str(tmp_path)
    )
    assert res.iterations == 2
    assert len(list(tmp_path.glob("ckpt_*.npz"))) == 2


def test_fused_em_auto_falls_back_to_host_recovery(rng):
    """fuse='auto' must not cost callers the host loop's fault recovery: a
    fused run whose statistics blow up falls back to the host-loop cadence
    (per-iteration retry/validation) and completes; explicit fuse=True
    keeps the hard error."""
    import jax.numpy as jnp

    from cpgisland_tpu.ops.forward_backward import SuffStats

    class PoisonedFusedBackend(backends.LocalBackend):
        """Healthy host-loop E-step; NaN-producing fused stats fn."""

        def fused_stats_fn(self, params, chunks, lengths):
            inner = super().fused_stats_fn(params, chunks, lengths)

            def poisoned(p, c, l):
                st = inner(p, c, l)
                # Poison the loglik (not the counts: mstep's zero-row
                # fallback silently repairs non-finite count rows).
                return SuffStats(
                    init=st.init, trans=st.trans, emit=st.emit,
                    loglik=st.loglik * jnp.nan, n_seqs=st.n_seqs,
                )

            return poisoned

    params = presets.durbin_cpg8()
    ck = _chunked(rng, n=2, t=128)
    res = baum_welch.fit(
        params, ck, num_iters=2, convergence=0.0,
        backend=PoisonedFusedBackend(),
    )
    assert res.iterations == 2
    assert all(np.isfinite(ll) for ll in res.logliks)
    with pytest.raises(FloatingPointError):
        baum_welch.fit(
            params, ck, num_iters=2, convergence=0.0,
            backend=PoisonedFusedBackend(), fuse=True,
        )


def test_seq_shard_budget_guard():
    """Oversize whole-sequence shards fail FAST with advice (r4: a 128 Mi
    single-chip shard died in an opaque remote-compile HTTP 500 after the
    upload; a 16 GB chip's measured budget is ~120 Mi)."""
    import jax.numpy as jnp

    from cpgisland_tpu.parallel.mesh import make_mesh

    backend = backends.SeqBackend(mesh=make_mesh(1, axis="seq"))
    # The guard fires on SHAPE alone, before any kernel work.
    n = backends.SEQ_SHARD_BUDGET + backend.block_size
    obs = jnp.zeros(n, jnp.uint8)
    lens = jnp.zeros(1, jnp.int32)
    with pytest.raises(ValueError, match="seq2d"):
        backend(presets.durbin_cpg8(), obs, lens)
