"""Model-core tests: presets, stochasticity, text dump round-trip (java:207-224)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams, dump_text, load_text


def test_durbin_preset_matches_reference_tables():
    m = presets.durbin_cpg8()
    m.validate()
    pi = np.asarray(m.pi)
    A = np.asarray(m.A)
    B = np.asarray(m.B)
    # Spot values from CpGIslandFinder.java:155-173.
    assert pi[0] == pytest.approx(0.05, rel=1e-4) and pi[4] == pytest.approx(0.2, rel=1e-4)
    assert A[0, 2] == pytest.approx(0.426, rel=1e-4)  # A+ -> G+
    assert A[5, 0] == pytest.approx(0.0025, rel=1e-4)  # C- -> A+ leakage
    assert A[5, 4] == pytest.approx(0.393, rel=1e-4)  # C- -> A-
    from conftest import tpu_atol

    # Rows sum to 1 by construction; TPU's exp(log(.)) round trip costs
    # ~2e-5 relative, CPU stays tight.
    np.testing.assert_allclose(A.sum(axis=1), 1.0, atol=tpu_atol(1e-5, 1e-4))
    # One-hot emissions: X+- emits x.
    np.testing.assert_allclose(B[np.arange(8), np.arange(8) % 4], 1.0, atol=tpu_atol(1e-7, 1e-4))
    assert np.count_nonzero(B) == 8


def test_state_names():
    assert presets.HIDDEN_STATE_NAMES == ("A+", "C+", "G+", "T+", "A-", "C-", "G-", "T-")
    assert presets.EMITTED_STATE_NAMES == ("a", "c", "g", "t")


def test_two_state_and_random_are_stochastic():
    presets.two_state_cpg().validate()
    presets.random_hmm(jax.random.key(0), 5, 4).validate()


def test_pytree_registration():
    m = presets.durbin_cpg8()
    leaves = jax.tree_util.tree_leaves(m)
    assert len(leaves) == 3
    m2 = jax.tree_util.tree_map(lambda x: x, m)
    assert isinstance(m2, HmmParams)


def test_log_zero_is_finite():
    m = presets.durbin_cpg8()
    assert np.isfinite(np.asarray(m.log_B)).all()
    np.testing.assert_allclose(np.asarray(m.B), np.where(np.asarray(m.B) > 0, np.asarray(m.B), 0.0))


def test_text_dump_roundtrip(tmp_path):
    m = presets.durbin_cpg8()
    p = tmp_path / "model.txt"
    dump_text(m, str(p))
    m2 = load_text(str(p))
    from conftest import tpu_atol

    # Text round trip; TPU adds its approximate exp/log on top.
    atol = tpu_atol(1e-5, 1e-4)
    np.testing.assert_allclose(np.asarray(m2.pi), np.asarray(m.pi), atol=atol)
    np.testing.assert_allclose(np.asarray(m2.A), np.asarray(m.A), atol=atol)
    np.testing.assert_allclose(np.asarray(m2.B), np.asarray(m.B), atol=atol)
    # Reference layout: 3 lines per state (pi / transition row / emission row).
    lines = p.read_text().splitlines()
    assert len(lines) == 24
    assert len(lines[1].split()) == 8 and len(lines[2].split()) == 4


def test_dump_text_accepts_file_object():
    buf = io.StringIO()
    dump_text(presets.two_state_cpg(), buf)
    buf.seek(0)
    m2 = load_text(io.StringIO(buf.read()))
    assert m2.n_states == 2 and m2.n_symbols == 4


def test_max_abs_diff():
    a = presets.durbin_cpg8()
    b = presets.durbin_cpg8()
    assert float(a.max_abs_diff(b)) == 0.0
    c = HmmParams.from_probs(np.asarray(a.pi), np.asarray(a.A), np.asarray(a.B) * 0 + 0.25)
    assert float(a.max_abs_diff(c)) == pytest.approx(0.75)


def test_from_probs_shape_validation():
    with pytest.raises(ValueError):
        HmmParams.from_probs(np.ones(3) / 3, np.eye(4), np.ones((3, 4)) / 4)
