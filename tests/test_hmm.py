"""Model-core tests: presets, stochasticity, text dump round-trip (java:207-224)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams, dump_text, load_text


def test_durbin_preset_matches_reference_tables():
    m = presets.durbin_cpg8()
    m.validate()
    pi = np.asarray(m.pi)
    A = np.asarray(m.A)
    B = np.asarray(m.B)
    # Spot values from CpGIslandFinder.java:155-173.
    assert pi[0] == pytest.approx(0.05, rel=1e-4) and pi[4] == pytest.approx(0.2, rel=1e-4)
    assert A[0, 2] == pytest.approx(0.426, rel=1e-4)  # A+ -> G+
    assert A[5, 0] == pytest.approx(0.0025, rel=1e-4)  # C- -> A+ leakage
    assert A[5, 4] == pytest.approx(0.393, rel=1e-4)  # C- -> A-
    from conftest import tpu_atol

    # Rows sum to 1 by construction; TPU's exp(log(.)) round trip costs
    # ~2e-5 relative, CPU stays tight.
    np.testing.assert_allclose(A.sum(axis=1), 1.0, atol=tpu_atol(1e-5, 1e-4))
    # One-hot emissions: X+- emits x.
    np.testing.assert_allclose(B[np.arange(8), np.arange(8) % 4], 1.0, atol=tpu_atol(1e-7, 1e-4))
    assert np.count_nonzero(B) == 8


def test_state_names():
    assert presets.HIDDEN_STATE_NAMES == ("A+", "C+", "G+", "T+", "A-", "C-", "G-", "T-")
    assert presets.EMITTED_STATE_NAMES == ("a", "c", "g", "t")


def test_two_state_and_random_are_stochastic():
    presets.two_state_cpg().validate()
    presets.random_hmm(jax.random.key(0), 5, 4).validate()


def test_pytree_registration():
    m = presets.durbin_cpg8()
    leaves = jax.tree_util.tree_leaves(m)
    assert len(leaves) == 3
    m2 = jax.tree_util.tree_map(lambda x: x, m)
    assert isinstance(m2, HmmParams)


def test_log_zero_is_finite():
    m = presets.durbin_cpg8()
    assert np.isfinite(np.asarray(m.log_B)).all()
    np.testing.assert_allclose(np.asarray(m.B), np.where(np.asarray(m.B) > 0, np.asarray(m.B), 0.0))


def test_text_dump_roundtrip(tmp_path):
    m = presets.durbin_cpg8()
    p = tmp_path / "model.txt"
    dump_text(m, str(p))
    m2 = load_text(str(p))
    from conftest import tpu_atol

    # Text round trip; TPU adds its approximate exp/log on top.
    atol = tpu_atol(1e-5, 1e-4)
    np.testing.assert_allclose(np.asarray(m2.pi), np.asarray(m.pi), atol=atol)
    np.testing.assert_allclose(np.asarray(m2.A), np.asarray(m.A), atol=atol)
    np.testing.assert_allclose(np.asarray(m2.B), np.asarray(m.B), atol=atol)
    # Reference layout: 3 lines per state (pi / transition row / emission row).
    lines = p.read_text().splitlines()
    assert len(lines) == 24
    assert len(lines[1].split()) == 8 and len(lines[2].split()) == 4


def test_java_double_str_golden():
    """Java Double.toString semantics, golden values (VERDICT r4 #7).

    The sub-1e-3 cases are the load-bearing ones: trained cross-block
    leakage probs sit exactly in the range where Java switches to
    scientific notation and Python repr does not.
    """
    from cpgisland_tpu.models.hmm import java_double_str

    cases = [  # pairs, not a dict: 0.0 and -0.0 are equal as dict keys
        (0.0, "0.0"),
        (-0.0, "-0.0"),
        (1.0, "1.0"),
        (-1.0, "-1.0"),
        (0.05, "0.05"),
        (0.2, "0.2"),
        (0.001, "0.001"),  # boundary: still decimal form
        (0.00025, "2.5E-4"),  # the reference's leakage-prob range
        (0.0009999, "9.999E-4"),
        (2.5e-7, "2.5E-7"),
        (1.25e-10, "1.25E-10"),
        (123.456, "123.456"),
        (100.0, "100.0"),
        (9999999.0, "9999999.0"),  # boundary: < 1e7 stays decimal
        (1e7, "1.0E7"),
        (1.5e300, "1.5E300"),
        (float("inf"), "Infinity"),
        (float("-inf"), "-Infinity"),
        (float("nan"), "NaN"),
        (0.9765624999999999, "0.9765624999999999"),  # shortest round-trip
    ]
    for v, want in cases:
        assert java_double_str(v) == want, (v, java_double_str(v), want)
    # Every formatted value must parse back exactly (load_text round-trip).
    for v, _ in cases:
        s = java_double_str(v)
        if s != "NaN":
            assert float(s) == v


def test_dump_text_sub_milli_scientific(tmp_path):
    """A model with probs in Double.toString's scientific range dumps them
    as Java would (d.dddE-4 scientific, never 0.000ddd) and round-trips.
    String asserts are format-level, not digit-level — the f32 parameter
    pipeline (exp∘log) perturbs 0.00025 by ~1 ulp before formatting."""
    pi = np.asarray([0.99975, 0.00025])
    A = np.asarray([[0.99975, 0.00025], [0.00025, 0.99975]])
    B = np.asarray([[0.9995, 0.0005, 0.0, 0.0], [0.0, 0.0, 0.5, 0.5]])
    m = HmmParams.from_probs(pi, A, B)
    p = tmp_path / "m.txt"
    dump_text(m, str(p))
    tokens = [t for line in p.read_text().splitlines() for t in line.split()]
    sub_milli = [t for t in tokens if 0 < float(t) < 1e-3]
    assert len(sub_milli) >= 3  # the 2.5e-4 / 5e-4 entries
    for t in sub_milli:
        assert "E-" in t, f"sub-1e-3 value {t!r} not in Java scientific form"
    for t in tokens:
        assert "e" not in t, f"{t!r} uses Python-style lowercase exponent"
    m2 = load_text(str(p))
    from conftest import tpu_atol

    # exp(log(.)) round trip: tight on CPU, ~2e-5 relative on TPU.
    np.testing.assert_allclose(np.asarray(m2.A), A, atol=tpu_atol(1e-6, 5e-5))


def test_dump_text_accepts_file_object():
    buf = io.StringIO()
    dump_text(presets.two_state_cpg(), buf)
    buf.seek(0)
    m2 = load_text(io.StringIO(buf.read()))
    assert m2.n_states == 2 and m2.n_symbols == 4


def test_max_abs_diff():
    a = presets.durbin_cpg8()
    b = presets.durbin_cpg8()
    assert float(a.max_abs_diff(b)) == 0.0
    c = HmmParams.from_probs(np.asarray(a.pi), np.asarray(a.A), np.asarray(a.B) * 0 + 0.25)
    assert float(a.max_abs_diff(c)) == pytest.approx(0.75)


def test_from_probs_shape_validation():
    with pytest.raises(ValueError):
        HmmParams.from_probs(np.ones(3) / 3, np.eye(4), np.ones((3, 4)) / 4)
