"""Sequence-parallel exact whole-sequence E-step (parallel/fb_sharded.py).

Anchors: the float64 NumPy oracle (tests/oracle.py) on the UNDIVIDED sequence —
the sharded statistics must match it, unlike the chunked backends whose
per-chunk independence approximation drops boundary pairs.  Runs on the 8-device
virtual CPU mesh from conftest.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import oracle
from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.parallel.fb_sharded import seq_stats_sharded, shard_sequence
from cpgisland_tpu.parallel.mesh import make_mesh
from cpgisland_tpu.train import baum_welch
from cpgisland_tpu.train.backends import SeqBackend
from cpgisland_tpu.utils import chunking


def _random_params(rng, K=3, M=4):
    pi = rng.dirichlet(np.ones(K))
    A = rng.dirichlet(np.ones(K), size=K)
    B = rng.dirichlet(np.ones(M), size=K)
    return pi, A, B, HmmParams.from_probs(pi, A, B)


def _oracle_stats(pi, A, B, obs):
    K, M = B.shape
    gamma, xi_sum, ll = oracle.forward_backward_oracle(pi, A, B, obs)
    emit = np.zeros((K, M))
    np.add.at(emit.T, obs, gamma)
    return gamma[0], xi_sum, emit, ll


from conftest import require_devices


@pytest.fixture
def mesh():
    require_devices(8)
    return make_mesh(8, axis="seq")


def test_matches_oracle_whole_sequence(rng, mesh):
    pi, A, B, params = _random_params(rng)
    obs = rng.integers(0, 4, size=5003).astype(np.uint8)  # ragged vs 8*64
    init_o, trans_o, emit_o, ll_o = _oracle_stats(pi, A, B, obs)

    stats = seq_stats_sharded(params, obs, mesh=mesh, block_size=64)
    assert float(stats.loglik) == pytest.approx(ll_o, abs=0.01)
    np.testing.assert_allclose(np.asarray(stats.init), init_o, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.trans), trans_o, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(stats.emit), emit_o, rtol=1e-4, atol=1e-4)
    assert int(stats.n_seqs) == 1


def test_counts_boundary_pairs_chunked_path_drops(rng, mesh):
    """Total expected transition count == T-1 exactly (every adjacent pair
    counted once, across all block and device boundaries)."""
    _, _, _, params = _random_params(rng)
    T = 4096
    obs = rng.integers(0, 4, size=T).astype(np.uint8)
    stats = seq_stats_sharded(params, obs, mesh=mesh, block_size=64)
    assert float(np.asarray(stats.trans).sum()) == pytest.approx(T - 1, rel=1e-4)
    assert float(np.asarray(stats.emit).sum()) == pytest.approx(T, rel=1e-4)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_durbin_preset_and_block_size_invariance(rng, mesh):
    params = presets.durbin_cpg8()
    obs = rng.integers(0, 4, size=2048 + 131).astype(np.uint8)
    s64 = seq_stats_sharded(params, obs, mesh=mesh, block_size=64)
    s256 = seq_stats_sharded(params, obs, mesh=mesh, block_size=256)
    np.testing.assert_allclose(np.asarray(s64.trans), np.asarray(s256.trans), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(s64.emit), np.asarray(s256.emit), rtol=2e-4, atol=2e-4)
    assert float(s64.loglik) == pytest.approx(float(s256.loglik), abs=0.05)


def test_tiny_sequence_mostly_padding(rng, mesh):
    """T far below n_devices * block_size: later shards are pure padding."""
    pi, A, B, params = _random_params(rng, K=2)
    obs = rng.integers(0, 4, size=37).astype(np.uint8)
    init_o, trans_o, emit_o, ll_o = _oracle_stats(pi, A, B, obs)
    stats = seq_stats_sharded(params, obs, mesh=mesh, block_size=64)
    assert float(stats.loglik) == pytest.approx(ll_o, abs=1e-3)
    np.testing.assert_allclose(np.asarray(stats.trans), trans_o, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.emit), emit_o, rtol=1e-4, atol=1e-5)


def test_shard_sequence_layout():
    obs = np.arange(100, dtype=np.uint8) % 4
    padded, lengths = shard_sequence(obs, 8, block_size=16)
    assert padded.shape[0] % (8 * 16) == 0
    L = padded.shape[0] // 8
    assert int(lengths.sum()) == 100
    # real symbols form a contiguous global prefix
    reassembled = np.concatenate([padded[d * L : d * L + lengths[d]] for d in range(8)])
    np.testing.assert_array_equal(reassembled, obs)


def test_em_step_matches_oracle_single_sequence(rng, mesh):
    """One full EM step through SeqBackend == oracle EM on the whole sequence."""
    pi, A, B, params = _random_params(rng)
    obs = rng.integers(0, 4, size=3000).astype(np.uint8)
    pi_o, A_o, B_o, _ = oracle.em_step_oracle(pi, A, B, [obs])

    backend = SeqBackend(mesh=mesh, block_size=64)
    chunked = chunking.frame(obs, 512)  # deliberately chunk-framed input
    res = baum_welch.fit(params, chunked, num_iters=1, convergence=0.0, backend=backend)
    got = res.params
    np.testing.assert_allclose(np.asarray(got.pi), pi_o, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got.A), A_o, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got.B), B_o, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("dp,sp", [(4, 2), (2, 4)])
def test_batch_2d_mesh_matches_per_sequence_oracle(rng, dp, sp):
    """dp x sp on one mesh: stats == sum of exact per-sequence oracle stats."""
    from cpgisland_tpu.parallel.fb_sharded import batch_seq_stats_sharded
    from cpgisland_tpu.parallel.mesh import make_mesh2d

    require_devices(8)
    pi, A, B, params = _random_params(rng)
    seqs = [rng.integers(0, 4, size=n).astype(np.uint8) for n in (701, 1203, 402)]
    init_o = np.zeros(3)
    trans_o = np.zeros((3, 3))
    emit_o = np.zeros((3, 4))
    ll_o = 0.0
    for s in seqs:
        i0, t0, e0, l0 = _oracle_stats(pi, A, B, s)
        init_o += i0
        trans_o += t0
        emit_o += e0
        ll_o += l0

    mesh2d = make_mesh2d(dp, sp)
    stats = batch_seq_stats_sharded(params, seqs, mesh=mesh2d, block_size=64)
    assert float(stats.loglik) == pytest.approx(ll_o, abs=0.02)
    np.testing.assert_allclose(np.asarray(stats.init), init_o, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.trans), trans_o, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(stats.emit), emit_o, rtol=1e-4, atol=1e-4)
    assert int(stats.n_seqs) == 3


def test_seq2d_backend_em_step_matches_oracle(rng):
    """fit() through Seq2DBackend: rows are whole sequences, dp x seq sharded."""
    from cpgisland_tpu.parallel.mesh import make_mesh2d
    from cpgisland_tpu.train.backends import Seq2DBackend

    require_devices(8)
    pi, A, B, params = _random_params(rng)
    seqs = [rng.integers(0, 4, size=n).astype(np.uint8) for n in (800, 650)]
    pi_o, A_o, B_o, _ = oracle.em_step_oracle(pi, A, B, seqs)

    T = max(len(s) for s in seqs)
    rows = np.full((2, T), 4, np.uint8)
    for i, s in enumerate(seqs):
        rows[i, : len(s)] = s
    chunked = chunking.Chunked(
        chunks=rows, lengths=np.array([len(s) for s in seqs], np.int32),
        total=sum(len(s) for s in seqs),
    )
    backend = Seq2DBackend(make_mesh2d(2, 4), block_size=64)
    res = baum_welch.fit(params, chunked, num_iters=1, convergence=0.0, backend=backend)
    np.testing.assert_allclose(np.asarray(res.params.pi), pi_o, atol=1e-5)
    np.testing.assert_allclose(np.asarray(res.params.A), A_o, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(res.params.B), B_o, rtol=1e-4, atol=1e-5)


def test_get_backend_rejects_mismatched_knobs():
    from cpgisland_tpu.train.backends import get_backend

    for name in ("seq", "seq2d"):
        with pytest.raises(ValueError, match="rescaled"):
            get_backend(name, mode="log")
        with pytest.raises(ValueError, match="engine"):
            get_backend(name, engine="bogus")
        # engine="pallas" is a valid explicit lowering for both (r1 had
        # Seq2DBackend inconsistently rejecting it).
        assert get_backend(name, engine="pallas") is not None
        assert get_backend(name) is not None


def test_em_loglik_monotone_seq_backend_any_devices(rng):
    """SeqBackend on however many devices exist (1 real chip included)."""
    _, _, _, params = _random_params(rng, K=2)
    obs = rng.integers(0, 4, size=4096).astype(np.uint8)
    backend = SeqBackend(block_size=128)  # default mesh: all devices
    res = baum_welch.fit(
        params, chunking.frame(obs, 1024), num_iters=3, convergence=0.0, backend=backend
    )
    lls = res.logliks
    assert all(b >= a - 1e-2 for a, b in zip(lls, lls[1:])), lls


def test_em_loglik_monotone_seq_backend(rng, mesh):
    _, _, _, params = _random_params(rng, K=2)
    obs = rng.integers(0, 4, size=8192).astype(np.uint8)
    backend = SeqBackend(mesh=mesh, block_size=128)
    res = baum_welch.fit(
        params, chunking.frame(obs, 1024), num_iters=6, convergence=0.0, backend=backend
    )
    lls = res.logliks
    assert all(b >= a - 1e-2 for a, b in zip(lls, lls[1:])), lls


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("dp,sp", [(2, 4), (4, 2)])
def test_batch_2d_pallas_engine_matches_xla(rng, dp, sp):
    """The fused-kernel lowering of the 2-D body == the XLA lanes body
    (kernels interpreted on the virtual mesh)."""
    from cpgisland_tpu.parallel.fb_sharded import pad_batch2d, place_batch2d, sharded_stats2d_fn
    from cpgisland_tpu.parallel.mesh import make_mesh2d

    require_devices(8)
    pi, A, B, params = _random_params(rng)
    seqs = [rng.integers(0, 4, size=n).astype(np.uint8) for n in (901, 1203, 402)]
    from cpgisland_tpu.parallel.fb_sharded import pack_ragged

    rows, lengths = pack_ragged(list(seqs), 4)
    mesh = make_mesh2d(dp, sp)
    obs, lens = pad_batch2d(rows, lengths, dp, sp, 64, 4)
    arr, l2 = place_batch2d(mesh, obs, lens)
    st_xla = sharded_stats2d_fn(mesh, 64, "xla")(params, arr, l2)
    st_pal = sharded_stats2d_fn(mesh, 64, "pallas")(params, arr, l2)
    np.testing.assert_allclose(np.asarray(st_pal.trans), np.asarray(st_xla.trans), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(st_pal.emit), np.asarray(st_xla.emit), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(st_pal.init), np.asarray(st_xla.init), atol=1e-4)
    assert float(st_pal.loglik) == pytest.approx(float(st_xla.loglik), abs=0.05)
    assert int(st_pal.n_seqs) == int(st_xla.n_seqs) == 3


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq2d_backend_explicit_pallas_engine_parity(rng):
    """Seq2DBackend(engine='pallas') — the knob, not just the underlying fn —
    matches engine='xla' through a full fit() on the 2-D mesh."""
    from cpgisland_tpu.parallel.mesh import make_mesh2d
    from cpgisland_tpu.train.backends import Seq2DBackend

    require_devices(8)
    _, _, _, params = _random_params(rng)
    seqs = [rng.integers(0, 4, size=n).astype(np.uint8) for n in (800, 650)]
    T = max(len(s) for s in seqs)
    rows = np.full((2, T), 4, np.uint8)
    for i, s in enumerate(seqs):
        rows[i, : len(s)] = s
    chunked = chunking.Chunked(
        chunks=rows, lengths=np.array([len(s) for s in seqs], np.int32),
        total=sum(len(s) for s in seqs),
    )
    kw = dict(block_size=64, lane_T=64, t_tile=64)
    res_p = baum_welch.fit(
        params, chunked, num_iters=1, convergence=0.0,
        backend=Seq2DBackend(make_mesh2d(2, 4), engine="pallas", **kw),
    )
    res_x = baum_welch.fit(
        params, chunked, num_iters=1, convergence=0.0,
        backend=Seq2DBackend(make_mesh2d(2, 4), engine="xla", **kw),
    )
    np.testing.assert_allclose(np.asarray(res_p.params.A), np.asarray(res_x.params.A), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(res_p.params.B), np.asarray(res_x.params.B), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(res_p.params.pi), np.asarray(res_x.params.pi), atol=1e-4)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_backend_explicit_engines(rng):
    """SeqBackend's new engine knob: explicit pallas == explicit xla, and an
    unsupported model errors instead of silently falling back."""
    require_devices(8)
    _, _, _, params = _random_params(rng)
    obs = rng.integers(0, 4, size=3000).astype(np.uint8)
    chunked = chunking.frame(obs, 512)
    mesh = make_mesh(8, axis="seq")
    kw = dict(mesh=mesh, block_size=64, lane_T=64, t_tile=64)
    res_p = baum_welch.fit(
        params, chunked, num_iters=1, convergence=0.0,
        backend=SeqBackend(engine="pallas", **kw),
    )
    res_x = baum_welch.fit(
        params, chunked, num_iters=1, convergence=0.0,
        backend=SeqBackend(engine="xla", **kw),
    )
    np.testing.assert_allclose(np.asarray(res_p.params.A), np.asarray(res_x.params.A), rtol=2e-4, atol=2e-4)

    big = HmmParams.from_probs(
        np.full(9, 1 / 9), np.full((9, 9), 1 / 9), np.full((9, 4), 0.25)
    )
    with pytest.raises(ValueError, match="support"):
        SeqBackend(engine="pallas", mesh=mesh, block_size=64)(
            big, jnp.asarray(obs[:2048]), jnp.asarray(np.full(8, 256, np.int32))
        )


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq2d_bucketed_matches_dense(rng):
    """Bucketed (host-memory-bounded) seq2d input produces the same
    statistics / fit trajectory as the dense [n_records, max_len] layout —
    per-group dp x sp meshes included (VERDICT r2 #2)."""
    from cpgisland_tpu.train import baum_welch
    from cpgisland_tpu.train.backends import Seq2DBackend
    from cpgisland_tpu.utils import chunking

    params = presets.durbin_cpg8()
    sizes = [900, 700, 2300, 150, 150, 150, 150, 400]
    records = [rng.integers(0, 4, size=n).astype(np.uint8) for n in sizes]

    rows = np.full((len(sizes), max(sizes)), 4, np.uint8)
    for i, r in enumerate(records):
        rows[i, : r.size] = r
    dense = chunking.Chunked(
        chunks=rows, lengths=np.asarray(sizes, np.int32), total=sum(sizes)
    )
    bucketed = chunking.bucket_records(
        iter(records), floor=256, budget=1024, pad_value=4
    )
    kw = dict(num_iters=2, convergence=0.0)
    r_dense = baum_welch.fit(
        params, dense, backend=Seq2DBackend(block_size=64), **kw
    )
    r_bucket = baum_welch.fit(
        params, bucketed, backend=Seq2DBackend(block_size=64), **kw
    )
    np.testing.assert_allclose(r_bucket.logliks, r_dense.logliks, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(r_bucket.params.log_A), np.asarray(r_dense.params.log_A),
        atol=1e-5,
    )


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq2d_small_record_rows_fast_path(rng):
    """Records that fit one kernel lane route to the whole-record-per-lane
    chunked fast path (fb_sharded.sharded_stats2d_rows_fn) on sp == 1
    meshes — exact vs the oracle (a whole record in one lane has no
    chunk-boundary approximation), agreeing with the generic
    sequence-parallel path."""
    from cpgisland_tpu.parallel.mesh import make_mesh2d
    from cpgisland_tpu.train.backends import SMALL_RECORD_ROWS_MAX, Seq2DBackend

    require_devices(8)
    pi, A, B, params = _random_params(rng)
    lens = (800, 650, 512, 333, 804, 100, 640, 720)
    seqs = [rng.integers(0, 4, size=n).astype(np.uint8) for n in lens]
    pi_o, A_o, B_o, _ = oracle.em_step_oracle(pi, A, B, seqs)

    T = max(lens)
    assert T <= SMALL_RECORD_ROWS_MAX
    rows = np.full((8, T), 4, np.uint8)
    for i, s in enumerate(seqs):
        rows[i, : len(s)] = s
    chunked = chunking.Chunked(
        chunks=rows, lengths=np.array(lens, np.int32), total=sum(lens)
    )
    res = baum_welch.fit(
        params, chunked, num_iters=1, convergence=0.0,
        backend=Seq2DBackend(make_mesh2d(8, 1)),
    )
    np.testing.assert_allclose(np.asarray(res.params.pi), pi_o, atol=1e-5)
    np.testing.assert_allclose(np.asarray(res.params.A), A_o, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(res.params.B), B_o, rtol=1e-4, atol=1e-5)
    # The generic sequence-parallel route (sp > 1 forces it) agrees.
    res2 = baum_welch.fit(
        params, chunked, num_iters=1, convergence=0.0,
        backend=Seq2DBackend(make_mesh2d(2, 4), block_size=64),
    )
    assert res.logliks[0] == pytest.approx(res2.logliks[0], rel=1e-5)
