"""Prepared-stream caching (ops.prepared): parity, cache keys, dispatch.

The contract under test (ISSUE 4 acceptance): prepared-vs-inline outputs
are BIT-IDENTICAL on every engine (the builders are the same code the
entries run inline), the identity-keyed cache invalidates on new arrays or
new geometry, reusing one prepared object across posterior -> EM adds no
fresh compiles, and the fused EM while_loop body contains no symbol-stream
prep primitives (with the synthetic-violation proof that the detector
actually detects).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu import obs as obs_mod
from cpgisland_tpu.models import presets
from cpgisland_tpu.ops import fb_pallas, prepared
from cpgisland_tpu.ops.viterbi_onehot import decode_batch_flat, prepare_decode_flat
from cpgisland_tpu.train import baum_welch
from cpgisland_tpu.train.backends import LocalBackend
from cpgisland_tpu.utils import chunking


@pytest.fixture
def rng():
    return np.random.default_rng(7)


@pytest.fixture
def params():
    return presets.durbin_cpg8()


def _chunks(rng, n=8, t=1024):
    chunks = jnp.asarray(rng.integers(0, 4, size=(n, t)).astype(np.uint8))
    lengths = jnp.asarray(
        rng.integers(t // 2, t + 1, size=n).astype(np.int32)
    )
    return chunks, lengths


def _assert_tree_bitwise(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("onehot", [False, True])
def test_chunked_prepared_vs_inline_bit_identity(rng, params, onehot):
    chunks, lengths = _chunks(rng)
    inline = fb_pallas.batch_stats_pallas(
        params, chunks, lengths, t_tile=256, onehot=onehot
    )
    prep = prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=onehot)
    with_prep = fb_pallas.batch_stats_pallas(
        params, chunks, lengths, t_tile=256, onehot=onehot, prepared=prep
    )
    _assert_tree_bitwise(inline, with_prep)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("onehot", [False, True])
def test_seq_prepared_vs_inline_bit_identity(rng, params, onehot):
    obs = jnp.asarray(rng.integers(0, 4, size=6000).astype(np.uint8))
    kw = dict(lane_T=512, t_tile=256, onehot=onehot)
    inline = fb_pallas.seq_stats_pallas(params, obs, 6000, **kw)
    prep = prepared.for_seq(4, obs, 6000, **kw)
    with_prep = fb_pallas.seq_stats_pallas(
        params, obs, 6000, prepared=prep, **kw
    )
    _assert_tree_bitwise(inline, with_prep)


@pytest.mark.parametrize("want_path", [False, True])
def test_posterior_prepared_vs_inline(rng, params, want_path):
    chunks, lengths = _chunks(rng, n=6, t=512)
    mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
    inline = fb_pallas.batch_posterior_pallas(
        params, chunks, lengths, mask, t_tile=256, want_path=want_path,
        onehot=True,
    )
    prep = prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=True)
    with_prep = fb_pallas.batch_posterior_pallas(
        params, chunks, lengths, mask, t_tile=256, want_path=want_path,
        onehot=True, prepared=prep,
    )
    _assert_tree_bitwise(inline, with_prep)


def test_seq_posterior_prepared_vs_inline(rng, params):
    obs = jnp.asarray(rng.integers(0, 4, size=6000).astype(np.uint8))
    mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
    kw = dict(lane_T=512, t_tile=256, onehot=True)
    c0, p0 = fb_pallas.seq_posterior_pallas(params, obs, 6000, mask, **kw)
    prep = prepared.for_seq(4, obs, 6000, **kw)
    c1, p1 = fb_pallas.seq_posterior_pallas(
        params, obs, 6000, mask, prepared=prep, **kw
    )
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))


def test_transfer_total_prepared_vs_inline(rng, params):
    obs = jnp.asarray(rng.integers(0, 4, size=6000).astype(np.uint8))
    kw = dict(lane_T=512, t_tile=256, onehot=True, first=True)
    t0 = fb_pallas.seq_transfer_total_pallas(params, obs, 6000, **kw)
    prep = prepared.for_seq(4, obs, 6000, lane_T=512, t_tile=256, onehot=True)
    t1 = fb_pallas.seq_transfer_total_pallas(
        params, obs, 6000, prepared=prep, **kw
    )
    np.testing.assert_array_equal(np.asarray(t0), np.asarray(t1))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_decode_flat_prepared_vs_inline(rng, params):
    chunks = jnp.asarray(rng.integers(0, 4, size=(4, 512)).astype(np.uint8))
    lengths = jnp.full(4, 512, jnp.int32)
    p0 = decode_batch_flat(params, chunks, lengths, block_size=256)
    pre = prepare_decode_flat(4, chunks, lengths, block_size=256)
    p1 = decode_batch_flat(
        params, chunks, lengths, block_size=256, prepared=pre
    )
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    # A stale prep (wrong block size or batch shape) must raise, not decode.
    with pytest.raises(ValueError, match="rebuild"):
        decode_batch_flat(
            params, chunks, lengths, block_size=512, prepared=pre
        )
    with pytest.raises(ValueError, match="rebuild"):
        decode_batch_flat(
            params, chunks[:2], lengths[:2], block_size=256, prepared=pre
        )


def test_cache_hit_and_invalidation(rng):
    prepared.clear_cache()
    chunks, lengths = _chunks(rng)
    p1 = prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=True)
    st = prepared.cache_stats()
    assert (st["hits"], st["misses"], st["entries"]) == (0, 1, 1)
    # Same arrays + geometry -> the SAME object (hit).
    p2 = prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=True)
    assert p2 is p1
    st = prepared.cache_stats()
    assert (st["hits"], st["misses"]) == (1, 1)
    # New arrays (same content) -> miss: the key is placed-array identity.
    chunks2 = jnp.asarray(np.asarray(chunks))
    p3 = prepared.for_chunked(4, chunks2, lengths, t_tile=256, onehot=True)
    assert p3 is not p1
    assert prepared.cache_stats()["misses"] == 2
    # New lane geometry -> miss even on the same arrays.
    p4 = prepared.for_chunked(4, chunks, lengths, t_tile=512, onehot=True)
    assert p4 is not p1 and p4.Tt != p1.Tt
    assert prepared.cache_stats()["misses"] == 3


def test_prepared_streams_event_emitted(rng, tmp_path):
    prepared.clear_cache()
    chunks, lengths = _chunks(rng, n=4, t=256)
    path = str(tmp_path / "metrics.jsonl")
    with obs_mod.observe(metrics=path):
        prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=True)
        prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=True)
    import json

    events = [
        json.loads(line) for line in open(path)
        if '"prepared_streams"' in line
    ]
    misses = [e for e in events if not e["hit"]]
    hits = [e for e in events if e["hit"]]
    assert len(misses) == 1 and len(hits) == 1
    assert misses[0]["bytes_resident"] > 0
    assert "prep_ms" in misses[0] and "key" in misses[0]


def test_geometry_mismatch_raises(rng, params):
    chunks, lengths = _chunks(rng)
    prep = prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=False)
    with pytest.raises(ValueError, match="rebuild"):
        fb_pallas.batch_stats_pallas(
            params, chunks, lengths, t_tile=512, onehot=False, prepared=prep
        )
    with pytest.raises(ValueError, match="onehot"):
        fb_pallas.batch_stats_pallas(
            params, chunks, lengths, t_tile=256, onehot=True, prepared=prep
        )


def test_no_new_compiles_across_posterior_then_em(rng, params):
    """Reusing ONE prepared object across posterior -> EM on the same batch
    adds no fresh compiles once each entry is warm (the pipeline-reuse
    acceptance: same prep, new params, steady dispatch surface)."""
    chunks, lengths = _chunks(rng, n=4, t=512)
    mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
    prep = prepared.for_chunked(4, chunks, lengths, t_tile=256, onehot=True)
    # Warm both entries with the shared prep.
    jax.block_until_ready(
        fb_pallas.batch_posterior_pallas(
            params, chunks, lengths, mask, t_tile=256, onehot=True,
            prepared=prep,
        )
    )
    jax.block_until_ready(
        fb_pallas.batch_stats_pallas(
            params, chunks, lengths, t_tile=256, onehot=True, prepared=prep
        )
    )
    # New params (an M-step away), same prep: no recompiles anywhere.
    stats = fb_pallas.batch_stats_pallas(
        params, chunks, lengths, t_tile=256, onehot=True, prepared=prep
    )
    params2 = baum_welch.mstep(params, stats)
    with obs_mod.no_new_compiles("prepared-posterior-em-reuse"):
        jax.block_until_ready(
            fb_pallas.batch_posterior_pallas(
                params2, chunks, lengths, mask, t_tile=256, onehot=True,
                prepared=prep,
            )
        )
        jax.block_until_ready(
            fb_pallas.batch_stats_pallas(
                params2, chunks, lengths, t_tile=256, onehot=True,
                prepared=prep,
            )
        )


def _chunked_input(rng, n=8, t=1024):
    raw = chunking.frame(
        rng.integers(0, 4, size=n * t).astype(np.uint8), t
    )
    return chunking.Chunked(
        chunks=jnp.asarray(raw.chunks), lengths=jnp.asarray(raw.lengths),
        total=raw.total,
    )


def test_fused_em_prepared_matches_host_loop(rng, params):
    """The prepared-aware fused loop reproduces the host loop bit-for-bit
    on the reduced engine (trajectories, final model)."""
    ck = _chunked_input(rng)
    host = baum_welch.fit(
        params, ck, num_iters=4, convergence=0.0,
        backend=LocalBackend(engine="onehot"), fuse=False,
    )
    fused = baum_welch.fit(
        params, ck, num_iters=4, convergence=0.0,
        backend=LocalBackend(engine="onehot"), fuse=True,
    )
    assert fused.iterations == host.iterations == 4
    np.testing.assert_allclose(fused.logliks, host.logliks, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(fused.params.A), np.asarray(host.params.A), atol=1e-5
    )


def test_fused_em_steady_state_zero_repreps(rng, params):
    """LEDGER ACCEPTANCE (extended): steady-state fused EM = 1 blocking
    dispatch + ZERO stream re-preparations — the second fit on the same
    placed input hits the prep cache (0 misses) and recompiles nothing."""
    ck = _chunked_input(rng)
    backend = LocalBackend(engine="onehot")

    def fit():
        return baum_welch.fit(
            params, ck, num_iters=5, convergence=0.0, backend=backend,
            fuse=True,
        )

    fit()  # warm: compiles the loop, builds the prep (a miss)
    before = prepared.cache_stats()
    with obs_mod.observe() as ob:
        snap = ob.ledger.snapshot()
        with obs_mod.no_new_compiles("fused-em-prep-steady"):
            fit()
        delta = ob.ledger.delta(snap)
    after = prepared.cache_stats()
    assert after["misses"] == before["misses"], (before, after)
    assert after["hits"] > before["hits"]
    assert delta["dispatches"] <= 2, delta


def test_em_body_contract_and_synthetic_violation(rng, params):
    """The em.body.invariant-free detector: clean on the prepared loop,
    and PROVEN on the synthetic violation (the inline-prep loop body must
    show the forward-fill marker primitives)."""
    from cpgisland_tpu.analysis import contracts

    res = contracts._em_body_contract()
    assert res.ok, res.violations
    assert res.notes["inline_markers"] == ["cummax"]

    # Synthetic violation, explicitly: trace the UNprepared loop and run
    # the detector by hand — the markers must be inside the while body.
    chunks, lengths = _chunks(rng)
    backend = LocalBackend(engine="onehot")
    stats_fn, prep = backend.fused_stats_with_prep(params, chunks, lengths)
    assert prep is not None
    fn0 = baum_welch._fused_em_fn(stats_fn, 2, False)
    closed0 = jax.make_jaxpr(fn0)(
        params.astype(jnp.float32), chunks, lengths, jnp.float32(0.0), None
    )
    body0 = contracts.while_body_prims(closed0)
    assert set(body0) & contracts.PREP_MARKER_PRIMS == {"cummax"}
    # And the prepared twin is clean.
    fn1 = baum_welch._fused_em_fn(stats_fn, 2, True)
    closed1 = jax.make_jaxpr(fn1)(
        params.astype(jnp.float32), chunks, lengths, jnp.float32(0.0), prep
    )
    body1 = contracts.while_body_prims(closed1)
    assert not set(body1) & contracts.PREP_MARKER_PRIMS
