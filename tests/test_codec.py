"""Codec tests: reference skip rules (CpGIslandFinder.java:112-128) + FASTA mode."""

import numpy as np

from cpgisland_tpu.utils import codec


def test_basic_mapping():
    assert codec.encode("ACGT").tolist() == [0, 1, 2, 3]
    assert codec.encode("acgt").tolist() == [0, 1, 2, 3]
    assert codec.encode("AaCcGgTt").tolist() == [0, 0, 1, 1, 2, 2, 3, 3]


def test_skips_everything_else():
    # N bases, digits, whitespace, punctuation are skipped like the reference.
    assert codec.encode("A\nC N G\t5 T!").tolist() == [0, 1, 2, 3]
    assert codec.encode("NNNN").size == 0
    assert codec.encode("").size == 0


def test_compat_mode_encodes_header_bases():
    # Reference quirk: no FASTA handling — 'c','a','t' inside a header encode.
    text = ">cat chr1\nACGT"  # header contributes c,a,t and the c in "chr1"
    assert codec.encode(text).tolist() == [1, 0, 3, 1, 0, 1, 2, 3]


def test_fasta_mode_strips_headers(tmp_path):
    p = tmp_path / "x.fa"
    p.write_text(">cat chr1 description acgt\nACGT\n>another g c\nGG\n")
    compat = codec.encode_file(str(p), skip_headers=False)
    clean = codec.encode_file(str(p), skip_headers=True)
    assert clean.tolist() == [0, 1, 2, 3, 2, 2]
    assert len(compat) > len(clean)


def test_streaming_matches_onehot(tmp_path, rng):
    # Large-ish file with headers crossing read boundaries.
    lines = []
    for i in range(50):
        lines.append(f">seq{i} with acgt junk")
        lines.append("".join(rng.choice(list("ACGTNacgtn"), size=997)))
    p = tmp_path / "big.fa"
    p.write_text("\n".join(lines) + "\n")
    data = p.read_bytes()

    whole = codec.encode_bytes(codec.strip_fasta_headers(data))
    streamed = np.concatenate(
        list(codec.iter_encoded_blocks(str(p), skip_headers=True, read_size=257))
    )
    np.testing.assert_array_equal(whole, streamed)

    compat_whole = codec.encode_bytes(data)
    compat_streamed = np.concatenate(
        list(codec.iter_encoded_blocks(str(p), skip_headers=False, read_size=311))
    )
    np.testing.assert_array_equal(compat_whole, compat_streamed)


def test_roundtrip():
    syms = np.array([0, 1, 2, 3, 3, 2, 1, 0], dtype=np.uint8)
    assert codec.encode(codec.decode_symbols(syms)).tolist() == syms.tolist()
