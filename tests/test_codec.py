"""Codec tests: reference skip rules (CpGIslandFinder.java:112-128) + FASTA mode."""

import numpy as np

from cpgisland_tpu.utils import codec


def test_basic_mapping():
    assert codec.encode("ACGT").tolist() == [0, 1, 2, 3]
    assert codec.encode("acgt").tolist() == [0, 1, 2, 3]
    assert codec.encode("AaCcGgTt").tolist() == [0, 0, 1, 1, 2, 2, 3, 3]


def test_skips_everything_else():
    # N bases, digits, whitespace, punctuation are skipped like the reference.
    assert codec.encode("A\nC N G\t5 T!").tolist() == [0, 1, 2, 3]
    assert codec.encode("NNNN").size == 0
    assert codec.encode("").size == 0


def test_compat_mode_encodes_header_bases():
    # Reference quirk: no FASTA handling — 'c','a','t' inside a header encode.
    text = ">cat chr1\nACGT"  # header contributes c,a,t and the c in "chr1"
    assert codec.encode(text).tolist() == [1, 0, 3, 1, 0, 1, 2, 3]


def test_fasta_mode_strips_headers(tmp_path):
    p = tmp_path / "x.fa"
    p.write_text(">cat chr1 description acgt\nACGT\n>another g c\nGG\n")
    compat = codec.encode_file(str(p), skip_headers=False)
    clean = codec.encode_file(str(p), skip_headers=True)
    assert clean.tolist() == [0, 1, 2, 3, 2, 2]
    assert len(compat) > len(clean)


def test_streaming_matches_onehot(tmp_path, rng):
    # Large-ish file with headers crossing read boundaries.
    lines = []
    for i in range(50):
        lines.append(f">seq{i} with acgt junk")
        lines.append("".join(rng.choice(list("ACGTNacgtn"), size=997)))
    p = tmp_path / "big.fa"
    p.write_text("\n".join(lines) + "\n")
    data = p.read_bytes()

    whole = codec.encode_bytes(codec.strip_fasta_headers(data))
    streamed = np.concatenate(
        list(codec.iter_encoded_blocks(str(p), skip_headers=True, read_size=257))
    )
    np.testing.assert_array_equal(whole, streamed)

    compat_whole = codec.encode_bytes(data)
    compat_streamed = np.concatenate(
        list(codec.iter_encoded_blocks(str(p), skip_headers=False, read_size=311))
    )
    np.testing.assert_array_equal(compat_whole, compat_streamed)


def test_roundtrip():
    syms = np.array([0, 1, 2, 3, 3, 2, 1, 0], dtype=np.uint8)
    assert codec.encode(codec.decode_symbols(syms)).tolist() == syms.tolist()


class TestFastaRecords:
    def _roundtrip(self, tmp_path, text, read_size=1 << 24):
        p = tmp_path / "g.fa"
        p.write_bytes(text if isinstance(text, bytes) else text.encode())
        return list(codec.iter_fasta_records(str(p), read_size=read_size))

    def test_multi_record(self, tmp_path):
        recs = self._roundtrip(tmp_path, ">chr1 desc here\nACGT\nAC\n>chr2\nGGTT\n")
        assert [(n, s.tolist()) for n, s in recs] == [
            ("chr1", [0, 1, 2, 3, 0, 1]),
            ("chr2", [2, 2, 3, 3]),
        ]

    def test_headerless_leading_sequence(self, tmp_path):
        recs = self._roundtrip(tmp_path, "ACG\n>chrX\nTT\n")
        assert [(n, s.tolist()) for n, s in recs] == [("", [0, 1, 2]), ("chrX", [3, 3])]

    def test_empty_record_preserved(self, tmp_path):
        recs = self._roundtrip(tmp_path, ">a\n>b\nAC\n")
        assert [(n, s.tolist()) for n, s in recs] == [("a", []), ("b", [0, 1])]

    def test_midline_gt_is_not_header(self, tmp_path):
        recs = self._roundtrip(tmp_path, ">a\nAC>GT\nTT\n")
        assert [(n, s.tolist()) for n, s in recs] == [("a", [0, 1, 2, 3, 3, 3])]

    def test_block_split_boundaries(self, tmp_path, rng):
        body = "".join(
            f">rec{i} junk\n" + codec.decode_symbols(rng.integers(0, 4, size=97)) + "\n"
            for i in range(23)
        )
        want = self._roundtrip(tmp_path, body)
        for rs in (1, 3, 64, 1024):
            got = self._roundtrip(tmp_path, body, read_size=rs)
            assert [n for n, _ in got] == [n for n, _ in want], rs
            for (_, a), (_, b) in zip(got, want):
                np.testing.assert_array_equal(a, b, err_msg=f"read_size={rs}")

    def test_matches_encode_file(self, tmp_path, rng):
        body = "".join(
            f">c{i}\n" + codec.decode_symbols(rng.integers(0, 4, size=1000)) + "\n"
            for i in range(5)
        )
        recs = self._roundtrip(tmp_path, body)
        merged = np.concatenate([s for _, s in recs])
        np.testing.assert_array_equal(
            merged, codec.encode_file(str(tmp_path / "g.fa"), skip_headers=True)
        )


def _write_fasta(path, rng, specs):
    with open(path, "w") as f:
        for name, nlen in specs:
            f.write(f">{name}\n")
            s = "".join(rng.choice(list("acgtN"), size=nlen))
            for i in range(0, len(s), 63):
                f.write(s[i : i + 63] + "\n")


def test_encode_byte_range_tiles_exactly(tmp_path, rng):
    """Concatenating every part's range encode equals the whole-file encode
    for any part count (line-aligned cuts; VERDICT r2 #4b)."""
    fa = tmp_path / "g.fa"
    _write_fasta(fa, rng, [("chrA", 50_000), ("chrB longer desc", 12_345), ("s", 777)])
    whole = codec.encode_file(str(fa), skip_headers=True)
    for P in (1, 2, 3, 7):
        parts = [codec.encode_byte_range(str(fa), q, P) for q in range(P)]
        np.testing.assert_array_equal(np.concatenate(parts), whole)
    # Compat (headers encoded) tiles too.
    whole_c = codec.encode_file(str(fa), skip_headers=False)
    parts_c = [
        codec.encode_byte_range(str(fa), q, 3, skip_headers=False) for q in range(3)
    ]
    np.testing.assert_array_equal(np.concatenate(parts_c), whole_c)


def test_symbol_cache_roundtrip_and_invalidation(tmp_path, rng):
    """Cache serves identical records from a memmap; editing the source
    invalidates it (VERDICT r2 #4a)."""
    import os
    import time

    fa = tmp_path / "g.fa"
    _write_fasta(fa, rng, [("r1", 3000), ("r2", 50)])
    cache = str(tmp_path / "g.fa")  # prefix beside the source
    direct = list(codec.iter_fasta_records(str(fa)))
    cached1 = list(codec.iter_fasta_records_cached(str(fa), cache))
    assert [n for n, _ in cached1] == [n for n, _ in direct]
    for (_, a), (_, b) in zip(direct, cached1):
        np.testing.assert_array_equal(a, b)
    # Second read is a pure cache hit (memmap-backed).
    hit = codec.open_symbol_cache(str(fa), cache)
    assert hit is not None
    cached2 = list(codec.iter_fasta_records_cached(str(fa), cache))
    assert isinstance(cached2[0][1], np.memmap)
    # Editing the source invalidates the cache.
    time.sleep(0.01)
    _write_fasta(fa, rng, [("r1", 3001), ("r2", 50)])
    os.utime(fa)
    assert codec.open_symbol_cache(str(fa), cache) is None
    cached3 = list(codec.iter_fasta_records_cached(str(fa), cache))
    direct3 = list(codec.iter_fasta_records(str(fa)))
    np.testing.assert_array_equal(cached3[0][1], direct3[0][1])


def test_encode_file_cached(tmp_path, rng):
    fa = tmp_path / "g.fa"
    _write_fasta(fa, rng, [("r1", 4000)])
    cache = str(tmp_path / "c")
    whole = codec.encode_file(str(fa), skip_headers=True)
    np.testing.assert_array_equal(
        codec.encode_file_cached(str(fa), cache, skip_headers=True), whole
    )
    np.testing.assert_array_equal(
        codec.encode_file_cached(str(fa), cache, skip_headers=True), whole
    )
    # Compat encoding never goes through the FASTA-aware cache.
    np.testing.assert_array_equal(
        codec.encode_file_cached(str(fa), cache, skip_headers=False),
        codec.encode_file(str(fa), skip_headers=False),
    )


def test_encode_byte_range_cached(tmp_path, rng):
    """Per-host byte-range cache: hit equals direct encode, editing the
    source invalidates, and a (part, n_parts) change never serves a stale
    split (VERDICT r3 #1's per-host symbol cache)."""
    import os
    import time

    fa = tmp_path / "g.fa"
    _write_fasta(fa, rng, [("chrA", 9000), ("s", 500)])
    cache = str(tmp_path / "c")
    for q in range(2):
        direct = codec.encode_byte_range(str(fa), q, 2)
        np.testing.assert_array_equal(
            codec.encode_byte_range_cached(str(fa), q, 2, cache), direct
        )
        assert os.path.exists(f"{cache}.range{q}of2.npz")
        # hit path
        np.testing.assert_array_equal(
            codec.encode_byte_range_cached(str(fa), q, 2, cache), direct
        )
    # A different split keys a different sidecar — no stale reuse.
    np.testing.assert_array_equal(
        codec.encode_byte_range_cached(str(fa), 0, 3, cache),
        codec.encode_byte_range(str(fa), 0, 3),
    )
    # Source edit invalidates.
    time.sleep(0.01)
    _write_fasta(fa, rng, [("chrA", 9001), ("s", 500)])
    os.utime(fa)
    np.testing.assert_array_equal(
        codec.encode_byte_range_cached(str(fa), 0, 2, cache),
        codec.encode_byte_range(str(fa), 0, 2),
    )
    # cache=None passes through; no sidecar appears.
    np.testing.assert_array_equal(
        codec.encode_byte_range_cached(str(fa), 1, 2, None),
        codec.encode_byte_range(str(fa), 1, 2),
    )
