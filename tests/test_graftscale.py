"""graftcheck Layer 6 (graftscale): scale-invariance dataflow + contracts.

Covers the satellite matrix from ISSUE 18: the abstract domain's rules
(degree arithmetic, guard literals, collapse, scan fixpoints, provenance),
the planted r9 cs-scaled/self-normalized pairing (flagged by the dataflow
AND refused at the runtime route guard), every shipped registry entry
certifying clean against the committed SCALE.json, and lockfile staleness
degrading to report-only exactly like test_graftune's freshness pins.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from cpgisland_tpu.analysis import scale_contracts, scalemodel  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sig(fn, args, tagged, mode="linear"):
    report, _ = scalemodel.trace_scales(fn, args, tagged, mode=mode)
    return report.signature(), report.out_scales


# -- the abstract domain -----------------------------------------------------


class TestScalemodel:
    x = jnp.asarray(np.linspace(0.5, 2.0, 8).astype(np.float32))

    def test_products_add_degrees(self):
        sig, _ = _sig(lambda a: a * a, (self.x,), (0,))
        assert sig == ["deg:2"]
        sig, _ = _sig(lambda a: a * a * a, (self.x,), (0,))
        assert sig == ["deg:3"]

    def test_ratio_collapses_to_free(self):
        sig, _ = _sig(lambda a: a / jnp.sum(a), (self.x,), (0,))
        assert sig == ["free"]

    def test_reductions_preserve_degree(self):
        sig, _ = _sig(lambda a: (jnp.sum(a), jnp.max(a * a)), (self.x,), (0,))
        assert sig == ["deg:1", "deg:2"]

    def test_argmax_collapses(self):
        sig, _ = _sig(lambda a: jnp.argmax(a), (self.x,), (0,))
        assert sig == ["free"]

    def test_untagged_inputs_stay_free(self):
        sig, _ = _sig(lambda a, b: a * b, (self.x, self.x), ())
        assert sig == ["free"]

    def test_guard_zero_literal_is_any(self):
        # a * 0.0 is exactly zero at any scale of a: degree-polymorphic,
        # so adding an untagged term keeps the result free (the
        # _enter_vectors v0*0.0 idiom must not poison maxplus decode).
        sig, _ = _sig(lambda a, b: a * 0.0 + b, (self.x, self.x), (0,))
        assert sig == ["free"]

    def test_mixed_sum_carries_provenance(self):
        sig, scales = _sig(lambda a, b: a + b, (self.x, self.x), (0,))
        assert sig == ["mixed"]
        assert "add" in scales[0].why and "test_graftscale" in scales[0].why

    def test_transcendental_of_tagged_is_mixed(self):
        sig, scales = _sig(lambda a: jnp.exp(a), (self.x,), (0,))
        assert sig == ["mixed"]
        assert "exp" in scales[0].why

    def test_scan_carry_fixed_point(self):
        def cumsum(a):
            return jax.lax.scan(
                lambda c, v: (c + v, c), jnp.zeros(()), a)[0]

        sig, _ = _sig(cumsum, (self.x,), (0,))
        assert sig == ["deg:1"]

    def test_scan_carry_growing_degree_is_mixed(self):
        def cumprod(a):
            return jax.lax.scan(
                lambda c, v: (c * v, c), jnp.ones(()), a)[0]

        sig, scales = _sig(cumprod, (self.x,), (0,))
        assert sig == ["mixed"]
        assert "fixed point" in scales[0].why

    def test_maxplus_offset_roles(self):
        # Log space: + takes the "scale" role, max preserves, argmax
        # erases — the true-score decode contract in miniature.
        def fn(a, dv):
            shifted = a + dv
            return jnp.argmax(shifted), jnp.max(shifted)

        sig, _ = _sig(fn, (self.x, jnp.float32(0.0)), (1,), mode="maxplus")
        assert sig == ["free", "deg:1"]

    def test_signature_is_stable_under_value_change(self):
        # The analysis reads graph structure, not values.
        a = jnp.asarray(np.random.default_rng(3).uniform(0.1, 1, 8)
                        .astype(np.float32))
        fn = lambda v: v / jnp.sum(v)  # noqa: E731
        assert _sig(fn, (a,), (0,))[0] == _sig(fn, (self.x,), (0,))[0]


# -- the planted r9 pairing: flagged by Layer 6, refused at runtime ----------


class TestPlantedPairing:
    def test_cs_stats_derives_degree_one_macc(self):
        # The EXACT arm's declared truth, derived from the dataflow.
        rec, viol = scale_contracts.derive_entry(
            _entry_by_name("em.chunked.onehot.split"))
        assert viol == []
        assert rec["signature"]["macc"] == "deg:1"

    def test_planted_pairing_is_exactly_one_finding_with_provenance(self):
        # Plant the bug: declare the cs-scaled stats consumer as if it
        # were a legal self-normalized-direction consumer (expect free).
        import dataclasses

        legal = _entry_by_name("em.chunked.onehot.split")
        planted = dataclasses.replace(
            legal, name="planted.cs.pairing",
            expect={"macc": "free", "emit_red": "free", "ll": "free"},
            tags_key="",
        )
        _rec, viol = scale_contracts.derive_entry(planted)
        assert len(viol) == 1
        msg = viol[0]
        assert "scale.free-consumers" in msg
        assert "macc" in msg and "deg:1" in msg
        # Equation provenance points into the kernel module.
        assert "fb_onehot.py" in msg

    def test_runtime_guard_refuses_selfnorm_betas(self):
        from cpgisland_tpu.ops import fb_onehot

        fn, args, _ = scale_contracts._mk_cs_stats()
        # The same streams routed with a self-normalized scale label must
        # raise at the route point, before any kernel runs.
        s = scale_contracts._reduced_streams()
        for bad in ("selfnorm", "matrix"):
            with pytest.raises(ValueError, match="pairing is a bug"):
                fb_onehot.run_stats_onehot(
                    s["params"], s["al2"], s["b2"], s["pair2"], s["lens2"],
                    s["gt"], s["Tp"], betas_scale=bad)
        # The legal routing still runs.
        macc, emit_red, ll = fn(*args)
        assert macc.shape[0] == s["K"] * s["K"]

    def test_beta_scale_of_route_labels(self):
        from cpgisland_tpu.ops import fb_onehot

        assert fb_onehot.beta_scale_of(fused=False) == "cs"
        assert fb_onehot.beta_scale_of(fused=True) == "selfnorm"
        assert fb_onehot.beta_scale_of(fused=True, one_pass=True) == "matrix"


def _entry_by_name(name):
    entries = {e.name: e for e in scale_contracts.default_entries()}
    return entries[name]


# -- the shipped registry against the committed lockfile ---------------------


@pytest.fixture(scope="module")
def live():
    records, violations = scale_contracts.live_entries()
    assert violations == [], violations
    return records


class TestRegistry:
    def test_declarations_match_ops_scale_tags(self):
        assert scale_contracts.check_declarations() == []

    def test_every_direction_consumer_is_free(self, live):
        for name in ("posterior.onehot", "posterior.conf.onehot",
                     "posterior.onehot.onepass", "em.seq.onehot",
                     "em.chunked.onehot", "em.seq.onehot.onepass"):
            assert set(live[name]["signature"].values()) <= {"free", "any"}, (
                name, live[name]["signature"])

    def test_exact_arms_pin_their_degrees(self, live):
        assert live["em.chunked.onehot.split"]["signature"]["macc"] == "deg:1"
        assert live["fb.mat.epilogue"]["signature"]["betas2"] == "deg:1"
        assert live["decode.score.onehot"]["signature"] == {
            "path": "free", "score": "deg:1"}
        assert live["em.seq.onepass.loglik"]["signature"]["ll"] == "mixed"

    def test_committed_lockfile_is_fresh_and_matching(self, live):
        lock = scale_contracts.load_lockfile()
        assert lock is not None, "SCALE.json must be committed"
        diff = scale_contracts.diff_scales(live, lock)
        assert diff.ok, diff.violations
        assert diff.stale == [], (
            "committed SCALE.json fingerprints drifted — re-derive with "
            "--update-scale", diff.notes)
        assert diff.checked == len(live)

    def test_const_bytes_far_under_remote_budget(self, live):
        from cpgisland_tpu.analysis import memmodel

        for name, rec in live.items():
            assert rec["const_bytes"] < memmodel.remote_const_budget(), name


# -- lockfile lifecycle: missing / stale / drifted ---------------------------


class TestLockfile:
    def test_missing_lockfile_is_violation(self, live):
        diff = scale_contracts.diff_scales(live, None)
        assert not diff.ok
        assert "no SCALE.json" in diff.violations[0]

    def test_missing_platform_is_note_only(self, live):
        diff = scale_contracts.diff_scales(
            live, {"platforms": {}}, platform="cpu")
        assert diff.ok
        assert "no 'cpu' section" in diff.notes[0]

    def test_missing_entry_is_violation(self, live):
        lock = copy.deepcopy(scale_contracts.load_lockfile())
        entries = lock["platforms"]["cpu"]["entries"]
        entries.pop("posterior.onehot")
        diff = scale_contracts.diff_scales(live, lock)
        assert any("posterior.onehot" in v and "missing" in v
                   for v in diff.violations)

    def test_fingerprint_drift_degrades_to_report_only(self, live):
        # The test_graftune freshness pin, Layer-6 edition: a synthetic
        # fingerprint bump STALES the entry — note, not violation; the
        # signature check is skipped for exactly that entry.
        lock = copy.deepcopy(scale_contracts.load_lockfile())
        entry = lock["platforms"]["cpu"]["entries"]["em.chunked.onehot"]
        entry["costs_fingerprint"] = "sha256:deadbeefdeadbeef"
        # Make the locked signature WRONG too: stale must win over drift.
        entry["signature"] = {"macc": "deg:7", "emit_red": "free",
                              "ll": "free"}
        diff = scale_contracts.diff_scales(live, lock)
        assert diff.ok, diff.violations
        assert diff.stale == ["em.chunked.onehot"]
        assert any("fingerprint" in n and "drifted" in n
                   for n in diff.notes)
        assert diff.checked == len(live) - 1

    def test_signature_drift_is_violation_when_fresh(self, live):
        lock = copy.deepcopy(scale_contracts.load_lockfile())
        entry = lock["platforms"]["cpu"]["entries"]["posterior.onehot"]
        entry["signature"] = {"conf": "deg:1", "path": "free"}
        diff = scale_contracts.diff_scales(live, lock)
        assert any("posterior.onehot" in v and "drifted" in v
                   for v in diff.violations)

    def test_write_round_trip(self, live, tmp_path):
        path = str(tmp_path / "SCALE.json")
        scale_contracts.write_lockfile(live, path)
        with open(path) as f:
            lock = json.load(f)
        diff = scale_contracts.diff_scales(live, lock)
        assert diff.ok and diff.checked == len(live)
        # Stamped with the real COSTS.json fingerprints.
        for rec in lock["platforms"]["cpu"]["entries"].values():
            assert rec["costs_fingerprint"].startswith("sha256:")


# -- CLI ---------------------------------------------------------------------


@pytest.mark.slow
def test_cli_scale_pass_is_green():
    proc = subprocess.run(
        [sys.executable, "-m", "cpgisland_tpu.analysis",
         "--scale", "--no-lint", "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    assert payload["scale"]["ok"]
    assert payload["scale"]["diff"]["checked"] == len(
        scale_contracts.default_entries())
