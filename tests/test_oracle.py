"""Sanity tests for the NumPy oracles themselves, against hand-computed values.

The oracles are the golden models every JAX/Pallas implementation is pinned
against (SURVEY.md §4), so they get their own hand-checkable fixtures.
"""

import numpy as np
import pytest

from tests import oracle


# A tiny 2-state weather-style HMM, worked by hand.
PI = np.array([0.6, 0.4])
A2 = np.array([[0.7, 0.3], [0.4, 0.6]])
B2 = np.array([[0.5, 0.4, 0.1, 0.0], [0.1, 0.3, 0.6, 0.0]])


def test_viterbi_oracle_hand_checked():
    # obs = [0, 2]:
    # delta0 = [.6*.5, .4*.1] = [.30, .04]
    # t=1 state0: max(.30*.7, .04*.4)*.1 = .021 (from 0)
    #     state1: max(.30*.3, .04*.6)*.6 = .054 (from 0)
    path, score = oracle.viterbi_oracle(PI, A2, B2, [0, 2])
    assert path.tolist() == [0, 1]
    assert np.exp(score) == pytest.approx(0.054)


def test_forward_backward_oracle_loglik_matches_brute_force():
    obs = [0, 2, 1]
    # Brute-force marginal likelihood over all 2^3 paths.
    total = 0.0
    for s0 in range(2):
        for s1 in range(2):
            for s2 in range(2):
                total += (
                    PI[s0] * B2[s0, obs[0]] * A2[s0, s1] * B2[s1, obs[1]] * A2[s1, s2] * B2[s2, obs[2]]
                )
    gamma, xi_sum, ll = oracle.forward_backward_oracle(PI, A2, B2, obs)
    assert ll == pytest.approx(np.log(total))
    np.testing.assert_allclose(gamma.sum(axis=1), 1.0, atol=1e-12)
    # xi_sum totals T-1 expected transitions.
    assert xi_sum.sum() == pytest.approx(len(obs) - 1)


def test_em_step_oracle_increases_likelihood():
    rng = np.random.default_rng(1)
    seqs = [rng.integers(0, 4, size=200) for _ in range(3)]
    pi, A, B = PI, A2, np.array([[0.4, 0.3, 0.2, 0.1], [0.1, 0.2, 0.3, 0.4]])
    _, _, _, ll0 = oracle.em_step_oracle(pi, A, B, seqs)
    pi1, A1, B1, _ = oracle.em_step_oracle(pi, A, B, seqs)
    _, _, _, ll1 = oracle.em_step_oracle(pi1, A1, B1, seqs)
    assert ll1 > ll0  # EM monotonicity
    np.testing.assert_allclose(A1.sum(axis=1), 1.0, atol=1e-12)
    np.testing.assert_allclose(B1.sum(axis=1), 1.0, atol=1e-12)


def test_islands_oracle_basic_call():
    # 10 in-island states flanked by background; C=1,G=2 alternating -> high GC
    # and high CpG observed/expected.
    path = [4] * 3 + [1, 2] * 5 + [4] * 3
    calls = oracle.islands_oracle(path)
    assert len(calls) == 1
    beg, end, length, gc, oe = calls[0]
    assert (beg, end, length) == (4, 13, 10)  # 1-based inclusive
    assert gc == pytest.approx(1.0)
    assert oe == pytest.approx((5 * 10) / (5 * 5))


def test_islands_oracle_open_island_never_emitted():
    path = [4] * 3 + [1, 2] * 5  # island runs to end of path -> dropped
    assert oracle.islands_oracle(path) == []


def test_islands_oracle_filters():
    # All-A island: gc = 0 -> filtered.
    assert oracle.islands_oracle([0] * 10 + [4]) == []


def test_islands_oracle_stale_atc_quirk():
    # Island 1 ends on C+ (state 1). Island 2 opens on A+ (state 0, which does
    # NOT reset atC per the reference, java:325-331) then G+ -> the G is counted
    # as a CpG even though no C precedes it in island 2.
    path = [1, 1, 1, 1] + [4] + [0, 2, 1, 2, 1, 2] + [4]
    calls = oracle.islands_oracle(path)
    # island 1 (all C, no G) has oe=0 -> filtered; island 2 emitted with
    # cg counted = (stale)1 + 2 real = 3.
    assert len(calls) == 1
    _, _, length, gc, oe = calls[0]
    assert length == 6
    assert gc == pytest.approx(5 / 6)
    assert oe == pytest.approx(3 * 6 / (2 * 3))


def test_islands_oracle_chunk_offset():
    path = [4] + [1, 2] * 4 + [4]
    calls = oracle.islands_oracle(path, chunk=2, chunk_size=100)
    assert calls[0][0] == 1 + 200 + 1  # beg=1 + chunk*size + 1
