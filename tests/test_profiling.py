"""Profiling/metrics subsystem (SURVEY.md §5 tracing + metrics + NaN guards)."""

import json

import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.train import baum_welch
from cpgisland_tpu.utils import chunking, profiling


def test_phase_timer_accumulates():
    pt = profiling.PhaseTimer()
    with pt.phase("a", items=100, unit="sym"):
        pass
    with pt.phase("a", items=100, unit="sym"):
        pass
    assert pt.phases["a"].items == 200
    assert pt.phases["a"].seconds > 0
    assert "a:" in pt.report()
    assert pt.as_dict()["a"]["sym"] == 200


def test_phase_timer_keeps_first_unit_on_mismatch(caplog):
    """Re-entering a phase with a different unit must not silently overwrite
    the unit (last-writer-wins corrupted throughput math) — the first unit
    wins and a warning is logged."""
    import logging

    pt = profiling.PhaseTimer()
    with pt.phase("p", items=100, unit="sym"):
        pass
    with caplog.at_level(logging.WARNING, logger="cpgisland_tpu.utils.profiling"):
        with pt.phase("p", items=2, unit="chunks"):
            pass
    assert pt.phases["p"].unit == "sym"
    # mismatched items are dropped, not summed into the first unit's count
    assert pt.phases["p"].items == 100
    assert any("unit" in r.message for r in caplog.records)


def test_phase_timer_merge_across_hosts():
    """Cross-host aggregation: concurrent hosts => max wall, summed items."""
    h0 = {"decode": {"seconds": 2.0, "sym": 100.0, "throughput": 50.0}}
    h1 = {"decode": {"seconds": 4.0, "sym": 300.0, "throughput": 75.0},
          "islands": {"seconds": 1.0, "sym": 300.0, "throughput": 300.0}}
    merged = profiling.PhaseTimer.merge([h0, h1])
    assert merged["decode"]["seconds"] == 4.0
    assert merged["decode"]["sym"] == 400.0
    assert merged["decode"]["throughput"] == 100.0
    assert merged["islands"]["sym"] == 300.0
    with pytest.raises(ValueError, match="unit mismatch"):
        profiling.PhaseTimer.merge(
            [h0, {"decode": {"seconds": 1.0, "chunks": 5.0, "throughput": 5.0}}]
        )


def test_metrics_logger_tags_process_index(tmp_path):
    p = tmp_path / "m.jsonl"
    with profiling.MetricsLogger(str(p)) as m:
        m.log("e")
    (rec,) = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert rec["process_index"] == 0  # single-process test env


def test_metrics_logger_jsonl(tmp_path):
    p = tmp_path / "m.jsonl"
    with profiling.MetricsLogger(str(p)) as m:
        m.log("em_iter", iteration=1, loglik=-12.5)
        m.log("decode", n_islands=3)
    recs = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert [r["event"] for r in recs] == ["em_iter", "decode"]
    assert recs[0]["loglik"] == -12.5
    assert all("ts" in r for r in recs)


def test_null_metrics_swallow():
    profiling.null().log("anything", x=1)  # must not raise


def test_check_finite_raises_on_nan():
    profiling.check_finite({"ok": np.ones(3)})
    with pytest.raises(FloatingPointError, match="bad"):
        profiling.check_finite({"bad": np.array([1.0, np.nan])})


def test_fit_emits_metrics(tmp_path, rng):
    p = tmp_path / "train.jsonl"
    syms = rng.integers(0, 4, size=1024).astype(np.uint8)
    ck = chunking.frame(syms, 256)
    with profiling.MetricsLogger(str(p)) as m:
        baum_welch.fit(presets.durbin_cpg8(), ck, num_iters=2, convergence=0.0, metrics=m)
    recs = [json.loads(ln) for ln in p.read_text().splitlines()]
    iters = [r for r in recs if r["event"] == "em_iter"]
    assert len(iters) == 2
    assert iters[0]["iteration"] == 1 and "loglik" in iters[0]


def test_decode_emits_metrics(tmp_path, rng):
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.utils import codec

    fa = tmp_path / "g.fa"
    fa.write_text(">t\n" + codec.decode_symbols(rng.integers(0, 4, size=4096)) + "\n")
    p = tmp_path / "decode.jsonl"
    with profiling.MetricsLogger(str(p)) as m:
        pipeline.decode_file(str(fa), presets.durbin_cpg8(), compat=False, metrics=m)
    recs = [json.loads(ln) for ln in p.read_text().splitlines()]
    dec = [r for r in recs if r["event"] == "decode"]
    assert len(dec) == 1
    assert dec[0]["n_symbols"] == 4096
    assert "decode" in dec[0] and dec[0]["decode"]["seconds"] > 0
