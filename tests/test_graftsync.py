"""Layer-4 (graftsync) unit tests: one triggering and one clean fixture
per concurrency rule, waiver forms, the unguarded/blocking registries, the
``--sync`` CLI exit-code contract, the runtime lock tracker (lock-order
recording, guarded-access descriptors, condition aliasing), and the two
real concurrency fixes this layer certified in-code — the multi-threaded
obs ledger and the locked prepared-stream cache — each hammered by real
threads.

The lint-layer and tracker tests touch no jax; the prepared-cache hammer
uses numpy-backed preps (the cache is content-agnostic).
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from cpgisland_tpu.analysis import all_rules, lint_file, synccheck, tracksync
from cpgisland_tpu.analysis.config import (
    sync_blocking_ok_for,
    sync_unguarded_for,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "graftsync")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULES = [
    ("sync-guarded-by", "guarded"),
    ("sync-lock-order", "order"),
    ("sync-blocking-under-lock", "blocking"),
    ("sync-thread-lifecycle", "thread"),
]


def _lint(name: str):
    path = os.path.join(FIXTURES, f"{name}.py")
    return lint_file(path, relpath=os.path.relpath(path, REPO))


@pytest.mark.parametrize("rule,stem", RULES, ids=[r for r, _ in RULES])
def test_rule_fires_on_trigger(rule, stem):
    findings, _ = _lint(f"{stem}_trigger")
    hits = [f for f in findings if f.rule == rule and not f.waived]
    assert hits, f"{rule} did not fire on its trigger fixture"


@pytest.mark.parametrize("rule,stem", RULES, ids=[r for r, _ in RULES])
def test_rule_quiet_on_clean(rule, stem):
    findings, _ = _lint(f"{stem}_clean")
    hits = [f for f in findings if f.rule == rule]
    assert hits == [], [f.format() for f in hits]


def test_guarded_by_names_attr_and_lock():
    findings, _ = _lint("guarded_trigger")
    msgs = "\n".join(
        f.message for f in findings if f.rule == "sync-guarded-by"
    )
    # The findings name the offending attribute AND its guarding lock.
    assert "self._count" in msgs and "Counter._lock" in msgs
    assert "_totals" in msgs and "_stats_lock" in msgs
    # Reads, writes, and container mutations are all distinguished.
    assert "read of 'self._count'" in msgs
    assert "write to 'self._count'" in msgs
    assert "write to 'self._events'" in msgs


def test_lock_order_names_cycle_and_self_deadlock():
    findings, _ = _lint("order_trigger")
    msgs = [f.message for f in findings if f.rule == "sync-lock-order"]
    cyc = [m for m in msgs if "lock-order cycle" in m]
    assert cyc and "Pair._a" in cyc[0] and "Pair._b" in cyc[0]
    assert "acquisition sites" in cyc[0]
    slf = [m for m in msgs if "non-reentrant" in m]
    assert slf and "Recurse._mu" in slf[0] and "Recurse.inner" in slf[0]


def test_blocking_flags_every_banned_class():
    findings, _ = _lint("blocking_trigger")
    msgs = "\n".join(
        f.message for f in findings if f.rule == "sync-blocking-under-lock"
    )
    for spelling in (
        "jax.block_until_ready", "self._q.put", ".recv()", "time.sleep",
        "_fetch_unlocked",  # the depth-1 callee expansion
    ):
        assert spelling in msgs, f"missing {spelling} in:\n{msgs}"
    assert "Fetcher._lock" in msgs  # the held lock is named


def test_thread_lifecycle_flags_both_halves():
    findings, _ = _lint("thread_trigger")
    msgs = [
        f.message for f in findings if f.rule == "sync-thread-lifecycle"
    ]
    assert any("neither daemonized nor deterministically joined" in m
               for m in msgs)
    assert any("drains an iterator" in m for m in msgs)


def test_queue_and_str_methods_do_not_false_positive():
    # dict.get / str.join / list "put-like" names on attributes the model
    # does NOT know to be queues/threads must not fire the blocking rule.
    import textwrap

    from cpgisland_tpu.analysis.core import FileContext

    src = textwrap.dedent(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}
                self._parts = []

            def ok(self, k):
                with self._lock:
                    v = self._d.get(k)
                    s = ",".join(str(p) for p in self._parts)
                    return v, s
        """
    )
    ctx = FileContext("<mem>", src, relpath="mem.py")
    rule = all_rules()["sync-blocking-under-lock"]
    assert list(rule.check(ctx)) == []


# -- waivers -----------------------------------------------------------------


def test_sync_waiver_forms():
    findings, waivers = _lint("waivers")
    gb = [f for f in findings if f.rule == "sync-guarded-by"]
    waived = [f for f in gb if f.waived]
    unwaived = [f for f in gb if not f.waived]
    assert len(waived) == 1 and waived[0].waiver_reason
    assert len(unwaived) == 1  # the missing-reason waiver does NOT waive
    assert any(f.rule == "waiver-syntax" for f in findings)
    stale = [w for w in waivers if not w.used]
    assert any("sync-lock-order" in w.rules for w in stale)


# -- registries --------------------------------------------------------------


def test_unguarded_registry_matches_repo_layout():
    ent = sync_unguarded_for("cpgisland_tpu/utils/native.py")
    assert "_lib" in ent and "double-checked" in ent["_lib"]
    assert sync_unguarded_for("cpgisland_tpu/models/hmm.py") == {}


def test_blocking_ok_registry_matches_repo_layout():
    ent = sync_blocking_ok_for("cpgisland_tpu/utils/native.py")
    assert "load" in ent and "leaf" in ent["load"]
    assert sync_blocking_ok_for("cpgisland_tpu/serve/broker.py") == {}


def test_all_four_sync_rules_registered():
    names = set(all_rules())
    for rule, _ in RULES:
        assert rule in names, rule


# -- the cross-module graph on fixture inputs --------------------------------


def test_run_sync_reports_fixture_cycle():
    rep = synccheck.run_sync(
        [os.path.join(FIXTURES, "order_trigger.py")], base=REPO
    )
    assert not rep.ok
    kinds = [f.message for f in rep.findings]
    assert any("lock-order cycle" in m for m in kinds)
    assert any("non-reentrant" in m for m in kinds)
    # The summary payload carries the locks and edges for the report.
    s = rep.summary()
    assert any("Pair._a" in lk for lk in s["locks"])
    assert any("->" in e for e in s["edges"])


def test_run_sync_module_locked_convention_carries_held_set(tmp_path):
    """A module-level ``_locked`` function runs with the module lock(s)
    held (prepared._sweep_dead_locked's convention) — its acquires must
    enter the graph as acquires-while-holding edges, or a cycle through a
    module-level helper is invisible to the deadlock check."""
    mod = tmp_path / "mod.py"
    mod.write_text(
        "import threading\n"
        "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def takes_b_then_a():\n"
        "    with _B:\n"
        "        with _A:\n"
        "            pass\n"
        "def helper_locked():\n"
        "    with _B:\n"
        "        pass\n"
    )
    rep = synccheck.run_sync([str(mod)], base=str(tmp_path))
    assert not rep.ok
    assert any("lock-order cycle" in f.message for f in rep.findings), [
        f.format() for f in rep.findings
    ]
    edges = {(e.src.label, e.dst.label) for e in rep.edges}
    assert ("mod.py::_A", "mod.py::_B") in edges, sorted(edges)


def test_run_sync_clean_on_clean_fixtures():
    rep = synccheck.run_sync(
        [os.path.join(FIXTURES, "order_clean.py"),
         os.path.join(FIXTURES, "guarded_clean.py")], base=REPO,
    )
    assert rep.ok, [f.format() for f in rep.findings]
    assert rep.files_checked == 2


# -- CLI ---------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "cpgisland_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO,
    )


def test_cli_exits_nonzero_on_each_sync_trigger():
    for _, stem in RULES:
        proc = _run_cli(os.path.join(FIXTURES, f"{stem}_trigger.py"))
        assert proc.returncode == 1, (stem, proc.stdout, proc.stderr)


def test_cli_sync_pass_fails_on_cycle_naming_locks():
    proc = _run_cli(
        "--no-lint", "--sync", "--json",
        os.path.join(FIXTURES, "order_trigger.py"),
    )
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    assert payload["ok"] is False
    viol = "\n".join(payload["sync"]["violations"])
    assert "Pair._a" in viol and "Pair._b" in viol


def test_cli_sync_pass_clean_fixture_exits_zero():
    proc = _run_cli(
        "--no-lint", "--sync", os.path.join(FIXTURES, "order_clean.py")
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


def test_cli_list_rules_includes_sync_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule, _ in RULES:
        assert rule in proc.stdout


# -- the runtime tracker -----------------------------------------------------


@pytest.fixture()
def tracker():
    # These unit tests assert exact edge/violation counts on a private
    # tracker; under CPGISLAND_TRACKSYNC=1 the session-wide one owns the
    # factories instead.
    if tracksync.current() is not None:
        pytest.skip("session-wide LockTracker active (CPGISLAND_TRACKSYNC=1)")
    tr, uninstall = tracksync.install()
    try:
        yield tr
    finally:
        uninstall()


def test_tracker_records_order_and_cycle(tracker):
    a = threading.Lock()
    b = threading.Lock()
    assert isinstance(a, tracksync.TrackedLock)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert tracker.acquires == 4
    cycles = tracker.cycles()
    assert cycles, "AB/BA order was observed but no cycle reported"
    with pytest.raises(AssertionError, match="lock-order-cycle"):
        tracker.assert_clean()


def test_tracker_clean_on_consistent_order(tracker):
    a = threading.Lock()
    b = threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    tracker.assert_clean()
    s = tracker.summary()
    assert s["violations"] == 0 and len(s["edges"]) == 1


def test_tracker_condition_aliases_to_its_lock(tracker):
    # Condition(lock) shares the mutex: `with cv` then `with other` must
    # record the edge FROM THE LOCK, not from a distinct cv identity.
    lk = threading.Lock()
    cv = threading.Condition(lk)
    other = threading.Lock()
    with cv:
        with other:
            pass
    with lk:
        pass  # same identity: no self-edge, no second node
    edges = list(tracker.edges)
    assert len(edges) == 1
    src, dst = edges[0]
    assert src == lk.name and dst == other.name
    assert cv.name == lk.name


def test_tracker_cv_wait_releases_in_bookkeeping(tracker):
    lk = threading.Lock()
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            cv.wait_for(lambda: bool(hits), timeout=5.0)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time

    time.sleep(0.05)
    with cv:  # acquirable because wait released the mutex
        hits.append(1)
        cv.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    tracker.assert_clean()


def test_tracker_guarded_access_descriptor(tracker):
    class Obj:
        pass

    o = Obj()
    lk = threading.Lock()
    o.x = 0  # pre-watch write is untracked
    tracker.watch_attrs(o, lk, ["x"], label="Obj")
    with lk:
        o.x = 1
        assert o.x == 1
    assert tracker.violations() == []
    o.x = 2  # unguarded write
    _ = o.x  # unguarded read
    bad = tracker.violations()
    assert len(bad) == 2
    assert all(v.kind == "guarded-access" for v in bad)
    assert "Obj.x" in bad[0].message
    with pytest.raises(AssertionError, match="guarded-access"):
        tracker.assert_clean()


def test_tracker_guarded_access_other_thread_violates(tracker):
    class Obj:
        pass

    o = Obj()
    lk = threading.Lock()
    tracker.watch_attrs(o, lk, ["y"], label="Obj")

    def writer():
        o.y = 7  # no lock held ON THIS THREAD

    t = threading.Thread(target=writer, daemon=True)
    with lk:  # holding it HERE does not cover the other thread
        t.start()
        t.join(5.0)
    bad = [v for v in tracker.violations() if v.kind == "guarded-access"]
    assert bad and "thread" in bad[0].message


def test_tracker_install_uninstall_restores_factories():
    if tracksync.current() is not None:
        pytest.skip("session-wide LockTracker active (CPGISLAND_TRACKSYNC=1)")
    real = threading.Lock
    tr, uninstall = tracksync.install()
    assert threading.Lock is not real
    assert tracksync.current() is tr
    with pytest.raises(RuntimeError):
        tracksync.install()
    uninstall()
    assert threading.Lock is real
    assert tracksync.current() is None


def test_tracker_uninstall_removes_guarded_descriptors():
    """watch_attrs rewires CLASS attributes; uninstall must restore them —
    a leaked descriptor would route every later instance of the class
    through a dead tracker for the rest of the process — including a
    genuine ``None`` class default, which must survive the round trip."""
    if tracksync.current() is not None:
        pytest.skip("session-wide LockTracker active (CPGISLAND_TRACKSYNC=1)")

    class Obj:
        y = None  # genuine None default, not "missing"

    tr, uninstall = tracksync.install()
    try:
        o = Obj()
        lk = threading.Lock()
        tr.watch_attrs(o, lk, ["x", "y"], label="Obj")
        assert isinstance(Obj.__dict__["x"], tracksync._GuardedDescriptor)
        o2 = Obj()
        assert o2.y is None  # default readable through the descriptor
        with lk:
            o.x = 1
            del o.x  # __delete__ path works while watched
            o.x = 2
    finally:
        uninstall()
    assert "x" not in Obj.__dict__  # missing attr removed outright
    assert Obj.__dict__["y"] is None  # None default restored, not dropped
    assert o.x == 2  # instance state written during the window survives


# -- the two in-code fixes, hammered -----------------------------------------


def test_ledger_counters_exact_under_threads():
    """The obs ledger fix: concurrent count_* / record_compile / snapshot
    callers must never tear a read-modify-write (ledger.py used to document
    single-threaded hosts; the serve daemon broke that)."""
    from cpgisland_tpu.obs.ledger import Ledger

    led = Ledger()
    N_THREADS, N_ITER = 8, 2000
    start = threading.Barrier(N_THREADS)

    def worker(i):
        start.wait()
        for k in range(N_ITER):
            led.count_dispatch()
            led.count_fetch(3)
            led.count_upload(5)
            if k % 100 == 0:
                led.record_compile(f"w{i}", [], 0.001)
            led.delta(led.snapshot())  # multi-field reads interleave

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(N_THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    tot = led.totals()
    per = N_ITER * N_THREADS
    assert tot["dispatches"] == 3 * per  # dispatch + fetch + upload each count
    assert tot["fetch_bytes"] == 3 * per
    assert tot["upload_bytes"] == 5 * per
    assert tot["compiles"] == N_THREADS * (N_ITER // 100)


def test_ledger_device_attribution_exact_under_threads():
    """Fleet attribution (graftscope): 8 concurrent writers, half tagged
    dev0 and half dev1 via ledger.device_scope — the per-device partition
    and the global totals must BOTH stay exact (the device maps are bumped
    under the same ledger lock; untagged legacy callers land in neither
    partition but always in the globals)."""
    from cpgisland_tpu.obs import ledger as ledger_mod
    from cpgisland_tpu.obs.ledger import Ledger

    led = Ledger()
    N_THREADS, N_ITER = 8, 2000
    start = threading.Barrier(N_THREADS)

    def worker(i):
        label = f"dev{i % 2}"
        start.wait()
        with ledger_mod.device_scope(label):
            assert ledger_mod.current_device() == label
            for _ in range(N_ITER):
                led.count_dispatch()
                led.count_fetch(3)
                led.count_upload(5)
        assert ledger_mod.current_device() == ""  # scope restored
        for _ in range(N_ITER):
            led.count_dispatch()  # untagged tail: globals only

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(N_THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    tot = led.totals()
    per = N_ITER * N_THREADS
    assert tot["dispatches"] == 4 * per  # 3 tagged + 1 untagged per iter
    assert tot["fetch_bytes"] == 3 * per
    assert tot["upload_bytes"] == 5 * per
    dev = led.device_totals()
    assert set(dev) == {"dev0", "dev1"}
    for label in ("dev0", "dev1"):
        d = dev[label]
        half = N_ITER * (N_THREADS // 2)
        assert d["dispatches"] == 3 * half
        assert d["fetch_bytes"] == 3 * half
        assert d["upload_bytes"] == 5 * half
    # The tagged partition sums to exactly the tagged share of the globals.
    assert sum(d["dispatches"] for d in dev.values()) == 3 * per


def test_observer_events_exact_under_threads():
    """The Observer event-state fix: serve's transport threads emit
    rejection events while the worker loop emits serve_flush — deduped
    counts, retained events, and the drop counter must stay exact (the
    same multi-writer reality the Ledger lock covers one layer down)."""
    from cpgisland_tpu import obs

    N_THREADS, N_ITER = 8, 500
    start = threading.Barrier(N_THREADS)

    def worker(i):
        start.wait()
        for k in range(N_ITER):
            obs.event("hammer_plain", thread=i, k=k)
            obs.event("hammer_dedupe", _dedupe=True, bucket=k % 4)

    with obs.observe() as o:
        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(N_THREADS)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        retained = sum(
            1 for e in o.events if e["event"] == "hammer_plain"
        )
        deduped = sum(
            1 for e in o.events if e["event"] == "hammer_dedupe"
        )
        summary = o.summary()
    total_plain = N_THREADS * N_ITER
    # Dedupe counts are exact per bucket and only the FIRST occurrence of
    # each payload was retained as an event line.
    decisions = {
        k: v for k, v in summary["decisions"].items()
        if k.startswith("hammer_dedupe")
    }
    assert len(decisions) == 4 and deduped == 4
    assert sum(decisions.values()) == total_plain
    # Nothing tore: every plain emit was retained (well under MAX_EVENTS)
    # and no drop was phantom-counted.
    assert retained == total_plain
    assert summary["dropped_events"] == 0


def test_prepared_cache_concurrent_sessions_no_lost_entries():
    """The prepared-cache fix: concurrent sessions hammering get/insert/
    evict/cache_stats must lose no entries, double-count no evictions, and
    publish ONE prep per key (first build wins; racers adopt it)."""
    from cpgisland_tpu.ops import prepared

    prepared.clear_cache()
    N_SESS = 6
    N_ITER = 40
    arrays = [np.arange(16, dtype=np.float32) + i for i in range(N_SESS)]
    builds = [0] * N_SESS
    got: list = [[] for _ in range(N_SESS)]
    start = threading.Barrier(N_SESS)

    def session(i):
        start.wait()
        arr = arrays[i]
        for k in range(N_ITER):
            def build():
                builds[i] += 1
                return [np.full(8, i, np.float32)]

            prep = prepared._cached("fixture", (arr,), ("s", i), build)
            got[i].append(prep)
            if k % 10 == 9:
                prepared.cache_stats()  # stats reader interleaves
            if k % 17 == 16:
                prepared.evict(arr)  # explicit eviction interleaves

    ts = [threading.Thread(target=session, args=(i,)) for i in range(N_SESS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    stats = prepared.cache_stats()
    # Every get returned a prep carrying the right content (no cross-key
    # leakage, no half-built entries).
    for i in range(N_SESS):
        for prep in got[i]:
            assert prep[0][0] == i
    # Accounting adds up exactly: every call was a hit or a miss.
    assert stats["hits"] + stats["misses"] == N_SESS * N_ITER
    # Explicit evictions each dropped at most one live entry and were
    # counted once (no double-evict of the same key).
    assert stats["evictions_explicit"] <= N_SESS * (N_ITER // 17)
    assert stats["entries"] <= prepared._CACHE_MAX
    prepared.clear_cache()


def test_prepared_cache_single_publish_per_key():
    """Racing builders on the SAME key: exactly one prep object is ever
    handed out once published (the first-published entry wins)."""
    from cpgisland_tpu.ops import prepared

    prepared.clear_cache()
    arr = np.arange(32, dtype=np.float32)
    N = 8
    start = threading.Barrier(N)
    out: list = [None] * N

    def racer(i):
        def build():
            return [np.full(4, 42, np.float32)]

        start.wait()
        out[i] = prepared._cached("fixture", (arr,), ("same",), build)

    ts = [threading.Thread(target=racer, args=(i,)) for i in range(N)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    stats = prepared.cache_stats()
    assert stats["entries"] == 1
    # All racers that found the published entry share ONE object identity.
    published = [p for p in out if p is not None]
    cached = prepared._cached(
        "fixture", (arr,), ("same",), lambda: pytest.fail("must hit")
    )
    assert sum(1 for p in published if p is cached) >= 1
    prepared.clear_cache()
