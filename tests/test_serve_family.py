"""Family serving tests: the ModelRegistry (named models, per-model
breaker isolation), model= request routing, and the compare request type
— the daemon side of the model-family layer."""

import numpy as np
import pytest

from cpgisland_tpu import family, resilience
from cpgisland_tpu.models import presets
from cpgisland_tpu.serve.broker import Backpressure, RequestBroker, BrokerConfig
from cpgisland_tpu.serve.session import ModelRegistry, Session


def _registry(names=("durbin8", "two_state", "null")):
    sess = Session(presets.durbin_cpg8(), name="t", private_breaker=True)
    reg = ModelRegistry(sess)
    for m in family.members_from_names(names):
        reg.register(m)
    return sess, reg


def _broker(reg, sess, **cfg):
    defaults = dict(flush_symbols=1 << 15, flush_deadline_s=0.0)
    defaults.update(cfg)
    return RequestBroker(sess, BrokerConfig(**defaults), registry=reg)


def _syms(n=3000, seed=0):
    return np.random.default_rng(seed).integers(0, 4, size=n).astype(np.uint8)


# ---------------------------------------------------------------------------
# registry


def test_registry_duplicate_name_rejected():
    sess, reg = _registry(("durbin8",))
    with pytest.raises(ValueError, match="duplicate model name"):
        reg.register(family.builtin_member("durbin8"))
    # ...even with a caller-supplied session.
    with pytest.raises(ValueError, match="duplicate model name"):
        reg.register(
            family.builtin_member("durbin8"),
            session=Session(presets.durbin_cpg8(), name="x"),
        )


def test_registry_lookup_and_default():
    sess, reg = _registry()
    assert reg.session("") is sess and reg.default is sess
    assert reg.session("two_state").params is reg.member("two_state").params
    assert set(reg.names()) == {"durbin8", "two_state", "null"}
    with pytest.raises(KeyError, match="unknown model"):
        reg.session("zzz")
    with pytest.raises(KeyError, match="unknown model"):
        reg.member("zzz")


def test_registry_per_model_breaker_isolation():
    """One model's faults must trip ITS session's breaker only — not the
    default session's, not another member's, not the process-global one."""
    sess, reg = _registry()
    a = reg.session("durbin8")
    b = reg.session("two_state")
    assert a is not b and a.breaker is not b.breaker
    assert a.breaker is not sess.breaker
    for _ in range(8):
        a.breaker.record_fault("decode.xla")
    assert a.breaker.tripped("decode.xla")
    assert not b.breaker.tripped("decode.xla")
    assert not sess.breaker.tripped("decode.xla")
    assert not resilience.get_breaker().tripped("decode.xla")


# ---------------------------------------------------------------------------
# admission


def test_unknown_model_admission_rejected():
    sess, reg = _registry()
    broker = _broker(reg, sess)
    with pytest.raises(ValueError, match="unknown model 'nope'"):
        broker.submit(
            request_id=1, tenant="a", kind="decode", symbols=_syms(),
            model="nope",
        )
    with pytest.raises(ValueError, match="unknown model"):
        broker.submit(
            request_id=2, tenant="a", kind="compare", symbols=_syms(),
            models=["durbin8", "zzz"],
        )
    # Nothing was admitted.
    assert broker.pending() == 0


def test_compare_request_validation():
    sess, reg = _registry()
    broker = _broker(reg, sess)
    with pytest.raises(ValueError, match="models"):
        broker.submit(
            request_id=1, tenant="a", kind="compare", symbols=_syms()
        )
    with pytest.raises(ValueError, match="duplicate"):
        broker.submit(
            request_id=2, tenant="a", kind="compare", symbols=_syms(),
            models=["null", "null"],
        )
    with pytest.raises(ValueError, match="compare-only"):
        broker.submit(
            request_id=3, tenant="a", kind="decode", symbols=_syms(),
            models=["durbin8"],
        )
    with pytest.raises(ValueError, match="not model="):
        broker.submit(
            request_id=4, tenant="a", kind="compare", symbols=_syms(),
            model="durbin8", models=["durbin8"],
        )
    # A JSON-string models field must produce an actionable error, not a
    # char-wise "unknown model 'd'".
    with pytest.raises(ValueError, match="list of member names"):
        broker.submit(
            request_id=5, tenant="a", kind="compare", symbols=_syms(),
            models="durbin8,null",
        )


def test_scoring_only_member_rejected_for_direct_routing():
    """A null member has no decode/posterior product — admission rejects
    it with advice instead of serving meaningless empty results."""
    sess, reg = _registry()
    broker = _broker(reg, sess)
    for kind in ("decode", "posterior"):
        with pytest.raises(ValueError, match="scoring-only"):
            broker.submit(
                request_id=1, tenant="a", kind=kind, symbols=_syms(),
                model="null",
            )


def test_order2_member_rejected_for_direct_routing():
    sess, reg = _registry(("durbin8", "dinuc_cpg", "null16"))
    broker = _broker(reg, sess)
    for kind in ("decode", "posterior"):
        with pytest.raises(ValueError, match="pair alphabet"):
            broker.submit(
                request_id=1, tenant="a", kind=kind, symbols=_syms(),
                model="dinuc_cpg",
            )
    # ...but compare serves it fine (base stream kept for composition).
    broker.submit(
        request_id=5, tenant="a", kind="compare", symbols=_syms(),
        models=["dinuc_cpg", "null16"],
    )
    (r,) = broker.drain()
    assert r.ok and set(r.compare["models"]) == {"dinuc_cpg", "null16"}


def test_compare_rejected_in_manifest_mode(tmp_path):
    sess, reg = _registry()
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 15, flush_deadline_s=0.0),
        registry=reg, manifest_path=str(tmp_path / "m.jsonl"),
    )
    with pytest.raises(ValueError, match="manifest"):
        broker.submit(
            request_id=1, tenant="a", kind="compare", symbols=_syms(),
            models=["durbin8", "null"],
        )
    broker.close()


# ---------------------------------------------------------------------------
# routing + results


def test_model_routing_matches_direct_pipeline():
    """model= routed results must equal the same units run directly
    against that member's params (the shared-record-unit contract)."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.parallel.posterior import resolve_fb_engine

    sess, reg = _registry()
    broker = _broker(reg, sess)
    syms = _syms(4000, seed=3)
    broker.submit(
        request_id=1, tenant="a", kind="posterior", symbols=syms,
        model="two_state", name="r1",
    )
    broker.submit(
        request_id=2, tenant="a", kind="posterior", symbols=syms, name="r2"
    )
    res = {r.id: r for r in broker.drain()}
    assert res[1].ok and res[2].ok

    two = reg.member("two_state")
    conf_ref, _ = pipeline._posterior_record_unit(
        two.params, syms, two.island_states, engine="auto",
        fb_eng=resolve_fb_engine("auto", two.params), want_path=True,
        return_device=False, sup=resilience.default_supervisor(),
    )
    np.testing.assert_array_equal(res[1].conf, np.asarray(conf_ref))
    # The default model (flagship) produced a different answer — the
    # routing genuinely switched models.
    assert not np.array_equal(res[1].conf, res[2].conf)


def test_compare_request_matches_family_compare():
    sess, reg = _registry()
    broker = _broker(reg, sess)
    syms = _syms(5000, seed=4)
    broker.submit(
        request_id=7, tenant="a", kind="compare", symbols=syms, name="rc",
        models=["durbin8", "two_state", "null"],
    )
    (r,) = broker.drain()
    assert r.ok and r.route == "compare"
    rc = family.compare_record(
        [reg.member(n) for n in ("durbin8", "two_state", "null")],
        syms, record="rc",
        sessions=reg.sessions_for(("durbin8", "two_state", "null")),
    )
    assert r.compare["baseline"] == "null"
    for m in rc.members:
        wire = r.compare["models"][m.name]
        assert wire["loglik"] == pytest.approx(m.loglik)
        assert wire["log_odds"] == pytest.approx(m.log_odds)
        assert wire["islands"] == len(m.calls)
    # The winner track rides in the standard calls field.
    assert r.calls.format_lines() == rc.winner_calls.format_lines()


def test_transport_wire_carries_model_and_compare(tmp_path):
    """JSONL round trip: model= routing and compare responses through
    serve_stream (the stdio transport)."""
    import io
    import json

    from cpgisland_tpu.serve import transport

    sess, reg = _registry()
    broker = _broker(reg, sess)
    seq = "".join("acgt"[i % 4] for i in range(2000))
    lines = [
        json.dumps({"id": 1, "kind": "decode", "seq": seq,
                    "model": "two_state"}),
        json.dumps({"id": 2, "kind": "compare", "seq": seq,
                    "models": ["durbin8", "two_state", "null"]}),
        json.dumps({"id": 3, "kind": "decode", "seq": seq,
                    "model": "zzz"}),
        json.dumps({"op": "shutdown"}),
    ]
    out = io.StringIO()
    transport.serve_stream(
        io.StringIO("\n".join(lines) + "\n"), out, broker,
        invalid_symbols="skip", use_worker=False,
    )
    got = {j["id"]: j for j in map(json.loads, out.getvalue().splitlines())}
    assert got[1]["ok"] and got[1]["kind"] == "decode"
    assert got[2]["ok"] and set(got[2]["compare"]["models"]) == {
        "durbin8", "two_state", "null"
    }
    assert "islands_text" in got[2]  # the winner track
    assert not got[3]["ok"] and "unknown model" in got[3]["error"]


def test_default_registry_keeps_single_model_behavior():
    """A broker built WITHOUT a registry serves exactly as before (the
    implicit default registry) and rejects any named model."""
    sess = Session(presets.durbin_cpg8(), name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 15, flush_deadline_s=0.0)
    )
    broker.submit(request_id=1, tenant="a", kind="decode", symbols=_syms())
    (r,) = broker.drain()
    assert r.ok
    with pytest.raises(ValueError, match="unknown model"):
        broker.submit(
            request_id=2, tenant="a", kind="decode", symbols=_syms(),
            model="durbin8",
        )
