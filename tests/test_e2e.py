"""End-to-end: synthetic genome with planted islands -> train -> decode -> calls.

SURVEY.md §4: "synthetic genome generated from a known HMM -> train -> decode ->
island calls must recover planted islands above threshold precision/recall."
Also exercises the two CLI forms end to end.
"""

import numpy as np
import pytest

from cpgisland_tpu import cli, pipeline
from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import load_text
from cpgisland_tpu.utils import codec


def synth_genome(rng, n_islands=8, island_len=600, bg_len=3000):
    """Background AT-rich sequence with planted CG-rich islands; returns
    (text, list of (start, end) 0-based inclusive island spans)."""
    parts = []
    spans = []
    pos = 0
    bases_bg = np.array(list("acgt"))
    p_bg = [0.32, 0.18, 0.18, 0.32]
    p_isl = [0.12, 0.38, 0.38, 0.12]
    for i in range(n_islands):
        bg = rng.choice(bases_bg, size=bg_len, p=p_bg)
        parts.append("".join(bg))
        pos += bg_len
        isl = rng.choice(bases_bg, size=island_len, p=p_isl)
        # Boost explicit CpG dinucleotides so O/E clears 0.6.
        isl_s = "".join(isl)
        isl_s = isl_s.replace("ca", "cg").replace("ta", "cg")
        parts.append(isl_s)
        spans.append((pos, pos + len(isl_s) - 1))
        pos += len(isl_s)
    tail = rng.choice(bases_bg, size=bg_len, p=p_bg)
    parts.append("".join(tail))
    return "".join(parts), spans


def _recall(calls, spans):
    hits = 0
    for s, e in spans:
        for b, en in zip(calls.beg, calls.end):
            b0, e0 = b - 1, en - 1  # back to 0-based
            inter = max(0, min(e, e0) - max(s, b0) + 1)
            if inter >= 0.5 * (e - s + 1):
                hits += 1
                break
    return hits / len(spans)


def test_train_decode_recovers_planted_islands(tmp_path, rng):
    text, spans = synth_genome(rng)
    fa = tmp_path / "genome.txt"
    fa.write_text(text)

    fit = pipeline.train_file(
        str(fa), num_iters=3, convergence=0.0, chunk_size=4096, model_out=str(tmp_path / "m.txt")
    )
    assert len(fit.logliks) == 3
    # Training must not have destroyed the two-block structure.
    m = load_text(str(tmp_path / "m.txt"))
    assert m.n_states == 8

    res = pipeline.decode_file(
        str(fa),
        fit.params,
        islands_out=str(tmp_path / "islands.txt"),
        compat=False,
        chunk_size=8192,
    )
    assert res.n_symbols == len(text)
    assert _recall(res.calls, spans) >= 0.8
    lines = (tmp_path / "islands.txt").read_text().splitlines()
    assert len(lines) == len(res.calls)
    cols = lines[0].split()
    assert len(cols) == 5 and int(cols[0]) < int(cols[1])


def test_compat_decode_resets_at_chunk_boundaries(tmp_path, rng):
    # An island straddling a chunk boundary is split in compat mode.
    text, _ = synth_genome(rng, n_islands=2, island_len=400, bg_len=1000)
    fa = tmp_path / "g.txt"
    fa.write_text(text)
    params = presets.durbin_cpg8()
    compat = pipeline.decode_file(str(fa), params, compat=True, chunk_size=1200)
    clean = pipeline.decode_file(str(fa), params, compat=False, chunk_size=1200)
    # Compat drops the remainder; clean sees every symbol.
    assert compat.n_symbols <= clean.n_symbols
    assert clean.n_symbols == len(text)


def test_clean_decode_spanwise_identical_to_onepass(tmp_path, rng):
    """A record forced through the span-wise decode (span smaller than the
    record) must produce IDENTICAL island calls to the one-pass decode —
    boundary messages thread between spans, no DP restart (VERDICT r2 #3)."""
    text, spans = synth_genome(rng, n_islands=4, island_len=400, bg_len=2000)
    fa = tmp_path / "g.txt"
    fa.write_text(text)
    params = presets.durbin_cpg8()
    one = pipeline.decode_file(str(fa), params, compat=False)
    spanned = pipeline.decode_file(str(fa), params, compat=False, span=3000)
    assert spanned.n_chunks > 1  # actually exercised the span path
    np.testing.assert_array_equal(one.calls.beg, spanned.calls.beg)
    np.testing.assert_array_equal(one.calls.end, spanned.calls.end)
    np.testing.assert_allclose(one.calls.gc_content, spanned.calls.gc_content)
    assert _recall(spanned.calls, spans) >= 0.75


def test_cli_compat_six_arg_form(tmp_path, rng):
    text, spans = synth_genome(rng, n_islands=3, island_len=400, bg_len=1500)
    train_f = tmp_path / "train.txt"
    test_f = tmp_path / "test.txt"
    train_f.write_text(text)
    test_f.write_text(text)
    islands_f = tmp_path / "islands.out"
    model_f = tmp_path / "model.out"
    rc = cli.main([str(train_f), str(test_f), str(islands_f), str(model_f), "0.005", "2"])
    assert rc == 0
    model_lines = model_f.read_text().splitlines()
    assert len(model_lines) == 24  # 8 states x 3 lines, reference layout
    assert islands_f.exists()


def test_cli_subcommands(tmp_path, rng, capsys):
    text, _ = synth_genome(rng, n_islands=2, island_len=300, bg_len=800)
    fa = tmp_path / "g.txt"
    fa.write_text(text)
    m = tmp_path / "m.txt"
    rc = cli.main(["train", str(fa), "--model-out", str(m), "--iters", "2"])
    assert rc == 0
    assert "trained:" in capsys.readouterr().out

    out = tmp_path / "i.txt"
    rc = cli.main(["decode", str(fa), "--model", str(m), "--islands-out", str(out), "--clean"])
    assert rc == 0
    assert "islands" in capsys.readouterr().out
    assert out.exists()


def test_cli_spmd_backend(tmp_path, rng):
    text, _ = synth_genome(rng, n_islands=2, island_len=300, bg_len=800)
    fa = tmp_path / "g.txt"
    fa.write_text(text)
    m = tmp_path / "m.txt"
    rc = cli.main(["train", str(fa), "--model-out", str(m), "--iters", "1", "--backend", "spmd"])
    assert rc == 0


def test_clean_decode_per_record_islands(tmp_path, rng):
    """Multi-chromosome FASTA: clean mode decodes per record — an island-like
    run crossing the record boundary must be split, and output lines carry
    the record name."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.utils import codec

    bg = codec.decode_symbols(rng.choice(4, size=3000, p=[0.35, 0.15, 0.15, 0.35]))
    cg = codec.decode_symbols(rng.choice(4, size=800, p=[0.05, 0.45, 0.45, 0.05]))
    # chrA ends with CG-rich tail; chrB starts CG-rich: must be 2 islands.
    fa = tmp_path / "multi.fa"
    fa.write_text(f">chrA x\n{bg}{cg}\n>chrB y\n{cg}{bg}\n")
    out = tmp_path / "islands.out"
    res = pipeline.decode_file(
        str(fa), presets.durbin_cpg8(), islands_out=str(out), compat=False
    )
    assert res.n_symbols == 2 * 3800
    lines = out.read_text().splitlines()
    assert len(lines) == len(res.calls) >= 2
    by_rec = {}
    for ln in lines:
        name, beg, end, ln_, gc, oe = ln.split()
        by_rec.setdefault(name, []).append((int(beg), int(end)))
    assert set(by_rec) == {"chrA", "chrB"}
    # chrA's island sits at its tail, chrB's at its head — both within-record.
    assert all(e <= 3800 for _, e in by_rec["chrA"])
    assert any(b <= 10 for b, _ in by_rec["chrB"])


def test_clean_decode_single_record_keeps_bare_format(tmp_path, rng):
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.utils import codec

    cg = codec.decode_symbols(rng.choice(4, size=900, p=[0.05, 0.45, 0.45, 0.05]))
    bg = codec.decode_symbols(rng.choice(4, size=2000, p=[0.35, 0.15, 0.15, 0.35]))
    fa = tmp_path / "one.fa"
    fa.write_text(f">only\n{bg}{cg}{bg}\n")
    out = tmp_path / "islands.out"
    pipeline.decode_file(str(fa), presets.durbin_cpg8(), islands_out=str(out), compat=False)
    lines = out.read_text().splitlines()
    assert lines and all(len(ln.split()) == 5 for ln in lines)


def test_train_file_seq2d_per_record(tmp_path, rng):
    """backend='seq2d': whole-chromosome exact EM on an auto 2-D mesh."""
    from cpgisland_tpu import pipeline

    fa = tmp_path / "multi.fa"
    with open(fa, "w") as f:
        for name, n in (("chrA", 6000), ("chrB", 4000), ("chrC", 2000)):
            f.write(f">{name}\n")
            s = "".join(rng.choice(list("acgt"), size=n))
            for i in range(0, n, 70):
                f.write(s[i : i + 70] + "\n")
    res = pipeline.train_file(str(fa), backend="seq2d", compat=False, num_iters=3,
                              convergence=0.0)
    lls = res.logliks
    assert len(lls) == 3
    assert all(b >= a - 1e-2 for a, b in zip(lls, lls[1:])), lls
    res.params.validate()


def test_train_file_seq2d_requires_clean(tmp_path):
    from cpgisland_tpu import pipeline

    fa = tmp_path / "x.fa"
    fa.write_text(">h\nacgt\n")
    with pytest.raises(ValueError, match="seq2d"):
        pipeline.train_file(str(fa), backend="seq2d", compat=True)


def test_decode_file_two_state_island_states(tmp_path, rng):
    """End-to-end with a non-base-encoding model: 2-state HMM decode + the
    observation-based island caller."""
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">chr\n")
        parts = []
        for _ in range(4):
            parts.append(rng.choice(list("acgt"), size=3000, p=[0.35, 0.15, 0.15, 0.35]))
            parts.append(rng.choice(list("acgt"), size=800, p=[0.08, 0.42, 0.42, 0.08]))
        s = "".join(np.concatenate(parts))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    params = presets.two_state_cpg()
    res = pipeline.decode_file(str(fa), params, compat=False, island_states=(0,))
    assert 3 <= len(res.calls) <= 6  # the 4 planted islands (merges tolerated)
    assert all(g > 0.5 for g in res.calls.gc_content)
    with pytest.raises(ValueError, match="clean mode"):
        pipeline.decode_file(str(fa), params, compat=True, island_states=(0,))


def test_cli_two_state_preset_island_states(tmp_path, rng):
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">c\n")
        parts = []
        for _ in range(3):
            parts.append(rng.choice(list("acgt"), size=3000, p=[0.35, 0.15, 0.15, 0.35]))
            parts.append(rng.choice(list("acgt"), size=700, p=[0.08, 0.42, 0.42, 0.08]))
        s = "".join(np.concatenate(parts))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    out = tmp_path / "i.txt"
    rc = cli.main(
        ["decode", str(fa), "--islands-out", str(out), "--clean",
         "--preset", "two_state", "--island-states", "0", "--min-len", "200"]
    )
    assert rc == 0
    lines = out.read_text().splitlines()
    assert 2 <= len(lines) <= 4  # the 3 planted islands
    # --island-states without --clean must be rejected
    with pytest.raises(SystemExit):
        cli.main(["decode", str(fa), "--islands-out", str(out), "--island-states", "0"])


def test_decode_file_rejects_non_8state_without_island_states(tmp_path):
    fa = tmp_path / "x.fa"
    fa.write_text(">h\nacgtacgtacgt\n")
    with pytest.raises(ValueError, match="island_states"):
        pipeline.decode_file(str(fa), presets.two_state_cpg(), compat=False)
    with pytest.raises(ValueError, match="island_states"):
        pipeline.decode_file(str(fa), presets.two_state_cpg(), compat=True)


def test_cli_run_two_state_full_loop(tmp_path, rng):
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">c\n")
        parts = []
        for _ in range(3):
            parts.append(rng.choice(list("acgt"), size=3000, p=[0.35, 0.15, 0.15, 0.35]))
            parts.append(rng.choice(list("acgt"), size=700, p=[0.08, 0.42, 0.42, 0.08]))
        s = "".join(np.concatenate(parts))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    out, m = tmp_path / "i.txt", tmp_path / "m.txt"
    rc = cli.main(
        ["run", str(fa), str(fa), "--islands-out", str(out), "--model-out", str(m),
         "--clean", "--preset", "two_state", "--island-states", "0", "--iters", "2"]
    )
    assert rc == 0
    assert 2 <= len(out.read_text().splitlines()) <= 4
    # malformed ids -> argparse error, not a traceback
    with pytest.raises(SystemExit):
        cli.main(["decode", str(fa), "--islands-out", str(out), "--clean",
                  "--island-states", "0,"])


def test_cli_run_two_state_without_island_states_fails_at_parse_time(tmp_path):
    """`run --preset two_state` without --island-states must error before any
    training happens, not hours later in decode_file (ADVICE r1)."""
    fa = tmp_path / "g.fa"
    fa.write_text(">c\nacgtacgtacgt\n")
    out, m = tmp_path / "i.txt", tmp_path / "m.txt"
    with pytest.raises(SystemExit):
        cli.main(["run", str(fa), str(fa), "--islands-out", str(out),
                  "--model-out", str(m), "--clean", "--preset", "two_state"])
    assert not m.exists()  # training never started


def test_spanwise_state_path_dump_identical(tmp_path, rng):
    """state_path_out through the span-wise decode equals the one-pass dump
    byte for byte (the dump is the concatenated per-record MPM of the hard
    path; spans must not perturb it)."""
    text, _ = synth_genome(rng, n_islands=3, island_len=300, bg_len=1500)
    fa = tmp_path / "g.txt"
    fa.write_text(text)
    params = presets.durbin_cpg8()
    p1, p2 = tmp_path / "p1.npy", tmp_path / "p2.npy"
    pipeline.decode_file(str(fa), params, compat=False, state_path_out=str(p1))
    pipeline.decode_file(
        str(fa), params, compat=False, state_path_out=str(p2), span=2000
    )
    np.testing.assert_array_equal(np.load(p1), np.load(p2))
