"""The self-scan gate: the repo is clean under its own linter (modulo
justified inline waivers), and every registered jaxpr contract holds on
the CPU backend — including the recompile sentinel and the
callback/pallas-detection machinery itself.
"""

import os

import jax
import jax.numpy as jnp
import pytest

from cpgisland_tpu.analysis import contracts, run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cpgisland_tpu")


def test_self_scan_clean():
    result = run_lint([PKG], base=REPO)
    assert result.files_checked > 40
    bad = [f.format() for f in result.unwaived]
    assert bad == [], "\n".join(bad)


def test_self_scan_waivers_all_used_and_justified():
    result = run_lint([PKG], base=REPO)
    # Every waiver in the tree covers a live finding (no stale exemptions)
    # and carries a reason (parse_waivers enforces the reason; double-check
    # through the applied findings).
    assert result.unused_waivers == [], result.unused_waivers
    assert result.waived, "expected the documented intentional exemptions"
    for f in result.waived:
        assert f.waiver_reason


def test_contracts_all_hold_on_cpu():
    results = contracts.run_contracts(execute=True)
    assert len(results) >= 10
    bad = {r.name: r.violations for r in results if not r.ok}
    assert bad == {}, bad
    byname = {r.name: r for r in results}
    # The reduced engines must have traced to their XLA twins off-TPU.
    assert byname["decode.onehot"].notes["pallas_calls"] == 0
    assert byname["em.seq.onehot"].notes["pallas_calls"] == 0
    # The dense pallas decode engine legitimately traces pallas_call (it
    # runs interpreted off-TPU in tests) — the detector must SEE them.
    assert byname["decode.pallas"].notes["pallas_calls"] > 0
    assert byname["engines.routing"].notes["auto_picks"]["decode"] == "xla"


def test_contract_summary_shape():
    results = contracts.run_contracts(execute=False)
    summary = contracts.summarize(results)
    assert summary["ok"] is True
    assert summary["checked"] == len(results)
    assert summary["violations"] == {}


def test_contract_detects_callback_primitive():
    def noisy(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    c = contracts.Contract(
        name="fixture.callback",
        make=lambda: (noisy, (jnp.ones(8),), None),
    )
    res = contracts.check_contract(c, execute=False)
    assert not res.ok
    assert any("callback" in v for v in res.violations)


def test_contract_detects_unstable_dispatch():
    # A jitted fn whose input SHAPE changes between the two stability
    # executions recompiles; the sentinel must catch it.
    fn = jax.jit(lambda x: x * 2)
    c = contracts.Contract(
        name="fixture.unstable",
        make=lambda: (fn, (jnp.ones(8),), (jnp.ones(16),)),
        stability=True,
    )
    res = contracts.check_contract(c, execute=True)
    assert not res.ok
    assert any("dispatch surface unstable" in v for v in res.violations)


def test_contract_pallas_expectation_is_platform_aware():
    if jax.default_backend() == "tpu":
        pytest.skip("off-TPU expectation test")
    # An entry that traces pallas off-TPU without the allowance violates.
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel

    params = contracts._flagship()
    o1, _ = contracts._obs_pair(2048, "int32")
    c = contracts.Contract(
        name="fixture.pallas-off-tpu",
        make=lambda: (
            lambda o: viterbi_parallel(
                params, o, block_size=256, engine="pallas"
            ),
            (o1,), None,
        ),
    )
    res = contracts.check_contract(c, execute=False)
    assert not res.ok
    assert any("XLA twin" in v for v in res.violations)
